"""Fault injection: the composable combined nemesis.

Reimplements and extends the reference's nemesis package
(`src/maelstrom/nemesis.clj` + jepsen.nemesis.combined): a special
'nemesis' process receives fault ops from its own generator and applies
them to the network and the nodes. Where the reference CLI stops at
bidirectional partitions (`core.clj:40-42`), this module is a registry of
*fault packages* in the style of jepsen.nemesis.combined:

  - ``partition``  — network partitions with grudge shapes: random
    halves, single isolated node, ``bridge`` (two halves joined by one
    node), ``majorities-ring`` (every node sees a majority, but
    different, ring-overlapping majorities — directional), and one-way
    splits (traffic flows a->b but not b->a).
  - ``kill``       — crash a minority of nodes: volatile state is wiped
    and the node restarts from its durable store (`NodeProgram.
    durable_keys`; SIGKILL + respawn on the host path).
  - ``pause``      — a minority of nodes stop stepping but keep state
    (GC/VM stalls; SIGSTOP/SIGCONT on the host path).
  - ``duplicate``  — at-least-once delivery: inter-server messages are
    re-enqueued with an independent latency draw with probability p.
  - ``weather``    — network weather fronts: seeded mid-run toggling of
    the net's loss probability (`p_loss`) and latency scale (the
    slow!/fast! knob, `net/tpu.py NetState.latency_scale` /
    `net/host.py LatencyDist.scale`). start-weather installs a drawn
    front (drizzle/storm/monsoon); stop-weather restores the run's
    BASELINE values (--p-loss / --latency-scale), so the final heal
    leaves the network exactly as configured.

Each package runs its own on/off generator schedule (offset so packages
interleave within the interval), built from the same ``g.Seq``/``cycle``
combinators the rest of the suite uses; ``package`` composes the
selected set and a final generator that heals *every* fault type so
eventually-consistent workloads are graded post-recovery
(reference `core.clj:63-70`).

Determinism: every random decision (grudge shape, kill/pause targets,
duplication probability) is drawn from a per-fault-package RNG stream
seeded from (seed, fault name). Same seed => same decision sequence per
package, regardless of how the packages interleave and identically on
the host and TPU paths (`NemesisDecisions`).
"""

from __future__ import annotations

import random

from . import generators as g

FAULTS = ("partition", "kill", "pause", "duplicate", "weather",
          "byzantine")

# duplication probabilities the duplicate package cycles through
DUP_PROBS = (0.1, 0.25, 0.5)

# weather fronts the weather package draws from: (name, p_loss,
# latency_scale). Scales stay within the edge-ring headroom budget
# (`nodes.edge_timing` sizes rings for max_latency_scale, default 10);
# loss stays moderate because it also eats CLIENT RPCs (like the
# reference's flaky!, net.clj:213-214) and each lost client message
# parks a worker for the full RPC timeout
WEATHER_FRONTS = (("drizzle", 0.02, 2.0),
                  ("storm", 0.1, 5.0),
                  ("monsoon", 0.25, 10.0))


# --- partition grudges -----------------------------------------------------
#
# A grudge maps dest -> set of blocked srcs (directional: src->dest
# messages are consumed and dropped). Symmetric grudges list both
# directions explicitly.


def split_half(nodes, rng: random.Random):
    """Random majority/minority split; returns (name, grudge) where grudge
    maps dest -> set of blocked srcs (both directions blocked)."""
    nodes = list(nodes)
    rng.shuffle(nodes)
    k = len(nodes) // 2
    a, b = set(nodes[:k]), set(nodes[k:])
    grudge = {}
    for d in a:
        grudge[d] = set(b)
    for d in b:
        grudge[d] = set(a)
    return f"halves {sorted(a)} | {sorted(b)}", grudge


def isolate_node(nodes, rng: random.Random):
    """Cuts one node off from everyone else."""
    nodes = list(nodes)
    n = rng.choice(nodes)
    rest = set(nodes) - {n}
    grudge = {n: set(rest)}
    for d in rest:
        grudge[d] = {n}
    return f"isolated {n}", grudge


def bridge(nodes, rng: random.Random):
    """Two halves joined only through one bridge node (jepsen
    nemesis/bridge): the halves cannot talk directly, but both talk to
    the bridge, so no component separation exists — this grudge needs
    the directional pair representation."""
    nodes = list(nodes)
    rng.shuffle(nodes)
    mid = nodes[len(nodes) // 2]
    a = set(nodes[: len(nodes) // 2])
    b = set(nodes) - a - {mid}
    grudge = {}
    for d in a:
        grudge[d] = set(b)
    for d in b:
        grudge[d] = set(a)
    return f"bridge {mid} between {sorted(a)} | {sorted(b)}", grudge


def majorities_ring(nodes, rng: random.Random):
    """Every node receives only from a majority-sized window starting at
    itself in (shuffled) ring order — overlapping majorities, directional
    (i hears i..i+m-1; i+m-1 does not hear i). The jepsen
    nemesis/majorities-ring grudge."""
    ring = list(nodes)
    rng.shuffle(ring)
    n = len(ring)
    m = n // 2 + 1
    grudge = {}
    for i, d in enumerate(ring):
        visible = {ring[(i + j) % n] for j in range(m)}
        grudge[d] = set(ring) - visible
    return f"majorities-ring {ring}", grudge


def one_way_halves(nodes, rng: random.Random):
    """Asymmetric split: half A's messages reach half B, but B's never
    reach A — the stale-leader/one-way-link shape symmetric partitions
    cannot express."""
    nodes = list(nodes)
    rng.shuffle(nodes)
    k = len(nodes) // 2
    a, b = set(nodes[:k]), set(nodes[k:])
    grudge = {d: set(b) for d in a}     # B -> A blocked; A -> B flows
    return f"one-way {sorted(b)} -/-> {sorted(a)}", grudge


GRUDGES = [split_half, isolate_node, bridge, majorities_ring,
           one_way_halves]


def isolate_set(nodes, cut):
    """Role-targeted partition: the `cut` subset is severed from every
    other node, both directions (the `--nemesis-targets
    partition=<group>` shape — e.g. cutting one acceptor-grid column
    off a compartmentalized cluster). Deterministic: no RNG draw."""
    cs = set(cut)
    cut = [n for n in nodes if n in cs]
    rest = [n for n in nodes if n not in cs]
    grudge = {d: set(cut) for d in rest}
    grudge.update({d: set(rest) for d in cut})
    return f"isolated {cut}", grudge


# --- role-targeted fault scoping ------------------------------------------


# faults whose decisions pick NODES and can therefore be scoped;
# duplicate/weather are cluster-global knobs, so a target spec for them
# would be silently meaningless — rejected up front instead
TARGETABLE_FAULTS = ("kill", "pause", "partition", "byzantine")


def parse_targets(spec) -> dict:
    """`--nemesis-targets kill=proxies,partition=acceptor-col-0` ->
    {fault: [group tokens]} ('+' joins multiple groups per fault)."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = {f: (list(v) if isinstance(v, (list, tuple))
                     else str(v).split("+"))
                 for f, v in spec.items()}
    else:
        items = {}
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            f, sep, val = part.partition("=")
            if not sep or not val.strip():
                raise ValueError(
                    f"--nemesis-targets: expected fault=group, got "
                    f"{part!r}")
            items[f.strip()] = [t.strip() for t in val.split("+")
                                if t.strip()]
    for f in items:
        if f not in TARGETABLE_FAULTS:
            raise ValueError(
                f"--nemesis-targets: {f!r} is not a targetable fault "
                f"(node-picking faults only: {list(TARGETABLE_FAULTS)})")
    return items


def resolve_targets(spec, groups: dict, nodes, dynamic=()) -> dict | None:
    """Resolves a target spec's group tokens against the node family's
    fault groups (`NodeProgram.fault_groups`: role names, acceptor grid
    rows/columns, ...) plus literal node names. Returns
    {fault: [node names]} for `NemesisDecisions`, or None when no
    targeting was requested.

    `dynamic` names groups the program resolves at INVOKE time
    (`NodeProgram.dynamic_fault_groups`, e.g. the compartment's
    `sequencer` = the live elected leader): their tokens stay symbolic
    (``"@<token>"``) in the output and the executing nemesis expands
    them against live cluster state when the fault fires — the
    kill-as-failover driver (doc/faults.md)."""
    parsed = parse_targets(spec)
    if not parsed:
        return None
    node_set = set(nodes)
    dynamic = set(dynamic or ())
    out: dict = {}
    for fault, tokens in parsed.items():
        names: list = []
        for tok in tokens:
            if tok in dynamic:
                members = [f"@{tok}"]
            elif tok in groups:
                members = groups[tok]
            elif tok in node_set:
                members = [tok]
            else:
                raise ValueError(
                    f"--nemesis-targets: unknown group {tok!r} for "
                    f"{fault!r}; known groups: "
                    f"{sorted(set(groups) | dynamic)} "
                    f"(or a literal node name)")
            names += [n for n in members if n not in names]
        if not names:
            raise ValueError(f"--nemesis-targets: empty target set for "
                             f"{fault!r}")
        out[fault] = names
    return out


def grudge_matrix(nodes, grudge):
    """Converts a dest -> blocked-srcs grudge map into the directional
    block representation the TPU network installs
    (`net/tpu.py partition_grudge`): every node is its own group,
    matrix[src, dest] blocks that direction. Expresses one-way, bridge,
    and majorities-ring grudges exactly. Lives here (not in the runner)
    because it is a pure representation transform on the decision
    stream's output, independent of which executor applies it."""
    import numpy as np
    idx = {n: i for i, n in enumerate(nodes)}
    n = len(nodes)
    groups = np.arange(n, dtype=np.int32)
    matrix = np.zeros((n, n), bool)
    for dest, srcs in grudge.items():
        for src in srcs:
            matrix[idx[src], idx[dest]] = True
    return groups, matrix


# --- shared fault decisions ------------------------------------------------


class NemesisDecisions:
    """The random choices a nemesis makes, factored out so the host and
    TPU executors draw IDENTICAL sequences from the same seed: one
    independent RNG stream per fault package, keyed by (seed, fault), so
    the decision sequence of each package does not depend on how the
    packages happen to interleave in real vs virtual time."""

    def __init__(self, nodes, seed: int = 0, targets: dict | None = None,
                 attacks=None):
        self.nodes = list(nodes)
        self.seed = seed
        self.rngs = {f: random.Random(f"{seed}:{f}") for f in FAULTS}
        # byzantine attack pool (--byz-attacks): restricts which attack
        # kinds the byzantine package draws; None = all of byzantine.ATTACKS
        self.byz_attacks = tuple(attacks) if attacks else None
        # legacy alias: pre-combined checkpoints stored a single rng
        self.rng = self.rngs["partition"]
        # role-targeted scoping (resolve_targets): {fault: [node names]}
        # restricts kill/pause sampling to the named pool and turns
        # partition draws into the deterministic isolate-the-set grudge.
        # "@<group>" entries are DYNAMIC: expanded against live cluster
        # state at invoke time via `resolve_dynamic` (set by the
        # executing runner's nemesis — e.g. "@sequencer" -> the elected
        # leader, the kill-as-failover driver)
        self.targets = dict(targets or {})
        self.resolve_dynamic = None

    def _expand_pool(self, pool):
        """Expands dynamic "@group" markers against live cluster state.
        Deterministic per seed on the TPU path: the state the resolver
        reads is itself a pure function of the seed."""
        if pool is None:
            return None
        out: list = []
        for t in pool:
            if isinstance(t, str) and t.startswith("@"):
                if self.resolve_dynamic is None:
                    raise ValueError(
                        f"dynamic nemesis target {t!r} needs a live "
                        f"runner to resolve it (TPU path only)")
                out += [n for n in self.resolve_dynamic(t[1:])
                        if n not in out]
            elif t not in out:
                out.append(t)
        return out

    def next_grudge(self):
        tg = self._expand_pool(self.targets.get("partition"))
        if tg:
            return isolate_set(self.nodes, tg)
        rng = self.rngs["partition"]
        return rng.choice(GRUDGES)(self.nodes, rng)

    def _minority(self, fault: str):
        """A non-empty set of target nodes: at most (n-1)//2 — a strict
        minority, so clusters of n >= 3 keep quorum through the fault
        window. Degenerate clusters (n <= 2) have no non-empty strict
        minority; there the package still targets one node, accepting a
        transient quorum loss that heals at the stop op. With a
        role-targeted pool (`--nemesis-targets`), the minority is taken
        OF THE POOL — 'kill a proxy' kills within the proxy tier, and
        'kill the sequencer' (a dynamic @group resolving to the live
        leader) is a forced failover."""
        rng = self.rngs[fault]
        pool = self._expand_pool(self.targets.get(fault)) or self.nodes
        k = rng.randint(1, max(1, (len(pool) - 1) // 2))
        return sorted(rng.sample(pool, k))

    def next_kill_targets(self):
        return self._minority("kill")

    def next_pause_targets(self):
        return self._minority("pause")

    def next_dup_prob(self) -> float:
        return self.rngs["duplicate"].choice(DUP_PROBS)

    def next_weather(self) -> tuple:
        """(name, p_loss, latency_scale) for the next weather front."""
        return self.rngs["weather"].choice(WEATHER_FRONTS)

    def next_byz_plan(self) -> tuple:
        """(attack, culprit, delta) for the next byzantine window: the
        attack kind, the lying node, and the corruption nonce. Drawn
        from the byzantine package's own stream so host and TPU inject
        the identical adversary schedule per seed (doc/faults.md)."""
        from .byzantine import ATTACKS
        rng = self.rngs["byzantine"]
        pool = self._expand_pool(self.targets.get("byzantine")) \
            or self.nodes
        culprit = rng.choice(sorted(pool))
        attack = rng.choice(list(self.byz_attacks or ATTACKS))
        delta = rng.randint(1, 0x7FFF)
        return attack, culprit, delta

    # checkpoint/resume: the decision streams plus the active-fault
    # bookkeeping must survive together
    def rng_state(self):
        return {"rngs": {f: r.getstate() for f, r in self.rngs.items()},
                "killed": list(getattr(self, "killed", [])),
                "paused_nodes": list(getattr(self, "paused_nodes", []))}

    def set_rng_state(self, st):
        if not isinstance(st, dict) or "rngs" not in st:
            # legacy checkpoint: a single partition-rng state tuple
            self.rngs["partition"].setstate(st)
            return
        for f, s in st["rngs"].items():
            self.rngs[f].setstate(s)
        if hasattr(self, "killed"):
            self.killed = list(st.get("killed", []))
        if hasattr(self, "paused_nodes"):
            self.paused_nodes = list(st.get("paused_nodes", []))


# --- host-path executor ----------------------------------------------------


class CombinedNemesis(NemesisDecisions):
    """Executes nemesis ops against the host network's fault API and the
    node processes (via the DB): the host-path analogue of
    jepsen.nemesis.combined/compose-packages."""

    def __init__(self, net, nodes, seed: int = 0, db=None,
                 targets: dict | None = None, attacks=None,
                 byz_rate: float = 1.0):
        super().__init__(nodes, seed, targets=targets, attacks=attacks)
        self.net = net
        self.db = db
        self.byz_rate = float(byz_rate)
        self.killed: list = []
        self.paused_nodes: list = []
        # weather baseline: the run's CONFIGURED loss/latency-scale (the
        # net carries them by the time the nemesis is built), restored
        # verbatim by stop-weather so the final heal is exact
        self._base_p_loss = float(net.p_loss)
        self._base_lat_scale = float(net.latency_dist.scale)

    def _need_db(self, f):
        if self.db is None:
            raise ValueError(
                f"nemesis op {f!r} needs process control, but no DB was "
                "wired (kill/pause require the bin path's HostDB)")
        return self.db

    def invoke(self, op: dict) -> dict:
        f = op["f"]
        if f == "start-partition":
            name, grudge = self.next_grudge()
            for dest, srcs in grudge.items():
                for src in srcs:
                    self.net.drop_link(src, dest)
            return {**op, "type": "info", "value": name}
        if f == "stop-partition":
            self.net.heal()
            return {**op, "type": "info", "value": "healed"}
        if f == "start-kill":
            # targets come straight from the kill decision stream — no
            # cross-package filtering, so the op's value depends only on
            # this package's RNG (the determinism contract). Overlaps
            # (killing a paused node) are handled at the process layer.
            db = self._need_db(f)
            targets = self.next_kill_targets()
            for n in targets:
                if n not in self.killed:
                    db.kill_node(n)
            self.killed = sorted(set(self.killed) | set(targets))
            return {**op, "type": "info", "value": f"killed {targets}"}
        if f == "stop-kill":
            db = self._need_db(f)
            restarted, self.killed = self.killed, []
            for n in restarted:
                db.restart_node(n)
                if n in self.paused_nodes:
                    # a still-open pause window covers this node: the
                    # respawn must come back stalled, like the TPU
                    # path's mask (stop-pause lifts it)
                    db.pause_node(n)
            return {**op, "type": "info",
                    "value": f"restarted {restarted}"}
        if f == "start-pause":
            db = self._need_db(f)
            targets = self.next_pause_targets()
            for n in targets:
                if n not in self.paused_nodes:
                    db.pause_node(n)
            self.paused_nodes = sorted(set(self.paused_nodes)
                                       | set(targets))
            return {**op, "type": "info", "value": f"paused {targets}"}
        if f == "stop-pause":
            db = self._need_db(f)
            resumed, self.paused_nodes = self.paused_nodes, []
            for n in resumed:
                db.resume_node(n)
            return {**op, "type": "info", "value": f"resumed {resumed}"}
        if f == "start-duplicate":
            p = self.next_dup_prob()
            self.net.duplicate(p)
            return {**op, "type": "info", "value": f"duplicate p={p}"}
        if f == "stop-duplicate":
            self.net.duplicate(0.0)
            return {**op, "type": "info", "value": "duplicate off"}
        if f == "start-weather":
            name, p, scale = self.next_weather()
            self.net.p_loss = p
            self.net.latency_dist = \
                self.net.latency_dist.unscaled().scaled(scale)
            return {**op, "type": "info",
                    "value": f"weather {name} p_loss={p} scale={scale}"}
        if f == "stop-weather":
            self.net.p_loss = self._base_p_loss
            self.net.latency_dist = self.net.latency_dist.unscaled() \
                .scaled(self._base_lat_scale)
            return {**op, "type": "info", "value": "weather cleared"}
        if f == "start-byzantine":
            attack, culprit, delta = self.next_byz_plan()
            self.net.set_byzantine(attack, culprit, delta,
                                   rate=self.byz_rate)
            return {**op, "type": "info",
                    "value": f"byzantine {attack} culprit={culprit}"}
        if f == "stop-byzantine":
            self.net.clear_byzantine()
            return {**op, "type": "info", "value": "byzantine cleared"}
        raise ValueError(f"unknown nemesis op {f!r}")


# Backwards-compatible name: the partition-only executor grew into the
# combined one (partition ops behave identically).
PartitionNemesis = CombinedNemesis


# --- schedules -------------------------------------------------------------


def fault_schedule(fault: str, interval_s: float, offset_s: float):
    """One package's generator: wait out its stagger offset, then cycle
    start -> hold an interval -> stop -> rest an interval, forever (the
    outer time-limit cuts it; the final generator heals leftovers).
    g.cycle pickles (checkpoint/resume); Seq never mutates the pristine
    Sleep instances it re-yields each lap."""
    return g.Seq([
        g.sleep(offset_s),
        g.Seq(g.cycle([
            {"f": f"start-{fault}", "type": "invoke"},
            g.sleep(interval_s),
            {"f": f"stop-{fault}", "type": "invoke"},
            g.sleep(interval_s),
        ])),
    ])


def package(faults: set, interval_s: float = 10.0):
    """Builds {generator, final_generator, faults} for the requested
    fault set — any subset of ``partition``, ``kill``, ``pause``,
    ``duplicate`` (the reference CLI stops at partition,
    `core.clj:40-42`). Packages compose: each keeps its own schedule,
    staggered across the interval so a ``kill,pause,partition`` run
    overlaps faults rather than synchronizing them. The final generator
    emits a stop op for every selected package so ALL fault types heal
    before recovery grading."""
    faults = set(faults)
    unknown = faults - set(FAULTS)
    if unknown:
        raise ValueError(f"unknown nemesis fault(s) {sorted(unknown)}; "
                         f"expected any of {list(FAULTS)}")
    ordered = [f for f in FAULTS if f in faults]
    if not ordered:
        return {"generator": None, "final_generator": None, "faults": ()}

    n = len(ordered)
    gens = [fault_schedule(f, interval_s, interval_s * (i + 1) / n)
            for i, f in enumerate(ordered)]
    sched = gens[0]
    for sub in gens[1:]:
        sched = g.Any2(sched, sub)

    final = g.Seq([{"f": f"stop-{f}", "type": "invoke"}
                   for f in ordered])
    return {"generator": sched, "final_generator": final,
            "faults": tuple(ordered)}
