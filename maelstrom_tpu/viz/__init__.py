"""Visualization: dependency-free SVG/HTML renderers for perf plots
(latency/rate), Lamport spacetime diagrams, and op timelines — the
counterparts of jepsen's perf charts, `net/viz.clj`'s messages.svg, and
jepsen.checker.timeline's timeline.html."""
