"""timeline.html: per-process operation tracks (jepsen.checker.timeline
equivalent, reference `core.clj:84`)."""

from __future__ import annotations

import html

COLORS = {"ok": "#a5d6a7", "info": "#ffcc80", "fail": "#ef9a9a"}


def render_timeline(history, path: str | None = None) -> str:
    """Empty and nemesis-only histories are valid inputs: the document
    renders with zero (or only-nemesis) tracks instead of raising into
    TimelineChecker's error catch (tests/test_viz.py). Process names
    and op text are escaped — fleet-merged histories tag processes
    `c<i>:<p>`, and nothing here may break the HTML."""
    pairs = history.pairs()
    if pairs:
        t_end = max((c.time for _, c in pairs if c is not None),
                    default=0)
    else:
        t_end = 0
    scale = 1000.0 / max(t_end, 1)      # px per ns across 1000px

    by_process: dict = {}
    for invoke, complete in pairs:
        by_process.setdefault(invoke.process, []).append((invoke, complete))

    rows = []
    for process in sorted(by_process, key=str):
        bars = []
        for invoke, complete in by_process[process]:
            x = invoke.time * scale
            w = max(((complete.time if complete else t_end) - invoke.time)
                    * scale, 2)
            outcome = complete.type if complete else "info"
            title = html.escape(
                f"{invoke.f} {invoke.value!r} -> "
                f"{outcome} {complete.value!r}" if complete
                else f"{invoke.f} {invoke.value!r} -> ?")
            bars.append(
                f'<div class="op {outcome}" style="left:{x:.1f}px;'
                f'width:{w:.1f}px" title="{title}">'
                f'{html.escape(str(invoke.f))}</div>')
        rows.append(f'<div class="row"><span class="proc">'
                    f'{html.escape(str(process))}'
                    f'</span><div class="track">{"".join(bars)}</div></div>')

    doc = f"""<!doctype html><html><head><meta charset="utf-8">
<title>timeline</title><style>
body {{ font-family: sans-serif; font-size: 12px; }}
.row {{ display: flex; align-items: center; margin: 2px 0; }}
.proc {{ width: 70px; text-align: right; padding-right: 8px; }}
.track {{ position: relative; height: 20px; width: 1010px;
          background: #f5f5f5; }}
.op {{ position: absolute; height: 18px; border: 1px solid #8886;
       overflow: hidden; font-size: 10px; }}
{"".join(f'.op.{k} {{ background: {v}; }}' for k, v in COLORS.items())}
</style></head><body><h3>Operation timeline</h3>
{"".join(rows)}
</body></html>"""
    if path:
        with open(path, "w") as f:
            f.write(doc)
    return doc
