"""Lamport spacetime diagrams: messages.svg.

The counterpart of `src/maelstrom/net/viz.clj`: nodes as vertical lines
(clients sorted first), time flowing downward, each message drawn as an
arrow from its send event to its recv event, labeled with its body type.
Client messages are blue, error messages pink (reference
`net/viz.clj:113-120`); rendering truncates at 10,000 events
(`net/viz.clj:13-16`)."""

from __future__ import annotations

from ..util import sort_clients, is_client

MAX_EVENTS = 10_000
NODE_W = 120
ROW_H = 24
TOP = 60


def _color(e) -> str:
    body = e.body or {}
    if body.get("type") == "error":
        return "#ff6fb3"        # pink
    if is_client(e.src) or is_client(e.dest):
        return "#6fa8ff"        # blue
    return "#666666"


def _label(e) -> str:
    body = e.body or {}
    t = body.get("type", "")
    extra = ""
    for k in ("key", "value", "delta", "message", "echo"):
        if k in body:
            extra = f" {body[k]!r}"
            break
    return f"{t}{extra}"[:28]


def plot_lamport(journal, path: str | None = None) -> str:
    """Renders the journal as an SVG spacetime diagram. Pairs send/recv by
    message id (reference `net/viz.clj:27-56`)."""
    events = journal.all_events()
    truncated = len(events) > MAX_EVENTS
    events = events[:MAX_EVENTS]

    nodes = sort_clients({e.src for e in events} | {e.dest for e in events})
    node_x = {n: NODE_W // 2 + i * NODE_W for i, n in enumerate(nodes)}

    # Each event gets a row (its y position), in time order.
    sends: dict = {}
    arrows = []
    for row, e in enumerate(events):
        if e.type == "send":
            sends[e.id] = (row, e)
        else:
            srow, se = sends.get(e.id, (row, e))
            arrows.append((srow, row, se if se.body else e))

    height = TOP + ROW_H * (len(events) + 1) + 40
    width = NODE_W * max(len(nodes), 1) + 40
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="monospace" font-size="11">',
           f'<rect width="{width}" height="{height}" fill="white"/>']
    if truncated:
        out.append(f'<text x="10" y="20" fill="#d62728">Truncated to '
                   f'{MAX_EVENTS} events</text>')
    for n in nodes:
        x = node_x[n]
        out.append(f'<text x="{x}" y="{TOP-20}" text-anchor="middle" '
                   f'font-weight="bold">{n}</text>')
        out.append(f'<line x1="{x}" y1="{TOP-10}" x2="{x}" '
                   f'y2="{height-20}" stroke="#ccc"/>')
    out.append('<defs><marker id="arr" markerWidth="8" markerHeight="8" '
               'refX="7" refY="3" orient="auto">'
               '<path d="M0,0 L7,3 L0,6 z" fill="context-stroke"/>'
               '</marker></defs>')
    for srow, rrow, e in arrows:
        x1, y1 = node_x.get(e.src, 0), TOP + srow * ROW_H
        x2, y2 = node_x.get(e.dest, 0), TOP + rrow * ROW_H
        c = _color(e)
        out.append(f'<line x1="{x1}" y1="{y1}" x2="{x2}" y2="{y2}" '
                   f'stroke="{c}" stroke-width="1.2" '
                   'marker-end="url(#arr)"/>')
        mx, my = (x1 + x2) / 2, (y1 + y2) / 2 - 3
        out.append(f'<text x="{mx}" y="{my}" text-anchor="middle" '
                   f'fill="{c}">{_label(e)}</text>')
    out.append("</svg>")
    svg = "\n".join(out)
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
