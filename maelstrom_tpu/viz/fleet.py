"""Fleet telemetry heatmap: clusters x windows, colored by a chosen
window metric (doc/observability.md).

Renders the flight recorder's telemetry.jsonl stream as one SVG grid —
row = cluster, column = window sequence, cell color = the metric value
(p99 latency by default) on a white->red ramp — so a `--fleet N`
campaign's hot clusters and hot phases are visible at a glance. Pure
stdlib SVG like the rest of viz/ (no matplotlib)."""

from __future__ import annotations

import html

CELL = 14          # px per window cell
ROW_H = 16
ML, MT = 70, 46    # margins: cluster labels left, title/legend top


def _metric(rec: dict, metric: str):
    if metric in ("p50", "p95", "p99", "max"):
        return (rec.get("lat_ms") or {}).get(metric)
    v = rec.get(metric)
    return v if isinstance(v, (int, float)) else None


def _ramp(frac: float) -> str:
    """White -> amber -> red ramp over [0, 1]."""
    frac = min(max(frac, 0.0), 1.0)
    if frac < 0.5:
        t = frac * 2
        r, g, b = 255, int(255 - 90 * t), int(255 * (1 - t))
    else:
        t = (frac - 0.5) * 2
        r, g, b = 255, int(165 * (1 - t) + 60 * t), int(60 * t * 0.5)
    return f"#{r:02x}{g:02x}{b:02x}"


def fleet_heatmap(records: list, path: str | None = None,
                  metric: str = "p99") -> str:
    """Builds the clusters x windows heatmap from parsed telemetry
    records (`type == "window"`); cells without a value render grey.
    Returns the SVG text; writes it when `path` is given."""
    grid: dict = {}          # (cluster, window) -> value-or-None
    clusters: list = []
    max_win = 0
    for rec in records:
        if rec.get("type") != "window":
            continue
        cl = rec.get("cluster")
        cl = 0 if cl is None else cl
        if cl not in clusters:
            clusters.append(cl)
        w = int(rec.get("window", 0))
        max_win = max(max_win, w + 1)
        grid[(cl, w)] = _metric(rec, metric)
    clusters.sort()

    vals = [v for v in grid.values() if v is not None]
    vmax = max(vals) if vals else 1.0
    vmin = min(vals) if vals else 0.0
    span = (vmax - vmin) or 1.0

    W = ML + max(max_win, 1) * CELL + 20
    H = MT + max(len(clusters), 1) * ROW_H + 30
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
           f'height="{H}" font-family="sans-serif" font-size="11">',
           f'<rect width="{W}" height="{H}" fill="white"/>',
           f'<text x="{ML}" y="16" font-size="13" font-weight="bold">'
           f'Fleet telemetry: {html.escape(metric)} per window</text>',
           f'<text x="{ML}" y="32" fill="#555">'
           f'{len(clusters)} cluster(s) x {max_win} window(s), '
           f'range {vmin:g}..{vmax:g}</text>']
    if not grid:
        out.append(f'<text x="{ML}" y="{MT + 12}">no window records'
                   '</text>')
    for yi, cl in enumerate(clusters):
        y = MT + yi * ROW_H
        out.append(f'<text x="{ML - 8}" y="{y + 11}" text-anchor="end">'
                   f'c{html.escape(str(cl))}</text>')
        for w in range(max_win):
            v = grid.get((cl, w))
            if v is None:
                fill = "#eee"
                title = f"c{cl} w{w}: -"
            else:
                fill = _ramp((v - vmin) / span)
                title = f"c{cl} w{w}: {metric}={v:g}"
            out.append(
                f'<rect x="{ML + w * CELL}" y="{y}" width="{CELL - 1}" '
                f'height="{ROW_H - 2}" fill="{fill}">'
                f'<title>{html.escape(title)}</title></rect>')
    out.append(f'<text x="{ML}" y="{H - 10}" fill="#555">window '
               f'(wave) index &#8594;</text>')
    out.append("</svg>")
    svg = "\n".join(out)
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
