"""Minimal SVG chart rendering (no matplotlib dependency).

Produces the perf artifacts the reference gets from jepsen's checker/perf
(`core.clj:83-84`, `doc/results.md:36-46`): latency-raw (scatter of op
latencies over time, colored by outcome), latency-quantiles (lines), and
rate (ops/sec lines per f)."""

from __future__ import annotations

import html
import math

W, H = 900, 420
ML, MR, MT, MB = 70, 130, 30, 50     # margins
COLORS = ["#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
          "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf"]
OUTCOME_COLORS = {"ok": "#2ca02c", "info": "#ff7f0e", "fail": "#d62728"}


def _nice_ticks(lo: float, hi: float, n: int = 6):
    if hi <= lo:
        hi = lo + 1
    span = hi - lo
    step = 10 ** math.floor(math.log10(span / max(n, 1)))
    for mult in (1, 2, 5, 10):
        if span / (step * mult) <= n:
            step *= mult
            break
    t0 = math.ceil(lo / step) * step
    ticks = []
    t = t0
    while t <= hi + 1e-12:
        ticks.append(round(t, 10))
        t += step
    return ticks


def _fmt(x: float) -> str:
    if x == 0:
        return "0"
    if abs(x) >= 1000:
        return f"{x:.0f}"
    if abs(x) >= 1:
        return f"{x:g}"
    return f"{x:.3g}"


def svg_chart(series: dict, title: str, xlabel: str, ylabel: str,
              kind: str = "line", log_y: bool = False) -> str:
    """series: name -> {"points": [(x, y), ...], "color": optional}.

    Degenerate inputs are a contract, not an accident: empty series
    (an empty or nemesis-only history — ISSUE 13's guard) render a
    labeled "no data" SVG, non-finite points are dropped, and names /
    labels are escaped — the renderers must never raise into the
    checker's plot-error catch."""
    series = {
        name: {**s, "points": [(x, y) for x, y in s.get("points", ())
                               if math.isfinite(x) and math.isfinite(y)]}
        for name, s in series.items()}
    title = html.escape(str(title))
    pts_all = [(x, y) for s in series.values() for x, y in s["points"]]
    if not pts_all:
        return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
                f'height="{H}"><text x="20" y="30">{title}: no data'
                '</text></svg>')
    xs = [p[0] for p in pts_all]
    ys = [p[1] for p in pts_all]
    x0, x1 = min(xs), max(xs)
    if log_y:
        ys_pos = [y for y in ys if y > 0] or [1e-3]
        y0, y1 = math.log10(min(ys_pos)), math.log10(max(ys_pos))
    else:
        y0, y1 = 0 if min(ys) >= 0 else min(ys), max(ys)
    if x1 <= x0:
        x1 = x0 + 1
    if y1 <= y0:
        y1 = y0 + 1
    pw, ph = W - ML - MR, H - MT - MB

    def X(x):
        return ML + (x - x0) / (x1 - x0) * pw

    def Y(y):
        if log_y:
            y = math.log10(y) if y > 0 else y0
        return MT + ph - (y - y0) / (y1 - y0) * ph

    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
           f'height="{H}" font-family="sans-serif" font-size="12">',
           f'<rect width="{W}" height="{H}" fill="white"/>',
           f'<text x="{ML}" y="18" font-size="14" font-weight="bold">'
           f'{title}</text>']
    # axes + ticks
    out.append(f'<line x1="{ML}" y1="{MT}" x2="{ML}" y2="{MT+ph}" '
               'stroke="black"/>')
    out.append(f'<line x1="{ML}" y1="{MT+ph}" x2="{ML+pw}" y2="{MT+ph}" '
               'stroke="black"/>')
    for t in _nice_ticks(x0, x1):
        out.append(f'<line x1="{X(t):.1f}" y1="{MT+ph}" x2="{X(t):.1f}" '
                   f'y2="{MT+ph+5}" stroke="black"/>'
                   f'<text x="{X(t):.1f}" y="{MT+ph+18}" '
                   f'text-anchor="middle">{_fmt(t)}</text>')
    yticks = ([10 ** e for e in
               range(math.floor(y0), math.ceil(y1) + 1)]
              if log_y else _nice_ticks(y0, y1))
    for t in yticks:
        ty = Y(t)
        out.append(f'<line x1="{ML-5}" y1="{ty:.1f}" x2="{ML}" '
                   f'y2="{ty:.1f}" stroke="black"/>'
                   f'<text x="{ML-8}" y="{ty+4:.1f}" text-anchor="end">'
                   f'{_fmt(t)}</text>')
        out.append(f'<line x1="{ML}" y1="{ty:.1f}" x2="{ML+pw}" '
                   f'y2="{ty:.1f}" stroke="#eee"/>')
    out.append(f'<text x="{ML+pw/2}" y="{H-8}" text-anchor="middle">'
               f'{html.escape(str(xlabel))}</text>')
    out.append(f'<text x="16" y="{MT+ph/2}" text-anchor="middle" '
               f'transform="rotate(-90 16 {MT+ph/2})">'
               f'{html.escape(str(ylabel))}</text>')

    for i, (name, s) in enumerate(series.items()):
        color = s.get("color") or COLORS[i % len(COLORS)]
        pts = sorted(s["points"])
        if kind == "line":
            d = " ".join(f'{X(x):.1f},{Y(y):.1f}' for x, y in pts)
            out.append(f'<polyline points="{d}" fill="none" '
                       f'stroke="{color}" stroke-width="1.5"/>')
        else:
            for x, y in pts:
                out.append(f'<circle cx="{X(x):.1f}" cy="{Y(y):.1f}" '
                           f'r="2" fill="{color}" fill-opacity="0.6"/>')
        ly = MT + 14 + 16 * i
        out.append(f'<rect x="{W-MR+8}" y="{ly-9}" width="10" height="10" '
                   f'fill="{color}"/>'
                   f'<text x="{W-MR+22}" y="{ly}">'
                   f'{html.escape(str(name))}</text>')
    out.append("</svg>")
    return "\n".join(out)


def perf_charts(history, out_dir: str):
    """Writes latency-raw.svg, latency-quantiles.svg, rate.svg.

    Empty and nemesis-only histories are valid inputs (a pure-fault
    run, a run preempted before its first op): every chart is still
    written, as an explicit "no data" SVG — the renderer never raises
    into PerfChecker's plot-error catch (tests/test_viz.py)."""
    import os
    pairs = history.pairs()
    # latency scatter: x = invoke time (s), y = latency (ms), by outcome
    raw: dict = {}
    lat_by_f: dict = {}
    rate_by_f: dict = {}
    for invoke, complete in pairs:
        if invoke.process == "nemesis":
            continue
        t_s = invoke.time / 1e9
        rate_by_f.setdefault(invoke.f, []).append(t_s)
        if complete is None:
            continue
        lat_ms = max((complete.time - invoke.time) / 1e6, 1e-3)
        raw.setdefault(complete.type, {"points": [],
                                       "color": OUTCOME_COLORS.get(
                                           complete.type)})[
            "points"].append((t_s, lat_ms))
        if complete.is_ok():
            lat_by_f.setdefault(invoke.f, []).append((t_s, lat_ms))

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "latency-raw.svg"), "w") as f:
        f.write(svg_chart(raw, "Latency (all ops)", "time (s)",
                          "latency (ms)", kind="scatter", log_y=True))

    # quantiles over windows
    qseries: dict = {}
    for fname, pts in lat_by_f.items():
        pts.sort()
        window = max((pts[-1][0] - pts[0][0]) / 20, 1e-9) if pts else 1
        for q in (0.5, 0.95, 0.99):
            qpts = []
            i = 0
            while i < len(pts):
                j = i
                lats = []
                t_end = pts[i][0] + window
                while j < len(pts) and pts[j][0] <= t_end:
                    lats.append(pts[j][1])
                    j += 1
                lats.sort()
                qpts.append((pts[i][0],
                             lats[min(len(lats) - 1, int(q * len(lats)))]))
                i = j
            qseries[f"{fname} p{int(q*100)}"] = {"points": qpts}
    with open(os.path.join(out_dir, "latency-quantiles.svg"), "w") as f:
        f.write(svg_chart(qseries, "Latency quantiles", "time (s)",
                          "latency (ms)", kind="line", log_y=True))

    # rate: ops/sec per f over windows
    rseries: dict = {}
    for fname, times in rate_by_f.items():
        times.sort()
        if not times:
            continue
        window = max((times[-1] - times[0]) / 30, 1e-9)
        pts = []
        t = times[0]
        i = 0
        while i < len(times):
            j = i
            while j < len(times) and times[j] < t + window:
                j += 1
            pts.append((t, (j - i) / window))
            i = j
            t += window
        rseries[str(fname)] = {"points": pts}
    with open(os.path.join(out_dir, "rate.svg"), "w") as f:
        f.write(svg_chart(rseries, "Request rate", "time (s)", "ops/sec",
                          kind="line"))
