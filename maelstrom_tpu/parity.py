"""Protocol-efficiency parity vs the reference's tutorial measurements.

The reference's quantitative record is the broadcast optimization arc in
its `doc/03-broadcast/02-performance.md` (tabulated in `BASELINE.md`):
server msgs-per-op and stable-latency quantiles for the *naive*
non-retrying broadcast node at 25 nodes across topologies and latencies.
Reproducing those numbers on this framework's simulation is the direct
evidence that the virtual-time network's semantics (per-message latency,
delivery order, message accounting) match the reference's wall-clock
JVM simulation.

Each config runs the same test the reference doc ran (rate 100, 20 s,
`--node-count 25 --topology X --latency Y`) against the TPU-path naive
broadcast program (`nodes/broadcast.py` `naive_broadcast`), and compares:

  - server msgs-per-op from the net-stats checker
  - stable-latency quantiles from the stock set-full checker

Writes `artifacts/parity.json` and a markdown table. Run via
`python -m maelstrom_tpu parity` (add --quick for a CI-sized subset).
"""

from __future__ import annotations

import json
import os
import sys
import time

# (name, test-opts overrides, reference expectations, source line)
CONFIGS = [
    ("naive 5-node grid (no skip-sender)",
     {"node_count": 5, "topology": "grid", "skip_sender": False},
     {"server_mpo": 5.01}, "02-performance.md:25-28"),
    ("skip-sender 5-node grid",
     {"node_count": 5, "topology": "grid"},
     {"server_mpo": 2.94}, "02-performance.md:73-76"),
    ("grid 25",
     {"node_count": 25, "topology": "grid"},
     {"server_mpo": 27.8}, "02-performance.md:89-92"),
    ("line 25",
     {"node_count": 25, "topology": "line"},
     {"server_mpo": 12.0}, "02-performance.md:112-115"),
    ("line 25, 10 ms",
     {"node_count": 25, "topology": "line", "latency": {"mean": 10}},
     {"p50": 86, "p95": 170, "p99": 193, "max": 224},
     "02-performance.md:145"),
    ("grid 25, 10 ms",
     {"node_count": 25, "topology": "grid", "latency": {"mean": 10}},
     {"p50": 11, "p95": 42, "p99": 56, "max": 72},
     "02-performance.md:165"),
    # the two 10 ms configs re-run at 4x time resolution: if the round-
    # quantization explanation for their deviations is right, the
    # quantiles must converge toward the reference's wall-clock numbers
    ("line 25, 10 ms (0.25 ms rounds)",
     {"node_count": 25, "topology": "line", "latency": {"mean": 10},
      "ms_per_round": 0.25},
     {"p50": 86, "p95": 170, "p99": 193, "max": 224},
     "02-performance.md:145"),
    ("grid 25, 10 ms (0.25 ms rounds)",
     {"node_count": 25, "topology": "grid", "latency": {"mean": 10},
      "ms_per_round": 0.25},
     {"p50": 11, "p95": 42, "p99": 56, "max": 72},
     "02-performance.md:165"),
    ("grid 25, 100 ms",
     {"node_count": 25, "topology": "grid", "latency": {"mean": 100}},
     {"p50": 452, "p95": 656, "p99": 748, "max": 791},
     "02-performance.md:187-191"),
    ("grid 25, 100 ms exponential",
     {"node_count": 25, "topology": "grid",
      "latency": {"mean": 100, "dist": "exponential"}},
     {"p50": 229, "p95": 431, "p99": 520, "max": 630},
     "02-performance.md:207-211"),
    ("total 25, 100 ms",
     {"node_count": 25, "topology": "total", "latency": {"mean": 100}},
     {"server_mpo": 290.6, "p50": 77, "p95": 95, "max": 97},
     "02-performance.md:225,234-237"),
    ("tree4 25, 100 ms",
     {"node_count": 25, "topology": "tree4", "latency": {"mean": 100}},
     {"server_mpo": 12.0, "p50": 386, "p95": 489, "max": 505},
     "02-performance.md:251-260"),
]

QUICK = {"line 25", "grid 25, 10 ms"}

QKEY = {"p50": "0.5", "p95": "0.95", "p99": "0.99", "max": "1"}


def run_config(name, over, time_limit=20.0, seed=3):
    from . import core
    opts = {"workload": "broadcast", "node": "tpu:broadcast",
            "naive_broadcast": True, "rate": 100.0,
            "time_limit": time_limit, "journal_rows": False, "seed": seed,
            "store_root": os.environ.get("PARITY_STORE",
                                         "/tmp/maelstrom-parity-store"),
            "name": "parity-" + name.replace(" ", "-").replace(",", "")}
    opts.update(over)
    res = core.run(opts)
    w = res["workload"]
    lat = w.get("stable-latencies") or {}
    return {
        "valid": res["valid"],
        "server_mpo": res["net"]["servers"].get("msgs-per-op"),
        "p50": lat.get("0.5"), "p95": lat.get("0.95"),
        "p99": lat.get("0.99"), "max": lat.get("1"),
        "lost": w.get("lost-count"),
        "server_msgs": res["net"]["servers"]["msg-count"],
        "ops": res["stats"]["count"],
    }


def compare(measured, expect):
    """[(key, expected, got, deviation_pct)] for the keys the reference
    published."""
    rows = []
    for k, want in expect.items():
        got = measured.get(k)
        dev = (None if got is None or not want
               else round(100.0 * (got - want) / want, 1))
        rows.append((k, want, got, dev))
    return rows


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in argv
    # --render-only: regenerate the markdown table + gate verdict from an
    # existing artifacts/parity.json (prose/gate changes shouldn't cost a
    # multi-hour re-measurement; the JSON is the measurement of record)
    render_only = "--render-only" in argv
    time_limit = float(os.environ.get("PARITY_TIME_LIMIT", 20.0))
    # --quick is a smoke run: never clobber the full-suite measurement
    # of record (artifacts/parity.json + doc/parity.md)
    out_json = os.environ.get(
        "PARITY_OUT",
        "artifacts/parity-quick.json" if quick else
        "artifacts/parity.json")
    out_md = os.environ.get(
        "PARITY_MD",
        "/tmp/parity-quick.md" if quick else "doc/parity.md")

    if render_only:
        with open(out_json) as f:
            recorded = json.load(f)
        results = recorded["results"]
        # the doc header must describe the recorded measurement, not
        # this process's env default
        time_limit = float(recorded.get("time_limit", time_limit))
    else:
        results = []
        for name, over, expect, src in CONFIGS:
            if quick and name not in QUICK:
                continue
            t0 = time.perf_counter()
            m = run_config(name, over, time_limit=time_limit)
            rows = compare(m, expect)
            results.append({"config": name, "source": src, "measured": m,
                            "comparison": [
                                {"metric": k, "reference": want,
                                 "measured": got, "deviation_pct": dev}
                                for k, want, got, dev in rows],
                            "wall_s": round(time.perf_counter() - t0, 1)})
            worst = max((abs(d) for _, _, _, d in rows if d is not None),
                        default=None)
            print(f"parity: {name}: worst deviation "
                  f"{worst}% ({results[-1]['wall_s']}s)", file=sys.stderr)

        os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
        with open(out_json, "w") as f:
            json.dump({"time_limit": time_limit, "rate": 100.0,
                       "results": results}, f, indent=2, default=str)

    lines = [
        "# Protocol-efficiency parity vs the reference",
        "",
        "Measured on this framework's TPU-path simulation (naive",
        "non-retrying broadcast node, `nodes/broadcast.py`), same configs",
        "as the reference tutorial: rate 100, "
        f"{time_limit:.0f} s, constant latency unless noted.",
        "Reference numbers from the reference's",
        "`doc/03-broadcast/02-performance.md`",
        "(tabulated in `BASELINE.md`). msgs-per-op = server messages /",
        "total client operations; stable latencies in ms from the stock",
        "set-full checker.",
        "",
        "| Config | Metric | Reference | Measured | Deviation | Run valid |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        m = r["measured"]
        ok = bool(m.get("valid")) and not m.get("lost")
        ok_s = "yes" if ok else (f"**NO** (lost {m.get('lost')})"
                                 if m.get("lost") else "**NO**")
        for c in r["comparison"]:
            got = c["measured"]
            got_s = "—" if got is None else (
                f"{got:.2f}" if isinstance(got, float) else str(got))
            dev = c["deviation_pct"]
            dev_s = "—" if dev is None else f"{dev:+.1f}%"
            lines.append(f"| {r['config']} ({r['source']}) | {c['metric']} "
                         f"| {c['reference']} | {got_s} | {dev_s} "
                         f"| {ok_s} |")
    lines += [
        "",
        "Every row's run must grade **valid** under the stock set-full",
        "checker with zero destroyed messages — a run that loses values",
        "is not parity evidence, whatever its quantiles say, and fails",
        "the gate below. (The naive protocol does not retransmit, so the",
        "edge channels use the collision-free spill write under",
        "randomized latency; see `net/static.py`.)",
        "",
        "## Reading the deviations",
        "",
        "- **msgs-per-op rows are the semantics evidence** — they count",
        "  protocol messages, independent of time discretization — and",
        "  land within ~2.5% across every topology.",
        "- Latency quantiles at **100 ms/hop** land within ~5% (tree4",
        "  within 1.6%). At **10 ms/hop** the quantiles sit 5–14 ms",
        "  above the reference's. Two hypotheses were tested:",
        "  - *Round quantization* — **disproven**: re-running both 10 ms",
        "    configs at 0.25 ms rounds (4x resolution, the table's",
        "    '0.25 ms rounds' rows) leaves the deviations unchanged.",
        "  - *Measurement-clock offset* — supported: recomputing the",
        "    quantiles from these runs' own histories with the",
        "    element's `known` (ack) time shifted later by a single",
        "    constant aligns **all 16 quantile comparisons** (grid +",
        "    line, both resolutions) at ~7 ms, collapsing the total",
        "    deviation to the ±6 ms noise floor of single-run order",
        "    statistics (`python -m maelstrom_tpu.parity_analysis`,",
        "    artifacts/parity_known_shift.json). A constant,",
        "    hop-scale-independent offset is the signature of *when the",
        "    ack is stamped*, not of propagation speed: the reference",
        "    stamps an element known when a JVM client thread returns",
        "    from a synchronous RPC (thread handoffs + queue polls after",
        "    the server actually had the value — milliseconds at 25",
        "    handlers × rate 100 on one machine), while this framework's",
        "    virtual-clock ack is exact to one round. A later known",
        "    shrinks (last_absent − known) at every quantile — and is",
        "    invisible at 100 ms/hop, exactly as observed. Per-hop",
        "    delivery here is exact by construction",
        "    (tests/test_edge_oracle.py).",
        "  - *The offset is now measured, not just fitted*",
        "    (`python -m maelstrom_tpu.parity_ackstamp`): driving this",
        "    framework's own wall-clock host path at the exact parity",
        "    config — 25 node processes, 25 concurrent synchronous",
        "    client workers, rate 100, 10 ms hops — with the broadcast",
        "    node stamping the monotonic instant it first holds each",
        "    value, the measured (ack-stamp − server-had-value) lag has",
        "    **median 22.9 ms** (p25 1.4 ms, artifacts/ackstamp_lag.json)",
        "    on this 1-core box; the identical run at rate 25 (the box",
        "    unsaturated) measures **median 0.77 ms** (p75 4.2 ms, p90",
        "    14.5 ms, artifacts/ackstamp_lag_rate25.json). Client links",
        "    are zero-latency in both harnesses, so this lag is pure",
        "    handler/worker scheduling plus history stamping — it is",
        "    strongly load-dependent, and the reference's fitted",
        "    7.5–8.5 ms at rate 100 on its own (multi-core JVM) box sits",
        "    squarely inside the measured band [0.8, 22.9] ms that the",
        "    same mechanism produces here. The fit is thereby grounded",
        "    in a measured distribution of the mechanism it names.",
        "- The **max of the exponential run** is a single order",
        "  statistic of an unbounded distribution (one latency draw);",
        "  the reference's own 630 ms is one sample of the same tail.",
        "",
        "Gate: msgs-per-op within 10%; latency quantiles within 15% or",
        "1.5 hops absolute; randomized-distribution maxima reported but",
        "not gated; any invalid or lossy row fails outright.",
    ]
    os.makedirs(os.path.dirname(out_md) or ".", exist_ok=True)
    with open(out_md, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_json} and {out_md}", file=sys.stderr)

    def gated(r, c):
        dev, got = c["deviation_pct"], c["measured"]
        if dev is None:
            return None
        if c["metric"] == "server_mpo":
            return abs(dev) <= 10.0
        # latency quantiles: 15% or 1.5 hops absolute, whichever is
        # looser (at 10 ms/hop a whole quantization hop is >50% of p50)
        mean = next((cfg[1].get("latency", {}).get("mean", 0)
                     for cfg in CONFIGS if cfg[0] == r["config"]), 0)
        if abs(dev) <= 15.0:
            return True
        want = c["reference"]
        if abs(got - want) <= 1.5 * mean:
            return True
        # a randomized distribution's max is a single unbounded draw
        dist = next((cfg[1].get("latency", {}).get("dist", "constant")
                     for cfg in CONFIGS if cfg[0] == r["config"]),
                    "constant")
        if c["metric"] == "max" and dist != "constant":
            return True
        return False

    fails = [(r["config"], c["metric"], c["deviation_pct"])
             for r in results for c in r["comparison"]
             if gated(r, c) is False]
    # an invalid run (stock-checker failure or any destroyed value) fails
    # the gate outright — quantiles from a lossy run are not evidence
    fails += [(r["config"], "valid", None) for r in results
              if not r["measured"].get("valid")
              or (r["measured"].get("lost") or 0) > 0]
    worst = max((abs(c["deviation_pct"]) for r in results
                 for c in r["comparison"]
                 if c["deviation_pct"] is not None), default=0.0)
    print(json.dumps({"parity_configs": len(results),
                      "worst_deviation_pct": worst,
                      "gate_failures": fails}))
    return 0 if not fails else 1


if __name__ == "__main__":
    sys.exit(main())
