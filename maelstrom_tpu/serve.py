"""`serve`: a web server over the store directory, for browsing past test
runs (the counterpart of jepsen's serve-cmd, reference `core.clj:230`,
`doc/results.md:7-10`)."""

from __future__ import annotations

import http.server
import json
import os
import socketserver
from functools import partial


class StoreHandler(http.server.SimpleHTTPRequestHandler):
    """Serves store files, rendering directory listings with validity
    badges pulled from results.json."""

    def list_directory(self, path):
        try:
            entries = sorted(os.listdir(path))
        except OSError:
            self.send_error(404)
            return None
        rel = os.path.relpath(path, self.directory)
        rows = []
        for name in entries:
            full = os.path.join(path, name)
            badge = ""
            results = os.path.join(full, "results.json")
            if os.path.isdir(full) and os.path.exists(results):
                try:
                    with open(results) as f:
                        valid = json.load(f).get("valid")
                    color = {"True": "#2ca02c", "False": "#d62728"}.get(
                        str(valid), "#ff7f0e")
                    badge = (f' <span style="color:{color}">'
                             f'[valid: {valid}]</span>')
                except Exception:
                    pass
            slash = "/" if os.path.isdir(full) else ""
            rows.append(f'<li><a href="{name}{slash}">{name}{slash}</a>'
                        f'{badge}</li>')
        body = (f"<html><head><title>store: {rel}</title></head><body>"
                f"<h2>{rel}</h2><ul>{''.join(rows)}</ul></body></html>")
        encoded = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(encoded)))
        self.end_headers()
        self.wfile.write(encoded)
        return None


def serve(store_root: str = "store", port: int = 8080):
    handler = partial(StoreHandler, directory=os.path.abspath(store_root))
    with socketserver.TCPServer(("", port), handler) as httpd:
        print(f"Serving {store_root} on http://localhost:{port}")
        httpd.serve_forever()
