"""`serve`: a web server over the store directory, for browsing past test
runs (the counterpart of jepsen's serve-cmd, reference `core.clj:230`,
`doc/results.md:7-10`)."""

from __future__ import annotations

import html
import http.server
import json
import os
import socketserver
from functools import partial


def _badge(valid):
    # valid comes from results.json — attacker-shaped on a shared store
    color = {"True": "#2ca02c", "False": "#d62728"}.get(
        str(valid), "#ff7f0e")
    return f'<span style="color:{color}">{html.escape(str(valid))}</span>'


def _scan_runs(root):
    """All runs under store/<workload>/<timestamp>/, newest first:
    (workload, ts, valid, op-count, rel-path)."""
    runs = []
    skip = {"latest", "current"}
    for wl in sorted(os.listdir(root) if os.path.isdir(root) else ()):
        wdir = os.path.join(root, wl)
        if wl in skip or not os.path.isdir(wdir):
            continue
        for ts in os.listdir(wdir):
            rdir = os.path.join(wdir, ts)
            if ts in skip or not os.path.isdir(rdir):
                continue
            # a run dir is one the test harness wrote: results.json (or
            # at least a history) — anything else (net-journal/, logs)
            # is reachable through the per-run listing, not the index
            results = os.path.join(rdir, "results.json")
            if not (os.path.exists(results)
                    or os.path.exists(os.path.join(rdir,
                                                   "history.jsonl"))):
                continue
            valid, ops = "?", ""
            try:
                with open(results) as f:
                    res = json.load(f)
                valid = res.get("valid")
                ops = (res.get("stats") or {}).get("count", "")
            except Exception:
                pass
            runs.append((wl, ts, valid, ops, f"{wl}/{ts}/"))
    runs.sort(key=lambda r: r[1], reverse=True)
    return runs


class StoreHandler(http.server.SimpleHTTPRequestHandler):
    """Serves store files; the root renders a run-index table (jepsen's
    serve gives the same sortable overview, `core.clj:230`), deeper
    directories render listings with validity badges from results.json."""

    def list_directory(self, path):
        if os.path.abspath(path) == os.path.abspath(self.directory):
            return self._index(path)
        return self._listing(path)

    def _index(self, path):
        # directory names and results.json fields are untrusted text:
        # html.escape every interpolation (quote=True in href contexts)
        rows = []
        for wl, ts, valid, ops, rel in _scan_runs(path):
            links = " ".join(
                f'<a href="{html.escape(rel + name, quote=True)}">'
                f'{label}</a>'
                for name, label in [("results.json", "results"),
                                    ("history.jsonl", "history"),
                                    ("node-logs/", "logs"),
                                    ("", "files")]
                if name == "" or os.path.exists(os.path.join(path, rel,
                                                             name)))
            rows.append(f"<tr><td><a href='"
                        f"{html.escape(rel, quote=True)}'>"
                        f"{html.escape(ts)}</a></td>"
                        f"<td>{html.escape(wl)}</td>"
                        f"<td>{_badge(valid)}</td>"
                        f"<td style='text-align:right'>"
                        f"{html.escape(str(ops))}</td>"
                        f"<td>{links}</td></tr>")
        # raw listing escape hatch: in-progress runs (no results.json
        # yet) and loose store entries stay reachable per-workload
        dirs = " ".join(
            f'<a href="{html.escape(d, quote=True)}/">'
            f'{html.escape(d)}/</a>'
            for d in sorted(os.listdir(path))
            if os.path.isdir(os.path.join(path, d)))
        body = (
            "<html><head><title>maelstrom-tpu runs</title><style>"
            "body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}"
            "th{cursor:pointer;text-decoration:underline dotted}"
            "td,th{padding:.3em .8em;border-bottom:1px solid #ddd;"
            "text-align:left}</style>"
            # column-click sorting, like jepsen's run table (core.clj:230)
            "<script>function srt(c){const t=document.querySelector"
            "('table'),r=[...t.rows].slice(1),d=t.dataset.d!==String(c)"
            "||t.dataset.a!=='1';t.dataset.d=c;t.dataset.a=d?'1':'0';"
            "const f=s=>/^-?\\d+(\\.\\d+)?$/.test(s)?parseFloat(s):null;"
            "r.sort((x,y)=>{const a=x.cells[c].innerText,"
            "b=y.cells[c].innerText,na=f(a),nb=f(b);"
            "return (na!==null&&nb!==null?na-nb:a.localeCompare(b))"
            "*(d?1:-1)});"
            "r.forEach(e=>t.appendChild(e))}</script></head><body>"
            f"<h2>runs ({len(rows)})</h2>"
            "<table><tr>"
            + "".join(f"<th onclick='srt({i})'>{h}</th>" for i, h in
                      enumerate(["time", "workload", "valid", "ops",
                                 "links"]))
            + f"</tr>{''.join(rows)}</table>"
            f"<p>browse: {dirs}</p></body></html>")
        return self._send_html(body)

    def _send_html(self, body):
        encoded = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(encoded)))
        self.end_headers()
        self.wfile.write(encoded)
        return None

    def _listing(self, path):
        try:
            entries = sorted(os.listdir(path))
        except OSError:
            self.send_error(404)
            return None
        rel = os.path.relpath(path, self.directory)
        rows = []
        for name in entries:
            full = os.path.join(path, name)
            badge = ""
            results = os.path.join(full, "results.json")
            if os.path.isdir(full) and os.path.exists(results):
                try:
                    with open(results) as f:
                        valid = json.load(f).get("valid")
                    color = {"True": "#2ca02c", "False": "#d62728"}.get(
                        str(valid), "#ff7f0e")
                    badge = (f' <span style="color:{color}">'
                             f'[valid: {html.escape(str(valid))}]'
                             f'</span>')
                except Exception:
                    pass
            slash = "/" if os.path.isdir(full) else ""
            rows.append(f'<li><a href='
                        f'"{html.escape(name + slash, quote=True)}">'
                        f'{html.escape(name)}{slash}</a>'
                        f'{badge}</li>')
        body = (f"<html><head>"
                f"<title>store: {html.escape(rel)}</title></head><body>"
                f'<p><a href="/">run index</a></p>'
                f"<h2>{html.escape(rel)}</h2>"
                f"<ul>{''.join(rows)}</ul></body></html>")
        return self._send_html(body)


def serve(store_root: str = "store", port: int = 8080):
    handler = partial(StoreHandler, directory=os.path.abspath(store_root))
    with socketserver.TCPServer(("", port), handler) as httpd:
        print(f"Serving {store_root} on http://localhost:{port}")
        httpd.serve_forever()
