"""Operation histories.

The history is the framework's core observable artifact: a list of operations
recorded by client worker threads, in the Jepsen style the reference inherits.
Each operation appears (usually) twice: once as an `invoke` and once as a
completion (`ok`, `fail`, or `info`):

  - invoke: the client began the operation
  - ok:     the operation definitely completed
  - fail:   the operation definitely did NOT take place (definite errors,
            reference `client.clj:214-233`)
  - info:   the outcome is unknown (timeouts / indefinite errors); the op may
            take effect at any later time

Checkers are pure functions of histories (reference test strategy,
`test/maelstrom/workload/pn_counter_test.clj`), so Op is a plain dataclass
that round-trips to JSON.

Storage is columnar (struct-of-arrays): scalar fields live in numpy
columns (type/f/process as small interned codes, time/index as int64,
final as bool) with one object column each for values and errors. At
production scale the analysis pipeline — partitioning, pairing,
screening — runs as numpy group-bys over these columns instead of
per-op Python interpretation; `Op` remains the lazy row view for
existing callers, materialized on access and never stored.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterable, Optional

import numpy as np

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

# type codes are fixed (the four Jepsen op types); anything else interns
# past them, so a malformed fixture degrades to a slow code, not a crash
TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}


@dataclass
class Op:
    type: str                   # invoke | ok | fail | info
    f: Optional[str] = None     # e.g. "read", "add", "broadcast", "txn"
    value: Any = None
    process: Any = None         # worker thread id or :nemesis
    time: int = 0               # nanoseconds since test start (virtual or real)
    index: int = -1             # position in the history
    error: Any = None
    final: bool = False         # marks final reads (pn-counter/set checkers)

    def is_invoke(self):
        return self.type == INVOKE

    def is_ok(self):
        return self.type == OK

    def is_fail(self):
        return self.type == FAIL

    def is_info(self):
        return self.type == INFO

    def to_dict(self) -> dict:
        d = {"index": self.index, "type": self.type, "f": self.f,
             "value": self.value, "process": self.process, "time": self.time}
        if self.error is not None:
            d["error"] = self.error
        if self.final:
            d["final"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Op":
        return cls(type=d["type"], f=d.get("f"), value=d.get("value"),
                   process=d.get("process"), time=d.get("time", 0),
                   index=d.get("index", -1), error=d.get("error"),
                   final=d.get("final", False))


def op(type: str, f=None, value=None, **kw) -> Op:
    return Op(type=type, f=f, value=value, **kw)


class _Interner:
    """Bidirectional value<->small-int-code table for a column whose
    domain is tiny (op types, :f names, process ids)."""

    __slots__ = ("values", "codes")

    def __init__(self, seed=()):
        self.values: list = list(seed)
        self.codes: dict = {v: i for i, v in enumerate(self.values)}

    def code(self, v) -> int:
        c = self.codes.get(v)
        if c is None:
            c = len(self.values)
            self.codes[v] = c
            self.values.append(v)
        return c


class Columns:
    """The struct-of-arrays view of a history: trimmed (length-n) column
    arrays plus the intern tables decoding the coded columns. Arrays are
    live views into the history's buffers — append-only, so rows < n are
    immutable and safe to read from analysis worker threads."""

    __slots__ = ("n", "type", "f", "process", "time", "index", "final",
                 "value", "error", "f_table", "process_table")

    def __init__(self, n, type_, f, process, time, index, final, value,
                 error, f_table, process_table):
        self.n = n
        self.type = type_
        self.f = f
        self.process = process
        self.time = time
        self.index = index
        self.final = final
        self.value = value
        self.error = error
        self.f_table = f_table
        self.process_table = process_table


def _obj_array(seq, m: int) -> np.ndarray:
    out = np.empty(m, object)
    out[:] = list(seq)
    return out


class History:
    """An indexed operation history with invoke/completion pairing
    (the analogue of knossos.history/pair-index used by the echo checker,
    reference `workload/echo.clj:49-63`).

    Backed by growable numpy columns; `history[i]` / iteration
    materialize `Op` rows lazily. `append_row` is the no-Op-object hot
    path used by the runners; `soa()` exposes the columns to the
    vectorized checkers."""

    _INIT_CAP = 1024

    def __init__(self, ops: Iterable[Op] = ()):
        self._n = 0
        cap = self._INIT_CAP
        self._type = np.zeros(cap, np.int8)
        self._f = np.zeros(cap, np.int32)
        self._process = np.zeros(cap, np.int32)
        self._time = np.zeros(cap, np.int64)
        self._index = np.zeros(cap, np.int64)
        self._final = np.zeros(cap, bool)
        self._value = np.empty(cap, object)
        self._error = np.empty(cap, object)
        self._types = _Interner((INVOKE, OK, FAIL, INFO))
        self._fs = _Interner()
        self._procs = _Interner()
        for o in ops:
            self.append(o)

    # --- growth ---

    def _grow(self):
        cap = max(2 * len(self._type), self._INIT_CAP)
        for name in ("_type", "_f", "_process", "_time", "_index",
                     "_final", "_value", "_error"):
            old = getattr(self, name)
            new = (np.empty(cap, object) if old.dtype == object
                   else np.zeros(cap, old.dtype))
            new[:self._n] = old[:self._n]
            setattr(self, name, new)

    # --- append paths ---

    def append_row(self, type: str, f=None, value=None, process=None,
                   time: int = 0, error=None, final: bool = False,
                   index: int = -1) -> int:
        """Appends one operation without constructing an Op. Returns the
        row index."""
        i = self._n
        if i >= len(self._type):
            self._grow()
        self._type[i] = self._types.code(type)
        self._f[i] = self._fs.code(f)
        self._process[i] = self._procs.code(process)
        self._time[i] = time
        self._index[i] = i if index < 0 else index
        self._final[i] = final
        self._value[i] = value
        self._error[i] = error
        self._n = i + 1
        return i

    def append(self, o: Op) -> Op:
        if o.index < 0:
            o.index = self._n
        self.append_row(o.type, o.f, o.value, o.process, o.time,
                        o.error, o.final, index=o.index)
        return o

    def extend_columns(self, type, f, value, process, time,
                       error=None, final=None):
        """Segment-append: bulk-appends parallel sequences (one drained
        ring's worth of decoded rows) without materializing per-op
        objects. `type`/`f`/`process` are sequences of raw values
        (interned here); `time` int64-coercible; `value`/`error` object
        sequences; `final` bool array or None."""
        m = len(time)
        while self._n + m > len(self._type):
            self._grow()
        i = self._n
        sl = slice(i, i + m)
        self._type[sl] = np.fromiter((self._types.code(t) for t in type),
                                     np.int8, m)
        self._f[sl] = np.fromiter((self._fs.code(x) for x in f),
                                  np.int32, m)
        self._process[sl] = np.fromiter(
            (self._procs.code(p) for p in process), np.int32, m)
        self._time[sl] = np.asarray(time, np.int64)
        self._index[sl] = np.arange(i, i + m, dtype=np.int64)
        self._final[sl] = (False if final is None
                           else np.asarray(final, bool))
        # elementwise object assignment: np.asarray would collapse
        # equal-length list values into a 2-D array
        self._value[sl] = _obj_array(value, m)
        self._error[sl] = (np.full(m, None, object) if error is None
                           else _obj_array(error, m))
        self._n = i + m

    # --- columnar access ---

    def snapshot_columns(self) -> dict:
        """Struct-of-arrays snapshot for checkpointing: trimmed column
        views plus copies of the intern tables. O(columns) on the main
        thread — no per-row Op materialization (the pre-columnar
        checkpoint path paid a full `list(history)` per save). The
        views stay valid while the run keeps appending: rows below `n`
        are append-only-immutable, and `_grow` replaces buffers (the
        old buffer is never written again), so a background writer may
        pickle the snapshot while the main loop appends."""
        n = self._n
        return {"version": 1, "n": n,
                "type": self._type[:n], "f": self._f[:n],
                "process": self._process[:n], "time": self._time[:n],
                "index": self._index[:n], "final": self._final[:n],
                "value": self._value[:n], "error": self._error[:n],
                "types": list(self._types.values),
                "fs": list(self._fs.values),
                "procs": list(self._procs.values)}

    @classmethod
    def from_columns(cls, snap: dict) -> "History":
        """Rebuilds a History from a `snapshot_columns` dict, losslessly
        (codes, intern tables, and indices are restored verbatim), and
        ready to keep appending."""
        if snap.get("version") != 1:
            raise ValueError(
                f"unknown history-columns version {snap.get('version')!r}")
        h = cls()
        n = int(snap["n"])
        while len(h._type) < n:
            h._grow()
        for attr, key in (("_type", "type"), ("_f", "f"),
                          ("_process", "process"), ("_time", "time"),
                          ("_index", "index"), ("_final", "final"),
                          ("_value", "value"), ("_error", "error")):
            getattr(h, attr)[:n] = snap[key]
        h._types = _Interner(snap["types"])
        h._fs = _Interner(snap["fs"])
        h._procs = _Interner(snap["procs"])
        h._n = n
        return h

    def soa(self) -> Columns:
        n = self._n
        return Columns(n, self._type[:n], self._f[:n], self._process[:n],
                       self._time[:n], self._index[:n], self._final[:n],
                       self._value[:n], self._error[:n],
                       self._fs.values, self._procs.values)

    # --- Op facade ---

    def _materialize(self, i: int) -> Op:
        return Op(type=self._types.values[self._type[i]],
                  f=self._fs.values[self._f[i]],
                  value=self._value[i],
                  process=self._procs.values[self._process[i]],
                  time=int(self._time[i]), index=int(self._index[i]),
                  error=self._error[i], final=bool(self._final[i]))

    @property
    def ops(self) -> list:
        return [self._materialize(i) for i in range(self._n)]

    def __iter__(self):
        for i in range(self._n):
            yield self._materialize(i)

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._materialize(j)
                    for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return self._materialize(i)

    # --- pairing ---

    def pairs_index(self) -> np.ndarray:
        """Vectorized invoke/completion pairing: [n_invokes, 2] int64
        rows of (invoke row, completion row or -1), in invoke order.

        Within one process, ops alternate invoke/completion (a worker is
        blocked until its op completes), so pairing reduces to adjacency
        in per-process order: a stable sort by process groups each
        process's rows in history order, and an invoke pairs with its
        immediate successor iff that successor is a same-process
        completion — exactly the open-slot scan the list form ran,
        as numpy index arithmetic."""
        n = self._n
        if n == 0:
            return np.empty((0, 2), np.int64)
        t = self._type[:n]
        order = np.argsort(self._process[:n], kind="stable")
        ts = t[order]
        is_inv = ts == TYPE_CODES[INVOKE]
        procs = self._process[:n][order]
        paired = np.zeros(n, bool)
        paired[:-1] = (is_inv[:-1] & (ts[1:] != TYPE_CODES[INVOKE])
                       & (procs[1:] == procs[:-1]))
        comp = np.full(n, -1, np.int64)
        good = np.flatnonzero(paired)
        comp[good] = order[good + 1]
        inv_rows = order[is_inv]
        inv_comp = comp[is_inv]
        by_invoke = np.argsort(inv_rows, kind="stable")
        return np.stack([inv_rows[by_invoke], inv_comp[by_invoke]],
                        axis=1)

    def pairs(self) -> list[tuple[Op, Optional[Op]]]:
        """Pairs each invoke with its completion (same process, next
        occurrence). Returns [(invoke, completion-or-None), ...]."""
        return [(self._materialize(i),
                 None if j < 0 else self._materialize(j))
                for i, j in self.pairs_index()]

    # --- filtered views (materialize on demand) ---

    def _where(self, mask) -> list[Op]:
        return [self._materialize(i) for i in np.flatnonzero(mask)]

    def completions(self) -> list[Op]:
        t = self._type[:self._n]
        return self._where(t != TYPE_CODES[INVOKE])

    def oks(self) -> list[Op]:
        return self._where(self._type[:self._n] == TYPE_CODES[OK])

    def invokes(self) -> list[Op]:
        return self._where(self._type[:self._n] == TYPE_CODES[INVOKE])

    def client_ops(self) -> list[Op]:
        nem = self._procs.codes.get("nemesis")
        if nem is None:
            return self.ops
        return self._where(self._process[:self._n] != nem)

    # --- (de)serialization ---

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(o.to_dict(), default=str)
                         for o in self)

    @classmethod
    def from_jsonl(cls, text: str) -> "History":
        h = cls()
        for line in text.splitlines():
            line = line.strip()
            if line:
                d = json.loads(line)
                h.append_row(d["type"], d.get("f"), d.get("value"),
                             d.get("process"), d.get("time", 0),
                             d.get("error"), d.get("final", False),
                             index=d.get("index", -1))
        return h


def coerce_history(history) -> History:
    """Accepts a History, a list of Ops, or a list of dicts (fixture
    style, mirroring the reference's literal-history checker tests)."""
    if isinstance(history, History):
        return history
    h = History()
    for o in history:
        if isinstance(o, Op):
            h.append(o)
        else:
            h.append(Op.from_dict(o))
    return h
