"""Operation histories.

The history is the framework's core observable artifact: a list of operations
recorded by client worker threads, in the Jepsen style the reference inherits.
Each operation appears (usually) twice: once as an `invoke` and once as a
completion (`ok`, `fail`, or `info`):

  - invoke: the client began the operation
  - ok:     the operation definitely completed
  - fail:   the operation definitely did NOT take place (definite errors,
            reference `client.clj:214-233`)
  - info:   the outcome is unknown (timeouts / indefinite errors); the op may
            take effect at any later time

Checkers are pure functions of histories (reference test strategy,
`test/maelstrom/workload/pn_counter_test.clj`), so Op is a plain dataclass
that round-trips to JSON.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"


@dataclass
class Op:
    type: str                   # invoke | ok | fail | info
    f: Optional[str] = None     # e.g. "read", "add", "broadcast", "txn"
    value: Any = None
    process: Any = None         # worker thread id or :nemesis
    time: int = 0               # nanoseconds since test start (virtual or real)
    index: int = -1             # position in the history
    error: Any = None
    final: bool = False         # marks final reads (pn-counter/set checkers)

    def is_invoke(self):
        return self.type == INVOKE

    def is_ok(self):
        return self.type == OK

    def is_fail(self):
        return self.type == FAIL

    def is_info(self):
        return self.type == INFO

    def to_dict(self) -> dict:
        d = {"index": self.index, "type": self.type, "f": self.f,
             "value": self.value, "process": self.process, "time": self.time}
        if self.error is not None:
            d["error"] = self.error
        if self.final:
            d["final"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Op":
        return cls(type=d["type"], f=d.get("f"), value=d.get("value"),
                   process=d.get("process"), time=d.get("time", 0),
                   index=d.get("index", -1), error=d.get("error"),
                   final=d.get("final", False))


def op(type: str, f=None, value=None, **kw) -> Op:
    return Op(type=type, f=f, value=value, **kw)


class History:
    """An indexed operation history with invoke/completion pairing
    (the analogue of knossos.history/pair-index used by the echo checker,
    reference `workload/echo.clj:49-63`)."""

    def __init__(self, ops: Iterable[Op] = ()):
        self.ops: list[Op] = []
        for o in ops:
            self.append(o)

    def append(self, o: Op) -> Op:
        if o.index < 0:
            o.index = len(self.ops)
        self.ops.append(o)
        return o

    def __iter__(self):
        return iter(self.ops)

    def __len__(self):
        return len(self.ops)

    def __getitem__(self, i):
        return self.ops[i]

    def pairs(self) -> list[tuple[Op, Optional[Op]]]:
        """Pairs each invoke with its completion (same process, next
        occurrence). Returns [(invoke, completion-or-None), ...]."""
        out = []
        open_by_process: dict[Any, int] = {}
        for o in self.ops:
            if o.type == INVOKE:
                open_by_process[o.process] = len(out)
                out.append((o, None))
            elif o.process in open_by_process:
                i = open_by_process.pop(o.process)
                out[i] = (out[i][0], o)
        return out

    def completions(self) -> list[Op]:
        return [o for o in self.ops if o.type in (OK, FAIL, INFO)]

    def oks(self) -> list[Op]:
        return [o for o in self.ops if o.type == OK]

    def invokes(self) -> list[Op]:
        return [o for o in self.ops if o.type == INVOKE]

    def client_ops(self) -> list[Op]:
        return [o for o in self.ops if o.process != "nemesis"]

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(o.to_dict(), default=str)
                         for o in self.ops)

    @classmethod
    def from_jsonl(cls, text: str) -> "History":
        h = cls()
        for line in text.splitlines():
            line = line.strip()
            if line:
                h.append(Op.from_dict(json.loads(line)))
        return h


def coerce_history(history) -> History:
    """Accepts a History, a list of Ops, or a list of dicts (fixture
    style, mirroring the reference's literal-history checker tests)."""
    if isinstance(history, History):
        return history
    h = History()
    for o in history:
        if isinstance(o, Op):
            h.append(o)
        else:
            h.append(Op.from_dict(o))
    return h
