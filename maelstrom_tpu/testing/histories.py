"""Shared randomized-history generators for property/equivalence
suites (and bench.py's screen fixtures): seeded, deterministic,
concurrency-shaped like real runner output. Moved out of
tests/test_overlap_equivalence.py so the elle device-path suites
(tests/test_elle_device.py, tests/test_edge_oracle.py) and the
checker bench pin all implementations against the SAME generator.
"""

from __future__ import annotations

import random

from ..history import History, Op


def random_register_history(seed, n=500, keys=4, workers=6,
                            info_rate=0.08, fail_rate=0.05,
                            corrupt=0.0, sequential=False):
    """Registers under a mix of outcomes; corrupt > 0 plants stale
    reads; sequential=True keeps every key in the screen's decidable
    class."""
    rng = random.Random(seed)
    h = History()
    t = 0
    state = {}
    openp = {}
    workers = 1 if sequential else workers
    for i in range(n):
        t += rng.randrange(1, 4)
        p = rng.randrange(workers)
        if p in openp:
            f, k, v = openp.pop(p)
            roll = rng.random()
            if not sequential and roll < fail_rate:
                h.append(Op(type="fail", f=f, value=[k, v], process=p,
                            time=t, error=["abort", "definite"]))
            elif not sequential and roll < fail_rate + info_rate:
                h.append(Op(type="info", f=f, value=[k, v], process=p,
                            time=t, error="net-timeout"))
            else:
                if f == "write":
                    state[k] = v
                val = state.get(k) if f == "read" else v
                if corrupt and f == "read" and rng.random() < corrupt:
                    val = 999
                h.append(Op(type="ok", f=f, value=[k, val], process=p,
                            time=t))
        else:
            f = rng.choice(["read", "write", "write", "read"]
                           + ([] if sequential else ["cas"]))
            k = rng.randrange(keys)
            v = (rng.randrange(5) if f != "cas"
                 else [rng.randrange(5), rng.randrange(5)])
            h.append(Op(type="invoke", f=f, value=[k, v], process=p,
                        time=t))
            openp[p] = (f, k, v)
    return h


def random_append_history(seed, n_txn=150, keys=5, workers=6,
                          corrupt=0.0, empty_reads=False):
    """txn-list-append histories with overlapping invocations: appends
    land atomically at completion, reads observe the then-current list
    (a valid serializable execution when corrupt == 0; corrupt > 0
    plants truncated/reversed reads that seed real anomalies).
    ok/fail/info outcomes mixed like runner output."""
    rng = random.Random(seed)
    h = History()
    t = 0
    lists = {k: [] for k in range(keys)}
    nextv = [0]
    openp = {}
    for i in range(n_txn * 2):
        t += rng.randrange(1, 3)
        p = rng.randrange(workers)
        if p in openp:
            micro, kind = openp.pop(p)
            if kind != "ok":
                h.append(Op(type=kind, f="txn", value=micro, process=p,
                            time=t))
                continue
            done = []
            for f, k, v in micro:
                if f == "append":
                    lists[k].append(v)
                    done.append([f, k, v])
                else:
                    obs = [] if empty_reads else list(lists[k])
                    if corrupt and rng.random() < corrupt:
                        obs = obs[:-1][::-1]
                    done.append([f, k, obs])
            h.append(Op(type="ok", f="txn", value=done, process=p,
                        time=t))
        else:
            micro = []
            for _ in range(rng.randrange(1, 4)):
                k = rng.randrange(keys)
                if not empty_reads and rng.random() < 0.5:
                    nextv[0] += 1
                    micro.append(["append", k, nextv[0]])
                else:
                    micro.append(["r", k, None])
            kind = rng.choices(["ok", "fail", "info"],
                               [0.85, 0.07, 0.08])[0]
            h.append(Op(type="invoke", f="txn", value=micro, process=p,
                        time=t))
            openp[p] = (micro, kind)
    return h
