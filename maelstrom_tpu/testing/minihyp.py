"""A minimal, deterministic property-testing fallback.

Implements the exact hypothesis subset the repo's oracle suites use —
`@settings(max_examples=, deadline=)`, `@given(**strategies)`, and the
`integers`/`booleans`/`tuples`/`lists`/`dictionaries` strategies — in
~150 lines of stdlib Python, so `tests/test_edge_oracle.py` and
`tests/test_tpu_net_oracle.py` run on images where `hypothesis` isn't
baked in (the dev/test extra in pyproject.toml installs the real thing
where pip is available).

Differences from hypothesis, deliberately accepted:
  - no shrinking: a failing example is re-raised with the generated
    inputs attached, not minimized;
  - examples are drawn from a PRNG seeded by the test's qualified name
    (md5, not `hash()` — PYTHONHASHSEED-independent), so every run of a
    given test sees the same schedule: failures reproduce exactly;
  - example 0 is always the all-minimal draw (bounds' minimums, empty
    collections) — the cheapest regression canary first.
"""

from __future__ import annotations

import hashlib
import os
import random

__all__ = ["given", "settings", "strategies", "MiniHypFailure"]


class Strategy:
    def example(self, rng: random.Random):
        raise NotImplementedError

    def minimal(self):
        raise NotImplementedError


class _Integers(Strategy):
    def __init__(self, min_value, max_value):
        self.min_value, self.max_value = int(min_value), int(max_value)

    def example(self, rng):
        return rng.randint(self.min_value, self.max_value)

    def minimal(self):
        return self.min_value


class _Booleans(Strategy):
    def example(self, rng):
        return rng.random() < 0.5

    def minimal(self):
        return False


class _Tuples(Strategy):
    def __init__(self, *strats):
        self.strats = strats

    def example(self, rng):
        return tuple(s.example(rng) for s in self.strats)

    def minimal(self):
        return tuple(s.minimal() for s in self.strats)


class _Lists(Strategy):
    def __init__(self, elements, min_size=0, max_size=10):
        self.elements = elements
        self.min_size, self.max_size = int(min_size), int(max_size)

    def example(self, rng):
        n = rng.randint(self.min_size, self.max_size)
        return [self.elements.example(rng) for _ in range(n)]

    def minimal(self):
        return [self.elements.minimal() for _ in range(self.min_size)]


class _SampledFrom(Strategy):
    def __init__(self, elements):
        self.elements = list(elements)
        if not self.elements:
            raise ValueError("sampled_from needs a non-empty sequence")

    def example(self, rng):
        return rng.choice(self.elements)

    def minimal(self):
        # hypothesis shrinks toward the FIRST element; the fallback's
        # minimal-example-first pass mirrors that
        return self.elements[0]


class _Dicts(Strategy):
    def __init__(self, keys, values, min_size=0, max_size=10):
        self.keys, self.values = keys, values
        self.min_size, self.max_size = int(min_size), int(max_size)

    def example(self, rng):
        n = rng.randint(self.min_size, self.max_size)
        out = {}
        for _ in range(4 * n):          # bounded dedup attempts
            if len(out) >= n:
                break
            out[self.keys.example(rng)] = self.values.example(rng)
        return out

    def minimal(self):
        return {}


class strategies:
    """Namespace mirroring `hypothesis.strategies` for the used subset
    (`from ... import strategies as st` keeps reading naturally)."""

    @staticmethod
    def integers(min_value, max_value):
        return _Integers(min_value, max_value)

    @staticmethod
    def booleans():
        return _Booleans()

    @staticmethod
    def tuples(*strats):
        return _Tuples(*strats)

    @staticmethod
    def lists(elements, min_size=0, max_size=10):
        return _Lists(elements, min_size=min_size, max_size=max_size)

    @staticmethod
    def dictionaries(keys, values, min_size=0, max_size=10):
        return _Dicts(keys, values, min_size=min_size, max_size=max_size)

    @staticmethod
    def sampled_from(elements):
        return _SampledFrom(elements)


class MiniHypFailure(AssertionError):
    pass


def settings(max_examples: int = 20, deadline=None, **_ignored):
    """Stores the example budget on the (already `given`-wrapped)
    function. `deadline` and unknown hypothesis knobs are accepted and
    ignored."""
    def deco(fn):
        fn._minihyp_max_examples = int(max_examples)
        return fn
    return deco


def _seed_for(qualname: str, i: int) -> random.Random:
    digest = hashlib.md5(f"minihyp:{qualname}:{i}".encode()).hexdigest()
    return random.Random(int(digest, 16))


def given(**strats):
    """Keyword-only `@given`: runs the test once per example with fresh
    draws for every strategy. The wrapper takes no parameters, so pytest
    never mistakes strategy names for fixtures."""
    bad = [k for k, s in strats.items() if not isinstance(s, Strategy)]
    if bad:
        raise TypeError(f"given() expects minihyp strategies, got "
                        f"non-strategies for {bad}")

    def deco(fn):
        def wrapper():
            n = getattr(wrapper, "_minihyp_max_examples", 20)
            cap = os.environ.get("MAELSTROM_MINIHYP_MAX_EXAMPLES")
            if cap:
                n = min(n, int(cap))
            qual = getattr(fn, "__qualname__", fn.__name__)
            for i in range(n):
                if i == 0:
                    kwargs = {k: s.minimal() for k, s in strats.items()}
                else:
                    rng = _seed_for(qual, i)
                    kwargs = {k: s.example(rng) for k, s in strats.items()}
                try:
                    fn(**kwargs)
                except Exception as e:
                    shown = {k: repr(v)[:400] for k, v in kwargs.items()}
                    raise MiniHypFailure(
                        f"{qual} failed on example {i}/{n} (no "
                        f"shrinking — minihyp fallback): {shown}") from e
        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = getattr(fn, "__qualname__", fn.__name__)
        wrapper.__doc__ = fn.__doc__
        wrapper.__module__ = fn.__module__
        return wrapper
    return deco
