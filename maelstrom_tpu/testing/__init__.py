"""Test-support utilities shipped with the package.

`minihyp` is a tiny, dependency-free property-testing fallback with a
hypothesis-compatible surface (`given`/`settings`/`strategies`) for the
subset the oracle suites use, so property tests run even on minimal
installs. When the real `hypothesis` is importable it should always be
preferred — it shrinks failures; this fallback only reports them.
"""
