"""Seed-variance study for a parity config: re-runs one config across
PRNG seeds and reports per-quantile spread.

The reference's tutorial numbers are single samples of a noisy
statistic (each stable-latency quantile is an order statistic over
~1000 values whose last-absent read is a race between a randomized read
schedule and propagation). Before attributing a deviation to the
simulation's semantics, measure how much of it is run-to-run variance.

    python -m maelstrom_tpu.parity_seeds "grid 25, 10 ms" 3 4 5 6 7
"""

from __future__ import annotations

import json
import sys


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    from .parity import CONFIGS, run_config
    name = argv[0]
    seeds = [int(s) for s in argv[1:]] or [3, 4, 5]
    cfg = next(c for c in CONFIGS if c[0] == name)
    _, over, expect, src = cfg
    rows = []
    for seed in seeds:
        m = run_config(name, over, seed=seed)
        rows.append({"seed": seed,
                     **{k: m.get(k) for k in
                        ("valid", "server_mpo", "p50", "p95", "p99",
                         "max", "lost")}})
        print(json.dumps(rows[-1]), file=sys.stderr)
    out = {"config": name, "source": src, "reference": expect,
           "seeds": rows}
    for q in ("p50", "p95", "p99", "max"):
        vals = [r[q] for r in rows if r[q] is not None]
        if vals:
            out[q] = {"min": min(vals), "max": max(vals),
                      "mean": round(sum(vals) / len(vals), 1),
                      "reference": expect.get(q)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
