"""Device-resident Elle: jitted dependency-edge construction and an
on-device cycle SCREEN for the txn-list-append checker
(doc/perf.md "device-resident grading").

The host checker (`checkers/elle.py`) builds ww/wr/rw dependency edges
and runs Tarjan SCC in Python — fine at thousands of transactions,
minutes at a million. This module moves the two hot pieces under XLA:

1. **Edge construction** (`_edges_fn`): the per-key version tables
   concatenate into one writer table on the host (a single columnar
   flatten, fed incrementally by the analysis pipeline's stream
   observer on overlapped runs), and the ww consecutive-writer pairs
   plus every read's wr/rw gathers run as one jitted batch of gathers —
   producing the same `(src, dst, kind)` edge set as
   `elle._edges_vectorized` (bit-equality pinned by
   tests/test_elle_device.py and the tests/test_edge_oracle.py property
   suite, with `elle._edges_python` as the oracle).

2. **Cycle screen** (`_screen_fn`): iterative label propagation to a
   fixed point in a `lax.while_loop`. The screen looks for a *strict
   potential* phi: an integer label per transaction that increases
   along every dependency edge (and, for the realtime stage, along the
   realtime closure). Each iteration raises phi by one `segment_max`
   over the edge list (forward reachability coloring) plus one
   `cummax` over the ret-ordered labels (the whole realtime closure in
   one step — the barrier-chain trick of `elle.analyze`, done as a
   prefix max instead of explicit barrier nodes). If the loop reaches
   zero violated constraints, phi is a topological certificate and the
   graph is **definitely acyclic** — Tarjan is skipped outright. If the
   iteration cap is hit or phi stops changing, the screen answers
   *undecided* and the host Tarjan/classification path runs unchanged.
   The screen is sound one-way by construction: a cyclic graph admits
   no strict potential, so it can never converge to zero violations —
   "acyclic" is a definite pass, and G0/G1c/G-single/G2 rendering stays
   bit-equal because it only ever runs on the exact same edge set.

   Two stages, two seeds:
     - data stage: phi0 from the *version potential* (2*version-index+1
       for writers, 2*observed-length for readers) — per-key version
       chains of any depth are satisfied analytically, so typical
       acyclic data graphs certify in a handful of iterations;
     - realtime stage: phi0 from the *ret-rank potential* (position in
       completion order), which satisfies every realtime constraint
       analytically — serial histories certify immediately, and only
       genuine data-vs-time entanglement costs iterations.

Everything here stays int32/bool (no 64-bit widening), uses no device
sorts (the ret order is precomputed on the host with a stable argsort),
and both jitted entry points are traced by the static auditor
(`analyze/jaxpr_audit.checker_step_specs`) under the zero-new-findings
gate.
"""

from __future__ import annotations

import time

import numpy as np

KIND_WW, KIND_WR, KIND_RW = 0, 1, 2
KIND_NAMES = ("ww", "wr", "rw")

# relaxation cap per screen stage: valid histories converge in a
# handful of iterations (the seeds satisfy the deep constraint families
# analytically); anything still violated after this many rounds falls
# back to host Tarjan
SCREEN_CAP = 32

# `--device-checker auto`: the device path only engages past this many
# transactions — below it, jit dispatch overhead beats the win and the
# host path is already instant
AUTO_MIN_TXNS = 1024

_NEG = -(2 ** 30)


def available() -> bool:
    try:
        import jax  # noqa: F401
        return True
    except Exception:       # pragma: no cover - jax is baked into CI
        return False


def resolve(mode, n_txns: int) -> bool:
    """Maps a `--device-checker` value (on/off/auto, None = auto) to a
    concrete use-the-device decision for this history."""
    if mode in (False, "off", "host", "0"):
        return False
    if mode in (True, "on", "1"):
        return available()
    # auto
    return n_txns >= AUTO_MIN_TXNS and available()


def _pad_to(n: int) -> int:
    """Pow-2 shape buckets bound the number of jit retraces."""
    return max(16, 1 << max(0, int(n - 1).bit_length()))


# ---------------------------------------------------------------------------
# Columnar read table (the host-side flatten)
# ---------------------------------------------------------------------------

class ElleColumns:
    """The columnar view of a transaction set's reads: per read, the
    transaction id, an interned key id, and the observed list length —
    everything the jitted edge constructor needs from the read side.
    Built either in one flatten pass (`build_columns`) or incrementally
    by the analysis pipeline's stream observer (`elle.ElleStreamObserver`),
    in which case the flatten cost overlaps device compute."""

    __slots__ = ("tid", "kid", "n", "key_objs", "_key_ids", "micro_ops")

    def __init__(self):
        self.tid: list = []         # txn id per read
        self.kid: list = []         # interned key id per read
        self.n: list = []           # observed list length per read
        self.key_objs: list = []    # interned raw key objects
        self._key_ids: dict = {}    # raw key (or repr fallback) -> id
        self.micro_ops = 0

    def key_id(self, k) -> int:
        try:
            ki = self._key_ids.get(k)
        except TypeError:           # unhashable key: intern by repr
            k2 = repr(k)
            ki = self._key_ids.get(k2)
            if ki is None:
                ki = self._key_ids[k2] = len(self.key_objs)
                self.key_objs.append(k)
            return ki
        if ki is None:
            ki = self._key_ids[k] = len(self.key_objs)
            self.key_objs.append(k)
        return ki

    def add_txn(self, tid: int, micro) -> None:
        """Appends one OK transaction's reads to the table. The read
        filter MUST match the host edge builders' `isinstance(v, list)`
        — a narrower check (e.g. exact-type) would silently drop a
        list-subclass read's wr/rw constraints from the screen, letting
        it certify a graph whose true edge set is cyclic."""
        ta, ka, na = self.tid.append, self.kid.append, self.n.append
        self.micro_ops += len(micro)
        for m in micro:
            if m[0] == "r":
                v = m[2]
                if isinstance(v, list):
                    ta(tid)
                    ka(self.key_id(m[1]))
                    na(len(v))

    def key_lut(self, key_idx: dict, hk) -> np.ndarray:
        """Maps interned key ids to positions in the checker's version
        table (`key_idx`, keyed by `hk(key)`); -1 = key never observed."""
        return np.fromiter(
            (key_idx.get(hk(k), -1) for k in self.key_objs),
            np.int32, len(self.key_objs))


def build_columns(txns) -> ElleColumns:
    """One-shot flatten of a transaction list (the non-overlapped path;
    pipeline-fed runs get the same table incrementally from the stream
    observer). One Python pass over the micro-ops; everything after
    is numpy/XLA."""
    cols = ElleColumns()
    add = cols.add_txn
    for i, t in enumerate(txns):
        if t["ok"]:
            add(i, t["micro"])
        else:
            cols.micro_ops += len(t["micro"])
    return cols


# ---------------------------------------------------------------------------
# Jitted kernels (built lazily so the module imports without jax)
# ---------------------------------------------------------------------------

_FNS = None


def _edge_candidates(jnp, writers, slot_key, r_tid, wr_pos, rw_pos):
    """Candidate (src, dst, kind, valid) arrays: ww consecutive-writer
    pairs inside each key's span plus per-read wr/rw writer-table
    gathers — the device form of `elle._edges_vectorized` (duplicates
    allowed; the set view dedups on the host, the screen is
    duplicate-indifferent)."""
    a, b = writers[:-1], writers[1:]
    ww_ok = (slot_key[:-1] == slot_key[1:]) & (slot_key[1:] >= 0) \
        & (a >= 0) & (b >= 0) & (a != b)
    wsrc = writers[jnp.maximum(wr_pos, 0)]
    wr_ok = (wr_pos >= 0) & (r_tid >= 0) & (wsrc >= 0) & (wsrc != r_tid)
    rdst = writers[jnp.maximum(rw_pos, 0)]
    rw_ok = (rw_pos >= 0) & (r_tid >= 0) & (rdst >= 0) & (rdst != r_tid)
    i32 = jnp.int32
    src = jnp.concatenate([a, wsrc, r_tid])
    dst = jnp.concatenate([b, r_tid, rdst])
    kind = jnp.concatenate([
        jnp.full(a.shape, KIND_WW, i32),
        jnp.full(wsrc.shape, KIND_WR, i32),
        jnp.full(r_tid.shape, KIND_RW, i32)])
    valid = jnp.concatenate([ww_ok, wr_ok, rw_ok])
    return src, dst, kind, valid


def _build_fns():
    import jax
    import jax.numpy as jnp

    NEG = jnp.int32(_NEG)

    def seg_max(vals, ids, n):
        return jax.ops.segment_max(vals, ids, num_segments=n)

    def edges_fn(writers, slot_key, r_tid, wr_pos, rw_pos):
        return _edge_candidates(jnp, writers, slot_key, r_tid, wr_pos,
                                rw_pos)

    def screen_fn(writers, slot_key, slot_idx, r_tid, r_n, wr_pos,
                  rw_pos, ret_tid, before_idx, n_txns_pad,
                  do_rt=True):
        """(data_acyclic, full_acyclic, data_iters, full_iters). The
        phi arrays are [n_txns_pad]; padded/absent transactions carry
        no constraints. n_txns_pad is static (shape bucket); with
        do_rt=False (static) the realtime stage compiles out entirely
        (callers with no realtime inputs — it could never certify)."""
        N = int(n_txns_pad)
        src, dst, _kind, valid = _edge_candidates(
            jnp, writers, slot_key, r_tid, wr_pos, rw_pos)
        src_c = jnp.where(valid, src, 0)
        dst_c = jnp.where(valid, dst, 0)

        def data_step(phi):
            contrib = seg_max(jnp.where(valid, phi[src_c] + 1, NEG),
                              dst_c, N)
            return jnp.maximum(phi, contrib)

        def data_viol(phi):
            return jnp.sum(jnp.where(valid, phi[src_c] >= phi[dst_c],
                                     False))

        def rt_bound(phi):
            # phi in ret order; prefix max = the full realtime closure
            # (every txn whose ret precedes my inv) in ONE step
            pr = jnp.where(ret_tid >= 0,
                           phi[jnp.maximum(ret_tid, 0)], NEG)
            m = jax.lax.cummax(pr, axis=0)
            return jnp.where(before_idx >= 0,
                             m[jnp.maximum(before_idx, 0)] + 1, NEG)

        def rt_viol(phi):
            return jnp.sum(jnp.where(before_idx >= 0,
                                     phi < rt_bound(phi), False))

        def fixpoint(phi0, step, viol):
            def cond(c):
                phi, it, v, changed = c
                return (v > 0) & changed & (it < SCREEN_CAP)

            def body(c):
                phi, it, _v, _ch = c
                nphi = step(phi)
                return (nphi, it + 1, viol(nphi),
                        jnp.any(nphi != phi))

            phi, it, v, _ = jax.lax.while_loop(
                cond, body, (phi0, jnp.int32(0), viol(phi0),
                             jnp.bool_(True)))
            return phi, it, v

        # --- data stage: version-potential seed -------------------------
        w_ids = jnp.where(writers >= 0, writers, 0)
        phi_w = seg_max(jnp.where(writers >= 0, 2 * slot_idx + 1, NEG),
                        w_ids, N)
        r_ids = jnp.where(r_tid >= 0, r_tid, 0)
        phi_r = seg_max(jnp.where(r_tid >= 0, 2 * r_n, NEG), r_ids, N)
        phi0 = jnp.maximum(jnp.int32(0), jnp.maximum(phi_w, phi_r))
        _phi, it_a, v_a = fixpoint(phi0, data_step, data_viol)
        data_ok = v_a == 0

        if not do_rt:
            return data_ok, jnp.bool_(False), it_a, jnp.int32(0)

        # --- realtime stage: ret-rank seed ------------------------------
        m_pos = jnp.arange(N, dtype=jnp.int32)
        phi_rank = seg_max(jnp.where(ret_tid >= 0, m_pos + 1, NEG),
                           jnp.where(ret_tid >= 0, ret_tid, 0), N)
        phi_rank = jnp.maximum(jnp.int32(0), phi_rank)

        def full_step(phi):
            return jnp.maximum(data_step(phi), rt_bound(phi))

        def full_viol(phi):
            return data_viol(phi) + rt_viol(phi)

        _phi2, it_b, v_b = fixpoint(phi_rank, full_step, full_viol)
        full_ok = v_b == 0
        return data_ok, full_ok, it_a, it_b

    return {
        "edges": jax.jit(edges_fn),
        "screen": jax.jit(screen_fn,
                          static_argnames=("n_txns_pad", "do_rt")),
        "screen_raw": screen_fn,
        "edges_raw": edges_fn,
    }


def _fns():
    global _FNS
    if _FNS is None:
        _FNS = _build_fns()
    return _FNS


# ---------------------------------------------------------------------------
# Host-side assembly
# ---------------------------------------------------------------------------

class DeviceElle:
    """One device analysis: screen verdicts plus a lazy edge-set view.
    `data_acyclic`/`full_acyclic` are definite (True = certified, False
    = undecided, fall back to Tarjan); `edge_set()` materializes the
    Python edge set — identical to `elle._edges_vectorized` — only when
    the fallback actually needs it."""

    def __init__(self, edge_arrays, data_acyclic, full_acyclic, iters,
                 stats):
        self._edge_arrays = edge_arrays     # (src, dst, kind, valid)
        self.data_acyclic = bool(data_acyclic)
        self.full_acyclic = bool(full_acyclic)
        self.iters = iters
        self.stats = stats
        self._set = None

    def edge_set(self) -> set:
        if self._set is None:
            src, dst, kind, valid = (np.asarray(a)
                                     for a in self._edge_arrays)
            m = np.asarray(valid)
            s, d, k = src[m].tolist(), dst[m].tolist(), kind[m].tolist()
            self._set = set(zip(s, d, (KIND_NAMES[x] for x in k)))
        return self._set

    def report(self) -> dict:
        """The deterministic `device` block for checker results (no
        wall times here — those ride TransferStats)."""
        return {
            "screen": {
                "data": "acyclic" if self.data_acyclic else "undecided",
                "realtime": ("acyclic" if self.full_acyclic
                             else "undecided"),
                "iters": list(self.iters),
            },
            "edges-on-device": True,
            **self.stats,
        }


def _writer_table(longest, appender, hk):
    """Concatenated per-key version tables: (writers, slot_key,
    slot_idx, offsets, lens, key_idx) — the host half of the per-key
    version-table merge (one dict gather per version, then numpy)."""
    keys = list(longest)
    key_idx = {kk: i for i, kk in enumerate(keys)}
    nk = len(keys)
    lens = np.fromiter((len(longest[kk]) for kk in keys), np.int64,
                       nk) if nk else np.zeros(0, np.int64)
    offsets = np.zeros(nk + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    ag = appender.get
    writers = np.fromiter(
        (ag((kk, v), -1) for kk in keys for v in longest[kk]),
        np.int64, total) if total else np.zeros(0, np.int64)
    slot_key = np.repeat(np.arange(nk, dtype=np.int64), lens) \
        if total else np.zeros(0, np.int64)
    slot_idx = np.arange(total, dtype=np.int64) - offsets[slot_key] \
        if total else np.zeros(0, np.int64)
    return writers, slot_key, slot_idx, offsets, lens, key_idx


def _padded(arr, n, fill, dtype=np.int32):
    out = np.full(n, fill, dtype)
    out[:len(arr)] = arr
    return out


def read_positions(columns: ElleColumns, key_idx: dict, offsets, lens,
                   hk):
    """Read-side arrays + writer-table gather positions (host numpy,
    exactly the `_edges_vectorized` index math): (tid, n, wr_pos,
    rw_pos), -1 positions masked."""
    n_reads = len(columns.tid)
    if n_reads and len(columns.key_objs):
        lut = columns.key_lut(key_idx, hk)
        ki = lut[np.asarray(columns.kid, np.int64)]
        n_ = np.asarray(columns.n, np.int64)
        tid = np.asarray(columns.tid, np.int64)
        ks = np.maximum(ki, 0)
        has = (ki >= 0) & (n_ > 0)
        if len(lens):
            wr_pos = np.where(has, offsets[ks] + n_ - 1, -1)
            can = (ki >= 0) & (n_ < lens[ks])
            rw_pos = np.where(can, offsets[ks] + n_, -1)
        else:
            wr_pos = rw_pos = np.full(n_reads, -1, np.int64)
        return tid, n_, wr_pos, rw_pos
    z = np.zeros(0, np.int64)
    return z, z, z, z


def device_args(writers, slot_key, slot_idx, tid, n_, wr_pos, rw_pos,
                ok_tids, before, n_txns):
    """The ONE host->device assembly (pow-2 shape-bucket padding + the
    per-txn realtime index scatter), shared by `screen_arrays` and the
    checker bench so measured timings always describe the production
    path. Returns (edge_args, screen_args, n_txns_pad, have_rt) —
    `screen_args` feeds `_fns()["screen"]` (add n_txns_pad/do_rt kw),
    `edge_args` feeds `_fns()["edges"]`."""
    vp = _pad_to(max(len(writers), 1))
    rp = _pad_to(max(len(tid), 1))
    tp = _pad_to(max(n_txns, 1))
    d_writers = _padded(writers, vp, -1)
    d_slot_key = _padded(slot_key, vp, -1)
    d_slot_idx = _padded(slot_idx, vp, 0)
    d_tid = _padded(tid, rp, -1)
    d_n = _padded(n_, rp, 0)
    d_wr = _padded(wr_pos, rp, -1)
    d_rw = _padded(rw_pos, rp, -1)
    have_rt = len(ok_tids) > 0
    ret_tid = _padded(np.asarray(ok_tids, np.int64), tp, -1)
    before_of = np.full(tp, -1, np.int32)
    if have_rt:
        before_of[np.asarray(ok_tids, np.int64)] = \
            np.asarray(before, np.int64)
    edge_args = (d_writers, d_slot_key, d_tid, d_wr, d_rw)
    screen_args = (d_writers, d_slot_key, d_slot_idx, d_tid, d_n,
                   d_wr, d_rw, ret_tid, before_of)
    return edge_args, screen_args, tp, have_rt


def screen_arrays(writers, slot_key, slot_idx, tid, n_, wr_pos, rw_pos,
                  ok_tids, before, n_txns, transfer=None,
                  want_edges=True):
    """Pads the host arrays into pow-2 shape buckets, dispatches the
    jitted screen (and optionally the edge constructor), and fetches
    the verdict scalars. The shared device entry point for the checker
    path (`run`) and the stream observer's per-window screen.
    `ok_tids`/`before`: ok txn ids in completion order and each
    position's latest-completion-strictly-before-invocation index
    (-1 = none); pass empty arrays to skip realtime certification.
    Returns a DeviceElle, or None when jax is unavailable."""
    if not available():
        return None
    t0 = time.perf_counter()
    if len(writers) == 0 and len(tid) == 0:
        # no versions and no reads: no edges can exist, and the
        # realtime closure alone is an (interval) partial order
        return DeviceElle((np.zeros(0, np.int32),) * 3
                          + (np.zeros(0, bool),), True, True, (0, 0),
                          {"edge-candidates": 0})

    edge_args, screen_args, tp, have_rt = device_args(
        writers, slot_key, slot_idx, tid, n_, wr_pos, rw_pos, ok_tids,
        before, n_txns)
    fns = _fns()
    import jax
    data_ok, full_ok, it_a, it_b = fns["screen"](
        *screen_args, n_txns_pad=tp, do_rt=have_rt)
    edge_arrays = None
    if want_edges:
        edge_arrays = fns["edges"](*edge_args)
    data_ok, full_ok, it_a, it_b = jax.device_get(
        (data_ok, full_ok, it_a, it_b))
    dt = time.perf_counter() - t0
    if transfer is not None:
        transfer.record_checker(dt)
    if not have_rt and n_txns > 1:
        full_ok = False     # no realtime inputs: never certify realtime
    # the combined certificate covers the data subgraph: a realtime-
    # acyclic graph is data-acyclic even when the version-potential
    # stage alone hit its cap
    data_ok = bool(data_ok) or bool(full_ok)
    return DeviceElle(edge_arrays, data_ok, full_ok,
                      (int(it_a), int(it_b)),
                      {"edge-candidates": int(len(writers) - 1
                                              + 2 * len(tid))
                       if len(writers) else int(2 * len(tid))})


def run(txns, longest, appender, hk, columns: ElleColumns | None = None,
        rt=None, transfer=None, want_edges=True):
    """Runs the device path over one transaction set. `rt` is the
    precomputed realtime structure from `elle.analyze` —
    `(ok_tids_in_ret_order, before)` with `before[i]` the ret-order
    index of the last completion strictly before ok-txn i's invocation
    (-1 if none) — realtime screening is skipped when rt is None.
    Returns a DeviceElle, or None when jax is unavailable."""
    if not available():
        return None
    if columns is None:
        columns = build_columns(txns)
    writers, slot_key, slot_idx, offsets, lens, key_idx = \
        _writer_table(longest, appender, hk)
    tid, n_, wr_pos, rw_pos = read_positions(columns, key_idx, offsets,
                                             lens, hk)
    if rt is not None:
        ok_tids, before = rt
    else:
        ok_tids = before = np.zeros(0, np.int64)
    return screen_arrays(writers, slot_key, slot_idx, tid, n_, wr_pos,
                         rw_pos, ok_tids, before, len(txns),
                         transfer=transfer, want_edges=want_edges)


def edges_device(txns, longest, appender, hk=repr):
    """`edges_impl`-shaped wrapper: the device edge build materialized
    as the Python edge set (benches/tests pin it against both
    `_edges_python` and `_edges_vectorized`). The production checker
    keeps the arrays on device and only materializes on a screen
    fallback."""
    out = run(txns, longest, appender, hk, rt=None)
    if out is None:
        raise RuntimeError("jax unavailable: no device edge path")
    return out.edge_set()
