"""Network statistics checker (reference `src/maelstrom/net/checker.clj`):
journal folds for send/recv/unique-message counts split all/clients/servers,
msgs-per-op, and the Lamport diagram side effect."""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import Checker
from ..history import coerce_history


@dataclass
class TransferStats:
    """Host-transfer accounting: how many times the run drained device
    state to the host (`drains`) and how many bytes crossed (`host_bytes`).

    The production runner's whole performance story is keeping these
    O(host-relevant rounds) — one batched drain per compiled dispatch —
    instead of O(simulated rounds); the TPU-path net-stats checker
    (`runner.tpu_runner.TpuNetStats`) surfaces the counters in every
    result so a regression (an accidental per-round device_get) is
    visible in plain test output and bench records.

    Overlap accounting (the analysis-pipeline counters): `blocked_s` is
    wall time the host spent inside `device_get`, waiting on the device
    — the irreducible synchronization cost; `overlapped_s` is analysis
    worker time that ran concurrently with device compute (history
    pairing, partitioning, incremental screens; see
    `checkers.pipeline.AnalysisPipeline`). A healthy overlapped run
    keeps overlapped_s growing while blocked_s stays flat."""

    drains: int = 0
    host_bytes: int = 0
    blocked_s: float = 0.0
    overlapped_s: float = 0.0
    # checkpoint accounting (doc/checkpoint.md): `ckpt_blocked_s` is
    # main-thread time per save — the sim device pull plus the snapshot
    # of the mutable host state; `ckpt_write_s` is background-writer
    # wall time (pickle + fsync + rename) that overlapped with device
    # compute. Async checkpointing is healthy when write_s dwarfs
    # blocked_s; --sync-checkpoint folds everything into blocked_s.
    ckpt_saves: int = 0
    ckpt_blocked_s: float = 0.0
    ckpt_write_s: float = 0.0
    # device-resident checker accounting (doc/perf.md "device-resident
    # grading"): wall time the elle edge build + cycle screen spent on
    # the device at check time — work that used to be host-blocked
    # Python (nested edge loops + recursive Tarjan) now leaves the
    # host-blocked ledger and shows up here instead.
    checker_device_calls: int = 0
    checker_device_s: float = 0.0
    # host-driver poll accounting (doc/perf.md "vectorized host
    # driver"): `host_polls` counts host poll passes — each a full
    # gather cycle over generator scheduling + the pending-table
    # timeout/deadline scans + inject encode before one compiled
    # dispatch — and `host_poll_s` their wall time. A standalone run
    # books one per stretch/window boundary; the fleet driver books ONE
    # per wave for the whole coalesced fleet, which is the O(waves)-
    # not-O(clusters) claim the fleet_stream bench measures: polls per
    # cluster-round shrink ~linearly with fleet size.
    host_polls: int = 0
    host_poll_s: float = 0.0

    def record_poll(self, seconds: float) -> None:
        """Books one host poll pass (generator scheduling + pending
        scans + inject encode) of `seconds` wall time."""
        self.host_polls += 1
        self.host_poll_s += seconds

    def record_checker(self, seconds: float) -> None:
        """Books one device-checker dispatch (edge build and/or cycle
        screen) of `seconds` wall time."""
        self.checker_device_calls += 1
        self.checker_device_s += seconds

    def record(self, tree) -> None:
        """Count one drain of `tree` (any pytree of device/numpy arrays),
        BEFORE the device_get that materializes it."""
        import jax
        self.drains += 1
        self.host_bytes += sum(int(getattr(x, "nbytes", 0) or 0)
                               for x in jax.tree.leaves(tree))

    def fetch(self, tree):
        """Books one drain AND the host-blocked wall time of the
        device_get that materializes it. Returns the host tree."""
        import time

        import jax
        self.record(tree)
        t0 = time.perf_counter()
        out = jax.device_get(tree)
        self.blocked_s += time.perf_counter() - t0
        return out

    def as_dict(self) -> dict:
        out = {"drains": self.drains, "host-bytes": self.host_bytes,
               "host-blocked-s": round(self.blocked_s, 6),
               "host-overlapped-s": round(self.overlapped_s, 6)}
        if self.ckpt_saves:
            out["ckpt-saves"] = self.ckpt_saves
            out["ckpt-blocked-s"] = round(self.ckpt_blocked_s, 6)
            out["ckpt-write-s"] = round(self.ckpt_write_s, 6)
        if self.checker_device_calls:
            out["checker-device-calls"] = self.checker_device_calls
            out["checker-device-s"] = round(self.checker_device_s, 6)
        if self.host_polls:
            out["host-polls"] = self.host_polls
            out["host-poll-s"] = round(self.host_poll_s, 6)
            # mean host wall per poll pass (per wave, on the fleet
            # driver): the fleet_stream bench's flatness column — an
            # O(1)-in-fleet-size host loop keeps this constant as F
            # grows, an O(F) one grows it linearly
            out["host-wall-per-wave"] = round(
                self.host_poll_s / self.host_polls, 9)
        return out


class NetStatsChecker(Checker):
    name = "net"

    def __init__(self, net):
        self.net = net

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        journal = getattr(self.net, "journal", None)
        if journal is None:
            return {"valid": True, "note": "no journal"}
        # msgs-per-op divides by client invocation count
        # (reference net/checker.clj:55-66)
        op_count = sum(1 for o in history
                       if o.type == "invoke" and o.process != "nemesis")
        stats = journal.stats(op_count=op_count or None)
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                from ..viz.lamport import plot_lamport
                plot_lamport(journal,
                             os.path.join(store_dir, "messages.svg"))
            except Exception as e:      # viz must never fail the test
                stats["viz-error"] = repr(e)
        # batched-payload units (net/host.py `_units`): surfaced only
        # when some message actually carried a batch record, so classic
        # workloads' results stay shaped as before
        if getattr(self.net, "batched_msgs", 0):
            stats["sent-units"] = self.net.sent_units
            stats["recv-units"] = self.net.recv_units
        # flight-recorder counter parity (doc/observability.md): the
        # same message-flow vocabulary the TPU path's device MetricRing
        # reports, booked by the host net — surfaced only on
        # --telemetry runs so classic results keep their shape
        if test.get("telemetry") and \
                hasattr(self.net, "telemetry_counters"):
            stats["telemetry"] = self.net.telemetry_counters()
        # journal ingest volume (counts() includes host-bytes): the host
        # path's analogue of the TPU path's device-drain accounting
        # (TransferStats above, surfaced by TpuNetStats)
        stats["journal"] = journal.counts()
        stats["valid"] = True
        return stats
