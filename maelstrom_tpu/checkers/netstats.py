"""Network statistics checker (reference `src/maelstrom/net/checker.clj`):
journal folds for send/recv/unique-message counts split all/clients/servers,
msgs-per-op, and the Lamport diagram side effect."""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import Checker
from ..history import coerce_history


@dataclass
class TransferStats:
    """Host-transfer accounting: how many times the run drained device
    state to the host (`drains`) and how many bytes crossed (`host_bytes`).

    The production runner's whole performance story is keeping these
    O(host-relevant rounds) — one batched drain per compiled dispatch —
    instead of O(simulated rounds); the TPU-path net-stats checker
    (`runner.tpu_runner.TpuNetStats`) surfaces the counters in every
    result so a regression (an accidental per-round device_get) is
    visible in plain test output and bench records."""

    drains: int = 0
    host_bytes: int = 0

    def record(self, tree) -> None:
        """Count one drain of `tree` (any pytree of device/numpy arrays),
        BEFORE the device_get that materializes it."""
        import jax
        self.drains += 1
        self.host_bytes += sum(int(getattr(x, "nbytes", 0) or 0)
                               for x in jax.tree.leaves(tree))

    def as_dict(self) -> dict:
        return {"drains": self.drains, "host-bytes": self.host_bytes}


class NetStatsChecker(Checker):
    name = "net"

    def __init__(self, net):
        self.net = net

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        journal = getattr(self.net, "journal", None)
        if journal is None:
            return {"valid": True, "note": "no journal"}
        # msgs-per-op divides by client invocation count
        # (reference net/checker.clj:55-66)
        op_count = sum(1 for o in history
                       if o.type == "invoke" and o.process != "nemesis")
        stats = journal.stats(op_count=op_count or None)
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                from ..viz.lamport import plot_lamport
                plot_lamport(journal,
                             os.path.join(store_dir, "messages.svg"))
            except Exception as e:      # viz must never fail the test
                stats["viz-error"] = repr(e)
        # journal ingest volume (counts() includes host-bytes): the host
        # path's analogue of the TPU path's device-drain accounting
        # (TransferStats above, surfaced by TpuNetStats)
        stats["journal"] = journal.counts()
        stats["valid"] = True
        return stats
