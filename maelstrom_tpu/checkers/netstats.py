"""Network statistics checker (reference `src/maelstrom/net/checker.clj`):
journal folds for send/recv/unique-message counts split all/clients/servers,
msgs-per-op, and the Lamport diagram side effect."""

from __future__ import annotations

import os

from . import Checker
from ..history import coerce_history


class NetStatsChecker(Checker):
    name = "net"

    def __init__(self, net):
        self.net = net

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        journal = getattr(self.net, "journal", None)
        if journal is None:
            return {"valid": True, "note": "no journal"}
        # msgs-per-op divides by client invocation count
        # (reference net/checker.clj:55-66)
        op_count = sum(1 for o in history
                       if o.type == "invoke" and o.process != "nemesis")
        stats = journal.stats(op_count=op_count or None)
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                from ..viz.lamport import plot_lamport
                plot_lamport(journal,
                             os.path.join(store_dir, "messages.svg"))
            except Exception as e:      # viz must never fail the test
                stats["viz-error"] = repr(e)
        stats["valid"] = True
        return stats
