"""Linearizability checking over pluggable models (the Knossos role).

The reference checks lin-kv with jepsen.tests.linearizable-register —
per-key Knossos linearizability over independent keys
(`workload/lin_kv.clj:95-102`). Knossos itself checks *arbitrary*
models (knossos.model: register, cas-register, mutex, set, queue...);
this module implements the Wing & Gong / Lowe (WGL) algorithm with
memoization over (linearized-set, model-state) pairs, parameterized the
same way: a `Model` maps (state, op, outcome) to the possible successor
states.

  - ok ops must linearize with their observed results
  - info (indeterminate) ops may take effect at any point after their
    invocation, or never
  - fail ops definitely didn't happen and are excluded

The register model carries the production lin-kv path (histories are
partitioned by key — values are [k, v] tuples, mirroring
jepsen.independent — which keeps each search small); the other models
prove the engine's generality, pinned by the adversarial corpus.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history

INF = float("inf")


class Model:
    """A sequential specification. States must be hashable (they key
    the WGL memo). `apply` returns every state the object could be in
    after linearizing op (f, value) with the given outcome — empty list
    means the op cannot linearize here. For ok ops `value` carries the
    observed result where the op has one (knossos.model/step's ops)."""

    initial = None

    def apply(self, state, f, value, ok: bool) -> list:
        raise NotImplementedError


class RegisterModel(Model):
    """read / write / cas register — jepsen's cas-register model."""

    initial = None

    def apply(self, state, f, value, ok):
        if f == "read":
            if ok:
                return [state] if state == value else []
            return [state]          # indeterminate read: no effect
        if f == "write":
            if ok:
                return [value]
            return [value, state]   # may or may not have happened
        if f == "cas":
            frm, to = value
            if ok:
                return [to] if state == frm else []
            if state == frm:
                return [to, state]
            return [state]
        raise ValueError(f"unknown register op {f!r}")


class MutexModel(Model):
    """acquire / release lock — knossos.model/mutex, holder-aware.
    State: None (free) or the holder id (`value`; an anonymous op is
    its own holder). A release by a non-holder cannot linearize."""

    initial = None

    def apply(self, state, f, value, ok):
        h = value if value is not None else True
        if f == "acquire":
            if ok:
                return [h] if state is None else []
            return [h, None] if state is None else [state]
        if f == "release":
            if ok:
                return [None] if state == h else []
            return [None, state] if state == h else [state]
        raise ValueError(f"unknown mutex op {f!r}")


class SetModel(Model):
    """Linearizable add / read set — knossos.model/set (NOT the CRDT
    g-set checker: a read must observe exactly the linearized set)."""

    initial = frozenset()

    def apply(self, state, f, value, ok):
        if f == "add":
            s2 = state | frozenset((value,))
            return [s2] if ok else [s2, state]
        if f == "read":
            if ok:
                return [state] if state == frozenset(value) else []
            return [state]
        raise ValueError(f"unknown set op {f!r}")


class QueueModel(Model):
    """FIFO enqueue / dequeue — knossos.model/unordered-queue's ordered
    sibling. State: tuple of pending values; a dequeue's observed value
    must be the head."""

    initial = ()

    def apply(self, state, f, value, ok):
        if f == "enqueue":
            s2 = state + (value,)
            return [s2] if ok else [s2, state]
        if f == "dequeue":
            if ok:
                return ([state[1:]] if state and state[0] == value
                        else [])
            return [state[1:], state] if state else [state]
        raise ValueError(f"unknown queue op {f!r}")


MODELS = {"register": RegisterModel, "mutex": MutexModel,
          "set": SetModel, "queue": QueueModel}


def check_history(ops, model: Model | None = None,
                  max_states: int = 5_000_000):
    """ops: [{f, value, inv, ret, ok}] with ret=INF for indeterminate ops.
    Returns {"valid": bool|"unknown", ...}.

    Just-in-time linearization (Lowe's WGL refinement, the Knossos-scale
    optimization): a configuration is (i, extra, state) where `i` is the
    invocation-order frontier (every op before it linearized) and
    `extra` the small set of ops linearized ahead of the frontier. The
    naive bitmask form keys its memo on an n-bit mask and scans all n
    ops per expansion — at n=600 it was already at its practical limit;
    this form's memo key and candidate scan are O(concurrent window)
    (bounded by worker count + open indeterminate ops), so histories of
    many thousands of ops check definitively in seconds."""
    model = model or RegisterModel()
    n = len(ops)
    if n == 0:
        return {"valid": True}
    order = sorted(range(n), key=lambda j: (ops[j]["inv"], ops[j]["ret"]))
    ops = [ops[j] for j in order]
    inv = [o["inv"] for o in ops]
    ret = [o["ret"] for o in ops]

    def norm(i, extra):
        while i < n and i in extra:
            extra = extra - frozenset((i,))
            i += 1
        return i, extra

    def candidates(i, extra):
        """Ops that may linearize next: scan forward from the frontier;
        op j is eligible unless some still-unlinearized op completed
        before j's invocation. Ops are invocation-sorted, so the running
        min-return gate is exact and the scan stops at the first op
        invoked after it (every later op is invoked later still)."""
        out = []
        m = INF
        j = i
        while j < n:
            if j in extra:
                j += 1
                continue
            if inv[j] > m:
                break
            out.append(j)
            if ret[j] < m:
                m = ret[j]
            j += 1
        return out

    seen = set()
    s0 = model.initial
    best = (0, frozenset(), s0)      # deepest configuration reached
    best_n = -1
    stack = [((0, frozenset(), s0), None)]
    while stack:
        (i, extra, state), it = stack.pop()
        if it is None:
            if i == n:
                return {"valid": True}
            key = (i, extra, state)
            if key in seen:
                continue
            seen.add(key)
            if i + len(extra) > best_n:
                best_n, best = i + len(extra), key
            if len(seen) > max_states:
                return {"valid": "unknown",
                        "error": "WGL configuration cap exceeded"}
            it = iter([(j, s2) for j in candidates(i, extra)
                       for s2 in model.apply(state, ops[j]["f"],
                                             ops[j]["value"],
                                             ops[j]["ok"])])
        nxt = next(it, None)
        if nxt is None:
            continue
        j, s2 = nxt
        stack.append(((i, extra, state), it))
        stack.append((norm(i, extra | frozenset((j,))) + (s2,), None))
    # witness: the deepest frontier any linearization reached, and the
    # op stuck there (the Knossos-style "this op cannot linearize" line)
    bi, bextra, bstate = best
    stuck = next((ops[j] for j in range(bi, n) if j not in bextra), None)
    return {"valid": False,
            "explored-configurations": len(seen),
            "op-count": n,
            "linearized-prefix": best_n,
            "final-state": bstate,
            "stuck-op": None if stuck is None else
            {"f": stuck["f"], "value": stuck["value"],
             "ok": stuck["ok"], "inv": stuck["inv"],
             "ret": None if stuck["ret"] == INF else stuck["ret"]}}


def check_register_history(ops, max_states: int = 5_000_000):
    """The register instance of `check_history` (production lin-kv
    path; kept as the stable entry point)."""
    return check_history(ops, RegisterModel(), max_states)


class LinearizableRegisterChecker(Checker):
    """Per-key independent linearizable register checking
    (the jepsen.tests.linearizable-register equivalent)."""

    name = "linear"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        by_key: dict = {}
        for invoke, complete in history.pairs():
            if invoke.f not in ("read", "write", "cas"):
                continue
            if not isinstance(invoke.value, (list, tuple)) or \
                    len(invoke.value) != 2:
                continue
            k, v = invoke.value
            by_key.setdefault(k, []).append((invoke, complete))

        results = {}
        failures = []
        for k, kpairs in sorted(by_key.items(), key=lambda kv: repr(kv[0])):
            ops = []
            for invoke, complete in kpairs:
                if complete is not None and complete.is_fail():
                    continue
                ok = complete is not None and complete.is_ok()
                val = (complete.value[1] if ok and complete.value is not None
                       else invoke.value[1])
                ops.append({"f": invoke.f, "value": val,
                            "inv": invoke.time,
                            "ret": complete.time if ok else INF,
                            "ok": ok})
            r = check_register_history(ops)
            results[str(k)] = r
            if r["valid"] is False:
                failures.append(k)
        valid = (False if failures else
                 ("unknown" if any(r["valid"] == "unknown"
                                   for r in results.values()) else True))
        out = {"valid": valid,
               "key-count": len(by_key),
               "failures": failures or None}
        if failures:
            # surface each failed key's witness (deepest linearizable
            # prefix + the op that cannot linearize) in the results file
            out["witnesses"] = {
                str(k): {kk: results[str(k)][kk]
                         for kk in ("linearized-prefix", "op-count",
                                    "final-state", "stuck-op")
                         if kk in results[str(k)]}
                for k in failures}
        return out
