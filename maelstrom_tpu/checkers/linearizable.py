"""Linearizability checking over pluggable models (the Knossos role).

The reference checks lin-kv with jepsen.tests.linearizable-register —
per-key Knossos linearizability over independent keys
(`workload/lin_kv.clj:95-102`). Knossos itself checks *arbitrary*
models (knossos.model: register, cas-register, mutex, set, queue...);
this module implements the Wing & Gong / Lowe (WGL) algorithm with
memoization over (linearized-set, model-state) pairs, parameterized the
same way: a `Model` maps (state, op, outcome) to the possible successor
states.

  - ok ops must linearize with their observed results
  - info (indeterminate) ops may take effect at any point after their
    invocation, or never
  - fail ops definitely didn't happen and are excluded

The register model carries the production lin-kv path (histories are
partitioned by key — values are [k, v] tuples, mirroring
jepsen.independent — which keeps each search small); the other models
prove the engine's generality, pinned by the adversarial corpus.

At production scale the per-key search is P-compositional: the history
partitions by key with numpy group-bys over the columnar history
(`partition_register`), each partition runs a vectorized *screen*
(`screen_register_arrays`) — sound, never claims validity wrongly —
and only partitions the screen cannot decide fall back to the full WGL
search. Verdicts are bit-identical to the sequential path by
construction: the screen only ever emits the same `{"valid": True}`
the search would, and fallback partitions carry identical op lists.
"""

from __future__ import annotations

import numpy as np

from . import Checker
from ..history import FAIL, OK, TYPE_CODES, coerce_history

INF = float("inf")


class Model:
    """A sequential specification. States must be hashable (they key
    the WGL memo). `apply` returns every state the object could be in
    after linearizing op (f, value) with the given outcome — empty list
    means the op cannot linearize here. For ok ops `value` carries the
    observed result where the op has one (knossos.model/step's ops)."""

    initial = None

    def apply(self, state, f, value, ok: bool) -> list:
        raise NotImplementedError


class RegisterModel(Model):
    """read / write / cas register — jepsen's cas-register model."""

    initial = None

    def apply(self, state, f, value, ok):
        if f == "read":
            if ok:
                return [state] if state == value else []
            return [state]          # indeterminate read: no effect
        if f == "write":
            if ok:
                return [value]
            return [value, state]   # may or may not have happened
        if f == "cas":
            frm, to = value
            if ok:
                return [to] if state == frm else []
            if state == frm:
                return [to, state]
            return [state]
        raise ValueError(f"unknown register op {f!r}")


class MutexModel(Model):
    """acquire / release lock — knossos.model/mutex, holder-aware.
    State: None (free) or the holder id (`value`).

    Anonymous ops (value None) all share the sentinel holder True: in an
    ALL-anonymous history that reduces to knossos's holder-blind mutex
    (held/free), a documented degradation — any anonymous release can
    linearize against any anonymous acquire. What it must NOT do is let
    an anonymous release match a NAMED holder's acquire (that would
    "verify" a lock-stealing history), so mixing the two styles in one
    history raises instead of silently degrading."""

    initial = None

    def apply(self, state, f, value, ok):
        if value is None and state not in (None, True):
            raise ValueError(
                f"mutex history mixes anonymous ops (value None) with "
                f"named holders (current holder {state!r}): anonymous "
                f"identity cannot be checked against named acquires — "
                f"stamp every op's value with its holder (lin_mutex "
                f"does) or none of them")
        h = value if value is not None else True
        if f == "acquire":
            if ok:
                return [h] if state is None else []
            return [h, None] if state is None else [state]
        if f == "release":
            if ok:
                return [None] if state == h else []
            return [None, state] if state == h else [state]
        raise ValueError(f"unknown mutex op {f!r}")


class SetModel(Model):
    """Linearizable add / read set — knossos.model/set (NOT the CRDT
    g-set checker: a read must observe exactly the linearized set)."""

    initial = frozenset()

    def apply(self, state, f, value, ok):
        if f == "add":
            s2 = state | frozenset((value,))
            return [s2] if ok else [s2, state]
        if f == "read":
            if ok:
                return [state] if state == frozenset(value) else []
            return [state]
        raise ValueError(f"unknown set op {f!r}")


class QueueModel(Model):
    """FIFO enqueue / dequeue — knossos.model/unordered-queue's ordered
    sibling. State: tuple of pending values; a dequeue's observed value
    must be the head."""

    initial = ()

    def apply(self, state, f, value, ok):
        if f == "enqueue":
            s2 = state + (value,)
            return [s2] if ok else [s2, state]
        if f == "dequeue":
            if ok:
                return ([state[1:]] if state and state[0] == value
                        else [])
            return [state[1:], state] if state else [state]
        raise ValueError(f"unknown queue op {f!r}")


MODELS = {"register": RegisterModel, "mutex": MutexModel,
          "set": SetModel, "queue": QueueModel}


def check_history(ops, model: Model | None = None,
                  max_states: int = 5_000_000):
    """ops: [{f, value, inv, ret, ok}] with ret=INF for indeterminate ops.
    Returns {"valid": bool|"unknown", ...}.

    Just-in-time linearization (Lowe's WGL refinement, the Knossos-scale
    optimization): a configuration is (i, extra, state) where `i` is the
    invocation-order frontier (every op before it linearized) and
    `extra` the small set of ops linearized ahead of the frontier. The
    naive bitmask form keys its memo on an n-bit mask and scans all n
    ops per expansion — at n=600 it was already at its practical limit;
    this form's memo key and candidate scan are O(concurrent window)
    (bounded by worker count + open indeterminate ops), so histories of
    many thousands of ops check definitively in seconds."""
    model = model or RegisterModel()
    n = len(ops)
    if n == 0:
        return {"valid": True}
    order = sorted(range(n), key=lambda j: (ops[j]["inv"], ops[j]["ret"]))
    ops = [ops[j] for j in order]
    inv = [o["inv"] for o in ops]
    ret = [o["ret"] for o in ops]

    def norm(i, extra):
        while i < n and i in extra:
            extra = extra - frozenset((i,))
            i += 1
        return i, extra

    def candidates(i, extra):
        """Ops that may linearize next: scan forward from the frontier;
        op j is eligible unless some still-unlinearized op completed
        before j's invocation. Ops are invocation-sorted, so the running
        min-return gate is exact and the scan stops at the first op
        invoked after it (every later op is invoked later still)."""
        out = []
        m = INF
        j = i
        while j < n:
            if j in extra:
                j += 1
                continue
            if inv[j] > m:
                break
            out.append(j)
            if ret[j] < m:
                m = ret[j]
            j += 1
        return out

    seen = set()
    s0 = model.initial
    best = (0, frozenset(), s0)      # deepest configuration reached
    best_n = -1
    stack = [((0, frozenset(), s0), None)]
    while stack:
        (i, extra, state), it = stack.pop()
        if it is None:
            if i == n:
                return {"valid": True}
            key = (i, extra, state)
            if key in seen:
                continue
            seen.add(key)
            if i + len(extra) > best_n:
                best_n, best = i + len(extra), key
            if len(seen) > max_states:
                # structured "undecided": the search ran out of state
                # budget, it did NOT find a violation. Overlapped
                # screens and composed checkers defer on this shape
                # instead of special-casing an error string.
                return {"valid": "unknown",
                        "undecided": True,
                        "reason": "max-states",
                        "max-states": max_states,
                        "explored-configurations": len(seen),
                        "op-count": n,
                        "error": "WGL configuration cap exceeded"}
            it = iter([(j, s2) for j in candidates(i, extra)
                       for s2 in model.apply(state, ops[j]["f"],
                                             ops[j]["value"],
                                             ops[j]["ok"])])
        nxt = next(it, None)
        if nxt is None:
            continue
        j, s2 = nxt
        stack.append(((i, extra, state), it))
        stack.append((norm(i, extra | frozenset((j,))) + (s2,), None))
    # witness: the deepest frontier any linearization reached, and the
    # op stuck there (the Knossos-style "this op cannot linearize" line)
    bi, bextra, bstate = best
    stuck = next((ops[j] for j in range(bi, n) if j not in bextra), None)
    return {"valid": False,
            "explored-configurations": len(seen),
            "op-count": n,
            "linearized-prefix": best_n,
            "final-state": bstate,
            "stuck-op": None if stuck is None else
            {"f": stuck["f"], "value": stuck["value"],
             "ok": stuck["ok"], "inv": stuck["inv"],
             "ret": None if stuck["ret"] == INF else stuck["ret"]}}


# --- the vectorized register fast path ---

# f codes inside a register partition's arrays
F_READ, F_WRITE, F_CAS = 0, 1, 2
_F_NAMES = ("read", "write", "cas")


def screen_register_arrays(f, value, inv, ret, ok):
    """The P-composition fast screen for one key's partition, fully
    vectorized. Returns True when the partition is DEFINITELY
    linearizable, None when undecided (the caller falls back to WGL).

    The decidable class: every op ok, only reads and writes, and the
    ops totally ordered in real time (sorted by invocation, no op
    overlaps the next). Real time then admits exactly one linearization
    order — the sorted order — so the partition is linearizable iff a
    sequential replay succeeds: each read observes the latest earlier
    write (or the initial None). The replay is a forward-fill of write
    indices plus one elementwise compare. Sound by construction (a pass
    exhibits a witness order); ties or replay mismatches return None,
    never False, so WGL keeps sole authority over invalid verdicts."""
    n = len(inv)
    if n == 0:
        return True
    f = np.asarray(f)
    ok = np.asarray(ok)
    if not ok.all() or (f == F_CAS).any():
        return None
    order = np.argsort(inv, kind="stable")
    invs = np.asarray(inv, np.float64)[order]
    rets = np.asarray(ret, np.float64)[order]
    if n > 1 and (rets[:-1] > invs[1:]).any():
        return None                      # concurrency: needs the search
    fo = f[order]
    vo = np.asarray(value, object)[order]
    w = fo == F_WRITE
    last_w = np.maximum.accumulate(np.where(w, np.arange(n), -1))
    rpos = np.flatnonzero(~w)
    if rpos.size == 0:
        return True
    prev = last_w[rpos]
    expected = np.empty(rpos.size, object)
    has_w = prev >= 0
    expected[has_w] = vo[prev[has_w]]
    expected[~has_w] = None
    mismatch = vo[rpos] != expected      # object elementwise ==
    if np.any(mismatch):
        return None
    return True


def _screen_ops(ops):
    """Screen adapter for the stable dict-shaped entry point."""
    n = len(ops)
    fmap = {"read": F_READ, "write": F_WRITE, "cas": F_CAS}
    try:
        f = np.fromiter((fmap[o["f"]] for o in ops), np.int8, n)
    except KeyError:
        return None                      # unknown f: let WGL raise
    value = np.empty(n, object)
    value[:] = [o["value"] for o in ops]
    inv = np.fromiter((o["inv"] for o in ops), np.float64, n)
    ret = np.fromiter((o["ret"] for o in ops), np.float64, n)
    ok = np.fromiter((o["ok"] for o in ops), bool, n)
    return screen_register_arrays(f, value, inv, ret, ok)


def check_register_history(ops, max_states: int = 5_000_000,
                           screen: bool = True):
    """The register instance of `check_history` (production lin-kv
    path; kept as the stable entry point). Tries the vectorized screen
    first; only undecided histories pay for the search."""
    if screen and _screen_ops(ops) is True:
        return {"valid": True}
    return check_history(ops, RegisterModel(), max_states)


_is_kv_pair = np.frompyfunc(
    lambda v: isinstance(v, (list, tuple)) and len(v) == 2, 1, 1)
_kv_key = np.frompyfunc(lambda v: v[0], 1, 1)
_completed_value = np.frompyfunc(
    lambda iv, cv, ok: cv[1] if ok and cv is not None else iv[1], 3, 1)


def partition_register(history):
    """Columnar P-composition: partitions a history's register ops by
    key with numpy group-bys. Returns [(key, arrays)] sorted by
    repr(key), where arrays is {"f", "value", "inv", "ret", "ok"} numpy
    columns in invoke order — exactly the per-key op list the
    sequential path builds (fail completions dropped, indeterminate
    rets at +inf, observed read values substituted), without
    materializing one dict per op."""
    history = coerce_history(history)
    soa = history.soa()
    pi = history.pairs_index()
    if len(pi) == 0:
        return []
    inv_rows, comp_rows = pi[:, 0], pi[:, 1]

    # register invokes with well-formed [k, v] values
    fmap = np.full(len(soa.f_table), -1, np.int8)
    for code, name in enumerate(soa.f_table):
        if name in _F_NAMES:
            fmap[code] = _F_NAMES.index(name)
    f = fmap[soa.f[inv_rows]]
    ivals = soa.value[inv_rows]
    keep = (f >= 0) & _is_kv_pair(ivals).astype(bool)
    if not keep.any():
        return []
    inv_rows, comp_rows, f = inv_rows[keep], comp_rows[keep], f[keep]
    ivals = ivals[keep]

    # completion columns (sentinel row -1 reads row 0 then gets masked)
    has_comp = comp_rows >= 0
    safe = np.where(has_comp, comp_rows, 0)
    ctype = np.where(has_comp, soa.type[safe], -1)
    ok = ctype == TYPE_CODES[OK]
    cvals = np.where(has_comp, soa.value[safe], None)
    ret = np.where(ok, soa.time[safe].astype(np.float64), INF)
    inv = soa.time[inv_rows]
    value = _completed_value(ivals, cvals, ok)
    not_fail = ctype != TYPE_CODES[FAIL]

    # group by key (first-appearance interning keeps repr-ties in the
    # sequential path's insertion order)
    codes = {}
    kc = np.fromiter((codes.setdefault(k, len(codes))
                      for k in _kv_key(ivals)), np.int64, len(ivals))
    keys = list(codes)
    order = np.argsort(kc, kind="stable")     # stable: invoke order kept
    bounds = np.searchsorted(kc[order], np.arange(len(keys) + 1))
    parts = []
    for ki in range(len(keys)):
        rows = order[bounds[ki]:bounds[ki + 1]]
        rows = rows[not_fail[rows]]           # fail ops definitely absent
        parts.append((keys[ki], {
            "f": f[rows], "value": value[rows],
            "inv": inv[rows], "ret": ret[rows], "ok": ok[rows]}))
    parts.sort(key=lambda kv: repr(kv[0]))
    return parts


def ops_from_arrays(arrs) -> list[dict]:
    """Materializes one partition's dict-shaped op list for the WGL
    fallback — identical to what the sequential path would have built
    (ints for definite rets, so witnesses render identically)."""
    return [{"f": _F_NAMES[arrs["f"][i]], "value": arrs["value"][i],
             "inv": int(arrs["inv"][i]),
             "ret": int(arrs["ret"][i]) if arrs["ok"][i] else INF,
             "ok": bool(arrs["ok"][i])}
            for i in range(len(arrs["inv"]))]


class LinearizableRegisterChecker(Checker):
    """Per-key independent linearizable register checking
    (the jepsen.tests.linearizable-register equivalent). The default
    path partitions columnarly and screens each partition; pass
    opts={"no_fast": True} for the sequential pure-Python baseline
    (bench/verification use)."""

    name = "linear"
    # the runner only spins up the overlapped analysis pipeline when
    # the test's checker tree contains a consumer of its partitions
    consumes_analysis = True

    def check(self, test, history, opts=None):
        opts = opts or {}
        history = coerce_history(history)
        if opts.get("no_fast"):
            return self._check_sequential(test, history, opts)
        parts = None
        pipeline = (test or {}).get("analysis") if isinstance(test, dict) \
            else None
        if pipeline is not None:
            # overlapped run: partitions (and screen verdicts) were
            # built incrementally while the simulation was still on the
            # device; None means the pipeline didn't cover this history
            parts = pipeline.register_partitions(len(history))
        if parts is None:
            parts = [(k, arrs, None) for k, arrs in
                     partition_register(history)]

        results = {}
        failures = []
        for k, arrs, screened in parts:
            if screened is None:
                screened = screen_register_arrays(
                    arrs["f"], arrs["value"], arrs["inv"], arrs["ret"],
                    arrs["ok"])
            r = ({"valid": True} if screened is True else
                 check_history(ops_from_arrays(arrs), RegisterModel()))
            results[str(k)] = r
            if r["valid"] is False:
                failures.append(k)
        return self._render(results, failures, len(parts))

    def _render(self, results, failures, key_count):
        valid = (False if failures else
                 ("unknown" if any(r["valid"] == "unknown"
                                   for r in results.values()) else True))
        out = {"valid": valid,
               "key-count": key_count,
               "failures": failures or None}
        if failures:
            # surface each failed key's witness (deepest linearizable
            # prefix + the op that cannot linearize) in the results file
            out["witnesses"] = {
                str(k): {kk: results[str(k)][kk]
                         for kk in ("linearized-prefix", "op-count",
                                    "final-state", "stuck-op")
                         if kk in results[str(k)]}
                for k in failures}
        return out

    def _check_sequential(self, test, history, opts=None):
        """The pre-columnar path: per-op Python partitioning + WGL on
        every key. Kept as the equivalence/bench baseline."""
        by_key: dict = {}
        for invoke, complete in history.pairs():
            if invoke.f not in ("read", "write", "cas"):
                continue
            if not isinstance(invoke.value, (list, tuple)) or \
                    len(invoke.value) != 2:
                continue
            k, v = invoke.value
            by_key.setdefault(k, []).append((invoke, complete))

        results = {}
        failures = []
        for k, kpairs in sorted(by_key.items(), key=lambda kv: repr(kv[0])):
            ops = []
            for invoke, complete in kpairs:
                if complete is not None and complete.is_fail():
                    continue
                ok = complete is not None and complete.is_ok()
                val = (complete.value[1] if ok and complete.value is not None
                       else invoke.value[1])
                ops.append({"f": invoke.f, "value": val,
                            "inv": invoke.time,
                            "ret": complete.time if ok else INF,
                            "ok": ok})
            r = check_history(ops, RegisterModel())
            results[str(k)] = r
            if r["valid"] is False:
                failures.append(k)
        return self._render(results, failures, len(by_key))
