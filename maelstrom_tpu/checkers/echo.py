"""Echo checker: every response's :echo equals the invocation's value
(reference `workload/echo.clj:44-63`)."""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


class EchoChecker(Checker):
    name = "echo"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        errs = []
        for invoke, complete in history.pairs():
            if complete is None or not complete.is_ok():
                continue
            got = complete.value
            echoed = got.get("echo") if isinstance(got, dict) else None
            if echoed != invoke.value:
                errs.append(["Expected a message with :echo", invoke.value,
                             "But received", got])
        return {"valid": not errs, "errors": errs or None}
