"""Elle-lite: transactional consistency checking for list-append workloads.

The reference delegates txn-list-append checking to Elle via
jepsen.tests.cycle.append (`workload/txn_list_append.clj:112-124`), checking
up to strict serializability. This is a from-scratch implementation of the
core of Elle's list-append analysis:

1. Per key, infer the version order from the longest observed list; every
   read must be a *prefix* of it (list semantics), else `incompatible-order`.
2. Direct anomalies: G1a (aborted read: observing a value whose append
   failed), G1b (intermediate read: observing a state mid-transaction),
   duplicate elements; cyclic-version-order (the union of all observed
   adjacencies for a key contains a cycle — reads imply contradictory
   version orders, beyond a mere prefix fork); lost-update (two
   transactions load the same version of a key — a read in the same
   transaction, own appends stripped — and both append to it; flagged
   even when no later read ever observes the colliding appends, the
   case the dependency graph alone cannot see); internal (a read
   disagreeing with the transaction's own earlier appends); fuzzy-read
   (Adya P2: two reads in one transaction revealing different
   pre-states — legal at read-committed, fatal at serializable).
3. Dependency graph over transactions: ww (version succession), wr (read
   observes a version), rw (anti-dependency: read of v precedes writer of
   v+1), plus rt (real-time) edges for strict serializability.
4. Cycle detection via Tarjan SCC; cycles are classified G0 (write cycle),
   G1c (ww/wr cycle), G-single (one rw edge), G2 (multiple rw edges,
   some pair adjacent in the witness cycle), G-nonadjacent (multiple rw
   edges, no two adjacent — the shape that additionally violates
   snapshot isolation, per Cerone-Gotsman's adjacent-rw criterion).

Consistency models map to which anomalies are violations:
  read-uncommitted:    G0, dirty reads of aborted state (G1a)
  read-committed:      + G1b, G1c
  serializable:        + G-single, G2 (ignoring rt edges)
  strict-serializable: + the same over the graph including rt edges
"""

from __future__ import annotations

import numpy as np

from . import Checker
from ..history import coerce_history

MODELS = ["read-uncommitted", "read-committed", "serializable",
          "strict-serializable"]


def _txn_ops(history):
    """Extracts transactions: [{id, txn (completed micro-ops), ok, invoke,
    complete}]. fail txns definitely didn't execute; info txns may have."""
    txns = []
    for invoke, complete in history.pairs():
        if invoke.f != "txn":
            continue
        if complete is not None and complete.is_fail():
            continue
        ok = complete is not None and complete.is_ok()
        micro = (complete.value if ok else invoke.value) or []
        txns.append({"id": len(txns), "micro": micro, "ok": ok,
                     "inv": invoke.time,
                     "ret": complete.time if ok else float("inf")})
    return txns


def _fail_appends(history):
    out = set()
    for invoke, complete in history.pairs():
        if invoke.f != "txn" or complete is None or not complete.is_fail():
            continue
        for f, k, v in invoke.value or []:
            if f == "append":
                out.add((_hk(k), _hv(v)))
    return out


def _digraph_cycle(g: dict):
    """One cycle in {node: set(succ)} as a closed node list, or None.
    Iterative coloring DFS (histories can be deep)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in g}
    for root in sorted(g):
        if color[root] != WHITE:
            continue
        path = []
        stack = [(root, iter(sorted(g.get(root, ()))))]
        color[root] = GRAY
        path.append(root)
        while stack:
            node, it = stack[-1]
            for w in it:
                c = color.get(w, WHITE)
                if c == GRAY:
                    return path[path.index(w):] + [w]
                if c == WHITE:
                    color[w] = GRAY
                    path.append(w)
                    stack.append((w, iter(sorted(g.get(w, ())))))
                    break
            else:
                color[node] = BLACK
                path.pop()
                stack.pop()
    return None


def _hk(k):
    return repr(k)


def _hv(v):
    return repr(v)


def _edges_python(txns, longest, appender):
    """Reference (pre-vectorization) dependency-edge construction:
    nested Python loops over every read. Kept as the equivalence oracle
    and the checker-throughput bench baseline."""
    edges: set = set()

    def version_writer(kk, idx):
        if idx <= 0 or idx > len(longest.get(kk, [])):
            return None
        return appender.get((kk, longest[kk][idx - 1]))

    for kk, order in longest.items():
        for i in range(1, len(order)):
            a, b = appender.get((kk, order[i - 1])), \
                appender.get((kk, order[i]))
            if a is not None and b is not None and a != b:
                # same-txn multi-appends don't create edges
                edges.add((a, b, "ww"))

    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f != "r" or not isinstance(v, list):
                continue
            kk = _hk(k)
            n = len(v)
            if n > 0:
                w = version_writer(kk, n)
                if w is not None and w != t["id"]:
                    edges.add((w, t["id"], "wr"))
            nxt = version_writer(kk, n + 1)
            if nxt is not None and nxt != t["id"]:
                edges.add((t["id"], nxt, "rw"))
    return edges


def _edges_vectorized(txns, longest, appender):
    """ww/wr/rw dependency edges from sorted index arrays: per-key
    version orders concatenate into one writer table (offsets +
    gathers), ww edges are the consecutive-writer pairs inside each
    key's span, and each read's wr/rw edges are two table gathers at
    positions offset+n-1 / offset+n. One Python pass flattens reads to
    arrays; everything after is numpy. Produces the identical edge set
    to `_edges_python` (pinned by tests)."""
    edges: set = set()
    keys = list(longest)
    key_idx = {kk: i for i, kk in enumerate(keys)}
    nk = len(keys)
    lens = np.fromiter((len(longest[kk]) for kk in keys), np.int64, nk)
    offsets = np.zeros(nk + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    writers = np.fromiter(
        (appender.get((kk, v), -1)
         for kk in keys for v in longest[kk]),
        np.int64, int(offsets[-1]))

    if len(writers) > 1:
        a, b = writers[:-1], writers[1:]
        same_key = np.ones(len(writers) - 1, bool)
        same_key[offsets[1:-1] - 1] = False     # pairs spanning two keys
        m = same_key & (a >= 0) & (b >= 0) & (a != b)
        edges.update(zip(a[m].tolist(), b[m].tolist(),
                         ("ww",) * int(m.sum())))

    r_tid, r_key, r_n = [], [], []
    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f == "r" and isinstance(v, list):
                r_tid.append(t["id"])
                r_key.append(key_idx.get(_hk(k), -1))
                r_n.append(len(v))
    if r_tid and nk:        # no keyed versions -> no read edges exist
        tid = np.asarray(r_tid, np.int64)
        ki = np.asarray(r_key, np.int64)
        n_ = np.asarray(r_n, np.int64)
        ks = np.maximum(ki, 0)
        # wr: the writer of the version this read observed (its length)
        has = (ki >= 0) & (n_ > 0)
        w = np.full(len(tid), -1, np.int64)
        w[has] = writers[offsets[ks[has]] + n_[has] - 1]
        m = (w >= 0) & (w != tid)
        edges.update(zip(w[m].tolist(), tid[m].tolist(),
                         ("wr",) * int(m.sum())))
        # rw anti-dependency: the writer of the NEXT version
        can = (ki >= 0) & (n_ < lens[ks])
        nxt = np.full(len(tid), -1, np.int64)
        nxt[can] = writers[offsets[ks[can]] + n_[can]]
        m = (nxt >= 0) & (nxt != tid)
        edges.update(zip(tid[m].tolist(), nxt[m].tolist(),
                         ("rw",) * int(m.sum())))
    return edges


def analyze(history, *, edges_impl=None, device=None) -> dict:
    history = coerce_history(history)
    return analyze_txns(_txn_ops(history), _fail_appends(history),
                        edges_impl=edges_impl, device=device)


def analyze_txns(txns, failed_appends, *, edges_impl=None, device=None,
                 columns=None, transfer=None, report=None) -> dict:
    """The anomaly analysis over a pre-extracted transaction set
    (`_txn_ops`-shaped dicts + the failed-append set). `analyze` wraps
    it for plain histories; the checker's stream observer serves the
    same inputs pre-collected by the overlapped pipeline.

    `device` selects the device-resident path (doc/perf.md
    "device-resident grading"): "on"/"off"/"auto" (None = auto). When
    it engages, ww/wr/rw edge construction runs jitted on the device
    (`checkers/elle_device.py`, bit-equal to `_edges_vectorized`) and
    an on-device cycle screen certifies acyclic dependency graphs —
    a definite pass that skips Tarjan entirely; any undecided graph
    falls back to the host Tarjan/classification path on the identical
    edge set, so verdicts are bit-equal by construction. `columns`
    optionally carries the pipeline-prebuilt read table; `transfer`
    books device wall time into the runner's TransferStats; `report`
    (a dict) receives the deterministic device stats block."""
    anomalies: dict[str, list] = {}

    def add_anom(kind, item):
        anomalies.setdefault(kind, []).append(item)

    # appender[(k, v)] = txn id; per-txn appends per key (order within txn)
    appender: dict = {}
    txn_appends: dict = {}      # txn id -> {key: [values]}
    for t in txns:
        per_key = {}
        for f, k, v in t["micro"]:
            if f == "append":
                kk, vv = _hk(k), _hv(v)
                if (kk, vv) in appender:
                    add_anom("duplicate-appends", {"key": k, "value": v})
                appender[(kk, vv)] = t["id"]
                per_key.setdefault(kk, []).append(vv)
        txn_appends[t["id"]] = per_key

    # Longest observed list per key = version order; reads must be prefixes.
    longest: dict = {}
    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f == "r" and isinstance(v, list):
                kk = _hk(k)
                vv = [_hv(x) for x in v]
                if len(vv) > len(longest.get(kk, [])):
                    longest[kk] = vv

    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f != "r" or not isinstance(v, list):
                continue
            kk = _hk(k)
            vv = [_hv(x) for x in v]
            if longest.get(kk, [])[:len(vv)] != vv:
                add_anom("incompatible-order",
                         {"key": k, "read": v, "longest": longest.get(kk)})
            for x, xv in zip(v, vv):
                if (kk, xv) in failed_appends:
                    add_anom("G1a", {"key": k, "value": x,
                                     "txn": t["micro"]})
                elif (kk, xv) not in appender:
                    add_anom("phantom-element", {"key": k, "value": x})
            # G1b: observed the middle of another txn's appends to this key
            writers_in_order = [appender.get((kk, xv)) for xv in vv]
            if writers_in_order:
                last_writer = writers_in_order[-1]
                if last_writer is not None and last_writer != t["id"]:
                    w_appends = txn_appends[last_writer].get(kk, [])
                    if w_appends and vv[-1] != w_appends[-1]:
                        add_anom("G1b", {"key": k, "read": v,
                                         "writer-appends": w_appends})

    # --- internal consistency: within one transaction, a read of k
    # after the transaction's own appends to k must observe those
    # appends, in order, as the list's suffix (the txn is one atomic
    # point: it sees the pre-state plus its own writes so far). Elle's
    # :internal anomaly class.
    # Two rules: (a) own appends so far must be the read's suffix; (b)
    # the pre-state a read reveals (the read minus that suffix) must
    # match what the txn's FIRST read of the key revealed — a txn whose
    # later read shows a different pre-state watched other commits move
    # underneath it mid-transaction.
    for t in txns:
        if not t["ok"]:
            continue
        own_sofar: dict = {}
        pre_seen: dict = {}            # kk -> pre-state from first read
        for f, k, v in t["micro"]:
            kk = _hk(k)
            if f == "append":
                own_sofar.setdefault(kk, []).append(_hv(v))
            elif f == "r" and isinstance(v, list):
                mine = own_sofar.get(kk, [])
                vv = [_hv(x) for x in v]
                if mine and vv[-len(mine):] != mine:
                    add_anom("internal",
                             {"txn": t["id"], "key": k, "read": v,
                              "own-appends": list(mine)})
                    continue
                # a later read revealing a DIFFERENT pre-state than the
                # first is Adya's P2 (fuzzy / non-repeatable read) — a
                # distinct anomaly, legal at read-committed and below,
                # NOT an internal-atomicity break
                pre = vv[:len(vv) - len(mine)] if mine else vv
                if kk in pre_seen and pre_seen[kk] != pre:
                    add_anom("fuzzy-read",
                             {"txn": t["id"], "key": k, "read": v,
                              "first-pre-state": pre_seen[kk],
                              "later-pre-state": pre})
                pre_seen.setdefault(kk, pre)

    # --- cyclic version order: union the adjacencies every observed
    # read asserts for a key; a cycle means no version order can satisfy
    # all reads (a fork is merely incompatible-order; this is stronger)
    vgraph: dict = {}                 # kk -> {a: set of b with a < b}
    raw_key: dict = {}                # kk -> original key object
    raw_val: dict = {}                # (kk, vv) -> original value
    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f == "r" and isinstance(v, list):
                kk = _hk(k)
                raw_key[kk] = k
                vv = [_hv(x) for x in v]
                for x, xv in zip(v, vv):
                    raw_val[(kk, xv)] = x
                g = vgraph.setdefault(kk, {})
                for a, b in zip(vv, vv[1:]):
                    g.setdefault(a, set()).add(b)
    for kk, g in vgraph.items():
        cyc = _digraph_cycle(g)
        if cyc is not None:
            add_anom("cyclic-version-order",
                     {"key": raw_key[kk],
                      "cycle": [raw_val.get((kk, n), n) for n in cyc]})

    # --- lost update: transactions that loaded the SAME version of a
    # key (a read in the same txn; the txn's own tail appends stripped,
    # so a post-append read still reveals the loaded state) and both
    # appended to it. Both cannot serialize after the state they read,
    # so one update is lost. Detected directly from the loads because
    # the colliding appends may never be observed by any later read —
    # the one anomaly here the dependency graph cannot express.
    lu_groups: dict = {}   # (kk, loaded-tuple) -> [txn ids], raw witness
    for t in txns:
        if not t["ok"]:
            continue
        own: dict = {}                # kk -> own values appended so far
        loaded: dict = {}             # kk -> first loaded version
        for f, k, v in t["micro"]:
            kk = _hk(k)
            if f == "append":
                own.setdefault(kk, []).append(_hv(v))
            elif f == "r" and isinstance(v, list) and kk not in loaded:
                vv = [_hv(x) for x in v]
                raw = list(v)
                mine = own.get(kk, [])
                if mine and vv[-len(mine):] == mine:
                    vv, raw = vv[:len(vv) - len(mine)], \
                        raw[:len(raw) - len(mine)]
                loaded[kk] = (tuple(vv), k, raw)
        for kk in own:
            if kk in loaded:
                vv, k, raw = loaded[kk]
                ids, _k, _raw = lu_groups.setdefault(
                    (kk, vv), ([], k, raw))
                ids.append(t["id"])
    for (kk, _vv), (ids, k, raw) in sorted(lu_groups.items()):
        if len(ids) > 1:
            add_anom("lost-update",
                     {"key": k, "loaded": raw, "txns": ids})

    # --- dependency graph ---
    # Realtime structure first (shared by the device screen and the host
    # barrier construction): ok txns in completion order, plus each
    # txn's latest-completion-strictly-before-invocation index — one
    # batched searchsorted over the ret-sorted completion times.
    ok_txns = sorted((t for t in txns if t["ok"]), key=lambda t: t["ret"])
    m = len(ok_txns)
    rets = np.fromiter((t["ret"] for t in ok_txns), np.float64, m)
    invs = np.fromiter((t["inv"] for t in ok_txns), np.float64, m)
    before = np.searchsorted(rets, invs, side="left") - 1

    # Device path (doc/perf.md "device-resident grading"): jitted edge
    # construction + the on-device cycle screen. The screen is sound
    # one-way — "acyclic" is a definite pass that skips Tarjan; any
    # undecided graph falls through to the host walk over the IDENTICAL
    # edge set, so verdicts stay bit-equal by construction.
    from . import elle_device as _device
    dev = None
    if edges_impl is None and _device.resolve(device, len(txns)):
        ok_tids = np.fromiter((t["id"] for t in ok_txns), np.int64, m)
        dev = _device.run(txns, longest, appender, _hk, columns=columns,
                          rt=(ok_tids, before), transfer=transfer)
    if report is not None and dev is not None:
        report.update(dev.report())

    # edges: (src, dst, kind) with kind in ww/wr/rw, built from sorted
    # index arrays (`_edges_vectorized`) or fetched off the device
    # arrays; tests/benches inject `_edges_python` to pin equivalence /
    # measure the speedup. Materialized lazily: a screened-acyclic run
    # never builds the Python edge set at all.
    _edge_cache: list = []

    def edge_set() -> set:
        if not _edge_cache:
            if dev is not None:
                _edge_cache.append(dev.edge_set())
            else:
                _edge_cache.append((edges_impl or _edges_vectorized)(
                    txns, longest, appender))
        return _edge_cache[0]

    # Real-time edges via a barrier chain rather than the O(n^2) transitive
    # closure: each txn points at the barrier for its completion time;
    # barriers chain forward; each txn is pointed at by the latest barrier
    # before its invocation. t1 reaches t2 through barriers iff
    # ret(t1) < inv(t2), preserving exactly the realtime cycles. Built
    # only when the screen did not already certify the combined graph.
    def realtime_edges() -> set:
        rt_edges = set()
        for i in range(len(ok_txns) - 1):
            rt_edges.add((("b", i), ("b", i + 1), "rt"))
        for i, t in enumerate(ok_txns):
            rt_edges.add((t["id"], ("b", i), "rt"))
        for i in np.flatnonzero(before >= 0):
            rt_edges.add((("b", int(before[i])), ok_txns[i]["id"], "rt"))
        return rt_edges

    def cycles_with(edge_set):
        """Tarjan SCC; returns list of cycles (as lists of txn ids)."""
        adj: dict = {}
        for a, b, kind in edge_set:
            adj.setdefault(a, set()).add(b)
        index = {}
        low = {}
        onstack = set()
        stack = []
        sccs = []
        counter = [0]

        def strongconnect(v):
            work = [(v, iter(sorted(adj.get(v, ()), key=repr)))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(adj.get(w, ()), key=repr))))
                        advanced = True
                        break
                    elif w in onstack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in list(adj):
            if v not in index:
                strongconnect(v)
        return sccs

    def txn_ids(scc):
        return sorted(x for x in scc if not isinstance(x, tuple))

    txn_by_id = {t["id"]: t for t in txns}
    _KIND_PRIO = {"rw": 0, "wr": 1, "ww": 2, "rt": 3}

    def _scc_graph(scc, edge_set):
        ids = set(scc)
        adj: dict = {}
        kinds: dict = {}
        for a, b, k in edge_set:
            if a in ids and b in ids:
                adj.setdefault(a, []).append(b)
                # prefer data edges over rt when parallel edges exist
                if (a, b) not in kinds or _KIND_PRIO[k] < _KIND_PRIO[
                        kinds[(a, b)]]:
                    kinds[(a, b)] = k
        return adj, kinds

    def _render(cyc, kinds):
        """cyc is a closed node list (first == last). Rotates it to start
        at a transaction node, then collapses runs of realtime barrier
        hops into single '-[rt]->' steps. Returns (text, ops, kinds)."""
        body = cyc[:-1]
        start = next(i for i, x in enumerate(body)
                     if not isinstance(x, tuple))
        body = body[start:] + body[:start]
        cyc = body + [body[0]]
        steps = []
        last_txn = cyc[0]
        via_rt = False
        for u, v in zip(cyc, cyc[1:]):
            if isinstance(v, tuple):
                via_rt = True
                continue
            kind = "rt" if via_rt else kinds[(u, v)]
            steps.append((last_txn, v, kind))
            last_txn, via_rt = v, False
        text = "  ".join(f"T{a} -[{k}]-> T{b}" for a, b, k in steps)
        ops = {f"T{i}": txn_by_id[i]["micro"]
               for i in txn_ids(cyc) if i in txn_by_id}
        return text, ops, [k for _a, _b, k in steps]

    def explain(scc, edge_set):
        """Renders one concrete cycle through the SCC, Elle-style:
        'T1 -[ww]-> T2 -[rw]-> T1', plus each txn's micro-ops — the
        human-readable evidence for the anomaly. The walk prefers rw >
        wr > ww > rt edges so the rarest dependency kinds (the ones that
        drive the classification) appear in the witness. The caller
        classifies the *rendered* cycle, so the label always matches the
        evidence."""
        adj, kinds = _scc_graph(scc, edge_set)

        def choice_key(u):
            def key(v):
                return (_KIND_PRIO[kinds[(u, v)]], repr(v))
            return key

        # greedy walk until a node repeats: yields a simple cycle
        cur = next((x for x in scc if not isinstance(x, tuple)), scc[0])
        path, seen = [cur], {cur: 0}
        while True:
            cur = sorted(adj[cur], key=choice_key(cur))[0]
            if cur in seen:
                cyc = path[seen[cur]:] + [cur]
                break
            seen[cur] = len(path)
            path.append(cur)
        return _render(cyc, kinds)

    def explain_realtime(scc, edge_set):
        """A witness for a realtime anomaly must actually traverse an rt
        edge; the greedy walk can close a pure data subcycle instead (an
        SCC may contain both). Anchor on an rt edge inside the SCC and
        close the cycle with a BFS path back to its tail — guaranteed to
        exist since the SCC is strongly connected. Returns None when the
        SCC has no rt edge at all."""
        adj, kinds = _scc_graph(scc, edge_set)
        anchor = next(((a, b) for (a, b), k in kinds.items()
                       if k == "rt"), None)
        if anchor is None:
            return None
        a, b = anchor
        # BFS shortest path b -> a
        from collections import deque
        prev = {b: None}
        q = deque([b])
        while q:
            u = q.popleft()
            if u == a:
                break
            for v in sorted(adj.get(u, ()), key=repr):
                if v not in prev:
                    prev[v] = u
                    q.append(v)
        # reconstruct b..a then orient as a -> b -> ... -> a
        back = [a]
        u = a
        while u != b:
            u = prev[u]
            back.append(u)
        back.reverse()                      # b ... a
        cyc = [a] + back                    # a -> b -> ... -> a
        return _render(cyc, kinds)

    def classify_steps(kinds_used):
        inner = set(kinds_used) - {"rt"}
        if inner <= {"ww"}:
            return "G0"
        if inner <= {"ww", "wr"}:
            return "G1c"
        rw = sum(1 for k in kinds_used if k == "rw")
        if rw == 1:
            return "G-single"
        # the witness cycle's steps, cyclically: two rw edges in a row
        # is plain G2; none adjacent anywhere is the shape that also
        # breaks snapshot isolation (every SI-legal cycle has an
        # adjacent rw pair) — report the stronger label. Only claimed
        # for pure data cycles: a cycle that needs an rt hop to close
        # is not an SI-graph cycle, so the SI assertion would overstate
        # the evidence
        if "rt" not in kinds_used:
            n = len(kinds_used)
            adjacent = any(kinds_used[i] == "rw"
                           and kinds_used[(i + 1) % n] == "rw"
                           for i in range(n))
            if not adjacent:
                return "G-nonadjacent"
        return "G2"

    # The screen's "acyclic" is definite: a Tarjan pass over the same
    # graph would find zero multi-node SCCs, so skipping it preserves
    # bit-equal verdicts. An undecided screen (or no device) walks the
    # host path unchanged.
    if dev is not None and dev.data_acyclic:
        base_sccs = []
    else:
        base_sccs = cycles_with(edge_set())
    for scc in base_sccs:
        text, ops, kinds_used = explain(scc, edge_set())
        add_anom(classify_steps(kinds_used),
                 {"txns": txn_ids(scc), "cycle": text, "txn-ops": ops})
    base_cycle_ids = {frozenset(txn_ids(s)) for s in base_sccs}
    if not (dev is not None and dev.full_acyclic):
        combined = edge_set() | realtime_edges()
        for scc in cycles_with(combined):
            if frozenset(txn_ids(scc)) not in base_cycle_ids:
                rendered = explain_realtime(scc, combined)
                if rendered is None:
                    # no rt edge in the SCC: it's a data anomaly whose
                    # SCC boundary merely shifted; the base pass covers
                    # its cycles
                    continue
                text, ops, kinds_used = rendered
                add_anom(classify_steps(kinds_used) + "-realtime",
                         {"txns": txn_ids(scc), "cycle": text,
                          "txn-ops": ops})

    return anomalies


ILLEGAL = {
    # cyclic-version-order is a data-integrity contradiction (no version
    # order exists at all), illegal under every model, like
    # incompatible-order; lost-update is permitted at read-committed
    # (Adya P4 is only proscribed from cursor stability up), so it
    # gates the serializable models only
    "read-uncommitted": {"G0", "G1a", "duplicate-appends",
                         "incompatible-order", "phantom-element",
                         "cyclic-version-order", "internal"},
    "read-committed": {"G0", "G1a", "G1b", "G1c", "duplicate-appends",
                       "incompatible-order", "phantom-element",
                       "cyclic-version-order", "internal"},
    # fuzzy-read (Adya P2) is legal at read-committed and below
    "serializable": {"G0", "G1a", "G1b", "G1c", "G-single", "G2",
                     "G-nonadjacent", "lost-update", "fuzzy-read",
                     "duplicate-appends", "incompatible-order",
                     "phantom-element", "cyclic-version-order", "internal"},
    "strict-serializable": {"G0", "G1a", "G1b", "G1c", "G-single", "G2",
                            "G-nonadjacent", "lost-update", "fuzzy-read",
                            "G0-realtime", "G1c-realtime",
                            "G-single-realtime", "G2-realtime",
                            "G-nonadjacent-realtime",
                            "duplicate-appends", "incompatible-order",
                            "phantom-element", "cyclic-version-order", "internal"},
}


class ElleStreamObserver:
    """Incremental transaction collection for the overlapped analysis
    pipeline (doc/streams.md): fed every completed (invoke, completion)
    pair as drained segments land, it builds the columnar read table
    the device edge constructor consumes (`elle_device.ElleColumns`) —
    so on overlapped runs the host-side flatten cost runs concurrently
    with device compute instead of serializing behind the run — plus
    the failed-append set and the per-txn records `analyze_txns` needs.
    At check time `finish_txns()` re-sorts to invoke order (provisional
    ids remap in one numpy pass), so verdicts are bit-equal to the
    post-hoc `_txn_ops` path by construction.

    With `--device-checker on` each window close additionally runs the
    on-device cycle screen over the prefix collected so far — an
    early-warning per-window verdict ("acyclic so far" vs "candidate
    cycle, Tarjan will classify at check time")."""

    # past this many collected micro-ops the per-window screen stops
    # (its per-close rebuild is O(prefix)); check time is unaffected
    WINDOW_SCREEN_CAP = 200_000

    def __init__(self, test):
        from . import elle_device
        self._ed = elle_device
        dev = (test or {}).get("device_checker")
        self._screen_windows = dev in (True, "on", "1") \
            and elle_device.available()
        self._rows: list = []       # invoke row per collected txn
        self._recs: list = []       # (ok, micro, inv_t, ret_t)
        self.columns = elle_device.ElleColumns()    # provisional tids
        self.failed: set = set()
        self._win_txns = 0
        # provisional-id structures for the window screen only
        self._app_raw: dict = {}    # (key id, value) -> prov txn id
        self._longest_raw: dict = {}            # key id -> raw list
        self._ok_inv: list = []
        self._ok_ret: list = []
        self._ok_pid: list = []
        self._finished = None       # memoized finish_txns result

    def observe(self, inv_row: int, invoke, complete):
        if invoke.f != "txn":
            return
        if complete is not None and complete.is_fail():
            for f, k, v in invoke.value or []:
                if f == "append":
                    self.failed.add((_hk(k), _hv(v)))
            return
        ok = complete is not None and complete.is_ok()
        micro = (complete.value if ok else invoke.value) or []
        pid = len(self._recs)
        self._rows.append(inv_row)
        self._recs.append((ok, micro, invoke.time,
                           complete.time if ok else float("inf")))
        self._win_txns += 1
        if ok:
            self.columns.add_txn(pid, micro)
            self._ok_inv.append(invoke.time)
            self._ok_ret.append(complete.time)
            self._ok_pid.append(pid)
        else:
            self.columns.micro_ops += len(micro)
        if self._screen_windows \
                and self.columns.micro_ops <= self.WINDOW_SCREEN_CAP:
            cols = self.columns
            for m in micro:
                if m[0] == "append":
                    try:
                        vk = (cols.key_id(m[1]), m[2])
                        hash(vk)
                    except TypeError:
                        vk = (cols.key_id(m[1]), repr(m[2]))
                    self._app_raw[vk] = pid
                elif ok and m[0] == "r" and isinstance(m[2], list):
                    ki = cols.key_id(m[1])
                    if len(m[2]) > len(self._longest_raw.get(ki, ())):
                        self._longest_raw[ki] = m[2]

    def observe_open(self, inv_row: int, invoke):
        """Still-open invokes at pipeline finish: indeterminate txns
        (they may have executed — `_txn_ops` includes them)."""
        self.observe(inv_row, invoke, None)

    def window_close(self) -> dict:
        out = {"txns": self._win_txns}
        self._win_txns = 0
        if not self._screen_windows:
            return out
        if self.columns.micro_ops > self.WINDOW_SCREEN_CAP:
            out["screen"] = "deferred"
            return out
        try:
            out["screen"] = self._screen_prefix()
        except Exception as e:      # advisory only; check time decides
            out["screen"] = f"error: {e!r}"
        return out

    def _screen_prefix(self) -> str:
        """Runs the device screen over the prefix collected so far
        (provisional ids — cycle existence is labeling-invariant)."""
        ed = self._ed
        n = len(self._recs)
        app, lng = self._app_raw, self._longest_raw
        lens = np.fromiter((len(v) for v in lng.values()), np.int64,
                           len(lng))
        total = int(lens.sum())

        def wlookup(ki, v):
            try:
                return app.get((ki, v), -1)
            except TypeError:       # unhashable value: stored by repr
                return app.get((ki, repr(v)), -1)

        writers = np.fromiter(
            (wlookup(ki, v) for ki, lst in lng.items() for v in lst),
            np.int64, total) if total else np.zeros(0, np.int64)
        slot_key = np.repeat(np.arange(len(lng), dtype=np.int64), lens)
        offsets = np.zeros(len(lng) + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        slot_idx = (np.arange(total, dtype=np.int64)
                    - offsets[slot_key]) if total else \
            np.zeros(0, np.int64)
        key_pos = {ki: i for i, ki in enumerate(lng)}
        cols = self.columns
        tid = np.asarray(cols.tid, np.int64)
        ki = np.fromiter((key_pos.get(k, -1) for k in cols.kid),
                         np.int64, len(cols.kid))
        n_ = np.asarray(cols.n, np.int64)
        ks = np.maximum(ki, 0)
        has = (ki >= 0) & (n_ > 0)
        wr_pos = np.where(has, offsets[ks] + n_ - 1, -1) \
            if len(lens) else np.full(len(tid), -1)
        can = (ki >= 0) & (n_ < lens[ks]) if len(lens) else \
            np.zeros(len(tid), bool)
        rw_pos = np.where(can, offsets[ks] + n_, -1) \
            if len(lens) else np.full(len(tid), -1)
        rets = np.asarray(self._ok_ret, np.float64)
        invs = np.asarray(self._ok_inv, np.float64)
        order = np.argsort(rets, kind="stable")
        before = np.searchsorted(rets[order], invs[order],
                                 side="left") - 1
        ok_tids = np.asarray(self._ok_pid, np.int64)[order]
        out = ed.screen_arrays(writers, slot_key, slot_idx, tid, n_,
                               wr_pos, rw_pos, ok_tids, before, n,
                               want_edges=False)
        if out is None:
            return "unavailable"
        if out.full_acyclic:
            return "acyclic"
        if out.data_acyclic:
            return "data-acyclic"
        return "undecided"

    def finish_txns(self):
        """(txns, failed_appends, columns) in invoke order — the exact
        `_txn_ops`/`_fail_appends` shape, with the read table's
        provisional ids remapped in one vectorized pass. Memoized: a
        second check() call must not remap the (already-final) ids
        again."""
        if self._finished is not None:
            return self._finished
        n = len(self._recs)
        rows = np.asarray(self._rows, np.int64)
        order = np.argsort(rows, kind="stable")
        final_of = np.empty(n, np.int64)
        final_of[order] = np.arange(n)
        txns = [None] * n
        recs = self._recs
        for newid, p in enumerate(order.tolist()):
            ok, micro, inv_t, ret_t = recs[p]
            txns[newid] = {"id": newid, "micro": micro, "ok": ok,
                           "inv": inv_t, "ret": ret_t}
        cols = self.columns
        if len(cols.tid):
            cols.tid = final_of[np.asarray(cols.tid, np.int64)]
        self._finished = (txns, self.failed, cols)
        return self._finished


class ElleListAppendChecker(Checker):
    name = "elle"
    # the overlapped pipeline feeds this checker's stream observer (the
    # columnar read table the device edge build consumes + windowed
    # early-warning screens); verdicts stay bit-identical to the
    # post-hoc path either way
    consumes_analysis = True

    def __init__(self, consistency_models=("strict-serializable",),
                 device=None):
        self.models = list(consistency_models)
        self.device = device

    def _mode(self, test):
        if self.device is not None:
            return self.device
        return (test or {}).get("device_checker") \
            if isinstance(test, dict) else None

    def make_stream_observer(self, test):
        from . import elle_device
        mode = self._mode(test)
        if mode in (False, "off", "host", "0") \
                or not elle_device.available():
            return None
        return ElleStreamObserver({**(test if isinstance(test, dict)
                                      else {}),
                                   "device_checker": mode})

    def check(self, test, history, opts=None):
        opts = opts or {}
        mode = opts.get("device_checker", self._mode(test))
        transfer = test.get("transfer") if isinstance(test, dict) \
            else None
        report: dict = {}
        served = None
        pipe = test.get("analysis") if isinstance(test, dict) else None
        if pipe is not None and hasattr(pipe, "stream_results"):
            served = pipe.stream_results("elle", len(history))
        if served is not None:
            observer, windows = served
            txns, failed, columns = observer.finish_txns()
            anomalies = analyze_txns(txns, failed, device=mode,
                                     columns=columns, transfer=transfer,
                                     report=report)
        else:
            windows = None
            history = coerce_history(history)
            anomalies = analyze_txns(_txn_ops(history),
                                     _fail_appends(history),
                                     device=mode, transfer=transfer,
                                     report=report)
        illegal = set()
        for m in self.models:
            illegal |= ILLEGAL.get(m, ILLEGAL["strict-serializable"])
        found = {k: v for k, v in anomalies.items() if k in illegal}
        out = {"valid": not found,
               "anomaly-types": sorted(anomalies),
               "anomalies": found or None,
               "models-checked": self.models}
        if report:
            out["device"] = report
        if windows is not None:
            lags = [w.get("lag-rounds") for w in windows
                    if w.get("lag-rounds") is not None]
            out["windows"] = windows
            out["checker-lag"] = {
                "windows": len(windows),
                "max-lag-rounds": max(lags) if lags else 0,
                "mean-lag-rounds": (round(sum(lags) / len(lags), 1)
                                    if lags else 0.0),
            }
        return out
