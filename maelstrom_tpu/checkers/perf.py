"""Perf checker: writes latency-raw.svg, latency-quantiles.svg, rate.svg to
the store dir and reports latency statistics (jepsen checker/perf
equivalent, reference `core.clj:83-84`)."""

from __future__ import annotations

import numpy as np

from . import Checker
from ..history import OK, TYPE_CODES, coerce_history


def _quantile_block(lats: np.ndarray) -> dict:
    """Stats over a SORTED float latency array, with the index rule and
    rounding the sequential path always used (q(p) = lats[min(n-1,
    int(p*n))], round 3)."""
    n = len(lats)
    if not n:
        return {}

    def q(p):
        return float(lats[min(n - 1, int(p * n))])
    return {"count": n, "p50": round(q(0.5), 3),
            "p95": round(q(0.95), 3), "p99": round(q(0.99), 3),
            "max": round(float(lats[-1]), 3)}


def latency_stats(history, by_f: bool = False) -> dict:
    """Latency percentiles over ok client ops, computed columnar: one
    `pairs_index()` pass + numpy masks over the history's
    struct-of-arrays columns — no per-pair Python loop (the pre-ISSUE-13
    path materialized every Op; `_latency_stats_loop` below keeps it as
    the bit-equality oracle). With `by_f`, adds a per-:f breakdown
    under "by-f"."""
    history = coerce_history(history)
    soa = history.soa()
    pairs = history.pairs_index()
    if not len(pairs):
        return {}
    inv, comp = pairs[:, 0], pairs[:, 1]
    try:
        nem = soa.process_table.index("nemesis")
    except ValueError:
        nem = -1
    ok_code = TYPE_CODES[OK]
    safe_comp = np.where(comp >= 0, comp, 0)
    mask = ((comp >= 0) & (soa.process[inv] != nem)
            & (soa.type[safe_comp] == ok_code))
    if not mask.any():
        return {}
    inv, comp = inv[mask], comp[mask]
    lats = (soa.time[comp] - soa.time[inv]) / 1e6
    order = np.argsort(lats, kind="stable")
    out = _quantile_block(lats[order])
    if by_f:
        fcodes = soa.f[inv]
        out["by-f"] = {
            str(soa.f_table[fc]): _quantile_block(
                np.sort(lats[fcodes == fc], kind="stable"))
            for fc in np.unique(fcodes)}
    return out


def _latency_stats_loop(history) -> dict:
    """The original per-pair Python loop, kept as the oracle the
    vectorized path is pinned against (tests/test_perf_stats.py)."""
    lats = []
    for invoke, complete in history.pairs():
        if invoke.process == "nemesis" or complete is None \
                or not complete.is_ok():
            continue
        lats.append((complete.time - invoke.time) / 1e6)
    lats.sort()
    if not lats:
        return {}
    q = lambda p: lats[min(len(lats) - 1, int(p * len(lats)))]  # noqa: E731
    return {"count": len(lats), "p50": round(q(0.5), 3),
            "p95": round(q(0.95), 3), "p99": round(q(0.99), 3),
            "max": round(lats[-1], 3)}


class PerfChecker(Checker):
    name = "perf"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        out = {"valid": True,
               "latency-ms": latency_stats(history, by_f=True)}
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                from ..viz.plots import perf_charts
                perf_charts(history, store_dir)
            except Exception as e:
                out["plot-error"] = repr(e)
        return out


class TimelineChecker(Checker):
    name = "timeline"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                import os
                from ..viz.timeline import render_timeline
                render_timeline(history,
                                os.path.join(store_dir, "timeline.html"))
            except Exception as e:
                return {"valid": True, "error": repr(e)}
        return {"valid": True}
