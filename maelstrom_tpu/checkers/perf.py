"""Perf checker: writes latency-raw.svg, latency-quantiles.svg, rate.svg to
the store dir and reports latency statistics (jepsen checker/perf
equivalent, reference `core.clj:83-84`)."""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


def latency_stats(history) -> dict:
    lats = []
    for invoke, complete in history.pairs():
        if invoke.process == "nemesis" or complete is None \
                or not complete.is_ok():
            continue
        lats.append((complete.time - invoke.time) / 1e6)
    lats.sort()
    if not lats:
        return {}
    q = lambda p: lats[min(len(lats) - 1, int(p * len(lats)))]
    return {"count": len(lats), "p50": round(q(0.5), 3),
            "p95": round(q(0.95), 3), "p99": round(q(0.99), 3),
            "max": round(lats[-1], 3)}


class PerfChecker(Checker):
    name = "perf"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        out = {"valid": True, "latency-ms": latency_stats(history)}
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                from ..viz.plots import perf_charts
                perf_charts(history, store_dir)
            except Exception as e:
                out["plot-error"] = repr(e)
        return out


class TimelineChecker(Checker):
    name = "timeline"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        store_dir = test.get("store_dir")
        if store_dir:
            try:
                import os
                from ..viz.timeline import render_timeline
                render_timeline(history,
                                os.path.join(store_dir, "timeline.html"))
            except Exception as e:
                return {"valid": True, "error": repr(e)}
        return {"valid": True}
