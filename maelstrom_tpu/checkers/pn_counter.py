"""PN-counter interval-arithmetic checker.

Verifies that every final read is the sum of all known-completed adds plus
any subset of possibly-completed (indeterminate) adds. This is the same
interval-set algorithm as the reference (`workload/pn_counter.clj:79-125`):
start with the definite sum, then for each indeterminate add union in a
shifted copy of the acceptable set. Output format matches the reference
checker exactly (see `test/maelstrom/workload/pn_counter_test.clj:7-36`).
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history
from ..intervals import IntervalSet


class PNCounterChecker(Checker):
    name = "pn-counter"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        # Classify adds. Completion-only fixture histories (like the
        # reference's unit test) and full invoke/complete histories both
        # work: an invoke with no completion is indeterminate, exactly like
        # an info completion.
        definite_sum = 0
        indeterminate: list = []
        open_invokes: dict = {}     # process -> Op
        for o in history:
            if o.f != "add":
                continue
            if o.type == "invoke":
                open_invokes[o.process] = o
                continue
            open_invokes.pop(o.process, None)
            if o.is_ok():
                definite_sum += o.value
            elif o.is_info():
                indeterminate.append(o.value)
            # fail: definitely didn't happen
        indeterminate.extend(o.value for o in open_invokes.values())

        acceptable = IntervalSet([(definite_sum, definite_sum)])
        for delta in indeterminate:
            # The add may or may not have happened: allow both outcomes
            # (reference `pn_counter.clj:100-109`).
            acceptable = acceptable.union(acceptable.shift(delta))

        reads = [o for o in history if o.final and o.is_ok()]
        errors = []
        for r in reads:
            assert isinstance(r.value, int), (
                "fractional reads break the interval arithmetic "
                f"(got {r.value!r})")
            if r.value not in acceptable:
                errors.append(r.to_dict())

        return {"valid": not errors,
                "errors": errors or None,
                "final-reads": [r.value for r in reads],
                "acceptable": acceptable.to_vecs()}
