"""Unique-ID checker: every acknowledged `generate` returned a distinct
id. A workload original to this framework (the reference's seven
workloads don't include it; classic Maelstrom ships one) — and the
worked example of doc/tutorial/09-workloads.md, because it is the
smallest checker with a real anomaly to hunt.

Semantics: ok-completed `generate` ops must carry pairwise-distinct
values. `fail`/`info` ops don't constrain anything — an id the client
never received can be reissued (the node may reuse it or not; nobody
observed it). Duplicates are reported with the processes and times of
every collision, so a failing run names its witness like every other
checker here.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


class UniqueIdsChecker(Checker):
    name = "unique-ids"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        seen: dict = {}          # id -> first (process, time)
        dups: dict = {}          # id -> [(process, time), ...]
        attempts = 0
        acked = 0
        for invoke, complete in history.pairs():
            if invoke.f != "generate":
                continue
            attempts += 1
            if complete is None or not complete.is_ok():
                continue
            acked += 1
            v = complete.value
            key = repr(v)
            if key in seen:
                dups.setdefault(key, [seen[key]]).append(
                    (complete.process, complete.time))
            else:
                seen[key] = (complete.process, complete.time)
        # zero observations can't violate uniqueness, but they can't
        # certify it either: "unknown", the codebase convention for
        # no-observation histories (cf. the stats checker) — the stats
        # gate separately fails a run whose generates never succeed
        valid = (False if dups else ("unknown" if acked == 0 else True))
        out = {
            "valid": valid,
            "attempt-count": attempts,
            "acknowledged-count": acked,
            "distinct-count": len(seen),
        }
        if dups:
            out["duplicated-count"] = len(dups)
            out["duplicated"] = {
                k: [{"process": p, "time": t} for p, t in v]
                for k, v in sorted(dups.items())[:16]}
        if acked == 0:
            out["error"] = "no generate op ever succeeded"
        return out
