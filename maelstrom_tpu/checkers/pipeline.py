"""The overlapped analysis pipeline: incremental history analysis that
runs concurrently with the compiled simulation.

The runner's drains hand each newly-completed history segment to a
background worker (`feed`), which does the host-Python analysis work the
sequential checker path would otherwise serialize behind the run:

  - invoke/completion pairing (the open-slot scan),
  - per-key partitioning of register ops (P-compositionality),
  - an incremental per-key linearizability screen (the running replay
    of `screen_register_arrays`' decidable class),
  - stream observers (doc/streams.md): checkers may register an
    incremental grader (`Checker.make_stream_observer`) that is fed
    every completed pair as segments land, grades each drained segment
    as a WINDOW at its close (per-window early-warning verdict +
    checker lag in rounds behind the scan head), and serves its carried
    observation state to the checker at finish,
  - completion stats by :f.

While the TPU executes stretch N+1, the worker chews stretch N. At
check time `LinearizableRegisterChecker` consumes the already-built
partitions (and short-circuits keys whose incremental screen stayed
clean), falling back to the full WGL search only on undecided keys —
verdicts are bit-identical to the sequential path because the screen is
sound and fallback partitions carry identical op lists (pinned by
tests/test_overlap_equivalence.py). `KafkaChecker` likewise consumes
its observer's records (re-sorted to invoke order) through the same
`grade` fold the post-hoc path uses — equal by construction.

The pipeline is strictly an accelerator: any internal error marks it
unusable and the checker silently recomputes from the history."""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ..history import FAIL, INVOKE, OK, TYPE_CODES
from .linearizable import F_CAS, F_READ, F_WRITE

INF = float("inf")
_F01 = {"read": F_READ, "write": F_WRITE, "cas": F_CAS}


class _KeyPart:
    """One key's growing register partition + incremental screen state."""

    __slots__ = ("f", "value", "inv", "ret", "ok", "inv_row",
                 "clean", "cur", "last_inv", "last_ret")

    def __init__(self):
        self.f: list = []
        self.value: list = []
        self.inv: list = []
        self.ret: list = []
        self.ok: list = []
        self.inv_row: list = []
        # incremental screen: stays clean while every op is an ok
        # read/write arriving in invocation order with no overlap and a
        # successful running replay — then the partition is decidedly
        # linearizable with no further work at check time
        self.clean = True
        self.cur = None
        self.last_inv = -INF
        self.last_ret = -INF

    def add(self, f01, val, inv, ret, ok, inv_row):
        self.f.append(f01)
        self.value.append(val)
        self.inv.append(inv)
        self.ret.append(ret)
        self.ok.append(ok)
        self.inv_row.append(inv_row)
        if not self.clean:
            return
        if (not ok) or f01 == F_CAS or inv < self.last_inv \
                or inv < self.last_ret:
            self.clean = False
            return
        if f01 == F_WRITE:
            self.cur = val
        elif val != self.cur:
            self.clean = False
            return
        self.last_inv, self.last_ret = inv, ret

    def arrays(self):
        n = len(self.inv)
        value = np.empty(n, object)
        value[:] = self.value
        arrs = {"f": np.asarray(self.f, np.int8),
                "value": value,
                "inv": np.asarray(self.inv, np.int64),
                "ret": np.asarray(self.ret, np.float64),
                "ok": np.asarray(self.ok, bool)}
        order = np.argsort(np.asarray(self.inv_row, np.int64),
                           kind="stable")
        return {k: v[order] for k, v in arrs.items()}


_NONREG = object()          # open slot held by a non-register invoke


class AnalysisPool:
    """A shared grader pool: one fixed set of worker threads serving
    MANY AnalysisPipelines (the fleet posture — `--fleet 512` with one
    dedicated grader thread per cluster would dwarf the host, so
    shells multiplex over this pool instead, sized by
    `--check-workers`). Pipelines submit drain jobs; each pipeline
    drains its own task deque from at most one worker at a time, so
    per-pipeline segment ORDER is preserved and verdicts stay
    bit-identical to the dedicated-thread path (pinned by
    tests/test_ordering.py::test_pooled_pipeline_bit_equal)."""

    def __init__(self, workers: int = 1):
        self.workers = max(1, int(workers))
        self._q: "queue.Queue" = queue.Queue()
        self._threads = []
        for i in range(self.workers):
            t = threading.Thread(target=self._run,
                                 name=f"maelstrom-analysis-pool-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._closed = False

    def submit(self, fn):
        self._q.put(fn)

    def _run(self):
        while True:
            fn = self._q.get()
            if fn is None:
                self._q.put(None)       # wake the next worker too
                return
            try:
                fn()
            finally:
                self._q.task_done()

    def close(self):
        """Stops the workers after the queued jobs drain. Idempotent;
        pipelines must be finish()ed/close()d first."""
        if not self._closed:
            self._closed = True
            self._q.put(None)
            for t in self._threads:
                t.join(timeout=5)


class AnalysisPipeline:
    """Background, in-order history analysis. `feed(history, lo, hi)`
    enqueues a segment (cheap; called from the runner's dispatch loop);
    a single worker thread preserves segment order. `finish()` drains
    the queue; afterwards `register_partitions(n)` serves the columnar
    partitions to the checker and `report()` summarizes overlap."""

    def __init__(self, workers: int = 1, observers: dict | None = None,
                 ns_per_round: float | None = None, head_round=None,
                 label=None, tracer=None, pool: AnalysisPool | None = None):
        self.workers = max(1, int(workers))
        # flight recorder (doc/observability.md): an optional
        # TelemetrySession; each analyzed segment lands a
        # "pipeline-grade" span on the trace's analysis thread row.
        # Purely observational — failures in the tracer count as
        # pipeline errors like any other (the checker then recomputes).
        self._tracer = tracer
        # fleet attribution (doc/perf.md "vectorized host driver"): a
        # cluster index stamped on window records and the report, so a
        # fleet's per-cluster stream-grading blocks stay attributable
        # when logs/results are read side by side. None standalone.
        self.label = label
        self.busy_s = 0.0           # worker seconds (compute-overlapped)
        self.segments = 0
        self.rows = 0
        self.error: Optional[str] = None
        self._open: dict = {}       # process code -> invoke record
        self._parts: dict = {}      # key -> _KeyPart
        self._stats = {"ok": 0, "fail": 0, "info": 0}
        self.resumed_rows = 0       # rows seeded from a resume checkpoint
        # stream observers (doc/streams.md): {name: observer}; each fed
        # completed pairs in segment order, each segment graded as a
        # window at its close. head_round() reads the runner's live scan
        # head so window records carry the checker-lag metric.
        self._observers: dict = dict(observers or {})
        self._ns_per_round = ns_per_round
        self._head_round = head_round
        self.windows: list = []
        self._history = None        # the (single) history being fed
        self._finished = False
        # two execution modes: a dedicated worker thread (standalone
        # runs — today's behavior), or a SHARED AnalysisPool (fleet
        # shells): tasks queue locally and a drain job runs them in
        # order from whichever pool worker picks it up, never two at
        # once for the same pipeline
        self._pool = pool
        self._thread = None
        if pool is None:
            self._q: "queue.Queue" = queue.Queue()
            self._thread = threading.Thread(
                target=self._run, name="maelstrom-analysis", daemon=True)
            self._thread.start()
        else:
            self._tasks: deque = deque()
            self._tlock = threading.Lock()
            self._scheduled = False
            self._idle = threading.Event()
            self._idle.set()

    # --- main-thread API ---

    def feed(self, history, lo: int, hi: int):
        if hi <= lo or self._finished:
            return
        if self._pool is None:
            self._q.put((history, lo, hi))
            return
        with self._tlock:
            self._tasks.append((history, lo, hi))
            self._idle.clear()
            if not self._scheduled:
                self._scheduled = True
                self._pool.submit(self._drain)

    def _drain(self):
        """Pool-mode worker body: runs THIS pipeline's queued segments
        in order, then yields the pool worker back. The scheduled flag
        guarantees at most one drain job per pipeline is ever live."""
        while True:
            with self._tlock:
                if not self._tasks:
                    self._scheduled = False
                    self._idle.set()
                    return
                item = self._tasks.popleft()
            self._process(item)

    def seed_resumed(self, history, n: int):
        """Feeds a resumed run's pre-existing rows [0, n) as segment 0,
        so the pipeline's pairing/partition state covers the whole
        stitched history. Without this a resumed run fails the
        check-time row-count match (`register_partitions`) and silently
        loses the overlap fast path; with it, resumed verdicts stay
        bit-identical AND fast (pinned by
        tests/test_checkpoint_resilience.py::
        test_resume_keeps_pipeline_overlap)."""
        self.resumed_rows = n
        self.feed(history, 0, n)

    def close(self):
        """Error-path shutdown: stops the worker without finalizing
        partitions (a closed pipeline declines service). Idempotent."""
        if not self._finished:
            self._finished = True
            self.error = self.error or "closed before finish"
            if self._pool is None:
                self._q.put(None)
                self._thread.join(timeout=5)
            else:
                with self._tlock:
                    self._tasks.clear()
                self._idle.wait(timeout=5)

    def finish(self):
        """Blocks until every fed segment is analyzed, then flushes
        still-open invokes as unpaired (completion None) ops — to the
        register partitions, and to any stream observer that opts in
        via `observe_open` (indeterminate ops matter to e.g. the elle
        checker: an open txn's appends still enter the version
        tables)."""
        if self._finished:
            return self
        if self._pool is None:
            self._q.put(None)
            self._thread.join()
        else:
            # every fed segment either ran already or sits in _tasks
            # with a drain job scheduled; idle fires when both empty
            self._idle.wait()
        self._finished = True
        try:
            open_rows = sorted(self._open.values(),
                               key=lambda rr: rr[0])
            for row, reg in open_rows:
                if reg is not _NONREG:
                    self._add_pair(reg, None, None, None)
            if self._observers and self._history is not None:
                flushers = [ob for ob in self._observers.values()
                            if hasattr(ob, "observe_open")]
                for row, _reg in open_rows:
                    inv = None
                    for ob in flushers:
                        if inv is None:
                            inv = self._history[row]
                        ob.observe_open(row, inv)
        except Exception as e:          # pragma: no cover - defensive
            self.error = repr(e)
        return self

    def register_partitions(self, n_rows: int):
        """[(key, arrays, screened)] sorted by repr(key), or None when
        this pipeline cannot vouch for the given history (analysis
        error, not finished, or a row-count mismatch — e.g. a history
        the pipeline never saw)."""
        if self.error or not self._finished or self.rows != n_rows:
            return None
        parts = [(k, p.arrays(), True if p.clean else None)
                 for k, p in self._parts.items()]
        parts.sort(key=lambda kv: repr(kv[0]))
        undecided = [i for i, (_k, _a, s) in enumerate(parts)
                     if s is None]
        if undecided and self.workers > 1:
            # fan the per-key vectorized screens over the worker pool
            # (numpy releases the GIL in the hot kernels); keys the
            # screen can't decide stay None and fall to WGL in the
            # checker
            from concurrent.futures import ThreadPoolExecutor
            from .linearizable import screen_register_arrays

            def screen(i):
                a = parts[i][1]
                return i, screen_register_arrays(
                    a["f"], a["value"], a["inv"], a["ret"], a["ok"])
            with ThreadPoolExecutor(self.workers) as pool:
                for i, verdict in pool.map(screen, undecided):
                    parts[i] = (parts[i][0], parts[i][1], verdict)
        return parts

    def stream_results(self, name: str, n_rows: int):
        """(observer, windows) for the named stream observer, or None
        when this pipeline cannot vouch for the given history (analysis
        error, not finished, a row-count mismatch — e.g. a history it
        never saw — or no such observer). `windows` carries the named
        observer's per-window verdict next to each window's row range,
        end round, and checker-lag."""
        if self.error or not self._finished or self.rows != n_rows:
            return None
        ob = self._observers.get(name)
        if ob is None:
            return None
        windows = []
        for w in self.windows:
            rec = {k: v for k, v in w.items() if k != "verdicts"}
            v = (w.get("verdicts") or {}).get(name)
            if v is not None:
                rec["verdict"] = v
            windows.append(rec)
        return ob, windows

    def report(self) -> dict:
        screened = sum(1 for p in self._parts.values() if p.clean)
        out = {"workers": self.workers,
               "segments": self.segments,
               "rows": self.rows,
               "busy-s": round(self.busy_s, 6),
               "register-keys": len(self._parts),
               "screened-clean-keys": screened,
               "completions": dict(self._stats)}
        if self.windows:
            out["windows"] = len(self.windows)
            lags = [w.get("lag-rounds") for w in self.windows
                    if w.get("lag-rounds") is not None]
            if lags:
                out["max-lag-rounds"] = max(lags)
        if self.resumed_rows:
            out["resumed-rows"] = self.resumed_rows
        if self.label is not None:
            out["cluster"] = self.label
        if self.error:
            out["error"] = self.error
        return out

    # --- worker ---

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._process(item)
            finally:
                self._q.task_done()

    def _process(self, item):
        """One segment's analysis + accounting — shared by the
        dedicated-thread and pooled modes."""
        t0 = time.perf_counter()
        try:
            if self.error is None:
                self._analyze(*item)
        except Exception as e:
            self.error = repr(e)
        finally:
            t1 = time.perf_counter()
            self.busy_s += t1 - t0
            if self._tracer is not None:
                try:
                    self._tracer.span(
                        "pipeline-grade", t0, t1, tid="analysis",
                        args={"rows": self.rows,
                              "segments": self.segments})
                except Exception:   # pragma: no cover - defensive
                    pass

    def _analyze(self, history, lo: int, hi: int):
        """One segment: the open-slot pairing scan over rows [lo, hi).
        History rows below `hi` are immutable once fed (append-only
        columns), so reading them off-thread is safe."""
        soa = history.soa()
        self._history = history
        inv_code = TYPE_CODES[INVOKE]
        ok_code, fail_code = TYPE_CODES[OK], TYPE_CODES[FAIL]
        # per-f-code register classification for this history's interner
        freg = [_F01.get(name) for name in soa.f_table]
        types, fs, procs = soa.type, soa.f, soa.process
        times, values = soa.time, soa.value
        opens = self._open
        observers = self._observers
        for i in range(lo, hi):
            p = procs[i]
            t = types[i]
            if t == inv_code:
                old = opens.pop(p, None)
                if old is not None:
                    row0, reg = old
                    if reg is not _NONREG:
                        self._add_pair(reg, None, None, None)
                    if observers:
                        inv = history[row0]
                        for ob in observers.values():
                            ob.observe(row0, inv, None)
                f01 = freg[fs[i]] if fs[i] < len(freg) else None
                v = values[i]
                if f01 is not None and isinstance(v, (list, tuple)) \
                        and len(v) == 2:
                    opens[p] = (i, (i, f01, v[0], v[1], int(times[i])))
                else:
                    opens[p] = (i, _NONREG)
            else:
                if t == ok_code:
                    self._stats["ok"] += 1
                elif t == fail_code:
                    self._stats["fail"] += 1
                else:
                    self._stats["info"] += 1
                rec = opens.pop(p, None)
                if rec is None:
                    continue
                row0, reg = rec
                if observers:
                    inv, comp = history[row0], history[i]
                    for ob in observers.values():
                        ob.observe(row0, inv, comp)
                if reg is _NONREG:
                    continue
                if t == fail_code:
                    # definitely didn't happen — excluded from the
                    # partition, but the KEY still counts (the
                    # sequential path's by_key holds it with zero ops)
                    if reg[2] not in self._parts:
                        self._parts[reg[2]] = _KeyPart()
                    continue
                self._add_pair(reg, t == ok_code, values[i],
                               int(times[i]))
        self.segments += 1
        self.rows = hi
        if observers:
            self._close_window(lo, hi, times)

    def _close_window(self, lo: int, hi: int, times):
        """Grades the just-analyzed segment as one WINDOW: each stream
        observer reports what the segment newly exposed, and the record
        carries the checker-lag metric — how many rounds the scan head
        had advanced past this window's last event by the time its
        analysis closed (bounded lag = the grader keeps up)."""
        head = None
        if self._head_round is not None:
            try:
                head = int(self._head_round())
            except Exception:       # pragma: no cover - defensive
                head = None
        end_round = None
        lag = None
        if self._ns_per_round and hi > lo:
            end_round = int(round(float(times[hi - 1])
                                  / self._ns_per_round))
            if head is not None:
                lag = max(head - end_round, 0)
        rec = {"window": len(self.windows), "rows": [lo, hi],
               "end-round": end_round, "lag-rounds": lag}
        if self.label is not None:
            rec["cluster"] = self.label
        for name, ob in self._observers.items():
            close = getattr(ob, "window_close", None)
            if close is not None:
                rec.setdefault("verdicts", {})[name] = close()
        self.windows.append(rec)

    def _add_pair(self, rec, ok, cval, ctime):
        """Appends one (invoke, completion-or-None) register pair to its
        key partition, with the sequential path's value/ret rules."""
        inv_row, f01, key, iv, itime = rec
        ok = bool(ok)
        val = cval[1] if ok and cval is not None else iv
        part = self._parts.get(key)
        if part is None:
            part = self._parts[key] = _KeyPart()
        part.add(f01, val, itime, float(ctime) if ok else INF, ok,
                 inv_row)
