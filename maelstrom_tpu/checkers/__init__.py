"""Checkers: pure functions of histories.

A checker takes (test, history, opts) and returns a map with at least
`{"valid": True | False | "unknown"}`. Mirrors jepsen.checker/Checker as used
by the reference (`core.clj:82-89`). Checkers must stay pure over plain
history data so they can be unit-tested with literal fixtures (reference
`test/maelstrom/workload/pn_counter_test.clj`).
"""

from __future__ import annotations

import traceback

from ..history import coerce_history


class Checker:
    name = "checker"

    def check(self, test: dict, history, opts: dict | None = None) -> dict:
        raise NotImplementedError

    def make_stream_observer(self, test: dict):
        """An incremental observer for the overlapped analysis pipeline
        (doc/streams.md), or None. An observer is fed each completed
        (invoke, completion) pair as drained segments are analyzed —
        ``observe(invoke_row, invoke, complete)`` — and asked for a
        per-window early-warning verdict at each segment boundary
        (``window_close() -> dict``). Check time then consumes its
        carried state instead of re-scanning the history; verdicts must
        stay bit-identical to the history-only path."""
        return None

    def convictions(self, test: dict, history, opts: dict | None = None):
        """Byzantine conviction hook (doc/faults.md): a list of
        ``{"rule", "culprit", "evidence", ...}`` dicts, one per lying
        node this checker can PROVE misbehaved (byzantine.conviction()
        builds them). Compose gathers these from every checker into the
        ``byzantine`` results block; a run under ``--nemesis byzantine``
        is valid only if every injected corruption is convicted, and a
        benign run must stay conviction-free. Default: nothing to say."""
        return []


def merge_valid(vs) -> bool | str:
    """Jepsen semantics for composing validity: false dominates, then
    unknown, then true."""
    vs = list(vs)
    if any(v is False for v in vs):
        return False
    if any(v == "unknown" for v in vs):
        return "unknown"
    return True


class Compose(Checker):
    """Runs a map of named checkers over the same history and merges their
    validity (reference `core.clj:82-89` / jepsen checker/compose)."""

    name = "compose"

    def __init__(self, checkers: dict[str, Checker]):
        self.checkers = checkers

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        results = {}
        for name, c in self.checkers.items():
            try:
                results[name] = c.check(test, history, opts or {})
            except Exception as e:     # a crashed checker is an invalid test
                results[name] = {"valid": "unknown",
                                 "error": repr(e),
                                 "traceback": traceback.format_exc()}
        self._check_convictions(test, history, opts, results)
        results["valid"] = merge_valid(
            r.get("valid", "unknown") for r in results.values())
        return results

    def _check_convictions(self, test, history, opts, results):
        """Gather Byzantine convictions from every checker and grade
        them against the injection ledger (test["byz_injected"], set by
        the runner). The block only appears when a byzantine nemesis ran
        or a checker actually convicted someone — benign runs that stay
        conviction-free produce no block at all."""
        convictions, cerrs = [], []
        for c in self.checkers.values():
            try:
                convictions.extend(c.convictions(test, history, opts or {}))
            except Exception as e:  # a crashed auditor can't prove innocence
                cerrs.append({"checker": c.name, "error": repr(e),
                              "traceback": traceback.format_exc()})
        injected = test.get("byz_injected")
        if injected is None and not convictions and not cerrs:
            return
        from ..byzantine import assemble_block
        block = assemble_block(convictions, injected or {})
        if cerrs:
            block["errors"] = cerrs
            block["valid"] = False
        results["byzantine"] = block


class UnhandledExceptions(Checker):
    """Surfaces ops that failed with unexpected exceptions, like
    jepsen.checker/unhandled-exceptions (reference `core.clj:86`)."""

    name = "exceptions"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        exceptions = [o.to_dict() for o in history
                      if o.error is not None
                      and isinstance(o.error, (list, tuple))
                      and len(o.error) > 0
                      and o.error[0] == "exception"]
        return {"valid": True, "exceptions": exceptions}


class Stats(Checker):
    """Op counts overall and by :f, like jepsen.checker/stats
    (reference `core.clj:87`). Valid iff every :f had at least one ok op
    (jepsen's rule), unknown when there were no completions at all."""

    name = "stats"

    def check(self, test, history, opts=None):
        history = coerce_history(history)

        def count_group(ops):
            counts = {"count": 0, "ok-count": 0, "fail-count": 0,
                      "info-count": 0}
            for o in ops:
                if o.type in ("ok", "fail", "info"):
                    counts["count"] += 1
                    counts[f"{o.type}-count"] += 1
            counts["valid"] = ("unknown" if counts["count"] == 0
                               else counts["ok-count"] > 0)
            return counts

        completions = [o for o in history.client_ops()
                       if o.type in ("ok", "fail", "info")]
        by_f: dict[str, list] = {}
        for o in completions:
            by_f.setdefault(o.f, []).append(o)
        result = count_group(completions)
        result["by-f"] = {f: count_group(ops) for f, ops in by_f.items()}
        result["valid"] = merge_valid(
            [result["valid"]] +
            [r["valid"] for r in result["by-f"].values()])
        return result
