"""Transactional read/write-register checker — the *observable subset*
of Elle's rw-register analysis, honestly scoped.

List-append reveals the full version order of every key (an observed
list names all its predecessors), which is why `checkers/elle.py` can
build ww/wr/rw edges and classify the whole anomaly zoo. A bare
register read reveals only WHICH write it observed — not where that
write sits among the others — so this checker proves exactly what the
observations support and documents what they cannot:

Detected (each with a witness):
  - **internal**: within one transaction, a read of k after the
    transaction's own write of k must observe its latest own write;
  - **G1a** (aborted read): a read observing a value whose writing
    transaction definitely failed;
  - **G1b** (intermediate read): a read observing a value the writer
    overwrote within its own transaction (visible because the workload
    generator never reuses a (key, value) pair);
  - **cyclic information flow**: cycles in wr ∪ realtime edges — a
    transaction chain where each link either read the previous link's
    write or started after it completed, closing on itself. This is
    the G1c-with-realtime family restated over observable edges.

NOT detected (requires version-order inference a register read cannot
provide): pure write-write cycles (G0) and anti-dependency cycles
(G-single/G2, e.g. write skew). Runs needing those guarantees should
use the list-append workload, whose checker sees them.

Assumes the workload's generator contract: every ok/indeterminate
write of a key carries a value never written to that key by any other
transaction (`workloads/txn_rw_register.py` uses per-key counters). A
violation of the contract itself is reported as `duplicate-writes`.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


class RWRegisterChecker(Checker):
    name = "txn-rw-register"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        txns = []      # (idx, invoke_time, complete_time, micro_ops, ok)
        failed_writes = {}      # (k, v) -> txn index (definite fails)
        writer_of = {}          # (k, v) -> txn index (ok/info writers)
        duplicate_writes = []
        internal = []
        g1a = []
        g1b = []

        for invoke, complete in history.pairs():
            if invoke.f != "txn":
                continue
            if complete is not None and complete.is_fail():
                for f, k, v in invoke.value or ():
                    if f == "w":
                        failed_writes[(str(k), repr(v))] = (k, v)
                continue
            ok = complete is not None and complete.is_ok()
            value = complete.value if ok else invoke.value
            idx = len(txns)
            txns.append((idx, invoke.time,
                         complete.time if ok else None,
                         [list(m) for m in (value or ())], ok))
            for f, k, v in value or ():
                if f == "w":
                    key = (str(k), repr(v))
                    if key in writer_of:
                        duplicate_writes.append(
                            {"key": k, "value": v,
                             "txns": [writer_of[key], idx]})
                    writer_of[key] = idx

        # a (key, value) written by both a definitely-failed txn and an
        # ok/info txn is the generator contract broken, not an aborted
        # read — report it as duplicate-writes so a read of that value
        # isn't mislabeled G1a
        for key in sorted(set(failed_writes) & set(writer_of)):
            k, v = failed_writes[key]
            duplicate_writes.append(
                {"key": k, "value": v,
                 "txns": [writer_of[key]],
                 "also-failed-writer": True})
            del failed_writes[key]

        # last own write per key per txn (for internal + G1b)
        final_write = {}        # txn idx -> {k: v}
        for idx, _i, _c, mops, _ok in txns:
            own: dict = {}
            for f, k, v in mops:
                if f == "w":
                    own[str(k)] = v
            final_write[idx] = own

        wr_edges = set()
        for idx, _i, _c, mops, ok in txns:
            if not ok:
                continue
            own_so_far: dict = {}
            for f, k, v in mops:
                k = str(k)
                if f == "w":
                    own_so_far[k] = v
                    continue
                if k in own_so_far:
                    if repr(v) != repr(own_so_far[k]):
                        internal.append({"txn": idx, "key": k,
                                         "expected": own_so_far[k],
                                         "observed": v})
                    continue
                if v is None:
                    continue                     # initial state
                key = (k, repr(v))
                if key in failed_writes:
                    g1a.append({"txn": idx, "key": k, "value": v})
                    continue
                w = writer_of.get(key)
                if w is None:
                    continue   # written by an unobserved (pending) txn
                if repr(final_write[w].get(k)) != repr(v):
                    g1b.append({"txn": idx, "key": k, "value": v,
                                "writer": w})
                if w != idx:
                    wr_edges.add((w, idx))

        # realtime edges via barrier chaining (the same closure-
        # preserving compression elle.py uses): each ok txn points at
        # the barrier for its completion time, barriers chain forward,
        # and the latest barrier before a txn's invocation points at
        # it — t1 reaches t2 through barriers iff ret(t1) < inv(t2)
        import bisect
        ok_txns = sorted((t for t in txns if t[4] and t[2] is not None),
                         key=lambda t: t[2])
        barrier_times = [t[2] for t in ok_txns]
        rt_edges = set()
        for i in range(len(ok_txns) - 1):
            rt_edges.add((("b", i), ("b", i + 1)))
        for i, t in enumerate(ok_txns):
            rt_edges.add((t[0], ("b", i)))
        for t in ok_txns:
            j = bisect.bisect_left(barrier_times, t[1]) - 1
            if j >= 0:
                rt_edges.add((("b", j), t[0]))

        # Tarjan over wr + realtime
        edges = wr_edges | rt_edges
        adj: dict = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        index = {}
        low = {}
        stack = []
        on_stack = set()
        sccs = []
        counter = [0]

        def strongconnect(v):
            work = [(v, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                for j in range(pi, len(adj.get(node, []))):
                    w = adj[node][j]
                    if w not in index:
                        work.append((node, j + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)     # mixed txn/barrier nodes
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for node in list(adj):
            if node not in index:
                strongconnect(node)

        # report only the transaction members of each cycle (barrier
        # nodes are plumbing); an SCC of barriers alone is impossible
        # (the barrier chain is acyclic)
        cycles = []
        for scc in sccs:
            members = [x for x in scc if not isinstance(x, tuple)]
            if len(members) < 2:
                continue      # a lone txn cycling through barriers
                #               would mean ret(t) < inv(t): impossible
            sset = set(scc)
            cycles.append({
                "txns": sorted(members),
                "wr-edges": sorted((a, b) for a, b in wr_edges
                                   if a in sset and b in sset),
                "via-realtime": any(isinstance(x, tuple) for x in scc)})

        problems = {}
        if internal:
            problems["internal"] = internal[:16]
        if g1a:
            problems["G1a"] = g1a[:16]
        if g1b:
            problems["G1b"] = g1b[:16]
        if cycles:
            problems["cycles"] = cycles[:8]
        if duplicate_writes:
            problems["duplicate-writes"] = duplicate_writes[:16]
        out = {
            "valid": not problems,
            "txn-count": len(txns),
            "ok-count": sum(1 for t in txns if t[4]),
            "wr-edge-count": len(wr_edges),
            "not-checked": ["G0", "G-single", "G2 (write skew)"],
        }
        out.update(problems)
        if not any(t[4] for t in txns):
            if problems:
                pass                      # anomalies dominate
            else:
                out["valid"] = "unknown"
                out["error"] = "no transaction ever completed ok"
        return out
