"""Linearizable timestamp-oracle checker (the `lin-tso` workload).

A TSO is linearizable iff the timestamps it hands out form a
linearization witness: all granted timestamps are unique, and whenever
op A completes before op B is invoked (real-time order), A's timestamp
is smaller. Verified in O(n log n): sort granted ops by timestamp and
compare each op's invoke time against the suffix-minimum of completion
times — a later-timestamped op that completed before an
earlier-timestamped op invoked is a witness violation."""

from __future__ import annotations

from . import Checker, coerce_history


class TSOChecker(Checker):
    name = "workload"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        pairs = history.pairs()      # invoke -> completion
        granted = []
        for inv, comp in pairs:
            if comp is None or comp.type != "ok":
                continue
            granted.append((int(comp.value), inv.time, comp.time))
        out = {"granted-count": len(granted)}
        if not granted:
            out["valid"] = "unknown" if len(history) else True
            return out
        by_ts = sorted(granted)
        dup = [a[0] for a, b in zip(by_ts, by_ts[1:]) if a[0] == b[0]]
        if dup:
            out["valid"] = False
            out["duplicate-ts"] = dup[:8]
            return out
        # suffix-min of completion times over the ts-sorted ops: if any
        # later-ts op completed before this op invoked, ts order
        # contradicts real-time order
        violations = []
        suffix_min = [None] * len(by_ts)
        m = None
        for i in range(len(by_ts) - 1, -1, -1):
            _ts, _inv, comp = by_ts[i]
            suffix_min[i] = m if (m is not None and m < comp) else comp
            m = suffix_min[i]
        for i, (ts, inv, _comp) in enumerate(by_ts[:-1]):
            if suffix_min[i + 1] < inv:
                violations.append({"ts": ts, "invoked-ns": inv,
                                   "later-ts-completed-ns":
                                       suffix_min[i + 1]})
        out["monotonic"] = not violations
        if violations:
            out["violations"] = violations[:8]
        out["valid"] = not violations
        return out
