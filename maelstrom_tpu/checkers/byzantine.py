"""Byzantine conviction checkers (doc/faults.md "byzantine is a
conviction driver").

Two auditors, one per execution path, both surfacing
``(rule, culprit, evidence)`` triples through the `Checker.convictions`
hook that `Compose` folds into the ``byzantine`` results block
(`byzantine.assemble_block`):

  - ``ByzantineChecker`` (host path) audits the network journal: the
    send event books the HONEST body before `HostNet._corrupt` rewrites
    the delivered copy, and the recv event books what actually arrived
    under the same message id — so every wire lie is provable from the
    record, and the diff's shape classifies the attack kind.
  - ``TpuByzantine`` (TPU path) reads the device-side evidence counters
    the node program accumulated inside the compiled round
    (`NodeProgram.byz_evidence`, e.g. the compartment proxies'
    equivocation/stale-ballot ledgers): the TPU journal keeps no bodies,
    so conviction evidence must ride the state tree.

Workload checkers may convict too (`BatchedBroadcastChecker` maps its
expansion-proof audit errors to forged-proof convictions) — Compose
gathers from EVERY checker, so whichever audit surface the corruption
hit does the convicting.
"""

from __future__ import annotations

from . import Checker
from ..byzantine import PROOF_FIELDS, conviction
from ..net.journal import RECV, SEND
from ..util import is_client


def classify_wire_diff(sent: dict, received: dict, prior: list) -> str:
    """Names the rule a corrupted delivery violates, from the shape of
    the send/recv body diff:

      - the delivered body is byte-equal to an EARLIER send from the
        same culprit -> ``stale-ballot`` (old traffic replayed over new)
      - the diff is confined to the proof vocabulary (`PROOF_FIELDS`)
        -> ``forged-proof``
      - anything else -> ``equivocation`` (same send, different story)
    """
    if any(p == received for p in prior):
        return "stale-ballot"
    keys = {k for k in set(sent) | set(received)
            if sent.get(k) != received.get(k)}
    if keys and keys <= set(PROOF_FIELDS):
        return "forged-proof"
    return "equivocation"


class ByzantineChecker(Checker):
    """Host-path wire auditor: convicts from the net journal's
    send-vs-recv body record. Its own `check` block is trivially valid —
    the verdict that matters is the Compose-assembled ``byzantine``
    block, graded against the injection ledger."""

    name = "byzantine"

    def __init__(self, net):
        self.net = net

    def check(self, test, history, opts=None):
        journal = getattr(self.net, "journal", None)
        return {"valid": True,
                "audited-events": len(journal.events)
                if journal is not None else 0}

    def convictions(self, test, history, opts=None):
        journal = getattr(self.net, "journal", None)
        if journal is None:
            return []
        with journal.lock:
            events = list(journal.events)
        # first pass: per-id honest send body + each sender's prior-send
        # prefix (the replay evidence pool), inter-server traffic only
        sends: dict = {}            # id -> (body, prefix_len)
        prior: dict = {}            # src -> [bodies in send order]
        for e in events:
            if e.type != SEND or e.body is None \
                    or is_client(e.src) or is_client(e.dest):
                continue
            log = prior.setdefault(e.src, [])
            sends[e.id] = (e.body, len(log))
            log.append(e.body)
        # second pass: any delivery whose body disagrees with its own
        # send record is a wire lie by the sender; aggregate per
        # (rule, culprit) so rate-1.0 windows stay readable
        agg: dict = {}
        for e in events:
            if e.type != RECV or e.body is None or e.id not in sends:
                continue
            sent, upto = sends[e.id]
            if e.body == sent:
                continue
            rule = classify_wire_diff(sent, e.body,
                                      prior.get(e.src, [])[:upto])
            key = (rule, e.src)
            if key in agg:
                agg[key]["evidence"]["count"] += 1
            else:
                agg[key] = conviction(rule, e.src, {
                    "count": 1, "msg_id": e.id,
                    "sent": dict(sent), "received": dict(e.body)},
                    witness=e.dest)
        return list(agg.values())


class TpuByzantine(Checker):
    """TPU-path conviction source: surfaces the device-resident evidence
    ledgers the node program accumulated in its compiled round
    (`NodeProgram.byz_evidence(nodes_host) -> [conviction...]`). The
    run-level injection ledger (`SimState.byz["injected"]`) lands in
    `test["byz_injected"]` via `run_tpu_test`, so Compose grades these
    convictions against exactly what the compiled masks rewrote."""

    name = "byzantine"

    def __init__(self, runner):
        self.runner = runner

    def check(self, test, history, opts=None):
        return {"valid": True,
                "injected": dict(test.get("byz_injected") or {})}

    def convictions(self, test, history, opts=None):
        fn = getattr(self.runner.program, "byz_evidence", None)
        if fn is None:
            return []
        return list(fn(self.runner._nodes_host()))
