"""Full set checker: the grading oracle for broadcast / g-set.

A reimplementation of jepsen.checker/set-full semantics, which the reference
uses for g-set (`workload/g_set.clj:62`) and (with :broadcast remapped to
:add) for broadcast (`workload/broadcast.clj:215-227`). For every element
attempted, classifies it as:

  - stable:      eventually present in every read that begins afterwards
  - lost:        known (acknowledged or observed), but a read that began
                 after it was known returned without it, and it never came
                 back — data loss, the test fails
  - never-read:  no read began after the element was known, so we can't say
  - stale:       eventually stable, but some read that began after the
                 element was known missed it (visibility lag)

Also reports stable-latencies at quantiles {0, 0.5, 0.95, 0.99, 1}:
the ms from an element becoming *known* (acknowledged or first
observed) to the last moment any read observed it missing — pure
propagation-visibility lag, 0 when no read ever missed it. This matches
the reference's tables (`doc/03-broadcast/02-performance.md:139-272`),
whose quantile 0 is always exactly 0 and whose maxima track propagation
time rather than the idle gap before final reads.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


def quantiles(sorted_xs: list, qs=(0, 0.5, 0.95, 0.99, 1)) -> dict:
    if not sorted_xs:
        return {q: None for q in qs}
    n = len(sorted_xs)
    out = {}
    for q in qs:
        i = min(n - 1, int(q * n))
        out[q] = sorted_xs[i]
    return out


class SetFullChecker(Checker):
    name = "set-full"

    def __init__(self, add_f: str = "add"):
        # Broadcast remaps :broadcast -> :add (`broadcast.clj:215-227`);
        # rather than rewriting history we accept the add f directly.
        self.add_f = add_f

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        pairs = history.pairs()

        # Element -> add info
        attempts = {}          # element -> invoke time
        acked = {}             # element -> ack (completion) time
        for invoke, complete in pairs:
            if invoke.f != self.add_f:
                continue
            attempts[invoke.value] = invoke.time
            if complete is not None and complete.is_ok():
                acked[invoke.value] = complete.time

        # Reads: (invoke_time, complete_time, frozenset elements, dup counts)
        reads = []
        duplicated = {}
        for invoke, complete in pairs:
            if invoke.f != "read" or complete is None or not complete.is_ok():
                continue
            value = complete.value if complete.value is not None else []
            els = frozenset(value)
            if len(els) < len(value):
                counts = {}
                for e in value:
                    counts[e] = counts.get(e, 0) + 1
                for e, c in counts.items():
                    if c > 1:
                        duplicated[e] = max(duplicated.get(e, 0), c)
            reads.append((invoke.time, complete.time, els))
        reads.sort()

        lost, stable, never_read, stale = [], [], [], []
        stale_durations = {}
        stable_latencies = []

        for e, invoke_time in attempts.items():
            present = [(ti, tc) for (ti, tc, els) in reads if e in els]
            # known: acknowledged, or observed by any read
            if e in acked:
                known_time = acked[e]
            elif present:
                known_time = min(tc for ti, tc in present)
            else:
                continue   # unacknowledged and never seen: no claim on it

            counting_absent = [ti for (ti, tc, els) in reads
                               if ti > known_time and e not in els]
            last_absent = max(counting_absent, default=None)

            if last_absent is not None and not any(
                    ti > last_absent for ti, tc in present):
                lost.append(e)
                continue
            if not present and not counting_absent:
                never_read.append(e)
                continue

            stable.append(e)
            # Stability latency, jepsen set-full style: the time from the
            # element becoming known to the LAST moment any read observed
            # it missing — 0 when no read ever missed it. (A value only
            # re-confirmed by the final reads still gets its true
            # propagation latency, not the idle gap before the finals;
            # this is what makes the reference's quantile-0 exactly 0 and
            # its grid@100ms max ~791 ms ≈ full propagation,
            # `doc/03-broadcast/02-performance.md:187-191`.)
            if last_absent is not None:
                stale.append(e)
                stale_durations[e] = last_absent - known_time
            stable_latencies.append(
                max(0, ((last_absent or known_time) - known_time)) / 1e6)

        worst_stale = sorted(stale_durations,
                             key=lambda e: -stale_durations[e])[:8]
        stable_latencies.sort()

        any_reads = bool(reads)
        valid = (False if lost
                 else ("unknown" if not any_reads else True))
        return {
            "valid": valid,
            "attempt-count": len(attempts),
            "acknowledged-count": len(acked),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted(lost, key=repr),
            "never-read-count": len(never_read),
            "never-read": sorted(never_read, key=repr),
            "stale-count": len(stale),
            "stale": sorted(stale, key=repr),
            "worst-stale": worst_stale,
            "duplicated-count": len(duplicated),
            "duplicated": duplicated,
            "stable-latencies": {
                str(q): (round(v, 3) if v is not None else None)
                for q, v in quantiles(stable_latencies).items()},
        }


class BroadcastChecker(SetFullChecker):
    """set-full with :broadcast as the add op
    (reference `workload/broadcast.clj:215-227`)."""

    name = "broadcast"

    def __init__(self):
        super().__init__(add_f="broadcast")


# --- batched atomic broadcast (nodes/broadcast_batched.py) ---

BATCH_F = "broadcast-batch"
# The ONE definition of the expansion-proof checksum: this module is
# the auditor, so it owns the spec; the node program
# (nodes/broadcast_batched.py) imports both names and implements the
# device half against them.
PROOF_MOD = 0x7FFFFFFF          # checksums stay positive int32


def range_checksum(lo: int, n: int) -> int:
    """sum(lo..lo+n-1) mod PROOF_MOD: the arithmetic-series identity
    all three parties compute — the client at distillation time, the
    server from its own expansion mask, and this checker from the
    acked (lo, n) record."""
    return (n * lo + (n * (n - 1)) // 2) % PROOF_MOD


def verify_batch_proofs(history) -> tuple[list, dict]:
    """Audits every `broadcast-batch` op's server-side expansion proof
    against its claim. Returns (errors, stats). Each error is a definite
    fail: a server that mis-expands a batch (or a batcher that ships a
    malformed record) degrades results exactly like silent message loss.

      - duplicate-in-batch: the distilled claim itself holds one value
        twice — distillation failed to dedup.
      - forged-count: the acked count disagrees with the claimed batch
        size (or with the server's own expanded id list).
      - truncated-batch: the server expanded different values than the
        batch claimed (fewer, extra, or reordered).
      - forged-proof: the acked checksum is not the arithmetic-series
        sum of the acked id range — count and range were tampered
        inconsistently.
      - replayed-batch: two acknowledged batches claim the same id
        range. Ranges are disjoint by construction (fresh sequential
        interns), so a second ack of one range is a replay — the
        at-least-once hazard the `duplicate` nemesis models.
    """
    history = coerce_history(history)
    errors: list = []
    acked_lo: dict = {}
    batches = acked = ops_claimed = 0
    for invoke, complete in history.pairs():
        if invoke.f != BATCH_F:
            continue
        batches += 1
        claimed = list(invoke.value or ())
        ops_claimed += len(claimed)
        keys = [repr(v) for v in claimed]
        if len(set(keys)) != len(keys):
            errors.append({"index": invoke.index,
                           "process": invoke.process,
                           "error": "duplicate-in-batch"})
        if complete is None or not complete.is_ok():
            continue
        acked += 1
        rec = complete.value
        if not (isinstance(rec, dict)
                and {"lo", "n", "proof", "expanded"} <= set(rec)):
            errors.append({"index": invoke.index,
                           "process": invoke.process,
                           "error": "malformed-ack", "value": rec})
            continue
        lo, n = int(rec["lo"]), int(rec["n"])
        expanded = list(rec["expanded"])
        if n != len(claimed) or n != len(expanded):
            errors.append({"index": invoke.index,
                           "process": invoke.process, "error": "forged-count",
                           "claimed": len(claimed), "acked": n,
                           "expanded": len(expanded)})
        if expanded != claimed:
            errors.append({"index": invoke.index,
                           "process": invoke.process,
                           "error": "truncated-batch",
                           "claimed": claimed, "expanded": expanded})
        if int(rec["proof"]) != range_checksum(lo, n):
            errors.append({"index": invoke.index,
                           "process": invoke.process, "error": "forged-proof",
                           "proof": int(rec["proof"]),
                           "expected": range_checksum(lo, n)})
        if lo in acked_lo:
            errors.append({"index": invoke.index,
                           "process": invoke.process,
                           "error": "replayed-batch", "lo": lo,
                           "first": acked_lo[lo]})
        else:
            acked_lo[lo] = invoke.index
    return errors, {"batch-count": batches, "acked-batch-count": acked,
                    "batched-op-count": ops_claimed}


def expand_batched_history(history):
    """The equivalent unbatched history: every `broadcast-batch` op is
    expanded into one `broadcast` op per claimed value (invoke/complete
    times preserved; each expanded op gets a synthetic sub-process so
    invoke/completion pairing stays adjacent per process), reads pass
    through unchanged. `BatchedBroadcastChecker` grades THIS history
    with the stock set-full fold — which is what makes its verdict
    bit-equal to the unbatched broadcast checker on the same op stream
    by construction (pinned in tests/test_broadcast_batched.py)."""
    from ..history import History
    history = coerce_history(history)
    out = History()
    for invoke, complete in history.pairs():
        if invoke.f != BATCH_F:
            out.append_row(invoke.type, invoke.f, invoke.value,
                           invoke.process, invoke.time,
                           final=invoke.final)
            if complete is not None:
                out.append_row(complete.type, complete.f, complete.value,
                               complete.process, complete.time,
                               complete.error, complete.final)
            continue
        for j, v in enumerate(invoke.value or ()):
            p = f"{invoke.process}#b{j}"
            out.append_row("invoke", "broadcast", v, p, invoke.time,
                           final=invoke.final)
            if complete is not None:
                out.append_row(complete.type, "broadcast", v, p,
                               complete.time, complete.error,
                               complete.final)
    return out


class BatchedBroadcastChecker(Checker):
    """Grades a batched-atomic-broadcast history: (1) every batch's
    server-side expansion proof is verified (`verify_batch_proofs` — any
    violation is a definite fail), (2) the expanded per-value stream is
    graded by the stock `BroadcastChecker` fold, so lost/stable/stale
    classification and stable-latency quantiles are bit-equal to the
    unbatched checker on the same op stream."""

    name = "broadcast-batched"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        errors, stats = verify_batch_proofs(history)
        sub = BroadcastChecker().check(
            test, expand_batched_history(history), opts)
        out = dict(sub)
        out.update(stats)
        out["proof-errors"] = errors
        if errors:
            out["valid"] = False
        return out

    def convictions(self, test, history, opts=None):
        """Byzantine conviction hook (doc/faults.md): every expansion-
        proof audit error doubles as a conviction of the node that
        served the batch — the proof vocabulary is exactly the surface
        the forged-proof attack corrupts, and the audit is a definite
        fail either way. Culprit: batch acks come from the client's
        home node (`process % N`, the runner's routing for non-leader
        programs on both paths)."""
        from ..byzantine import conviction
        errors, _stats = verify_batch_proofs(history)
        nodes = list(test.get("nodes") or ())
        agg: dict = {}
        for e in errors:
            p = e.get("process")
            culprit = (nodes[p % len(nodes)]
                       if nodes and isinstance(p, int) else "unknown")
            key = (e["error"], culprit)
            if key in agg:
                agg[key]["evidence"]["count"] += 1
            else:
                agg[key] = conviction(e["error"], culprit,
                                      {"count": 1, **e})
        return list(agg.values())
