"""Full set checker: the grading oracle for broadcast / g-set.

A reimplementation of jepsen.checker/set-full semantics, which the reference
uses for g-set (`workload/g_set.clj:62`) and (with :broadcast remapped to
:add) for broadcast (`workload/broadcast.clj:215-227`). For every element
attempted, classifies it as:

  - stable:      eventually present in every read that begins afterwards
  - lost:        known (acknowledged or observed), but a read that began
                 after it was known returned without it, and it never came
                 back — data loss, the test fails
  - never-read:  no read began after the element was known, so we can't say
  - stale:       eventually stable, but some read that began after the
                 element was known missed it (visibility lag)

Also reports stable-latencies at quantiles {0, 0.5, 0.95, 0.99, 1}:
the ms from an element becoming *known* (acknowledged or first
observed) to the last moment any read observed it missing — pure
propagation-visibility lag, 0 when no read ever missed it. This matches
the reference's tables (`doc/03-broadcast/02-performance.md:139-272`),
whose quantile 0 is always exactly 0 and whose maxima track propagation
time rather than the idle gap before final reads.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


def quantiles(sorted_xs: list, qs=(0, 0.5, 0.95, 0.99, 1)) -> dict:
    if not sorted_xs:
        return {q: None for q in qs}
    n = len(sorted_xs)
    out = {}
    for q in qs:
        i = min(n - 1, int(q * n))
        out[q] = sorted_xs[i]
    return out


class SetFullChecker(Checker):
    name = "set-full"

    def __init__(self, add_f: str = "add"):
        # Broadcast remaps :broadcast -> :add (`broadcast.clj:215-227`);
        # rather than rewriting history we accept the add f directly.
        self.add_f = add_f

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        pairs = history.pairs()

        # Element -> add info
        attempts = {}          # element -> invoke time
        acked = {}             # element -> ack (completion) time
        for invoke, complete in pairs:
            if invoke.f != self.add_f:
                continue
            attempts[invoke.value] = invoke.time
            if complete is not None and complete.is_ok():
                acked[invoke.value] = complete.time

        # Reads: (invoke_time, complete_time, frozenset elements, dup counts)
        reads = []
        duplicated = {}
        for invoke, complete in pairs:
            if invoke.f != "read" or complete is None or not complete.is_ok():
                continue
            value = complete.value if complete.value is not None else []
            els = frozenset(value)
            if len(els) < len(value):
                counts = {}
                for e in value:
                    counts[e] = counts.get(e, 0) + 1
                for e, c in counts.items():
                    if c > 1:
                        duplicated[e] = max(duplicated.get(e, 0), c)
            reads.append((invoke.time, complete.time, els))
        reads.sort()

        lost, stable, never_read, stale = [], [], [], []
        stale_durations = {}
        stable_latencies = []

        for e, invoke_time in attempts.items():
            present = [(ti, tc) for (ti, tc, els) in reads if e in els]
            # known: acknowledged, or observed by any read
            if e in acked:
                known_time = acked[e]
            elif present:
                known_time = min(tc for ti, tc in present)
            else:
                continue   # unacknowledged and never seen: no claim on it

            counting_absent = [ti for (ti, tc, els) in reads
                               if ti > known_time and e not in els]
            last_absent = max(counting_absent, default=None)

            if last_absent is not None and not any(
                    ti > last_absent for ti, tc in present):
                lost.append(e)
                continue
            if not present and not counting_absent:
                never_read.append(e)
                continue

            stable.append(e)
            # Stability latency, jepsen set-full style: the time from the
            # element becoming known to the LAST moment any read observed
            # it missing — 0 when no read ever missed it. (A value only
            # re-confirmed by the final reads still gets its true
            # propagation latency, not the idle gap before the finals;
            # this is what makes the reference's quantile-0 exactly 0 and
            # its grid@100ms max ~791 ms ≈ full propagation,
            # `doc/03-broadcast/02-performance.md:187-191`.)
            if last_absent is not None:
                stale.append(e)
                stale_durations[e] = last_absent - known_time
            stable_latencies.append(
                max(0, ((last_absent or known_time) - known_time)) / 1e6)

        worst_stale = sorted(stale_durations,
                             key=lambda e: -stale_durations[e])[:8]
        stable_latencies.sort()

        any_reads = bool(reads)
        valid = (False if lost
                 else ("unknown" if not any_reads else True))
        return {
            "valid": valid,
            "attempt-count": len(attempts),
            "acknowledged-count": len(acked),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted(lost, key=repr),
            "never-read-count": len(never_read),
            "never-read": sorted(never_read, key=repr),
            "stale-count": len(stale),
            "stale": sorted(stale, key=repr),
            "worst-stale": worst_stale,
            "duplicated-count": len(duplicated),
            "duplicated": duplicated,
            "stable-latencies": {
                str(q): (round(v, 3) if v is not None else None)
                for q, v in quantiles(stable_latencies).items()},
        }


class BroadcastChecker(SetFullChecker):
    """set-full with :broadcast as the add op
    (reference `workload/broadcast.clj:215-227`)."""

    name = "broadcast"

    def __init__(self):
        super().__init__(add_f="broadcast")
