"""Kafka-style replicated-log checker (classic Maelstrom's `kafka`
workload, beyond the reference's seven; jepsen.tests.kafka's core
invariants) — for BOTH protocol modes (doc/streams.md):

Classic (full-prefix polls, `kafka_groups` unset):
  send ok:   [key, msg, offset]
  poll ok:   {key: [[offset, msg], ...]}    (full prefix from offset 0)
  commit ok: {key: offset}
  list ok:   {key: offset}

Streaming (consumer groups, `kafka_groups` > 0):
  poll ok:   {key: [[offset, msg], ...]}    (cursor fetch: a CONTIGUOUS
                                             run from the member's
                                             cursor — not a prefix)
  commit ok: {"group": g, "offsets": {key: offset}}
  list ok:   {"group": g, "offsets": {key: offset}}
  subscribe ok / rebalanced fails constrain nothing.

Checked invariants:
  1. **No divergence**: (key, offset) maps to one msg across every ok
     send and every poll observation, ever.
  2. **Order**: classic polls are strictly increasing full prefixes
     (a truncated head is a violation, not lag); streaming fetches are
     contiguous ascending runs (a gap inside a fetch is a violation).
  3. **No lost writes**: classic — a send acked at offset o must appear
     in every poll that *begins after the ack completes* and reads past
     a hole at o. Streaming — consumers advance contiguous cursors, so
     an acked offset that is NEVER observed while later offsets of the
     same key are observed by polls beginning after the ack is lost.
  4. **Committed-offset monotonicity**, per (group, key) in streaming
     mode (group None classic): a `list` that *begins after* a commit
     (or an earlier list) *completed* must report at least that offset.
     Commit REQUESTS for lower offsets are legal (the server clamps).

Indeterminate (`info`) ops constrain nothing; `fail` ops (misrouted,
fenced/rebalanced commits) definitely did not happen.

Structure: `extract_observation` compresses one (invoke, completion)
pair into a compact record; `grade` folds an invoke-ordered record list
into the verdict. The post-hoc path extracts from `history.pairs()`;
the overlapped pipeline (`checkers/pipeline.py`) extracts the SAME
records incrementally per drained window (with per-window early-warning
verdicts and a checker-lag metric) and re-sorts them by invoke row at
finish — so the two final verdicts are equal by construction (pinned
bit-equal in tests/test_pipeline_windows.py and test_continuous.py).
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


def _commit_shape(v: dict):
    """(group_or_None, {key: offset}) from a commit/list ok value —
    streaming values are {"group": g, "offsets": {...}}, classic ones
    are the flat offsets map."""
    if "offsets" in v and "group" in v:
        # `keys` (banked wide-key lists, nodes/kafka.py key_count > 4)
        # declares which keys the observation covers: a committed key
        # OUTSIDE the declared scope is unobserved, not regressed.
        # None = the observation covers every key (all pre-bank values).
        scope = (frozenset(str(k) for k in v["keys"])
                 if "keys" in v else None)
        return (int(v["group"]),
                {str(k): int(o) for k, o in v["offsets"].items()},
                scope)
    return None, {str(k): int(o) for k, o in v.items()}, None


def extract_observation(invoke, complete):
    """One (invoke, completion-or-None) pair -> a compact observation
    tuple, or None when the pair constrains nothing (unpaired, info,
    fail, malformed). Pure; shared by the post-hoc and windowed paths.

      ("send", ack_time, key, offset, msg)
      ("poll", invoke_time, {key: [[offset, msg], ...]})
      ("commit", complete_time, group_or_None, {key: offset})
      ("list", invoke_time, complete_time, group_or_None, {key: offset},
       scope_or_None)

    `scope` (streaming lists only) is the frozenset of key names the
    observation covers — banked wide-key lists read one 4-key window
    per RPC (see `_commit_shape`); None covers every key.
    """
    if complete is None or not complete.is_ok():
        return None
    f = invoke.f
    v = complete.value
    if f == "send":
        k, m, o = v
        return ("send", complete.time, str(k), int(o), m)
    if f == "poll" and isinstance(v, dict):
        return ("poll", invoke.time, v)
    if f == "commit" and isinstance(v, dict):
        grp, offs, _scope = _commit_shape(v)
        return ("commit", complete.time, grp, offs)
    if f == "list" and isinstance(v, dict):
        grp, offs, scope = _commit_shape(v)
        return ("list", invoke.time, complete.time, grp, offs, scope)
    return None


def grade(observations, streaming: bool = False) -> dict:
    """Folds observation records IN INVOKE ORDER into the whole-history
    verdict — the single grading implementation behind both checker
    paths (bit-equality of the windowed path is by construction)."""
    assign: dict = {}        # (key, offset) -> msg (first observer)
    divergent = []
    order_violations = []
    lost = []
    commit_regressions = []

    def observe(k, o, m, where):
        cur = assign.get((k, o))
        if cur is None:
            assign[(k, o)] = m
        elif cur != m:
            divergent.append({"key": k, "offset": o,
                              "values": [cur, m], "in": where})

    acked_sends = []         # (ack_time, key, offset, msg)
    polls = []               # (invoke_time, {key: [[o, m], ...]})
    commits = []             # (complete_time, group, {key: offset})
    lists = []               # (inv_t, complete_t, group, {key: offset})

    for rec in observations:
        tag = rec[0]
        if tag == "send":
            _, t, k, o, m = rec
            observe(k, o, m, "send_ok")
            acked_sends.append((t, k, o, m))
        elif tag == "poll":
            _, inv_t, value = rec
            polls.append((inv_t, value))
            for k, pairs in value.items():
                if streaming:
                    # cursor-fetch contract: one CONTIGUOUS ascending
                    # run (the server slices [start, start+n))
                    last = None
                    for o, m in pairs:
                        if last is not None and int(o) != last + 1:
                            order_violations.append(
                                {"key": k, "offsets": [last, int(o)],
                                 "error": "fetch entries must be "
                                          "contiguous"})
                        last = int(o)
                        observe(str(k), int(o), m, "poll_ok")
                else:
                    if pairs and int(pairs[0][0]) != 0:
                        order_violations.append(
                            {"key": k, "head-offset": int(pairs[0][0]),
                             "error": "full-prefix poll must start "
                                      "at offset 0"})
                    last = -1
                    for o, m in pairs:
                        if int(o) <= last:
                            order_violations.append(
                                {"key": k, "offsets": [last, int(o)]})
                        last = int(o)
                        observe(str(k), int(o), m, "poll_ok")
        elif tag == "commit":
            _, t, grp, offs = rec
            commits.append((t, grp, offs))
        else:   # list
            _, inv_t, t, grp, offs, scope = rec
            lists.append((inv_t, t, grp, offs, scope))

    # 3. lost writes.
    if streaming:
        # Consumers advance contiguous per-group cursors from the log
        # head, so the union of observed offsets per key has no holes on
        # a correct server. An acked offset never observed while a poll
        # that BEGAN after the ack observed a later offset of the same
        # key marks a loss (the cursor stream read past it).
        union: dict = {}         # key -> set of observed offsets
        per_key_polls: dict = {}  # key -> [(inv_t, max observed o)]
        for inv_t, value in polls:
            for k, pairs in value.items():
                if not pairs:
                    continue
                offs = {int(p[0]) for p in pairs}
                union.setdefault(str(k), set()).update(offs)
                per_key_polls.setdefault(str(k), []).append(
                    (inv_t, max(offs)))
        for ack_t, k, o, m in acked_sends:
            if o in union.get(k, ()):
                continue
            later = [mx for t2, mx in per_key_polls.get(k, ())
                     if t2 > ack_t and mx > o]
            if later:
                lost.append({"key": k, "offset": o, "msg": m,
                             "poll-max-offset": max(later)})
    else:
        # Polls are full prefixes, so a poll's "holes" (offsets below
        # its max that it does NOT contain) are the only places a loss
        # can show — and a correct server has none, which makes this
        # sweep effectively linear.
        holes_by_key: dict = {}  # key -> [(poll_t, max_o, holes set)]
        for poll_t, value in polls:
            for k, pairs in value.items():
                if not pairs:
                    continue
                offsets = {int(p[0]) for p in pairs}
                max_o = max(offsets)
                holes = set(range(max_o + 1)) - offsets
                if holes:
                    holes_by_key.setdefault(str(k), []).append(
                        (poll_t, max_o, holes))
        for ack_t, k, o, m in acked_sends:
            for poll_t, max_o, holes in holes_by_key.get(k, ()):
                if poll_t > ack_t and o in holes:
                    lost.append({"key": k, "offset": o, "msg": m,
                                 "poll-max-offset": max_o})
                    break

    # 4. the stored committed mark only advances, per (group, key):
    # every list that BEGAN after a commit (or an earlier list)
    # COMPLETED must observe at least that offset. One time-sorted sweep
    # with running per-(group, key) floors; at equal timestamps checks
    # run before floor-raises (lenient toward concurrency).
    events = ([(c_t, 1, None, offs, grp, None)
               for c_t, grp, offs in commits]
              + [(c2, 1, None, offs, grp, None)
                 for _i, c2, grp, offs, _s in lists]
              + [(li_inv, 0, offs, None, grp, scope)
                 for li_inv, _c, grp, offs, scope in lists])
    floor: dict = {}             # (group, key) -> offset
    for _t, _kind, check_offs, raise_offs, grp, scope in sorted(
            events, key=lambda e: (e[0], e[1])):
        if check_offs is not None:
            for (g2, k), lo in floor.items():
                if g2 != grp:
                    continue
                if scope is not None and k not in scope:
                    continue    # banked list: key outside its window
                if check_offs.get(k, -1) < lo:
                    rec = {"key": k, "committed": lo,
                           "observed": check_offs.get(k, -1)}
                    if g2 is not None:
                        rec["group"] = g2
                    commit_regressions.append(rec)
        else:
            for k, o in raise_offs.items():
                key = (grp, k)
                floor[key] = max(floor.get(key, -1), o)

    problems = {}
    if divergent:
        problems["divergent"] = divergent[:16]
    if order_violations:
        problems["poll-order"] = order_violations[:16]
    if lost:
        problems["lost-writes"] = lost[:16]
    if commit_regressions:
        problems["commit-regressions"] = commit_regressions[:16]
    out = {
        "valid": not problems,
        "acked-sends": len(acked_sends),
        "polls": len(polls),
        "distinct-offsets": len(assign),
    }
    out.update(problems)
    # a run with no certifiable observations can't certify anything
    # — but found anomalies always dominate (false beats unknown)
    if not problems and not acked_sends and not polls and not lists:
        out["valid"] = "unknown"
        out["error"] = ("no certifiable kafka observation (send/poll/"
                        "list) ever succeeded")
    return out


class KafkaStreamObserver:
    """The pipeline-side incremental grader (doc/streams.md): fed one
    (invoke, completion) pair at a time in COMPLETION order by the
    analysis worker, it extracts the same compact records `grade`
    consumes, carries cross-window state (assignment map, pending acked
    sends, committed floors with their raise times — the open-
    subscription state), and reports per-window verdicts:

      - divergence / order violations: exact (order-independent /
        poll-local), detected in the window whose fetch exposes them;
      - lost-acked-writes: detected in the window whose poll reads past
        the loss (classic rule exact — every binding ack precedes the
        poll in completion order; streaming rule conservative the same
        way `grade`'s is);
      - commit regressions: exact including the equal-timestamp
        leniency — floors are kept as (raise_time, cummax) runs, and a
        list checks only floors raised strictly before its invoke.

    The FINAL verdict never comes from this running state: at check
    time the records re-sort by invoke row and go through the same
    `grade` fold as the post-hoc path, so the two verdicts are equal by
    construction."""

    name = "kafka"

    def __init__(self, test=None):
        self.streaming = bool((test or {}).get("kafka_groups"))
        self.obs: list = []      # (invoke_row, record), completion order
        self._assign: dict = {}
        self._acked: list = []   # (ack_t, key, offset, msg), unobserved
        self._union: dict = {}   # streaming: key -> observed offsets
        self._raises: dict = {}  # (grp, key) -> [(raise_t, cummax)]
        self._win_new = {"divergent": 0, "poll-order": 0,
                         "lost-writes": 0, "commit-regressions": 0}
        self._win_ops = 0

    # --- feeding (analysis worker thread) ---

    def observe(self, inv_row: int, invoke, complete):
        rec = extract_observation(invoke, complete)
        if rec is None:
            return
        self.obs.append((inv_row, rec))
        self._win_ops += 1
        self._fold(rec)

    def _bump(self, which: str, n: int = 1):
        if n:
            self._win_new[which] += n

    def _observe_assign(self, k, o, m):
        cur = self._assign.get((k, o))
        if cur is None:
            self._assign[(k, o)] = m
        elif cur != m:
            self._bump("divergent")

    def _fold(self, rec):
        tag = rec[0]
        if tag == "send":
            _, t, k, o, m = rec
            self._observe_assign(k, o, m)
            # classic mode keeps every ack (any later poll may hole it);
            # streaming prunes observed offsets (the union never
            # un-observes, so they can't be lost anymore)
            if not (self.streaming and o in self._union.get(k, ())):
                self._acked.append((t, k, o, m))
        elif tag == "poll":
            _, inv_t, value = rec
            for k, pairs in value.items():
                k = str(k)
                if not pairs:
                    continue
                offs = {int(p[0]) for p in pairs}
                last = None
                for o, m in pairs:
                    o = int(o)
                    if last is None:
                        if not self.streaming and o != 0:
                            self._bump("poll-order")
                    elif (o != last + 1 if self.streaming
                          else o <= last):
                        self._bump("poll-order")
                    last = o
                    self._observe_assign(k, o, m)
                u = self._union.setdefault(k, set())
                u.update(offs)
                max_o = max(offs)
                if self.streaming:
                    self._bump("lost-writes", sum(
                        1 for t2, k2, o2, _m in self._acked
                        if k2 == k and o2 not in u and inv_t > t2
                        and max_o > o2))
                    self._acked = [a for a in self._acked
                                   if a[1] != k or a[2] not in u]
                else:
                    holes = set(range(max_o + 1)) - offs
                    if holes:
                        self._bump("lost-writes", sum(
                            1 for t2, k2, o2, _m in self._acked
                            if k2 == k and inv_t > t2 and o2 in holes))
        elif tag == "commit":
            _, t, grp, offs = rec
            for k, o in offs.items():
                self._raise_floor(grp, k, t, o)
        else:   # list
            _, inv_t, t, grp, offs, scope = rec
            for (g2, k), runs in self._raises.items():
                if g2 != grp:
                    continue
                if scope is not None and k not in scope:
                    continue    # banked list: key outside its window
                # binding floor: highest raise STRICTLY before the
                # list's invoke (equal-timestamp leniency of `grade`)
                lo = -1
                for rt, cm in reversed(runs):
                    if rt < inv_t:
                        lo = cm
                        break
                if lo >= 0 and offs.get(k, -1) < lo:
                    self._bump("commit-regressions")
            for k, o in offs.items():
                self._raise_floor(grp, k, t, o)

    def _raise_floor(self, grp, k, t, o):
        runs = self._raises.setdefault((grp, k), [])
        cur = runs[-1][1] if runs else -1
        runs.append((t, max(cur, o)))

    # --- window close (analysis worker thread) ---

    def window_close(self) -> dict:
        v = {"ops": self._win_ops,
             "ok": not any(self._win_new.values())}
        v.update({k: n for k, n in self._win_new.items() if n})
        self._win_ops = 0
        self._win_new = dict.fromkeys(self._win_new, 0)
        return v

    # --- finish (check time) ---

    def records_in_invoke_order(self) -> list:
        return [rec for _row, rec in
                sorted(self.obs, key=lambda t: t[0])]


class KafkaChecker(Checker):
    name = "kafka"
    # the overlapped pipeline feeds this checker's stream observer
    # (windowed incremental grading); verdicts stay bit-identical to
    # the post-hoc path either way
    consumes_analysis = True

    def make_stream_observer(self, test):
        return KafkaStreamObserver(test)

    def check(self, test, history, opts=None):
        streaming = bool(test.get("kafka_groups")) \
            if isinstance(test, dict) else False
        pipe = test.get("analysis") if isinstance(test, dict) else None
        if pipe is not None and hasattr(pipe, "stream_results"):
            served = pipe.stream_results("kafka", len(history))
            if served is not None:
                observer, windows = served
                out = grade(observer.records_in_invoke_order(),
                            streaming)
                lags = [w.get("lag-rounds") for w in windows
                        if w.get("lag-rounds") is not None]
                out["windows"] = windows
                out["checker-lag"] = {
                    "windows": len(windows),
                    "max-lag-rounds": max(lags) if lags else 0,
                    "mean-lag-rounds": (round(sum(lags) / len(lags), 1)
                                        if lags else 0.0),
                }
                return out
        history = coerce_history(history)
        obs = [extract_observation(i, c) for i, c in history.pairs()]
        return grade([r for r in obs if r is not None], streaming)
