"""Kafka-style replicated-log checker (classic Maelstrom's `kafka`
workload, beyond the reference's seven; jepsen.tests.kafka's core
invariants, restated for full-prefix polls).

History value conventions (see workloads/kafka.py):
  send ok:   [key, msg, offset]
  poll ok:   {key: [[offset, msg], ...]}    (server returns the full
                                             prefix, from offset 0)
  commit ok: {key: offset}
  list ok:   {key: offset}

Checked invariants:
  1. **No divergence**: (key, offset) maps to one msg across every ok
     send and every poll, ever.
  2. **Order**: within a single poll, each key's offsets are strictly
     increasing AND start at the log head (offset 0) — the poll RPC's
     contract is a full prefix, so a truncated head is an order
     violation, not lag.
  3. **No lost writes**: a send acked at offset o must appear in every
     poll that *begins after the ack completes* and observes any offset
     >= o for that key (reading past a hole means the hole is a loss,
     not lag).
  4. **Committed-offset monotonicity**: the stored committed offset of
     a key only advances. Observable as: a `list` that *begins after* a
     `commit` completed must report at least the committed offset, and
     a `list` that begins after another `list` completed must never
     report less. (A commit *requesting* a lower offset is legal — the
     server clamps — so commit requests are lower bounds, not
     observations.)

Indeterminate (`info`) sends constrain nothing (their offset was never
observed); indeterminate commits may or may not advance the committed
offset, so they widen what a later list may legally return.
"""

from __future__ import annotations

from . import Checker
from ..history import coerce_history


class KafkaChecker(Checker):
    name = "kafka"

    def check(self, test, history, opts=None):
        history = coerce_history(history)
        assign: dict = {}        # (key, offset) -> msg (first observer)
        divergent = []
        order_violations = []
        lost = []
        commit_regressions = []

        def observe(k, o, m, where):
            cur = assign.get((k, o))
            if cur is None:
                assign[(k, o)] = m
            elif cur != m:
                divergent.append({"key": k, "offset": o,
                                  "values": [cur, m], "in": where})

        acked_sends = []         # (ack_time, key, offset, msg)
        polls = []               # (invoke_time, {key: [[o, m], ...]})
        commits = []             # (complete_time, {key: offset})
        lists = []               # (invoke_time, complete_time, {k: o})

        for invoke, complete in history.pairs():
            ok = complete is not None and complete.is_ok()
            if invoke.f == "send":
                if ok:
                    k, m, o = complete.value
                    observe(str(k), int(o), m, "send_ok")
                    acked_sends.append((complete.time, str(k), int(o), m))
            elif invoke.f == "poll":
                if ok and isinstance(complete.value, dict):
                    polls.append((invoke.time, complete.value))
                    for k, pairs in complete.value.items():
                        if pairs and int(pairs[0][0]) != 0:
                            order_violations.append(
                                {"key": k, "head-offset": int(pairs[0][0]),
                                 "error": "full-prefix poll must start "
                                          "at offset 0"})
                        last = -1
                        for o, m in pairs:
                            if int(o) <= last:
                                order_violations.append(
                                    {"key": k, "offsets": [last, int(o)]})
                            last = int(o)
                            observe(str(k), int(o), m, "poll_ok")
            elif invoke.f == "commit":
                if ok and isinstance(complete.value, dict):
                    commits.append(
                        (complete.time,
                         {str(k): int(v) for k, v in
                          complete.value.items()}))
            elif invoke.f == "list":
                if ok and isinstance(complete.value, dict):
                    lists.append(
                        (invoke.time, complete.time,
                         {str(k): int(v) for k, v in
                          complete.value.items()}))

        # 3. lost writes. Polls are full prefixes, so a poll's "holes"
        # (offsets below its max that it does NOT contain) are the only
        # places a loss can show — and a correct server has none, which
        # makes this sweep effectively linear: enumerate each poll's
        # holes once, then check acked sends only against the (rare)
        # holey polls that started after their ack.
        holes_by_key: dict = {}     # key -> [(poll_t, max_o, holes set)]
        for poll_t, value in polls:
            for k, pairs in value.items():
                if not pairs:
                    continue
                offsets = {int(p[0]) for p in pairs}
                max_o = max(offsets)
                holes = set(range(max_o + 1)) - offsets
                if holes:
                    holes_by_key.setdefault(str(k), []).append(
                        (poll_t, max_o, holes))
        for ack_t, k, o, m in acked_sends:
            for poll_t, max_o, holes in holes_by_key.get(k, ()):
                if poll_t > ack_t and o in holes:
                    lost.append({"key": k, "offset": o, "msg": m,
                                 "poll-max-offset": max_o})
                    break

        # 4. the stored committed mark only advances: every list that
        # BEGAN after a commit (or an earlier list) COMPLETED must
        # observe at least that offset per key. One time-sorted sweep
        # with a running per-key floor; at equal timestamps checks run
        # before floor-raises (lenient toward concurrency).
        events = ([(c_t, 1, None, offs) for c_t, offs in commits]
                  + [(c2, 1, None, offs) for _i, c2, offs in lists]
                  + [(li_inv, 0, offs, None) for li_inv, _c, offs in lists])
        floor: dict = {}
        for _t, _kind, check_offs, raise_offs in sorted(
                events, key=lambda e: (e[0], e[1])):
            if check_offs is not None:
                for k, lo in floor.items():
                    if check_offs.get(k, -1) < lo:
                        commit_regressions.append(
                            {"key": k, "committed": lo,
                             "observed": check_offs.get(k, -1)})
            else:
                for k, o in raise_offs.items():
                    floor[k] = max(floor.get(k, -1), o)

        problems = {}
        if divergent:
            problems["divergent"] = divergent[:16]
        if order_violations:
            problems["poll-order"] = order_violations[:16]
        if lost:
            problems["lost-writes"] = lost[:16]
        if commit_regressions:
            problems["commit-regressions"] = commit_regressions[:16]
        out = {
            "valid": not problems,
            "acked-sends": len(acked_sends),
            "polls": len(polls),
            "distinct-offsets": len(assign),
        }
        out.update(problems)
        # a run with no certifiable observations can't certify anything
        # — but found anomalies always dominate (false beats unknown)
        if not problems and not acked_sends and not polls and not lists:
            out["valid"] = "unknown"
            out["error"] = ("no certifiable kafka observation (send/poll/"
                            "list) ever succeeded")
        return out
