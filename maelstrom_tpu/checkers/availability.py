"""Availability accounting: the measured "dips, never violations" claim.

The failover work (doc/compartment.md "leader election") turns killing
the live sequencer from durable downtime into an availability DIP — a
bounded window with no committed client replies. This checker makes that
a measured artifact instead of a log line: it folds the history's ok
completions into no-reply gaps (virtual rounds, so the numbers are
deterministic per seed and identical plain/--mesh/resumed), attributes a
recovery time to every kill window, and surfaces the program's election
accounting (completed failovers, rounds-to-new-leader) when the node
family reports one (`NodeProgram.election_report`).

Purely observational: `valid` is always True — the linearizable verdict
stays the workload checker's job; this block quantifies the outage
shape beside it. Everything except `check-wall-s` is a pure function of
the (deterministic) history + device state, so `crash_soak.compare_runs`
and the overlap-equivalence `_comparable` strip only that wall-clock
key.
"""

from __future__ import annotations

import time

from . import Checker
from ..history import coerce_history


def gaps_rounds(ok_rounds: list, start_r: int, end_r: int) -> list:
    """[(gap_start_round, gap_rounds)] between consecutive committed
    replies, including the leading (start -> first ok) and trailing
    (last ok -> end) windows. Empty history = one gap spanning the
    run."""
    out = []
    prev = start_r
    for r in ok_rounds:
        if r > prev:
            out.append((prev, r - prev))
        prev = max(prev, r)
    if end_r > prev:
        out.append((prev, end_r - prev))
    return out


def availability_block(history, ms_per_round: float, end_round: int,
                       dip_threshold_rounds: int,
                       kill_rounds: list | None = None) -> dict:
    """The pure (history-only) part of the block: longest no-ok gap,
    dips past the threshold, and per-kill recovery times. All units are
    VIRTUAL rounds."""
    history = coerce_history(history)
    ns_pr = ms_per_round * 1e6
    ok_r = sorted(int(o.time // ns_pr) for o in history
                  if o.type == "ok" and o.process != "nemesis")
    gaps = gaps_rounds(ok_r, 0, int(end_round))
    longest = max((g for _s, g in gaps), default=int(end_round))
    dips = [(s, g) for s, g in gaps if g > dip_threshold_rounds]
    out = {
        "ok-count": len(ok_r),
        "final-round": int(end_round),
        "longest-ok-gap-rounds": int(longest),
        "dip-threshold-rounds": int(dip_threshold_rounds),
        "dip-count": len(dips),
        # cap the listing: the headline numbers above stay exact
        "dips": [{"from-round": int(s), "rounds": int(g)}
                 for s, g in dips[:32]],
    }
    if kill_rounds is None:
        kill_rounds = [int(o.time // ns_pr) for o in history
                       if o.process == "nemesis" and o.type == "invoke"
                       and o.f == "start-kill"]
    if kill_rounds:
        import bisect
        rec = []
        for kr in kill_rounds:
            i = bisect.bisect_right(ok_r, kr)
            rec.append((ok_r[i] - kr) if i < len(ok_r)
                       else (int(end_round) - kr))
        out["failover-recovery-rounds"] = {
            "per-kill": [int(x) for x in rec],
            "mean": round(sum(rec) / len(rec), 2),
            "max": int(max(rec)),
        }
    return out


class AvailabilityChecker(Checker):
    """Runner-attached availability block (TPU path; installed by
    `run_tpu_test` / the fleet's per-cluster check next to TpuNetStats).
    The dip threshold defaults to the run's RPC timeout in rounds — a
    no-reply window longer than the client timeout is an outage by any
    client's measure — and is overridable via the
    `availability_dip_rounds` option."""

    name = "availability"

    def __init__(self, runner):
        self.runner = runner

    def check(self, test, history, opts=None):
        t0 = time.perf_counter()
        thr = int(test.get("availability_dip_rounds")
                  or self.runner.timeout_rounds)
        out = availability_block(
            history,
            ms_per_round=float(test.get("ms_per_round", 1.0)),
            end_round=int(getattr(self.runner, "final_round", 0) or 0),
            dip_threshold_rounds=thr)
        rep_fn = getattr(self.runner.program, "election_report", None)
        if rep_fn is not None:
            try:
                rep = rep_fn(self.runner._nodes_host())
            except Exception as e:    # observational: never fail the run
                rep = {"error": repr(e)}
            if rep is not None:
                out["election"] = rep
        out["valid"] = True
        out["check-wall-s"] = round(time.perf_counter() - t0, 6)
        return out
