"""Network messages (reference: src/maelstrom/net/message.clj).

Messages always have a `src`, `dest`, and `body`; an `id` is assigned
internally by the network (reference `net.clj:26-32`, `message.clj:8-25`).
Bodies are arbitrary JSON objects at this (host) layer; the TPU network core
(`maelstrom_tpu.net.tpu.Msgs`) uses a fixed-width integer encoding — a type
code plus payload words — and each TPU node program defines the JSON<->words
codec applied at the host boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class Message:
    id: int
    src: str
    dest: str
    body: Any

    def to_json(self) -> dict:
        return {"id": self.id, "src": self.src, "dest": self.dest,
                "body": self.body}


def message(src: str, dest: str, body, id: int = -1) -> Message:
    """Constructs a new Message. If no ID is provided, uses -1
    (reference `message.clj:10-15`)."""
    return Message(id=id, src=src, dest=dest, body=body)


class MalformedMessage(Exception):
    def __init__(self, msg, why: str):
        self.message = msg
        super().__init__(why)


def validate(m) -> Message:
    """Checks that a message is well-formed (reference `message.clj:17-25`,
    `net.clj:165-175`)."""
    if not isinstance(m, Message):
        raise MalformedMessage(m, f"Expected message {m!r} to be a Message")
    if not m.src:
        raise MalformedMessage(m, f"No source for message {m!r}")
    if not m.dest:
        raise MalformedMessage(m, f"No destination for message {m!r}")
    if not isinstance(m.body, dict):
        raise MalformedMessage(
            m, f"Message body must be an object, got {m.body!r}")
    return m


def parse_msg(node_id: str, line: str) -> Message:
    """Parses a JSON line printed by a node process into a Message, with
    teaching errors (reference `process.clj:35-66`)."""
    import json
    try:
        parsed = json.loads(line)
    except json.JSONDecodeError:
        raise MalformedMessage(
            line,
            f"Node {node_id} printed a line to STDOUT which was not "
            f"well-formed JSON:\n{line}\nDid you mean to encode this line as "
            "JSON? Or was this line intended for STDERR? See doc/protocol.md "
            "for more guidance.")
    if not isinstance(parsed, dict) or not isinstance(parsed.get("body"), dict):
        raise MalformedMessage(
            parsed,
            f"Malformed network message. Node {node_id} tried to send the "
            f"following message via STDOUT:\n\n{line}\n\nMessages must be "
            "JSON objects with src, dest, and an object body. See "
            "doc/protocol.md for more guidance.")
    m = Message(id=int(parsed.get("id", -1)), src=parsed.get("src"),
                dest=parsed.get("dest"), body=parsed["body"])
    if not m.src or not m.dest:
        raise MalformedMessage(
            parsed,
            f"Malformed network message from node {node_id}: messages "
            f"require both src and dest:\n\n{line}\n\nSee doc/protocol.md "
            "for more guidance.")
    return m
