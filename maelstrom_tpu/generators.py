"""Jepsen-style operation generators.

The reference composes its workload generators from jepsen.generator:
`stagger` (rate limiting), `mix`, `each-thread`, `phases`, `time-limit`,
`nemesis` wrapping, `sleep`, `log`, and final-generator recovery phases
(reference `core.clj:58-71`, workload files). This module provides the same
combinators as *pure* generators so that the same workload definitions drive
both the real-time host path and the virtual-time TPU path.

A generator responds to `op(ctx)` with a pair `(result, next_gen)`:

  - result is an op dict   -> dispatch it (process/time filled in)
  - result is PENDING      -> nothing yet; ask again at ctx["time"] >=
                              the generator's next interesting time
  - result is None         -> exhausted forever

`update(ctx, event)` lets generators observe invocations/completions.
ctx is {"time": ns, "free": [process ...], "processes": [...]} where
"nemesis" is a special process; all others are client workers.
"""

from __future__ import annotations

import math
import random
from typing import Any, Iterable, Optional

PENDING = "pending"
NEMESIS = "nemesis"


def rotate_free(free, dispatch_count: int) -> list:
    """Rotates the sorted free-process list by a monotonically increasing
    dispatch counter so successive ops spread across workers — and
    therefore across nodes (worker i talks to node i % n). Leaf generators
    take free[0]; always offering the same first worker would starve every
    node but one of client traffic (fatal for e.g. a raft leader elsewhere).
    The counter must count dispatches, not history length: history grows by
    two per op (invoke + completion), which aliases even-sized pools."""
    fs = sorted(free, key=str)
    if not fs:
        return fs
    k = dispatch_count % len(fs)
    return fs[k:] + fs[:k]


def client_processes(ctx) -> list:
    """Processes visible in this context. Routing to clients vs the nemesis
    is done by the OnProcesses wrappers (clients()/nemesis_gen()), so leaf
    generators simply take whatever the context offers."""
    return list(ctx["processes"])


def free_clients(ctx) -> list:
    return list(ctx["free"])


class Gen:
    def op(self, ctx):
        raise NotImplementedError

    def update(self, ctx, event):
        return self

    def next_interesting_time(self, ctx) -> float:
        """After this generator returned PENDING at ctx["time"]: the
        earliest ctx time (ns) at which it might produce an op *without any
        new completion event*, or +inf if only a completion (reply/timeout
        freeing a worker) can unblock it. Lets the TPU runner scan many
        rounds in one compiled dispatch, stopping exactly where the
        generator could next act. Returning a too-late time would delay
        ops (wrong); too-early merely costs a dispatch (safe). The inf
        default is correct for every generator that PENDs only for lack of
        free processes."""
        return math.inf


class cycle:
    """Endless iterator over a fixed element list — the picklable
    itertools.cycle replacement (itertools pickling goes away in 3.14).
    Feed to Seq for repeating schedules (e.g. the nemesis on/off cycle)."""

    def __init__(self, elements, i: int = 0):
        self.elements = list(elements)
        self.i = i

    def __iter__(self):
        return self

    def __next__(self):
        x = self.elements[self.i % len(self.elements)]
        self.i += 1
        return x


def fill_op(op: dict, ctx, process) -> dict:
    out = dict(op)
    out.setdefault("process", process)
    out["time"] = ctx["time"]
    out.setdefault("type", "invoke")
    return out


def to_gen(x) -> Optional[Gen]:
    """Coerces maps, iterables, functions, and generators to Gen."""
    if x is None or isinstance(x, Gen):
        return x
    if isinstance(x, dict):
        return Once(x)
    if callable(x):
        return Fn(x)
    if isinstance(x, (list, tuple)) or hasattr(x, "__iter__"):
        return Seq(x)
    raise TypeError(f"can't coerce {x!r} to a generator")


class Once(Gen):
    """Emits a single op to the first free client."""

    def __init__(self, op_map: dict, done: bool = False):
        self.op_map = op_map
        self.done = done

    def op(self, ctx):
        if self.done:
            return None, self
        free = free_clients(ctx)
        if not free:
            return PENDING, self
        return fill_op(self.op_map, ctx, free[0]), Once(self.op_map, True)


class Seq(Gen):
    """Emits ops from an iterable, one per request. Elements may themselves
    be generators (e.g. the nemesis cycle interleaves Sleep gens with op
    maps); a nested generator runs until exhausted, then Seq advances."""

    def __init__(self, iterable):
        self.it = iter(iterable)
        self.head = None        # lookahead buffer (op map or nested Gen)

    def op(self, ctx):
        while True:
            if self.head is None:
                try:
                    self.head = next(self.it)
                except StopIteration:
                    return None, self
            h = self.head
            if isinstance(h, Gen) or callable(h) or not isinstance(h, dict):
                sub = to_gen(h)
                res, sub2 = sub.op(ctx)
                if res is None:
                    self.head = None    # nested gen exhausted: next element
                    continue
                self.head = sub2        # keep successor state
                return res, self
            free = free_clients(ctx)
            if not free:
                return PENDING, self
            self.head = None
            return fill_op(h, ctx, free[0]), self

    def next_interesting_time(self, ctx) -> float:
        if isinstance(self.head, Gen):
            return self.head.next_interesting_time(ctx)
        return math.inf


class Fn(Gen):
    """Calls a zero-arg function to produce each op map (like the
    reference's `(fn [] {:f :add :value (rand-int ...)})` generators)."""

    def __init__(self, f):
        self.f = f

    def op(self, ctx):
        free = free_clients(ctx)
        if not free:
            return PENDING, self
        op_map = self.f()
        if op_map is None:
            return None, self
        return fill_op(op_map, ctx, free[0]), self


class Counting(Gen):
    """Emits {"f": f, "value": 0}, {"f": f, "value": 1}, ... — the picklable
    form of `Seq({"f": f, "value": x} for x in itertools.count())` used by
    set-style workloads (reference `broadcast.clj:229-233`)."""

    def __init__(self, f: str, n: int = 0):
        self.f = f
        self.n = n

    def op(self, ctx):
        free = free_clients(ctx)
        if not free:
            return PENDING, self
        op_map = {"f": self.f, "value": self.n}
        return fill_op(op_map, ctx, free[0]), Counting(self.f, self.n + 1)


class BatchCounting(Gen):
    """Columnar distilled-batch assembly (doc/perf.md "batched atomic
    broadcast"): each emission is ONE `broadcast-batch` op whose value
    is a distilled batch — up to `batch_max` fresh sequential client
    values plus a seeded fraction of duplicate re-submissions, deduped
    and sorted by the batcher before the op leaves.

    The assembly is numpy-columnar: the raw submission buffer is an
    int array and distillation is one `np.unique` — no per-value Python
    dict churn — and one generator poll (one host-loop iteration, one
    pending-table entry, one wire message) now covers a whole batch of
    client values instead of one. At `--fleet` scale this is the host
    bookkeeping lever ROADMAP flags: per-cluster generator cost scales
    with batches, not ops.

    Like Stagger/MixG, successor states share the mutable RNG; draws
    happen only on actual emission (PENDING polls are rng-neutral), so
    the scan-ahead and per-round paths see identical op streams."""

    def __init__(self, f: str = "broadcast-batch", batch_max: int = 16,
                 dup_rate: float = 0.25, seed: int = 0,
                 next_value: int = 0, rng=None):
        import numpy as np
        self.f = f
        self.batch_max = max(1, int(batch_max))
        self.dup_rate = float(dup_rate)
        self.next_value = next_value
        self.rng = rng if rng is not None else np.random.RandomState(
            seed & 0x7FFFFFFF)

    def op(self, ctx):
        import numpy as np
        free = free_clients(ctx)
        if not free:
            return PENDING, self
        b = int(self.rng.randint(1, self.batch_max + 1))
        fresh = np.arange(self.next_value, self.next_value + b,
                          dtype=np.int64)
        # seeded duplicate submissions FROM THIS batch: the raw stream a
        # real client fleet offers is at-least-once, and distillation is
        # what collapses it (Chop Chop's dedup half)
        n_dup = int(self.rng.binomial(b, self.dup_rate))
        raw = fresh if not n_dup else np.concatenate(
            [fresh, self.rng.choice(fresh, size=n_dup)])
        distilled = np.unique(raw)          # dedup + sort, one pass
        op_map = {"f": self.f, "value": [int(v) for v in distilled],
                  "raw-count": int(raw.size)}
        nxt = BatchCounting(self.f, self.batch_max, self.dup_rate,
                            next_value=self.next_value + b, rng=self.rng)
        return fill_op(op_map, ctx, free[0]), nxt


class Repeat(Gen):
    def __init__(self, op_map: dict):
        self.op_map = op_map

    def op(self, ctx):
        free = free_clients(ctx)
        if not free:
            return PENDING, self
        return fill_op(self.op_map, ctx, free[0]), self


class EachThread(Gen):
    """Emits the op once on every client process
    (jepsen gen/each-thread; used for final reads,
    reference `broadcast.clj:239`)."""

    def __init__(self, op_map: dict, done: frozenset = frozenset()):
        self.op_map = op_map
        self.done = done

    def op(self, ctx):
        remaining = [p for p in free_clients(ctx) if p not in self.done]
        if not remaining:
            if all(p in self.done for p in client_processes(ctx)):
                return None, self
            return PENDING, self
        p = remaining[0]
        return (fill_op(self.op_map, ctx, p),
                EachThread(self.op_map, self.done | {p}))


class TimeLimit(Gen):
    """Stops emitting after dt_ns of ctx time (jepsen gen/time-limit,
    reference `core.clj:62`)."""

    def __init__(self, dt_ns: int, gen, t0: int | None = None):
        self.dt_ns = dt_ns
        self.gen = to_gen(gen)
        self.t0 = t0

    def op(self, ctx):
        t0 = ctx["time"] if self.t0 is None else self.t0
        if ctx["time"] - t0 >= self.dt_ns:
            return None, self
        res, g2 = self.gen.op(ctx)
        return res, TimeLimit(self.dt_ns, g2, t0)

    def update(self, ctx, event):
        return TimeLimit(self.dt_ns, self.gen.update(ctx, event), self.t0)

    def next_interesting_time(self, ctx) -> float:
        t0 = ctx["time"] if self.t0 is None else self.t0
        if ctx["time"] - t0 >= self.dt_ns:
            return math.inf     # already exhausted: nothing more, ever
        # expiry matters: it exhausts this gen, which can advance Phases
        return min(self.gen.next_interesting_time(ctx), t0 + self.dt_ns)


class Stagger(Gen):
    """Rate limiting: introduces random delays averaging dt between ops
    (jepsen gen/stagger; reference `core.clj:59` uses (stagger (/ rate)))."""

    def __init__(self, dt_ns: float, gen, next_time: float | None = None,
                 rng: random.Random | None = None):
        self.dt_ns = dt_ns
        self.gen = to_gen(gen)
        self.next_time = next_time
        self.rng = rng or random.Random(1)

    def op(self, ctx):
        t = ctx["time"]
        nt = t if self.next_time is None else self.next_time
        if t < nt:
            return PENDING, self
        res, g2 = self.gen.op(ctx)
        if res is None or res == PENDING:
            return res, Stagger(self.dt_ns, g2, nt, self.rng)
        # schedule next emission: uniform in [0, 2*dt] after this one
        nt2 = nt + self.rng.uniform(0, 2 * self.dt_ns)
        return res, Stagger(self.dt_ns, g2, nt2, self.rng)

    def update(self, ctx, event):
        return Stagger(self.dt_ns, self.gen.update(ctx, event),
                       self.next_time, self.rng)

    def next_interesting_time(self, ctx) -> float:
        if self.next_time is not None and ctx["time"] < self.next_time:
            return self.next_time
        return self.gen.next_interesting_time(ctx)


class Sleep(Gen):
    """Emits nothing for dt, then is exhausted (jepsen gen/sleep,
    reference `core.clj:69`)."""

    def __init__(self, dt_ns: int, t0: int | None = None):
        self.dt_ns = dt_ns
        self.t0 = t0

    def op(self, ctx):
        t0 = ctx["time"] if self.t0 is None else self.t0
        if self.t0 is None:
            return PENDING, Sleep(self.dt_ns, ctx["time"])
        if ctx["time"] - t0 >= self.dt_ns:
            return None, self
        return PENDING, self

    def next_interesting_time(self, ctx) -> float:
        t0 = ctx["time"] if self.t0 is None else self.t0
        return t0 + self.dt_ns


class Log(Gen):
    """Logs a message once, emits no ops (jepsen gen/log,
    reference `core.clj:68`)."""

    def __init__(self, message: str, done: bool = False):
        self.message = message
        self.done = done

    def op(self, ctx):
        if not self.done:
            import logging
            logging.getLogger("maelstrom").info(self.message)
            self.done = True    # mutate: callers may re-poll the same node
        return None, Log(self.message, True)


class Phases(Gen):
    """Runs generators in sequence; a phase must be exhausted AND all its
    ops completed (every process free) before the next phase starts
    (jepsen gen/phases, reference `core.clj:66-71`)."""

    def __init__(self, *gens):
        self.gens = [to_gen(g) for g in gens if g is not None]

    def op(self, ctx):
        if not self.gens:
            return None, self
        res, g2 = self.gens[0].op(ctx)
        if res is None:
            # phase exhausted; wait for quiescence before advancing
            if set(ctx["free"]) >= set(ctx["processes"]):
                nxt = Phases(*self.gens[1:])
                if not nxt.gens:
                    return None, nxt
                return nxt.op(ctx)
            rest = Phases()
            rest.gens = [g2] + self.gens[1:]
            return PENDING, rest
        p = Phases()
        p.gens = [g2] + self.gens[1:]
        return res, p

    def update(self, ctx, event):
        if not self.gens:
            return self
        p = Phases()
        p.gens = [self.gens[0].update(ctx, event)] + self.gens[1:]
        return p

    def next_interesting_time(self, ctx) -> float:
        # Advancement past an exhausted phase requires quiescence (a
        # completion event), so the current phase alone bounds the time.
        if not self.gens:
            return math.inf
        return self.gens[0].next_interesting_time(ctx)


class OnProcesses(Gen):
    """Restricts a generator to a subset of processes. The basis for
    gen/clients (client processes only) and gen/nemesis (the nemesis
    process), reference `core.clj:60,67,70`."""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = to_gen(gen)

    def op(self, ctx):
        sub = dict(ctx)
        sub["free"] = [p for p in ctx["free"] if self.pred(p)]
        sub["processes"] = [p for p in ctx["processes"] if self.pred(p)]
        if not sub["processes"]:
            return None, self
        res, g2 = self.gen.op(sub)
        return res, OnProcesses(self.pred, g2)

    def update(self, ctx, event):
        return OnProcesses(self.pred, self.gen.update(ctx, event))

    def next_interesting_time(self, ctx) -> float:
        return self.gen.next_interesting_time(ctx)


class _NotNemesis:
    """Picklable predicate: client processes only. Generator trees must
    stay picklable end-to-end so runs can checkpoint/resume."""

    def __call__(self, p):
        return p != NEMESIS


class _IsNemesis:
    def __call__(self, p):
        return p == NEMESIS


def clients(gen):
    return OnProcesses(_NotNemesis(), gen)


def nemesis_gen(gen):
    g = OnProcesses(_IsNemesis(), gen)
    return g


class Any2(Gen):
    """Interleaves two generators: each request tries both, preferring
    whichever has an op ready (used to run nemesis alongside clients,
    like jepsen's `gen/nemesis` wrapping in `core.clj:60-61`)."""

    def __init__(self, a, b):
        self.a = to_gen(a)
        self.b = to_gen(b)

    def op(self, ctx):
        res_a, a2 = self.a.op(ctx) if self.a else (None, None)
        if res_a not in (None, PENDING):
            return res_a, Any2(a2, self.b)
        res_b, b2 = self.b.op(ctx) if self.b else (None, None)
        if res_b not in (None, PENDING):
            return res_b, Any2(a2 if self.a else None, b2)
        if res_a is None and res_b is None:
            return None, self
        return PENDING, Any2(a2 if self.a else None, b2 if self.b else None)

    def update(self, ctx, event):
        return Any2(self.a.update(ctx, event) if self.a else None,
                    self.b.update(ctx, event) if self.b else None)

    def next_interesting_time(self, ctx) -> float:
        return min(self.a.next_interesting_time(ctx) if self.a else math.inf,
                   self.b.next_interesting_time(ctx) if self.b else math.inf)


def nemesis_wrap(nemesis_g, client_g):
    """Clients run client_g; the nemesis process runs nemesis_g
    (jepsen gen/nemesis with two args)."""
    if nemesis_g is None:
        return clients(client_g)
    return Any2(nemesis_gen(nemesis_g), clients(client_g))


class Filter(Gen):
    """Keeps only ops matching pred (jepsen gen/filter; used by g-counter
    to drop negative deltas, reference `g_counter.clj:30-40`)."""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = to_gen(gen)

    def op(self, ctx):
        g = self.gen
        for _ in range(10000):
            res, g = g.op(ctx)
            if res is None or res == PENDING:
                return res, Filter(self.pred, g)
            if self.pred(res):
                return res, Filter(self.pred, g)
        raise RuntimeError("gen/filter: no matching op in 10000 tries")

    def update(self, ctx, event):
        return Filter(self.pred, self.gen.update(ctx, event))

    def next_interesting_time(self, ctx) -> float:
        return self.gen.next_interesting_time(ctx)


class FMap(Gen):
    """Transforms emitted ops with f (jepsen gen/map)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = to_gen(gen)

    def op(self, ctx):
        res, g2 = self.gen.op(ctx)
        if res is None or res == PENDING:
            return res, FMap(self.f, g2)
        return self.f(res), FMap(self.f, g2)

    def update(self, ctx, event):
        return FMap(self.f, self.gen.update(ctx, event))

    def next_interesting_time(self, ctx) -> float:
        return self.gen.next_interesting_time(ctx)


class MixG(Gen):
    """Random mixture of generators (clean implementation)."""

    def __init__(self, gens, rng: random.Random | None = None):
        self.gens = [to_gen(g) for g in gens]
        self.rng = rng or random.Random(0)

    def op(self, ctx):
        # Fruitless polls must be rng-neutral: the scan-ahead fast path
        # polls once per *dispatch* while the per-round path polls once per
        # *round*, and any draw consumed on a PENDING poll would make their
        # op streams diverge (breaking scan/per-round equivalence and
        # deterministic resume). Child successor states from fruitless
        # polls are already discarded below for the same reason.
        st = self.rng.getstate()
        live = list(range(len(self.gens)))
        pending = False
        while live:
            j = self.rng.randrange(len(live))
            i = live[j]
            res, g2 = self.gens[i].op(ctx)
            if res is None:
                live.pop(j)
                continue
            if res == PENDING:
                pending = True
                live.pop(j)
                continue
            gens2 = list(self.gens)
            gens2[i] = g2
            return res, MixG(gens2, self.rng)
        self.rng.setstate(st)
        return (PENDING if pending else None), self

    def next_interesting_time(self, ctx) -> float:
        return min((gen.next_interesting_time(ctx) for gen in self.gens),
                   default=math.inf)


def mix(gens, rng=None):
    return MixG(gens, rng)


def stagger(dt_seconds: float, gen, rng=None):
    return Stagger(dt_seconds * 1e9, gen, rng=rng)


def time_limit(seconds: float, gen):
    return TimeLimit(int(seconds * 1e9), gen)


def sleep(seconds: float):
    return Sleep(int(seconds * 1e9))


def each_thread(op_map: dict):
    return EachThread(op_map)


def phases(*gens):
    return Phases(*gens)


def sched_columns(rows, r0: int, q: int, n_nodes: int) -> dict:
    """Numpy-columnar encode of pre-scheduled injection rows
    (doc/perf.md "vectorized host driver").

    `rows` is the continuous loop's carry_sched list — tuples of
    ``(round, process, op, node_idx, t, a, b, c)`` from
    `schedule_ahead` + the runner's encode pass — and the result is the
    [Q] column set the sched-inject scan consumes: ``at`` (round offsets
    relative to `r0`, -1 on padding), ``valid``, and the wire fields
    ``src``/``dest``/``type``/``a``/``b``/``c``. One `np.asarray` per
    field replaces a per-row Python loop, and the fleet driver fills
    one row of its [fleet, Q] buffers per cluster from these columns —
    so the whole fleet's window rides ONE device transfer per field per
    wave instead of per-cluster jnp constructions."""
    import numpy as np
    m = len(rows)
    if m > q:
        raise ValueError(f"{m} scheduled rows exceed the {q}-row "
                         f"inject batch")
    at = np.full(q, -1, np.int32)
    valid = np.zeros(q, bool)
    src = np.zeros(q, np.int32)
    dest = np.zeros(q, np.int32)
    typ = np.zeros(q, np.int32)
    a = np.zeros(q, np.int32)
    b = np.zeros(q, np.int32)
    c = np.zeros(q, np.int32)
    if m:
        cols = np.asarray([(rw[0], rw[1], rw[3], rw[4], rw[5], rw[6],
                            rw[7]) for rw in rows], np.int64).T
        at[:m] = cols[0] - r0
        valid[:m] = True
        src[:m] = cols[1] + n_nodes
        dest[:m] = cols[2]
        typ[:m] = cols[3]
        a[:m] = cols[4]
        b[:m] = cols[5]
        c[:m] = cols[6]
    return {"at": at, "valid": valid, "src": src, "dest": dest,
            "type": typ, "a": a, "b": b, "c": c}


def schedule_ahead(gen, processes, free, r0: int, horizon_r: int,
                   ns_per_round: float, dispatch_count: int):
    """Continuous-mode pre-scheduler (doc/streams.md): polls `gen`
    forward through VIRTUAL time — no simulation rounds execute — and
    collects the client ops it emits, each stamped with the round it is
    due, so one compiled scan can inject them at their exact offered-rate
    rounds inside the window [r0, horizon_r).

    Time advances along the generator's own `next_interesting_time`
    contract: a PENDING answer with a finite next time jumps the virtual
    clock there (the same bound the round-synchronous scan path stops
    at, so an op lands on the identical round either way); PENDING with
    +inf means only a completion event can unblock the generator — the
    window ends there ("starved"). Emitted client ops RESERVE their
    worker for the rest of the window (the host can't see mid-window
    completions), which bounds the events list by len(free).

    A NEMESIS op is a window boundary: its fault surgery is host-side
    state the scan cannot apply mid-flight. One emitted at r0 before any
    client op is returned for immediate execution (end == r0, no
    events); one emitted later ends the window at its round and is
    carried to the caller. Generators are advanced functionally but may
    share mutable RNGs between successor states, so a drawn op is never
    "un-polled" — the caller must execute or carry everything returned.

    Returns (gen', events, nem, end_r, end_kind) where events is
    [(round, op), ...] in nondecreasing round order, nem is (round, op)
    or None, end_r the exclusive window bound, and end_kind one of
    "horizon" | "starved" | "exhausted" | "nemesis"."""
    free = set(free)
    events: list = []
    r_v = r0
    while True:
        ctx = {"time": int(r_v * ns_per_round),
               "free": rotate_free(free, dispatch_count),
               "processes": list(processes)}
        res, gen = gen.op(ctx)
        if res is None:
            # exhausted forever (the Gen contract): the window may still
            # run to the horizon to drain in-flight ops
            return gen, events, None, horizon_r, "exhausted"
        if res == PENDING:
            nt = gen.next_interesting_time(ctx)
            if nt == math.inf:
                return gen, events, None, horizon_r, "starved"
            nr = int(math.ceil(nt / ns_per_round))
            if nr <= r_v:
                nr = r_v + 1        # same one-round floor as _scan_bound
            if nr >= horizon_r:
                return gen, events, None, horizon_r, "horizon"
            r_v = nr
            continue
        if res["process"] == NEMESIS:
            return gen, events, (r_v, res), max(r_v, r0), "nemesis"
        free.discard(res["process"])
        dispatch_count += 1
        events.append((r_v, res))
