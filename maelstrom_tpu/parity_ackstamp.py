"""Measure the ack-stamp lag instead of fitting it (VERDICT r3 item 7).

The one surviving deviation in `doc/parity.md` is grid-25 @ 10 ms,
where this framework's stable-latency p50 undershoots the reference's
published number by ~7.5 ms. `parity_analysis.py` showed a single
shared 7.5-8.5 ms shift aligns all 16 quantile comparisons and
attributed it to *ack-stamp lag*: the checker's "known time" for a
value is the broadcast_ok **completion stamp recorded by the client
harness**, which trails the instant the server actually held the value
(request transit + handler scheduling + reply transit + history
stamping under 25 concurrent handler threads at rate 100).

That story was a fit. This experiment measures it: run the real host
path — 25 node processes, 25 concurrent client workers, rate 100,
10 ms hop latency, the reference's grid-25 parity config — with the
broadcast node stamping the monotonic instant it first holds each value
(`demo/python/broadcast.py` BCAST_STAMP). Both clocks are
CLOCK_MONOTONIC on one box; the store's `t0_monotonic_ns` aligns the
node stamps with the history's relative timeline. For every
client-acked broadcast:

    lag = t(broadcast_ok in history) - t(acking node first held value)

The distribution's center is the measured ack-stamp offset; doc/parity.md
cites it against the fitted 7.5-8.5 ms band.

Usage:
    python -m maelstrom_tpu.parity_ackstamp [--rate 100] [--nodes 25]
        [--time-limit 8] [--out artifacts/ackstamp_lag.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys


def run_instrumented(nodes: int, rate: float, time_limit: float,
                     latency_ms: float, repo_root: str) -> str:
    """Runs the host-path broadcast test with HADVAL stamping on; returns
    the store directory of the completed run."""
    env = dict(os.environ, BCAST_STAMP="1", JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "maelstrom_tpu", "test",
           "--workload", "broadcast",
           "--bin", "demo/python/broadcast.py",
           "--node-count", str(nodes),
           "--concurrency", str(nodes),
           "--rate", str(rate),
           "--time-limit", str(time_limit),
           "--latency", str(latency_ms),
           "--topology", "grid"]
    r = subprocess.run(cmd, cwd=repo_root, env=env,
                       capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        raise RuntimeError(f"instrumented run failed:\n{r.stderr[-2000:]}")
    m = re.search(r"store: ([^\s)]+)", r.stderr + r.stdout)
    if not m:
        raise RuntimeError("no store dir in run output")
    return os.path.join(repo_root, m.group(1))


def analyze(store_dir: str) -> dict:
    with open(os.path.join(store_dir, "test.json")) as f:
        test = json.load(f)
    t0 = int(test["t0_monotonic_ns"])
    node_names = test["nodes"]
    n_nodes = len(node_names)

    # node stamps: value -> {node: monotonic_ns of first holding}
    hadval: dict = {}
    logdir = os.path.join(store_dir, "node-logs")
    for fn in os.listdir(logdir):
        node = fn.rsplit(".", 1)[0]
        with open(os.path.join(logdir, fn)) as f:
            for line in f:
                m = re.search(r"HADVAL (\S+) (\d+)", line)
                if m:
                    hadval.setdefault(m.group(1), {})[node] = \
                        int(m.group(2)) - t0

    # history: completed broadcasts -> (value, ack time, acking node)
    lags = []
    with open(os.path.join(store_dir, "history.jsonl")) as f:
        ops = [json.loads(line) for line in f if line.strip()]
    invokes = {}
    for o in ops:
        if o["f"] != "broadcast":
            continue
        if o["type"] == "invoke":
            invokes[o["process"]] = o
        elif o["type"] == "ok":
            inv = invokes.get(o["process"])
            if inv is None:
                continue
            # worker i drives nodes[i % n] (host_runner worker mapping)
            node = node_names[o["process"] % n_nodes]
            stamp = hadval.get(str(inv["value"]), {}).get(node)
            if stamp is not None:
                lags.append((o["time"] - stamp) / 1e6)   # ms

    lags.sort()
    n = len(lags)
    if n < 30:
        raise RuntimeError(f"only {n} matched acks — run longer")

    def q(p):
        return round(lags[min(n - 1, int(p * n))], 3)
    return {
        "matched_acks": n,
        "lag_ms": {"p10": q(.10), "p25": q(.25), "p50": q(.50),
                   "p75": q(.75), "p90": q(.90), "p99": q(.99),
                   "mean": round(sum(lags) / n, 3),
                   "min": round(lags[0], 3), "max": round(lags[-1], 3)},
        "fitted_shift_band_ms": [7.5, 8.5],
        "store": store_dir,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=25)
    ap.add_argument("--rate", type=float, default=100.0)
    ap.add_argument("--time-limit", type=float, default=8.0)
    ap.add_argument("--latency", type=float, default=10.0)
    ap.add_argument("--out", default="artifacts/ackstamp_lag.json")
    ap.add_argument("--store", default=None,
                    help="analyze an existing store dir instead of running")
    args = ap.parse_args(argv)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    store_dir = args.store or run_instrumented(
        args.nodes, args.rate, args.time_limit, args.latency, repo_root)
    report = analyze(store_dir)
    report["config"] = {"nodes": args.nodes, "rate": args.rate,
                        "time_limit": args.time_limit,
                        "latency_ms": args.latency}
    out = os.path.join(repo_root, args.out)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report["lag_ms"]))
    print(f"wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
