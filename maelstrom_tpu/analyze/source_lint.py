"""AST lint of the hot HOST modules for Python-level nondeterminism.

The jaxpr audit covers the compiled step; this pass covers the host
code wrapped around it — the paths that must replay byte-identically
across checkpoint/resume and scan-vs-run equivalence:

  - `np-unstable-sort`: module-form `np.argsort`/`np.sort` without
    ``kind="stable"`` — numpy defaults to introsort, so equal keys land
    in arbitrary order. Method-form sorts are deliberately exempt: jax
    arrays' method sorts are stable by default (and device sorts are
    the jaxpr pass's job), `list.sort` is stable.
  - `set-iteration`: a `for` loop or comprehension iterating directly
    over a set literal / `set(...)` / set comprehension (hash-seed
    dependent order) without a `sorted(...)` wrapper.
  - `wall-clock`: `time.time()`/`time.time_ns()`/`datetime.now()` —
    replayed paths must read virtual time (`perf_counter`/`monotonic`
    stay legal: they only ever feed duration accounting).
  - `unseeded-random`: module-level `random.<draw>()` calls — the
    process-global RNG is unseeded; deterministic paths draw from
    seeded `random.Random` instances.

Pure stdlib (`ast`), no imports of the linted modules.
"""

from __future__ import annotations

import ast
import functools
import os

from . import Finding

# The replay-critical host modules (relative to the package directory):
# the runner loop, both network paths, the sim composition, nemesis
# scheduling, and the history/analysis pairing + screening paths —
# plus the two threaded-worker modules (checkpoint writer, telemetry
# session) the `thread-shared-mutation` rule covers.
DEFAULT_LINT_PATHS = (
    "runner", "net", "sim.py", "nemesis.py", "history.py",
    "checkers/pipeline.py", "checkers/linearizable.py",
    "checkers/elle.py", "checkers/elle_device.py",
    "checkpoint.py", "telemetry.py",
)

# Classes that pair worker threads with main-thread readers: the
# `thread-shared-mutation` rule analyzes exactly these (a generic
# heuristic over every class would drown the gate in false positives).
THREAD_CLASSES = ("AnalysisPool", "AnalysisPipeline",
                  "CheckpointWriter", "TelemetrySession")

_RANDOM_DRAWS = {"random", "randint", "randrange", "choice", "choices",
                 "shuffle", "sample", "uniform", "gauss", "betavariate",
                 "expovariate", "getrandbits", "triangular"}
_WALL_CLOCK = {("time", "time"), ("time", "time_ns"),
               ("datetime", "now"), ("datetime", "utcnow")}


def _is_name(node, name: str) -> bool:
    return isinstance(node, ast.Name) and node.id == name


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str, source_lines):
        self.relpath = relpath
        self.lines = source_lines
        self.findings: list[Finding] = []
        self._func_stack: list[str] = []

    # --- helpers ---

    def _func(self) -> str:
        return self._func_stack[-1] if self._func_stack else "<module>"

    def _add(self, rule: str, node, detail: str):
        line = getattr(node, "lineno", 0)
        excerpt = ""
        if 0 < line <= len(self.lines):
            excerpt = self.lines[line - 1].strip()[:80]
        self.findings.append(Finding(
            rule=rule, entry="source-lint",
            where=f"{self.relpath}:{line} ({self._func()})",
            key=f"{self.relpath}:{self._func()}",
            detail=detail or excerpt))

    def _visit_func(self, node):
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # --- rules ---

    def _check_iterable(self, it):
        """Direct iteration over an unordered set."""
        if isinstance(it, (ast.Set, ast.SetComp)):
            self._add("set-iteration", it,
                      "iterating a set literal/comprehension")
        elif isinstance(it, ast.Call) and (
                _is_name(it.func, "set") or _is_name(it.func, "frozenset")):
            self._add("set-iteration", it,
                      f"iterating {it.func.id}(...) directly")

    def visit_For(self, node):
        self._check_iterable(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        self._check_iterable(node.iter)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            kind = next((kw for kw in node.keywords if kw.arg == "kind"),
                        None)
            stable = (kind is not None
                      and isinstance(kind.value, ast.Constant)
                      and kind.value.value == "stable")
            if f.attr in ("argsort", "sort") and (
                    _is_name(f.value, "np") or _is_name(f.value, "numpy")):
                # module-form only: `x.argsort()` method calls are NOT
                # flagged — jax arrays' method sorts are stable by
                # default (device sorts are the jaxpr pass's job) and
                # list.sort is stable, so a generic method rule would
                # produce false errors on legitimate code
                if not stable:
                    self._add("np-unstable-sort", node,
                              f"np.{f.attr} without kind=\"stable\"")
            elif isinstance(f.value, ast.Name) and \
                    (f.value.id, f.attr) in _WALL_CLOCK:
                self._add("wall-clock", node, f"{f.value.id}.{f.attr}()")
            elif _is_name(f.value, "random") and f.attr in _RANDOM_DRAWS:
                self._add("unseeded-random", node, f"random.{f.attr}()")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# thread-shared-mutation: unguarded assignment to an attribute that a
# worker thread of the same class also reads.
#
# Worker bodies are found structurally: methods passed as
# `Thread(target=self.m)` / `pool.submit(self.m)`, nested functions
# passed as `Thread(target=fn)`, plus the transitive closure over
# `self.m()` calls from those roots. "Shared" = attributes those
# bodies READ (`self.x` loads and augmented assigns; method names
# excluded). A mutation (`self.x = ...` / `self.x += ...`, tuple
# targets included) anywhere in the class outside `__init__` and
# outside a `with self.<...lock...>:` block is flagged. Deliberately
# exempt (documented in doc/analyze.md): mutating METHOD calls
# (`.append`/`.clear`) and subscript stores (`self.d[k] = v`) — both
# are container-internal updates whose safety depends on the container,
# not on attribute rebinding, and flagging them would bury the gate.
# ---------------------------------------------------------------------------

def _is_self_attr(node) -> bool:
    return isinstance(node, ast.Attribute) and _is_name(node.value, "self")


def _lint_thread_class(cls, relpath: str) -> list[Finding]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    nested: dict[tuple, ast.FunctionDef] = {}
    for mname, m in methods.items():
        for sub in ast.walk(m):
            if isinstance(sub, ast.FunctionDef) and sub is not m:
                nested[(mname, sub.name)] = sub

    workers: list = []
    seen: set[int] = set()

    def add_worker(node):
        if id(node) not in seen:
            seen.add(id(node))
            workers.append(node)

    for mname, m in methods.items():
        for call in ast.walk(m):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            callee = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if callee == "Thread":
                for kw in call.keywords:
                    if kw.arg != "target":
                        continue
                    v = kw.value
                    if _is_self_attr(v) and v.attr in methods:
                        add_worker(methods[v.attr])
                    elif isinstance(v, ast.Name) and \
                            (mname, v.id) in nested:
                        add_worker(nested[(mname, v.id)])
            elif callee == "submit" and call.args:
                v = call.args[0]
                if _is_self_attr(v) and v.attr in methods:
                    add_worker(methods[v.attr])

    changed = True
    while changed:                      # closure over self.m() calls
        changed = False
        for node in list(workers):
            for call in ast.walk(node):
                if isinstance(call, ast.Call) and \
                        _is_self_attr(call.func) and \
                        call.func.attr in methods and \
                        id(methods[call.func.attr]) not in seen:
                    add_worker(methods[call.func.attr])
                    changed = True
    if not workers:
        return []

    shared: set[str] = set()
    for node in workers:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and \
                    _is_name(sub.value, "self") and \
                    isinstance(sub.ctx, ast.Load) and \
                    sub.attr not in methods:
                shared.add(sub.attr)
            elif isinstance(sub, ast.AugAssign) and \
                    _is_self_attr(sub.target):
                shared.add(sub.target.attr)

    findings: list[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[str] = []
            self.lock = 0

        def _visit_func(self, node):
            if not self.stack and node.name == "__init__":
                return              # construction precedes the threads
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_With(self, node):
            locked = any(
                _is_self_attr(item.context_expr)
                and "lock" in item.context_expr.attr.lower()
                for item in node.items)
            self.lock += locked
            self.generic_visit(node)
            self.lock -= locked

        def _attr_targets(self, t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from self._attr_targets(e)
            elif isinstance(t, ast.Attribute):
                yield t

        def _flag(self, a):
            if self.lock or not self.stack:
                return
            if _is_name(a.value, "self") and a.attr in shared:
                func = f"{cls.name}.{self.stack[-1]}"
                findings.append(Finding(
                    rule="thread-shared-mutation", entry="source-lint",
                    where=f"{relpath}:{a.lineno} ({func})",
                    key=f"{relpath}:{func}",
                    detail=f"self.{a.attr} assigned outside a lock; "
                           f"worker threads of {cls.name} read it"))

        def visit_Assign(self, node):
            for t in node.targets:
                for a in self._attr_targets(t):
                    self._flag(a)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            for a in self._attr_targets(node.target):
                self._flag(a)
            self.generic_visit(node)

    V().visit(cls)
    return findings


def lint_thread_shared(tree, relpath: str) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in THREAD_CLASSES:
            out += _lint_thread_class(node, relpath)
    return out


def lint_source(source: str, relpath: str) -> list[Finding]:
    tree = ast.parse(source, filename=relpath)
    v = _Visitor(relpath, source.splitlines())
    v.visit(tree)
    return v.findings + lint_thread_shared(tree, relpath)


def lint_file(path: str, relpath: str | None = None) -> list[Finding]:
    with open(path) as f:
        source = f.read()
    return lint_source(source, relpath or path)


def lint_paths(paths, package_dir: str | None = None) -> list[Finding]:
    """Lints files/directories given relative to the package dir (or
    absolute). Directories recurse over ``*.py``."""
    if package_dir is None:                 # analyze/ -> maelstrom_tpu/
        package_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    pkg_parent = os.path.dirname(package_dir)
    findings: list[Finding] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(package_dir, p)
        if os.path.isdir(full):
            files = sorted(
                os.path.join(r, fn)
                for r, _dirs, fns in os.walk(full)
                for fn in fns if fn.endswith(".py"))
        else:
            files = [full]
        for fpath in files:
            rel = os.path.relpath(fpath, pkg_parent)
            findings += lint_file(fpath, rel)
    return findings


@functools.lru_cache(maxsize=1)
def _lint_default_cached() -> tuple:
    return tuple(lint_paths(DEFAULT_LINT_PATHS))


def lint_default_paths() -> list[Finding]:
    """Lint of the shipped hot modules. Cached for the process lifetime
    — the sources cannot change under a running process, and the
    self-report block would otherwise re-parse ~20 modules per run
    config (callers only read; `dedupe_sites` copies before any
    mutation)."""
    return list(_lint_default_cached())
