"""`python -m maelstrom_tpu.analyze` — the standalone CI gate.

Identical to the `analyze` subcommand of `python -m maelstrom_tpu`;
this module exists so CI scripts can run the gate without the full CLI
(`scripts/check.sh` wires it next to ruff). Exit codes: 0 = clean,
1 = new (non-baselined) findings, 2 = usage/config error.
"""

from __future__ import annotations

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
