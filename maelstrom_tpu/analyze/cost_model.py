"""Static roofline cost model over the production jaxprs.

The jaxpr audit (`jaxpr_audit.py`) proves the compiled hot loop is
*hazard-free*; this module prices it. The same abstract traces (nothing
is compiled or executed) are walked again, booking per equation:

  - FLOPs (a documented per-primitive table: 2·M·N·K for dot_general,
    n·log2(n) comparator passes for sort, element counts for the rest),
  - HBM bytes read/written (operand/result aval bytes — a fusion-free
    upper bound; the device profile's effective bandwidth absorbs the
    constant factor),
  - collective bytes per mesh axis: explicit collectives inside
    `shard_map` manual regions (psum/all_gather/reduce_scatter/
    ppermute/all_to_all, standard ring-cost factors), plus a documented
    GSPMD heuristic charging partial-reshard traffic for sort/scatter/
    gather reached under >1-size visible mesh axes.

Loop handling mirrors the audit's recursion: `scan` bodies multiply by
the static `length`, `while` bodies are booked ONCE (trip counts are
data-dependent; the production scan drives rounds through a
`lax.while_loop`, so a scan-entry total reads as ~one round plus the
dispatch prologue/epilogue), `cond` books every branch (upper bound),
`pjit`/custom-call sub-jaxprs recurse transparently.

The roll-up per entry is a cost *record*: arithmetic intensity, scan
carry bytes, peak live-buffer bytes (last-use liveness scan, donation
credited — a donated carry aliases its output and counts once), and a
predicted round rate on a declared `DeviceProfile`
(`overhead + max(compute, memory, ICI, DCN)` — roofline, not additive).
Predicted msgs/s scales the round rate by the config's per-round
message capacity bound (`min(pool_cap, n·inbox_cap + client_cap)`), an
upper bound; bench stamping substitutes each record's own measured
msgs/round for the ratio (the model predicts the ROUND RATE; message
density is workload semantics).

Four gateable rules ride on the model (registered in `analyze.RULES`):
`collective-on-dp`, `carry-growth`, `hbm-overflow`, and
`intensity-regression` against the checked-in
`analyze/cost_baseline.json`. See doc/analyze.md for the catalog, the
profile format, and the baseline workflow.
"""

from __future__ import annotations

import json
import math
import os
import time
from dataclasses import dataclass, field

from . import Finding
from .jaxpr_audit import StepSpec, _iter_subjaxprs, _mesh_axis_sizes, _site

__all__ = [
    "DeviceProfile", "PROFILES", "resolve_profile", "default_profile",
    "cost_jaxpr", "cost_step", "predict", "predict_round",
    "cost_production", "cost_findings", "CostReport",
    "cost_baseline_path", "load_cost_baseline", "write_cost_baseline",
    "DEFAULT_CARRY_BUDGET", "STRETCH_ROUNDS",
]

# Rounds in one scan stretch for the per-stretch roll-up (matches the
# k=8 example the audit traces the scan entries with).
STRETCH_ROUNDS = 8

# Per-entry scan-carry budget when cost_baseline.json declares none.
DEFAULT_CARRY_BUDGET = 64 * 1024 * 1024


# ---------------------------------------------------------------------------
# Device profiles
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DeviceProfile:
    """Declared (not measured) peak numbers for one device class.

    `hbm_bw` is an EFFECTIVE bandwidth: the model books fusion-free
    aval bytes, so the profile's bandwidth is calibrated against real
    round rates (doc/analyze.md records the calibration band) rather
    than copied from a spec sheet. `dispatch_overhead_s` is the fixed
    per-round host+launch cost that dominates small configs."""
    name: str
    peak_flops: float           # FLOP/s
    hbm_bw: float               # bytes/s, effective
    ici_bw: float               # bytes/s per device (sp-axis links)
    dcn_bw: float               # bytes/s per host (dp-axis links)
    hbm_bytes: float            # per-device memory capacity
    dispatch_overhead_s: float  # fixed per-round overhead


PROFILES: dict[str, DeviceProfile] = {
    # The 2-core CPU dev box, CALIBRATED against the committed r01
    # bench artifacts (doc/analyze.md "predicted vs measured"). The
    # numbers are far above physical DRAM/scalar rates on purpose: the
    # model books fusion-free aval bytes and per-element logical ops,
    # and XLA:CPU fuses the mask-heavy round bodies ~100x (SIMD bool
    # lanes, fused elementwise chains), so the EFFECTIVE bandwidth/peak
    # absorb that constant. The per-round dispatch+Python overhead
    # (milliseconds) dominates small configs.
    "cpu": DeviceProfile("cpu", peak_flops=1.0e11, hbm_bw=1.6e10,
                         ici_bw=2.0e9, dcn_bw=1.0e9,
                         hbm_bytes=8.0 * 2**30,
                         dispatch_overhead_s=6.0e-3),
    # TPU v4 (public spec: 275 TFLOP/s bf16, 1.2 TB/s HBM, 32 GiB,
    # ~300 GB/s aggregate ICI per chip, DCN O(25 GB/s) per host).
    # int32-heavy round bodies see a fraction of bf16 peak; declared.
    "tpu-v4": DeviceProfile("tpu-v4", peak_flops=275.0e12, hbm_bw=1.2e12,
                            ici_bw=300.0e9, dcn_bw=25.0e9,
                            hbm_bytes=32.0 * 2**30,
                            dispatch_overhead_s=5.0e-6),
    # TPU v5e (public spec: 197 TFLOP/s bf16, 819 GB/s HBM, 16 GiB,
    # ~200 GB/s ICI).
    "tpu-v5e": DeviceProfile("tpu-v5e", peak_flops=197.0e12,
                             hbm_bw=819.0e9, ici_bw=200.0e9,
                             dcn_bw=25.0e9, hbm_bytes=16.0 * 2**30,
                             dispatch_overhead_s=5.0e-6),
}


def default_profile() -> str:
    """MAELSTROM_COST_PROFILE env override, else by visible backend."""
    env = os.environ.get("MAELSTROM_COST_PROFILE")
    if env:
        return env
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "tpu-v4" if backend == "tpu" else "cpu"


def resolve_profile(profile=None) -> DeviceProfile:
    if isinstance(profile, DeviceProfile):
        return profile
    name = profile or default_profile()
    if name not in PROFILES:
        raise ValueError(f"unknown device profile {name!r}; expected one "
                         f"of {sorted(PROFILES)}")
    return PROFILES[name]


# ---------------------------------------------------------------------------
# Per-equation booking tables
# ---------------------------------------------------------------------------

# Pure data movement / metadata: 0 FLOPs, bytes only.
_ZERO_FLOP_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "gather", "scatter", "iota", "copy", "convert_element_type",
    "bitcast_convert_type", "stop_gradient", "device_put",
    "sharding_constraint", "split", "expand_dims",
})

# Explicit collectives (shard_map manual regions / GSPMD-visible axis
# primitives). Wire-byte factors are the standard ring costs.
_COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pbroadcast", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "ppermute",
})

# GSPMD resharding heuristic: primitives whose sharded lowering
# typically moves operand data across >1-size visible mesh axes
# (partitioned sorts merge across shards; scatter/gather may target
# remote shards). Booked as (s-1)/s of operand bytes per axis — a
# declared estimate, never a `collective-on-dp` trigger.
_GSPMD_RESHARD_PRIMS = frozenset({
    "sort", "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max", "gather", "dynamic_update_slice",
})


def _aval_bytes(v) -> int:
    import numpy as np
    aval = getattr(v, "aval", None)
    try:
        return int(aval.size) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _elems(v) -> int:
    aval = getattr(v, "aval", None)
    try:
        return int(aval.size)
    except Exception:
        return 0


def _flops(eqn, p: str) -> int:
    """Documented per-primitive FLOP table (doc/analyze.md). Counts are
    per logical element; the profile's peak absorbs the constant."""
    if p in _ZERO_FLOP_PRIMS:
        return 0
    out_elems = sum(_elems(v) for v in eqn.outvars)
    in_elems = sum(_elems(v) for v in eqn.invars)
    if p == "dot_general":
        (lc, _rc), _batch = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        k = 1
        for ax in lc:
            k *= int(lhs.shape[ax])
        return 2 * out_elems * max(k, 1)
    if p == "sort":
        dim = eqn.params.get("dimension", -1)
        shape = getattr(eqn.invars[0].aval, "shape", ())
        n = int(shape[dim]) if shape else 1
        return in_elems * max(1, math.ceil(math.log2(max(n, 2))))
    if p.startswith("reduce_") or p in ("argmax", "argmin"):
        return in_elems
    if p.startswith("cum"):
        return 2 * in_elems
    if p.startswith("scatter-"):
        return _elems(eqn.invars[-1])        # one combine per update elem
    if p == "integer_pow":
        return 2 * out_elems
    return out_elems                         # elementwise default


def _collective_axis_names(eqn) -> tuple:
    ax = eqn.params.get("axes")
    if ax is None:
        ax = eqn.params.get("axis_name")
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _wire_bytes(p: str, in_b: int, s: int) -> int:
    """Per-device wire bytes for one collective over a group of size s
    (ring algorithms): all-reduce moves 2(s-1)/s of the data, gather
    (s-1)x the shard, scatter/all-to-all (s-1)/s, permute 1x."""
    if p in ("psum", "pmax", "pmin"):
        return 2 * in_b * (s - 1) // s
    if p == "all_gather":
        return in_b * (s - 1)
    if p in ("reduce_scatter", "psum_scatter", "all_to_all"):
        return in_b * (s - 1) // s
    return in_b                              # ppermute / pbroadcast


# ---------------------------------------------------------------------------
# The recursive walker
# ---------------------------------------------------------------------------

class _Acc:
    """Booked totals for one entry trace."""

    def __init__(self):
        self.flops = 0
        self.bytes_read = 0
        self.bytes_written = 0
        self.collective: dict[str, int] = {}
        self.carry_bytes = 0
        self.carry_site = ""
        self.dp_sites: list[dict] = []

    def note_carry(self, b: int, eqn) -> None:
        if b > self.carry_bytes:
            self.carry_bytes = b
            self.carry_site, _ = _site(eqn)

    def note_dp(self, eqn, p: str, wire: int) -> None:
        where, key = _site(eqn)
        self.dp_sites.append({
            "where": where, "key": key,
            "detail": f"{p} crosses the dp/DCN axis ({wire} wire B per "
                      f"round-body execution)"})


def _book_leaf(eqn, p, in_b, out_b, coll_axes, gspmd_axes, mult, acc):
    acc.flops += _flops(eqn, p) * mult
    acc.bytes_read += in_b * mult
    acc.bytes_written += out_b * mult
    if p in _COLLECTIVE_PRIMS:
        names = _collective_axis_names(eqn)
        sizes = {a: int(coll_axes.get(a, 1)) for a in names}
        group = 1
        for v in sizes.values():
            group *= max(v, 1)
        if group > 1:
            wire = _wire_bytes(p, in_b, group)
            for a, sz in sizes.items():
                if sz > 1:
                    acc.collective[a] = acc.collective.get(a, 0) \
                        + wire * mult
            if sizes.get("dp", 1) > 1:
                acc.note_dp(eqn, p, wire)
    elif p in _GSPMD_RESHARD_PRIMS:
        for a, sz in gspmd_axes.items():
            if sz > 1:
                acc.collective[a] = acc.collective.get(a, 0) \
                    + (in_b * (sz - 1) // sz) * mult


def _walk(jx, coll_axes, gspmd_axes, mult, acc) -> int:
    """Books every equation of `jx` (times `mult`) into `acc` and
    returns the jaxpr's peak live bytes (last-use liveness scan; an
    equation with sub-jaxprs contributes its sub-peak minus the operand
    bytes already counted live)."""
    from jax.core import Literal
    last: dict = {}
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal):
                last[v] = i
    for v in jx.outvars:
        if not isinstance(v, Literal):
            last[v] = len(jx.eqns)
    live = sum(_aval_bytes(v)
               for v in list(jx.invars) + list(jx.constvars))
    peak = live
    for i, eqn in enumerate(jx.eqns):
        p = eqn.primitive.name
        in_b = sum(_aval_bytes(v) for v in eqn.invars)
        out_b = sum(_aval_bytes(v) for v in eqn.outvars)
        subs = list(_iter_subjaxprs(eqn.params))
        sub_peak = 0
        if not subs:
            _book_leaf(eqn, p, in_b, out_b, coll_axes, gspmd_axes, mult,
                       acc)
        elif p == "scan":
            length = int(eqn.params.get("length") or 1)
            nc = int(eqn.params.get("num_consts") or 0)
            nk = int(eqn.params.get("num_carry") or 0)
            acc.note_carry(
                sum(_aval_bytes(v) for v in eqn.invars[nc:nc + nk]), eqn)
            for sub in subs:
                sub_peak = max(sub_peak, _walk(sub, coll_axes, gspmd_axes,
                                               mult * length, acc))
        elif p == "while":
            # trip count is data-dependent: body booked ONCE. The
            # production scan entries drive rounds through a
            # lax.while_loop, so their totals read as ~one round.
            bn = int(eqn.params.get("body_nconsts") or 0)
            body = eqn.params.get("body_jaxpr")
            bj = getattr(body, "jaxpr", body)
            if bj is not None:
                acc.note_carry(
                    sum(_aval_bytes(v) for v in list(bj.invars)[bn:]),
                    eqn)
            for sub in subs:
                sub_peak = max(sub_peak, _walk(sub, coll_axes, gspmd_axes,
                                               mult, acc))
        elif p == "shard_map":
            # inside the manual region the mesh axes become explicit
            # collective axis names; GSPMD only sees the `auto` subset
            m = eqn.params.get("mesh")
            auto = eqn.params.get("auto") or frozenset()
            mesh_shape = dict(getattr(m, "shape", {}) or {})
            sub_gspmd = {k: v for k, v in mesh_shape.items() if k in auto}
            for sub in subs:
                sub_peak = max(sub_peak, _walk(sub, mesh_shape, sub_gspmd,
                                               mult, acc))
        else:
            # pjit / cond / custom_* / remat: recurse transparently.
            # cond books EVERY branch — a deterministic upper bound.
            for sub in subs:
                sub_peak = max(sub_peak, _walk(sub, coll_axes, gspmd_axes,
                                               mult, acc))
        transient = sub_peak - in_b if sub_peak > in_b else 0
        cand = live + out_b + transient
        if cand > peak:
            peak = cand
        live += out_b
        for v in set(eqn.outvars):
            if v not in last:               # result never used: dies here
                live -= _aval_bytes(v)
        for v in {v for v in eqn.invars
                  if not isinstance(v, Literal) and last.get(v) == i}:
            live -= _aval_bytes(v)
    return peak


def cost_jaxpr(closed, mesh_axes: dict | None = None):
    """Walks one ClosedJaxpr; returns (acc, peak_bytes, donated_bytes).
    Donation credit reads the REAL `donated_invars` off a single-pjit
    trace (the shape every jitted entry point produces)."""
    from jax.core import Literal
    axes = dict(mesh_axes or {})
    acc = _Acc()
    peak = _walk(closed.jaxpr, axes, axes, 1, acc)
    donated = 0
    eqns = closed.jaxpr.eqns
    if len(eqns) == 1 and eqns[0].primitive.name == "pjit":
        don = eqns[0].params.get("donated_invars") or ()
        for flag, v in zip(don, eqns[0].invars):
            if flag and not isinstance(v, Literal):
                donated += _aval_bytes(v)
    return acc, peak, donated


# ---------------------------------------------------------------------------
# Entry records and predictions
# ---------------------------------------------------------------------------

def predict(record: dict, profile=None,
            rounds_per_dispatch: int = 1) -> dict:
    """Roofline prediction from a cost record's invariant totals:
    round_s = overhead + max(compute, memory, ICI, DCN). Returns a
    fresh dict; `record` is not mutated.

    `rounds_per_dispatch` amortizes the dispatch overhead for chunked
    scan drivers (the benches run `chunk` rounds per host dispatch);
    the production host loop pays it every round, the default."""
    prof = resolve_profile(profile)
    flops = record["flops"]
    hbm = record["hbm_bytes_read"] + record["hbm_bytes_written"]
    coll = record.get("collective_bytes") or {}
    ici_b = sum(b for a, b in coll.items() if a != "dp")
    dcn_b = coll.get("dp", 0)
    t = prof.dispatch_overhead_s / max(int(rounds_per_dispatch), 1) \
        + max(flops / prof.peak_flops, hbm / prof.hbm_bw,
              ici_b / prof.ici_bw, dcn_b / prof.dcn_bw)
    rps = 1.0 / t
    cap = record.get("msgs_per_round_cap")
    return {
        "profile": prof.name,
        "round_s": round(t, 9),
        "rounds_per_sec": round(rps, 3),
        "msgs_per_round_cap": cap,
        "msgs_per_sec": round(cap * rps, 3) if cap else None,
    }


def _msgs_per_round_cap(spec: StepSpec):
    """Static per-round message capacity bound from the spec's config:
    deliveries are capped by pool occupancy and per-node inbox + client
    lanes; a fleet multiplies by the cluster count. An upper bound —
    real message density is workload semantics."""
    cfg = (spec.meta or {}).get("cfg")
    if cfg is None:
        return None
    try:
        cap = min(int(cfg.pool_cap),
                  int(cfg.n_nodes) * int(cfg.inbox_cap)
                  + int(getattr(cfg, "client_cap", 0)))
    except Exception:
        return None
    fleet = (spec.meta or {}).get("fleet")
    return cap * int(fleet) if fleet else cap


def cost_step(spec: StepSpec, profile=None) -> dict:
    """Cost record for one auditable entry point (abstract trace only).
    Counts are exact integers of the model — goldens pin them
    tolerance-free."""
    import jax
    prof = resolve_profile(profile)
    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    acc, peak, donated = cost_jaxpr(
        closed, _mesh_axis_sizes(spec.in_shardings))
    hbm = acc.bytes_read + acc.bytes_written
    record = {
        "entry": spec.name,
        "flops": int(acc.flops),
        "hbm_bytes_read": int(acc.bytes_read),
        "hbm_bytes_written": int(acc.bytes_written),
        "collective_bytes": {k: int(v)
                             for k, v in sorted(acc.collective.items())},
        "arithmetic_intensity": round(acc.flops / max(hbm, 1), 6),
        "carry_bytes": int(acc.carry_bytes),
        "carry_site": acc.carry_site,
        "peak_bytes": int(peak),
        "donated_bytes": int(donated),
        "peak_bytes_donated": int(max(peak - donated, 0)),
        "msgs_per_round_cap": _msgs_per_round_cap(spec),
        "dp_collectives": list(acc.dp_sites),
        "stretch": {"rounds": STRETCH_ROUNDS,
                    "flops": int(acc.flops) * STRETCH_ROUNDS,
                    "hbm_bytes": int(hbm) * STRETCH_ROUNDS},
    }
    record["predicted"] = predict(record, prof)
    return record


def predict_round(program, cfg, *, fleet: int | None = None,
                  inject_width: int = 1, profile=None,
                  msgs_per_round: float | None = None,
                  rounds_per_dispatch: int = 1) -> dict:
    """Bench-facing prediction: traces the per-round step for an
    ALREADY-BUILT program/config at its real shape (state via
    `jax.eval_shape` — no arrays are materialized, so 100k-node bench
    shapes trace in milliseconds) and returns a cost record. With
    `msgs_per_round` (the record under comparison's own message
    density) `predicted.msgs_per_sec` uses it instead of the static
    capacity bound. `rounds_per_dispatch` amortizes dispatch overhead
    for chunked-scan benches (see `predict`)."""
    import jax

    from ..net import tpu as T
    from ..sim import make_round_fn, make_sim

    prof = resolve_profile(profile)
    ex = jax.eval_shape(lambda: make_sim(program, cfg, seed=0))
    inj = jax.eval_shape(lambda: T.Msgs.empty(max(int(inject_width), 1)))
    fn = make_round_fn(program, cfg, donate=False)
    if fleet:
        F = int(fleet)
        bcast = lambda s: jax.ShapeDtypeStruct((F,) + tuple(s.shape),
                                               s.dtype)
        ex = jax.tree.map(bcast, ex)
        inj = jax.tree.map(bcast, inj)
        fn = jax.vmap(fn)
    spec = StepSpec(name=f"predict[{type(program).__name__}"
                         f"{'@fleet=' + str(fleet) if fleet else ''}]",
                    fn=fn, args=(ex, inj),
                    meta={"cfg": cfg, "fleet": fleet})
    record = cost_step(spec, prof)
    if rounds_per_dispatch > 1:
        record["predicted"] = predict(
            record, prof, rounds_per_dispatch=rounds_per_dispatch)
    if msgs_per_round:
        rps = record["predicted"]["rounds_per_sec"]
        record["predicted"]["msgs_per_round"] = round(
            float(msgs_per_round), 3)
        record["predicted"]["msgs_per_sec"] = round(
            float(msgs_per_round) * rps, 3)
    return record


# ---------------------------------------------------------------------------
# Baseline + rules
# ---------------------------------------------------------------------------

def cost_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "cost_baseline.json")


def load_cost_baseline(path: str | None = None) -> dict:
    path = path or cost_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def write_cost_baseline(records: dict, path: str | None = None,
                        profile=None) -> str:
    """Regenerates cost_baseline.json from current records. Carry
    budgets and the tolerance are preserved across rewrites; entries
    are emitted in sorted order so regenerated baselines diff
    cleanly."""
    path = path or cost_baseline_path()
    prof = resolve_profile(profile)
    old = load_cost_baseline(path)
    entries = {}
    for name in sorted(records):
        rec = records[name]
        pred = predict(rec, prof)
        entries[name] = {
            "flops": rec["flops"],
            "hbm_bytes": rec["hbm_bytes_read"] + rec["hbm_bytes_written"],
            "collective_bytes": rec["collective_bytes"],
            "carry_bytes": rec["carry_bytes"],
            "peak_bytes_donated": rec["peak_bytes_donated"],
            "rounds_per_sec": pred["rounds_per_sec"],
            "msgs_per_sec": pred["msgs_per_sec"],
        }
    data = {
        "version": 1,
        "profile": prof.name,
        "tolerance_pct": float(old.get("tolerance_pct", 20.0)),
        "default_carry_budget_bytes": int(
            old.get("default_carry_budget_bytes", DEFAULT_CARRY_BUDGET)),
        "carry_budgets": dict(sorted(
            (old.get("carry_budgets") or {}).items())),
        "entries": entries,
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def cost_findings(records: dict, baseline: dict | None = None,
                  profile=None) -> list[Finding]:
    """The four model rules over a set of entry records.

    `intensity-regression` always compares under the BASELINE's
    declared profile (like-with-like regardless of --profile);
    `hbm-overflow` checks the REQUESTED profile's capacity. Pass
    `baseline={}` to skip the regression gate (runner self-report
    blocks, whose entry tags differ from the production baseline's)."""
    prof = resolve_profile(profile)
    base = load_cost_baseline() if baseline is None else baseline
    base_prof = None
    if base:
        try:
            base_prof = resolve_profile(base.get("profile", "cpu"))
        except ValueError:
            base_prof = None
    tol = float(base.get("tolerance_pct", 20.0)) if base else 20.0
    budgets = (base.get("carry_budgets") or {}) if base else {}
    default_budget = int(base.get("default_carry_budget_bytes",
                                  DEFAULT_CARRY_BUDGET)) \
        if base else DEFAULT_CARRY_BUDGET
    out: list[Finding] = []
    for name in sorted(records):
        rec = records[name]
        for hit in rec.get("dp_collectives") or ():
            out.append(Finding(
                rule="collective-on-dp", entry=name,
                where=hit["where"], key=hit["key"],
                detail=hit["detail"]))
        budget = int(budgets.get(name, default_budget))
        if rec["carry_bytes"] > budget:
            out.append(Finding(
                rule="carry-growth", entry=name,
                where=rec.get("carry_site") or f"{name} scan carry",
                key=f"cost:{name}:carry",
                detail=f"scan carry {rec['carry_bytes']} B exceeds "
                       f"budget {budget} B"))
        if rec["peak_bytes_donated"] > prof.hbm_bytes:
            out.append(Finding(
                rule="hbm-overflow", entry=name, where=name,
                key=f"cost:{name}:hbm",
                detail=f"predicted peak {rec['peak_bytes_donated']} B "
                       f"(donation credited) exceeds {prof.name} HBM "
                       f"{int(prof.hbm_bytes)} B"))
        if base and base_prof is not None:
            bent = (base.get("entries") or {}).get(name)
            cur = predict(rec, base_prof)
            cur_v = cur["msgs_per_sec"] or cur["rounds_per_sec"]
            if bent is None:
                out.append(Finding(
                    rule="intensity-regression", entry=name, where=name,
                    key=f"cost:{name}:baseline",
                    detail="entry missing from cost_baseline.json "
                           "(regenerate with --write-cost-baseline)"))
            else:
                prev = bent.get("msgs_per_sec") or \
                    bent.get("rounds_per_sec")
                if prev and cur_v < prev * (1.0 - tol / 100.0):
                    out.append(Finding(
                        rule="intensity-regression", entry=name,
                        where=name, key=f"cost:{name}:intensity",
                        detail=f"predicted {cur_v:.1f}/s under "
                               f"{base_prof.name} profile is "
                               f"{100 * (1 - cur_v / prev):.1f}% below "
                               f"baseline {prev:.1f}/s "
                               f"(tolerance {tol:.0f}%)"))
    return out


# ---------------------------------------------------------------------------
# The production report (CLI / gate surface)
# ---------------------------------------------------------------------------

@dataclass
class CostReport:
    records: dict = field(default_factory=dict)
    findings: list = field(default_factory=list)    # [Finding]
    notes: list = field(default_factory=list)
    profile: str = "cpu"
    wall_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {"ok": self.ok, "profile": self.profile,
                "records": {k: self.records[k]
                            for k in sorted(self.records)},
                "findings": [f.as_dict() for f in self.findings],
                "notes": list(self.notes),
                "wall-s": round(self.wall_s, 3)}

    def render_text(self) -> str:
        lines = [f"cost audit [{self.profile}]: "
                 f"{len(self.records)} entries costed, "
                 f"{len(self.findings)} finding(s), {self.wall_s:.1f}s"]
        for note in self.notes:
            lines.append(f"  note: {note}")
        hdr = (f"  {'entry':<44} {'MFLOP':>8} {'MB':>8} {'AI':>7} "
               f"{'rounds/s':>9} {'msgs/s cap':>11}")
        lines.append(hdr)
        for name in sorted(self.records):
            rec = self.records[name]
            pred = rec["predicted"]
            hbm = rec["hbm_bytes_read"] + rec["hbm_bytes_written"]
            mps = pred["msgs_per_sec"]
            lines.append(
                f"  {name:<44} {rec['flops'] / 1e6:>8.2f} "
                f"{hbm / 1e6:>8.2f} {rec['arithmetic_intensity']:>7.4f} "
                f"{pred['rounds_per_sec']:>9.1f} "
                f"{(f'{mps:.0f}' if mps else '-'):>11}")
        from . import RULES
        for f in self.findings:
            meta = RULES.get(f.rule, {})
            lines.append(f"\nNEW [{f.severity}] {f.rule} @ {f.where}")
            lines.append(f"  {meta.get('summary', '')}")
            if f.detail:
                lines.append(f"  detail: {f.detail}")
            if f.entry:
                lines.append(f"  entry: {f.entry}")
        lines.append("\ncost result: " + (
            "CLEAN (no findings)" if self.ok
            else f"{len(self.findings)} finding(s)"))
        return "\n".join(lines)


def cost_production(programs=None, mesh: str | None = "auto",
                    fleet: bool = True, profile=None,
                    baseline: dict | None = None) -> CostReport:
    """Costs every production entry point the hazard audit traces (same
    job list: plain + mesh variants + fleet + telemetry + checker
    kernels) and gates the records against cost_baseline.json."""
    from .jaxpr_audit import iter_production_specs
    t0 = time.perf_counter()
    prof = resolve_profile(profile)
    specs, notes = iter_production_specs(programs=programs, mesh=mesh,
                                         fleet=fleet)
    records = {}
    for spec in specs:
        records[spec.name] = cost_step(spec, prof)
    findings = cost_findings(records, baseline=baseline, profile=prof)
    return CostReport(records=records, findings=findings, notes=notes,
                      profile=prof.name,
                      wall_s=time.perf_counter() - t0)
