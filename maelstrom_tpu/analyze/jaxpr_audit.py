"""Jaxpr-level hazard audit of the compiled hot loop.

Traces step functions to ClosedJaxprs with `jax.make_jaxpr` (abstract
evaluation only — nothing is compiled or executed) and walks every
equation, recursing into `pjit`/`scan`/`while`/`cond` sub-jaxprs, to
flag the hazard classes that have produced real soak-only bugs here:

  - `unstable-sort`: a `sort` primitive with ``is_stable=False`` and no
    index-tiebreak operand (``num_keys < 2``). Stability is NOT portable
    across sharded sorts — the PR 2 delivery-order bug class. A lexsort
    with an explicit ``arange`` tiebreak (num_keys >= 2) passes.
  - `host-transfer`: `io_callback`/`pure_callback`/`debug_callback`/
    `device_put` equations inside the traced step — each one is a host
    round-trip per round instead of per dispatch.
  - `dtype-widening`: `convert_element_type` widening a 32-bit type to
    64 bits (x64 leaks, weak-type widening after canonicalization).
  - `scatter-nonunique`: scatter-SET without ``unique_indices`` —
    overlapping updates apply in compiler order (scatter-add/-mul/etc.
    are combiner-commutative for ints and are not flagged).
  - `donation-alias` / `donation-reshard`: donated example trees holding
    one buffer twice, and donated carries whose pinned input sharding
    differs from the output pin (a donated arg cannot be resharded).

`audit_production` builds the REAL production step functions —
`make_round_fn`/`make_scan_fn` over `TpuRunner`-constructed
program/config/sharding state, exactly as `runner.tpu_runner` builds
them — with donation forced ON (the TPU configuration) so the audit
sees what production sees even when it runs on a CPU dev box.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field

from . import Finding

# Workload -> built-in TPU node program (the `--node tpu:<x>` namespace;
# lin-mutex rides the lin-kv program). A dict value names a program
# whose audit entry is not 1:1 with a workload — it carries the node
# spec, the workload it serves, and any extra build options (e.g. the
# role-partitioned compartment cluster, which derives its own node
# count from the role spec).
WORKLOAD_NODES = {
    "broadcast": "tpu:broadcast",
    "broadcast-batched": "tpu:broadcast-batched",
    "g-set": "tpu:g-set",
    "g-counter": "tpu:g-counter", "pn-counter": "tpu:pn-counter",
    "lin-kv": "tpu:lin-kv", "txn-list-append": "tpu:txn-list-append",
    "unique-ids": "tpu:unique-ids", "kafka": "tpu:kafka",
    "txn-rw-register": "tpu:txn-rw-register",
    # role-partitioned families (doc/compartment.md): the compartment
    # consensus cluster and the in-cluster service nodes — both step
    # heterogeneous role slices inside the one compiled round, so the
    # gate traces the RolePartition step path too
    "compartment": {"workload": "lin-kv", "node": "tpu:compartment",
                    "opts": {"node_count": None}},
    # the ELECTED configuration (sequencers > 1, doc/compartment.md
    # "leader election") compiles a different sequencer/acceptor/proxy
    # step body — phase-1 prepare/promise, recovery queries, ballot
    # fencing — under the full fault soup, so the gate traces it as its
    # own program
    "compartment-failover": {
        "workload": "lin-kv", "node": "tpu:compartment",
        "opts": {"node_count": None,
                 "roles": "sequencers=3,proxies=2,acceptors=2x2,"
                          "replicas=2",
                 "nemesis": {"kill", "pause", "partition",
                             "duplicate"}}},
    # the byzantine adversary (doc/faults.md "byzantine is a conviction
    # driver") threads a corruption-mask rewrite (`byzantine.corrupt_
    # pool` and the proxies' detection/NACK lanes) through the compiled
    # round, so the gate traces the attacked elected compartment as its
    # own scan variant — the byz_mask machinery must stay free of new
    # hazards (host transfers, unstable sorts) at zero findings
    "compartment-byzantine": {
        "workload": "lin-kv", "node": "tpu:compartment",
        "opts": {"node_count": None,
                 "roles": "sequencers=2,proxies=2,acceptors=2x2,"
                          "replicas=2",
                 "nemesis": {"byzantine"}}},
    "lin-tso": {"workload": "lin-tso", "node": "tpu:services",
                "opts": {"node_count": None}},
    # the ordering-layer axis (doc/ordering.md): `--ordering` composes
    # an engine's UNCHANGED device program with a host-side applier, so
    # the step bodies are the welded engines' — but the gate traces the
    # composed programs anyway (config drift in the composition would
    # surface here). Two entries cover the two engine families whose
    # composition differs from any welded audit entry: the batched
    # broadcast under a non-broadcast workload, and the role-partitioned
    # compartment under kafka. ordered[raft] is config-identical to the
    # txn-list-append entry (same program class, same opts shape).
    "ordered-batched": {"workload": "lin-kv", "node": "tpu:ordered",
                        "opts": {"ordering": "batched"}},
    "ordered-compartment": {"workload": "kafka", "node": "tpu:ordered",
                            "opts": {"ordering": "compartment",
                                     "node_count": None}},
}
DEFAULT_PROGRAMS = tuple(WORKLOAD_NODES)
# mesh variants are traced for one pool-path and one edge-path program;
# the sharding machinery is shared, so this covers the --mesh hot loop
# without tripling the audit's wall time
DEFAULT_MESH_PROGRAMS = ("lin-kv", "broadcast")
# fleet variants likewise: the vmapped fleet scan re-batches every
# scatter/sort in the round body, so one pool-path and one edge-path
# program cover the whole --fleet hot loop (plain + --mesh 2,1, which
# shards the cluster axis over dp)
DEFAULT_FLEET_PROGRAMS = ("lin-kv", "broadcast")
AUDIT_FLEET = 4                     # clusters in the traced fleet batch

HOST_TRANSFER_PRIMS = ("io_callback", "pure_callback", "debug_callback",
                       "device_put")


@dataclass
class StepSpec:
    """One auditable compiled entry point: the function, example args to
    trace it with, and its donation/sharding contract (argument
    `carry_argnum` is donated and comes back as output 0 under the same
    pinned sharding — the contract every runner entry point follows)."""
    name: str
    fn: object
    args: tuple
    donate_argnums: tuple = ()
    carry_argnum: int = 0
    in_shardings: object = None     # sharding pytree for the carry, or None
    out_shardings: object = None    # sharding pytree for output 0, or None
    extra_findings: list = field(default_factory=list)
    # builder context the cost model reads (cfg/fleet for the per-round
    # message-capacity bound); the hazard audit ignores it
    meta: dict = field(default_factory=dict)


def _repo_rel(path: str) -> str:
    import maelstrom_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(maelstrom_tpu.__file__)))
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root)
    return os.path.basename(path)


def _site(eqn):
    """(display, key): `file:line (func)` and the line-free baseline key
    `file:func`."""
    from jax._src import source_info_util
    summary = source_info_util.summarize(eqn.source_info)
    # summarize() -> "path:line (function)" (or "unknown")
    func = ""
    path_line = summary
    if " (" in summary and summary.endswith(")"):
        path_line, func = summary[:-1].rsplit(" (", 1)
    path, _, line = path_line.rpartition(":")
    rel = _repo_rel(path) if path else path_line
    display = f"{rel}:{line} ({func})" if func else f"{rel}:{line}"
    return display, f"{rel}:{func or line}"


def _iter_subjaxprs(params: dict):
    from jax.core import ClosedJaxpr, Jaxpr
    for v in params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            if isinstance(item, ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, Jaxpr):
                yield item


def _mesh_axis_sizes(shardings) -> dict:
    """{axis name: size} of the first NamedSharding mesh found in a
    sharding pytree (empty when unsharded) — the GSPMD axis context the
    `replicated-scatter` rule starts a walk with."""
    import jax
    from jax.sharding import NamedSharding
    for leaf in jax.tree.leaves(shardings):
        if isinstance(leaf, NamedSharding):
            return dict(leaf.mesh.shape)
    return {}


def _is_mixed_axes(axes: dict) -> bool:
    """>= 2 mesh axes of size > 1 visible to GSPMD: the regime where a
    scatter-SET necessarily has some operand replicated over a >1 axis
    (PR 2's corrupted-reply-row class — per-replica scatter
    contributions combine additively)."""
    return sum(1 for s in axes.values() if s > 1) >= 2


def audit_jaxpr(jaxpr, entry: str = "",
                mesh_axes: dict | None = None) -> list[Finding]:
    """Walks one (open) jaxpr recursively and returns raw findings
    (per-equation; `analyze.dedupe_sites` collapses duplicates).

    `mesh_axes` ({axis: size}, from the entry's sharding pins) arms the
    `replicated-scatter` rule: a plain scatter-SET reached while >= 2
    visible mesh axes exceed size 1 is flagged — GSPMD must replicate
    some scatter operand over one of them, which is not value-safe.
    Entering a `shard_map` region shrinks the visible axes to the
    region's `auto` (unmanual) set: inside a full-manual body the
    scatter is local per shard and the rule cannot fire."""
    import numpy as np
    out: list[Finding] = []

    def visit(jx, axes):
        for eqn in jx.eqns:
            p = eqn.primitive.name
            if p == "shard_map":
                m = eqn.params.get("mesh")
                auto = eqn.params.get("auto") or frozenset()
                sub_axes = {k: v for k, v in dict(
                    getattr(m, "shape", {}) or {}).items() if k in auto}
                for sub in _iter_subjaxprs(eqn.params):
                    visit(sub, sub_axes)
                continue
            if p == "sort":
                if not eqn.params.get("is_stable") and \
                        int(eqn.params.get("num_keys", 1)) < 2:
                    where, key = _site(eqn)
                    out.append(Finding(
                        rule="unstable-sort", entry=entry, where=where,
                        key=key,
                        detail=f"sort is_stable=False "
                               f"num_keys={eqn.params.get('num_keys', 1)}"))
            elif p in HOST_TRANSFER_PRIMS:
                where, key = _site(eqn)
                out.append(Finding(rule="host-transfer", entry=entry,
                                   where=where, key=key, detail=p))
            elif p == "convert_element_type":
                try:
                    old = np.dtype(eqn.invars[0].aval.dtype)
                    new = np.dtype(eqn.params["new_dtype"])
                except (TypeError, AttributeError, KeyError):
                    continue
                if (new.itemsize > old.itemsize and new.itemsize >= 8
                        and new.kind in "fiuc"):
                    where, key = _site(eqn)
                    out.append(Finding(
                        rule="dtype-widening", entry=entry, where=where,
                        key=key, detail=f"{old.name} -> {new.name}"))
            elif p == "scatter":
                # plain scatter = .at[].set — order-dependent under
                # overlap. Combiner scatters (-add/-mul/-min/-max) are
                # commutative over ints and stay un-flagged.
                if not eqn.params.get("unique_indices"):
                    where, key = _site(eqn)
                    out.append(Finding(
                        rule="scatter-nonunique", entry=entry,
                        where=where, key=key,
                        detail=f"mode={eqn.params.get('mode')}"))
                if _is_mixed_axes(axes):
                    where, key = _site(eqn)
                    out.append(Finding(
                        rule="replicated-scatter", entry=entry,
                        where=where, key=key,
                        detail=f"scatter-SET under mixed mesh axes "
                               f"{axes} outside a shard_map manual "
                               f"region"))
            for sub in _iter_subjaxprs(eqn.params):
                visit(sub, axes)

    visit(jaxpr, dict(mesh_axes or {}))
    return out


# ---------------------------------------------------------------------------
# Donation checks (example-tree level: aliasing is invisible in a jaxpr)
# ---------------------------------------------------------------------------

def _buffer_token(leaf):
    """Best-effort identity of a leaf's underlying buffer."""
    try:
        return ("ptr", leaf.unsafe_buffer_pointer())
    except Exception:
        pass
    try:
        iface = leaf.__array_interface__
        return ("np", iface["data"][0])
    except Exception:
        return ("id", id(leaf))


def check_donation_alias(spec: StepSpec) -> list[Finding]:
    """Two leaves of a donated argument sharing one buffer: XLA rejects
    the dispatch outright (`f(donate(a), donate(a))`), and the usual
    cause is state built without `sim.dealias` — the PR 2 bug class."""
    import jax
    out: list[Finding] = []
    seen: dict = {}
    for argnum in spec.donate_argnums:
        for leaf in jax.tree.leaves(spec.args[argnum]):
            if getattr(leaf, "size", 1) == 0:
                continue            # zero-byte buffers may legally share
            tok = _buffer_token(leaf)
            if tok in seen:
                out.append(Finding(
                    rule="donation-alias", entry=spec.name,
                    where=f"{spec.name} donated arg {argnum}",
                    key=f"entry:{spec.name}:donation-alias",
                    detail=f"duplicate buffer {tok[0]} in donated tree "
                           f"(leaf shapes {seen[tok]} and "
                           f"{getattr(leaf, 'shape', ())})"))
            else:
                seen[tok] = getattr(leaf, "shape", ())
    return out


def check_donation_reshard_pjit(closed, spec: StepSpec):
    """Reads the REAL donation/sharding contract off the traced pjit
    equation (`donated_invars`, resolved `in_shardings`/`out_shardings`)
    and compares the pins positionally over the donated carry prefix —
    the entry-point contract is carry = argument 0 = output 0, so leaf i
    of the donated region must come back under the same pin. A donated
    argument cannot be resharded at the next call boundary; a mismatch
    forces a copy of a buffer the caller no longer owns.

    Returns None when the trace exposes nothing comparable (not a
    single-pjit trace, nothing donated, or unresolved shardings) — the
    caller then falls back to the spec-declared pins."""
    from jax.sharding import Sharding
    eqns = closed.jaxpr.eqns
    if len(eqns) != 1 or eqns[0].primitive.name != "pjit":
        return None
    params = eqns[0].params
    donated = params.get("donated_invars") or ()
    ins = params.get("in_shardings") or ()
    outs = params.get("out_shardings") or ()
    bad = []
    comparable = False
    for i, don in enumerate(donated):
        if not don or i >= len(ins) or i >= len(outs):
            continue
        a, b = ins[i], outs[i]
        if not (isinstance(a, Sharding) and isinstance(b, Sharding)):
            continue                    # unresolved/unspecified pin
        comparable = True
        if a != b:
            bad.append((i, a, b))
    if not comparable:
        return None
    if not bad:
        return []
    i, a, b = bad[0]
    return [Finding(
        rule="donation-reshard", entry=spec.name,
        where=f"{spec.name} carry leaf {i}",
        key=f"entry:{spec.name}:donation-reshard",
        detail=f"{len(bad)} leaf pin(s) differ, first: in={a} out={b}")]


def check_donation_reshard(spec: StepSpec) -> list[Finding]:
    """Spec-declared fallback for entry points whose trace exposes no
    resolved pjit pins: compares the shardings the caller SAYS it pins.
    Weaker than the pjit-param check (it cannot catch a builder that
    diverges from its declaration), hence used only as the fallback."""
    import jax
    if spec.in_shardings is None or spec.out_shardings is None:
        return []
    ins = jax.tree.leaves(spec.in_shardings)
    outs = jax.tree.leaves(spec.out_shardings)
    bad = []
    for i, (a, b) in enumerate(zip(ins, outs)):
        if a != b:
            bad.append((i, a, b))
    if not bad:
        return []
    i, a, b = bad[0]
    return [Finding(
        rule="donation-reshard", entry=spec.name,
        where=f"{spec.name} carry leaf {i}",
        key=f"entry:{spec.name}:donation-reshard",
        detail=f"{len(bad)} leaf pin(s) differ, first: in={a} out={b}")]


def audit_step(spec: StepSpec) -> list[Finding]:
    """Audits one entry point: donation checks on the example tree, then
    the recursive jaxpr walk of the abstract trace. The reshard check
    prefers the REAL pins on the traced pjit equation and falls back to
    the spec-declared ones."""
    import jax
    findings = list(spec.extra_findings)
    findings += check_donation_alias(spec)
    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    reshard = check_donation_reshard_pjit(closed, spec)
    if reshard is None:
        reshard = check_donation_reshard(spec)
    findings += reshard
    findings += audit_jaxpr(closed.jaxpr, entry=spec.name,
                            mesh_axes=_mesh_axis_sizes(spec.in_shardings))
    return findings


# ---------------------------------------------------------------------------
# Building the REAL production step functions
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _force_donation(on: bool = True):
    """Audit-as-TPU: `sim.donation_enabled` consults MAELSTROM_DONATE at
    every call, so pinning it while the step functions are BUILT makes a
    CPU dev box trace exactly the donating TPU configuration."""
    prev = os.environ.get("MAELSTROM_DONATE")
    os.environ["MAELSTROM_DONATE"] = "1" if on else "0"
    try:
        yield
    finally:
        if prev is None:
            del os.environ["MAELSTROM_DONATE"]
        else:
            os.environ["MAELSTROM_DONATE"] = prev


def production_step_specs(workload: str, mesh: str | None = None,
                          donate: bool = True,
                          telemetry: bool = False) -> list[StepSpec]:
    """Builds the production `round_fn` / `scan_fn` (plain and journaled)
    for one workload the exact way `runner.tpu_runner` does — same
    program, NetConfig, capacities, shardings, donation — and returns
    them as auditable StepSpecs. With `mesh`, the runner's `--mesh`
    sharding pins are applied and traced."""
    import jax.numpy as jnp

    from .. import core
    from ..net import tpu as T
    from ..runner.tpu_runner import TpuRunner
    from ..sim import make_round_fn, make_scan_fn

    entry = WORKLOAD_NODES.get(workload)
    if entry is None:
        raise ValueError(f"unknown workload {workload!r}; expected one of "
                         f"{sorted(WORKLOAD_NODES)}")
    if isinstance(entry, dict):
        node = entry["node"]
        opts = {"workload": entry.get("workload", workload),
                "node": node, "node_count": 5, "time_limit": 1.0,
                **entry.get("opts", {})}
    else:
        node = entry
        opts = {"workload": workload, "node": node, "node_count": 5,
                "time_limit": 1.0}
    if mesh:
        opts["mesh"] = mesh
    if telemetry:
        # flight-recorder rings (doc/observability.md): the telemetry
        # fold becomes part of the traced round/scan bodies, so the
        # gate proves it adds no host transfers / unstable sorts /
        # non-unique scatters
        opts["telemetry"] = "audit"
    with _force_donation(donate):
        test = core.build_test(opts)
        runner = TpuRunner(test)
        inject = T.Msgs.empty(max(runner.concurrency, 1))
        sh = runner._shardings
        sim_sh, out0_sh = (sh[0], sh[0]) if sh is not None else (None, None)
        tag = (f"{workload}{'@mesh=' + mesh if mesh else ''}"
               f"{'@telemetry' if telemetry else ''}")
        common = dict(donate_argnums=(0,) if donate else (),
                      in_shardings=sim_sh, out_shardings=out0_sh,
                      meta={"cfg": runner.cfg, "workload": workload})
        specs = [
            StepSpec(name=f"round_fn[{tag}]",
                     fn=make_round_fn(runner.program, runner.cfg,
                                      donate=donate, shardings=sh),
                     args=(runner.sim, inject), **common),
            StepSpec(name=f"scan_fn[{tag}]",
                     fn=make_scan_fn(runner.program, runner.cfg,
                                     reply_cap=runner.reply_log_cap,
                                     donate=donate, shardings=sh),
                     args=(runner.sim, inject, jnp.int32(8), True),
                     **common),
            StepSpec(name=f"scan_journal_fn[{tag}]",
                     fn=make_scan_fn(runner.program, runner.cfg,
                                     journal_cap=runner.journal_scan_cap,
                                     reply_cap=runner.reply_log_cap,
                                     donate=donate, shardings=sh),
                     args=(runner.sim, inject, jnp.int32(8), True),
                     **common),
            # the continuous-mode (--continuous) injection path: the
            # sched-inject scan masks the inject batch per round and
            # drains per-row assigned mids — a distinct compiled entry
            # point, so the gate traces it like the others
            StepSpec(name=f"cscan_fn[{tag}]",
                     fn=make_scan_fn(runner.program, runner.cfg,
                                     reply_cap=runner.reply_log_cap,
                                     donate=donate, shardings=sh,
                                     sched_inject=True),
                     args=(runner.sim, inject,
                           jnp.zeros(max(runner.concurrency, 1),
                                     jnp.int32),
                           jnp.int32(8), True),
                     **common),
        ]
    return specs


def fleet_step_specs(workload: str, fleet: int = AUDIT_FLEET,
                     mesh: str | None = None,
                     donate: bool = True) -> list[StepSpec]:
    """Builds the FLEET entry points — `make_fleet_scan_fn` (the vmapped
    scan every `--fleet` dispatch runs) and the vmapped per-round
    function — over a cluster-batched state tree built the way
    `runner.fleet_runner` builds it, and returns them as auditable
    StepSpecs. With `mesh` (e.g. "2,1"), the fleet axis shards over dp
    exactly as `--fleet N --mesh dp,sp` runs it."""
    import jax
    import jax.numpy as jnp

    from .. import core, parallel
    from ..net import tpu as T
    from ..runner.tpu_runner import TpuRunner
    from ..sim import dealias, donation_enabled, make_fleet_scan_fn

    node = WORKLOAD_NODES.get(workload)
    if node is None:
        raise ValueError(f"unknown workload {workload!r}; expected one of "
                         f"{sorted(WORKLOAD_NODES)}")
    opts = {"workload": workload, "node": node, "node_count": 5,
            "time_limit": 1.0}
    with _force_donation(donate):
        test = core.build_test(opts)
        runner = TpuRunner(test)
        F = fleet
        # the EXACT production construction (runner/fleet_runner.py):
        # make_fleet_sims pins row i == make_sim(seed_i), dealiased
        # before donation like the fleet runner does — so the audit
        # traces the entry point `--fleet` actually runs
        sim = parallel.make_fleet_sims(runner.program, runner.cfg,
                                       seeds=range(F))
        if donation_enabled():
            sim = dealias(sim)
        inject1 = T.Msgs.empty(max(runner.concurrency, 1))
        inject = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (F,) + a.shape), inject1)
        sh = None
        if mesh:
            m = parallel.mesh_from_spec(mesh)
            if F % m.shape["dp"]:
                raise ValueError(f"fleet audit: {F} % dp="
                                 f"{m.shape['dp']} != 0")
            sh = parallel.fleet_scan_shardings(m, sim, inject)
            sim = jax.device_put(sim, sh[0])
        kv = jnp.full((F,), 8, jnp.int32)
        flags = jnp.ones((F,), bool)
        at = jnp.zeros((F, max(runner.concurrency, 1)), jnp.int32)
        tag = f"{workload}@fleet={F}" + (f"@mesh={mesh}" if mesh else "")
        sim_sh = sh[0] if sh is not None else None
        common = dict(donate_argnums=(0,) if donate else (),
                      in_shardings=sim_sh, out_shardings=sim_sh,
                      meta={"cfg": runner.cfg, "workload": workload,
                            "fleet": F})
        specs = [
            StepSpec(name=f"fleet_scan_fn[{tag}]",
                     fn=make_fleet_scan_fn(runner.program, runner.cfg,
                                           reply_cap=runner.reply_log_cap,
                                           donate=donate, shardings=sh),
                     args=(sim, inject, kv, flags, flags), **common),
            # the continuous-mode fleet dispatch (`--fleet N
            # --continuous`, ISSUE 12): the vmapped sched-inject scan
            # with its [F, Q] round-offset tensor and inj_mids drain —
            # a distinct compiled entry point, traced like the rest
            StepSpec(name=f"fleet_cscan_fn[{tag}]",
                     fn=make_fleet_scan_fn(runner.program, runner.cfg,
                                           reply_cap=runner.reply_log_cap,
                                           donate=donate, shardings=sh,
                                           sched_inject=True),
                     args=(sim, inject, at, kv, flags, flags), **common),
            # in_shardings here only arms the replicated-scatter rule's
            # mesh context (no donation contract on the round fn)
            StepSpec(name=f"fleet_round_fn[{tag}]",
                     fn=parallel.make_cluster_round_fn(
                         runner.program, runner.cfg,
                         mesh=(parallel.mesh_from_spec(mesh)
                               if mesh else None),
                         example=sim, example_inject=inject),
                     args=(sim, inject),
                     donate_argnums=(), in_shardings=sim_sh,
                     out_shardings=None,
                     meta={"cfg": runner.cfg, "workload": workload,
                           "fleet": F}),
        ]
    return specs


def checker_step_specs() -> list[StepSpec]:
    """The device-resident checker's jitted entry points (doc/perf.md
    "device-resident grading"): the elle edge constructor and the
    cycle-screen fixed point (`checkers/elle_device.py`). Small example
    shape buckets — the kernels are shape-polymorphic over pow-2
    buckets, so one trace covers the hazard surface. No donation: the
    checker runs between dispatches on throwaway arrays."""
    import numpy as np

    from ..checkers import elle_device as ed

    vp, rp, tp = 32, 32, 32
    writers = np.full(vp, -1, np.int32)
    writers[:8] = np.arange(8)
    slot_key = np.full(vp, -1, np.int32)
    slot_key[:8] = np.array([0, 0, 0, 0, 1, 1, 1, 1])
    slot_idx = np.zeros(vp, np.int32)
    slot_idx[:8] = np.array([0, 1, 2, 3, 0, 1, 2, 3])
    r_tid = np.full(rp, -1, np.int32)
    r_tid[:4] = np.array([8, 9, 10, 11])
    r_n = np.zeros(rp, np.int32)
    r_n[:4] = np.array([1, 2, 0, 4])
    wr_pos = np.full(rp, -1, np.int32)
    wr_pos[:4] = np.array([0, 1, -1, 3])
    rw_pos = np.full(rp, -1, np.int32)
    rw_pos[:4] = np.array([1, 2, 0, -1])
    ret_tid = np.full(tp, -1, np.int32)
    ret_tid[:12] = np.arange(12)
    before_idx = np.full(tp, -1, np.int32)
    before_idx[:12] = np.arange(12) - 1
    fns = ed._build_fns()
    return [
        StepSpec(name="elle_edges_fn",
                 fn=fns["edges_raw"],
                 args=(writers, slot_key, r_tid, wr_pos, rw_pos)),
        StepSpec(name="elle_screen_fn",
                 fn=lambda *a: fns["screen_raw"](*a, n_txns_pad=tp),
                 args=(writers, slot_key, slot_idx, r_tid, r_n, wr_pos,
                       rw_pos, ret_tid, before_idx)),
    ]


def iter_production_specs(programs=None, mesh: str | None = "auto",
                          fleet: bool = True):
    """Builds the FULL production job list — every entry point the gate
    traces — and returns (specs, notes). Shared by the hazard audit
    (`audit_production`) and the cost model (`cost_model.
    cost_production`), so both gates always cover the same surface.

    `mesh="auto"` adds `--mesh 1,2` variants for DEFAULT_MESH_PROGRAMS
    when >= 2 devices are visible; an explicit mesh spec is applied to
    every requested program; None disables mesh variants. `fleet`
    additionally builds the vmapped fleet scan/round for
    DEFAULT_FLEET_PROGRAMS (plain, sharded `--mesh 2,1` at >= 2
    devices, and the mixed `--mesh 2,2` shard_map configuration at
    >= 4). Telemetry-ring variants and the device checker kernels ride
    along as in the audit."""
    import jax
    programs = list(programs or DEFAULT_PROGRAMS)
    specs: list[StepSpec] = []
    notes: list[str] = []

    jobs: list[tuple[str, str | None]] = [(p, None) for p in programs]
    if mesh == "auto":
        if jax.device_count() >= 2:
            jobs += [(p, "1,2") for p in DEFAULT_MESH_PROGRAMS
                     if p in programs]
        else:
            notes.append("mesh variants skipped: < 2 visible devices "
                         "(set XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=2 to audit them on CPU)")
    elif mesh:
        jobs += [(p, mesh) for p in programs]

    for workload, mesh_spec in jobs:
        specs += production_step_specs(workload, mesh=mesh_spec)

    if fleet:
        fleet_jobs: list[tuple[str, str | None]] = \
            [(p, None) for p in DEFAULT_FLEET_PROGRAMS if p in programs]
        if mesh == "auto":
            if jax.device_count() >= 2:
                fleet_jobs += [(p, "2,1") for p in DEFAULT_FLEET_PROGRAMS
                               if p in programs]
            else:
                notes.append("fleet mesh variants skipped: < 2 visible "
                             "devices")
            if jax.device_count() >= 4:
                # the pod-scale MIXED mesh (dp>1 x sp>1): the shard_map
                # manual scan body, traced so the replicated-scatter
                # rule proves every scatter sits inside the manual
                # region (AUDIT_FLEET=4 divides the mesh -> the
                # fully-sharded P(("dp","sp")) fleet-axis mode)
                fleet_jobs += [(p, "2,2") for p in DEFAULT_FLEET_PROGRAMS
                               if p in programs]
            else:
                notes.append("fleet mixed-mesh variants skipped: < 4 "
                             "visible devices")
        elif mesh:
            from .. import parallel
            dp = parallel.mesh_from_spec(mesh).shape["dp"]
            if AUDIT_FLEET % max(dp, 1) == 0:
                fleet_jobs += [(p, mesh) for p in DEFAULT_FLEET_PROGRAMS
                               if p in programs]
        for workload, mesh_spec in fleet_jobs:
            specs += fleet_step_specs(workload, mesh=mesh_spec)

    # flight-recorder rings (doc/observability.md): ring-enabled traces
    # of one pool-path and one edge-path workload, so the gate audits
    # the telemetry fold itself — the host-transfer / scatter rules
    # must stay at zero findings with rings compiled in
    for workload in ("lin-kv", "broadcast"):
        if workload in programs:
            specs += production_step_specs(workload, telemetry=True)

    # device-resident checker kernels (doc/perf.md "device-resident
    # grading"): traced whenever the program set includes the elle
    # workload — the checker is part of that workload's hot path now
    if "txn-list-append" in programs:
        specs += checker_step_specs()
    return specs, notes


def audit_production(programs=None, mesh: str | None = "auto",
                     fleet: bool = True):
    """Traces and audits the production step functions for each
    workload (job list from `iter_production_specs` — the shared
    audit/cost surface). Returns (findings, entry_names, notes)."""
    specs, notes = iter_production_specs(programs=programs, mesh=mesh,
                                         fleet=fleet)
    findings: list[Finding] = []
    entries: list[str] = []
    for spec in specs:
        findings += audit_step(spec)
        entries.append(spec.name)
    return findings, entries, notes


def fleet_runner_step_specs(runner) -> list[StepSpec]:
    """Spec for a LIVE FleetRunner's dispatch entry point: the vmapped
    fleet scan over the runner's own batched tree, shardings, and
    donation setting (the exact dispatch every fleet wave runs).
    Shared by the `static-audit` and `cost` self-report blocks."""
    import jax
    import jax.numpy as jnp

    from ..sim import donation_enabled, make_fleet_scan_fn

    donate = donation_enabled()
    F = runner.spec.fleet
    inject = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (F,) + a.shape),
        runner._empty_inject)
    sh = runner._shardings
    sim_sh = sh[0] if sh is not None else None
    kv = jnp.full((F,), 8, jnp.int32)
    flags = jnp.ones((F,), bool)
    tag = f"{type(runner.program).__name__}@fleet={F}"
    common = dict(donate_argnums=(0,) if donate else (),
                  in_shardings=sim_sh, out_shardings=sim_sh,
                  meta={"cfg": runner.cfg, "fleet": F})
    if getattr(runner, "continuous", False):
        # a continuous fleet's waves dispatch the vmapped sched-inject
        # scan: that is the entry point to self-report
        at = jnp.zeros((F, max(runner.concurrency, 1)), jnp.int32)
        spec = StepSpec(
            name=f"fleet_cscan_fn[{tag}]",
            fn=make_fleet_scan_fn(runner.program, runner.cfg,
                                  reply_cap=runner.reply_log_cap,
                                  donate=donate, shardings=sh,
                                  sched_inject=True),
            args=(runner.sim, inject, at, kv, flags, flags), **common)
    else:
        spec = StepSpec(
            name=f"fleet_scan_fn[{tag}]",
            fn=make_fleet_scan_fn(runner.program, runner.cfg,
                                  reply_cap=runner.reply_log_cap,
                                  donate=donate, shardings=sh),
            args=(runner.sim, inject, kv, flags, flags), **common)
    return [spec]


def audit_fleet_runner_steps(runner):
    """Self-report variant for a LIVE FleetRunner: audits the vmapped
    fleet scan dispatch (`fleet_runner_step_specs`)."""
    findings: list[Finding] = []
    names: list[str] = []
    for spec in fleet_runner_step_specs(runner):
        findings += audit_step(spec)
        names.append(spec.name)
    return findings, names, []


def runner_step_specs(runner) -> list[StepSpec]:
    """Specs for a LIVE runner's own program/config under its actual
    donation setting (no as-TPU forcing — the self-report blocks
    describe what this run really executed). Shared by the
    `static-audit` and `cost` results blocks."""
    import jax.numpy as jnp

    from ..net import tpu as T
    from ..sim import donation_enabled, make_round_fn, make_scan_fn

    donate = donation_enabled()
    inject = T.Msgs.empty(max(runner.concurrency, 1))
    sh = runner._shardings
    sim_sh = sh[0] if sh is not None else None
    tag = type(runner.program).__name__
    common = dict(donate_argnums=(0,) if donate else (),
                  in_shardings=sim_sh, out_shardings=sim_sh,
                  meta={"cfg": runner.cfg})
    specs = [
        StepSpec(name=f"round_fn[{tag}]",
                 fn=make_round_fn(runner.program, runner.cfg,
                                  donate=donate, shardings=sh),
                 args=(runner.sim, inject), **common),
        StepSpec(name=f"scan_fn[{tag}]",
                 fn=make_scan_fn(runner.program, runner.cfg,
                                 reply_cap=runner.reply_log_cap,
                                 donate=donate, shardings=sh),
                 args=(runner.sim, inject, jnp.int32(8), True), **common),
    ]
    if getattr(runner, "continuous", False):
        # a continuous run's replies come off the sched-inject scan:
        # that is the entry point to self-report, not the plain one
        specs.append(StepSpec(
            name=f"cscan_fn[{tag}]",
            fn=make_scan_fn(runner.program, runner.cfg,
                            reply_cap=runner.reply_log_cap,
                            donate=donate, shardings=sh,
                            sched_inject=True),
            args=(runner.sim, inject,
                  jnp.zeros(max(runner.concurrency, 1), jnp.int32),
                  jnp.int32(8), True), **common))
    return specs


def audit_runner_steps(runner):
    """Self-report variant: audits a LIVE runner's own entry points
    (`runner_step_specs`)."""
    findings: list[Finding] = []
    names: list[str] = []
    for spec in runner_step_specs(runner):
        findings += audit_step(spec)
        names.append(spec.name)
    return findings, names, []
