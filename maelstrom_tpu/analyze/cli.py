"""Argument parsing + entry point for the static-analysis gate.

Shared by `python -m maelstrom_tpu analyze` (the CLI subcommand) and
`python -m maelstrom_tpu.analyze` (the standalone module CI scripts
call)."""

from __future__ import annotations

import argparse
import json
import sys


def add_analyze_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="Finding output format (json is one object: "
                        "rules, new, suppressed, entries, wall-s)")
    p.add_argument("--programs",
                   help="Comma-separated workloads to trace (default: "
                        "all built-in TPU node programs); 'none' skips "
                        "the jaxpr audit entirely")
    p.add_argument("--mesh", default="auto",
                   help="Mesh variants: 'auto' (default) traces "
                        "--mesh 1,2 for a pool-path and an edge-path "
                        "program when >= 2 devices are visible; an "
                        "explicit dp,sp spec applies to every program; "
                        "'none' disables mesh variants")
    p.add_argument("--no-lint", action="store_true",
                   help="Skip the host-module source lint pass")
    p.add_argument("--no-fleet", action="store_true",
                   help="Skip the vmapped --fleet scan/round variants "
                        "(traced by default: plain, plus --mesh 2,1 — "
                        "the cluster axis sharded over dp — when >= 2 "
                        "devices are visible)")
    p.add_argument("--baseline",
                   help="Alternate baseline file (default: the "
                        "checked-in analyze/baseline.json, or "
                        "analyze/cost_baseline.json under --cost)")
    p.add_argument("--write-baseline", action="store_true",
                   help="Regenerate the baseline to cover every current "
                        "finding (existing reasons are preserved; new "
                        "entries get a FIXME reason to edit) and exit 0")
    p.add_argument("--cost", action="store_true",
                   help="Run the jaxpr cost auditor instead of the "
                        "hazard audit: static roofline records "
                        "(FLOPs/HBM/collective bytes, predicted "
                        "rounds/s) for the same production entry "
                        "points, gated against cost_baseline.json "
                        "(collective-on-dp, carry-growth, "
                        "hbm-overflow, intensity-regression)")
    p.add_argument("--profile",
                   help="Device profile for --cost predictions "
                        "(cpu, tpu-v4, tpu-v5e; default: inferred "
                        "from the JAX backend)")
    p.add_argument("--write-cost-baseline", action="store_true",
                   help="With --cost: regenerate cost_baseline.json "
                        "from the current records (tolerance and "
                        "carry budgets preserved) and exit 0")


def run_analyze(args) -> int:
    from . import run_audit
    programs = None
    jaxpr = True
    if args.programs:
        if args.programs.strip() == "none":
            jaxpr = False
        else:
            programs = [p.strip() for p in args.programs.split(",")
                        if p.strip()]
    mesh = None if args.mesh == "none" else args.mesh
    if getattr(args, "cost", False):
        return _run_cost(args, programs, mesh)
    try:
        report = run_audit(programs=programs, mesh=mesh, jaxpr=jaxpr,
                           lint=not args.no_lint, baseline=args.baseline,
                           fleet=not args.no_fleet)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        path = report.write_baseline(args.baseline)
        print(f"wrote {path} ({len(report.new) + len(report.suppressed)} "
              f"suppressed site(s)); edit any FIXME reasons before "
              f"committing")
        return 0
    if args.format == "json":
        print(json.dumps(report.as_dict(), indent=2))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def _run_cost(args, programs, mesh) -> int:
    from .cost_model import (cost_production, load_cost_baseline,
                             resolve_profile, write_cost_baseline)
    try:
        profile = resolve_profile(args.profile)
        baseline = load_cost_baseline(args.baseline)
        report = cost_production(programs=programs, mesh=mesh,
                                 fleet=not args.no_fleet,
                                 profile=profile, baseline=baseline)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.write_cost_baseline:
        path = write_cost_baseline(report.records, args.baseline,
                                   profile=profile)
        print(f"wrote {path} ({len(report.records)} entr"
              f"{'y' if len(report.records) == 1 else 'ies'})")
        return 0
    if args.format == "json":
        print(json.dumps(report.as_dict(), indent=2))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="maelstrom_tpu.analyze",
        description="Static determinism & hot-path hazard audit "
                    "(jaxpr trace of the production step functions + "
                    "AST lint of the hot host modules), gated against "
                    "analyze/baseline.json. See doc/analyze.md.")
    add_analyze_args(p)
    from ..util import honor_jax_platforms
    honor_jax_platforms()
    return run_analyze(p.parse_args(argv))
