"""Static analysis: determinism & hot-path hazard auditing.

Every hard bug the project has shipped so far was a *silent hot-path
hazard* found only by soak replay after the fact: the PR 2 unstable
delivery sort that diverged under `--mesh` (partitioned sorts don't
preserve stability), and the PR 2/4 donated-carry + CPU zero-copy
`device_get` views that corrupted histories under buffer recycling.
This package converts that bug history into machine-checked invariants,
enforced at *trace time* instead of by replay:

  - `jaxpr_audit` traces the real production step functions
    (`round_fn`/`scan_fn` from `runner.tpu_runner`, plain and `--mesh`
    variants) to ClosedJaxprs and walks every equation (recursing into
    `scan`/`while`/`cond`/`pjit` sub-jaxprs) for unstable sorts, host
    round-trips, dtype widening, non-unique scatters, and donation
    hazards (aliased carries, resharded donated args, CPU zero-copy
    views).
  - `source_lint` (stdlib `ast`) walks the hot *host* modules for
    Python-level nondeterminism: unstable `np.argsort`/`np.sort`,
    iteration over sets feeding sim state, wall-clock reads and
    unseeded module-level `random` in replayed paths.

Findings are structured (rule id, severity, location, excerpt) and
suppressible through the checked-in `analyze/baseline.json`, so the CI
gate (`python -m maelstrom_tpu.analyze`, or the `analyze` CLI
subcommand) only fails on *new* findings. See doc/analyze.md for the
rule catalog and the incident each rule would have caught.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

__all__ = [
    "RULES", "Finding", "AuditReport", "Baseline", "baseline_path",
    "dedupe_sites", "apply_baseline", "run_audit", "audit_runner",
    "audit_fleet_runner", "check_fingerprint_coverage", "cost_runner",
    "cost_fleet_runner",
]


# ---------------------------------------------------------------------------
# Rule catalog. Severity "error" = a hazard class that has shipped a real
# bug here (or would corrupt results outright); "warn" = order/config
# dependence that is frequently deliberate and gets baselined with a
# justification. The gate treats both the same: any NON-baselined
# finding fails.
# ---------------------------------------------------------------------------

RULES: dict[str, dict] = {
    "unstable-sort": {
        "severity": "error",
        "summary": "sort without is_stable=True or an explicit index "
                   "tiebreak operand (num_keys >= 2)",
        "incident": "PR 2: delivery argsort ties diverged under --mesh — "
                    "partitioned sorts don't preserve stability",
    },
    "host-transfer": {
        "severity": "error",
        "summary": "host round-trip primitive inside the compiled hot "
                   "loop (io_callback/pure_callback/debug_callback/"
                   "device_put)",
        "incident": "a per-round host callback turns the one-dispatch "
                    "scan into O(rounds) round trips (~160 ms each on "
                    "remote backends)",
    },
    "dtype-widening": {
        "severity": "error",
        "summary": "implicit 32->64-bit dtype promotion "
                   "(convert_element_type widening; x64 leak)",
        "incident": "f64 sneaking into the scan doubles HBM traffic and "
                    "breaks cross-backend bit-identity",
    },
    "scatter-nonunique": {
        "severity": "warn",
        "summary": "scatter-set without unique_indices: overlapping "
                   "updates are compiler-order-dependent",
        "incident": "same hazard class as the PR 2 sort ties: GSPMD may "
                    "reorder per-shard updates",
    },
    "replicated-scatter": {
        "severity": "error",
        "summary": "scatter-set reached with >= 2 mesh axes of size > 1 "
                    "visible to GSPMD (outside any shard_map manual "
                    "region): some operand is replicated over a >1 axis "
                    "and per-replica contributions combine additively",
        "incident": "PR 2/18: corrupted reply rows at --fleet 2 --mesh "
                    "2,2 — mixed-mesh scan bodies must run manual under "
                    "shard_map (sim.fleet_shard_map)",
    },
    "donation-alias": {
        "severity": "error",
        "summary": "donated argument tree contains the same buffer "
                   "twice (XLA rejects f(donate(a), donate(a)); a "
                   "missed dealias)",
        "incident": "PR 2: make_sim trees alias heavily (Msgs.empty "
                    "fan-out, durable_view views); donation requires "
                    "sim.dealias first",
    },
    "donation-reshard": {
        "severity": "error",
        "summary": "donated carry's pinned input sharding differs from "
                   "its output sharding — the next call must reshard a "
                   "donated buffer",
        "incident": "PR 2: donated args cannot be resharded at the call "
                    "boundary; every producer of the carry must hand "
                    "back the canonical placement",
    },
    "donation-cpu-view": {
        "severity": "warn",
        "summary": "carry donation forced on while the backend is CPU: "
                   "device_get returns zero-copy views that a donating "
                   "dispatch may recycle under live host references",
        "incident": "PR 2/4: rare nondeterministic histories in CPU "
                    "soak runs; donation defaults off on CPU "
                    "(sim.donation_enabled)",
    },
    # ---- source-lint rules (host-side Python, stdlib ast) ----
    "np-unstable-sort": {
        "severity": "error",
        "summary": "np.argsort/np.sort without kind=\"stable\" in a "
                   "replayed host path (numpy defaults to introsort)",
        "incident": "pairing/screening argsorts must be stable or "
                    "equal-key op order diverges between runs",
    },
    "set-iteration": {
        "severity": "warn",
        "summary": "iteration over a set feeding sim/history state: "
                   "order is hash-seed dependent",
        "incident": "replay equality (checkpoint/resume, scan-vs-run) "
                    "requires deterministic iteration order",
    },
    "wall-clock": {
        "severity": "warn",
        "summary": "time.time()/datetime.now() in a replayed path "
                   "(virtual time must come from the round counter)",
        "incident": "wall-clock reads make checkpoint/resume histories "
                    "diverge byte-wise",
    },
    "unseeded-random": {
        "severity": "error",
        "summary": "module-level random.* call (unseeded global RNG) in "
                   "a replayed path; use a seeded random.Random",
        "incident": "nemesis/generator decisions must replay identically "
                    "from the same seed on both paths",
    },
    "thread-shared-mutation": {
        "severity": "warn",
        "summary": "unguarded assignment to an attribute that a worker "
                   "thread of the same class also reads (no enclosing "
                   "`with self.<lock>:` block)",
        "incident": "the checker pipeline / checkpoint writer / "
                    "telemetry session all pair a worker thread with "
                    "main-thread readers; a torn or lost update "
                    "surfaces only under scheduler jitter",
    },
    "fingerprint-coverage": {
        "severity": "error",
        "summary": "a core.DEFAULTS key is neither in FINGERPRINT_KEYS "
                   "nor allowlisted in checkpoint.FINGERPRINT_EXEMPT "
                   "(or the two lists contradict)",
        "incident": "a new CLI knob that changes the compiled schedule "
                    "but skips the fingerprint lets a checkpoint resume "
                    "into a different program silently",
    },
    # ---- cost-model rules (analyze/cost_model.py) ----
    "collective-on-dp": {
        "severity": "error",
        "summary": "a collective inside the round body crosses the "
                   "dp/DCN axis (dp size > 1) — per-round DCN latency "
                   "in the hot loop",
        "incident": "the multi-host leg's perf killer: dp is the "
                    "data-center network axis; round-rate collapses if "
                    "the scan body synchronizes across it every round",
    },
    "carry-growth": {
        "severity": "warn",
        "summary": "scan/while carry bytes exceed the per-program "
                   "budget declared in analyze/cost_baseline.json",
        "incident": "the carry is resident for the whole stretch; "
                    "silent carry growth is how HBM headroom erodes "
                    "release over release",
    },
    "hbm-overflow": {
        "severity": "error",
        "summary": "predicted peak live-buffer footprint (donation "
                   "credited) exceeds the device profile's HBM",
        "incident": "an OOM found at trace time instead of on the first "
                    "pod dispatch",
    },
    "intensity-regression": {
        "severity": "warn",
        "summary": "predicted msgs/s under the baseline profile dropped "
                   "more than tolerance_pct vs the checked-in "
                   "analyze/cost_baseline.json",
        "incident": "the static analogue of a bench regression: catches "
                    "a round body quietly gaining FLOPs/bytes before "
                    "any hardware run",
    },
}


@dataclass
class Finding:
    """One hazard site. `where` is display-precise
    (``relpath:line (function)``); `key` is the line-free baseline
    grouping key (``relpath:function``) so baselines survive unrelated
    line drift."""
    rule: str
    where: str
    key: str
    detail: str = ""
    entry: str = ""                 # traced entry point / "source-lint"
    entries: list = field(default_factory=list)

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, {}).get("severity", "error")

    def as_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "where": self.where, "key": self.key,
                "detail": self.detail,
                "entries": sorted(set(self.entries or [self.entry]))}


def dedupe_sites(findings: list[Finding]) -> list[Finding]:
    """Collapses per-entry duplicates (the same source site traced in
    round_fn, scan_fn, and the journal/mesh variants) into one site
    finding that remembers every entry it appeared in."""
    by_site: dict[tuple, Finding] = {}
    for f in findings:
        site = (f.rule, f.where, f.detail)
        cur = by_site.get(site)
        if cur is None:
            cur = Finding(rule=f.rule, where=f.where, key=f.key,
                          detail=f.detail, entry=f.entry,
                          entries=[f.entry] if f.entry else [])
            by_site[site] = cur
        elif f.entry and f.entry not in cur.entries:
            cur.entries.append(f.entry)
    return sorted(by_site.values(), key=lambda f: (f.rule, f.where))


# ---------------------------------------------------------------------------
# Baseline: checked-in deliberate exceptions. Suppressions group by
# (rule, relpath:function) with a max_sites budget, so unrelated line
# drift never breaks CI but a NEW hazard in the same function (one more
# site than budgeted) does.
# ---------------------------------------------------------------------------

def baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass
class Baseline:
    suppressions: list = field(default_factory=list)

    @classmethod
    def load(cls, path: str | None = None) -> "Baseline":
        path = path or baseline_path()
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(suppressions=list(data.get("suppressions", ())))

    def budget(self, rule: str, key: str):
        for s in self.suppressions:
            if s.get("rule") == rule and s.get("where") == key:
                return s
        return None


def apply_baseline(sites: list[Finding], baseline: Baseline):
    """Splits deduped site findings into (new, suppressed). A
    suppression covers up to `max_sites` distinct sites of its rule in
    its function; extra sites mean something NEW appeared there and the
    whole group is surfaced (we cannot tell old from new without line
    numbers, and re-baselining is explicit)."""
    groups: dict[tuple, list[Finding]] = {}
    for f in sites:
        groups.setdefault((f.rule, f.key), []).append(f)
    new, suppressed = [], []
    for (rule, key), group in sorted(groups.items()):
        s = baseline.budget(rule, key)
        if s is not None and len(group) <= int(s.get("max_sites", 1)):
            suppressed.extend(group)
        elif s is not None:
            for f in group:
                f.detail = (f.detail + " " if f.detail else "") + \
                    f"[exceeds baseline max_sites={s.get('max_sites', 1)}]"
            new.extend(group)
        else:
            new.extend(group)
    return new, suppressed


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

@dataclass
class AuditReport:
    new: list = field(default_factory=list)          # [Finding]
    suppressed: list = field(default_factory=list)   # [Finding]
    entries: list = field(default_factory=list)      # audited entry points
    notes: list = field(default_factory=list)
    wall_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.new

    def rule_counts(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.new + self.suppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))

    def as_dict(self) -> dict:
        return {"ok": self.ok,
                "rules": self.rule_counts(),
                "new": [f.as_dict() for f in self.new],
                "suppressed": [f.as_dict() for f in self.suppressed],
                "suppressed-count": len(self.suppressed),
                "entries": list(self.entries),
                "notes": list(self.notes),
                "wall-s": round(self.wall_s, 3)}

    def render_text(self) -> str:
        lines = [f"static audit: {len(self.entries)} entries traced, "
                 f"{len(self.new)} new finding(s), "
                 f"{len(self.suppressed)} baselined, "
                 f"{self.wall_s:.1f}s"]
        for note in self.notes:
            lines.append(f"  note: {note}")
        for f in self.new:
            meta = RULES.get(f.rule, {})
            lines.append(f"\nNEW [{f.severity}] {f.rule} @ {f.where}")
            lines.append(f"  {meta.get('summary', '')}")
            if f.detail:
                lines.append(f"  detail: {f.detail}")
            if f.entries:
                lines.append(f"  seen in: {', '.join(sorted(f.entries))}")
            if meta.get("incident"):
                lines.append(f"  incident: {meta['incident']}")
        if self.suppressed:
            lines.append("\nbaselined:")
            for f in self.suppressed:
                lines.append(f"  [{f.rule}] {f.where}")
        lines.append("\nresult: " + ("CLEAN (no new findings)" if self.ok
                                     else f"{len(self.new)} NEW finding(s)"))
        return "\n".join(lines)

    def write_baseline(self, path: str | None = None) -> str:
        """Regenerates baseline.json covering every current site.
        Reasons for pre-existing entries are preserved; new entries get
        a FIXME reason the author must edit."""
        path = path or baseline_path()
        old = Baseline.load(path)
        groups: dict[tuple, int] = {}
        for f in self.new + self.suppressed:
            groups[(f.rule, f.key)] = groups.get((f.rule, f.key), 0) + 1
        suppressions = []
        for (rule, key), n in sorted(groups.items()):
            prev = old.budget(rule, key) or {}
            suppressions.append({
                "rule": rule, "where": key, "max_sites": n,
                "reason": prev.get("reason",
                                   "FIXME: justify this exception")})
        with open(path, "w") as f:
            json.dump({"version": 1, "suppressions": suppressions}, f,
                      indent=2)
            f.write("\n")
        return path


# ---------------------------------------------------------------------------
# Fingerprint coverage (satellite of the cost auditor PR): every
# core.DEFAULTS key must either pin the checkpoint fingerprint
# (checkpoint.FINGERPRINT_KEYS) or be explicitly allowlisted with a
# reason (checkpoint.FINGERPRINT_EXEMPT). A new CLI knob that changes
# the compiled schedule cannot silently skip resume pinning.
# ---------------------------------------------------------------------------

def check_fingerprint_coverage() -> list[Finding]:
    from .. import core
    from ..checkpoint import FINGERPRINT_EXEMPT, FINGERPRINT_KEYS
    out: list[Finding] = []
    fp = set(FINGERPRINT_KEYS)
    exempt = set(FINGERPRINT_EXEMPT)
    for k in sorted(set(core.DEFAULTS) - fp - exempt):
        out.append(Finding(
            rule="fingerprint-coverage", entry="source-lint",
            where=f"maelstrom_tpu/core.py DEFAULTS[{k!r}]",
            key=f"maelstrom_tpu/core.py:DEFAULTS.{k}",
            detail=f"{k!r} is neither in FINGERPRINT_KEYS nor "
                   f"allowlisted in checkpoint.FINGERPRINT_EXEMPT"))
    for k in sorted(fp & exempt):
        out.append(Finding(
            rule="fingerprint-coverage", entry="source-lint",
            where=f"maelstrom_tpu/checkpoint.py FINGERPRINT_EXEMPT"
                  f"[{k!r}]",
            key=f"maelstrom_tpu/checkpoint.py:FINGERPRINT_EXEMPT.{k}",
            detail=f"{k!r} is both fingerprinted and allowlisted — "
                   f"the lists contradict"))
    for k in sorted(exempt - set(core.DEFAULTS)):
        out.append(Finding(
            rule="fingerprint-coverage", entry="source-lint",
            where=f"maelstrom_tpu/checkpoint.py FINGERPRINT_EXEMPT"
                  f"[{k!r}]",
            key=f"maelstrom_tpu/checkpoint.py:FINGERPRINT_EXEMPT.{k}",
            detail=f"allowlist entry {k!r} is not a core.DEFAULTS key "
                   f"(stale)"))
    return out


# ---------------------------------------------------------------------------
# Top-level drivers
# ---------------------------------------------------------------------------

def run_audit(programs=None, mesh: str | None = "auto",
              jaxpr: bool = True, lint: bool = True,
              baseline: str | None = None,
              fleet: bool = True) -> AuditReport:
    """The full gate: trace the production step functions for every
    requested workload (plus the `--mesh` variants when enough devices
    are visible, plus the vmapped `--fleet` scan/round variants unless
    `fleet=False`), lint the hot host modules, and split the deduped
    findings against the checked-in baseline."""
    t0 = time.perf_counter()
    report = AuditReport()
    raw: list[Finding] = []
    if jaxpr:
        from . import jaxpr_audit
        fs, entries, notes = jaxpr_audit.audit_production(
            programs=programs, mesh=mesh, fleet=fleet)
        raw += fs
        report.entries += entries
        report.notes += notes
    if lint:
        from . import source_lint
        raw += source_lint.lint_default_paths()
        raw += check_fingerprint_coverage()
        report.entries.append("source-lint")
    sites = dedupe_sites(raw)
    report.new, report.suppressed = apply_baseline(
        sites, Baseline.load(baseline))
    report.wall_s = time.perf_counter() - t0
    return report


_runner_audit_memo: dict = {}


def _runner_audit(cfg_key_fn, steps_fn, trace: bool,
                  extra_fn=lambda: {}) -> dict:
    """Shared body of `audit_runner`/`audit_fleet_runner`: memoized per
    config key, jaxpr-traces the runner's own step functions via
    `steps_fn` when tracing is on, lints the installed hot modules,
    applies the runtime config rule (donation-cpu-view — the PR 2/4 CPU
    zero-copy hazard), and splits the deduped findings against the
    checked-in baseline. Never raises: an audit failure must not fail a
    production run (the callables are evaluated inside the guard)."""
    t0 = time.perf_counter()
    try:
        import jax

        from ..sim import donation_enabled
        cfg_key = cfg_key_fn()
        cached = _runner_audit_memo.get(cfg_key)
        if cached is not None:
            out = dict(cached)
            out["wall-s"] = round(time.perf_counter() - t0, 3)
            out["memoized"] = True
            return out
        raw: list[Finding] = []
        notes: list[str] = []
        if trace:
            fs, _entries, notes = steps_fn()
            raw += fs
        from . import source_lint
        raw += source_lint.lint_default_paths()
        raw += check_fingerprint_coverage()
        if donation_enabled() and jax.default_backend() == "cpu":
            raw.append(Finding(
                rule="donation-cpu-view", entry="runtime-config",
                where="sim.donation_enabled (MAELSTROM_DONATE forced on, "
                      "cpu backend)",
                key="maelstrom_tpu/sim.py:donation_enabled"))
        new, suppressed = apply_baseline(dedupe_sites(raw),
                                         Baseline.load())
        counts: dict[str, int] = {}
        for f in new + suppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        out = {"ok": not new,
               "rules": dict(sorted(counts.items())),
               "new": [f.as_dict() for f in new],
               "suppressed-count": len(suppressed),
               "traced": bool(trace),
               **extra_fn()}
        if notes:
            out["notes"] = notes
        _runner_audit_memo[cfg_key] = dict(out)
        out["wall-s"] = round(time.perf_counter() - t0, 3)
        return out
    except Exception as e:       # the audit must never fail a real run
        return {"ok": None, "audit-error": repr(e),
                "wall-s": round(time.perf_counter() - t0, 3)}


def audit_runner(runner, trace: bool = True) -> dict:
    """The production self-report block (`static-audit` in results.json,
    surfaced via TpuNetStats): audits the runner's OWN program/config —
    jaxpr trace of its step functions under its actual donation/sharding
    settings, source lint of the installed hot modules, and the runtime
    config rules (donation-cpu-view). Memoized per config so repeated
    runs in one process (test suites) pay the trace once. Never raises:
    an audit failure must not fail a production run."""
    from ..sim import donation_enabled

    def steps():
        from . import jaxpr_audit
        return jaxpr_audit.audit_runner_steps(runner)
    return _runner_audit(
        lambda: (type(runner.program).__name__, repr(runner.cfg),
                 runner._shardings is not None, bool(trace),
                 # continuous runs trace the sched-inject variant: a
                 # round-synchronous trace must not satisfy them
                 getattr(runner, "continuous", False),
                 donation_enabled()),
        steps, trace)


def audit_fleet_runner(runner, trace: bool = True) -> dict:
    """The fleet-level `static-audit` results block: ONE audit of the
    vmapped fleet step functions shared by every cluster (per-cluster
    blocks would repeat the identical trace F times). Same contract as
    `audit_runner`: memoized per config, never raises."""
    from ..sim import donation_enabled

    def steps():
        from . import jaxpr_audit
        return jaxpr_audit.audit_fleet_runner_steps(runner)
    return _runner_audit(
        lambda: ("fleet", type(runner.program).__name__,
                 repr(runner.cfg), runner.spec.fleet,
                 runner._shardings is not None, bool(trace),
                 getattr(runner, "continuous", False),
                 donation_enabled()),
        steps, trace, extra_fn=lambda: {"fleet": runner.spec.fleet})


# ---------------------------------------------------------------------------
# Cost self-report blocks (the `cost` sub-block beside `static-audit`
# in results.json — doc/analyze.md "cost model")
# ---------------------------------------------------------------------------

_runner_cost_memo: dict = {}


def _runner_cost(cfg_key_fn, specs_fn, trace: bool, profile,
                 extra_fn=lambda: {}) -> dict:
    """Shared body of `cost_runner`/`cost_fleet_runner`: memoized per
    (profile, config) key, costs the runner's own entry points when
    tracing is on, and applies the structural cost rules (carry-growth
    / hbm-overflow / collective-on-dp; NO baseline regression — the
    self-report entry tags differ from the production baseline's).
    Never raises: a cost-model failure must not fail a real run."""
    t0 = time.perf_counter()
    try:
        from . import cost_model
        prof = cost_model.resolve_profile(profile)
        cfg_key = (prof.name,) + tuple(cfg_key_fn())
        cached = _runner_cost_memo.get(cfg_key)
        if cached is not None:
            out = dict(cached)
            out["wall-s"] = round(time.perf_counter() - t0, 3)
            out["memoized"] = True
            return out
        records: dict = {}
        findings: list[Finding] = []
        if trace:
            for spec in specs_fn():
                records[spec.name] = cost_model.cost_step(spec, prof)
            findings = cost_model.cost_findings(records, baseline={},
                                                profile=prof)
        out = {"ok": (not findings) if trace else None,
               "profile": prof.name,
               "records": {k: records[k] for k in sorted(records)},
               "findings": [f.as_dict() for f in findings],
               "traced": bool(trace),
               **extra_fn()}
        _runner_cost_memo[cfg_key] = dict(out)
        out["wall-s"] = round(time.perf_counter() - t0, 3)
        return out
    except Exception as e:     # the cost block must never fail a run
        return {"ok": None, "cost-error": repr(e),
                "wall-s": round(time.perf_counter() - t0, 3)}


def cost_runner(runner, trace: bool = True, profile=None) -> dict:
    """The production cost self-report block (`cost` in results.json,
    beside `static-audit`): per-round FLOPs/bytes/collective totals and
    roofline predictions for the runner's OWN entry points under the
    active device profile. Memoized per config; never raises."""
    from ..sim import donation_enabled

    def specs():
        from . import jaxpr_audit
        return jaxpr_audit.runner_step_specs(runner)
    return _runner_cost(
        lambda: (type(runner.program).__name__, repr(runner.cfg),
                 runner._shardings is not None, bool(trace),
                 getattr(runner, "continuous", False),
                 donation_enabled()),
        specs, trace, profile)


def cost_fleet_runner(runner, trace: bool = True, profile=None) -> dict:
    """The fleet-level `cost` results block: ONE costing of the vmapped
    fleet dispatch shared by every cluster. Same contract as
    `cost_runner`."""
    from ..sim import donation_enabled

    def specs():
        from . import jaxpr_audit
        return jaxpr_audit.fleet_runner_step_specs(runner)
    return _runner_cost(
        lambda: ("fleet", type(runner.program).__name__,
                 repr(runner.cfg), runner.spec.fleet,
                 runner._shardings is not None, bool(trace),
                 getattr(runner, "continuous", False),
                 donation_enabled()),
        specs, trace, profile,
        extra_fn=lambda: {"fleet": runner.spec.fleet})
