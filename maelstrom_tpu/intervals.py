"""Integer interval sets.

A dependency-free replacement for Guava's TreeRangeSet as used by the
pn-counter checker (reference `workload/pn_counter.clj:60-125`): a set of
disjoint *closed* integer ranges supporting union, shifting by a delta, and
membership. The reference uses open ranges (lower-1, upper+1) so adjacent
ranges coalesce on insert (`pn_counter.clj:72-77`); here we keep closed
ranges and merge when ranges overlap or touch (hi + 1 >= next lo), which is
equivalent.
"""

from __future__ import annotations

from bisect import bisect_left


class IntervalSet:
    """A sorted set of disjoint closed integer intervals [lo, hi]."""

    def __init__(self, ranges=()):
        self.ranges: list[tuple[int, int]] = []
        for lo, hi in ranges:
            self.add(lo, hi)

    def add(self, lo: int, hi: int) -> "IntervalSet":
        """Insert closed range [lo, hi], coalescing overlapping or adjacent
        ranges (the TreeRangeSet open-range merge trick,
        `pn_counter.clj:72-77`)."""
        assert lo <= hi
        new = []
        placed = False
        for a, b in self.ranges:
            if b + 1 < lo:          # entirely left of new range
                new.append((a, b))
            elif hi + 1 < a:        # entirely right: emit pending new range
                if not placed:
                    new.append((lo, hi))
                    placed = True
                new.append((a, b))
            else:                   # overlaps/touches: absorb
                lo = min(lo, a)
                hi = max(hi, b)
        if not placed:
            new.append((lo, hi))
        self.ranges = new
        return self

    def shift(self, delta: int) -> "IntervalSet":
        """A new IntervalSet with every range translated by delta."""
        s = IntervalSet()
        s.ranges = [(a + delta, b + delta) for a, b in self.ranges]
        return s

    def union(self, other: "IntervalSet") -> "IntervalSet":
        s = IntervalSet()
        s.ranges = list(self.ranges)
        for a, b in other.ranges:
            s.add(a, b)
        return s

    def __contains__(self, x: int) -> bool:
        i = bisect_left(self.ranges, (x + 1,)) - 1
        if i < 0:
            return False
        a, b = self.ranges[i]
        return a <= x <= b

    def to_vecs(self) -> list[list[int]]:
        """Closed [lower, upper] pairs (reference `pn_counter.clj:66-70`)."""
        return [[a, b] for a, b in self.ranges]

    def __eq__(self, other):
        return isinstance(other, IntervalSet) and self.ranges == other.ranges

    def __repr__(self):
        return f"IntervalSet({self.ranges})"
