"""Root-cause analysis for the 10 ms-latency parity deviations.

The 0.25 ms-round parity configs disprove the round-quantization
explanation: at 4x time resolution the deviations do not shrink
(grid p50 17 -> 18.75 vs reference 11). This module tests the competing
hypothesis directly on the parity runs' own histories:

    The reference's stable-latency quantiles are computed from
    wall-clock operation timestamps. An element's `known` time is the
    add's :ok completion, stamped by a JVM client thread after a
    synchronous RPC through the simulated scheduler (two thread
    handoffs and queue polls away from the moment the origin server
    actually had the value). On a laptop running 25 server handlers
    plus Jepsen's workers at rate 100, that stamp lags by milliseconds.
    This framework's virtual-clock ack is exact (within one simulation
    round). A LATER known shrinks (last_absent - known) by exactly the
    lateness — at every quantile, on every topology, at any hop scale.

Method: recompute the stock checker's stable-latency quantiles from the
stored parity histories with `known` shifted by a constant delta, and
find the delta that minimizes the total absolute deviation from the
reference's published quantiles ACROSS ALL 10 ms configs at once (one
shared constant — a per-config fit could chase noise).

Result (see artifacts/parity_known_shift.json): a single delta of
~6-8 ms aligns all 16 quantile comparisons (grid + line, 1 ms and
0.25 ms rounds) from systematic +5..+14 ms deviations down to a
residual of roughly +/-6 ms — the noise floor of single-run order
statistics. The 100 ms-latency rows never showed the offset above noise
(~7 ms against 450-800 ms quantiles), which is consistent: the offset
is absolute, not hop-scaled, so it is a property of the *measurement
clock*, not of message propagation (per-hop delivery here is exact by
construction — see tests/test_edge_oracle.py).

Run after a parity sweep:  python -m maelstrom_tpu.parity_analysis
"""

from __future__ import annotations

import glob
import json
import os
import sys

# (store-dir name, reference quantiles) for the 10 ms configs
TEN_MS_CONFIGS = {
    "parity-grid-25-10-ms": {"p50": 11, "p95": 42, "p99": 56, "max": 72},
    "parity-line-25-10-ms": {"p50": 86, "p95": 170, "p99": 193,
                             "max": 224},
    "parity-grid-25-10-ms-(0.25-ms-rounds)": {"p50": 11, "p95": 42,
                                              "p99": 56, "max": 72},
    "parity-line-25-10-ms-(0.25-ms-rounds)": {"p50": 86, "p95": 170,
                                              "p99": 193, "max": 224},
}


def quantiles_with_shift(history, shift_ms: float) -> dict:
    """The stock set-full stable-latency computation with the element's
    `known` time shifted later by `shift_ms` (modelling ack-stamp
    lateness in a wall-clock harness)."""
    pairs = history.pairs()
    attempts, acked = {}, {}
    for inv, comp in pairs:
        if inv.f != "broadcast":
            continue
        attempts[inv.value] = inv.time
        if comp is not None and comp.is_ok():
            acked[inv.value] = comp.time
    reads = []
    for inv, comp in pairs:
        if inv.f != "read" or comp is None or not comp.is_ok():
            continue
        reads.append((inv.time, comp.time, frozenset(comp.value or [])))
    reads.sort()
    lat = []
    for e in attempts:
        present = [(ti, tc) for ti, tc, els in reads if e in els]
        if e in acked:
            known = acked[e] + shift_ms * 1e6
        elif present:
            known = min(tc for ti, tc in present) + shift_ms * 1e6
        else:
            continue
        absent = [ti for ti, tc, els in reads
                  if ti > known and e not in els]
        la = max(absent, default=None)
        if la is None and not any(ti > known for ti, tc in present):
            continue                            # never-read: no verdict
        if la is not None and not any(ti > la for ti, tc in present):
            continue                            # lost (none here)
        lat.append(
            max(0, ((known if la is None else la) - known)) / 1e6)
    lat.sort()
    # the stock checker's quantile indexing, not a reimplementation
    from .checkers.set_full import quantiles
    qs = quantiles(lat, qs=(0.5, 0.95, 0.99))
    return {"p50": qs[0.5], "p95": qs[0.95], "p99": qs[0.99],
            "max": lat[-1] if lat else None}


def main(argv=None):
    from .history import History
    store = os.environ.get("PARITY_STORE", "/tmp/maelstrom-parity-store")
    out_path = os.environ.get("PARITY_SHIFT_OUT",
                              "artifacts/parity_known_shift.json")
    hists = {}
    for name in TEN_MS_CONFIGS:
        dirs = sorted(glob.glob(os.path.join(store, name, "2*")))
        if not dirs:
            print(f"missing store for {name}; run the parity sweep first",
                  file=sys.stderr)
            return 1
        with open(os.path.join(dirs[-1], "history.jsonl")) as f:
            hists[name] = History.from_jsonl(f.read())

    shifts = [round(0.5 * i, 1) for i in range(0, 25)]   # 0..12 ms
    key0 = str(shifts[0])
    table = {}
    totals = {}
    for s in shifts:
        total = 0.0
        per = {}
        for name, ref in TEN_MS_CONFIGS.items():
            qs = quantiles_with_shift(hists[name], s)
            devs = {k: round(qs[k] - ref[k], 2) for k in ref
                    if qs[k] is not None}
            per[name] = {"quantiles": qs, "abs_dev_ms": devs}
            total += sum(abs(v) for v in devs.values())
        table[str(s)] = per
        totals[str(s)] = round(total, 1)
    best = min(totals, key=lambda k: totals[k])
    out = {
        "hypothesis": "constant known-time (ack-stamp) offset between "
                      "the reference's wall-clock harness and this "
                      "framework's exact virtual-time acks",
        "shifts_ms": shifts,
        "total_abs_dev_ms_by_shift": totals,
        "best_shift_ms": float(best),
        "total_abs_dev_at_0": totals[key0],
        "total_abs_dev_at_best": totals[best],
        "detail_at_0": table[key0],
        "detail_at_best": table[best],
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({"best_shift_ms": out["best_shift_ms"],
                      "total_abs_dev_at_0": out["total_abs_dev_at_0"],
                      "total_abs_dev_at_best": out["total_abs_dev_at_best"],
                      "wrote": out_path}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
