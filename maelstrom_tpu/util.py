"""Kitchen sink utilities (reference: src/maelstrom/util.clj)."""

from __future__ import annotations

import re


def is_client(node_id: str) -> bool:
    """Is a given node id a client? (reference `util.clj:7-10`)"""
    return bool(node_id) and node_id[0] == "c"


def involves_client(message) -> bool:
    """Does a given network message involve a client? (`util.clj:12-16`)"""
    return is_client(message.src) or is_client(message.dest)


_NODE_RE = re.compile(r"(\w+?)(\d+)")


def node_sort_key(node_id: str):
    """Natural sort key for node ids: 'c2' < 'c10', services last
    (reference `util.clj:18-28`)."""
    m = _NODE_RE.fullmatch(node_id)
    if m:
        return (0, m.group(1), int(m.group(2)))
    return (1, node_id, 0)


def sort_clients(node_ids):
    """Sorts a collection of node ids naturally (`util.clj:18-28`)."""
    return sorted(node_ids, key=node_sort_key)


def majority(n: int) -> int:
    """Smallest majority of n."""
    return n // 2 + 1


def honor_jax_platforms():
    """Re-asserts the JAX_PLATFORMS env var as jax config. Some images
    register an experimental backend from sitecustomize and programmatically
    override the env var (e.g. tunneled-TPU 'axon'); calling this makes the
    user's choice win again. The CLI calls it at startup; library users
    embedding maelstrom_tpu can call it before building simulations."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def xla_device_count_flags(flags: str, n_devices: int) -> str:
    """Returns `flags` with `--xla_force_host_platform_device_count`
    set to `n_devices` (replacing any existing setting). Shared by
    `force_virtual_cpu_mesh` and the crash-soak harness's subprocess
    environment so the flag handling cannot diverge."""
    opt = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        return re.sub(r"--xla_force_host_platform_device_count=\d+",
                      opt, flags)
    return (flags + " " + opt).strip()


def force_virtual_cpu_mesh(n_devices: int):
    """Puts this process on n_devices virtual CPU devices, defeating any
    sitecustomize backend override: env vars must be set before jax's
    first backend creation, and jax.config must be re-asserted after
    import (the env var alone cannot win against a programmatic
    override). One-way switch for the whole process — call it before any
    jax work, never before TPU work. Used by tests/conftest.py and the
    driver's `dryrun_multichip` entry."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = xla_device_count_flags(
        os.environ.get("XLA_FLAGS", ""), n_devices)

    honor_jax_platforms()

    import jax
    try:
        n_got = len(jax.devices("cpu"))
    except RuntimeError as e:  # backends cached without a cpu entry
        n_got, cause = 0, e
    else:
        cause = None
    if n_got < n_devices:
        raise RuntimeError(
            f"need {n_devices} cpu devices, got {n_got}: jax was "
            "initialized before force_virtual_cpu_mesh could set "
            "xla_force_host_platform_device_count") from cause
