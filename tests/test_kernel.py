"""Unit tests for the pure-Python kernel: errors, schema, intervals,
history, and the fixture-tested checkers (reference test strategy §4:
checkers are pure functions of histories)."""

import pytest

from maelstrom_tpu import errors, schema, util
from maelstrom_tpu.history import History, Op
from maelstrom_tpu.intervals import IntervalSet
from maelstrom_tpu.checkers import Compose, Stats, merge_valid
from maelstrom_tpu.checkers.pn_counter import PNCounterChecker
from maelstrom_tpu.checkers.echo import EchoChecker
from maelstrom_tpu.checkers.set_full import SetFullChecker


# --- errors ---

def test_error_registry_codes():
    # The standard error table (reference client.clj:57-100)
    assert errors.ERROR_REGISTRY[0].name == "timeout"
    assert not errors.ERROR_REGISTRY[0].definite
    assert errors.ERROR_REGISTRY[1].definite
    assert errors.ERROR_REGISTRY[13].name == "crash"
    assert not errors.ERROR_REGISTRY[13].definite
    assert errors.ERROR_REGISTRY[14].definite


def test_duplicate_error_raises():
    with pytest.raises(errors.DuplicateError):
        errors.deferror(0, "other-name", "different doc")


def test_rpc_error():
    e = errors.RPCError(14, {"text": "nope"})
    assert e.definite and e.name == "abort"
    t = errors.Timeout()
    assert not t.definite and t.code == 0


# --- util ---

def test_client_ids():
    assert util.is_client("c1") and not util.is_client("n1")
    assert util.sort_clients(["c10", "c2", "lin-kv", "c1"]) == \
        ["c1", "c2", "c10", "lin-kv"]


# --- schema ---

def test_schema_check():
    s = {"type": schema.Eq("echo"), "echo": schema.Any, "msg_id": int}
    assert schema.check(s, {"type": "echo", "echo": [1], "msg_id": 3}) is None
    assert schema.check(s, {"type": "echo", "msg_id": 3}) == \
        {"echo": "missing required key"}
    assert schema.check(s, {"type": "nope", "echo": 1, "msg_id": 3})
    assert schema.check(s, {"type": "echo", "echo": 1, "msg_id": "x"})
    # disallowed extra keys
    assert schema.check(s, {"type": "echo", "echo": 1, "msg_id": 3, "z": 1})


def test_schema_tuple_either():
    micro = schema.Either(
        schema.Tup(schema.Eq("r"), schema.Any, schema.Eq(None)),
        schema.Tup(schema.Eq("append"), schema.Any, schema.Any))
    assert schema.check([micro], [["r", 5, None], ["append", 5, 3]]) is None
    assert schema.check([micro], [["r", 5, 3]])  # read with value: invalid


def test_schema_map_of_any_keys():
    s = {str: [str]}
    assert schema.check(s, {"n0": ["n1"], "n1": ["n0"]}) is None
    assert schema.check(s, {"n0": "n1"})


# --- intervals ---

def test_interval_set_merge_adjacent():
    s = IntervalSet([(0, 0)])
    s.add(1, 2)
    assert s.to_vecs() == [[0, 2]]
    s.add(5, 6)
    assert s.to_vecs() == [[0, 2], [5, 6]]
    s.add(3, 4)
    assert s.to_vecs() == [[0, 6]]
    assert 4 in s and 7 not in s and -1 not in s


def test_interval_shift_union():
    s = IntervalSet([(5, 5)])
    s2 = s.union(s.shift(3))
    assert s2.to_vecs() == [[5, 5], [8, 8]]


# --- pn-counter checker (fixtures from the reference's own unit test,
# test/maelstrom/workload/pn_counter_test.clj:7-36) ---

def check_pn(history):
    return PNCounterChecker().check({}, history)


def test_pn_counter_empty():
    r = check_pn([])
    assert r == {"valid": True, "errors": None, "final-reads": [],
                 "acceptable": [[0, 0]]}


def test_pn_counter_definite():
    r = check_pn([
        {"type": "ok", "f": "add", "value": 2},
        {"type": "ok", "f": "add", "value": 3},
        {"type": "ok", "f": "read", "final": True, "value": 5},
        {"type": "ok", "f": "read", "final": True, "value": 4},
    ])
    assert r["valid"] is False
    assert r["final-reads"] == [5, 4]
    assert r["acceptable"] == [[5, 5]]
    assert len(r["errors"]) == 1 and r["errors"][0]["value"] == 4


def test_pn_counter_indefinite():
    r = check_pn([
        {"type": "ok", "f": "add", "value": 10},
        {"type": "info", "f": "add", "value": 5},
        {"type": "info", "f": "add", "value": -1},
        {"type": "info", "f": "add", "value": -1},
        {"type": "ok", "f": "read", "final": True, "value": 11},
        {"type": "ok", "f": "read", "final": True, "value": 15},
    ])
    assert r["valid"] is False
    assert r["final-reads"] == [11, 15]
    assert r["acceptable"] == [[8, 10], [13, 15]]
    assert [e["value"] for e in r["errors"]] == [11]


# --- echo checker ---

def test_echo_checker():
    h = [
        {"type": "invoke", "f": "echo", "value": "hi", "process": 0, "time": 0},
        {"type": "ok", "f": "echo", "value": {"type": "echo_ok", "echo": "hi"},
         "process": 0, "time": 1},
    ]
    assert EchoChecker().check({}, h)["valid"] is True
    h[1]["value"] = {"type": "echo_ok", "echo": "bye"}
    assert EchoChecker().check({}, h)["valid"] is False


# --- set-full checker ---

MS = 1_000_000  # ns per ms


def _add(p, t, v, ok=True):
    return [
        {"type": "invoke", "f": "add", "value": v, "process": p, "time": t},
        {"type": "ok" if ok else "info", "f": "add", "value": v,
         "process": p, "time": t + MS},
    ]


def _read(p, t, els, final=False):
    return [
        {"type": "invoke", "f": "read", "value": None, "process": p,
         "time": t},
        {"type": "ok", "f": "read", "value": els, "process": p,
         "time": t + MS, "final": final},
    ]


def test_set_full_stable():
    h = (_add(0, 0, 1) + _add(0, 2 * MS, 2) +
         _read(1, 10 * MS, [1, 2], final=True))
    r = SetFullChecker().check({}, h)
    assert r["valid"] is True
    assert r["stable-count"] == 2 and r["lost-count"] == 0


def test_set_full_lost():
    h = (_add(0, 0, 1) + _add(0, 2 * MS, 2) +
         _read(1, 10 * MS, [1], final=True))
    r = SetFullChecker().check({}, h)
    assert r["valid"] is False
    assert r["lost"] == [2]


def test_set_full_unacked_absent_ok():
    # An indeterminate add that never shows up makes no claim
    h = (_add(0, 0, 1) + _add(0, 2 * MS, 2, ok=False) +
         _read(1, 10 * MS, [1], final=True))
    r = SetFullChecker().check({}, h)
    assert r["valid"] is True


def test_set_full_stale_then_stable():
    # Element 1 acked at ~1ms, missing from a read at 5ms, present at 20ms:
    # stale but stable.
    h = (_add(0, 0, 1) + _read(1, 5 * MS, []) + _read(1, 20 * MS, [1]))
    r = SetFullChecker().check({}, h)
    assert r["valid"] is True
    assert r["stale"] == [1] and r["stable-count"] == 1


def test_set_full_no_reads_unknown():
    r = SetFullChecker().check({}, _add(0, 0, 1))
    assert r["valid"] == "unknown"


# --- compose / stats ---

def test_compose_and_stats():
    h = (_add(0, 0, 1) + _read(1, 5 * MS, [1], final=True))
    c = Compose({"set": SetFullChecker(), "stats": Stats()})
    r = c.check({}, h)
    assert r["valid"] is True
    assert r["stats"]["by-f"]["add"]["ok-count"] == 1
    assert merge_valid([True, "unknown"]) == "unknown"
    assert merge_valid([True, False, "unknown"]) is False


# --- history pairing ---

def test_history_pairs():
    h = History([
        Op(type="invoke", f="read", process=0, time=0),
        Op(type="invoke", f="read", process=1, time=1),
        Op(type="ok", f="read", process=1, time=2),
        Op(type="info", f="read", process=0, time=3),
    ])
    pairs = h.pairs()
    assert len(pairs) == 2
    assert pairs[0][1].type == "info" and pairs[1][1].type == "ok"
    # JSON round-trip
    h2 = History.from_jsonl(h.to_jsonl())
    assert [o.to_dict() for o in h2] == [o.to_dict() for o in h]


def test_public_api_lazy_exports():
    import maelstrom_tpu as m
    assert callable(m.run) and callable(m.build_test)
    assert m.History and m.Op and m.Journal and m.SyncClient
    assert m.HostNet
    assert set(m._EXPORTS) <= set(dir(m))
    assert callable(m.fuzz_broadcast) and callable(m.honor_jax_platforms)
    assert m.__version__
    import pytest
    with pytest.raises(AttributeError):
        m.no_such_thing


def test_package_import_is_lazy():
    """`import maelstrom_tpu` must not pull in jax/numpy (-S bypasses the
    image's sitecustomize, which preloads jax and would mask this)."""
    import subprocess, sys
    code = ("import sys; sys.path.insert(0, '.'); import maelstrom_tpu; "
            "assert 'jax' not in sys.modules, 'jax imported eagerly'; "
            "assert 'numpy' not in sys.modules, 'numpy imported eagerly'")
    subprocess.run([sys.executable, "-S", "-c", code], check=True,
                   cwd=__import__('os').path.dirname(
                       __import__('os').path.dirname(
                           __import__('os').path.abspath(__file__))))
