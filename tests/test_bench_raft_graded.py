"""The raft cluster-grading pipeline (maelstrom_tpu.bench_raft_graded) at
CI scale: sampled vmapped clusters driven with real contending client
traffic, per-cluster histories graded by the stock WGL linearizability
checker — the grading half of the 10k-cluster benchmark config."""

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def test_raft_clusters_graded_small():
    from maelstrom_tpu.bench_raft_graded import run_raft_graded

    s = run_raft_graded(n_clusters=24, sample=6, ops_per_client=6,
                        chunk=10, verbose=False)
    assert s["sampled_clusters"] == 6
    assert s["all_linearizable"] is True, s
    # the traffic was real: two workers contended on a shared register
    assert s["workers_per_cluster"] == 2
    assert s["indeterminate_ops"] <= 2, s


def test_raft_clusters_graded_under_partition():
    """The reference's flagship test shape: lin-kv + partition nemesis.
    Every cluster gets a majority/minority split mid-run; histories must
    stay linearizable (ops may go indeterminate, never inconsistent),
    and the final reads land after the heal."""
    from maelstrom_tpu.bench_raft_graded import run_raft_graded

    s = run_raft_graded(n_clusters=24, sample=6, ops_per_client=8,
                        chunk=10, partition_at=2, partition_chunks=6,
                        verbose=False)
    assert s["all_linearizable"] is True, s
    assert s["partition"]["rounds"] == 60
    assert s["partition"]["clusters_partitioned"] == 24
