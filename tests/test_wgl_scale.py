"""Knossos-scale WGL: long per-key histories must check definitively.

The round-3 checker returned "unknown" above 600 ops per key
(VERDICT r3 missing item 3); the just-in-time configuration form must
handle thousands-of-ops histories from the graded configs — bounded
worker concurrency, mixed read/write/cas, indeterminate ops from
timeouts — in seconds, with no "unknown" escape hatch.
"""

import random
import time

from maelstrom_tpu.checkers.linearizable import (INF,
                                                 check_register_history)


def _simulate(n_ops: int, workers: int, seed: int, info_rate: float = 0.02):
    """A real linearizable schedule: a hidden register serializes ops at
    a random point inside each op's [inv, ret] window; concurrent ops
    overlap via per-worker clocks. Some completions are dropped to
    indeterminate (ret=INF), mimicking RPC timeouts."""
    rng = random.Random(seed)
    reg = None
    clock = 0.0
    ops = []
    open_until = [0.0] * workers
    for _ in range(n_ops):
        w = rng.randrange(workers)
        inv = max(open_until[w], clock) + rng.random()
        lin = inv + rng.random()            # serialization point
        ret = lin + rng.random()
        open_until[w] = ret
        clock = inv                          # invocations march forward
        kind = rng.random()
        if kind < 0.45:
            f, val = "read", None
        elif kind < 0.8:
            f, val = "write", rng.randrange(6)
        else:
            f, val = "cas", (rng.randrange(6), rng.randrange(6))
        # apply at lin
        if f == "read":
            val = reg
            ok = val is not None             # read of empty: model as ok
            if reg is None:
                continue                      # skip empty-register reads
        elif f == "write":
            reg = val
            ok = True
        else:
            frm, to = val
            ok = reg == frm
            if ok:
                reg = to
            else:
                continue                      # failed cas: excluded anyway
        if rng.random() < info_rate:
            ops.append({"f": f, "value": val, "inv": inv, "ret": INF,
                        "ok": False})
        else:
            ops.append({"f": f, "value": val, "inv": inv, "ret": ret,
                        "ok": True})
    return ops


def test_long_valid_history_checks_definitively():
    ops = _simulate(5_000, workers=4, seed=1)
    assert len(ops) > 3_000
    t0 = time.perf_counter()
    r = check_register_history(ops)
    dt = time.perf_counter() - t0
    assert r["valid"] is True
    assert dt < 60, f"5k-op check took {dt:.1f}s"


def test_long_invalid_history_detected():
    ops = _simulate(3_000, workers=4, seed=2, info_rate=0.0)
    # corrupt one late read: claim a value the register never held there
    for o in reversed(ops):
        if o["f"] == "read":
            o["value"] = 99
            break
    r = check_register_history(ops)
    assert r["valid"] is False


def test_concurrent_window_history():
    # heavier concurrency: 16 workers, overlapping windows
    ops = _simulate(2_000, workers=16, seed=3)
    r = check_register_history(ops)
    assert r["valid"] is True


def test_no_unknown_below_cap():
    # the old implementation returned "unknown" above 600 ops; any
    # verdict other than True/False here is a regression
    ops = _simulate(1_200, workers=2, seed=4)
    r = check_register_history(ops)
    assert r["valid"] in (True, False)
    assert r["valid"] is True
