"""The unique-ids workload (doc/tutorial/09-workloads.md's worked
example): checker unit tests on literal histories — legal, forged-
duplicate, and vacuous — plus the batched program's same-round minting
rank logic."""

import jax.numpy as jnp

from maelstrom_tpu.checkers.unique_ids import UniqueIdsChecker
from maelstrom_tpu.history import History, Op


def _h(ops):
    return History([Op(**o) for o in ops])


def _gen(process, t, value, type="ok"):
    return [
        {"type": "invoke", "f": "generate", "process": process,
         "time": t, "value": None},
        {"type": type, "f": "generate", "process": process,
         "time": t + 1, "value": value},
    ]


def test_distinct_ids_valid():
    ops = _gen(0, 0, "n0-1") + _gen(1, 10, "n1-1") + _gen(0, 20, "n0-2")
    r = UniqueIdsChecker().check({}, _h(ops), {})
    assert r["valid"] is True
    assert r["distinct-count"] == 3


def test_duplicate_named_with_witness():
    ops = _gen(0, 0, 12345) + _gen(1, 10, 777) + _gen(2, 20, 12345)
    r = UniqueIdsChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["duplicated-count"] == 1
    (dup,) = r["duplicated"].values()
    assert [d["process"] for d in dup] == [0, 2]


def test_indeterminate_ids_unconstrained():
    # an info op's id was never observed: reissuing it is legal
    ops = _gen(0, 0, 99, type="info") + _gen(1, 10, 99)
    r = UniqueIdsChecker().check({}, _h(ops), {})
    assert r["valid"] is True


def test_vacuous_run_unknown():
    # zero observations can't certify uniqueness: "unknown" (which
    # merge_valid treats as not-valid), never a clean True
    ops = _gen(0, 0, None, type="info")
    r = UniqueIdsChecker().check({}, _h(ops), {})
    assert r["valid"] == "unknown"
    assert "error" in r


def test_batched_program_same_round_ranks():
    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program

    program = get_program("unique-ids", {}, ["n0", "n1"])
    state = program.init_state()
    inbox = T.Msgs.empty((2, 3))
    # node 0 gets two same-round requests, node 1 gets one
    inbox = inbox.replace(
        valid=jnp.asarray([[True, True, False], [True, False, False]]),
        type=jnp.full((2, 3), 10, T.I32),
        src=jnp.full((2, 3), 2, T.I32),
        mid=jnp.asarray([[5, 6, 0], [7, 0, 0]], T.I32))
    state, out = program.step(state, inbox,
                              {"round": jnp.int32(0), "key": None})
    ids = [(int(a), int(b)) for v, a, b in
           zip(out.valid.reshape(-1), out.a.reshape(-1),
               out.b.reshape(-1)) if bool(v)]
    assert len(ids) == len(set(ids)) == 3
    assert (0, 1) in ids and (0, 2) in ids and (1, 1) in ids
    assert [int(x) for x in state["counter"]] == [2, 1]
