"""Scan/loop equivalence at the sim layer: one `make_scan_fn` dispatch
(with and without the `journal_cap`/`reply_cap` device rings) and one
`make_run_fn` lax.scan must be bit-identical to stepping `make_round_fn`
round by round — same PRNG stream, same state evolution, same journal io
rows, same client replies at the same rounds. This is the contract that
lets the production runner drain extraction in large batches: the rings
must be a pure reorganization of the per-round outputs, never a
different simulation."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.net import tpu as T
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.sim import (dealias, make_round_fn, make_run_fn,
                               make_scan_fn, make_sim)

R = 12          # rounds per equivalence window


def _build(name):
    n = 4
    nodes = [f"n{i}" for i in range(n)]
    opts = {"latency": {"mean": 0}}
    if name == "broadcast":
        opts.update({"topology": "grid", "max_values": 8})
    program = get_program(name, opts, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=2, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    return program, cfg


def _inject(name, cfg):
    """One client request in round 0 (the scan applies `inject` in its
    first round; the reference loop passes the same batch)."""
    if name == "broadcast":
        from maelstrom_tpu.nodes.broadcast import T_BCAST
        typ, a = T_BCAST, 3
    else:
        from maelstrom_tpu.nodes.echo import T_ECHO
        typ, a = T_ECHO, 7
    CC = max(cfg.n_clients, 1)
    inj = T.Msgs.empty(CC)
    return inj.replace(valid=inj.valid.at[0].set(True),
                       src=inj.src.at[0].set(cfg.n_nodes),
                       dest=inj.dest.at[0].set(1),
                       type=inj.type.at[0].set(typ),
                       a=inj.a.at[0].set(a))


def _tree_eq(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _reference(program, cfg, inject, seed=3):
    """Per-round dispatch: the ground truth the compiled paths must
    reproduce bit for bit."""
    round_fn = make_round_fn(program, cfg)
    empty = T.Msgs.empty(max(cfg.n_clients, 1))
    sim = make_sim(program, cfg, seed=seed)
    ios, cms = [], []
    for i in range(R):
        sim, cm, io = round_fn(sim, inject if i == 0 else empty)
        ios.append(jax.device_get(io))
        cms.append(jax.device_get(cm))
    return jax.device_get(sim), ios, cms


@pytest.mark.parametrize("name", ["echo", "broadcast"])
def test_scan_matches_per_round(name):
    """No rings: one scan dispatch == R per-round dispatches."""
    program, cfg = _build(name)
    inject = _inject(name, cfg)
    ref_sim, _ios, _cms = _reference(program, cfg, inject)

    scan = make_scan_fn(program, cfg)
    sim = make_sim(program, cfg, seed=3)
    sim, _cm, k = scan(sim, inject, jnp.int32(R), False)
    assert int(k) == R
    _tree_eq(ref_sim, jax.device_get(sim))


@pytest.mark.parametrize("name", ["echo", "broadcast"])
def test_scan_rings_match_per_round(name):
    """With the device rings on: the collected journal io rows and the
    reply log must equal the per-round outputs exactly — same rows, same
    producing rounds — and the state must still be bit-identical."""
    program, cfg = _build(name)
    inject = _inject(name, cfg)
    ref_sim, ios, cms = _reference(program, cfg, inject)

    scan = make_scan_fn(program, cfg, journal_cap=R, reply_cap=32)
    sim = make_sim(program, cfg, seed=3)
    sim, _cm, k, rl, buf = scan(sim, inject, jnp.int32(R), False)
    assert int(k) == R
    _tree_eq(ref_sim, jax.device_get(sim))

    # journal ring rows i == round i's io tree
    buf = jax.device_get(buf)
    for i in range(R):
        _tree_eq(jax.tree.map(lambda b, i=i: b[i], buf), ios[i])

    # reply ring == the valid client msgs of each round, in order, each
    # stamped with its producing round's post-round counter
    rlog, rounds, _plog, rn = jax.device_get(rl)
    expect = []
    for i, cm in enumerate(cms):
        valid = np.asarray(cm.valid)
        for j in np.nonzero(valid)[0]:
            expect.append((i + 1, int(np.asarray(cm.mid)[j]),
                           int(np.asarray(cm.type)[j]),
                           int(np.asarray(cm.a)[j])))
    got = [(int(rounds[j]), int(rlog.mid[j]), int(rlog.type[j]),
            int(rlog.a[j])) for j in range(int(rn))]
    assert got == expect and len(got) > 0


@pytest.mark.parametrize("name", ["echo", "broadcast"])
def test_run_fn_matches_per_round(name):
    """`make_run_fn` (the bench path, donated carry) over a plan with
    the same injection == the per-round reference."""
    program, cfg = _build(name)
    inject = _inject(name, cfg)
    ref_sim, _ios, cms = _reference(program, cfg, inject)

    CC = max(cfg.n_clients, 1)
    plan = jax.tree.map(
        lambda z, f: z.at[0].set(f),
        T.Msgs.empty((R, CC)), inject)
    run_fn = make_run_fn(program, cfg, donate=True)
    sim = dealias(make_sim(program, cfg, seed=3))
    sim, counts = run_fn(sim, plan)
    _tree_eq(ref_sim, jax.device_get(sim))
    np.testing.assert_array_equal(
        np.asarray(counts),
        np.asarray([int(np.asarray(cm.valid).sum()) for cm in cms]))


def test_donated_scan_matches_and_requires_dealias(monkeypatch):
    """Donation actually engaged (it defaults off on CPU): a donated
    scan over a dealiased sim is bit-identical to the undonated one
    across chained dispatches, and a freshly-built (aliased) sim is
    rejected by XLA — the contract `dealias` exists to satisfy. Without
    forcing MAELSTROM_DONATE=1 the donation machinery would compile
    away in CI and only ever run on hardware."""
    monkeypatch.setenv("MAELSTROM_DONATE", "1")
    program, cfg = _build("echo")
    inject = _inject("echo", cfg)
    ref_sim, _ios, _cms = _reference(program, cfg, inject)

    scan = make_scan_fn(program, cfg, reply_cap=16, donate=True)
    sim = dealias(make_sim(program, cfg, seed=3))
    for _ in range(3):      # chained donated dispatches reuse buffers
        sim, _cm, k, _rl = scan(sim, inject if _ == 0 else
                                T.Msgs.empty(max(cfg.n_clients, 1)),
                                jnp.int32(R // 3), False)
    _tree_eq(ref_sim, jax.device_get(sim))

    # an aliased tree (Msgs.empty fans one buffer across fields) must
    # be refused at the donating boundary, not silently miscomputed
    with pytest.raises(Exception, match="[Dd]onate"):
        scan(make_sim(program, cfg, seed=3), inject, jnp.int32(2), False)


def test_scan_stop_on_reply_prefix():
    """stop_on_reply exits at the first reply-bearing round; the rounds
    it did execute must be the bit-identical prefix of the full run."""
    program, cfg = _build("echo")
    inject = _inject("echo", cfg)
    _ref_sim, _ios, cms = _reference(program, cfg, inject)
    first_reply = next(i for i, cm in enumerate(cms)
                       if np.asarray(cm.valid).any())

    scan = make_scan_fn(program, cfg)
    sim = make_sim(program, cfg, seed=3)
    sim, cm, k = scan(sim, inject, jnp.int32(R), True)
    assert int(k) == first_reply + 1
    _tree_eq(cms[first_reply], jax.device_get(cm))
