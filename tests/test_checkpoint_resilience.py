"""Crash-consistent checkpointing: format durability, fingerprint
drift, async-writer semantics, pipeline-aware resume, and sharded
(mesh) checkpoint/resume.

These are the fast (tier-1) companions of tests/test_checkpoint.py's
slow end-to-end determinism suite and tests/test_crash_soak.py's
subprocess kill/resume harness.
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import pytest

from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import core
from maelstrom_tpu.history import History, Op
from maelstrom_tpu.runner.tpu_runner import TpuRunner

from conftest import ops_projection as _ops


# --- format / durability units (no simulation) ---


def _mini_state(r=5):
    h = History([Op(type="invoke", f="read", value=[0, None], process=0,
                    time=10),
                 Op(type="ok", f="read", value=[0, 7], process=0,
                    time=20)])
    return {
        "fingerprint": {"seed": 0, "workload": "lin-kv"},
        "r": r,
        "sim": {"x": np.arange(3, dtype=np.int32), "y": np.float32(r)},
        "meta_blob": pickle.dumps({"r": r, "dispatches": 2, "gen": None,
                                   "pending": {}, "free": set(),
                                   "intern": None, "nemesis_rng": None}),
        "history_columns": h.snapshot_columns(),
    }


def test_save_load_roundtrip(tmp_path):
    d = str(tmp_path)
    cp.save(d, _mini_state())
    st = cp.load(d)
    assert st["r"] == 5 and st["dispatches"] == 2
    assert isinstance(st["history"], History) and len(st["history"]) == 2
    assert st["history"][1].value == [0, 7]
    assert int(np.asarray(st["sim"]["x"]).sum()) == 3
    # no stray tmp after a clean save
    assert not os.path.exists(os.path.join(d, cp.CHECKPOINT_FILE + ".tmp"))


def test_truncated_checkpoint_versioned_error(tmp_path):
    d = str(tmp_path)
    path = cp.save(d, _mini_state())
    blob = open(path, "rb").read()
    # header-only truncation
    with open(path, "wb") as f:
        f.write(blob[:8])
    with pytest.raises(cp.CheckpointError, match="truncated"):
        cp.load(d)
    # payload truncation
    with open(path, "wb") as f:
        f.write(blob[:-20])
    with pytest.raises(cp.CheckpointError, match="truncated"):
        cp.load(d)


def test_old_raw_pickle_versioned_error(tmp_path):
    """Pre-versioning checkpoints were bare pickles: the load error must
    say so instead of surfacing a raw UnpicklingError mid-resume."""
    d = str(tmp_path)
    with open(os.path.join(d, cp.CHECKPOINT_FILE), "wb") as f:
        pickle.dump({"r": 1, "sim": {}}, f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    with pytest.raises(cp.CheckpointError, match="pre-versioning"):
        cp.load(d)


def test_unknown_version_versioned_error(tmp_path):
    d = str(tmp_path)
    path = cp.save(d, _mini_state())
    blob = bytearray(open(path, "rb").read())
    blob[8] = 99                    # bump the little-endian version field
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(cp.CheckpointError, match="v99"):
        cp.load(d)


def test_torn_write_falls_back_to_previous_checkpoint(tmp_path):
    """A corrupted newest checkpoint (torn write) must not lose the run:
    load falls back to checkpoint.prev.pkl, the last good snapshot."""
    d = str(tmp_path)
    cp.save(d, _mini_state(r=100))
    path = cp.save(d, _mini_state(r=200))
    assert os.path.exists(os.path.join(d, cp.PREV_CHECKPOINT_FILE))
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF                # flip a payload byte: digest mismatch
    with open(path, "wb") as f:
        f.write(bytes(blob))
    st = cp.load(d)
    assert st["r"] == 100
    # without a fallback the digest failure is surfaced, named
    os.unlink(os.path.join(d, cp.PREV_CHECKPOINT_FILE))
    with pytest.raises(cp.CheckpointError, match="digest"):
        cp.load(d)


def test_missing_checkpoint_still_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError, match="checkpoint-every"):
        cp.load(str(tmp_path / "nope"))


def test_failed_write_leaves_no_stale_tmp(tmp_path, monkeypatch):
    d = str(tmp_path)
    real_replace = os.replace

    def boom(src, dst):
        if dst.endswith(cp.CHECKPOINT_FILE):
            raise OSError("disk full")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="disk full"):
        cp.save(d, _mini_state())
    assert not os.path.exists(os.path.join(d, cp.CHECKPOINT_FILE + ".tmp"))


def test_writer_failure_surfaces_on_wait(tmp_path):
    w = cp.CheckpointWriter()
    w.submit(str(tmp_path), {"sim": {}, "bad": lambda: None})  # unpicklable
    with pytest.raises(cp.CheckpointError, match="write failed"):
        w.wait()
    # the writer recovers: a good snapshot still lands
    w.submit(str(tmp_path), _mini_state())
    w.wait()
    assert cp.load(str(tmp_path))["r"] == 5
    assert w.writes == 2 and not w.in_flight()


def test_writer_single_flight(tmp_path):
    """Back-to-back submits serialize: the second joins the first, so
    the newest file always reflects the newest submit."""
    w = cp.CheckpointWriter()
    for r in (1, 2, 3):
        w.submit(str(tmp_path), _mini_state(r=r))
    w.wait()
    assert cp.load(str(tmp_path))["r"] == 3
    assert w.writes == 3


# --- fingerprint drift ---


def test_fingerprint_names_mismatched_compiled_shape_flags():
    """Every flag that shapes the compiled state tree or the op stream
    must be fingerprinted, and a mismatched resume must name the
    offending key(s)."""
    for key in ("mesh", "journal_scan_cap", "reply_log_cap",
                "journal_rows", "collect_replies", "max_scan",
                "pool_cap", "ms_per_round", "seed"):
        assert key in cp.FINGERPRINT_KEYS, key
    base = {"workload": "lin-kv", "seed": 1, "mesh": "1,2",
            "journal_scan_cap": 128, "reply_log_cap": 256}
    ck = {"fingerprint": cp.fingerprint(base)}
    cp.check_fingerprint(ck, dict(base))        # identical: fine
    for key, other in (("mesh", "1,4"), ("journal_scan_cap", 512),
                       ("reply_log_cap", 64), ("seed", 2)):
        with pytest.raises(ValueError, match=key):
            cp.check_fingerprint(ck, {**base, key: other})


def test_fingerprint_excludes_analysis_flags():
    """Analysis- and durability-side flags deliberately stay OUT of the
    fingerprint: they never touch the op stream, so a resume may freely
    change them (e.g. resume with more check workers, or switch the
    checkpoint cadence / sync mode)."""
    for key in ("check_workers", "no_overlap", "checkpoint_every",
                "sync_checkpoint", "on_preempt", "resume"):
        assert key not in cp.FINGERPRINT_KEYS, key
    base = {"workload": "lin-kv", "seed": 1, "check_workers": 1,
            "no_overlap": False, "checkpoint_every": 1.0}
    ck = {"fingerprint": cp.fingerprint(base)}
    cp.check_fingerprint(ck, {**base, "check_workers": 4,
                              "no_overlap": True,
                              "checkpoint_every": 0.25,
                              "sync_checkpoint": True})


# --- end-to-end: async writer, pipeline-aware resume, mesh ---


def _build(root, **over):
    opts = {"workload": "lin-kv", "node": "tpu:lin-kv", "node_count": 3,
            "rate": 15.0, "time_limit": 2.0, "nemesis": {"partition"},
            "nemesis_interval": 1.0, "recovery_s": 0.5, "seed": 7,
            "store_root": str(root)}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(root)
    return test


def _run_resumed(tmp_path, sub, **over):
    """Checkpointed partial run + resume; returns (runner, history,
    test) of the resumed run."""
    tb = _build(tmp_path / sub, checkpoint_every=0.5, **over)
    tb["max_rounds"] = 1000
    TpuRunner(tb).run()
    tc = _build(tmp_path / sub, checkpoint_every=0.5, **over)
    runner = TpuRunner(tc)
    resume = cp.load(str(tmp_path / sub))
    cp.check_fingerprint(resume, tc)
    return runner, runner.run(resume=resume), tc


def test_async_and_sync_checkpoints_agree(tmp_path):
    """--sync-checkpoint is an escape hatch, not a different format: the
    background writer and the inline path produce interchangeable
    checkpoints and identical resumed histories."""
    ta = _build(tmp_path / "base")
    hist_a = TpuRunner(ta).run()

    runner_b, hist_b, _ = _run_resumed(tmp_path, "async")
    runner_c, hist_c, _ = _run_resumed(tmp_path, "sync",
                                       sync_checkpoint=True)
    assert _ops(hist_b) == _ops(hist_a)
    assert _ops(hist_c) == _ops(hist_a)
    # the async path actually used the background writer; sync didn't
    assert runner_b._ckpt_writer is not None
    assert runner_c._ckpt_writer is None
    for r in (runner_b, runner_c):
        assert r.transfer.ckpt_saves > 0
    # background write time is booked (the amortization counter)
    assert runner_b.transfer.ckpt_write_s > 0.0


def test_resume_keeps_pipeline_overlap(tmp_path):
    """Regression: resumed runs must keep the overlapped analysis
    pipeline. The pipeline is seeded with the resumed rows, covers the
    whole stitched history at check time, and its verdicts equal the
    sequential path's bit-for-bit."""
    runner, hist, test = _run_resumed(tmp_path, "p")
    assert runner.pipeline is not None
    rep = runner.pipeline.report()
    assert rep["rows"] == len(hist)
    assert rep.get("resumed-rows", 0) > 0
    assert "error" not in rep
    # the checker actually gets served (no row-count decline)
    parts = runner.pipeline.register_partitions(len(hist))
    assert parts is not None and len(parts) > 0
    # verdict equality: pipeline-fed vs sequential recompute
    wl = test["workload_map"]["checker"]
    fast = wl.check({**test, "analysis": runner.pipeline}, hist, {})
    seq = wl.check({k: v for k, v in test.items() if k != "analysis"},
                   hist, {})
    assert fast == seq


def test_preempt_writes_final_checkpoint_and_resumes(tmp_path):
    """The graceful-preemption path, in-process and deterministic: with
    the preempt flag raised, the runner writes a final (synchronous)
    checkpoint at the next stretch boundary and unwinds with Preempted;
    resuming from that checkpoint completes bit-identically to an
    uninterrupted run. (The real-signal subprocess version — SIGTERM,
    exit code 75 — lives in tests/test_crash_soak.py, slow suite.)"""
    ta = _build(tmp_path / "base")
    hist_a = TpuRunner(ta).run()

    tb = _build(tmp_path / "g")
    runner = TpuRunner(tb)
    runner._preempt.set()
    with pytest.raises(cp.Preempted) as ei:
        runner.run()
    assert ei.value.checkpoint_dir == str(tmp_path / "g")
    st = cp.load(str(tmp_path / "g"))

    tc = _build(tmp_path / "g")
    rc = TpuRunner(tc)
    cp.check_fingerprint(st, tc)
    hist_c = rc.run(resume=st)
    assert _ops(hist_c) == _ops(hist_a)


@pytest.mark.multichip
def test_mesh_checkpoint_resume_bit_identical(tmp_path):
    """Sharded checkpointing: a --mesh 1,2 run checkpoints its sharded
    state tree (saved host-side), resumes onto the same mesh via
    `_reshard`, and the stitched history is bit-identical to the
    uninterrupted sharded run."""
    ta = _build(tmp_path / "base", mesh="1,2")
    hist_a = TpuRunner(ta).run()
    assert len(hist_a) > 10

    _, hist_c, _ = _run_resumed(tmp_path, "m", mesh="1,2")
    assert _ops(hist_c) == _ops(hist_a)


@pytest.mark.multichip
def test_mesh_checkpoint_rejects_other_mesh(tmp_path):
    """A checkpoint taken under one mesh refuses to resume under a
    different mesh (or none): the mismatch is named, not silently
    resharded into an untested donation/sharding combination."""
    tb = _build(tmp_path / "b", checkpoint_every=0.5, mesh="1,2")
    tb["max_rounds"] = 1000
    TpuRunner(tb).run()
    ck = cp.load(str(tmp_path / "b"))
    with pytest.raises(ValueError, match="mesh"):
        cp.check_fingerprint(
            ck, _build(tmp_path / "b", checkpoint_every=0.5, mesh="1,4"))
    with pytest.raises(ValueError, match="mesh"):
        cp.check_fingerprint(
            ck, _build(tmp_path / "b", checkpoint_every=0.5))
