"""The broadcast fuzz harness (BASELINE config 5, scaled down for CI):
random partitions injected mid-broadcast plus loss must leave every
*born* value fully propagated after healing, with zero silent drops."""

from __future__ import annotations

from maelstrom_tpu.fuzz import DEFAULT_SWEEP, fuzz_broadcast


def test_fuzz_broadcast_partitions_and_loss():
    results = fuzz_broadcast(n_nodes=36, values=6, sweep=DEFAULT_SWEEP[:2],
                             seed=5, chunk=60, log=lambda *_: None)
    assert len(results) == 2
    for r in results:
        assert r["ok"], r
        assert r["dropped_overflow"] == 0
    # the partition actually bit: cross-component sends were dropped
    assert any(r["dropped_partition"] > 0 for r in results)
