"""The broadcast fuzz harness (BASELINE config 5, scaled down for CI):
random partitions injected mid-broadcast plus loss must leave every
*born* value fully propagated after healing, with zero silent drops."""

from __future__ import annotations

from maelstrom_tpu.fuzz import DEFAULT_SWEEP, fuzz_broadcast

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def test_fuzz_broadcast_partitions_and_loss():
    results = fuzz_broadcast(n_nodes=36, values=6, sweep=DEFAULT_SWEEP[:2],
                             seed=5, chunk=60, log=lambda *_: None)
    assert len(results) == 2
    for r in results:
        assert r["ok"], r
        assert r["dropped_overflow"] == 0
    # the partition actually bit: cross-component sends were dropped
    assert any(r["dropped_partition"] > 0 for r in results)


def test_fuzz_raft_sweep_small():
    from maelstrom_tpu.fuzz import fuzz_raft

    rows = fuzz_raft(n_clusters=12, sample=4, seed=3, log=lambda s: None)
    assert len(rows) == 5
    for r in rows:
        assert r["ok"] is True, r
        assert r["dropped_overflow"] == 0
    # the sweep genuinely exercised each fault class somewhere
    assert any(r["net_stats"]["lost"] > 0 for r in rows)
    assert any(r["net_stats"]["dropped_partition"] > 0 for r in rows)


def test_fuzz_kafka_sweep_small():
    from maelstrom_tpu.fuzz import fuzz_kafka

    rows = fuzz_kafka(seed=5, time_limit=3.0, rate=12.0,
                      log=lambda s: None)
    assert len(rows) == 5
    for r in rows:
        assert r["ok"] is True, r
        assert r["dropped_overflow"] == 0
    assert any((r["lost"] or 0) > 0 for r in rows)
    assert any((r["dropped_partition"] or 0) > 0 for r in rows)
