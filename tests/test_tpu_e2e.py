"""End-to-end tests for the TPU execution path: full tests (generators ->
jitted simulation rounds -> history -> stock checkers) with built-in batched
node programs, the analogue of the reference's `demo` self-test
(`core.clj:93-111`)."""

import pytest

from maelstrom_tpu import core


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def run(opts):
    # journal_rows off by default: engages the compiled scan-ahead fast
    # path. The grid test below keeps it on to cover TPU-path journaling.
    base = dict(store_root="/tmp/maelstrom-tpu-test-store", seed=7,
                rate=20.0, time_limit=2.0, journal_rows=False)
    return core.run({**base, **opts})


def test_echo_tpu_e2e():
    res = run({"workload": "echo", "node": "tpu:echo", "node_count": 5})
    assert res["valid"] is True
    assert res["workload"]["valid"] is True
    # every echo got a reply: client sends == client recvs, no server msgs
    assert res["net"]["servers"]["send-count"] == 0
    assert res["net"]["all"]["send-count"] > 0
    assert res["stats"]["count"] > 10


def test_broadcast_tpu_e2e_grid():
    import os
    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "node_count": 5, "topology": "grid", "journal_rows": True})
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["valid"] is True
    assert w["stable-count"] > 0 and w["lost-count"] == 0
    # gossip happened between servers
    assert res["net"]["servers"]["send-count"] > 0
    # TPU-path journaling produced a Lamport diagram
    latest = "/tmp/maelstrom-tpu-test-store/latest"
    assert os.path.exists(os.path.join(latest, "messages.svg"))


def test_broadcast_tpu_e2e_line_with_latency():
    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "node_count": 8, "topology": "line",
               "latency": {"mean": 5, "dist": "constant"}})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["lost-count"] == 0


def test_broadcast_tpu_partition_recovery():
    """Values broadcast during a partition must still become stable after
    healing (retransmission), like the reference's retrying demo."""
    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "node_count": 5, "topology": "grid",
               "nemesis": {"partition"}, "nemesis_interval": 0.5,
               "time_limit": 3.0, "recovery_s": 2})
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["lost-count"] == 0
    assert w["stable-count"] > 0


@pytest.mark.parametrize("dist", ["uniform", "exponential"])
def test_edge_journal_exact_pairing_random_latency(dist):
    """Every edge-channel journal recv must pair to its true send (same
    id, send strictly earlier, same endpoints) — under randomized latency
    draws, not just constant. The channels carry each message's send
    round (`EdgeChannels.sent`), matching the reference journal's
    exactness (`net/journal.clj:225-239`)."""
    from maelstrom_tpu.net.journal import Journal

    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "node_count": 5, "topology": "grid", "journal_rows": True,
               "latency": {"mean": 3, "dist": dist}, "time_limit": 2.0})
    assert res["valid"] is True, res["workload"]
    jr = Journal.load("/tmp/maelstrom-tpu-test-store/latest/net-journal")
    EDGE = 1 << 40
    events = jr.all_events()
    sends = {e.id: e for e in events if e.id >= EDGE and e.type == "send"}
    recvs = [e for e in events if e.id >= EDGE and e.type == "recv"]
    assert recvs, "no edge traffic journaled"
    delays = set()
    for e in recvs:
        s = sends.get(e.id)
        assert s is not None, f"recv {e.id} has no matching send"
        assert s.time < e.time, (s, e)
        assert (s.src, s.dest) == (e.src, e.dest), (s, e)
        delays.add(e.time - s.time)
    # the draws actually varied (otherwise this test is the constant case)
    assert len(delays) > 1, delays


def test_broadcast_tpu_with_loss_is_lossless_to_checker():
    """5% message loss: acks + retransmission keep the workload valid."""
    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "node_count": 5, "topology": "total", "p_loss": 0.05,
               "time_limit": 2.0})
    # p_loss wiring goes through the test opts
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["lost-count"] == 0


def test_naive_broadcast_exponential_latency_lossless():
    """The naive (non-retrying) protocol under randomized latency: the
    spill write must deliver every message — an edge-ring collision may
    move a message to another lane but never destroy it (the reference's
    network only loses by explicit loss/partition, `net.clj:188-246`).
    Regression for VERDICT r2: 'grid 25, 100 ms exponential' lost 2
    values to ring-cell overwrites and the run was presented as parity
    evidence anyway."""
    res = run({"workload": "broadcast", "node": "tpu:broadcast",
               "naive_broadcast": True, "node_count": 9,
               "topology": "grid", "rate": 50.0,
               "latency": {"mean": 3, "dist": "exponential"},
               "max_latency_scale": 2, "time_limit": 2.0})
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["lost-count"] == 0
    # the net checker saw zero destroyed messages (naive mode no longer
    # tolerates overwrites, so any destruction would flip valid False)
    assert res["net"]["channel-overwrites"] == 0
    assert res["net"]["lost"] == 0
