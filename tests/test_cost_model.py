"""The jaxpr cost & collective auditor (maelstrom_tpu.analyze.cost_model).

Mirrors the ISSUE 20 acceptance contract:

  - golden cost records: the REAL production round_fn/cscan_fn for
    lin-kv, broadcast-batched and compartment — plain and (multichip)
    --mesh 1,2 — with PINNED integer totals, tolerance-free: the model
    books exact aval bytes and per-equation FLOPs, so any drift is a
    deliberate model or program change that must re-pin these numbers
    AND regenerate analyze/cost_baseline.json;
  - seeded-violation fixtures per rule: a minimal record/step that
    trips carry-growth, hbm-overflow, intensity-regression and (on a
    2,2 mesh) collective-on-dp exactly once;
  - the zero-new-findings gate + baseline round-trip.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import pytest

from maelstrom_tpu.analyze.cost_model import (DeviceProfile, PROFILES,
                                              cost_findings,
                                              cost_production, cost_step,
                                              load_cost_baseline, predict,
                                              predict_round,
                                              resolve_profile,
                                              write_cost_baseline)
from maelstrom_tpu.analyze.jaxpr_audit import StepSpec


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# golden records: pinned exact totals for the stock production programs
# ---------------------------------------------------------------------------

# (entry, flops, hbm_read, hbm_written, carry, peak) — exact integers.
_GOLDEN_PLAIN = {
    "lin-kv": [
        ("round_fn[lin-kv]", 33031, 572509, 340417, 512, 131194),
        ("cscan_fn[lin-kv]", 67682, 1180388, 712433, 57429, 200801),
    ],
    "broadcast-batched": [
        ("round_fn[broadcast-batched]",
         1425156, 3758834, 2683610, 512, 441016),
        ("cscan_fn[broadcast-batched]",
         2934172, 8087404, 5954693, 100162, 624746),
    ],
    "compartment": [
        ("round_fn[compartment]",
         1482756, 10944030, 6797295, 32768, 642191),
        ("cscan_fn[compartment]",
         2966998, 21922156, 13625537, 178638, 826676),
    ],
}


def _assert_golden(records, pins):
    for entry, flops, read, written, carry, peak in pins:
        rec = records[entry]
        got = (rec["flops"], rec["hbm_bytes_read"],
               rec["hbm_bytes_written"], rec["carry_bytes"],
               rec["peak_bytes"])
        assert got == (flops, read, written, carry, peak), \
            f"{entry}: {got} != pinned — a model/program change must " \
            f"re-pin this AND regenerate cost_baseline.json"


@pytest.mark.parametrize("program", sorted(_GOLDEN_PLAIN))
def test_golden_records_plain(program):
    rep = cost_production(programs=[program], mesh=None, fleet=False,
                          profile="cpu", baseline={})
    _assert_golden(rep.records, _GOLDEN_PLAIN[program])
    # structural rules clean on every stock program
    assert rules_of(rep.findings) == []


@pytest.mark.multichip
def test_golden_records_mesh_12():
    """--mesh 1,2: same invariant totals as plain (costs are booked on
    the UNSHARDED abstract shapes — the model is mesh-invariant for
    compute/HBM) plus an explicit sp collective-byte column from the
    GSPMD reshard heuristic."""
    rep = cost_production(programs=["lin-kv"], mesh="1,2", fleet=False,
                          profile="cpu", baseline={})
    rec = rep.records["round_fn[lin-kv@mesh=1,2]"]
    assert (rec["flops"], rec["hbm_bytes_read"], rec["hbm_bytes_written"],
            rec["carry_bytes"]) == (33031, 572509, 340417, 512)
    assert rec["collective_bytes"] == {"sp": 96547}
    # the sp reshard traffic never counts as a dp hazard
    assert rec["dp_collectives"] == []
    assert rules_of(rep.findings) == []


def test_record_derived_fields_consistent():
    rep = cost_production(programs=["lin-kv"], mesh=None, fleet=False,
                          profile="cpu", baseline={})
    rec = rep.records["round_fn[lin-kv]"]
    hbm = rec["hbm_bytes_read"] + rec["hbm_bytes_written"]
    assert rec["arithmetic_intensity"] == round(rec["flops"] / hbm, 6)
    assert rec["peak_bytes_donated"] == max(
        rec["peak_bytes"] - rec["donated_bytes"], 0)
    assert rec["stretch"]["hbm_bytes"] == hbm * rec["stretch"]["rounds"]
    pred = rec["predicted"]
    assert pred["profile"] == "cpu"
    assert pred["rounds_per_sec"] == round(1.0 / pred["round_s"], 3)
    # capacity bound: pool vs inbox+client lanes from the run's cfg
    assert rec["msgs_per_round_cap"] is not None
    assert pred["msgs_per_sec"] == pytest.approx(
        rec["msgs_per_round_cap"] * pred["rounds_per_sec"], rel=1e-4)


# ---------------------------------------------------------------------------
# seeded-violation fixtures: each rule fires exactly once
# ---------------------------------------------------------------------------

def _scan_record(carry_elems=8, name="fx"):
    """Cost record for a minimal scan whose carry is carry_elems f32s."""
    def fn(x):
        def body(c, _):
            return c * 2.0, ()
        c, _ = jax.lax.scan(body, x, None, length=4)
        return c
    spec = StepSpec(name=name, fn=fn,
                    args=(jnp.zeros((carry_elems,), jnp.float32),))
    return cost_step(spec, "cpu")


def test_fixture_carry_growth_fires_once():
    rec = _scan_record(carry_elems=1024)     # 4096 B carry
    base = {"profile": "cpu", "entries": {"fx": dict(rec["predicted"])},
            "carry_budgets": {"fx": 1024}}
    found = cost_findings({"fx": rec}, baseline=base, profile="cpu")
    assert rules_of(found) == ["carry-growth"]
    assert "exceeds budget 1024" in found[0].detail
    # under the default budget the same record is clean
    assert cost_findings({"fx": rec}, baseline={}, profile="cpu") == []


def test_fixture_hbm_overflow_fires_once():
    rec = _scan_record(carry_elems=1024)
    tiny = DeviceProfile("tiny", peak_flops=1e9, hbm_bw=1e9,
                         ici_bw=1e9, dcn_bw=1e9,
                         hbm_bytes=64.0,     # smaller than any real peak
                         dispatch_overhead_s=1e-3)
    found = cost_findings({"fx": rec}, baseline={}, profile=tiny)
    assert rules_of(found) == ["hbm-overflow"]
    assert cost_findings({"fx": rec}, baseline={}, profile="cpu") == []


def test_fixture_intensity_regression_fires_once():
    rec = _scan_record()
    fast = {"rounds_per_sec": rec["predicted"]["rounds_per_sec"] * 10,
            "msgs_per_sec": None}
    base = {"profile": "cpu", "tolerance_pct": 20.0,
            "entries": {"fx": fast}}
    found = cost_findings({"fx": rec}, baseline=base, profile="cpu")
    assert rules_of(found) == ["intensity-regression"]
    # within tolerance: the same prediction against itself is clean
    same = {"profile": "cpu", "tolerance_pct": 20.0,
            "entries": {"fx": dict(rec["predicted"])}}
    assert cost_findings({"fx": rec}, baseline=same,
                         profile="cpu") == []


def test_fixture_missing_baseline_entry_fires():
    rec = _scan_record()
    base = {"profile": "cpu", "entries": {}}
    found = cost_findings({"fx": rec}, baseline=base, profile="cpu")
    assert rules_of(found) == ["intensity-regression"]
    assert "missing from cost_baseline.json" in found[0].detail


@pytest.mark.multichip
def test_fixture_collective_on_dp_fires_once():
    """An explicit psum over the dp axis inside shard_map on a 2,2
    mesh — the cross-replica traffic the fleet contract forbids —
    fires collective-on-dp exactly once; the same psum over sp is a
    legal shard-parallel reduction and stays quiet."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maelstrom_tpu import parallel
    mesh = parallel.mesh_from_spec("2,2")

    def over(axis):
        def fn(x):
            return shard_map(
                lambda v: jax.lax.psum(v, axis), mesh,
                in_specs=P("dp", "sp"), out_specs=P(None, "sp"),
                check_rep=False)(x)
        sh = NamedSharding(mesh, P("dp", "sp"))
        x = jax.device_put(jnp.ones((4, 8), jnp.float32), sh)
        spec = StepSpec(name=f"fx-{axis}", fn=fn, args=(x,),
                        in_shardings=sh)
        rec = cost_step(spec, "cpu")
        return cost_findings({spec.name: rec}, baseline={},
                             profile="cpu"), rec

    found_dp, rec_dp = over("dp")
    assert rules_of(found_dp) == ["collective-on-dp"]
    assert rec_dp["collective_bytes"].get("dp", 0) > 0
    found_sp, rec_sp = over("sp")
    assert rules_of(found_sp) == []
    assert rec_sp["collective_bytes"].get("sp", 0) > 0


# ---------------------------------------------------------------------------
# baseline round-trip + gate
# ---------------------------------------------------------------------------

def test_checked_in_cost_baseline_is_well_formed():
    base = load_cost_baseline()
    assert base, "analyze/cost_baseline.json missing"
    assert base["profile"] in PROFILES
    assert base["entries"], "no entries"
    assert list(base["entries"]) == sorted(base["entries"]), \
        "baseline entries must be emitted sorted (clean diffs)"
    for name, ent in base["entries"].items():
        assert ent["rounds_per_sec"] > 0, name
        assert ent["flops"] >= 0 and ent["hbm_bytes"] > 0, name


def test_gate_production_lin_kv_clean_vs_checked_in_baseline():
    """The committed cost_baseline.json covers today's lin-kv entries
    at a >=20% tolerance: the production trace gates clean."""
    rep = cost_production(programs=["lin-kv"], mesh=None, fleet=False,
                          profile="cpu", baseline=load_cost_baseline())
    assert rep.ok, [f.as_dict() for f in rep.findings]


def test_write_cost_baseline_round_trips_sorted(tmp_path):
    rec = _scan_record(name="zz")
    rec2 = _scan_record(carry_elems=16, name="aa")
    path = str(tmp_path / "cost_baseline.json")
    write_cost_baseline({"zz": rec, "aa": rec2}, path, profile="cpu")
    data = json.load(open(path))
    assert list(data["entries"]) == ["aa", "zz"]
    # tolerance/carry budgets survive a rewrite
    data["tolerance_pct"] = 35.0
    data["carry_budgets"] = {"zz": 12345}
    json.dump(data, open(path, "w"))
    write_cost_baseline({"zz": rec, "aa": rec2}, path, profile="cpu")
    data2 = json.load(open(path))
    assert data2["tolerance_pct"] == 35.0
    assert data2["carry_budgets"] == {"zz": 12345}
    # and gating against the round-tripped file is clean
    assert cost_findings({"zz": rec, "aa": rec2}, baseline=data2,
                         profile="cpu") == []


# ---------------------------------------------------------------------------
# bench-facing prediction + CLI
# ---------------------------------------------------------------------------

def test_predict_round_traces_bench_shape_abstractly():
    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    nodes = [f"n{i}" for i in range(64)]
    prog = get_program("broadcast",
                       {"topology": "grid", "max_values": 4,
                        "latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=64, n_clients=1, pool_cap=256,
                      inbox_cap=prog.inbox_cap, client_cap=0)
    rec = predict_round(prog, cfg, profile="cpu", msgs_per_round=10.0)
    assert rec["flops"] > 0 and rec["hbm_bytes_read"] > 0
    assert rec["predicted"]["msgs_per_sec"] == round(
        10.0 * rec["predicted"]["rounds_per_sec"], 3)
    # fleet vmap multiplies the booked work ~linearly (a few scalar
    # bookkeeping equations stay unbatched, so not exactly 8x)
    rec8 = predict_round(prog, cfg, fleet=8, profile="cpu")
    assert 6 * rec["flops"] < rec8["flops"] <= 8 * rec["flops"]
    # chunked dispatch amortizes the overhead: strictly faster rounds
    rec_amort = predict_round(prog, cfg, profile="cpu",
                              rounds_per_dispatch=64)
    assert rec_amort["predicted"]["round_s"] < \
        rec["predicted"]["round_s"]


def test_roofline_bound_selection():
    base = {"flops": 0, "hbm_bytes_read": 0, "hbm_bytes_written": 0,
            "collective_bytes": {}, "msgs_per_round_cap": None}
    prof = DeviceProfile("t", peak_flops=10.0, hbm_bw=10.0, ici_bw=10.0,
                         dcn_bw=10.0, hbm_bytes=1e9,
                         dispatch_overhead_s=1.0)
    assert predict(dict(base, flops=30), prof)["round_s"] == 4.0
    assert predict(dict(base, hbm_bytes_read=50), prof)["round_s"] == 6.0
    assert predict(dict(base, collective_bytes={"sp": 20}),
                   prof)["round_s"] == 3.0
    # dp traffic rides the (slower) DCN lane in the max()
    slow_dcn = DeviceProfile("t2", peak_flops=10.0, hbm_bw=10.0,
                             ici_bw=10.0, dcn_bw=1.0, hbm_bytes=1e9,
                             dispatch_overhead_s=1.0)
    assert predict(dict(base, collective_bytes={"dp": 20}),
                   slow_dcn)["round_s"] == 21.0


def test_resolve_profile_rejects_unknown():
    with pytest.raises(ValueError, match="unknown device profile"):
        resolve_profile("gpu-z9000")
    assert resolve_profile("tpu-v4").name == "tpu-v4"
    assert resolve_profile(PROFILES["cpu"]) is PROFILES["cpu"]


def test_analyze_cli_cost_json(capsys, tmp_path):
    from maelstrom_tpu.analyze.cli import main
    rc = main(["--cost", "--programs", "lin-kv", "--mesh", "none",
               "--no-fleet", "--format", "json", "--profile", "cpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out
    assert out["ok"] is True
    assert "round_fn[lin-kv]" in out["records"]
    # --write-cost-baseline emits a fresh gateable file
    path = str(tmp_path / "cb.json")
    rc = main(["--cost", "--programs", "lin-kv", "--mesh", "none",
               "--no-fleet", "--profile", "cpu",
               "--write-cost-baseline", "--baseline", path])
    capsys.readouterr()
    assert rc == 0
    assert json.load(open(path))["entries"]


def test_runner_results_carry_cost_block(tmp_path):
    """End to end: a CLI-path run's results carry the `cost` block
    beside `static-audit`, memoized on the second identical config."""
    from maelstrom_tpu import core
    res = core.run(dict(store_root=str(tmp_path), seed=5,
                        workload="echo", node="tpu:echo", node_count=2,
                        rate=5, time_limit=0.5, journal_rows=False,
                        audit=True, audit_trace=True))
    blk = res["net"]["cost"]
    assert blk["ok"] is True, blk
    assert blk["records"], blk
    rec = next(iter(blk["records"].values()))
    assert rec["flops"] > 0 and rec["predicted"]["rounds_per_sec"] > 0
    res2 = core.run(dict(store_root=str(tmp_path), seed=6,
                         workload="echo", node="tpu:echo", node_count=2,
                         rate=5, time_limit=0.5, journal_rows=False,
                         audit=True, audit_trace=True))
    assert res2["net"]["cost"].get("memoized") is True
