"""In-cluster service nodes (ISSUE 10 satellite): the lin-tso / seq-kv /
lww-kv role programs pinned against the PURE reference state machines in
`maelstrom_tpu/services.py` (the oracles), plus the lin-tso workload
smoke on the role-partitioned services cluster."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu import core
from maelstrom_tpu.history import History
from maelstrom_tpu.checkers.tso import TSOChecker
from maelstrom_tpu.net.tpu import Msgs
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.nodes.services import (
    LWWKVRole, SeqKVRole, TSORole, T_CAS, T_MERGE, T_READ, T_TS,
    T_TS_OK, T_WRITE, parse_service_roles, roles_node_count)
from maelstrom_tpu.services import (LWWKV, Linearizable, PersistentKV,
                                    PersistentTSO)

STORE = "/tmp/maelstrom-services-store"


class _Msg:
    """Shape of the host services' message argument."""

    def __init__(self, body, src="c1"):
        self.body = body
        self.src = src


def _inbox(rows, K=8, n=1):
    """[n, K] inbox with `rows` = [(node, type, a, b, c)] packed into
    successive lanes of their node."""
    ib = {f: np.zeros((n, K), np.int32) for f in
          ("src", "dest", "due", "mid", "reply_to", "type", "a", "b",
           "c")}
    valid = np.zeros((n, K), bool)
    lane = [0] * n
    for i, (node, t, a, b, c) in enumerate(rows):
        k = lane[node]
        lane[node] += 1
        valid[node, k] = True
        ib["type"][node, k] = t
        ib["a"][node, k] = a
        ib["b"][node, k] = b
        ib["c"][node, k] = c
        ib["src"][node, k] = 100 + i
        ib["mid"][node, k] = 1000 + i
    return Msgs(valid=jnp.asarray(valid),
                **{f: jnp.asarray(v) for f, v in ib.items()})


def _replies(out):
    o = jax.device_get(out)
    v = np.asarray(o.valid)
    rows = []
    for n, k in zip(*np.nonzero(v)):
        rows.append((int(o.reply_to[n, k]), int(o.type[n, k]),
                     int(o.a[n, k])))
    return rows


def _ctx(r=0):
    return {"round": jnp.int32(r), "key": jax.random.PRNGKey(0)}


# --- oracles ---------------------------------------------------------------

def test_tso_role_matches_persistent_tso_oracle():
    prog = TSORole({}, ["n0"])
    st = prog.init_state()
    oracle = Linearizable(PersistentTSO())
    got = []
    for rnd in range(4):
        st, out = prog.step(st, _inbox([(0, T_TS, 0, 0, 0),
                                        (0, T_TS, 0, 0, 0)]), _ctx(rnd))
        got += [a for _m, t, a in _replies(out) if t == T_TS_OK]
    want = [oracle.handle(_Msg({"type": "ts"}))["ts"] for _ in range(8)]
    assert got == want
    assert len(set(got)) == len(got)


def test_seq_kv_role_matches_linearizable_kv_oracle():
    import random
    rng = random.Random(5)
    prog = SeqKVRole({"kv_keys": 8}, ["n0"])
    st = prog.init_state()
    oracle = Linearizable(PersistentKV())
    for rnd in range(16):
        ops = []
        for _ in range(3):
            k, v = rng.randrange(4), rng.randrange(5)
            ops.append(rng.choice([
                (0, T_READ, k, 0, 0),
                (0, T_WRITE, k, v, 0),
                (0, T_CAS, k, v, rng.randrange(5)),
            ]))
        st, out = prog.step(st, _inbox(ops), _ctx(rnd))
        reps = {m: (t, a) for m, t, a in _replies(out)}
        for i, (node, t, a, b, c) in enumerate(ops):
            if t == T_READ:
                body = {"type": "read", "key": a}
            elif t == T_WRITE:
                body = {"type": "write", "key": a, "value": b}
            else:
                body = {"type": "cas", "key": a, "from": b, "to": c}
            want = oracle.handle(_Msg(body))
            rt, ra = reps[1000 + i]
            if want["type"] == "read_ok":
                assert (rt, ra - 1) == (11, want["value"])
            elif want["type"] == "error":
                assert (rt, ra) == (1, want["code"])
            else:
                assert rt in (13, 15)


def test_lww_role_single_replica_matches_lww_oracle():
    import random
    rng = random.Random(9)
    prog = LWWKVRole({"kv_keys": 8}, ["n0"])
    st = prog.init_state()
    oracle = LWWKV()
    for rnd in range(24):
        k, v = rng.randrange(4), rng.randrange(5)
        t = rng.choice([T_READ, T_WRITE])
        st, out = prog.step(st, _inbox([(0, t, k, v, 0)]), _ctx(rnd))
        body = ({"type": "read", "key": k} if t == T_READ
                else {"type": "write", "key": k, "value": v})
        oracle, want = oracle.handle(_Msg(body))
        ((_m, rt, ra),) = _replies(out)
        if want["type"] == "read_ok":
            assert (rt, ra - 1) == (11, want["value"])
        elif want["type"] == "error":
            assert (rt, ra) == (1, want["code"])
        else:
            assert rt == 13


def test_lww_gossip_converges_and_quiesces():
    """Three replicas: a write at replica 0 propagates the ring via
    dirty-set gossip; all copies converge and the dirty sets drain
    (the quiescence signal)."""
    prog = LWWKVRole({"kv_keys": 8, "gossip_keys": 4},
                     ["n0", "n1", "n2"], base=0)
    st = prog.init_state()
    st, out = prog.step(
        st, _inbox([(0, T_WRITE, 3, 7, 0), (1, T_WRITE, 5, 2, 0)],
                   n=3), _ctx(0))
    # the write's dirty bits drained into in-flight gossip the same
    # round (the POOL keeps the runner non-quiescent while they fly)
    o0 = jax.device_get(out)
    assert (np.asarray(o0.valid)
            & (np.asarray(o0.type) == T_MERGE)).sum() == 2
    for rnd in range(1, 12):
        # hand-route the gossip: T_MERGE lanes target dest node
        o = jax.device_get(out)
        rows = []
        v = np.asarray(o.valid)
        for n, k in zip(*np.nonzero(v)):
            if int(o.type[n, k]) == T_MERGE:
                rows.append((int(o.dest[n, k]), T_MERGE,
                             int(o.a[n, k]), int(o.b[n, k]),
                             int(o.c[n, k])))
        st, out = prog.step(st, _inbox(rows, n=3), _ctx(rnd))
    kv = np.asarray(jax.device_get(st["kv"]))
    assert (kv[:, 3] == 8).all() and (kv[:, 5] == 3).all()  # value+1
    assert bool(prog.quiescent(st))


# --- services partition + workload ----------------------------------------

def test_parse_service_roles():
    assert parse_service_roles(None) == {"lin-tso": 1, "seq-kv": 1,
                                         "lww-kv": 3}
    assert roles_node_count(None) == 5
    assert roles_node_count("lin-tso=1,lww-kv=2") == 3
    with pytest.raises(ValueError, match="unknown service"):
        parse_service_roles("tso=1")
    with pytest.raises(ValueError, match="single-copy"):
        parse_service_roles("lin-tso=1,seq-kv=2")


def test_lin_tso_e2e_on_services_cluster():
    res = core.run(dict(store_root=STORE, seed=7, workload="lin-tso",
                        node="tpu:services", rate=20.0, time_limit=2.0,
                        journal_rows=False, audit=False))
    assert res["valid"] is True, res.get("workload")
    w = res["workload"]
    assert w["valid"] is True and w["monotonic"] is True
    assert w["granted-count"] > 10


def test_services_fault_groups():
    prog = get_program("services", {}, [f"n{i}" for i in range(5)])
    g = prog.fault_groups()
    assert g["lin-tso"] == ["n0"]
    assert g["seq-kv"] == ["n1"]
    assert g["lww-kv"] == ["n2", "n3", "n4"]


# --- TSO checker -----------------------------------------------------------

def _tso_history(rows):
    """rows: (process, invoke_ns, complete_ns, ts) — appended in global
    time order, the way a real runner interleaves them."""
    events = []
    for p, inv, comp, ts in rows:
        events.append((inv, "invoke", p, None))
        events.append((comp, "ok", p, ts))
    h = History()
    for t, kind, p, val in sorted(events, key=lambda e: e[0]):
        h.append_row(kind, "ts", val, p, t)
    return h


def test_tso_checker_accepts_witness_order():
    h = _tso_history([(0, 0, 10, 0), (1, 20, 30, 1), (0, 25, 40, 2)])
    res = TSOChecker().check({}, h, {})
    assert res["valid"] is True


def test_tso_checker_rejects_realtime_violation():
    # op with ts=5 completed before the ts=1 op invoked: violation
    h = _tso_history([(0, 0, 10, 5), (1, 20, 30, 1)])
    res = TSOChecker().check({}, h, {})
    assert res["valid"] is False and res["violations"]


def test_tso_checker_rejects_duplicates():
    h = _tso_history([(0, 0, 10, 3), (1, 20, 30, 3)])
    res = TSOChecker().check({}, h, {})
    assert res["valid"] is False and res["duplicate-ts"] == [3]
