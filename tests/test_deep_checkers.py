"""Tests for the linearizability (WGL) and Elle-lite checkers against
known-good and known-bad histories — the checker cross-validation the
reference lacks (SURVEY.md section 4)."""

from maelstrom_tpu.checkers.linearizable import (
    LinearizableRegisterChecker, check_register_history)
from maelstrom_tpu.checkers.elle import ElleListAppendChecker, analyze

INF = float("inf")


def op(f, value, inv, ret, ok=True):
    return {"f": f, "value": value, "inv": inv, "ret": ret, "ok": ok}


# --- register WGL ---

def test_sequential_rw_ok():
    ops = [op("write", 1, 0, 1),
           op("read", 1, 2, 3),
           op("write", 2, 4, 5),
           op("read", 2, 6, 7)]
    assert check_register_history(ops)["valid"] is True


def test_stale_read_invalid():
    # read of 1 strictly after write 2 completed: not linearizable
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, 3),
           op("read", 1, 4, 5)]
    assert check_register_history(ops)["valid"] is False


def test_concurrent_read_either_value_ok():
    # read overlaps the write: may see old or new
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, 6),
           op("read", 1, 3, 5)]
    assert check_register_history(ops)["valid"] is True
    ops[2] = op("read", 2, 3, 5)
    assert check_register_history(ops)["valid"] is True


def test_cas_semantics():
    ops = [op("write", 1, 0, 1),
           op("cas", [1, 5], 2, 3),
           op("read", 5, 4, 5)]
    assert check_register_history(ops)["valid"] is True
    # cas claiming success from a wrong precondition
    ops = [op("write", 1, 0, 1),
           op("cas", [2, 5], 2, 3),
           op("read", 5, 4, 5)]
    assert check_register_history(ops)["valid"] is False


def test_indeterminate_write_may_or_may_not_happen():
    # info write of 2: both later reads of 1 and of 2 are fine...
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, INF, ok=False),
           op("read", 1, 3, 4)]
    assert check_register_history(ops)["valid"] is True
    ops[2] = op("read", 2, 3, 4)
    assert check_register_history(ops)["valid"] is True
    # ...but flip-flopping 1 -> 2 -> 1 is not (write 2 can't un-happen)
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, INF, ok=False),
           op("read", 2, 3, 4),
           op("read", 1, 5, 6)]
    assert check_register_history(ops)["valid"] is False


def test_read_initial_none():
    assert check_register_history([op("read", None, 0, 1)])["valid"] is True
    assert check_register_history([op("read", 3, 0, 1)])["valid"] is False


def test_per_key_checker():
    MS = 1_000_000
    h = []
    t = 0

    def add(f, value, typ="ok", proc=0):
        nonlocal t
        h.append({"type": "invoke", "f": f, "value": value, "process": proc,
                  "time": t})
        t += MS
        h.append({"type": typ, "f": f, "value": value, "process": proc,
                  "time": t})
        t += MS
    add("write", [0, 1])
    add("read", [0, 1])
    add("write", [1, 3])
    add("read", [1, 2])     # wrong: key 1 should be 3
    r = LinearizableRegisterChecker().check({}, h)
    assert r["valid"] is False and r["failures"] == [1]


# --- Elle-lite ---

def _txn_pair(h, micro_in, micro_out, t0, t1, typ="ok", proc=0):
    h.append({"type": "invoke", "f": "txn", "value": micro_in,
              "process": proc, "time": t0})
    h.append({"type": typ, "f": "txn",
              "value": micro_out if typ == "ok" else micro_in,
              "process": proc, "time": t1})


def test_elle_clean_history():
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 2, 3)
    _txn_pair(h, [["append", 1, 2]], [["append", 1, 2]], 4, 5)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 6, 7)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is True, r


def test_elle_g1a_aborted_read():
    h = []
    _txn_pair(h, [["append", 1, 9]], None, 0, 1, typ="fail")
    _txn_pair(h, [["r", 1, None]], [["r", 1, [9]]], 2, 3)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is False and "G1a" in r["anomalies"]


def test_elle_incompatible_order():
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 1)
    _txn_pair(h, [["append", 1, 2]], [["append", 1, 2]], 2, 3)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 4, 5)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [2, 1]]], 6, 7)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is False and "incompatible-order" in r["anomalies"]


def test_elle_g_single_cycle():
    # T1 reads key 1 before T2's append (rw), but T1's own append to key 2
    # is read... classic write-skew-ish: T1: r(1,[]) append(2,1);
    # T2: r(2,[]) append(1,1). Each anti-depends on the other: G2.
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 2, 1]],
              [["r", 1, []], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 2, None], ["append", 1, 1]],
              [["r", 2, []], ["append", 1, 1]], 1, 11, proc=1)
    # make the versions observable
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1]], ["r", 2, [1]]], 12, 13, proc=0)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is False
    assert "G2" in r["anomalies"], r


def test_elle_realtime_violation():
    # T1 appends 1 and completes; T2 *then* starts, reads [] (missing T1's
    # committed write) but observes nothing contradictory serializably...
    # then T3 reads [1]. Serializable order: T2, T1, T3 — fine without
    # realtime, violation with strict-serializable.
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 1, proc=0)
    _txn_pair(h, [["r", 1, None]], [["r", 1, []]], 5, 6, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 8, 9, proc=0)
    strict = ElleListAppendChecker(["strict-serializable"]).check({}, h)
    serial = ElleListAppendChecker(["serializable"]).check({}, h)
    assert strict["valid"] is False, strict
    assert serial["valid"] is True, serial


def test_elle_g1b_intermediate_read():
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2]],
              [["append", 1, 1], ["append", 1, 2]], 0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 2, 3)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 4, 5)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is False and "G1b" in r["anomalies"]


def test_elle_cycle_explanation_rendered():
    """Anomalies carry a concrete rendered cycle and the evidence ops
    (the Elle-style human-readable explanation)."""
    h = []
    _txn_pair(h, [["append", 8, 1], ["append", 9, 2]],
              [["append", 8, 1], ["append", 9, 2]], 0, 1, proc=0)
    _txn_pair(h, [["r", 8, None], ["r", 9, None]],
              [["r", 8, [1]], ["r", 9, []]], 0, 1, proc=1)
    _txn_pair(h, [["r", 9, None]], [["r", 9, [2]]], 2, 3, proc=0)
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is False
    (anom,) = r["anomalies"]["G-single"]
    assert "-[rw]->" in anom["cycle"] and "-[wr]->" in anom["cycle"]
    assert anom["txn-ops"]["T0"] == [["append", 8, 1], ["append", 9, 2]]


def test_elle_realtime_anomaly_survives_data_subcycle():
    """An SCC mixing a pure data cycle (T0<->T1) with a realtime cycle
    through a later txn must still report the realtime anomaly, with a
    witness that actually traverses an rt edge (regression: the greedy
    walk used to close the data subcycle and drop the anomaly)."""
    h = []
    _txn_pair(h, [["append", 1, 1], ["r", 2, None]],
              [["append", 1, 1], ["r", 2, [2]]], 0, 5, proc=0)
    _txn_pair(h, [["append", 2, 2], ["r", 1, None]],
              [["append", 2, 2], ["r", 1, [1]]], 0, 5, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, []]], 10, 11, proc=0)
    r = ElleListAppendChecker(["strict-serializable"]).check({}, h)
    assert r["valid"] is False
    rt_keys = [k for k in r["anomalies"] if k.endswith("-realtime")]
    assert rt_keys, r["anomalies"]
    (anom,) = r["anomalies"][rt_keys[0]]
    assert 2 in anom["txns"]
    assert "-[rt]->" in anom["cycle"]


def test_lin_mutex_checker_catches_double_hold():
    """Forged history: two non-overlapping successful acquires with no
    release between them — the mutex model must reject what the
    register view of the same cas ops cannot see... (the register view
    IS consistent only if the server misbehaved; here we forge the
    mutual-exclusion break directly)."""
    from maelstrom_tpu.history import History, Op
    from maelstrom_tpu.workloads.lin_mutex import FREE, LinMutexChecker

    def cas(t0, t1, frm, to, proc, typ="ok"):
        return [Op(type="invoke", f="cas", value=[0, [frm, to]],
                   process=proc, time=t0),
                Op(type=typ, f="cas", value=[0, [frm, to]],
                   process=proc, time=t1)]

    # both workers acquire ok, sequentially, no release: impossible
    h = (cas(0, 1, FREE, 2, 0) + cas(2, 3, FREE, 3, 1))
    r = LinMutexChecker().check({}, History([o for p in [h] for o in p]))
    assert r["valid"] is False, r
    assert r["mutex"]["valid"] is False

    # legal handoff: init, acquire(2), release(2), acquire(3)
    init = [Op(type="invoke", f="write", value=[0, FREE], process=0,
               time=-2),
            Op(type="ok", f="write", value=[0, FREE], process=0,
               time=-1)]
    h2 = (init + cas(0, 1, FREE, 2, 0) + cas(2, 3, 2, FREE, 0)
          + cas(4, 5, FREE, 3, 1))
    r2 = LinMutexChecker().check({}, History(h2))
    assert r2["valid"] is True, r2


def test_lin_mutex_checker_rejects_foreign_release():
    """A release by a worker that never held the lock linearizes
    nowhere under the holder-aware model."""
    from maelstrom_tpu.history import History, Op
    from maelstrom_tpu.workloads.lin_mutex import FREE, LinMutexChecker

    ops = [Op(type="invoke", f="cas", value=[0, [FREE, 2]], process=0,
              time=0),
           Op(type="ok", f="cas", value=[0, [FREE, 2]], process=0,
              time=1),
           # worker 1 "releases" holder 3's lock — never acquired
           Op(type="invoke", f="cas", value=[0, [3, FREE]], process=1,
              time=2),
           Op(type="ok", f="cas", value=[0, [3, FREE]], process=1,
              time=3)]
    r = LinMutexChecker().check({}, History(ops))
    assert r["valid"] is False, r
