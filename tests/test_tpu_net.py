"""Semantics tests for the TPU flight-pool network (`maelstrom_tpu.net.tpu`),
mirroring the reference network behaviors in `src/maelstrom/net.clj`:
deadline-ordered delivery, loss at send, partitions consumed at receive,
client zero latency, backpressure instead of silent drops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.net import tpu as T


def mk(cfg, msgs):
    """Builds a flat Msgs batch from (src, dest, type, a) tuples."""
    M = len(msgs)
    out = T.Msgs.empty(max(M, 1))
    if not msgs:
        return out
    src, dest, typ, a = map(jnp.array, zip(*msgs))
    return out.replace(valid=jnp.ones(M, bool), src=src.astype(T.I32),
                       dest=dest.astype(T.I32), type=typ.astype(T.I32),
                       a=a.astype(T.I32))


def pump(cfg, net, key=None, rounds=1):
    """Advance `rounds` rounds with no node logic, collecting deliveries."""
    inboxes, client_batches = [], []
    for _ in range(rounds):
        net, inbox, cmsgs = T.deliver(cfg, net)
        inboxes.append(jax.device_get(inbox))
        client_batches.append(jax.device_get(cmsgs))
        net = T.advance(net)
    return net, inboxes, client_batches


def test_send_deliver_roundtrip():
    cfg = T.NetConfig(n_nodes=3, n_clients=1, pool_cap=32, inbox_cap=4)
    net = T.make_net(cfg)
    key = jax.random.PRNGKey(0)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 7, 42), (2, 1, 7, 43)]), key)
    assert int(net.pool.count()) == 2
    net, inboxes, _ = pump(cfg, net, rounds=2)
    # zero latency config: due = round+1, delivered on round 1
    ib = inboxes[1]
    assert ib.valid[1].sum() == 2
    got = sorted(ib.a[1][ib.valid[1]].tolist())
    assert got == [42, 43]
    assert ib.valid[0].sum() == 0 and ib.valid[2].sum() == 0
    assert int(net.pool.count()) == 0
    st = T.stats_dict(net)
    assert st["sent_all"] == 2 and st["recv_all"] == 2
    assert st["sent_servers"] == 2 and st["recv_servers"] == 2


def test_message_ids_unique_and_monotonic():
    cfg = T.NetConfig(n_nodes=2, pool_cap=16)
    net = T.make_net(cfg)
    k = jax.random.PRNGKey(0)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 0), (1, 0, 1, 0)]), k)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 0)]), k)
    pool = jax.device_get(net.pool)
    mids = sorted(pool.mid[pool.valid].tolist())
    assert mids == [0, 1, 2]
    assert int(net.next_mid) == 3


def test_latency_rounds_delay_delivery():
    cfg = T.NetConfig(n_nodes=2, pool_cap=16, latency_mean_rounds=3,
                      latency_dist="constant")
    net = T.make_net(cfg)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 9)]), jax.random.PRNGKey(0))
    net, inboxes, _ = pump(cfg, net, rounds=5)
    per_round = [ib.valid.sum() for ib in inboxes]
    # deadline = now + latency (net.clj:201-204): due = 0 + 3 -> round 3
    assert per_round == [0, 0, 0, 1, 0]


def test_client_zero_latency_and_extraction():
    cfg = T.NetConfig(n_nodes=2, n_clients=1, pool_cap=16,
                      latency_mean_rounds=50, latency_dist="constant")
    net = T.make_net(cfg)
    k = jax.random.PRNGKey(1)
    # client (index 2) -> node 0, and node 0 -> client: both bypass latency
    net, _ = T.send(cfg, net, mk(cfg, [(2, 0, 1, 1), (0, 2, 2, 2)]), k)
    net, inboxes, cmsgs = pump(cfg, net, rounds=2)
    assert inboxes[1].valid.sum() == 1          # client -> node arrived
    cb = cmsgs[1]
    assert cb.valid.sum() == 1 and cb.a[cb.valid].tolist() == [2]
    st = T.stats_dict(net)
    assert st["sent_servers"] == 0 and st["recv_servers"] == 0
    assert st["recv_all"] == 2


def test_earliest_due_wins_inbox_slots_backpressure():
    # 6 messages due the same round to one node with inbox_cap=2: the two
    # earliest-due arrive first; the rest stay pooled (no drops).
    cfg = T.NetConfig(n_nodes=2, pool_cap=32, inbox_cap=2)
    net = T.make_net(cfg)
    out = T.Msgs.empty(6)
    out = out.replace(valid=jnp.ones(6, bool),
                      src=jnp.zeros(6, T.I32),
                      dest=jnp.ones(6, T.I32),
                      type=jnp.ones(6, T.I32),
                      a=jnp.arange(6, dtype=T.I32))
    net, _ = T.send(cfg, net, out, jax.random.PRNGKey(0))
    # hand-tweak due rounds: msgs 4,5 due earliest
    pool = net.pool
    due = jnp.where(pool.valid & (pool.a >= 4), 1, 2)
    net = net.replace(pool=pool.replace(due=jnp.where(pool.valid, due,
                                                      pool.due)))
    net, inboxes, _ = pump(cfg, net, rounds=4)
    r1 = inboxes[1]
    assert sorted(r1.a[1][r1.valid[1]].tolist()) == [4, 5]
    r2 = inboxes[2]
    assert r2.valid[1].sum() == 2
    r3 = inboxes[3]
    assert r3.valid[1].sum() == 2
    st = T.stats_dict(net)
    assert st["dropped_overflow"] == 0 and st["recv_all"] == 6


def test_loss_at_send():
    cfg = T.NetConfig(n_nodes=2, pool_cap=2048)
    net = T.make_net(cfg)
    net = T.flaky(net, 0.5)
    M = 1000
    out = T.Msgs.empty(M).replace(
        valid=jnp.ones(M, bool), src=jnp.zeros(M, T.I32),
        dest=jnp.ones(M, T.I32), type=jnp.ones(M, T.I32),
        a=jnp.arange(M, dtype=T.I32))
    net, _ = T.send(cfg, net, out, jax.random.PRNGKey(7))
    st = T.stats_dict(net)
    assert st["sent_all"] == M                  # journal logs before loss
    assert 350 < st["lost"] < 650
    assert int(net.pool.count()) == M - st["lost"]
    assert int(net.next_mid) == M               # lost msgs still consume ids


def test_partition_consumes_messages():
    cfg = T.NetConfig(n_nodes=4, n_clients=1, pool_cap=32)
    net = T.make_net(cfg)
    k = jax.random.PRNGKey(0)
    net = T.partition_components(net, [0, 0, 1, 1])
    msgs = [(0, 2, 1, 1),    # cross-partition: consumed + dropped
            (0, 1, 1, 2),    # same side: delivered
            (2, 3, 1, 3),    # same side: delivered
            (4, 2, 1, 4),    # client -> node: partitions never block clients
            (2, 4, 2, 5)]    # node -> client: same
    net, _ = T.send(cfg, net, mk(cfg, msgs), k)
    net, inboxes, cmsgs = pump(cfg, net, rounds=2)
    ib = inboxes[1]
    assert ib.a[1][ib.valid[1]].tolist() == [2]
    got2 = sorted(ib.a[2][ib.valid[2]].tolist())
    assert got2 == [4]                          # msg 1 blocked
    assert ib.a[3][ib.valid[3]].tolist() == [3]
    assert cmsgs[1].a[cmsgs[1].valid].tolist() == [5]
    st = T.stats_dict(net)
    assert st["dropped_partition"] == 1
    assert int(net.pool.count()) == 0           # blocked msg was consumed
    # heal clears components
    net = T.heal(net)
    assert jax.device_get(net.component).tolist() == [0] * 5


def test_pool_overflow_counted():
    cfg = T.NetConfig(n_nodes=2, pool_cap=4)
    net = T.make_net(cfg)
    out = mk(cfg, [(0, 1, 1, i) for i in range(6)])
    net, _ = T.send(cfg, net, out, jax.random.PRNGKey(0))
    st = T.stats_dict(net)
    assert st["dropped_overflow"] == 2
    assert int(net.pool.count()) == 4


def test_client_cap_zero_counts_without_materializing():
    cfg = T.NetConfig(n_nodes=2, n_clients=1, pool_cap=16, client_cap=0)
    net = T.make_net(cfg)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 2, 1, 1), (0, 1, 1, 2)]),
                 jax.random.PRNGKey(0))
    net, inboxes, cmsgs = pump(cfg, net, rounds=2)
    assert cmsgs[1].valid.shape == (0,)
    assert inboxes[1].valid.sum() == 1
    st = T.stats_dict(net)
    assert st["recv_all"] == 2          # client msg consumed and counted
    assert int(net.pool.count()) == 0


def test_slow_fast_latency_scale():
    cfg = T.NetConfig(n_nodes=2, pool_cap=16, latency_mean_rounds=2,
                      latency_dist="constant")
    net = T.make_net(cfg)
    net = T.slow(net, 3.0)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 1)]), jax.random.PRNGKey(0))
    pool = jax.device_get(net.pool)
    assert pool.due[pool.valid].tolist() == [6]     # 0 + 2*3
    net = T.fast(net)
    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 2)]), jax.random.PRNGKey(1))
    pool = jax.device_get(net.pool)
    assert sorted(pool.due[pool.valid].tolist()) == [2, 6]


def test_uniform_and_exponential_latency_distributions():
    for dist, lo, hi in [("uniform", 0, 20), ("exponential", 0, 200)]:
        cfg = T.NetConfig(n_nodes=2, pool_cap=4096, latency_mean_rounds=10,
                          latency_dist=dist)
        net = T.make_net(cfg)
        M = 2000
        out = T.Msgs.empty(M).replace(
            valid=jnp.ones(M, bool), src=jnp.zeros(M, T.I32),
            dest=jnp.ones(M, T.I32), type=jnp.ones(M, T.I32),
            a=jnp.arange(M, dtype=T.I32))
        net, _ = T.send(cfg, net, out, jax.random.PRNGKey(3))
        pool = jax.device_get(net.pool)
        lat = pool.due[pool.valid] - 1
        assert lat.min() >= lo
        assert abs(float(lat.mean()) - 10) < 1.5, dist
        if dist == "uniform":
            assert lat.max() <= hi


def test_deliver_under_jit_and_scan():
    """The whole round loop must compile: deliver + send under lax.scan."""
    cfg = T.NetConfig(n_nodes=4, pool_cap=64, inbox_cap=4)
    net = T.make_net(cfg)
    # each node sends to (i+1) % 4 every round; run 10 rounds in one scan
    def body(carry, _):
        net, key = carry
        key, k = jax.random.split(key)
        net, inbox, _ = T.deliver(cfg, net)
        # forward every received message to the next node
        out = jax.tree.map(lambda f: f.reshape((-1,) + f.shape[2:]), inbox)
        out = out.replace(src=out.dest,
                          dest=(out.dest + 1) % cfg.n_nodes)
        net, _ = T.send(cfg, net, out, k)
        net = T.advance(net)
        return (net, key), inbox.count()

    net, _ = T.send(cfg, net, mk(cfg, [(0, 1, 1, 5)]), jax.random.PRNGKey(0))

    @jax.jit
    def run(net, key):
        (net, _), counts = jax.lax.scan(body, (net, key), None, length=10)
        return net, counts

    net, counts = run(net, jax.random.PRNGKey(1))
    assert int(counts.sum()) == 9       # delivered once per round from r1
    st = T.stats_dict(net)
    assert st["recv_all"] == 9 and st["dropped_overflow"] == 0


def test_sent_by_type_counters():
    """The per-RPC-type device counters: pool sends bucket by wire type
    code, summed correctly across rounds (the journal-fold breakdown at
    bench scale)."""
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T

    cfg = T.NetConfig(n_nodes=2, n_clients=0, pool_cap=16, inbox_cap=4)
    net = T.make_net(cfg)
    key = jax.random.PRNGKey(0)
    m = T.Msgs.empty(3).replace(
        valid=jnp.array([True, True, False]),
        src=jnp.array([0, 1, 0]), dest=jnp.array([1, 0, 1]),
        type=jnp.array([10, 10, 12]))
    net, _ = T._send(cfg, net, m, key)
    m2 = m.replace(type=jnp.array([12, 10, 10]))
    net, _ = T._send(cfg, net, m2, key)
    st = T.stats_dict(net)
    assert st["sent_by_type"] == {10: 3, 12: 1}, st["sent_by_type"]
    assert st["sent_all"] == 4
