"""Streaming kafka: the consumer-group protocol (doc/streams.md) —
wire packing, the deterministic round-robin assignment, device-side
eviction + generation fencing, the host session state machine, the
streaming checker rules, and the end-to-end rebalance loop the kill
nemesis drives."""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu import core
from maelstrom_tpu.checkers.kafka import KafkaChecker
from maelstrom_tpu.history import History, Op
from maelstrom_tpu.net.static import EdgeMsgs
from maelstrom_tpu.net.tpu import Msgs
from maelstrom_tpu.nodes import Intern, get_program
from maelstrom_tpu.nodes.kafka import (T_FETCH, T_FETCH_OK, T_GCOMMIT,
                                       T_GCOMMIT_OK, T_REBAL, T_SUB,
                                       T_SUB_OK, _unpack_assign)

STORE = "/tmp/maelstrom-tpu-test-store"


def _program(groups=2, n=3, conc=6, **opts):
    o = {"key_count": 4, "kafka_groups": groups, "concurrency": conc,
         "rate": 10, "time_limit": 3, "session_timeout_ms": 100.0,
         "ms_per_round": 1.0}
    o.update(opts)
    return get_program("kafka", o, [f"n{i}" for i in range(n)])


# --- packing + assignment ---------------------------------------------------


def test_assign_pack_roundtrip():
    p = _program()
    asg = jnp.asarray([[0, 3, -1, 5]], jnp.int32)      # [N=1, K=4]
    b, c = p._pack_assign(asg)
    got = _unpack_assign(int(b[0]), int(c[0]), 4)
    assert got == {0: 0, 1: 3, 2: None, 3: 5}


def test_assignment_is_rank_round_robin():
    """Key k goes to the member of rank (k mod count) in member-id
    order — the pure function of membership device and host share."""
    p = _program(groups=1, conc=8)
    act = np.zeros((1, 1, 8), bool)
    act[0, 0, [2, 5, 7]] = True          # members 2, 5, 7 active
    asg = np.asarray(p._assign_members(jnp.asarray(act)))[0, 0]
    # ranks: 2->0, 5->1, 7->2; keys 0..3 -> ranks 0,1,2,0
    assert list(asg) == [2, 5, 7, 2]
    # nobody active: all keys unassigned
    none = np.asarray(p._assign_members(jnp.zeros((1, 1, 8), bool)))
    assert (none == -1).all()


# --- device: eviction, fencing, rebalance -----------------------------------


def _step(p, state, rnd, client_rows=()):
    """One edge_step with an empty network and the given client slots:
    [(node, slot, type, a, b, c), ...]."""
    N, D, K, A = p.n_nodes, p.D, p.lanes, p.inbox_cap
    edge_in = EdgeMsgs.empty((N, D, K))
    client = Msgs.empty((N, A))
    for node, slot, t, a, b, c in client_rows:
        client = client.replace(
            valid=client.valid.at[node, slot].set(True),
            src=client.src.at[node, slot].set(N + (a & 1023)),
            type=client.type.at[node, slot].set(t),
            a=client.a.at[node, slot].set(a),
            b=client.b.at[node, slot].set(b),
            c=client.c.at[node, slot].set(c))
    s2, _eo, out = p.edge_step(state, edge_in, client,
                               {"round": jnp.int32(rnd),
                                "key": None})
    return s2, out


def test_device_join_evict_fence_cycle():
    p = _program(groups=1, n=3, conc=4, session_timeout_ms=50.0)
    s = p.init_state()
    # member 1 subscribes at round 1 (coordinator = node 0)
    s, out = _step(p, s, 1, [(0, 0, T_SUB, (0 << 10) | 1, 0, 0)])
    assert int(out.type[0, 0]) == T_SUB_OK
    gen1 = int(out.a[0, 0])
    assert gen1 == 1                      # first join bumped the gen
    assert bool(s["gactive"][0, 0, 1])
    # a matching-generation commit is accepted
    s, out = _step(p, s, 10,
                   [(0, 0, T_GCOMMIT, (0 << 26) | (1 << 16) | gen1,
                     0, 0)])
    assert int(out.type[0, 0]) == T_GCOMMIT_OK
    # silence past the session timeout evicts the member + bumps gen
    s, _ = _step(p, s, 100)
    assert not bool(s["gactive"][0, 0, 1])
    assert int(s["ggen"][0, 0]) == gen1 + 1
    # the stale-generation commit is FENCED: rejected with T_REBAL,
    # member rejoined, generation bumped again
    s, out = _step(p, s, 101,
                   [(0, 0, T_GCOMMIT, (0 << 26) | (1 << 16) | gen1,
                     0, 0)])
    assert int(out.type[0, 0]) == T_REBAL
    assert int(out.a[0, 0]) == gen1 + 2
    assert bool(s["gactive"][0, 0, 1])


def test_device_fetch_is_cursor_sized_not_prefix():
    p = _program(groups=1, n=3, conc=4)
    s = p.init_state()
    # key 1 (owner = node 1) gets 5 entries on node 1's replica
    s = dict(s)
    s["log_len"] = s["log_len"].at[1, 1].set(5)
    # fetch key 1 from cursor 3, batch 2, served by node 1
    s, out = _step(p, s, 5,
                   [(1, 0, T_FETCH, (0 << 10) | 2,
                     (1 << 16) | (3 + 1), 2)])
    assert int(out.type[1, 0]) == T_FETCH_OK
    assert int(out.a[1, 0]) >> 16 == 1                 # key
    assert (int(out.a[1, 0]) & 0xFFFF) - 1 == 3        # start = cursor
    assert int(out.b[1, 0]) == 2                       # n = batch, not 5
    # cursor at the head: nothing to return
    s, out = _step(p, s, 6,
                   [(1, 0, T_FETCH, (0 << 10) | 2,
                     (1 << 16) | (5 + 1), 2)])
    assert int(out.b[1, 0]) == 0


# --- host session state machine ---------------------------------------------


def test_host_session_subscribe_fetch_commit_flow():
    p = _program(groups=2, conc=6)
    intern = Intern()
    # worker 0 (group 0) polls before subscribing -> subscribe request,
    # coordinator-routed
    op = {"f": "poll", "process": 0, "value": None}
    assert p.node_for_op(op) == 0
    body = p.request_for_op(op)
    assert body["type"] == "subscribe" and body["group"] == 0
    # the reply assigns keys; poll completion is an empty observation
    done = p.completion(op, {"type": "subscribe_ok", "gen": 1,
                             "assign": {0: 0, 1: 3, 2: 0, 3: 3}},
                        lambda: None, intern)
    assert done["type"] == "ok" and done["value"] == {}
    sub = p._subs[0]
    assert sub["keys"] == [0, 2] and sub["gen"] == 1
    # now polls round-robin cursor fetches over the assigned keys
    b1 = p.request_for_op(op)
    b2 = p.request_for_op(op)
    assert [b1["type"], b2["type"]] == ["fetch", "fetch"]
    assert {b1["key"], b2["key"]} == {0, 2}
    assert b1["cursor"] == 0
    # a commit claims exactly the consumed cursors (none yet -> empty,
    # still a real round trip: the heartbeat)
    bc = p.request_for_op({"f": "commit", "process": 0, "value": None})
    assert bc["type"] == "commit_group" and bc["offsets"] == {}
    # a fenced commit's rebalance reply rejoins and fails the op
    done = p.completion({"f": "commit", "process": 0, "value": None},
                        {"type": "rebalance", "gen": 3,
                         "assign": {0: 0, 1: 0, 2: 0, 3: 0}},
                        lambda: None, intern)
    assert done["type"] == "fail" and done["error"][0] == "rebalanced"
    assert p._subs[0]["gen"] == 3
    assert p._subs[0]["keys"] == [0, 1, 2, 3]


def test_host_state_roundtrip():
    p = _program()
    p._subs[1] = {"group": 1, "gen": 2, "keys": [1], "rr": 3,
                  "cursors": {1: 4}, "known_commit": {1: 3}}
    p._host_polled["0"] = 7
    st = p.host_state()
    q = _program()
    q.set_host_state(st)
    assert q._subs == p._subs and q._host_polled == p._host_polled


# --- streaming checker rules ------------------------------------------------


def _h(ops):
    return History([Op(**o) for o in ops])


def _op(f, t, value, type="ok", process=0):
    return [
        {"type": "invoke", "f": f, "process": process, "time": t,
         "value": None},
        {"type": type, "f": f, "process": process, "time": t + 1,
         "value": value},
    ]


STREAM = {"kafka_groups": 2}


def test_stream_cursor_fetch_not_flagged_as_truncated():
    # a fetch starting mid-log is the POINT of cursors: legal in
    # streaming mode, an order violation in classic mode
    ops = _op("poll", 0, {"0": [[3, 13], [4, 14]]})
    assert KafkaChecker().check(STREAM, _h(ops), {})["valid"] is True
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is False and "poll-order" in r


def test_stream_gap_inside_fetch_detected():
    ops = _op("poll", 0, {"0": [[3, 13], [5, 15]]})
    r = KafkaChecker().check(STREAM, _h(ops), {})
    assert r["valid"] is False
    assert r["poll-order"][0]["offsets"] == [3, 5]


def test_stream_lost_write_detected():
    # offset 1 acked, never observed; a later fetch reads past it
    ops = (_op("send", 0, ["0", 11, 1])
           + _op("poll", 10, {"0": [[2, 12], [3, 13]]}))
    r = KafkaChecker().check(STREAM, _h(ops), {})
    assert r["valid"] is False
    assert r["lost-writes"][0]["offset"] == 1


def test_stream_lost_write_not_flagged_when_observed_later():
    # a lagging group fetches offset 1 later: not lost
    ops = (_op("send", 0, ["0", 11, 1])
           + _op("poll", 10, {"0": [[2, 12]]})
           + _op("poll", 20, {"0": [[1, 11], [2, 12]]}, process=1))
    r = KafkaChecker().check(STREAM, _h(ops), {})
    assert r["valid"] is True


def test_stream_commit_monotone_per_group():
    # group 0 commits offset 5; group 1 may report less (separate
    # floors); group 0 reporting less is a regression
    ops = (_op("commit", 0, {"group": 0, "offsets": {"0": 5}})
           + _op("list", 10, {"group": 1, "offsets": {"0": 2}},
                 process=1))
    assert KafkaChecker().check(STREAM, _h(ops), {})["valid"] is True
    ops2 = (_op("commit", 0, {"group": 0, "offsets": {"0": 5}})
            + _op("list", 10, {"group": 0, "offsets": {"0": 2}}))
    r = KafkaChecker().check(STREAM, _h(ops2), {})
    assert r["valid"] is False
    assert r["commit-regressions"][0] == {
        "key": "0", "committed": 5, "observed": 2, "group": 0}


def test_stream_rebalanced_commit_constrains_nothing():
    ops = (_op("commit", 0, None, type="fail")
           + _op("subscribe", 5, {"gen": 2, "assigned": [0, 1]},
                 process=1)
           + _op("list", 10, {"group": 0, "offsets": {}}))
    r = KafkaChecker().check(STREAM, _h(ops), {})
    assert r["valid"] is True


# --- end to end -------------------------------------------------------------


def test_kafka_groups_e2e_round_synchronous():
    """Group mode works in the ROUND-SYNCHRONOUS runner too (continuous
    is orthogonal): subscriptions, cursor fetches, commits — valid."""
    res = core.run(dict(store_root=STORE, seed=11, workload="kafka",
                        node="tpu:kafka", node_count=5, rate=20.0,
                        time_limit=3.0, journal_rows=False,
                        kafka_groups=2))
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["acked-sends"] > 0 and w["polls"] > 0


@pytest.mark.slow
def test_kafka_rebalance_driven_by_kill():
    """The kill nemesis drives the rebalance loop: killed bound nodes
    park members on RPC timeouts, the coordinator evicts them
    (generation bump), and their return is fenced + rejoined — visible
    as 'rebalanced' commit fails and multi-generation subscriptions,
    while the stream still grades valid."""
    res = core.run(dict(store_root=STORE, seed=23, workload="kafka",
                        node="tpu:kafka", node_count=5, rate=30.0,
                        time_limit=4.0, journal_rows=False,
                        kafka_groups=2, continuous=True,
                        session_timeout_ms=400.0, timeout_ms=800,
                        recovery_s=1.5, nemesis={"kill"},
                        nemesis_interval=0.8))
    assert res["valid"] is True, res["workload"]
    with open(f"{STORE}/latest/history.jsonl") as f:
        hist = [json.loads(line) for line in f]
    fenced = [o for o in hist if o.get("f") == "commit"
              and o["type"] == "fail"
              and (o.get("error") or [None])[0] == "rebalanced"]
    gens = [o["value"]["gen"] for o in hist
            if o.get("f") == "subscribe" and o["type"] == "ok"
            and isinstance(o.get("value"), dict) and "gen" in o["value"]]
    # membership actually churned: fenced commits happened, or a late
    # subscription saw a bumped generation
    assert fenced or (gens and max(gens) > 1), (len(fenced), gens)


def test_banked_offset_pack_roundtrip():
    """The bank-split commit wire (key_count <= 8): offsets for keys
    4..7 pack into the same two words as keys 0..3, labeled by the
    header's bank bit on the way back."""
    from maelstrom_tpu.nodes.kafka import (BANK_KEYS, _pack_offsets,
                                           _unpack_offsets)
    offs = {"4": 7, "6": 123, "7": 0}
    a, b, _c = _pack_offsets(offs, BANK_KEYS, base=BANK_KEYS)
    got = _unpack_offsets(a, b, 0, BANK_KEYS, base=BANK_KEYS)
    assert got == offs
    # bank 0 stays bit-identical to the pre-bank layout
    offs0 = {"0": 1, "3": 9}
    a0, b0, _ = _pack_offsets(offs0, BANK_KEYS)
    assert _unpack_offsets(a0, b0, 0, BANK_KEYS) == offs0


def test_wide_keys_kill_nemesis_regression():
    """The PR 7 known restriction, lifted: key_count=8 group mode under
    the kill nemesis grades valid, and committed floors advance in BOTH
    banks (commits rotate banks, lists declare their observed bank)."""
    res = core.run(dict(store_root=STORE, seed=5, rate=60.0,
                        time_limit=4.0, journal_rows=False,
                        workload="kafka", node="tpu:kafka",
                        node_count=5, concurrency=8, key_count=8,
                        kafka_groups=2, session_timeout_ms=400.0,
                        timeout_ms=800, recovery_s=1.5,
                        nemesis={"kill"}, nemesis_interval=0.9,
                        audit=False))
    w = res["workload"]
    assert res["valid"] is True, w
    assert w["valid"] is True
    assert w["acked-sends"] > 10
    banks = {0: set(), 1: set()}
    for o in res_history(STORE):
        if o.get("f") in ("commit", "list") and o["type"] == "ok" \
                and isinstance(o.get("value"), dict):
            for k in (o["value"].get("offsets") or {}):
                banks[int(k) // 4].add(k)
    assert banks[0] and banks[1], banks


def res_history(store):
    with open(f"{store}/latest/history.jsonl") as f:
        return [json.loads(line) for line in f]


def test_kafka_groups_rejects_bad_shapes():
    # banked commits lifted the old key_count<=4 / groups<=8 caps: group
    # mode now runs up to 8 keys (two 4-key commit banks) and 16 groups
    # (4 header bits); past those the wire genuinely has no room
    with pytest.raises(ValueError, match="keys"):
        _program(groups=2, key_count=9)
    with pytest.raises(ValueError, match="kafka_groups"):
        _program(groups=17)
    # classic mode keeps the 3-word cap (poll/commit replies ride a|b|c)
    with pytest.raises(ValueError, match="keys"):
        _program(groups=0, key_count=7)
    _program(groups=2, key_count=8)     # the lifted shape builds
    _program(groups=16, key_count=6)
