"""Role-partitioned cluster regression pins (ISSUE 10).

A `RolePartition` with a single homogeneous role is PURE DELEGATION:
same PRNG stream, same inbox/outbox shapes, bit-identical histories to
running the inner program directly — for the edge path (raft,
broadcast), plain and `--mesh 1,2`, and under the combined nemesis.
`--node tpu:solo:<program>` is the CLI surface for this configuration.
"""

import os

import pytest

from maelstrom_tpu import core
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.sim import RolePartition

STORE = "/tmp/maelstrom-role-partition-store"


def _run(store, opts):
    base = dict(store_root=store, seed=7, rate=20.0, time_limit=2.0,
                journal_rows=False, audit=False)
    return core.run({**base, **opts})


def _history(store):
    with open(os.path.join(store, "latest", "history.jsonl"),
              "rb") as f:
        return f.read()


def _pin_identity(opts, tag):
    a = f"{STORE}-{tag}-a"
    b = f"{STORE}-{tag}-b"
    res1 = _run(a, opts)
    res2 = _run(b, {**opts, "node": "tpu:solo:"
                    + opts["node"][len("tpu:"):]})
    assert res1["valid"] is True, res1.get("workload")
    assert res2["valid"] is True, res2.get("workload")
    assert _history(a) == _history(b), \
        f"solo-wrapped {opts['node']} diverged from the direct run"
    assert res1["workload"] == res2["workload"]


def test_solo_wrapper_is_role_partition():
    prog = get_program("solo:lin-kv",
                       {"rate": 5, "time_limit": 1}, [f"n{i}"
                                                      for i in range(5)])
    assert isinstance(prog, RolePartition)
    assert prog.is_edge                      # raft delegates its edges
    assert prog.fault_groups() == {"r0": [f"n{i}" for i in range(5)]}


def test_solo_raft_bit_identical_plain():
    """lin-kv on raft: the edge path through a one-role partition is
    bit-identical to today's single-program sim."""
    _pin_identity({"workload": "lin-kv", "node": "tpu:lin-kv"}, "raft")


@pytest.mark.slow
def test_solo_broadcast_bit_identical_combined_nemesis():
    """broadcast under kill,pause,partition,duplicate: durable views,
    kill/restart, freeze masks, and duplication all flow through the
    partition's delegation unchanged."""
    _pin_identity({"workload": "broadcast", "node": "tpu:broadcast",
                   "topology": "grid", "time_limit": 3.0,
                   "nemesis": {"kill", "pause", "partition",
                               "duplicate"},
                   "nemesis_interval": 0.7, "recovery_s": 2},
                  "broadcast-soup")


@pytest.mark.multichip
@pytest.mark.slow
def test_solo_raft_bit_identical_mesh():
    """`--mesh 1,2`: the sharded scan sees identical shapes and
    shardings through the partition wrapper."""
    _pin_identity({"workload": "lin-kv", "node": "tpu:lin-kv",
                   "mesh": "1,2"}, "raft-mesh")


@pytest.mark.slow
def test_solo_raft_bit_identical_combined_nemesis():
    _pin_identity({"workload": "lin-kv", "node": "tpu:lin-kv",
                   "time_limit": 3.0,
                   "nemesis": {"kill", "pause", "partition",
                               "duplicate"},
                   "nemesis_interval": 0.7, "recovery_s": 2},
                  "raft-soup")


@pytest.mark.multichip
@pytest.mark.slow
def test_solo_broadcast_bit_identical_mesh_nemesis():
    _pin_identity({"workload": "broadcast", "node": "tpu:broadcast",
                   "topology": "grid", "time_limit": 3.0, "mesh": "1,2",
                   "nemesis": {"kill", "pause", "partition",
                               "duplicate"},
                   "nemesis_interval": 0.7, "recovery_s": 2},
                  "broadcast-mesh-soup")


def test_partition_rejects_bad_role_sum():
    import jax.numpy as jnp  # noqa: F401

    inner = get_program("echo", {}, ["n0", "n1", "n2"])
    with pytest.raises(ValueError, match="role sizes"):
        RolePartition({}, ["n0", "n1"], [("r0", inner)])


def test_partition_rejects_multi_role_edge():
    opts = {"rate": 5, "time_limit": 1}
    raft = get_program("lin-kv", opts, ["n0", "n1", "n2"])
    echo = get_program("echo", opts, ["n3", "n4"])
    with pytest.raises(ValueError, match="single role"):
        RolePartition(opts, [f"n{i}" for i in range(5)],
                      [("kv", raft), ("echo", echo)])
