"""Regression tests for op spreading and schema nullability — two small
host-side utilities whose failure modes were subtle (rotation aliasing
pinned all client traffic to one node; strict read schemas rejected null
reads of absent keys)."""

from maelstrom_tpu import schema as S
from maelstrom_tpu.generators import rotate_free


def test_rotate_free_spreads_over_even_pool_under_serial_load():
    """Serial load: all workers free at every dispatch. The rotation must
    still visit every worker (keying on history length, which grows by 2
    per op, would alias an even pool and pin everything to worker 0)."""
    free = {0, 1}
    seen = set()
    for dispatch in range(4):
        seen.add(rotate_free(free, dispatch)[0])
    assert seen == {0, 1}


def test_rotate_free_covers_all_workers():
    free = {0, 1, 2, "nemesis"}
    firsts = [rotate_free(free, d)[0] for d in range(8)]
    assert set(firsts) == {0, 1, 2, "nemesis"}


def test_rotate_free_empty():
    assert rotate_free(set(), 3) == []


def test_schema_maybe_allows_null_and_checks_inner():
    sch = S.Maybe([S.Any])
    assert S.check(sch, None) is None
    assert S.check(sch, [1, 2]) is None
    assert S.check(sch, "nope") is not None


def test_txn_read_result_schema_accepts_null_reads():
    from maelstrom_tpu.workloads.txn_list_append import ReadRes
    assert S.check(ReadRes, ["r", 5, None]) is None
    assert S.check(ReadRes, ["r", 5, [1, 2]]) is None
    assert S.check(ReadRes, ["append", 5, 1]) is not None
