"""The static determinism & hot-path auditor (maelstrom_tpu.analyze).

Two halves, mirroring the acceptance contract:

  - seeded-violation fixtures: for every rule, a minimal step function
    (or source snippet) that CONTAINS the hazard, asserting the rule id
    fires exactly once — including a regression fixture reproducing the
    PR 2 unstable-delivery-sort-under-mesh bug shape;
  - the zero-new-findings gate: the REAL production `round_fn`/`scan_fn`
    (plain and 2-device `--mesh`) trace clean against the checked-in
    `analyze/baseline.json`, and the hot host modules lint clean.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import maelstrom_tpu.analyze as analyze
from maelstrom_tpu.analyze import (Baseline, Finding, apply_baseline,
                                   dedupe_sites, jaxpr_audit, source_lint)
from maelstrom_tpu.analyze.jaxpr_audit import StepSpec, audit_step


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# seeded-violation fixtures: each hazard fires its rule id exactly once
# ---------------------------------------------------------------------------

def test_fixture_unstable_sort_fires_once():
    spec = StepSpec(name="fx", fn=lambda x: jnp.argsort(x, stable=False),
                    args=(jnp.arange(8, dtype=jnp.int32),))
    assert rules_of(audit_step(spec)) == ["unstable-sort"]


def test_stable_and_tiebroken_sorts_pass():
    """The two legal shapes: is_stable=True, and an explicit index
    tiebreak operand (the PR 2 fix, num_keys >= 2)."""
    def ok(x):
        a = jnp.argsort(x)                       # stable by default
        b = jnp.lexsort((jnp.arange(x.shape[0], dtype=jnp.int32), x))
        return a, b
    spec = StepSpec(name="fx", fn=ok,
                    args=(jnp.arange(8, dtype=jnp.int32),))
    assert rules_of(audit_step(spec)) == []


def test_fixture_host_callback_fires_once():
    def step(x):
        return jax.pure_callback(
            np.sin, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    spec = StepSpec(name="fx", fn=step, args=(jnp.ones(4, jnp.float32),))
    assert rules_of(audit_step(spec)) == ["host-transfer"]


def test_fixture_f64_promotion_fires_once():
    with jax.experimental.enable_x64():
        spec = StepSpec(name="fx", fn=lambda x: x * np.float64(2.0),
                        args=(jnp.ones(4, jnp.float32),))
        findings = audit_step(spec)
    assert rules_of(findings) == ["dtype-widening"]
    assert findings[0].detail == "float32 -> float64"


def test_fixture_aliased_donated_carry_fires_once():
    """The PR 2 dealias bug shape: one buffer appearing twice in a
    donated tree (Msgs.empty fan-out / durable_view views)."""
    a = jnp.zeros(8, jnp.int32)
    spec = StepSpec(name="fx", fn=lambda t: t[0] + t[1], args=((a, a),),
                    donate_argnums=(0,))
    assert rules_of(audit_step(spec)) == ["donation-alias"]
    # and the fix: a dealiased tree passes
    from maelstrom_tpu.sim import dealias
    spec2 = StepSpec(name="fx", fn=lambda t: t[0] + t[1],
                     args=(dealias((a, a)),), donate_argnums=(0,))
    assert rules_of(audit_step(spec2)) == []


def test_fixture_overlapping_scatter_fires_once():
    spec = StepSpec(
        name="fx",
        fn=lambda x: x.at[jnp.array([0, 0])].set(jnp.array([1, 2])),
        args=(jnp.zeros(4, jnp.int32),))
    assert rules_of(audit_step(spec)) == ["scatter-nonunique"]
    # scatter-add is combiner-commutative over ints: not flagged
    spec2 = StepSpec(
        name="fx",
        fn=lambda x: x.at[jnp.array([0, 0])].add(jnp.array([1, 2])),
        args=(jnp.zeros(4, jnp.int32),))
    assert rules_of(audit_step(spec2)) == []


@pytest.mark.multichip
def test_fixture_replicated_scatter_fires_once():
    """ISSUE 18: a scatter-SET traced while >= 2 mesh axes of size > 1
    are visible to GSPMD (the dp>1 x sp>1 regime that corrupted reply
    rows pre-PR-18) fires replicated-scatter exactly once; the same
    body run manual under shard_map is clean — per-shard scatters are
    local and no mesh axis is visible inside the region."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maelstrom_tpu import parallel
    mesh = parallel.mesh_from_spec("2,2")
    sh = NamedSharding(mesh, P("dp"))

    def body(x):
        row = jnp.ones((1, x.shape[1]), jnp.int32)
        return x.at[jnp.array([1])].set(row, unique_indices=True)

    x = jax.device_put(jnp.zeros((4, 8), jnp.int32), sh)
    spec = StepSpec(name="fx", fn=body, args=(x,), in_shardings=sh)
    assert rules_of(audit_step(spec)) == ["replicated-scatter"]

    # the PR 18 shape: the same scatter inside a full-manual shard_map
    # region (sim.fleet_shard_map's construction) — rule is quiet
    manual = shard_map(body, mesh, in_specs=P("dp"), out_specs=P("dp"),
                       check_rep=False)
    assert rules_of(audit_step(
        StepSpec(name="fx", fn=manual, args=(x,), in_shardings=sh))) == []
    # and a single->1 mesh (dp=2, sp=1) is NOT mixed: plain dp-sharded
    # scatters stay legal without shard_map
    mesh21 = parallel.mesh_from_spec("2,1")
    sh21 = NamedSharding(mesh21, P("dp"))
    x21 = jax.device_put(jnp.zeros((4, 8), jnp.int32), sh21)
    assert rules_of(audit_step(
        StepSpec(name="fx", fn=body, args=(x21,), in_shardings=sh21))) == []


@pytest.mark.multichip
def test_fixture_donation_reshard_fires_once():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maelstrom_tpu import parallel
    mesh = parallel.mesh_for(2, dp=1)
    sh_sp, sh_rep = NamedSharding(mesh, P("sp")), NamedSharding(mesh, P())
    # spec-declared fallback: the caller SAYS its pins disagree
    spec = StepSpec(name="fx", fn=lambda x: x + 1, args=(jnp.zeros(8),),
                    donate_argnums=(0,), in_shardings=sh_sp,
                    out_shardings=sh_rep)
    assert rules_of(audit_step(spec)) == ["donation-reshard"]


@pytest.mark.multichip
def test_fixture_donation_reshard_read_off_real_pjit_pins():
    """The strong form: the auditor reads donated_invars and the
    RESOLVED in/out shardings off the traced pjit equation itself, so a
    builder whose actual jit pins diverge is caught even when the spec
    declares nothing (and a self-consistent jit proves the negative)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maelstrom_tpu import parallel
    mesh = parallel.mesh_for(2, dp=1)
    sh_sp, sh_rep = NamedSharding(mesh, P("sp")), NamedSharding(mesh, P())
    x = jax.device_put(jnp.zeros(8), sh_sp)
    bad = jax.jit(lambda v: v + 1, donate_argnums=(0,),
                  in_shardings=(sh_sp,), out_shardings=sh_rep)
    spec = StepSpec(name="fx", fn=bad, args=(x,))   # no declared pins
    assert rules_of(audit_step(spec)) == ["donation-reshard"]
    ok = jax.jit(lambda v: v + 1, donate_argnums=(0,),
                 in_shardings=(sh_sp,), out_shardings=sh_sp)
    assert rules_of(audit_step(
        StepSpec(name="fx", fn=ok, args=(x,)))) == []


@pytest.mark.multichip
def test_pr2_regression_unstable_delivery_sort_under_mesh():
    """The PR 2 incident, reduced: a delivery-order argsort over a
    mesh-sharded due-round key with NO index tiebreak. Partitioned sorts
    don't preserve stability across shard merges, so equal-key ties
    diverged between --mesh and single-chip runs; the auditor must flag
    this shape statically."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maelstrom_tpu import parallel
    from maelstrom_tpu.net.tpu import INT32_MAX
    mesh = parallel.mesh_for(2, dp=1)
    sh = NamedSharding(mesh, P("sp"))

    def delivery_order(due, valid):
        key = jnp.where(valid, due, INT32_MAX)
        return jnp.argsort(key, stable=False)   # the pre-PR-2 bug shape

    fn = jax.jit(delivery_order, in_shardings=(sh, sh))
    args = (jax.device_put(jnp.zeros(16, jnp.int32), sh),
            jax.device_put(jnp.ones(16, bool), sh))
    sites = dedupe_sites(audit_step(
        StepSpec(name="pr2-regression", fn=fn, args=args)))
    assert rules_of(sites) == ["unstable-sort"]

    # and the shipped fix's shape — lexsort with the explicit index
    # tiebreak operand — is clean
    def fixed(due, valid):
        key = jnp.where(valid, due, INT32_MAX)
        return jnp.lexsort((jnp.arange(16, dtype=jnp.int32), key))
    fn2 = jax.jit(fixed, in_shardings=(sh, sh))
    assert rules_of(audit_step(
        StepSpec(name="pr2-fixed", fn=fn2, args=args))) == []


def test_fixture_donation_cpu_view_config_rule(monkeypatch):
    """The PR 2/4 runtime-config hazard: donation forced on while the
    backend is CPU (zero-copy device_get views + buffer recycling).
    Reported by the production self-report block."""
    monkeypatch.setenv("MAELSTROM_AUDIT", "")
    monkeypatch.setenv("MAELSTROM_DONATE", "1")

    class StubProgram:
        pass

    class StubRunner:
        program = StubProgram()
        cfg = "stub-cfg"
        _shardings = None
    block = analyze.audit_runner(StubRunner(), trace=False)
    assert block["ok"] is False
    assert [f["rule"] for f in block["new"]] == ["donation-cpu-view"]
    # donation off (the CPU default): clean
    monkeypatch.setenv("MAELSTROM_DONATE", "0")
    block = analyze.audit_runner(StubRunner(), trace=False)
    assert block["ok"] is True and block["new"] == []


# ---------------------------------------------------------------------------
# source-lint seeded violations
# ---------------------------------------------------------------------------

def test_lint_np_unstable_sort_fires():
    src = ("import numpy as np\n"
           "def pair(xs):\n"
           "    return np.argsort(xs)\n")
    assert rules_of(source_lint.lint_source(src, "fx.py")) == \
        ["np-unstable-sort"]
    ok = ("import numpy as np\n"
          "def pair(xs):\n"
          "    return np.argsort(xs, kind=\"stable\")\n")
    assert source_lint.lint_source(ok, "fx.py") == []


def test_lint_np_sort_fires_module_form_only():
    src = ("import numpy as np\n"
           "def f(xs):\n"
           "    b = np.sort(xs)\n")
    assert rules_of(source_lint.lint_source(src, "fx.py")) == \
        ["np-unstable-sort"]
    # method-form sorts are deliberately exempt: list.sort is stable,
    # and jax arrays' method sorts are stable by default (device sorts
    # are the jaxpr pass's job)
    ok = ("def f(parts, key):\n"
          "    parts.sort(key=repr)\n"
          "    return key.argsort()\n")
    assert source_lint.lint_source(ok, "fx.py") == []


def test_lint_set_iteration_fires():
    src = ("def f(pending, d):\n"
           "    for p in set(pending):\n"
           "        d[p] = 1\n"
           "    xs = [k for k in {1, 2, 3}]\n")
    assert rules_of(source_lint.lint_source(src, "fx.py")) == \
        ["set-iteration", "set-iteration"]
    ok = ("def f(pending):\n"
          "    for p in sorted(set(pending)):\n"
          "        pass\n")
    assert source_lint.lint_source(ok, "fx.py") == []


def test_lint_wall_clock_fires():
    src = ("import time\n"
           "def stamp():\n"
           "    return time.time()\n")
    assert rules_of(source_lint.lint_source(src, "fx.py")) == \
        ["wall-clock"]
    # duration accounting stays legal
    ok = ("import time\n"
          "def bench():\n"
          "    return time.perf_counter()\n")
    assert source_lint.lint_source(ok, "fx.py") == []


def test_lint_unseeded_random_fires():
    src = ("import random\n"
           "def jitter():\n"
           "    return random.random() + random.randint(0, 3)\n")
    assert rules_of(source_lint.lint_source(src, "fx.py")) == \
        ["unseeded-random", "unseeded-random"]
    ok = ("import random\n"
          "def jitter(seed):\n"
          "    rng = random.Random(seed)\n"
          "    return rng.random()\n")
    assert source_lint.lint_source(ok, "fx.py") == []


def test_lint_hot_modules_clean():
    """The shipped hot host modules lint clean against the baseline.
    The nondeterminism rules carry zero raw findings — deliberately NO
    suppression; the `thread-shared-mutation` sites (the pipeline and
    checkpoint-writer handshake flags) are the ONLY baselined lint
    exceptions, each with its happens-before argument."""
    findings = source_lint.lint_default_paths()
    extra = [f for f in findings if f.rule != "thread-shared-mutation"]
    assert extra == [], [f.as_dict() for f in extra]
    assert findings, "thread-shared-mutation sites vanished: prune " \
                     "the baseline suppressions"
    new, suppressed = apply_baseline(dedupe_sites(findings),
                                     Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    assert all(s.rule == "thread-shared-mutation" for s in suppressed)


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

def _site(rule, where, key):
    return Finding(rule=rule, where=where, key=key, entry="t")


def test_baseline_suppresses_up_to_max_sites_only():
    bl = Baseline(suppressions=[
        {"rule": "scatter-nonunique", "where": "m/x.py:f", "max_sites": 1,
         "reason": "t"}])
    one = [_site("scatter-nonunique", "m/x.py:3 (f)", "m/x.py:f")]
    new, suppressed = apply_baseline(one, bl)
    assert (len(new), len(suppressed)) == (0, 1)
    # a SECOND site in the same function exceeds the budget: the whole
    # group surfaces (re-baselining is an explicit, reviewed act)
    two = one + [_site("scatter-nonunique", "m/x.py:9 (f)", "m/x.py:f")]
    new, suppressed = apply_baseline(two, bl)
    assert (len(new), len(suppressed)) == (2, 0)
    assert all("exceeds baseline" in f.detail for f in new)


def test_baseline_never_crosses_rules():
    bl = Baseline(suppressions=[
        {"rule": "scatter-nonunique", "where": "m/x.py:f", "max_sites": 9,
         "reason": "t"}])
    new, suppressed = apply_baseline(
        [_site("unstable-sort", "m/x.py:3 (f)", "m/x.py:f")], bl)
    assert (len(new), len(suppressed)) == (1, 0)


def test_dedupe_merges_entries_across_variants():
    a = Finding(rule="unstable-sort", where="m/x.py:3 (f)", key="m/x.py:f",
                entry="round_fn")
    b = Finding(rule="unstable-sort", where="m/x.py:3 (f)", key="m/x.py:f",
                entry="scan_fn")
    sites = dedupe_sites([a, b])
    assert len(sites) == 1
    assert sorted(sites[0].entries) == ["round_fn", "scan_fn"]


# ---------------------------------------------------------------------------
# the zero-new-findings gate over the REAL production step functions
# ---------------------------------------------------------------------------

def test_gate_production_plain_round_and_scan_fns():
    """round_fn/scan_fn/scan_journal_fn for lin-kv (the raft-backed edge
    path through the flight pool), traced with donation forced on — the
    TPU configuration — must carry zero non-baselined findings, and the
    baseline's deliberate exceptions must actually match."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["lin-kv"], mesh=None)
    assert any(e.startswith("round_fn[") for e in entries)
    assert any(e.startswith("scan_fn[") for e in entries)
    new, suppressed = apply_baseline(dedupe_sites(findings),
                                     Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    assert suppressed, "baseline entries stopped matching: stale baseline"


def test_gate_traces_role_partitioned_step_fns():
    """ISSUE 10: the default program set traces the role-partitioned
    families — the compartmentalized consensus cluster and the
    in-cluster service nodes — so the PR 5 rules cover the
    RolePartition step path (per-role slicing, heterogeneous state
    tree, scatter-heavy table allocation) with zero non-baselined
    findings."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["compartment", "lin-tso"], mesh=None, fleet=False)
    assert any(e.startswith("round_fn[compartment") for e in entries)
    assert any(e.startswith("scan_fn[lin-tso") for e in entries)
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]


def test_gate_traces_continuous_scan_variant():
    """ISSUE 7: the default program set now traces the continuous-mode
    (`--continuous`) sched-inject scan, so the PR 5 rules cover the new
    injection path too — zero non-baselined findings."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["kafka"], mesh=None, fleet=False)
    assert any(e.startswith("cscan_fn[") for e in entries), entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]


def test_gate_traces_fleet_continuous_scan_variant():
    """ISSUE 12: the fleet program set now traces the vmapped
    sched-inject scan (`fleet_cscan_fn` — the `--fleet N --continuous`
    dispatch) next to the round-synchronous fleet scan, under the same
    zero-new-findings gate."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["lin-kv"], mesh=None, fleet=True)
    assert any(e.startswith("fleet_cscan_fn[") for e in entries), entries
    assert any(e.startswith("fleet_scan_fn[") for e in entries), entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]


def test_gate_traces_telemetry_ring_variants():
    """ISSUE 13: the gate traces ring-enabled (`--telemetry`) variants
    of one pool-path and one edge-path workload, proving the flight
    recorder's per-round fold (telemetry.ring_update) introduces no
    host transfers, unstable sorts, widenings, or non-unique scatters
    — zero NEW findings, and no telemetry-attributed finding needed
    baselining at all."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["lin-kv", "broadcast"], mesh=None, fleet=False)
    assert any("@telemetry]" in e and e.startswith("scan_fn[lin-kv")
               for e in entries), entries
    assert any("@telemetry]" in e and e.startswith("scan_fn[broadcast")
               for e in entries), entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    tel_hits = [f for f in findings
                if "telemetry" in f.key or "telemetry" in f.where]
    assert tel_hits == [], [f.as_dict() for f in tel_hits]


def test_gate_traces_device_checker_kernels():
    """ISSUE 11: the txn-list-append program set traces the
    device-resident checker's jitted entry points — the elle edge
    constructor and the cycle-screen fixed point
    (checkers/elle_device.py) — under the same zero-new-findings gate
    (no baseline exemption: the kernels use no device sorts, stay
    int32, and their only scatters are combiner segment-max)."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["txn-list-append"], mesh=None, fleet=False)
    assert "elle_edges_fn" in entries, entries
    assert "elle_screen_fn" in entries, entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    # and none of the checker findings needed baselining at all
    checker_hits = [f for f in findings
                    if f.entry in ("elle_edges_fn", "elle_screen_fn")]
    assert checker_hits == [], [f.as_dict() for f in checker_hits]


def test_gate_traces_byzantine_scan_variant():
    """ISSUE 16: the gate traces the byz-enabled compartment variant —
    the compiled corruption masks (byzantine.corrupt_pool) and the
    proxy tier's conviction lanes run INSIDE the audited round — at
    zero non-baselined findings, and no byzantine-attributed finding
    needed baselining at all."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["compartment-byzantine"], mesh=None, fleet=False)
    assert any(e.startswith("round_fn[compartment-byzantine")
               for e in entries), entries
    assert any(e.startswith("scan_fn[compartment-byzantine")
               for e in entries), entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    byz_hits = [f for f in findings
                if "byzantine" in f.key or "byzantine" in f.where]
    assert byz_hits == [], [f.as_dict() for f in byz_hits]


def test_fixture_violation_in_continuous_scan_path_fires():
    """A seeded hazard INSIDE the continuous scan body is caught through
    the cscan trace: an unstable argsort planted in a program step
    surfaces as exactly one unstable-sort site when the sched-inject
    scan is audited."""
    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.sim import make_scan_fn, make_sim

    program = get_program("echo", {}, ["n0", "n1", "n2"])
    orig = program.step

    def bad_step(state, inbox, ctx):
        state2, outbox = orig(state, inbox, ctx)
        order = jnp.argsort(inbox.mid[:, 0], stable=False)  # seeded bug
        return state2, outbox.replace(
            a=outbox.a + inbox.mid[order][0, 0] * 0)
    program.step = bad_step
    cfg = T.NetConfig(n_nodes=3, n_clients=2)
    sim = make_sim(program, cfg)
    inject = T.Msgs.empty(2)
    spec = StepSpec(
        name="cscan_fn[fx]",
        fn=make_scan_fn(program, cfg, reply_cap=8, sched_inject=True),
        args=(sim, inject, jnp.zeros(2, jnp.int32), jnp.int32(4), True))
    # the step appears in both the window's first round and the loop
    # body: dedupe collapses the two traces to the one seeded site
    sites = dedupe_sites(audit_step(spec))
    unstable = [s for s in sites if s.rule == "unstable-sort"]
    assert len(unstable) == 1, [s.as_dict() for s in sites]


@pytest.mark.multichip
def test_gate_production_mesh_round_and_scan_fns():
    """The --mesh 1,2 variants: same zero-new-findings bar with the
    sharding pins applied (in == out for the donated carry, so the
    donation-reshard rule also proves a negative here)."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["lin-kv"], mesh="1,2")
    assert any("@mesh=1,2" in e for e in entries)
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]


@pytest.mark.slow
@pytest.mark.multichip
def test_gate_production_mixed_mesh_fleet_fns():
    """ISSUE 18: the pod-scale mixed mesh (`--fleet 4 --mesh 2,2`). The
    `mesh="auto"` gate now traces the fleet scan/cscan/round variants
    whose bodies run manual under shard_map, with the
    replicated-scatter rule armed by the 2x2 sharding pins — zero new
    findings proves every scatter sits inside the manual region, and
    no mixed-mesh finding needed baselining at all."""
    findings, entries, _notes = jaxpr_audit.audit_production(
        programs=["lin-kv"], mesh="auto")
    assert any("@mesh=2,2" in e and e.startswith("fleet_scan_fn[")
               for e in entries), entries
    assert any("@mesh=2,2" in e and e.startswith("fleet_cscan_fn[")
               for e in entries), entries
    assert any("@mesh=2,2" in e and e.startswith("fleet_round_fn[")
               for e in entries), entries
    new, _suppressed = apply_baseline(dedupe_sites(findings),
                                      Baseline.load())
    assert new == [], [f.as_dict() for f in new]
    rep = [f for f in findings if f.rule == "replicated-scatter"]
    assert rep == [], [f.as_dict() for f in rep]


def test_baseline_file_is_well_formed():
    with open(analyze.baseline_path()) as f:
        data = json.load(f)
    assert data["version"] == 1
    for s in data["suppressions"]:
        assert s["rule"] in analyze.RULES
        assert s["max_sites"] >= 1
        # every deliberate exception records an actual justification
        assert s["reason"] and "FIXME" not in s["reason"]


# ---------------------------------------------------------------------------
# CLI + results-block surfacing
# ---------------------------------------------------------------------------

def test_analyze_cli_json_lint_only(capsys):
    """`--programs none` = lint-only: fast, structured, exit 0 on the
    clean tree."""
    from maelstrom_tpu.analyze.cli import main
    rc = main(["--programs", "none", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["ok"] is True
    assert out["new"] == []
    assert "source-lint" in out["entries"]
    assert out["wall-s"] >= 0


def test_results_carry_static_audit_block(tmp_path):
    """A real (tiny) TPU-path run self-reports its hazard status in the
    net results block: rule counts, suppressed count, audit wall time."""
    from maelstrom_tpu import core
    res = core.run({
        "workload": "echo", "node": "tpu:echo", "node_count": 2,
        "time_limit": 0.5, "rate": 10, "store_root": str(tmp_path),
        "recovery_s": 0.1})
    block = res["net"]["static-audit"]
    assert block["ok"] is True
    assert isinstance(block["rules"], dict)
    assert "suppressed-count" in block
    assert block["wall-s"] >= 0
    # and the kill switch works
    res2 = core.run({
        "workload": "echo", "node": "tpu:echo", "node_count": 2,
        "time_limit": 0.5, "rate": 10, "store_root": str(tmp_path),
        "recovery_s": 0.1, "audit": False})
    assert "static-audit" not in res2["net"]


# ---------------------------------------------------------------------------
# ISSUE 20 satellites: thread lint, fingerprint coverage, sorted baseline
# ---------------------------------------------------------------------------

_THREADED = (
    "import threading\n"
    "class AnalysisPipeline:\n"
    "    def __init__(self):\n"
    "        self.lock = threading.Lock()\n"
    "        self.done = False\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._worker).start()\n"
    "    def _worker(self):\n"
    "        while not self.done:\n"
    "            pass\n"
    "    def finish(self):\n"
    "{guard}"
    "        self.done = True\n")


def test_lint_thread_shared_mutation_fires_once():
    """A main-thread assignment to an attribute a worker thread reads,
    outside any lock, fires exactly once; the same store under
    `with self.lock:` is the sanctioned idiom and stays quiet."""
    src = _THREADED.format(guard="")
    found = source_lint.lint_source(src, "fx.py")
    assert rules_of(found) == ["thread-shared-mutation"]
    assert "AnalysisPipeline.finish" in found[0].where
    assert "worker threads" in found[0].detail
    ok = _THREADED.replace("        self.done = True\n",
                           "            self.done = True\n") \
                  .format(guard="        with self.lock:\n")
    assert source_lint.lint_source(ok, "fx.py") == []
    # a class OUTSIDE the explicit allowlist is deliberately not
    # analyzed (a generic heuristic would drown the gate)
    other = _THREADED.format(guard="").replace(
        "AnalysisPipeline", "SomeRandomHelper")
    assert source_lint.lint_source(other, "fx.py") == []


def test_lint_thread_classes_match_shipped_code():
    """Every allowlisted thread-pairing class still exists in the tree
    — a rename must update THREAD_CLASSES or the rule silently covers
    nothing."""
    import subprocess
    for cls in source_lint.THREAD_CLASSES:
        rc = subprocess.run(
            ["grep", "-rl", f"class {cls}", "maelstrom_tpu/"],
            capture_output=True, text=True)
        assert rc.stdout.strip(), f"THREAD_CLASSES entry {cls} stale"


def test_fingerprint_coverage_clean_and_seeded(monkeypatch):
    from maelstrom_tpu import checkpoint, core
    assert analyze.check_fingerprint_coverage() == []
    # a NEW knob in neither list fires exactly once
    monkeypatch.setitem(core.DEFAULTS, "fx_new_knob", 1)
    found = analyze.check_fingerprint_coverage()
    assert rules_of(found) == ["fingerprint-coverage"]
    assert "fx_new_knob" in found[0].where
    # allowlisting it restores the clean gate
    monkeypatch.setitem(checkpoint.FINGERPRINT_EXEMPT, "fx_new_knob",
                        "test: seeded")
    assert analyze.check_fingerprint_coverage() == []


def test_fingerprint_coverage_contradiction_and_stale(monkeypatch):
    from maelstrom_tpu import checkpoint
    # a key both fingerprinted and allowlisted: the lists contradict
    k = checkpoint.FINGERPRINT_KEYS[0]
    monkeypatch.setitem(checkpoint.FINGERPRINT_EXEMPT, k, "oops")
    found = analyze.check_fingerprint_coverage()
    assert rules_of(found) == ["fingerprint-coverage"]
    assert "contradict" in found[0].detail
    monkeypatch.delitem(checkpoint.FINGERPRINT_EXEMPT, k)
    # an allowlist entry naming no DEFAULTS key is stale
    monkeypatch.setitem(checkpoint.FINGERPRINT_EXEMPT, "fx_gone", "old")
    found = analyze.check_fingerprint_coverage()
    assert rules_of(found) == ["fingerprint-coverage"]
    assert "stale" in found[0].detail


def test_write_baseline_emits_sorted_suppressions(tmp_path):
    """Regenerated baselines list suppressions in sorted (rule, where)
    order regardless of finding arrival order — reviewable diffs."""
    path = str(tmp_path / "baseline.json")
    rep = analyze.AuditReport(new=[
        _site("unstable-sort", "m/z.py:9 (g)", "m/z.py:g"),
        _site("host-callback", "m/a.py:2 (f)", "m/a.py:f"),
        _site("unstable-sort", "m/a.py:5 (f)", "m/a.py:f"),
    ])
    rep.write_baseline(path)
    data = json.load(open(path))
    pairs = [(s["rule"], s["where"]) for s in data["suppressions"]]
    assert pairs == sorted(pairs)
    assert pairs == [("host-callback", "m/a.py:f"),
                     ("unstable-sort", "m/a.py:f"),
                     ("unstable-sort", "m/z.py:g")]
    # rewriting preserves an edited reason (the FIXME is one-shot)
    data["suppressions"][0]["reason"] = "justified: test"
    json.dump(data, open(path, "w"))
    rep.write_baseline(path)
    data2 = json.load(open(path))
    assert data2["suppressions"][0]["reason"] == "justified: test"
    assert all(s["reason"] for s in data2["suppressions"])
