"""The checker-graded bench pipeline (maelstrom_tpu.bench_graded) at CI
scale: a real history synthesized from protocol traffic, graded by the
stock BroadcastChecker. Guards the synthesis logic the 100k-node
benchmark artifact relies on (BASELINE.json north star: "passing the
stock checker")."""

import json
import os


def test_graded_broadcast_small(tmp_path):
    from maelstrom_tpu.bench_graded import run_graded

    s = run_graded(n_nodes=256, values=16, chunk=50, pool_cap=1024,
                   reads=8, out_dir=str(tmp_path), verbose=False)
    c = s["checker"]
    assert c["valid"] is True
    # every broadcast is invoked, acked through the protocol, and stable
    assert c["attempt-count"] == 16
    assert c["acknowledged-count"] == 16
    assert c["stable-count"] == 16
    assert c["lost-count"] == 0 and c["stale-count"] == 0
    assert s["dropped_overflow"] == 0
    # stable latencies are measured (known -> last-absent lag)
    assert c["stable-latencies"]["0.5"] is not None

    # artifacts written and loadable
    res = json.load(open(os.path.join(tmp_path, "results.json")))
    assert res["valid"] is True
    from maelstrom_tpu.history import History
    h = History.from_jsonl(
        open(os.path.join(tmp_path, "history.jsonl")).read())
    # invoke/ok pairs for 16 broadcasts + the reads
    pairs = h.pairs()
    assert all(c is not None and c.is_ok() for _, c in pairs)
    assert sum(1 for i, _ in pairs if i.f == "broadcast") == 16
    reads = [(i, c) for i, c in pairs if i.f == "read"]
    assert reads and all(len(c.value) == 16 for _, c in reads)
