"""The checker-graded bench pipeline (maelstrom_tpu.bench_graded) at CI
scale: a real history synthesized from protocol traffic, graded by the
stock BroadcastChecker. Guards the synthesis logic the 100k-node
benchmark artifact relies on (BASELINE.json north star: "passing the
stock checker")."""

import json
import os

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def test_graded_broadcast_small(tmp_path):
    from maelstrom_tpu.bench_graded import run_graded

    s = run_graded(n_nodes=256, values=16, chunk=50, pool_cap=1024,
                   reads=8, racing_read_every=8, out_dir=str(tmp_path),
                   verbose=False)
    c = s["checker"]
    assert c["valid"] is True
    # every broadcast is invoked, acked through the protocol, and stable
    assert c["attempt-count"] == 16
    assert c["acknowledged-count"] == 16
    assert c["stable-count"] == 16
    # racing reads may observe values mid-propagation (stale is legal,
    # lost is not)
    assert c["lost-count"] == 0
    assert s["dropped_overflow"] == 0
    assert s["racing_reads"] > 0
    # stable latencies are measured (known -> last-absent lag) and
    # bounded by the propagation model
    assert c["stable-latencies"]["0.5"] is not None
    assert (c["stable-latencies"]["1"] or 0) <= s["hop_bound_ms"]

    # artifacts written and loadable
    res = json.load(open(os.path.join(tmp_path, "results.json")))
    assert res["valid"] is True
    from maelstrom_tpu.history import History
    h = History.from_jsonl(
        open(os.path.join(tmp_path, "history.jsonl")).read())
    # invoke/ok pairs for 16 broadcasts + the reads
    pairs = h.pairs()
    assert all(c is not None and c.is_ok() for _, c in pairs)
    assert sum(1 for i, _ in pairs if i.f == "broadcast") == 16
    # final reads (post-convergence) observe the complete set; racing
    # reads observe a monotone prefix of propagation
    finals = [(i, c) for i, c in pairs if i.f == "read" and i.final]
    racing = [(i, c) for i, c in pairs if i.f == "read" and not i.final]
    assert finals and all(len(c.value) == 16 for _, c in finals)
    assert racing


def test_graded_racing_reads_produce_nonzero_latency(tmp_path):
    """With reads racing propagation on a large-diameter topology, the
    stock checker's stable-latency quantiles must be nonzero — the
    VERDICT r2 gap: an all-zeros grading exercised only the
    attempt/ack machinery."""
    from maelstrom_tpu.bench_graded import run_graded

    # 1024-node grid: diameter ~62 rounds, injections span 32 rounds,
    # racing reads every 8 — plenty of reads land mid-flood
    s = run_graded(n_nodes=1024, values=16, chunk=50, pool_cap=1024,
                   reads=4, racing_read_every=8, verbose=False)
    c = s["checker"]
    assert c["valid"] is True and c["lost-count"] == 0
    assert (c["stable-latencies"]["1"] or 0) > 0, c["stable-latencies"]
