"""Host network, client, and services tests (reference semantics:
net.clj, client.clj, service.clj)."""

import threading
import time

import pytest

from maelstrom_tpu.client import SyncClient, with_errors, defrpc
from maelstrom_tpu.errors import RPCError, Timeout
from maelstrom_tpu.message import message
from maelstrom_tpu.net.host import HostNet, LatencyDist
from maelstrom_tpu.net.journal import Journal
from maelstrom_tpu import schema as S
from maelstrom_tpu.services import (Eventual, LWWKV, Linearizable,
                                    PersistentKV, PersistentTSO,
                                    Sequential, ServiceRunner)


def test_send_recv_roundtrip():
    net = HostNet()
    net.add_node("n0").add_node("n1")
    net.send({"src": "n0", "dest": "n1", "body": {"type": "hi"}})
    msg = net.recv("n1", 100)
    assert msg.body == {"type": "hi"} and msg.src == "n0" and msg.id == 0
    assert net.recv("n1", 10) is None


def test_send_to_missing_node_raises_error_1():
    net = HostNet()
    net.add_node("n0")
    with pytest.raises(RPCError) as ei:
        net.send({"src": "n0", "dest": "nope", "body": {"type": "hi"}})
    assert ei.value.code == 1 and ei.value.definite


def test_partition_drops_at_delivery():
    net = HostNet()
    net.add_node("n0").add_node("n1")
    net.drop_link("n0", "n1")     # n1 blocks packets from n0
    net.send({"src": "n0", "dest": "n1", "body": {"type": "hi"}})
    assert net.recv("n1", 50) is None     # consumed and dropped
    # Asymmetric: n1 -> n0 still works
    net.send({"src": "n1", "dest": "n0", "body": {"type": "yo"}})
    assert net.recv("n0", 50).body == {"type": "yo"}
    net.heal()
    net.send({"src": "n0", "dest": "n1", "body": {"type": "hi2"}})
    assert net.recv("n1", 50).body == {"type": "hi2"}


def test_loss():
    net = HostNet(seed=1)
    net.add_node("n0").add_node("n1")
    net.flaky(1.0)      # lose everything
    for _ in range(10):
        net.send({"src": "n0", "dest": "n1", "body": {"type": "x"}})
    assert net.recv("n1", 20) is None
    net.p_loss = 0.0
    net.send({"src": "n0", "dest": "n1", "body": {"type": "y"}})
    assert net.recv("n1", 50) is not None


def test_latency_ordering_and_client_zero_latency():
    # Two messages: the second sent has a shorter deadline and should be
    # delivered first (priority by deadline, not FIFO).
    net = HostNet(latency={"mean": 40, "dist": "constant"})
    net.add_node("n0").add_node("n1")
    net.send({"src": "n0", "dest": "n1", "body": {"v": 1}})
    net.slow(0.001)   # subsequent messages ~0 latency
    net.send({"src": "n0", "dest": "n1", "body": {"v": 2}})
    # poll after both are queued
    time.sleep(0.005)
    assert net.recv("n1", 100).body == {"v": 2}
    assert net.recv("n1", 200).body == {"v": 1}
    # clients always get zero latency (net.clj:177-186)
    net.fast()
    net.add_node("c9")
    t0 = time.monotonic()
    net.send({"src": "c9", "dest": "n1", "body": {"v": 3}})
    assert net.recv("n1", 1000).body == {"v": 3}
    assert time.monotonic() - t0 < 0.03


def test_journal_stats():
    net = HostNet()
    net.journal = Journal()
    net.add_node("n0").add_node("n1").add_node("c0")
    net.send({"src": "n0", "dest": "n1", "body": {"type": "x"}})
    net.recv("n1", 50)
    net.send({"src": "c0", "dest": "n0", "body": {"type": "r"}})
    net.recv("n0", 50)
    s = net.journal.stats(op_count=1)
    assert s["all"]["send-count"] == 2 and s["all"]["recv-count"] == 2
    assert s["servers"]["msg-count"] == 1
    assert s["clients"]["msg-count"] == 1
    assert s["all"]["msgs-per-op"] == 2.0


def test_sync_client_rpc_and_stale_replies():
    net = HostNet()
    net.add_node("n0")
    client = SyncClient(net)

    def server():
        # ignore the first request (client times out), answer the second
        m1 = net.recv("n0", 1000)
        m2 = net.recv("n0", 2000)
        if m2 is not None:
            # reply late to m1 (stale), then to m2
            net.send({"src": "n0", "dest": m1.src,
                      "body": {"type": "echo_ok",
                               "in_reply_to": m1.body["msg_id"],
                               "echo": "stale"}})
            net.send({"src": "n0", "dest": m2.src,
                      "body": {"type": "echo_ok",
                               "in_reply_to": m2.body["msg_id"],
                               "echo": "fresh"}})
    t = threading.Thread(target=server, daemon=True)
    t.start()
    with pytest.raises(Timeout):
        client.rpc("n0", {"type": "echo", "echo": "a"}, timeout_ms=150)
    res = client.rpc("n0", {"type": "echo", "echo": "b"}, timeout_ms=2000)
    assert res["echo"] == "fresh"   # stale reply to msg 1 was discarded
    client.close()


def test_with_errors_mapping():
    op = {"f": "write", "value": 1, "type": "invoke"}

    def boom_definite():
        raise RPCError(14, {"text": "nope"})

    def boom_indef():
        raise RPCError(13, {"text": "hm"})

    def boom_timeout():
        raise Timeout()

    assert with_errors(op, set(), boom_definite)["type"] == "fail"
    assert with_errors(op, set(), boom_indef)["type"] == "info"
    assert with_errors(op, set(), boom_timeout)["type"] == "info"
    # idempotent fs fail fast even on timeouts (client.clj:221-225)
    rop = {"f": "read", "type": "invoke"}
    assert with_errors(rop, {"read"}, boom_timeout)["type"] == "fail"


def test_defrpc_validation():
    echo = defrpc("echo", "test echo",
                  {"type": S.Eq("echo"), "echo": S.Any},
                  {"type": S.Eq("echo_ok"), "echo": S.Any},
                  ns="test")
    net = HostNet()
    net.add_node("n0")
    client = SyncClient(net)

    def server():
        m = net.recv("n0", 2000)
        net.send({"src": "n0", "dest": m.src,
                  "body": {"type": "echo_ok", "echo": m.body["echo"],
                           "in_reply_to": m.body["msg_id"]}})
    threading.Thread(target=server, daemon=True).start()
    res = echo(client, "n0", {"echo": "hello"})
    assert res["echo"] == "hello"
    client.close()


# --- services (reference service.clj) ---

def _msg(src, body):
    return message(src, "svc", body)


def test_persistent_kv():
    kv = PersistentKV()
    kv, r = kv.handle(_msg("c0", {"type": "read", "key": "x"}))
    assert r["code"] == 20
    kv, r = kv.handle(_msg("c0", {"type": "write", "key": "x", "value": 5}))
    assert r == {"type": "write_ok"}
    kv, r = kv.handle(_msg("c0", {"type": "cas", "key": "x", "from": 5,
                                  "to": 6}))
    assert r == {"type": "cas_ok"}
    kv, r = kv.handle(_msg("c0", {"type": "cas", "key": "x", "from": 5,
                                  "to": 7}))
    assert r["code"] == 22
    kv, r = kv.handle(_msg("c0", {"type": "cas", "key": "y", "from": 1,
                                  "to": 2}))
    assert r["code"] == 20
    kv, r = kv.handle(_msg("c0", {"type": "cas", "key": "y", "from": None,
                                  "to": 2, "create_if_not_exists": True}))
    assert r == {"type": "cas_ok"}
    kv, r = kv.handle(_msg("c0", {"type": "read", "key": "y"}))
    assert r == {"type": "read_ok", "value": 2}


def test_lww_kv_merge():
    a = LWWKV()
    a, _ = a.handle(_msg("c0", {"type": "write", "key": "k", "value": "a"}))
    b = LWWKV()
    b, _ = b.handle(_msg("c0", {"type": "write", "key": "k", "value": "b"}))
    b, _ = b.handle(_msg("c0", {"type": "write", "key": "k", "value": "b2"}))
    m = a.merge(b)
    _, r = m.handle(_msg("c0", {"type": "read", "key": "k"}))
    assert r["value"] == "b2"     # higher lamport ts wins
    assert m.clock == 2


def test_tso_monotonic():
    tso = Linearizable(PersistentTSO())
    ts = [tso.handle(_msg("c0", {"type": "ts"}))["ts"] for _ in range(5)]
    assert ts == [0, 1, 2, 3, 4]


def test_sequential_client_monotonicity():
    svc = Sequential(PersistentKV(), seed=3)
    for i in range(5):
        svc.handle(_msg("c0", {"type": "write", "key": "x", "value": i}))
    # c0 wrote 4 last; its reads must observe monotonically advancing states,
    # and since its last write forced the newest state, reads must return 4.
    for _ in range(10):
        r = svc.handle(_msg("c0", {"type": "read", "key": "x"}))
        assert r["value"] == 4
    # A fresh client may observe older states, but never older than a state
    # it has already seen.
    seen = []
    for _ in range(20):
        r = svc.handle(_msg("c1", {"type": "read", "key": "x"}))
        seen.append(r["value"])
    assert all(b >= a for a, b in zip(seen, seen[1:])), seen


def test_eventual_converges():
    svc = Eventual(LWWKV(), n=3, seed=7)
    svc.handle(_msg("c0", {"type": "write", "key": "k", "value": 9}))
    ok = 0
    for _ in range(200):
        r = svc.handle(_msg("c0", {"type": "read", "key": "k"}))
        if r.get("value") == 9:
            ok += 1
    assert ok > 100     # gossip merges propagate the write


def test_service_runner_over_net():
    net = HostNet()
    from maelstrom_tpu.services import default_services
    runner = ServiceRunner(net, default_services())
    runner.start()
    try:
        client = SyncClient(net)
        res = client.rpc("lin-kv", {"type": "write", "key": "a", "value": 1})
        assert res["type"] == "write_ok"
        res = client.rpc("lin-kv", {"type": "read", "key": "a"})
        assert res == {"type": "read_ok", "value": 1,
                       "in_reply_to": res["in_reply_to"]}
        res = client.rpc("lin-tso", {"type": "ts"})
        assert res["type"] == "ts_ok"
        client.close()
    finally:
        runner.stop()
