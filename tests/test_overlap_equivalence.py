"""The analysis pipeline's hard invariant: overlapped/columnar analysis
produces bit-identical histories and checker verdicts to the sequential
path for the same seed.

Three layers:
  - checker-level: the columnar fast path (partition + vectorized
    screen + WGL fallback) vs the sequential pairs()+WGL baseline on
    randomized register histories, full result-dict equality
  - pipeline-level: incrementally-fed partitions vs one-shot columnar
    partitioning, array-for-array
  - end-to-end: same-seed runs with the pipeline on vs --no-overlap,
    history files byte-identical, workload verdicts equal (lin-kv in
    the fast tier; broadcast/raft/kafka fault soups in the slow tier)
"""

import os
import random

import numpy as np
import pytest

from maelstrom_tpu import core
from maelstrom_tpu.checkers.linearizable import (
    INF, LinearizableRegisterChecker, check_history, check_register_history,
    ops_from_arrays, partition_register, screen_register_arrays)
from maelstrom_tpu.checkers.pipeline import AnalysisPipeline
from maelstrom_tpu.history import History, Op
from maelstrom_tpu.testing.histories import (random_append_history,
                                             random_register_history)

STORE = "/tmp/maelstrom-tpu-test-store"


@pytest.mark.parametrize("seed", range(12))
def test_checker_fast_path_matches_sequential(seed):
    rng = random.Random(seed)
    h = random_register_history(
        seed, info_rate=rng.random() * 0.2, fail_rate=0.05,
        corrupt=rng.choice([0.0, 0.0, 0.1]),
        sequential=seed % 4 == 0)
    c = LinearizableRegisterChecker()
    assert c.check({}, h) == c.check({}, h, {"no_fast": True})


def test_screen_is_sound_never_false():
    """The screen may only ever answer True (definitely linearizable)
    or None (undecided); an invalid partition must come back None so
    WGL alone renders failures."""
    for seed in range(20):
        h = random_register_history(seed, corrupt=0.2, sequential=True)
        for k, arrs in partition_register(h):
            s = screen_register_arrays(arrs["f"], arrs["value"],
                                       arrs["inv"], arrs["ret"],
                                       arrs["ok"])
            assert s in (True, None)
            if s is True:
                assert check_history(ops_from_arrays(arrs))["valid"] \
                    is True


def test_undecided_result_is_structured():
    """The max_states guard reports a structured undecided result the
    overlapped screen can defer on (not an exception, not a bare
    string)."""
    ops = [{"f": "write", "value": i % 3, "inv": i, "ret": INF,
            "ok": False} for i in range(40)]
    r = check_register_history(ops, max_states=10)
    assert r["valid"] == "unknown"
    assert r["undecided"] is True
    assert r["reason"] == "max-states"
    assert r["max-states"] == 10
    assert r["op-count"] == 40
    assert r["explored-configurations"] > 10


def test_pipeline_partitions_match_columnar():
    h = random_register_history(21, n=1200, keys=5, workers=7)
    # a key whose every op definitely failed: it still counts toward
    # key-count (the sequential by_key holds it with zero ops), so the
    # pipeline must surface an empty partition for it
    h.append(Op(type="invoke", f="write", value=["failk", 1], process=0,
                time=10 ** 8))
    h.append(Op(type="fail", f="write", value=["failk", 1], process=0,
                time=10 ** 8 + 1, error=["abort", "definite"]))
    p = AnalysisPipeline(workers=2)
    step = 97                      # deliberately odd segment boundaries
    for lo in range(0, len(h), step):
        p.feed(h, lo, min(lo + step, len(h)))
    p.finish()
    got = p.register_partitions(len(h))
    want = partition_register(h)
    assert got is not None and len(got) == len(want)
    for (k1, a1, screened), (k2, a2) in zip(got, want):
        assert k1 == k2
        for field in ("f", "inv", "ret", "ok"):
            assert np.array_equal(a1[field], a2[field]), (k1, field)
        assert list(a1["value"]) == list(a2["value"])
        if screened is True:
            # incremental screen short-circuits only truly-valid keys
            assert check_history(ops_from_arrays(a2))["valid"] is True
    # full checker through the pipeline == sequential baseline
    res_pipe = LinearizableRegisterChecker().check({"analysis": p}, h)
    res_seq = LinearizableRegisterChecker().check({}, h,
                                                  {"no_fast": True})
    assert res_pipe == res_seq


def test_closed_pipeline_declines_service():
    """close() (the runner's error-path cleanup) stops the worker and
    the pipeline refuses to vouch for anything afterwards."""
    h = random_register_history(6, n=200)
    p = AnalysisPipeline()
    p.feed(h, 0, len(h))
    p.close()
    p.close()                             # idempotent
    assert p.register_partitions(len(h)) is None
    assert not p._thread.is_alive()
    c = LinearizableRegisterChecker()
    assert c.check({"analysis": p}, h) == c.check({}, h,
                                                  {"no_fast": True})


def test_stale_pipeline_falls_back():
    h = random_register_history(5, n=300)
    p = AnalysisPipeline()
    p.feed(h, 0, len(h))
    p.finish()
    h.append(Op(type="invoke", f="read", value=[0, None], process=0,
                time=10 ** 9))
    assert p.register_partitions(len(h)) is None
    c = LinearizableRegisterChecker()
    assert c.check({"analysis": p}, h) == c.check({}, h,
                                                  {"no_fast": True})


@pytest.mark.parametrize("seed", range(8))
def test_elle_vectorized_edges_match_python(seed):
    from maelstrom_tpu.checkers.elle import (_edges_python, analyze)
    rng = random.Random(seed)
    h = random_append_history(seed, corrupt=rng.choice([0.0, 0.15]),
                              empty_reads=seed == 3)
    assert analyze(h) == analyze(h, edges_impl=_edges_python)


def test_elle_reads_with_no_observed_versions():
    """Regression: histories whose every read is empty build an empty
    version table; the vectorized edge gather must not index it."""
    from maelstrom_tpu.checkers.elle import (_edges_python, analyze)
    h = random_append_history(9, empty_reads=True)
    assert analyze(h) == analyze(h, edges_impl=_edges_python)


# --- end to end: overlapped vs sequential runs, same seed ---

def _run_pair(opts):
    """Runs the same test twice — pipeline on vs --no-overlap — and
    returns ((results, history_text) x 2)."""
    out = []
    for variant in ({"check_workers": 2}, {"no_overlap": True}):
        root = os.path.join(STORE, f"overlap-{len(out)}")
        res = core.run({**opts, **variant, "store_root": root})
        with open(os.path.join(root, "latest", "history.jsonl")) as f:
            out.append((res, f.read()))
    return out


def _comparable(res):
    """Checker results minus wall-clock-dependent accounting (the
    static-audit and cost self-reports carry audit wall time + memo
    state; the windowed-grading blocks carry checker lag, which is
    wall-clock, and exist only on the overlapped path — the FINAL
    verdict fields are compared and must match bit-for-bit)."""
    drop = {"host-blocked-s", "host-overlapped-s", "host-poll-s",
            "host-wall-per-wave", "static-audit", "cost", "windows",
            "checker-lag", "check-wall-s"}
    return {name: ({k: v for k, v in r.items() if k not in drop}
                   if isinstance(r, dict) else r)
            for name, r in res.items()
            if name not in ("analysis-pipeline",)}


def test_overlap_run_bit_identical_lin_kv():
    (r1, h1), (r2, h2) = _run_pair(dict(
        seed=11, workload="lin-kv", node="tpu:lin-kv", node_count=5,
        rate=20.0, time_limit=3.0, journal_rows=False,
        nemesis={"partition"}, nemesis_interval=1.5))
    assert h1 == h2                      # histories byte-identical
    assert _comparable(r1) == _comparable(r2)
    assert r1["valid"] is True
    assert r1["analysis-pipeline"]["rows"] == len(h1.strip().splitlines())


SOUPS = [
    ("broadcast", "tpu:broadcast", {"topology": "grid"},
     {"partition"}),
    ("lin-kv", "tpu:lin-kv", {}, {"kill", "partition"}),
    ("kafka", "tpu:kafka", {}, {"partition", "duplicate"}),
]


@pytest.mark.slow
@pytest.mark.parametrize("workload,node,extra,nemesis", SOUPS,
                         ids=[s[0] for s in SOUPS])
def test_overlap_soups_bit_identical(workload, node, extra, nemesis):
    (r1, h1), (r2, h2) = _run_pair(dict(
        seed=29, workload=workload, node=node, node_count=5,
        rate=15.0, time_limit=4.0, journal_rows=False,
        latency={"mean": 3, "dist": "exponential"}, p_loss=0.02,
        nemesis=nemesis, nemesis_interval=2.0, **extra))
    assert h1 == h2
    assert _comparable(r1) == _comparable(r2)
