"""End-to-end tests for the batched Raft lin-kv program: linearizability
under the stock checker, leader re-election under partitions, and the
vmapped many-clusters configuration (BASELINE "10k x 5-node clusters",
scaled down for CI)."""

import jax
import jax.numpy as jnp
import numpy as np

from maelstrom_tpu import core
from maelstrom_tpu.net import tpu as T

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def run(opts):
    base = dict(store_root="/tmp/maelstrom-tpu-test-store", seed=3,
                rate=10.0, time_limit=3.0, journal_rows=False)
    return core.run({**base, **opts})


def test_lin_kv_raft_tpu_e2e():
    res = run({"workload": "lin-kv", "node": "tpu:lin-kv",
               "node_count": 5})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    # raft actually committed client ops
    assert res["stats"]["by-f"]["read"]["ok-count"] > 0
    assert res["stats"]["by-f"]["write"]["ok-count"] > 0


def test_lin_kv_raft_survives_partition():
    res = run({"workload": "lin-kv", "node": "tpu:lin-kv",
               "node_count": 5, "nemesis": {"partition"},
               "nemesis_interval": 1.0, "time_limit": 4.0})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    # ops continue to commit across partitions (majority side)
    ok = sum(res["stats"]["by-f"][f]["ok-count"]
             for f in res["stats"]["by-f"])
    assert ok > 0


def test_lin_kv_raft_with_message_loss():
    """5% loss: AE entry lanes drop independently of headers; the follower
    contiguity check must keep acknowledged = actually-stored, so the
    history stays linearizable."""
    res = run({"workload": "lin-kv", "node": "tpu:lin-kv",
               "node_count": 5, "p_loss": 0.05, "time_limit": 4.0})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    ok = sum(res["stats"]["by-f"][f]["ok-count"]
             for f in res["stats"]["by-f"])
    assert ok > 0


def test_raft_log_overflow_invalidates_run():
    """A run that busts `log_cap` must be flagged, not silently degraded:
    the leader sheds requests the client only sees as timeouts."""
    # short client timeout so ops keep retrying into the full log (an
    # in-flight op that will never be answered otherwise outlives the run)
    res = run({"workload": "lin-kv", "node": "tpu:lin-kv",
               "node_count": 3, "log_cap": 4, "rate": 20.0,
               "time_limit": 4.0, "timeout_ms": 500})
    assert res["net"]["log-overflow"] > 0
    assert res["net"]["valid"] is False
    assert res["valid"] is False


def test_raft_log_cap_scales_with_workload():
    """The default log capacity follows the expected op count, so a run
    whose operations exceed the old fixed cap of 256 commits them all
    with zero overflow."""
    res = run({"workload": "lin-kv", "node": "tpu:lin-kv",
               "node_count": 3, "rate": 30.0, "time_limit": 12.0,
               "seed": 5})
    assert res["valid"] is True, res["workload"]
    ok = sum(res["stats"]["by-f"][f]["ok-count"]
             for f in res["stats"]["by-f"])
    assert ok > 256
    assert res["net"]["log-overflow"] == 0


def test_raft_many_clusters_vmap():
    """64 independent 5-node raft clusters under one vmap: each elects
    exactly one leader."""
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.parallel import make_cluster_round_fn, \
        make_cluster_sims

    n, clusters = 5, 64
    nodes = [f"n{i}" for i in range(n)]
    prog = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=64,
                      inbox_cap=prog.inbox_cap, client_cap=4)
    sims = make_cluster_sims(prog, cfg, clusters, seed=1)
    round_fn = make_cluster_round_fn(prog, cfg)
    inject = T.Msgs.empty((clusters, 1))
    for _ in range(120):
        sims, _cm, _io = round_fn(sims, inject)
    roles = np.asarray(jax.device_get(sims.nodes["role"]))
    leaders = (roles == 2).sum(axis=1)
    # elections are randomized; virtually all clusters are stable by now
    assert (leaders == 1).mean() > 0.9, leaders
    terms = np.asarray(jax.device_get(sims.nodes["term"]))
    assert (terms >= 1).all()


def test_raft_survives_reordering_exponential_latency_partition():
    """Regression: per-lane latency draws tore AE batches apart — an AE
    header arriving with entry lanes from a DIFFERENT AE wrote entries
    at wrong log indices (same-term log divergence), surfacing as a
    committed write reverting after a partition-window election. The
    exact fuzz config that caught it (64 clusters, seed 303); raft's
    edge_atomic_rpc shares one fault draw per (edge, round) so the RPC
    travels whole."""
    from maelstrom_tpu.bench_raft_graded import run_raft_graded

    r = run_raft_graded(n_clusters=64, sample=16, seed=303, p_loss=0.0,
                        latency={"mean": 3, "dist": "exponential"},
                        warmup_chunks=14, max_chunks=600,
                        partition_at=4, partition_chunks=12,
                        verbose=False)
    assert r["all_linearizable"] is True, r
    assert r["dropped_overflow"] == 0
