"""Equivalence suite: the columnar (struct-of-arrays) History vs the
Op-list semantics it replaced. Every facade surface — iteration order,
indexing, pairing, filtered views, JSONL round-trips, and the
checkpoint-resume materialize/rebuild cycle — must behave exactly like
a plain list of Ops."""

import json
import random

import numpy as np
import pytest

from maelstrom_tpu.history import History, Op, coerce_history


def reference_pairs(ops):
    """The pre-columnar open-slot pairing scan (the semantics
    pairs_index must reproduce)."""
    out = []
    open_by_process = {}
    for o in ops:
        if o.type == "invoke":
            open_by_process[o.process] = len(out)
            out.append((o, None))
        elif o.process in open_by_process:
            i = open_by_process.pop(o.process)
            out[i] = (out[i][0], o)
    return out


def random_ops(seed, n=500, workers=8, stray=True):
    rng = random.Random(seed)
    ops = []
    t = 0
    openp = set()
    for i in range(n):
        t += rng.randrange(0, 3)
        p = rng.randrange(workers) if rng.random() < 0.9 else "nemesis"
        if p in openp and rng.random() < 0.65:
            openp.discard(p)
            ops.append(Op(type=rng.choice(["ok", "fail", "info"]),
                          f=rng.choice(["read", "write", "txn", None]),
                          value=rng.choice([None, [1, 2], "x", 7]),
                          process=p, time=t,
                          error=rng.choice([None, "net-timeout",
                                            ["code", "text"]]),
                          final=rng.random() < 0.05))
        else:
            openp.add(p)
            ops.append(Op(type="invoke", f=rng.choice(["read", "write"]),
                          value=[rng.randrange(3), rng.randrange(5)],
                          process=p, time=t))
    if stray:
        # completions with no open invoke, processes never seen before
        ops.append(Op(type="ok", f="read", value=None, process=777, time=t))
    return ops


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_facade_matches_op_list(seed):
    ops = random_ops(seed)
    h = History(ops)
    assert len(h) == len(ops)
    # append assigned indices in order, mutating the originals like the
    # list form did
    assert [o.index for o in ops] == list(range(len(ops)))
    assert [o.to_dict() for o in h] == [o.to_dict() for o in ops]
    assert h[0].to_dict() == ops[0].to_dict()
    assert h[-1].to_dict() == ops[-1].to_dict()
    assert [o.to_dict() for o in h[3:10]] == \
        [o.to_dict() for o in ops[3:10]]
    assert [o.to_dict() for o in h.ops] == [o.to_dict() for o in ops]


@pytest.mark.parametrize("seed", range(6))
def test_pairs_equivalence(seed):
    ops = random_ops(seed, n=800, workers=5)
    h = History(ops)
    ref = reference_pairs(ops)
    got = h.pairs()
    assert len(ref) == len(got)
    for (i1, c1), (i2, c2) in zip(ref, got):
        assert i1.to_dict() == i2.to_dict()
        assert (c1 is None) == (c2 is None)
        if c1 is not None:
            assert c1.to_dict() == c2.to_dict()


def test_filtered_views():
    ops = random_ops(3)
    h = History(ops)
    assert [o.to_dict() for o in h.invokes()] == \
        [o.to_dict() for o in ops if o.type == "invoke"]
    assert [o.to_dict() for o in h.oks()] == \
        [o.to_dict() for o in ops if o.type == "ok"]
    assert [o.to_dict() for o in h.completions()] == \
        [o.to_dict() for o in ops if o.type in ("ok", "fail", "info")]
    assert [o.to_dict() for o in h.client_ops()] == \
        [o.to_dict() for o in ops if o.process != "nemesis"]


def test_jsonl_round_trip():
    ops = random_ops(4)
    h = History(ops)
    text = h.to_jsonl()
    # line-per-op, dict-shaped exactly like Op.to_dict
    lines = [json.loads(x) for x in text.splitlines()]
    assert lines == [json.loads(json.dumps(o.to_dict(), default=str))
                     for o in ops]
    h2 = History.from_jsonl(text)
    assert [o.to_dict() for o in h2] == [o.to_dict() for o in h]


def test_checkpoint_materialize_rebuild_cycle():
    """The (legacy) checkpoint path saved list(history) and resumed with
    History(list): the cycle must be lossless, and the rebuilt history
    must keep appending with correct indices."""
    ops = random_ops(5, n=300)
    h = History(ops)
    rebuilt = History(list(h))
    assert [o.to_dict() for o in rebuilt] == [o.to_dict() for o in h]
    nxt = rebuilt.append(Op(type="invoke", f="read", value=[0, None],
                            process=1, time=999))
    assert nxt.index == len(ops)
    assert rebuilt[-1].index == len(ops)


def test_checkpoint_columns_snapshot_rebuild_cycle():
    """The checkpoint path proper saves snapshot_columns() and resumes
    with from_columns(): lossless, no per-op materialization, and the
    rebuilt history keeps appending with correct indices. The snapshot
    must also be immune to appends that land after it was taken (the
    async writer pickles it while the run keeps going)."""
    import pickle

    ops = random_ops(5, n=300)
    h = History(ops)
    snap = h.snapshot_columns()
    # keep appending (growing past a buffer reallocation) AFTER the
    # snapshot: the snapshot must still describe exactly the first 300
    for i in range(2000):
        h.append_row("invoke", "read", [i, None], i % 7, time=1000 + i)
    snap = pickle.loads(pickle.dumps(snap))     # what the writer does
    rebuilt = History.from_columns(snap)
    assert len(rebuilt) == len(ops)
    assert ([o.to_dict() for o in rebuilt]
            == [o.to_dict() for o in History(ops)])
    nxt = rebuilt.append(Op(type="invoke", f="read", value=[0, None],
                            process=1, time=999))
    assert nxt.index == len(ops)
    assert rebuilt[-1].index == len(ops)
    # pairing runs identically on the rebuilt columns
    assert (History(ops).pairs_index().tolist()
            == History.from_columns(History(ops).snapshot_columns())
            .pairs_index().tolist())


def test_extend_columns_matches_append():
    rows = [("invoke", "read", [0, 1], 0, 10, None, False),
            ("ok", "read", [0, 1], 0, 12, None, False),
            ("invoke", "write", [1, 5], 1, 13, None, True),
            ("info", "write", [1, 5], 1, 20, "net-timeout", False)]
    h1 = History()
    for t, f, v, p, tm, e, fin in rows:
        h1.append(Op(type=t, f=f, value=v, process=p, time=tm, error=e,
                     final=fin))
    h2 = History()
    h2.extend_columns([r[0] for r in rows], [r[1] for r in rows],
                      [r[2] for r in rows], [r[3] for r in rows],
                      [r[4] for r in rows], [r[5] for r in rows],
                      np.asarray([r[6] for r in rows]))
    assert [o.to_dict() for o in h1] == [o.to_dict() for o in h2]
    # equal-length list values must stay per-row lists (the 2-D
    # collapse hazard of np.asarray on object input)
    assert h2[0].value == [0, 1] and h2[2].value == [1, 5]


def test_soa_views_are_append_stable():
    """Column views taken before later appends keep reading the rows
    that existed when they were taken (the analysis pipeline reads
    segment slices from a worker thread while the runner appends)."""
    h = History()
    for i in range(10):
        h.append(Op(type="invoke", f="read", value=[i, i], process=0,
                    time=i))
    soa = h.soa()
    times = soa.time.copy()
    for i in range(5000):           # force several growth reallocations
        h.append(Op(type="ok", f="read", value=[i, i], process=0,
                    time=100 + i))
    assert np.array_equal(soa.time, times)
    assert h.soa().n == 5010


def test_coerce_from_dicts_and_history_identity():
    ops = [{"type": "invoke", "f": "read", "value": [0, None],
            "process": 0, "time": 1},
           {"type": "ok", "f": "read", "value": [0, None],
            "process": 0, "time": 2}]
    h = coerce_history(ops)
    assert isinstance(h, History) and len(h) == 2
    assert coerce_history(h) is h
    assert h.pairs()[0][1].type == "ok"
