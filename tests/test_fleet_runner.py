"""Fleet execution (`--fleet N`): N independent cluster instances inside
ONE compiled scan, vmapped over a leading cluster axis and sharded
`("dp", "sp")` under `--mesh dp,sp`.

The contract under test is **bit-identity**: every cluster of a fleet
replays the standalone run of its own option set (seed / nemesis
schedule / offered load, depending on `--fleet-sweep`) op for op —
types, values, processes, virtual times, errors. The fleet changes
batching, never semantics. On top of that: the coalesced fleet
checkpoint resumes every cluster byte-identically (graceful preemption
in-process here; the SIGKILL subprocess soak is slow-marked), and the
`--mesh 2,1` dp=2 configuration — the one PR 2 had to reject — runs on
the 2 virtual CPU devices (multichip marker).
"""

from __future__ import annotations

import threading
import time

import pytest

from conftest import ops_projection as _ops
from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import core
from maelstrom_tpu.core import FleetSpec
from maelstrom_tpu.runner.fleet_runner import FleetRunner
from maelstrom_tpu.runner.tpu_runner import TpuRunner


BROADCAST = {"workload": "broadcast", "node": "tpu:broadcast",
             "topology": "grid", "node_count": 5, "rate": 10.0,
             "time_limit": 1.0, "recovery_s": 0.5, "seed": 7,
             "audit": False}
LIN_KV = {"workload": "lin-kv", "node": "tpu:lin-kv", "node_count": 3,
          "rate": 10.0, "time_limit": 1.5, "recovery_s": 0.5, "seed": 11,
          "audit": False}
KAFKA = {"workload": "kafka", "node": "tpu:kafka", "node_count": 4,
         "rate": 10.0, "time_limit": 1.5, "recovery_s": 0.5, "seed": 5,
         "audit": False}
SOUP = {"nemesis": ["kill", "pause", "partition", "duplicate"],
        "nemesis_interval": 0.4}


_SOLO_CACHE: dict = {}


def _solo(opts):
    # several tests compare against the same standalone runs (e.g. the
    # BROADCAST seed 7/8 solos anchor both the seed sweep and the dp=2
    # mesh smoke) — memoize them; runs are deterministic by contract
    key = repr(sorted(opts.items(), key=lambda kv: kv[0]))
    if key not in _SOLO_CACHE:
        test = core.build_test(dict(opts))
        # construct BEFORE the nemesis truthiness rewrite, exactly like
        # run_tpu_test: program builders sniff the fault SET (edge ring
        # headroom under `duplicate` — nodes.edge_timing)
        runner = TpuRunner(test)
        test["nemesis"] = (True if test["nemesis_pkg"]["generator"]
                           is not None else None)
        _SOLO_CACHE[key] = runner.run()
    return _SOLO_CACHE[key]


def _fleet(opts, **fleet_over):
    test = core.build_test({**opts, **fleet_over})
    runner = FleetRunner(test)
    return runner, runner.run()


# ---------------------------------------------------------------------------
# FleetSpec: the campaign description (pure host logic, no device work)
# ---------------------------------------------------------------------------

def test_fleet_spec_validation():
    assert FleetSpec.from_test({}) == FleetSpec(1, "seed")
    assert FleetSpec.from_test({"fleet": 8, "fleet_sweep": "capacity"}) \
        == FleetSpec(8, "capacity")
    with pytest.raises(ValueError, match="--fleet must be >= 1"):
        FleetSpec.from_test({"fleet": 0})
    with pytest.raises(ValueError, match="--fleet-sweep"):
        FleetSpec.from_test({"fleet": 2, "fleet_sweep": "chaos"})


def test_cluster_opts_sweeps():
    """cluster_opts(i) is the option set whose STANDALONE run cluster i
    replays: seed sweep offsets the whole seed, nemesis sweep pins the
    op stream and moves only the fault RNG, capacity sweep ramps the
    offered load; fleet-level mechanics (mesh, resume, journaling,
    audit) are stripped or forced off."""
    base = core.build_test({**LIN_KV, "fleet": 3, "mesh": "2,1",
                            "journal_rows": True})
    spec = FleetSpec.from_test(base)
    for i in range(3):
        o = spec.cluster_opts(base, i)
        assert o["fleet"] == 1 and o["mesh"] is None
        assert o["resume"] is None and o["journal_rows"] is False
        assert o["audit"] is False
        assert "generator" not in o and "checker" not in o \
            and "nemesis_pkg" not in o and "net" not in o
    assert [spec.cluster_opts(base, i)["seed"] for i in range(3)] == \
        [11, 12, 13]

    nem = FleetSpec(3, "nemesis")
    assert [nem.cluster_opts(base, i)["nemesis_seed"]
            for i in range(3)] == [11, 12, 13]
    assert all(nem.cluster_opts(base, i)["seed"] == 11 for i in range(3))

    cap = FleetSpec(3, "capacity")
    assert [cap.cluster_opts(base, i)["rate"] for i in range(3)] == \
        [10.0, 20.0, 30.0]
    assert all(cap.cluster_opts(base, i)["seed"] == 11 for i in range(3))


def test_fleet_requires_dp_divisor():
    test = core.build_test({**BROADCAST, "fleet": 3, "mesh": "2,1"})
    with pytest.raises(ValueError, match="multiple of dp"):
        FleetRunner(test)


def test_standalone_dp_error_names_fleet(tmp_path):
    """The PR 2 hard rejection is now a signpost: dp > 1 without a
    fleet tells the user to give dp a fleet to shard."""
    test = core.build_test({**BROADCAST, "mesh": "2,1"})
    with pytest.raises(ValueError, match="--fleet N --mesh"):
        TpuRunner(test)


# ---------------------------------------------------------------------------
# Bit-identity: every cluster == its standalone run
# ---------------------------------------------------------------------------

def test_fleet_seed_sweep_bit_identical():
    """The core contract, cheapest config: a 2-cluster broadcast fleet
    equals the standalone runs of seeds 7 and 8 op for op, and the
    whole fleet drains O(dispatches), not O(rounds)."""
    solos = [_solo({**BROADCAST, "seed": s}) for s in (7, 8)]
    runner, hs = _fleet(BROADCAST, fleet=2)
    assert len(hs[0]) > 20
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"
    assert max(runner.final_rounds) > 1000
    assert 0 < runner.transfer.drains < max(runner.final_rounds) // 4


@pytest.mark.slow
def test_fleet_combined_nemesis_bit_identical():
    """Under the full fault soup (kill/pause/partition/duplicate),
    per-cluster nemesis decision streams stay independent and every
    cluster still replays its standalone run exactly. Slow-marked for
    wall time (the kill package's durable-store restarts dominate);
    tier-1 keeps the partition-nemesis sweep test, and the slow trio
    covers the soup on all three workloads."""
    opts = {**BROADCAST, **SOUP, "time_limit": 1.2}
    solos = [_solo({**opts, "seed": s}) for s in (7, 8)]
    _, hs = _fleet(opts, fleet=2)
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


def test_fleet_nemesis_sweep_fixed_ops_varied_faults():
    """`--fleet-sweep nemesis`: same workload seed (same op stream),
    per-cluster fault schedules. Cluster i == standalone with
    nemesis_seed = seed + i; the invoked client-op streams agree across
    clusters while the nemesis streams differ."""
    opts = {**LIN_KV, "nemesis": ["partition"], "nemesis_interval": 0.5}
    solos = [_solo({**opts, "nemesis_seed": 11 + i}) for i in range(2)]
    _, hs = _fleet(opts, fleet=2, fleet_sweep="nemesis")
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"

    def client_invokes(h):
        return [(o.f, o.value) for o in h
                if o.type == "invoke" and o.process != "nemesis"]

    def nemesis_rows(h):
        # the fault choice lands in the completion values
        # ("isolated n1" vs "halves ..."), not the invoke rows
        return [(o.type, o.f, o.value, o.time) for o in h
                if o.process == "nemesis"]
    assert client_invokes(hs[0]) == client_invokes(hs[1])
    assert nemesis_rows(hs[0]) != nemesis_rows(hs[1])


def test_fleet_capacity_sweep_ramps_load():
    """`--fleet-sweep capacity`: cluster i runs at rate * (i + 1);
    cluster i == the standalone run at that rate, and the op count
    grows with the offered load."""
    opts = {**BROADCAST, "time_limit": 1.0}
    solos = [_solo({**opts, "rate": 10.0 * (i + 1)}) for i in range(2)]
    _, hs = _fleet(opts, fleet=2, fleet_sweep="capacity")
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"
    assert len(hs[1]) > len(hs[0])


@pytest.mark.slow
@pytest.mark.parametrize("opts,seeds", [
    ({**BROADCAST, **SOUP, "time_limit": 1.5}, (7, 8, 9, 10)),
    ({**LIN_KV, **SOUP, "time_limit": 2.0}, (11, 12, 13, 14)),
    ({**KAFKA, **SOUP, "time_limit": 2.0}, (5, 6, 7, 8)),
])
def test_fleet_soup_bit_identical_all_workloads(opts, seeds):
    """Acceptance trio: broadcast, raft-backed lin-kv, and kafka fleets
    under the combined nemesis, each cluster bit-identical to its
    standalone run."""
    solos = [_solo({**opts, "seed": s}) for s in seeds]
    _, hs = _fleet({**opts, "seed": seeds[0]}, fleet=len(seeds))
    for i in range(len(seeds)):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


# ---------------------------------------------------------------------------
# Mesh: dp finally means something
# ---------------------------------------------------------------------------

@pytest.mark.multichip
def test_fleet_mesh_dp2_bit_identical():
    """`--fleet 2 --mesh 2,1`: the cluster axis shards over dp=2 (the
    configuration PR 2 had to reject) and every cluster still equals
    its standalone run (one cluster per dp shard; the solos are shared
    with the seed-sweep test's cache)."""
    solos = [_solo({**BROADCAST, "seed": 7 + i}) for i in range(2)]
    runner, hs = _fleet(BROADCAST, fleet=2, mesh="2,1")
    assert runner.mesh is not None and runner.mesh.shape["dp"] == 2
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


@pytest.mark.multichip
def test_fleet_mesh_dp2_sp2_bit_identical():
    """`--fleet 2 --mesh 2,2`: the POD-SCALE MIXED mesh — the shape PR 2
    and PR 18's predecessors had to reject (GSPMD scatter-set over a
    replicated axis combined per-replica contributions additively;
    observed as corrupted reply rows under exactly this configuration).
    The scan body now runs MANUAL over the mesh under shard_map
    (sim.fleet_shard_map): per-cluster scatters are plain local
    scatters, and every cluster equals its standalone run bit for
    bit."""
    solos = [_solo({**BROADCAST, "seed": 7 + i}) for i in range(2)]
    runner, hs = _fleet(BROADCAST, fleet=2, mesh="2,2")
    assert runner.mesh is not None
    assert runner.mesh.shape["dp"] == 2 and runner.mesh.shape["sp"] == 2
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


@pytest.mark.slow
@pytest.mark.multichip
def test_fleet_mesh_dp2_sp2_soup_bit_identical():
    """`--fleet 4 --mesh 2,2` under the combined fault soup
    (kill/pause/partition/duplicate): mask surgery, crash-restarts, and
    duplicate deliveries all land inside the shard_map manual body, and
    with fleet % mesh.size == 0 the cluster axis shards over BOTH mesh
    axes (one cluster per device). Every cluster still replays its
    standalone run exactly."""
    opts = {**BROADCAST, **SOUP, "time_limit": 1.2}
    solos = [_solo({**opts, "seed": 7 + i}) for i in range(4)]
    _, hs = _fleet(opts, fleet=4, mesh="2,2")
    for i in range(4):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


@pytest.mark.slow
@pytest.mark.multichip
def test_fleet_mesh_sp2_bit_identical():
    """`--fleet 2 --mesh 1,2`: the per-cluster node/pool axes sharded
    over sp under a fleet, every cluster equal to its standalone run
    (the PR 2 regime, vmapped). Slow-marked for wall time (the soup +
    8-node sp-sharded scan dominates); tier-1 keeps mesh coverage via
    the dp=2 smoke."""
    opts = {**BROADCAST, **SOUP, "node_count": 8, "time_limit": 1.0}
    solos = [_solo({**opts, "seed": 7 + i}) for i in range(2)]
    _, hs = _fleet(opts, fleet=2, mesh="1,2")
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


# ---------------------------------------------------------------------------
# Checkpoint / preemption / resume
# ---------------------------------------------------------------------------

def test_fleet_preempt_checkpoint_resume_bit_identical(tmp_path):
    """Graceful preemption mid-run: the coalesced fleet checkpoint
    (one framed file covering every cluster's freshest snapshot)
    resumes ALL clusters to histories bit-identical to the
    uninterrupted fleet — including clusters that were mid-stretch and
    clusters that had already finished."""
    opts = {**LIN_KV, "nemesis": ["partition"], "nemesis_interval": 0.8,
            "time_limit": 2.0}

    a_dir = tmp_path / "a"
    a_dir.mkdir()
    t = core.build_test({**opts, "fleet": 2})
    t["store_dir"] = str(a_dir)
    hs_a = FleetRunner(t).run()
    assert len(hs_a[0]) > 20

    b_dir = tmp_path / "b"
    b_dir.mkdir()
    t2 = core.build_test({**opts, "fleet": 2, "checkpoint_every": 0.25})
    t2["store_dir"] = str(b_dir)
    fr2 = FleetRunner(t2)

    def preempt_after_first_checkpoint():
        # deterministic mid-run preemption: fire as soon as the first
        # coalesced checkpoint has been submitted (~round 250 of ~5000+)
        deadline = time.time() + 300
        while time.time() < deadline and not fr2._preempt.is_set():
            if fr2.transfer.ckpt_saves >= 1:
                fr2._preempt.set()
                return
            time.sleep(0.01)
    threading.Thread(target=preempt_after_first_checkpoint,
                     daemon=True).start()
    with pytest.raises(cp.Preempted):
        fr2.run()

    ck = cp.load(str(b_dir))
    assert ck["fingerprint"]["fleet"] == 2
    t3 = core.build_test({**opts, "fleet": 2, "checkpoint_every": 0.25})
    t3["store_dir"] = str(b_dir)
    fr3 = FleetRunner(t3)
    cp.check_fingerprint(ck, t3)
    hs_c = fr3.run(resume=ck)
    for i in range(2):
        assert _ops(hs_c[i]) == _ops(hs_a[i]), \
            f"cluster {i} diverged after resume"


def test_fleet_checkpoint_rejects_other_fleet(tmp_path):
    """fleet/fleet_sweep are fingerprinted: a fleet checkpoint only
    resumes into the same campaign."""
    opts = {**LIN_KV, "time_limit": 1.0, "checkpoint_every": 0.25}
    t = core.build_test({**opts, "fleet": 2})
    t["store_dir"] = str(tmp_path)
    FleetRunner(t).run()
    ck = cp.load(str(tmp_path))
    bad = core.build_test({**opts, "fleet": 4})
    with pytest.raises(ValueError, match="fleet"):
        cp.check_fingerprint(ck, bad)


def test_fleet_checkpoint_mesh_fingerprint():
    """A checkpoint's mesh shape is part of the campaign: a 2,2
    fingerprint refuses a 2,1 resume (no device work — the full
    run/resume pin is the slow test below)."""
    opts = {**LIN_KV, "time_limit": 2.0}
    ck = {"fingerprint": cp.fingerprint(
        core.build_test({**opts, "fleet": 2, "mesh": "2,2"}))}
    with pytest.raises(ValueError, match="mesh"):
        cp.check_fingerprint(
            ck, core.build_test({**opts, "fleet": 2, "mesh": "2,1"}))
    cp.check_fingerprint(
        ck, core.build_test({**opts, "fleet": 2, "mesh": "2,2"}))


@pytest.mark.slow
@pytest.mark.multichip
def test_fleet_checkpoint_mesh_shapes(tmp_path):
    """ISSUE 18: checkpoint/resume across mesh shapes. A `--fleet 2
    --mesh 2,2` (mixed-mesh) checkpoint resumes byte-identical on the
    SAME mesh — the sharded carries snapshot and restore through the
    same host-replayable path as unsharded fleets — and a checkpoint
    taken on a DIFFERENT mesh shape is rejected by fingerprint (`mesh`
    is a FINGERPRINT_KEYS member; mirrors the PR 4 multichip pins)."""
    opts = {**LIN_KV, "time_limit": 2.0}

    a_dir = tmp_path / "a"
    a_dir.mkdir()
    t = core.build_test({**opts, "fleet": 2, "mesh": "2,2"})
    t["store_dir"] = str(a_dir)
    hs_a = FleetRunner(t).run()

    b_dir = tmp_path / "b"
    b_dir.mkdir()
    t2 = core.build_test({**opts, "fleet": 2, "mesh": "2,2",
                          "checkpoint_every": 0.25})
    t2["store_dir"] = str(b_dir)
    fr2 = FleetRunner(t2)

    def preempt_after_first_checkpoint():
        deadline = time.time() + 300
        while time.time() < deadline and not fr2._preempt.is_set():
            if fr2.transfer.ckpt_saves >= 1:
                fr2._preempt.set()
                return
            time.sleep(0.01)
    threading.Thread(target=preempt_after_first_checkpoint,
                     daemon=True).start()
    with pytest.raises(cp.Preempted):
        fr2.run()

    ck = cp.load(str(b_dir))
    assert ck["fingerprint"]["mesh"] == "2,2"
    # a different mesh shape cannot adopt the checkpoint: the placement
    # (and with it the compiled layout) is part of the campaign
    bad = core.build_test({**opts, "fleet": 2, "mesh": "2,1",
                           "checkpoint_every": 0.25})
    with pytest.raises(ValueError, match="mesh"):
        cp.check_fingerprint(ck, bad)
    # the same mesh resumes every cluster bit-identically
    t3 = core.build_test({**opts, "fleet": 2, "mesh": "2,2",
                          "checkpoint_every": 0.25})
    t3["store_dir"] = str(b_dir)
    fr3 = FleetRunner(t3)
    cp.check_fingerprint(ck, t3)
    hs_c = fr3.run(resume=ck)
    for i in range(2):
        assert _ops(hs_c[i]) == _ops(hs_a[i]), \
            f"cluster {i} diverged after mixed-mesh resume"


@pytest.mark.slow
def test_fleet_sigkill_resume_byte_identical(tmp_path):
    """Real SIGKILL, real subprocess: a --fleet 2 run killed after its
    first coalesced checkpoint and resumed with --resume lands
    byte-identical history.jsonl and verdict-identical results.json
    against the uninterrupted fleet baseline."""
    import os
    import random

    from maelstrom_tpu import crash_soak

    # seed 16: fleet seeds (16, 17) both grade valid under this config
    # (the soak launches the real CLI, whose exit code encodes validity)
    opts = {"-w": "lin-kv", "--node": "tpu:lin-kv", "--node-count": "3",
            "--rate": "10", "--time-limit": "4", "--seed": "16",
            "--nemesis": "partition", "--nemesis-interval": "1",
            "--checkpoint-every": "0.5", "--fleet": "2"}
    root = str(tmp_path / "baseline")
    baseline = crash_soak.run_once(root, opts,
                                   os.path.join(str(tmp_path),
                                                "baseline.log"))
    res = crash_soak.run_with_kills(str(tmp_path / "killed"), opts,
                                    kills=1, rng=random.Random(5),
                                    kill_jitter_s=0.2)
    assert len(res["kills"]) == 1, res
    verdict = crash_soak.compare_runs(baseline, res["dir"])
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict


# ---------------------------------------------------------------------------
# run_fleet_test: per-cluster checking, storage, reporting
# ---------------------------------------------------------------------------

def test_run_fleet_test_per_cluster_results(tmp_path):
    """The end-to-end entry point: per-cluster artifacts under
    cluster-XXXX/, per-cluster verdicts (each checker fed ONLY its own
    cluster's history — no double counting), one fleet-level summary
    with ONE static-audit block, and a seed column per cluster."""
    import json
    import os

    from maelstrom_tpu.runner.tpu_runner import run_tpu_test

    # seed 16: the cheapest consecutive pair (16, 17) whose standalone
    # runs BOTH grade valid (seed 12's cas ops legitimately all fail
    # the stats rule, which would make the fleet verdict False)
    test = core.build_test({**LIN_KV, "seed": 16, "fleet": 2,
                            "audit": False})
    res = run_tpu_test(test, str(tmp_path))
    assert res["fleet"] == 2 and res["fleet-sweep"] == "seed"
    assert res["valid"] is True
    assert [c["seed"] for c in res["clusters"]] == [16, 17]
    for i in range(2):
        cdir = os.path.join(str(tmp_path), f"cluster-{i:04d}")
        assert os.path.exists(os.path.join(cdir, "history.jsonl"))
        stored = json.load(open(os.path.join(cdir, "results.json")))
        assert stored["cluster"] == i
        # the workload checker graded exactly this cluster's history:
        # op counts in the stats block match the stored history rows
        rows = [json.loads(line) for line in
                open(os.path.join(cdir, "history.jsonl")) if line.strip()]
        n_completions = sum(1 for r in rows
                            if r["type"] in ("ok", "fail", "info")
                            and r["process"] != "nemesis")
        assert stored["stats"]["count"] == n_completions
        ap = stored.get("analysis-pipeline")
        if ap is not None:
            # the pipeline saw exactly this cluster's rows, not the
            # fleet's (no double counting)
            assert ap["rows"] == len(rows)
    # fleet-level history.jsonl tags each row with its cluster
    merged = open(os.path.join(str(tmp_path), "history.jsonl")).read()
    assert '"c0:' in merged and '"c1:' in merged


def test_run_fleet_test_audit_block(tmp_path):
    """One fleet-level static-audit block (the vmapped step functions
    are shared — per-cluster blocks would repeat the trace F times),
    and it is clean against the checked-in baseline."""
    from maelstrom_tpu.runner.tpu_runner import run_tpu_test

    test = core.build_test({**BROADCAST, "fleet": 2, "time_limit": 0.5,
                            "audit": True})
    res = run_tpu_test(test, str(tmp_path))
    audit = res["static-audit"]
    assert audit["ok"] is True, audit
    assert audit["fleet"] == 2
    assert all("static-audit" not in c.get("net", {})
               for c in res["clusters"])
