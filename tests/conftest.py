"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run over
`--xla_force_host_platform_device_count=8` CPU devices. This must happen
before the first `import jax` anywhere in the test process.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# XLA compiles cost ~1 s each in this environment, so cache them across
# test runs (first run pays, reruns are fast). Same directory bench.py
# uses: one shared persistent cache (entries are keyed by backend, so
# CPU test compiles and TPU bench compiles coexist).
_cache_default = os.path.join(os.path.dirname(__file__), "..",
                              "artifacts", "xla-cache")
if os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                         _cache_default) == _cache_default:
    os.makedirs(_cache_default, exist_ok=True)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# Force genuinely-local CPU devices: remote-TPU dispatch has ~100 ms
# round-trip latency, which would make the lockstep runner unusably slow
# under pytest. The helper beats this image's sitecustomize override.
# The mesh is pinned to exactly 8 devices (any pre-set
# xla_force_host_platform_device_count is overridden): sharding tests
# assert factorizations of 8.
from maelstrom_tpu.util import force_virtual_cpu_mesh  # noqa: E402

force_virtual_cpu_mesh(8)


def pytest_collection_modifyitems(config, items):
    """`multichip` tests need >= 2 devices (the sharded production
    path). The virtual CPU mesh above provides 8 in CI; on environments
    where that failed to stick (e.g. a pre-initialized single-device
    backend) they skip instead of erroring."""
    import jax
    import pytest
    n = jax.device_count()
    if n < 2:
        skip = pytest.mark.skip(
            reason=f"multichip: needs >= 2 JAX devices, have {n}")
        for item in items:
            if "multichip" in item.keywords:
                item.add_marker(skip)
    # checker_bench: throughput micro-benches of the analysis pipeline.
    # Auto-skipped in tier-1 (they measure, they don't verify — the
    # equivalence suites own correctness); opt in explicitly, mirroring
    # the multichip gate: MAELSTROM_CHECKER_BENCH=1 pytest -m checker_bench
    if not os.environ.get("MAELSTROM_CHECKER_BENCH"):
        skip_cb = pytest.mark.skip(
            reason="checker_bench: set MAELSTROM_CHECKER_BENCH=1 to run")
        for item in items:
            if "checker_bench" in item.keywords:
                item.add_marker(skip_cb)
    # soak: multi-cycle SIGKILL/resume crash soaks (subprocess-heavy,
    # minutes each). Tier-1 keeps a single-kill smoke; the full
    # randomized soaks are opt-in: MAELSTROM_SOAK=1 pytest -m soak
    if not os.environ.get("MAELSTROM_SOAK"):
        skip_soak = pytest.mark.skip(
            reason="soak: set MAELSTROM_SOAK=1 to run")
        for item in items:
            if "soak" in item.keywords:
                item.add_marker(skip_soak)


def ops_projection(history):
    """Comparable tuple projection of a history, shared by the
    determinism suites (scan-equivalence, checkpoint/resume) so both
    always compare the same fields."""
    return [(o.type, o.f, o.value, o.process, o.time, o.error, o.final)
            for o in history]
