"""Windowed incremental grading: adversarial window-boundary cases
(ISSUE 7 satellite). Each case feeds a literal history to the analysis
pipeline in hostile segmentations — observations landing N windows
after their obligations, pairs spanning a checkpoint/resume seam
(`seed_resumed`), windows ending mid-rebalance — and pins the windowed
verdict bit-equal to the post-hoc whole-history checker."""

from __future__ import annotations

from maelstrom_tpu.checkers.kafka import KafkaChecker
from maelstrom_tpu.checkers.pipeline import AnalysisPipeline
from maelstrom_tpu.history import coerce_history


def _rows(pairs):
    """Flattens [(f, inv_t, comp_t, value, type, process), ...] into
    interleaved invoke/completion dicts sorted by time (completions
    before invokes at equal times, like the runner's boundary order)."""
    evs = []
    for i, (f, inv_t, comp_t, value, typ, proc) in enumerate(pairs):
        evs.append((inv_t, 1, i, {"type": "invoke", "f": f,
                                  "process": proc, "time": inv_t,
                                  "value": None}))
        if comp_t is not None:
            evs.append((comp_t, 0, i, {"type": typ, "f": f,
                                       "process": proc, "time": comp_t,
                                       "value": value}))
    evs.sort(key=lambda e: (e[0], e[1], e[2]))
    return [e[3] for e in evs]


def _windowed_vs_posthoc(rows, cuts, test=None, resumed=0):
    """Runs the SAME history through (a) a pipeline fed in segments cut
    at the given row indices (the first `resumed` rows seeded as a
    resume segment) and (b) the plain post-hoc checker. Returns
    (windowed_result, posthoc_result, windows)."""
    test = dict(test or {})
    h = coerce_history(rows)
    ck = KafkaChecker()
    pipe = AnalysisPipeline(
        workers=1, observers={"kafka": ck.make_stream_observer(test)},
        ns_per_round=1.0, head_round=lambda: 10 ** 6)
    lo = 0
    if resumed:
        pipe.seed_resumed(h, resumed)
        lo = resumed
    for hi in list(cuts) + [len(h)]:
        if hi > lo:
            pipe.feed(h, lo, hi)
            lo = hi
    pipe.finish()
    assert pipe.error is None, pipe.error
    win = ck.check({**test, "analysis": pipe}, h, {})
    post = ck.check(test, h, {})
    windows = win.pop("windows")
    win.pop("checker-lag")
    return win, post, windows


def test_pipeline_declines_unknown_history():
    # a pipeline that never saw the rows declines service (row-count
    # mismatch) and the checker recomputes post-hoc
    rows = _rows([("send", 0, 1, ["0", 10, 0], "ok", 0)])
    h = coerce_history(rows)
    ck = KafkaChecker()
    pipe = AnalysisPipeline(
        workers=1, observers={"kafka": ck.make_stream_observer({})})
    pipe.finish()
    assert pipe.stream_results("kafka", len(h)) is None
    r = ck.check({"analysis": pipe}, h, {})
    assert "windows" not in r and r["acked-sends"] == 1


def test_ack_observed_n_windows_later():
    """An acked send whose (holey) poll observation lands three windows
    later: the loss is detected in THAT window, and the final verdict
    equals post-hoc."""
    rows = _rows([
        ("send", 0, 1, ["0", 10, 0], "ok", 0),
        ("send", 2, 3, ["0", 11, 1], "ok", 0),      # the lost one
        ("poll", 4, 5, {"0": [[0, 10]]}, "ok", 1),
        ("send", 6, 7, ["0", 12, 2], "ok", 0),
        ("poll", 8, 9, {"0": [[0, 10], [2, 12]]}, "ok", 1),  # hole at 1
    ])
    win, post, windows = _windowed_vs_posthoc(
        rows, cuts=[2, 4, 6, 8])
    assert win == post
    assert win["valid"] is False
    assert win["lost-writes"][0]["offset"] == 1
    # the loss surfaced in the window holding the exposing poll (the
    # last one), not earlier
    flagged = [w["window"] for w in windows
               if w["verdict"].get("lost-writes")]
    assert flagged == [len(windows) - 1]
    earlier_ok = [w["verdict"]["ok"] for w in windows[:-1]]
    assert all(earlier_ok)


def test_commit_spanning_resume_boundary():
    """A commit whose invoke lands in the resumed (seed_resumed) rows
    and whose completion arrives in a later window: the pairing state
    crosses the seam, and the committed floor still binds later lists —
    equal to post-hoc."""
    rows = _rows([
        ("send", 0, 1, ["0", 10, 0], "ok", 0),
        ("commit", 2, 14, {"0": 5}, "ok", 0),       # spans the seam
        ("list", 20, 21, {"0": 3}, "ok", 1),        # regression!
    ])
    # rows: inv(send)@0, comp(send)@1, inv(commit)@2, comp@14,
    # inv(list)@20, comp@21 — cut the resume seam INSIDE the commit
    win, post, windows = _windowed_vs_posthoc(
        rows, cuts=[4], resumed=3)
    assert win == post
    assert win["valid"] is False
    assert win["commit-regressions"][0]["committed"] == 5
    assert sum(1 for w in windows
               if w["verdict"].get("commit-regressions")) == 1


def test_list_invoked_before_commit_completion_across_windows():
    """The equal-obligation edge: a list that BEGAN before the commit
    completed owes nothing, even when the commit's completion lands a
    window earlier than the list's — the raise-time floors keep the
    windowed path exactly as lenient as the post-hoc sweep."""
    rows = _rows([
        ("commit", 0, 10, {"0": 5}, "ok", 0),
        ("list", 8, 30, {"0": 2}, "ok", 1),     # began before t=10
    ])
    win, post, _ = _windowed_vs_posthoc(rows, cuts=[3])
    assert win == post
    assert win["valid"] is True


def test_window_ends_mid_rebalance():
    """Streaming mode: the window boundary falls between a fenced
    commit (fail: constrains nothing) and the rejoined session's
    next fetch + group commit — carried subscription state keeps the
    verdict equal to post-hoc."""
    test = {"kafka_groups": 2}
    rows = _rows([
        ("send", 0, 1, ["0", 10, 0], "ok", 0),
        ("poll", 2, 3, {"0": [[0, 10]]}, "ok", 1),
        ("commit", 4, 5, None, "fail", 1),          # fenced mid-window
        # --- window boundary lands here (mid-rebalance) ---
        ("subscribe", 6, 7, {"gen": 2, "assigned": [0, 1]}, "ok", 1),
        ("poll", 8, 9, {"0": [[1, 11]]}, "ok", 1),  # cursor continues
        ("send", 10, 11, ["0", 11, 1], "ok", 0),
        ("commit", 12, 13, {"group": 1, "offsets": {"0": 1}}, "ok", 1),
        ("list", 14, 15, {"group": 1, "offsets": {"0": 1}}, "ok", 1),
    ])
    win, post, windows = _windowed_vs_posthoc(rows, cuts=[6], test=test)
    assert win == post
    assert win["valid"] is True, win
    assert len(windows) == 2 and all(w["verdict"]["ok"]
                                     for w in windows)


def test_divergence_across_windows_equal_and_flagged():
    rows = _rows([
        ("send", 0, 1, ["0", 10, 0], "ok", 0),
        ("poll", 10, 11, {"0": [[0, 999]]}, "ok", 1),
    ])
    win, post, windows = _windowed_vs_posthoc(rows, cuts=[2])
    assert win == post
    assert win["valid"] is False and win["divergent"]
    assert windows[1]["verdict"].get("divergent") == 1


def test_lag_metric_rides_windows():
    rows = _rows([("send", 0, 1, ["0", 10, 0], "ok", 0)])
    test = {}
    h = coerce_history(rows)
    ck = KafkaChecker()
    pipe = AnalysisPipeline(
        workers=1, observers={"kafka": ck.make_stream_observer(test)},
        ns_per_round=1.0, head_round=lambda: 500)
    pipe.feed(h, 0, len(h))
    pipe.finish()
    r = ck.check({"analysis": pipe}, h, {})
    (w,) = r["windows"]
    assert w["end-round"] == 1
    assert w["lag-rounds"] == 499
    assert r["checker-lag"]["max-lag-rounds"] == 499
    assert pipe.report()["max-lag-rounds"] == 499
