"""Kafka workload: checker unit tests on literal histories — legal and
forged (divergent assignment, unordered poll, lost write, committed-
offset regression) — plus indeterminate-op semantics."""

from maelstrom_tpu.checkers.kafka import KafkaChecker
from maelstrom_tpu.history import History, Op


def _h(ops):
    return History([Op(**o) for o in ops])


def _op(f, t, value, type="ok", process=0):
    return [
        {"type": "invoke", "f": f, "process": process, "time": t,
         "value": None},
        {"type": type, "f": f, "process": process, "time": t + 1,
         "value": value},
    ]


def test_legal_history():
    ops = (_op("send", 0, ["0", 10, 0])
           + _op("send", 10, ["0", 11, 1])
           + _op("poll", 20, {"0": [[0, 10], [1, 11]]})
           + _op("commit", 30, {"0": 1})
           + _op("list", 40, {"0": 1}))
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is True
    assert r["acked-sends"] == 2 and r["distinct-offsets"] == 2


def test_divergent_offset_detected():
    ops = (_op("send", 0, ["0", 10, 0])
           + _op("poll", 20, {"0": [[0, 999]]}))     # same offset, other msg
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["divergent"][0]["offset"] == 0


def test_unordered_poll_detected():
    ops = _op("poll", 0, {"0": [[1, 11], [0, 10]]})
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert "poll-order" in r


def test_lost_write_detected():
    # send acked at offset 0; a later poll reads past it without it
    ops = (_op("send", 0, ["0", 10, 0])
           + _op("poll", 20, {"0": [[1, 11]]}))
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["lost-writes"][0]["offset"] == 0


def test_commit_regression_detected():
    ops = (_op("commit", 0, {"0": 5})
           + _op("list", 20, {"0": 3}))              # observed < committed
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["commit-regressions"][0]["committed"] == 5


def test_lower_commit_request_is_legal():
    # a second worker committing a lower offset must NOT fail the run:
    # the stored mark clamps, and the later list sees the higher one
    ops = (_op("commit", 0, {"0": 5})
           + _op("commit", 10, {"0": 2}, process=1)
           + _op("list", 20, {"0": 5}))
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is True


def test_indeterminate_send_unconstrained():
    # an info send's offset was never observed: later polls owe nothing
    ops = (_op("send", 0, None, type="info")
           + _op("poll", 20, {"0": [[0, 10]]}))
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is True


def test_concurrent_list_not_flagged():
    # list B invoked BEFORE commit completed: no ordering obligation
    ops = (_op("commit", 10, {"0": 5})
           + _op("list", 10, {"0": 3}))     # overlaps the commit
    r = KafkaChecker().check({}, _h(ops), {})
    assert r["valid"] is True


def test_node_for_op_routing():
    # the smart-client hook routes sends to owners and commit/list to
    # the coordinator; polls stay on the worker's bound node (None)
    from maelstrom_tpu.nodes import get_program

    p = get_program("kafka", {"key_count": 4}, ["n0", "n1", "n2"])
    assert p.node_for_op({"f": "send", "value": [2, 99]}) == 2 % 3
    assert p.node_for_op({"f": "send", "value": [3, 99]}) == 0  # wraps
    assert p.node_for_op({"f": "commit", "value": None}) == 0
    assert p.node_for_op({"f": "list", "value": None}) == 0
    assert p.node_for_op({"f": "poll", "value": None}) is None
    # out-of-range keys aren't routed — and encode rejects them with a
    # definite failure (the device would otherwise clip into the WRONG
    # key's log)
    assert p.node_for_op({"f": "send", "value": [5, 99]}) is None
    import pytest as _pytest
    from maelstrom_tpu.nodes import EncodeCapacityError, Intern
    with _pytest.raises(EncodeCapacityError):
        p.encode_body({"type": "send", "key": 5, "msg": 1}, Intern())
    # default hook: no routing
    echo = get_program("echo", {}, ["n0", "n1"])
    assert echo.node_for_op({"f": "echo", "value": "x"}) is None


def test_kafka_tpu_e2e():
    """The batched program end to end: ownership-assigned offsets,
    anti-entropy replication feeding full-prefix polls, coordinator-
    routed commits — graded by the same checker as the process path."""
    from maelstrom_tpu import core

    res = core.run(dict(store_root="/tmp/maelstrom-tpu-test-store",
                        seed=7, rate=20.0, time_limit=3.0,
                        journal_rows=False, workload="kafka",
                        node="tpu:kafka", node_count=5))
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["acked-sends"] > 0 and w["polls"] > 0
    # replication is real server traffic
    assert res["net"]["servers"]["send-count"] > 0
