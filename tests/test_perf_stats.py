"""Vectorized latency_stats (ISSUE 13 satellite): the columnar numpy
path must be bit-equal to the original per-pair Python loop, on random
histories and on the degenerate shapes the loop handled incidentally."""

from __future__ import annotations

import random

from maelstrom_tpu.checkers.perf import (_latency_stats_loop,
                                         latency_stats)
from maelstrom_tpu.history import History


def _random_history(seed, n_procs=6, n_ops=300, with_nemesis=True):
    rng = random.Random(seed)
    h = History()
    open_p = {}
    t = 0
    fs = ["read", "write", "cas", None]
    for _ in range(n_ops):
        t += rng.randint(1, 5) * 1_000_000
        if with_nemesis and rng.random() < 0.08:
            if rng.random() < 0.5:
                h.append_row("invoke", "start-partition", None,
                             "nemesis", t)
            else:
                h.append_row("info", "start-partition", "x",
                             "nemesis", t)
            continue
        p = rng.randrange(n_procs)
        if p in open_p and rng.random() < 0.8:
            kind = rng.choice(["ok", "ok", "fail", "info"])
            h.append_row(kind, open_p.pop(p), [None, rng.randint(0, 9)],
                         p, t)
        else:
            # possibly double-invoke (crashed worker): the old pair
            # drops the stale invoke
            f = rng.choice(fs)
            h.append_row("invoke", f, [None, rng.randint(0, 9)], p, t)
            open_p[p] = f
    return h


def test_vectorized_matches_loop_random():
    for seed in range(8):
        h = _random_history(seed)
        assert latency_stats(h) == _latency_stats_loop(h), seed


def test_vectorized_matches_loop_degenerate():
    assert latency_stats(History()) == _latency_stats_loop(History())
    # nemesis-only
    h = History()
    h.append_row("invoke", "start-kill", None, "nemesis", 5)
    h.append_row("info", "start-kill", "x", "nemesis", 9)
    assert latency_stats(h) == _latency_stats_loop(h) == {}
    # unpaired invoke only
    h2 = History()
    h2.append_row("invoke", "read", None, 0, 5)
    assert latency_stats(h2) == _latency_stats_loop(h2) == {}
    # fail/info completions only -> no ok latencies
    h3 = History()
    h3.append_row("invoke", "read", None, 0, 0)
    h3.append_row("info", "read", None, 0, 1_000_000, "net-timeout")
    h3.append_row("invoke", "write", [None, 1], 1, 0)
    h3.append_row("fail", "write", [None, 1], 1, 2_000_000)
    assert latency_stats(h3) == _latency_stats_loop(h3) == {}


def test_by_f_breakdown_partitions_the_same_latencies():
    h = _random_history(3)
    top = latency_stats(h, by_f=True)
    plain = latency_stats(h)
    by_f = top.pop("by-f")
    assert top == plain
    # per-f counts sum to the total, every block carries the quantiles
    assert sum(b["count"] for b in by_f.values()) == plain["count"]
    for b in by_f.values():
        assert {"count", "p50", "p95", "p99", "max"} <= set(b)
    # a single-f history's by-f block IS the top-level block
    h2 = History()
    for i in range(10):
        h2.append_row("invoke", "read", None, 0, i * 10_000_000)
        h2.append_row("ok", "read", [None, i], 0,
                      i * 10_000_000 + (i + 1) * 1_000_000)
    out = latency_stats(h2, by_f=True)
    assert out["by-f"]["read"] == latency_stats(h2)
