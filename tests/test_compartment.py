"""Compartmentalized consensus (ISSUE 10, doc/compartment.md): the
role-partitioned proxy/acceptor/replica cluster serving lin-kv, graded
by the stock linearizable checker — plain, sharded, and under
role-targeted fault soups."""

import pytest

from maelstrom_tpu import core
from maelstrom_tpu import nemesis as nem
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.nodes.compartment import (parse_roles,
                                             roles_node_count)

STORE = "/tmp/maelstrom-compartment-store"


def run(opts):
    base = dict(store_root=STORE, seed=7, rate=20.0, time_limit=2.0,
                journal_rows=False, audit=False,
                node="tpu:compartment", workload="lin-kv")
    return core.run({**base, **opts})


# --- role spec / layout ----------------------------------------------------

def test_parse_roles():
    assert parse_roles("proxies=4,acceptors=2x3,replicas=2") == {
        "sequencers": 1, "proxies": 4, "rows": 2, "cols": 3,
        "replicas": 2}
    # a plain acceptor count is a single-row grid
    assert parse_roles("acceptors=3") == {
        "sequencers": 1, "proxies": 2, "rows": 1, "cols": 3,
        "replicas": 2}
    # the elected configuration: a candidate tier
    assert parse_roles("sequencers=3,acceptors=3") == {
        "sequencers": 3, "proxies": 2, "rows": 1, "cols": 3,
        "replicas": 2}
    assert roles_node_count(None) == 9          # 1 + 2 + 2x2 + 2
    assert roles_node_count("proxies=4,acceptors=2x3,replicas=3") == 14
    assert roles_node_count("sequencers=3,proxies=4,acceptors=2x3,"
                            "replicas=3") == 16
    with pytest.raises(ValueError, match="unknown role"):
        parse_roles("leaders=2")
    with pytest.raises(ValueError, match=">= 1"):
        parse_roles("proxies=0")


def test_roles_size_the_cluster():
    nodes = core.parse_nodes({"node": "tpu:compartment",
                              "roles": "proxies=1,acceptors=1x2,"
                                       "replicas=1"})
    assert nodes == ["n0", "n1", "n2", "n3", "n4"]
    # a mismatched explicit node count is rejected with a clear error
    with pytest.raises(ValueError, match="needs 9 nodes"):
        get_program("compartment", {"rate": 5, "time_limit": 1},
                    [f"n{i}" for i in range(7)])


def test_fault_groups_name_roles_and_grid_lines():
    prog = get_program("compartment", {"rate": 5, "time_limit": 1},
                       [f"n{i}" for i in range(9)])
    g = prog.fault_groups()
    assert g["sequencers"] == ["n0"]
    assert g["proxies"] == ["n1", "n2"]
    assert g["acceptors"] == ["n3", "n4", "n5", "n6"]
    assert g["replicas"] == ["n7", "n8"]
    # grid: acceptor local idx = row * cols + col over n3..n6
    assert g["acceptor-col-0"] == ["n3", "n5"]
    assert g["acceptor-col-1"] == ["n4", "n6"]
    assert g["acceptor-row-0"] == ["n3", "n4"]
    assert g["acceptor-row-1"] == ["n5", "n6"]


def test_resolve_targets_and_isolate_set():
    groups = {"proxies": ["n1", "n2"], "acceptor-col-0": ["n3", "n5"]}
    nodes = [f"n{i}" for i in range(9)]
    t = nem.resolve_targets("kill=proxies,partition=acceptor-col-0",
                            groups, nodes)
    assert t == {"kill": ["n1", "n2"], "partition": ["n3", "n5"]}
    # '+' unions groups and literal node names resolve too
    t2 = nem.resolve_targets("pause=proxies+n7", groups, nodes)
    assert t2 == {"pause": ["n1", "n2", "n7"]}
    with pytest.raises(ValueError, match="unknown group"):
        nem.resolve_targets("kill=replicas", groups, nodes)
    name, grudge = nem.isolate_set(nodes, ["n3", "n5"])
    assert "n3" in name
    assert grudge["n0"] == {"n3", "n5"}
    assert grudge["n3"] == set(nodes) - {"n3", "n5"}


def test_targeted_decisions_stay_in_pool():
    d = nem.NemesisDecisions([f"n{i}" for i in range(9)], seed=3,
                             targets={"kill": ["n1", "n2"],
                                      "partition": ["n3", "n5"]})
    for _ in range(8):
        assert set(d.next_kill_targets()) <= {"n1", "n2"}
    name, grudge = d.next_grudge()
    assert grudge["n3"] == set(f"n{i}" for i in range(9)) - {"n3", "n5"}


# --- end to end ------------------------------------------------------------

def test_compartment_lin_kv_plain():
    res = run({})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True
    assert res["stats"]["ok-count"] > 10
    # the tiers actually talked: inter-server traffic dominates
    assert res["net"]["servers"]["send-count"] > \
        res["stats"]["count"] * 4


def test_compartment_targeted_kill_partition_soup():
    """Kills sample the proxy tier only, the partition cuts acceptor
    column 0 off the cluster, and the verdict stays valid post-heal."""
    res = run({"seed": 11, "time_limit": 3.0,
               "nemesis": {"kill", "partition"},
               "nemesis_interval": 0.7,
               "nemesis_targets": "kill=proxies,"
                                  "partition=acceptor-col-0",
               "recovery_s": 2})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True
    assert res["stats"]["ok-count"] > 10
    # the recorded kill ops targeted proxies (n1/n2) exclusively
    import json
    import os
    with open(os.path.join(STORE, "latest", "history.jsonl")) as f:
        kills = [json.loads(ln) for ln in f
                 if '"start-kill"' in ln and '"info"' in ln]
    assert kills, "no kill windows fired"
    for k in kills:
        v = str(k.get("value"))
        assert "n1" in v or "n2" in v
        for other in ("n0", "n3", "n4", "n5", "n6", "n7", "n8"):
            assert f"'{other}'" not in v


@pytest.mark.slow
def test_compartment_combined_soup():
    """The full four-package soup (untargeted): kills may wipe volatile
    proxies, pause anyone, partition arbitrarily, duplicate at-least-
    once — linearizability must hold through recovery."""
    res = run({"seed": 13, "time_limit": 3.0,
               "nemesis": {"kill", "pause", "partition", "duplicate"},
               "nemesis_interval": 0.7, "recovery_s": 2})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True


@pytest.mark.multichip
def test_compartment_lin_kv_mesh():
    res = run({"mesh": "1,2"})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True


@pytest.mark.multichip
@pytest.mark.slow
def test_compartment_soup_mesh():
    res = run({"seed": 11, "time_limit": 3.0, "mesh": "1,2",
               "nemesis": {"kill", "partition"},
               "nemesis_interval": 0.7,
               "nemesis_targets": "kill=proxies,"
                                  "partition=acceptor-col-0",
               "recovery_s": 2})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True


def test_compartment_checkpoints_heterogeneous_tree():
    """Checkpointing a role-partitioned run snapshots the whole
    {role: subtree} state (plus the mixed durable views) into a
    loadable crash-consistent file whose fingerprint pins the role
    spec."""
    import os

    from maelstrom_tpu import checkpoint as cp
    res = run({"checkpoint_every": 0.5, "sync_checkpoint": True})
    assert res["valid"] is True, res.get("workload")
    latest = os.path.join(STORE, "latest")
    state = cp.load(os.path.realpath(latest))
    assert set(state["sim"].nodes) == {"sequencers", "proxies",
                                       "acceptors", "replicas"}
    assert state["fingerprint"]["roles"] is None      # default spec
    # a different role spec must refuse to resume this checkpoint
    with pytest.raises(ValueError, match="roles"):
        cp.check_fingerprint(
            state, core.build_test({
                "workload": "lin-kv", "node": "tpu:compartment",
                "roles": "proxies=4,acceptors=2x2,replicas=2",
                "seed": 7, "rate": 20.0, "time_limit": 2.0}))


def test_leader_backpressure_sheds_definitely():
    """A full sequencer table sheds with error 11 (definite fail) —
    visible backpressure, never a silent drop, and still
    linearizable."""
    res = run({"rate": 200.0, "time_limit": 1.0, "leader_slots": 2,
               "proxy_slots": 2, "concurrency": 16})
    assert res["valid"] is True, res.get("workload")
    assert res["stats"]["fail-count"] > 0
    assert res["stats"]["ok-count"] > 0


@pytest.mark.slow
def test_proxy_scaling_more_ok_ops_at_saturation():
    """The bench claim in miniature: at an offered rate far above the
    P=1 tier's capacity, 4 proxies complete materially more ops than 1
    at the SAME leader/acceptor budget (the full sweep with the >= 2x
    acceptance floor is BENCH_MODE=compartment)."""
    fixed = dict(rate=2000.0, time_limit=1.0, concurrency=48,
                 leader_slots=64, proxy_slots=4, compartment_inbox=16,
                 kv_keys=256, timeout_ms=20000, seed=11)
    r1 = run({**fixed, "roles": "proxies=1,acceptors=2x2,replicas=2"})
    r4 = run({**fixed, "roles": "proxies=4,acceptors=2x2,replicas=2"})
    assert r1["valid"] is True and r4["valid"] is True
    ok1, ok4 = r1["stats"]["ok-count"], r4["stats"]["ok-count"]
    assert ok4 > 1.5 * ok1, (ok1, ok4)
