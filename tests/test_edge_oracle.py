"""Property tests: the static edge channels vs a pure-Python oracle.

The flight pool has an oracle suite (test_tpu_net_oracle.py); this is
the same discipline for the sort-free edge fast path (`net/static.py`),
which carries all topology traffic in the batched programs. Semantics
pinned: a message written on edge (n, d, lane) at round r with latency
L arrives at the receiving end's reverse slot at round r + max(1, L)
(deadline = now + latency with a one-round causal floor); draws beyond
ring-1 are clipped (and counted); two messages landing in the same
(edge, lane, arrival-round) cell overwrite (bounded-channel loss,
counted); masked (lost/partitioned) messages never enter the ring."""

from __future__ import annotations

import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    # minimal installs: the vendored fallback backend (same surface, no
    # shrinking) keeps the property suite running where hypothesis isn't
    # baked in; importorskip still guards truly bare environments
    minihyp = pytest.importorskip(
        "maelstrom_tpu.testing.minihyp",
        reason="property tests need hypothesis or the vendored fallback")
    given, settings, st = (minihyp.given, minihyp.settings,
                           minihyp.strategies)

import jax
import jax.numpy as jnp
import numpy as np

from maelstrom_tpu.net import static as S
from maelstrom_tpu.net.tpu import I32

# a fixed 4-node line: n0 - n1 - n2 - n3
NEIGHBORS = np.array([[1, -1], [0, 2], [1, 3], [2, -1]], np.int32)
REV = S.reverse_index(NEIGHBORS)
N, D = NEIGHBORS.shape
LANES = 2


def drive(cfg, schedule, rounds, lat_fill=None, track_sent=False):
    """schedule: {round: [(n, d, lane, a, lat, deliver)]}. Returns
    (delivered {(round, receiver, rev_edge, lane): a-or-(a, sent)},
    overwrites, clipped). `lat_fill`: {round: lv} fills the WHOLE
    latency array (the uniform_arrival contract: constant draws cover
    every entry, valid or not)."""
    ch = S.make_channels(cfg, track_send_round=track_sent)
    nb = jnp.asarray(NEIGHBORS)
    rev = jnp.asarray(REV)
    delivered = {}
    for r in range(rounds):
        ch, inbox = S.edge_read(cfg, ch, nb, rev, jnp.int32(r))
        ib = jax.device_get(inbox)
        for m in range(N):
            for e in range(D):
                for l in range(LANES):
                    if ib.valid[m, e, l]:
                        delivered[(r, m, e, l)] = (
                            (int(ib.a[m, e, l]), int(ib.sent[m, e, l]))
                            if track_sent else int(ib.a[m, e, l]))
        out = S.EdgeMsgs.empty((N, D, LANES))
        lat = np.full((N, D, LANES),
                      0 if lat_fill is None else lat_fill.get(r, 0),
                      np.int32)
        mask = np.ones((N, D, LANES), bool)
        valid = np.zeros((N, D, LANES), bool)
        a = np.zeros((N, D, LANES), np.int32)
        for (n, d, l, av, lv, dv) in schedule.get(r, []):
            valid[n, d, l] = True
            a[n, d, l] = av
            lat[n, d, l] = lv
            mask[n, d, l] = dv
        out = out.replace(valid=jnp.asarray(valid), a=jnp.asarray(a),
                          type=jnp.ones((N, D, LANES), I32))
        ch = S.edge_write(cfg, ch, out, jnp.int32(r), jnp.asarray(lat),
                          jnp.asarray(mask))
    return (delivered, int(jax.device_get(ch.overwrites)),
            int(jax.device_get(ch.lat_clipped)))


def oracle(cfg, schedule, rounds):
    """The documented semantics over plain dicts."""
    cells = {}          # (arrival_round, n, d, lane) -> a
    overwrites = 0
    clipped = 0
    delivered = {}
    for r in range(rounds):
        # read first (matches _round_edge's edge_read-then-edge_write)
        for m in range(N):
            for e in range(D):
                if NEIGHBORS[m, e] < 0:
                    continue
                src, sd = NEIGHBORS[m, e], REV[m, e]
                for l in range(LANES):
                    key = (r, src, sd, l)
                    if key in cells:
                        delivered[(r, m, e, l)] = cells.pop(key)
        for (n, d, l, av, lv, dv) in schedule.get(r, []):
            if not dv:
                continue
            if lv > cfg.ring - 1:
                clipped += 1
            eff = max(1, min(lv, cfg.ring - 1))
            key = (r + eff, n, d, l)
            if key in cells:
                overwrites += 1
            cells[key] = av
    return delivered, overwrites, clipped


events = st.lists(
    st.tuples(st.integers(0, 5),          # round
              st.integers(0, N - 1),      # src node
              st.integers(0, D - 1),      # edge
              st.integers(0, LANES - 1),  # lane
              st.integers(1, 99),         # payload
              st.integers(0, 9),          # latency (beyond ring clips)
              st.booleans()),             # deliver mask
    min_size=0, max_size=24)


@settings(max_examples=40, deadline=None)
@given(evs=events, ring=st.integers(2, 6))
def test_edge_channels_match_oracle(evs, ring):
    cfg = S.EdgeConfig(n_nodes=N, degree=D, lanes=LANES, ring=ring)
    # one message per (round, n, d, lane) slot — the out batch is an
    # array, so a later event in the same slot replaces the earlier one;
    # dedup so the oracle sees exactly what the device sees
    slots = {}
    for (r, n, d, l, av, lv, dv) in evs:
        if NEIGHBORS[n, d] < 0:
            continue        # no edge there: programs never write these
        slots[(r, n, d, l)] = (av, lv, dv)
    schedule = {}
    for (r, n, d, l), (av, lv, dv) in slots.items():
        schedule.setdefault(r, []).append((n, d, l, av, lv, dv))
    rounds = 6 + ring + 10
    got = drive(cfg, schedule, rounds)
    want = oracle(cfg, schedule, rounds)
    assert got[0] == want[0], (got[0], want[0])
    assert got[1] == want[1]        # overwrites
    assert got[2] == want[2]        # clipped draws


# --- spill mode: collision-free writes -------------------------------------

def drive_spill(cfg, schedule, rounds, lanes_out):
    """Like `drive`, but with a spill-mode EdgeConfig and possibly fewer
    out lanes than channel lanes. Returns (delivered multisets keyed by
    (round, receiver, rev_edge), overwrites, clipped)."""
    ch = S.make_channels(cfg)
    nb = jnp.asarray(NEIGHBORS)
    rev = jnp.asarray(REV)
    delivered = {}
    for r in range(rounds):
        ch, inbox = S.edge_read(cfg, ch, nb, rev, jnp.int32(r))
        ib = jax.device_get(inbox)
        for m in range(N):
            for e in range(D):
                got = sorted(int(ib.a[m, e, l]) for l in range(cfg.lanes)
                             if ib.valid[m, e, l])
                if got:
                    delivered[(r, m, e)] = got
        out = S.EdgeMsgs.empty((N, D, lanes_out))
        lat = np.zeros((N, D, lanes_out), np.int32)
        mask = np.ones((N, D, lanes_out), bool)
        valid = np.zeros((N, D, lanes_out), bool)
        a = np.zeros((N, D, lanes_out), np.int32)
        for (n, d, l, av, lv, dv) in schedule.get(r, []):
            valid[n, d, l] = True
            a[n, d, l] = av
            lat[n, d, l] = lv
            mask[n, d, l] = dv
        out = out.replace(valid=jnp.asarray(valid), a=jnp.asarray(a),
                          type=jnp.ones((N, D, lanes_out), I32))
        ch = S.edge_write(cfg, ch, out, jnp.int32(r), jnp.asarray(lat),
                          jnp.asarray(mask))
    return (delivered, int(jax.device_get(ch.overwrites)),
            int(jax.device_get(ch.lat_clipped)))


def oracle_spill(cfg, schedule, rounds, lanes_out):
    """Spill semantics: a cell holds up to cfg.lanes messages; incoming
    messages append in lane order; only cell exhaustion drops (counted),
    and drops take the newest arrivals (the stable sort keeps existing
    messages first)."""
    cells = {}          # (arrival_round, n, d) -> [a, ...]
    overwrites = 0
    clipped = 0
    delivered = {}
    for r in range(rounds):
        for m in range(N):
            for e in range(D):
                if NEIGHBORS[m, e] < 0:
                    continue
                src, sd = NEIGHBORS[m, e], REV[m, e]
                got = cells.pop((r, src, sd), None)
                if got:
                    delivered[(r, m, e)] = sorted(got)
        for (n, d, l, av, lv, dv) in sorted(schedule.get(r, []),
                                            key=lambda t: t[2]):
            if not dv:
                continue
            if lv > cfg.ring - 1:
                clipped += 1
            eff = max(1, min(lv, cfg.ring - 1))
            cell = cells.setdefault((r + eff, n, d), [])
            if len(cell) >= cfg.lanes:
                overwrites += 1
            else:
                cell.append(av)
    return delivered, overwrites, clipped


@settings(max_examples=40, deadline=None)
@given(evs=events, ring=st.integers(2, 6), extra=st.integers(0, 2))
def test_edge_channels_spill_match_oracle(evs, ring, extra):
    """spill=True never destroys a message short of cell exhaustion, and
    delivery rounds are unchanged; lane positions may differ (compared as
    multisets). `extra` exercises channel lanes > out lanes (headroom)."""
    cfg = S.EdgeConfig(n_nodes=N, degree=D, lanes=LANES + extra, ring=ring,
                       spill=True)
    slots = {}
    for (r, n, d, l, av, lv, dv) in evs:
        if NEIGHBORS[n, d] < 0:
            continue
        slots[(r, n, d, l)] = (av, lv, dv)
    schedule = {}
    for (r, n, d, l), (av, lv, dv) in slots.items():
        schedule.setdefault(r, []).append((n, d, l, av, lv, dv))
    rounds = 6 + ring + 10
    got = drive_spill(cfg, schedule, rounds, LANES)
    want = oracle_spill(cfg, schedule, rounds, LANES)
    assert got[0] == want[0], (got[0], want[0])
    assert got[1] == want[1]        # drops only on cell exhaustion
    assert got[2] == want[2]        # clipped draws


def test_spill_no_loss_when_capacity_suffices():
    """Two same-cell arrivals with a free lane both deliver — the exact
    collision that destroyed messages in overwrite mode (VERDICT r2:
    naive broadcast, grid 25, 100 ms exponential, lost: 2)."""
    cfg = S.EdgeConfig(n_nodes=N, degree=D, lanes=2, ring=4, spill=True)
    # lane 0 at round 0 with latency 2 and lane 0 at round 1 with
    # latency 1 both arrive at round 2 on edge (1, 1)
    schedule = {0: [(1, 1, 0, 7, 2, True)], 1: [(1, 1, 0, 9, 1, True)]}
    delivered, overwrites, _ = drive_spill(cfg, schedule, 6, 2)
    assert overwrites == 0
    assert delivered == {(2, 2, 0): [7, 9]}


# --- device-resident elle edges: third implementation vs both oracles ------
#
# The same discipline for the checker's device edge path
# (checkers/elle_device.py, doc/perf.md "device-resident grading"):
# randomized list-append histories from the SHARED generator
# (testing/histories.py — the one the overlap-equivalence suite and the
# bench's screen fixtures draw from), with the jitted device build
# pinned set-equal against BOTH `_edges_python` (the original oracle)
# and `_edges_vectorized` (the PR 3 fast path), and full analyze()
# verdict equality on top (screen + Tarjan-fallback paths included).

@settings(max_examples=15, deadline=None)
@given(seed=st.integers(0, 10 ** 6),
       corrupt=st.sampled_from([0.0, 0.0, 0.1, 0.25]),
       empty_reads=st.booleans(),
       keys=st.integers(1, 6))
def test_elle_device_edges_match_both_oracles(seed, corrupt,
                                              empty_reads, keys):
    from maelstrom_tpu.checkers.elle import (_edges_python,
                                             _edges_vectorized,
                                             _fail_appends, _hk, _hv,
                                             _txn_ops, analyze)
    from maelstrom_tpu.checkers.elle_device import edges_device
    from maelstrom_tpu.testing.histories import random_append_history

    h = random_append_history(seed, n_txn=60, keys=keys,
                              corrupt=corrupt, empty_reads=empty_reads)
    txns = _txn_ops(h)
    appender, longest = {}, {}
    for t in txns:
        for f, k, v in t["micro"]:
            if f == "append":
                appender[(_hk(k), _hv(v))] = t["id"]
    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f == "r" and isinstance(v, list):
                kk = _hk(k)
                vv = [_hv(x) for x in v]
                if len(vv) > len(longest.get(kk, [])):
                    longest[kk] = vv
    dev = edges_device(txns, longest, appender)
    assert dev == _edges_vectorized(txns, longest, appender)
    assert dev == _edges_python(txns, longest, appender)
    assert analyze(h, device="on") \
        == analyze(h, edges_impl=_edges_python)


@pytest.mark.slow
@settings(max_examples=25, deadline=None)
@given(evs=events, ring=st.integers(2, 6),
       lat_of_round=st.lists(st.integers(0, 5), min_size=16, max_size=16))
def test_uniform_arrival_matches_general_write(evs, ring, lat_of_round):
    """EdgeConfig(uniform_arrival=True) — the constant-latency single-
    cell write — must be observationally identical to the general write
    whenever every round's latency array is uniform (the constant-dist
    contract, scale nemesis included)."""
    base = S.EdgeConfig(n_nodes=N, degree=D, lanes=LANES, ring=ring)
    uni = S.EdgeConfig(n_nodes=N, degree=D, lanes=LANES, ring=ring,
                       uniform_arrival=True)
    slots = {}
    for (r, n, d, l, av, _lv, dv) in evs:
        if NEIGHBORS[n, d] < 0:
            continue
        slots[(r, n, d, l)] = (av, lat_of_round[r % 16], dv)
    schedule = {}
    for (r, n, d, l), (av, lv, dv) in slots.items():
        schedule.setdefault(r, []).append((n, d, l, av, lv, dv))
    rounds = 6 + ring + 10
    fill = {r: lat_of_round[r % 16] for r in range(rounds)}
    # track_sent also pins the uniform path's journal-stamp plane
    assert (drive(base, schedule, rounds, lat_fill=fill, track_sent=True)
            == drive(uni, schedule, rounds, lat_fill=fill,
                     track_sent=True))
