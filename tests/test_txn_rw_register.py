"""The txn-rw-register observable-subset checker on literal histories:
legal chains, forged G1a/G1b/internal anomalies, and the wr+realtime
cycle (a read of a write from the future) it must catch — plus the
concurrent case it must NOT flag."""

from maelstrom_tpu.checkers.txn_rw_register import RWRegisterChecker
from maelstrom_tpu.history import History, Op


def _h(ops):
    return History([Op(**o) for o in ops])


def _txn(t_inv, t_ok, mops, completed=None, type="ok", process=0):
    return [
        {"type": "invoke", "f": "txn", "process": process, "time": t_inv,
         "value": mops},
        {"type": type, "f": "txn", "process": process, "time": t_ok,
         "value": completed if completed is not None else mops},
    ]


def test_legal_chain():
    ops = (_txn(0, 1, [["w", 1, 10]])
           + _txn(2, 3, [["r", 1, None]], [["r", 1, 10]])
           + _txn(4, 5, [["w", 1, 11], ["r", 1, None]],
                  [["w", 1, 11], ["r", 1, 11]]))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is True
    assert r["wr-edge-count"] == 1


def test_internal_violation():
    ops = _txn(0, 1, [["w", 1, 10], ["r", 1, None]],
               [["w", 1, 10], ["r", 1, 3]])
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["internal"][0]["expected"] == 10


def test_g1a_aborted_read():
    ops = (_txn(0, 1, [["w", 1, 99]], type="fail")
           + _txn(2, 3, [["r", 1, None]], [["r", 1, 99]]))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["G1a"][0]["value"] == 99


def test_g1b_intermediate_read():
    ops = (_txn(0, 1, [["w", 1, 10], ["w", 1, 11]])
           + _txn(2, 3, [["r", 1, None]], [["r", 1, 10]]))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["G1b"][0]["value"] == 10


def test_read_from_the_future_cycle():
    # T1 completed before T2 even invoked, yet T1 observed T2's write:
    # wr edge T2->T1 plus realtime T1->T2 closes a cycle
    ops = (_txn(0, 1, [["r", 1, None]], [["r", 1, 50]])
           + _txn(10, 11, [["w", 1, 50]], process=1))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["cycles"][0]["txns"] == [0, 1]
    assert r["cycles"][0]["via-realtime"] is True


def test_concurrent_read_not_flagged():
    # same shape but OVERLAPPING ops: no realtime edge, no cycle
    ops = (_txn(0, 20, [["r", 1, None]], [["r", 1, 50]])
           + _txn(5, 15, [["w", 1, 50]], process=1))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is True


def test_duplicate_writes_reported():
    ops = (_txn(0, 1, [["w", 1, 7]])
           + _txn(2, 3, [["w", 1, 7]], process=1))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert r["duplicate-writes"][0]["key"] == 1


def test_vacuous_unknown():
    ops = _txn(0, 1, [["w", 1, 5]], type="info")
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] == "unknown"


def test_rw_register_tpu_e2e():
    from maelstrom_tpu import core

    res = core.run(dict(store_root="/tmp/maelstrom-tpu-test-store",
                        seed=7, rate=15.0, time_limit=3.0,
                        journal_rows=False, workload="txn-rw-register",
                        node="tpu:txn-rw-register", node_count=3))
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["ok-count"] > 5


def test_duplicate_write_with_failed_writer_is_not_g1a():
    # generator-contract break: key 1 value 7 written by BOTH a
    # definitely-failed txn and an ok txn — a later read of 7 must be
    # reported as duplicate-writes (contract violation), not mislabeled
    # as an aborted read
    ops = (_txn(0, 1, [["w", 1, 7]], type="fail")
           + _txn(2, 3, [["w", 1, 7]])
           + _txn(4, 5, [["r", 1, None]], [["r", 1, 7]]))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    dups = r["duplicate-writes"]
    assert any(d.get("also-failed-writer") and d["key"] == 1
               and d["value"] == 7 for d in dups), r
    assert "G1a" not in r, r


def test_failed_write_alone_is_not_duplicate():
    # the same failed write WITHOUT an ok twin stays a plain G1a when
    # read, and raises no duplicate-writes
    ops = (_txn(0, 1, [["w", 1, 7]], type="fail")
           + _txn(2, 3, [["r", 1, None]], [["r", 1, 7]]))
    r = RWRegisterChecker().check({}, _h(ops), {})
    assert r["valid"] is False
    assert "duplicate-writes" not in r, r
    assert r["G1a"][0]["value"] == 7
