"""Fault soup across every batched workload program: partitions, 2-5%
message loss, and nonzero latency together, end to end through the
interactive runner, graded by each workload's stock checker. The point
is breadth — every program's protocol machinery (retries, re-offers,
election barriers, ownership routing) exercised under the same storm
its tutorial chapter claims it survives.

Two storms per program: constant latency, and EXPONENTIAL latency —
randomized delays reorder messages (including header-vs-payload within
a protocol, the mode that exposed the torn-AE bug), so every program
faces out-of-order delivery plus loss plus partitions. Constant
latency can never reorder; the second storm is the one that can."""

import pytest

from maelstrom_tpu import core


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'

CONFIGS = [
    ("broadcast", "tpu:broadcast", {"topology": "grid"}),
    ("g-set", "tpu:g-set", {}),
    ("pn-counter", "tpu:pn-counter", {}),
    ("g-counter", "tpu:g-counter", {}),
    ("lin-kv", "tpu:lin-kv", {}),
    ("lin-mutex", "tpu:lin-kv", {}),
    ("unique-ids", "tpu:unique-ids", {}),
    ("kafka", "tpu:kafka", {}),
    ("txn-list-append", "tpu:txn-list-append", {}),
    ("txn-rw-register", "tpu:txn-rw-register", {}),
]

STORMS = [
    ("constant", 11, {"mean": 5, "dist": "constant"}, 0.03),
    ("reordering", 23, {"mean": 3, "dist": "exponential"}, 0.02),
]


@pytest.mark.parametrize("storm,seed,latency,p_loss", STORMS,
                         ids=[s[0] for s in STORMS])
@pytest.mark.parametrize("workload,node,extra",
                         CONFIGS, ids=[c[0] for c in CONFIGS])
def test_fault_soup(workload, node, extra, storm, seed, latency, p_loss):
    res = core.run(dict(
        store_root="/tmp/maelstrom-tpu-test-store", seed=seed,
        workload=workload, node=node, node_count=5,
        rate=15.0, time_limit=4.0, journal_rows=False,
        latency=latency, p_loss=p_loss,
        nemesis={"partition"}, nemesis_interval=2.0, **extra))
    assert res["valid"] is True, {
        k: v for k, v in res.items()
        if isinstance(v, dict) and v.get("valid") not in (True, None)}
