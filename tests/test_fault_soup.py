"""Fault soup across every batched workload program: partitions, 2-5%
message loss, and nonzero latency together, end to end through the
interactive runner, graded by each workload's stock checker. The point
is breadth — every program's protocol machinery (retries, re-offers,
election barriers, ownership routing) exercised under the same storm
its tutorial chapter claims it survives.

Two storms per program: constant latency, and EXPONENTIAL latency —
randomized delays reorder messages (including header-vs-payload within
a protocol, the mode that exposed the torn-AE bug), so every program
faces out-of-order delivery plus loss plus partitions. Constant
latency can never reorder; the second storm is the one that can."""

import pytest

from maelstrom_tpu import core


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'

CONFIGS = [
    ("broadcast", "tpu:broadcast", {"topology": "grid"}),
    ("g-set", "tpu:g-set", {}),
    ("pn-counter", "tpu:pn-counter", {}),
    ("g-counter", "tpu:g-counter", {}),
    ("lin-kv", "tpu:lin-kv", {}),
    ("lin-mutex", "tpu:lin-kv", {}),
    ("unique-ids", "tpu:unique-ids", {}),
    ("kafka", "tpu:kafka", {}),
    ("txn-list-append", "tpu:txn-list-append", {}),
    ("txn-rw-register", "tpu:txn-rw-register", {}),
]

STORMS = [
    ("constant", 11, {"mean": 5, "dist": "constant"}, 0.03),
    ("reordering", 23, {"mean": 3, "dist": "exponential"}, 0.02),
]


@pytest.mark.parametrize("storm,seed,latency,p_loss", STORMS,
                         ids=[s[0] for s in STORMS])
@pytest.mark.parametrize("workload,node,extra",
                         CONFIGS, ids=[c[0] for c in CONFIGS])
def test_fault_soup(workload, node, extra, storm, seed, latency, p_loss):
    res = core.run(dict(
        store_root="/tmp/maelstrom-tpu-test-store", seed=seed,
        workload=workload, node=node, node_count=5,
        rate=15.0, time_limit=4.0, journal_rows=False,
        latency=latency, p_loss=p_loss,
        nemesis={"partition"}, nemesis_interval=2.0, **extra))
    assert res["valid"] is True, {
        k: v for k, v in res.items()
        if isinstance(v, dict) and v.get("valid") not in (True, None)}


# The combined nemesis: every fault package at once — crash-kills with
# durable-store restarts, GC pauses, directional partitions, AND
# at-least-once duplication — on the consensus/ordering workloads. Raft
# (lin-kv) must stay linearizable through restarts-from-log; kafka's
# offsets must stay ordered through duplicate replication traffic.
COMBINED_CONFIGS = [
    ("lin-kv", "tpu:lin-kv", {}),
    ("lin-mutex", "tpu:lin-kv", {}),
    ("kafka", "tpu:kafka", {}),
]


@pytest.mark.parametrize("workload,node,extra", COMBINED_CONFIGS,
                         ids=[c[0] for c in COMBINED_CONFIGS])
def test_combined_fault_soup(workload, node, extra):
    # seed chosen so the op mix actually lands >= 1 ok CAS between
    # outage windows (the Stats checker's per-f rule; a CAS only
    # succeeds when its random from-guess matches, so dense storms plus
    # an unlucky seed can zero it out legitimately)
    res = core.run(dict(
        store_root="/tmp/maelstrom-tpu-test-store", seed=39,
        workload=workload, node=node, node_count=5,
        rate=15.0, time_limit=8.0, journal_rows=False, recovery_s=2.5,
        latency={"mean": 2, "dist": "constant"}, p_loss=0.02,
        nemesis={"kill", "pause", "partition", "duplicate"},
        nemesis_interval=1.5, **extra))
    assert res["valid"] is True, {
        k: v for k, v in res.items()
        if isinstance(v, dict) and v.get("valid") not in (True, None)}
    # availability recovers post-heal: oks follow the first kill-restart
    import json
    with open("/tmp/maelstrom-tpu-test-store/latest/history.jsonl") as f:
        hist = [json.loads(line) for line in f]
    restarts = [o["time"] for o in hist if o.get("f") == "stop-kill"
                and o["type"] == "info"]
    assert restarts
    assert any(o["type"] == "ok" and o.get("process") != "nemesis"
               and o["time"] > restarts[0] for o in hist)


# Eventually-consistent workloads graded POST-HEAL after a soup that
# includes kill and pause: the final generator heals everything, the
# runner drains to quiescence, and the checkers see a converged system.
EC_CONFIGS = [
    ("broadcast", "tpu:broadcast", {"topology": "grid"}),
    ("g-set", "tpu:g-set", {}),
    ("pn-counter", "tpu:pn-counter", {}),
]


@pytest.mark.parametrize("workload,node,extra", EC_CONFIGS,
                         ids=[c[0] for c in EC_CONFIGS])
def test_kill_pause_soup_converges_post_heal(workload, node, extra):
    res = core.run(dict(
        store_root="/tmp/maelstrom-tpu-test-store", seed=41,
        workload=workload, node=node, node_count=5,
        rate=15.0, time_limit=4.0, journal_rows=False, recovery_s=3,
        nemesis={"kill", "pause"}, nemesis_interval=1.0, **extra))
    assert res["valid"] is True, {
        k: v for k, v in res.items()
        if isinstance(v, dict) and v.get("valid") not in (True, None)}
