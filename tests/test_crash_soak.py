"""Kill/resume soak: byte-identical recovery under real SIGKILL.

The harness (maelstrom_tpu.crash_soak) runs the test as a subprocess,
SIGKILLs it after a checkpoint has landed, resumes with --resume, and
compares the completed run's history.jsonl and results.json against an
uninterrupted same-seed baseline.

Tier-1 keeps the cheap proofs: one SIGKILL+resume cycle and one
graceful SIGTERM (exit code EXIT_PREEMPTED, loadable final checkpoint).
The full randomized multi-kill soaks — including the sharded --mesh
path — carry the `soak` marker (opt in with MAELSTROM_SOAK=1).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import time

import pytest

from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import crash_soak

# Small, fast smoke config: partition nemesis only (the combined
# kill/pause/duplicate soup belongs to the soak-marked runs), tight
# checkpoint cadence so a kill always lands between checkpoints.
SMOKE_OPTS = {
    "-w": "lin-kv", "--node": "tpu:lin-kv", "--node-count": "3",
    "--rate": "10", "--time-limit": "4", "--seed": "11",
    "--nemesis": "partition", "--nemesis-interval": "1",
    "--checkpoint-every": "0.5",
}


@pytest.fixture(scope="module")
def smoke_baseline(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("crash-smoke-baseline"))
    return crash_soak.run_once(root, SMOKE_OPTS,
                               os.path.join(root, "baseline.log"))


@pytest.mark.slow
def test_single_sigkill_resume_bit_identical(smoke_baseline, tmp_path):
    """One SIGKILL after the first checkpoint, one resume,
    byte-identical history and verdicts (subprocess-signal path; the
    in-process preempt/resume pins stay tier-1)."""
    res = crash_soak.run_with_kills(str(tmp_path), SMOKE_OPTS, kills=1,
                                    rng=random.Random(5),
                                    kill_jitter_s=0.2)
    assert len(res["kills"]) == 1, res
    verdict = crash_soak.compare_runs(smoke_baseline, res["dir"])
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict


@pytest.mark.slow
def test_sigterm_graceful_preempt_then_resume(smoke_baseline, tmp_path):
    """Graceful preemption end to end, real signal + real process:
    SIGTERM mid-run exits EXIT_PREEMPTED with a loadable final
    checkpoint; a --resume relaunch completes and matches the
    uninterrupted baseline bit-for-bit. (Tier-1 pins the same path
    in-process and cheaply:
    test_checkpoint_resilience.py::test_preempt_writes_final_checkpoint.)"""
    store = str(tmp_path)
    log_path = os.path.join(store, "child.log")
    os.makedirs(store, exist_ok=True)
    with open(log_path, "ab") as lf:
        proc = subprocess.Popen(
            crash_soak.argv_for(store, SMOKE_OPTS),
            env=crash_soak.child_env(), stdout=lf,
            stderr=subprocess.STDOUT)
        # wait for the run dir and its first checkpoint (the runner is
        # live and its SIGTERM handler installed), then preempt
        deadline = time.time() + 300
        my_dir = None
        while proc.poll() is None and time.time() < deadline:
            dirs = crash_soak.run_dirs(store, SMOKE_OPTS["-w"])
            if dirs:
                my_dir = dirs[-1]
                if os.path.exists(os.path.join(my_dir,
                                               cp.CHECKPOINT_FILE)):
                    break
            time.sleep(0.02)
        assert proc.poll() is None, "run finished before it could be " \
            "preempted; grow --time-limit"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=300)
    assert rc == cp.EXIT_PREEMPTED, (rc, open(log_path).read()[-2000:])
    # the graceful path wrote a loadable final checkpoint
    final = cp.load(my_dir)
    assert final["r"] > 0
    # supervisor relaunch: resume to completion, compare to baseline
    with open(log_path, "ab") as lf:
        rc2 = subprocess.call(
            crash_soak.argv_for(store, SMOKE_OPTS, resume=my_dir),
            env=crash_soak.child_env(), stdout=lf,
            stderr=subprocess.STDOUT, timeout=600)
    assert rc2 == 0, open(log_path).read()[-2000:]
    done = crash_soak.run_dirs(store, SMOKE_OPTS["-w"])[-1]
    verdict = crash_soak.compare_runs(smoke_baseline, done)
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict


@pytest.mark.soak
def test_crash_soak_combined_nemesis(tmp_path):
    """≥5 randomized SIGKILL+resume cycles under the combined
    kill/pause/partition/duplicate nemesis: stitched history and
    checker verdicts bit-identical to the uninterrupted run, with the
    analysis pipeline active after every resume (lin-kv's register
    checker consumes it; a pipeline decline would still pass the
    verdict check, so test_resume_keeps_pipeline_overlap pins the
    coverage itself)."""
    import json

    verdict = crash_soak.soak(str(tmp_path), kills=5, rng_seed=1)
    assert verdict["kills"] >= 5, verdict
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict
    assert verdict["valid"][0] == verdict["valid"][1]
    # the final (resumed) launch kept the overlapped analysis pipeline:
    # it covered the whole stitched history, seeded with resumed rows
    res = json.load(open(os.path.join(verdict["soak_dir"],
                                      "results.json")))
    pipe = res["analysis-pipeline"]
    n_hist = sum(1 for line in open(
        os.path.join(verdict["soak_dir"], "history.jsonl")) if line.strip())
    assert pipe["rows"] == n_hist, pipe
    assert pipe.get("resumed-rows", 0) > 0, pipe
    assert "error" not in pipe, pipe


@pytest.mark.soak
@pytest.mark.multichip
def test_crash_soak_mesh(tmp_path):
    """The sharded path: same ≥5-kill soak under --mesh 1,2 (sharded
    save, `_reshard` restore), bit-identical to the uninterrupted
    sharded run."""
    verdict = crash_soak.soak(str(tmp_path), kills=5, rng_seed=2,
                              mesh="1,2")
    assert verdict["kills"] >= 5, verdict
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict
