"""The compiled scan-ahead fast path (sim.make_scan_fn + the runner's
_scan_bound) must be *observationally identical* to per-round dispatch:
same rounds executed, same PRNG stream, replies processed at the same
virtual round. max_scan=1 degenerates every scan to a single round (the
old per-round behavior), so running the same test at max_scan=1 and at
the default and comparing histories pins the equivalence."""

from __future__ import annotations

from maelstrom_tpu import core
from maelstrom_tpu.runner.tpu_runner import TpuRunner


from conftest import ops_projection as _ops

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def _run(tmp_path, **over):
    opts = {"workload": "pn-counter", "node": "tpu:pn-counter",
            "node_count": 5, "rate": 25.0, "time_limit": 2.0,
            "nemesis": {"partition"}, "nemesis_interval": 0.7,
            "recovery_s": 1.0, "seed": 13,
            "store_root": str(tmp_path)}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(tmp_path)
    return TpuRunner(test), test


def test_scan_path_matches_per_round_path(tmp_path):
    r1, _ = _run(tmp_path / "a", max_scan=1)
    h1 = r1.run()

    r2, t2 = _run(tmp_path / "b")
    h2 = r2.run()

    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)

    res = t2["workload_map"]["checker"].check(t2, h2, {})
    assert res["valid"], res


def test_scan_equivalence_under_worker_saturation(tmp_path):
    """rate >> concurrency keeps every worker busy, so the generator is
    polled fruitlessly many times per round on the per-round path and once
    per dispatch on the scan path; a mix() whose rng is consumed on
    fruitless polls would diverge here (regression: MixG rng neutrality)."""
    over = {"rate": 2000.0, "concurrency": 2, "time_limit": 1.0,
            "nemesis": set()}
    r1, _ = _run(tmp_path / "a", max_scan=1, **over)
    h1 = r1.run()
    r2, _ = _run(tmp_path / "b", **over)
    h2 = r2.run()
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)


def test_collect_replies_scan_matches_per_round(tmp_path):
    """lin-kv enables the collect-replies scan mode (no per-reply early
    exit); histories must still match per-round dispatch exactly,
    including completion times."""
    over = {"workload": "lin-kv", "node": "tpu:lin-kv", "rate": 20.0,
            "time_limit": 2.5}
    r1, _ = _run(tmp_path / "a", max_scan=1, **over)
    h1 = r1.run()
    r2, t2 = _run(tmp_path / "b", **over)
    assert r2.collect_replies is True
    h2 = r2.run()
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)
    res = t2["workload_map"]["checker"].check(t2, h2, {})
    assert res["valid"], res


def test_collect_replies_saturated_matches_per_round(tmp_path):
    """Worker starvation on a collect-enabled workload: every reply
    enables the next emission, so the runner must fall back to
    stop-on-reply (the starvation check in _stop_on_reply) and histories
    must still match per-round dispatch exactly."""
    over = {"workload": "echo", "node": "tpu:echo", "rate": 2000.0,
            "concurrency": 2, "time_limit": 1.0, "nemesis": set()}
    r1, _ = _run(tmp_path / "a", max_scan=1, **over)
    h1 = r1.run()
    r2, _ = _run(tmp_path / "b", **over)
    assert r2.collect_replies is True
    h2 = r2.run()
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)


def test_collect_replies_off_matches_too(tmp_path):
    """The collect_replies=False escape hatch is observationally
    identical as well."""
    over = {"workload": "lin-kv", "node": "tpu:lin-kv", "rate": 20.0,
            "time_limit": 2.0}
    r1, _ = _run(tmp_path / "a", collect_replies=False, **over)
    assert r1.collect_replies is False
    h1 = r1.run()
    r2, _ = _run(tmp_path / "b", **over)
    h2 = r2.run()
    assert _ops(h1) == _ops(h2)


def test_journaled_scan_matches_per_round_journal(tmp_path):
    """With a journal attached, the io-collecting scan must produce the
    same history AND the same journal events as per-round dispatch."""
    from maelstrom_tpu.net.journal import Journal

    def run_with_journal(path, **over):
        r, t = _run(path, **over)
        r.journal = Journal()
        h = r.run()
        return r, h

    r1, h1 = run_with_journal(tmp_path / "a", max_scan=1)
    r2, h2 = run_with_journal(tmp_path / "b")
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)

    from collections import Counter
    ev1 = Counter((e.type, e.id, e.time, e.src, e.dest)
                  for e in r1.journal.all_events())
    ev2 = Counter((e.type, e.id, e.time, e.src, e.dest)
                  for e in r2.journal.all_events())
    assert ev1 == ev2 and sum(ev1.values()) > 0
