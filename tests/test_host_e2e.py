"""End-to-end tests of the host compatibility path: real subprocess nodes
speaking stdio JSON, driven through the full stack (network, db, init
handshake, generator interpreter, history, checkers, store artifacts) —
the counterpart of the reference's `demo` self-test suite
(`core.clj:93-111`)."""

import os

import pytest

from maelstrom_tpu import core


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo", "python")


def run(tmp_path, **opts):
    opts.setdefault("store_root", str(tmp_path / "store"))
    opts.setdefault("node_count", 3)
    opts.setdefault("rate", 10)
    opts.setdefault("time_limit", 2)
    opts.setdefault("recovery_s", 0.5)
    return core.run(opts)


def test_echo_e2e(tmp_path):
    r = run(tmp_path, workload="echo", bin=os.path.join(DEMO, "echo.py"))
    assert r["valid"] is True, r.get("workload")
    assert r["stats"]["ok-count"] > 0
    assert r["net"]["all"]["send-count"] > 0
    # one request + one reply per op, clients only
    assert r["net"]["all"]["msgs-per-op"] == pytest.approx(2.0, abs=0.3)
    # store artifacts
    store_root = str(tmp_path / "store")
    latest = os.path.join(store_root, "latest")
    for f in ("history.jsonl", "results.json", "messages.svg",
              "timeline.html", "latency-raw.svg", "rate.svg"):
        assert os.path.exists(os.path.join(latest, f)), f
    assert os.path.exists(os.path.join(latest, "node-logs", "n0.log"))


def test_node_spawn_strips_accelerator_env(tmp_path, monkeypatch):
    """Spawned node binaries must not inherit accelerator-hookup env
    vars: this image's sitecustomize costs ~2s of backend registration
    per interpreter when they're set, which serializes >=5-node clusters
    past the init handshake on small hosts."""
    from maelstrom_tpu.net.host import HostNet
    from maelstrom_tpu.process import NodeProcess

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    probe = tmp_path / "envprobe.py"
    probe.write_text(
        "#!/usr/bin/env python3\n"
        "import json, os, sys\n"
        "for line in sys.stdin:\n"
        "    m = json.loads(line)\n"
        "    b = m['body']\n"
        "    keys = [k for k in os.environ\n"
        "            if k.startswith(('AXON_', 'PALLAS_AXON_'))]\n"
        "    print(json.dumps({'src': b['node_id'], 'dest': m['src'],\n"
        "        'body': {'type': 'init_ok', 'msg_id': 1,\n"
        "                 'in_reply_to': b['msg_id'],\n"
        "                 'axon_keys': keys}}), flush=True)\n")
    probe.chmod(0o755)

    net = HostNet(latency={"mean": 0})
    h = NodeProcess("n0", str(probe), [], net,
                    log_file=str(tmp_path / "n0.log"))
    try:
        net.add_node("c0")
        net.send({"src": "c0", "dest": "n0",
                  "body": {"type": "init", "msg_id": 1, "node_id": "n0",
                           "node_ids": ["n0"]}})
        msg = net.recv("c0", timeout_ms=10_000)
        assert msg is not None
        assert msg.body["axon_keys"] == []
    finally:
        h.stop()


def test_c_echo_node_e2e(tmp_path):
    """The protocol boundary is language-agnostic: a compiled C node
    (demo/c/echo.c, no JSON library) passes the echo workload."""
    import shutil
    import subprocess

    cc = shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    bin_path = str(tmp_path / "echo")
    subprocess.run([cc, "-O2", "-Wall", "-Wextra", "-std=c99",
                    "-o", bin_path,
                    os.path.join(REPO, "demo", "c", "echo.c")],
                   check=True, capture_output=True)
    res = run(tmp_path, workload="echo", bin=bin_path,
              node_count=3, rate=10.0)
    assert res["valid"] is True
    assert res["workload"]["valid"] is True


def test_broadcast_e2e(tmp_path):
    r = run(tmp_path, workload="broadcast",
            bin=os.path.join(DEMO, "broadcast.py"), topology="grid")
    assert r["valid"] is True, r.get("workload")
    w = r["workload"]
    assert w["stable-count"] > 0 and w["lost-count"] == 0


def test_g_set_e2e(tmp_path):
    r = run(tmp_path, workload="g-set", bin=os.path.join(DEMO, "g_set.py"),
            time_limit=3, recovery_s=2.5)
    assert r["valid"] is True, r.get("workload")


def test_pn_counter_e2e(tmp_path):
    r = run(tmp_path, workload="pn-counter",
            bin=os.path.join(DEMO, "pn_counter.py"), time_limit=3,
            recovery_s=2.5)
    assert r["valid"] is True, r.get("workload")


def test_crashed_node_fails_test(tmp_path):
    crasher = tmp_path / "crasher.py"
    crasher.write_text("#!/usr/bin/env python3\nimport sys; sys.exit(2)\n")
    crasher.chmod(0o755)
    with pytest.raises(Exception):
        run(tmp_path, workload="echo", bin=str(crasher), time_limit=1)


def test_lin_kv_proxy_e2e(tmp_path):
    r = run(tmp_path, workload="lin-kv",
            bin=os.path.join(DEMO, "lin_kv_proxy.py"), time_limit=3,
            concurrency=6)
    assert r["valid"] is True, r.get("workload")


def test_raft_demo_e2e(tmp_path):
    """The userland Python Raft demo passes the linearizability checker
    (requires the op-spreading free-list rotation: a single always-first
    worker would only ever talk to one node)."""
    r = run(tmp_path, workload="lin-kv",
            bin=os.path.join(DEMO, "raft.py"), time_limit=8,
            concurrency=6, rate=8)
    assert r["valid"] is True, r.get("workload")
    ok = sum(v["ok-count"] for v in r["stats"]["by-f"].values())
    assert ok > 5


def test_raft_tutorial_stages(tmp_path):
    """The staged Raft tutorial demos (doc/tutorial/06-raft.md) hold
    their advertised properties at the cheap end: stage 1 is valid at
    one node (a dict is trivially linearizable) and invalid at five
    independent dicts — the chapter's opening measurement."""
    r = run(tmp_path, workload="lin-kv",
            bin=os.path.join(DEMO, "raft_1_kv.py"), time_limit=4,
            node_count=1, rate=10)
    assert r["valid"] is True, r.get("workload")
    r = run(tmp_path, workload="lin-kv",
            bin=os.path.join(DEMO, "raft_1_kv.py"), time_limit=4,
            node_count=5, rate=10, concurrency=6)
    assert r["valid"] is False
    assert r["workload"]["failures"], r.get("workload")


def test_raft_tutorial_stage2_elects(tmp_path, monkeypatch):
    """Stage 2 (election only) elects a leader on a quiet 5-node cluster
    and serves clients through it. The election timeout is widened for
    the oversubscribed 1-core CI host: at the demo default (0.6 s) a
    scheduler hiccup longer than the timeout triggers election churn —
    which is the chapter's teaching point, but not this test's."""
    import glob
    monkeypatch.setenv("RAFT_ELECTION_S", "2.0")
    r = run(tmp_path, workload="lin-kv",
            bin=os.path.join(DEMO, "raft_2_election.py"), time_limit=8,
            node_count=5, rate=5, concurrency=6)
    leaders = 0
    for f in glob.glob(str(tmp_path / "store" / "lin-kv" / "*" /
                           "node-logs" / "*.log")):
        with open(f) as fh:
            leaders += fh.read().count("became leader")
    assert leaders >= 1
    ok = sum(v["ok-count"] for v in r["stats"]["by-f"].values())
    assert ok > 0, r["stats"]


def test_c_broadcast_node_e2e_with_partitions(tmp_path):
    """The non-trivial second-language node: the compiled C broadcast
    (gossip + retry-until-ack, demo/c/broadcast.c, written against
    doc/protocol.md + doc/workloads.md alone) passes the set-full
    checker under partitions — retransmission carries values across the
    heal, like the tutorial's Python demo."""
    import shutil
    import subprocess

    cc = shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    bin_path = str(tmp_path / "broadcast")
    subprocess.run([cc, "-O2", "-Wall", "-Wextra", "-std=c99",
                    "-o", bin_path,
                    os.path.join(REPO, "demo", "c", "broadcast.c")],
                   check=True, capture_output=True)
    res = run(tmp_path, workload="broadcast", bin=bin_path,
              node_count=5, topology="grid", rate=10.0, time_limit=6,
              nemesis={"partition"}, nemesis_interval=2, recovery_s=3)
    assert res["valid"] is True, res.get("workload")
    w = res["workload"]
    assert w["lost-count"] == 0
    assert w["stable-count"] > 0


def test_perl_broadcast_node_e2e_with_partitions(tmp_path):
    """The third-language node: the Perl broadcast (gossip +
    retry-until-ack on demo/perl/MaelstromNode.pm, written against
    doc/protocol.md alone) passes the set-full checker under partitions
    — proving the any-language-over-stdio contract a third time
    (reference ships Ruby/Python/Clojure node libraries)."""
    import shutil

    if shutil.which("perl") is None:
        pytest.skip("no perl")
    res = run(tmp_path, workload="broadcast",
              bin=os.path.join(REPO, "demo", "perl", "broadcast.pl"),
              node_count=5, topology="grid", rate=10.0, time_limit=6,
              nemesis={"partition"}, nemesis_interval=2, recovery_s=3)
    assert res["valid"] is True, res.get("workload")
    w = res["workload"]
    assert w["lost-count"] == 0
    assert w["stable-count"] > 0
