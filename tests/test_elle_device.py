"""Device-resident Elle (checkers/elle_device.py, doc/perf.md
"device-resident grading"): the jitted edge constructor must be
set-equal to both host builds on every history shape, the on-device
cycle screen must never call a cyclic graph acyclic (the seeded-cycle
fixtures below all survive the screen and reach Tarjan), and verdicts
— valid/anomaly sets, rendered cycles — must be bit-equal to the host
path: plain analyze, through the overlapped pipeline's stream
observer, end to end on the TPU runner (plain, --mesh 1,2, and under
the combined nemesis soup)."""

import os

import pytest

from maelstrom_tpu.checkers import elle_device as ed
from maelstrom_tpu.checkers.elle import (ElleListAppendChecker,
                                         _edges_python,
                                         _edges_vectorized,
                                         _fail_appends, _txn_ops,
                                         analyze, analyze_txns)
from maelstrom_tpu.checkers.pipeline import AnalysisPipeline
from maelstrom_tpu.history import History, Op, coerce_history
from maelstrom_tpu.testing.histories import random_append_history

STORE = "/tmp/maelstrom-tpu-test-store"


def _screen(h):
    """(report, anomalies) for a device-on analyze."""
    h = coerce_history(h)
    rep = {}
    anoms = analyze_txns(_txn_ops(h), _fail_appends(h), device="on",
                         report=rep)
    return rep, anoms


def _txn_pair(h, micro_in, micro_out, t0, t1, typ="ok", proc=0):
    h.append({"type": "invoke", "f": "txn", "value": micro_in,
              "process": proc, "time": t0})
    h.append({"type": typ, "f": "txn",
              "value": micro_out if typ == "ok" else micro_in,
              "process": proc, "time": t1})


# --- edge-set equality ------------------------------------------------------

@pytest.mark.parametrize("seed", range(6))
def test_device_edges_match_both_hosts(seed):
    h = random_append_history(seed, corrupt=0.15 if seed % 2 else 0.0)
    txns = _txn_ops(h)
    # rebuild longest/appender the way analyze does
    rep = {}
    a_dev = analyze_txns(txns, _fail_appends(h), device="on",
                         report=rep)
    a_vec = analyze_txns(txns, _fail_appends(h), device="off")
    a_py = analyze_txns(txns, _fail_appends(h),
                        edges_impl=_edges_python)
    assert a_dev == a_vec == a_py


def test_device_edge_set_equals_vectorized_directly():
    """The raw edge arrays (not just the verdict) are set-equal to
    both host builds — the third implementation pinned against the
    oracle pair."""
    h = random_append_history(3, n_txn=200)
    txns = _txn_ops(h)
    # build longest/appender exactly as analyze's host passes do
    from maelstrom_tpu.checkers.elle import _hk, _hv
    appender, longest = {}, {}
    for t in txns:
        for f, k, v in t["micro"]:
            if f == "append":
                appender[(_hk(k), _hv(v))] = t["id"]
    for t in txns:
        if not t["ok"]:
            continue
        for f, k, v in t["micro"]:
            if f == "r" and isinstance(v, list):
                kk = _hk(k)
                vv = [_hv(x) for x in v]
                if len(vv) > len(longest.get(kk, [])):
                    longest[kk] = vv
    es = ed.edges_device(txns, longest, appender)
    assert es == _edges_vectorized(txns, longest, appender)
    assert es == _edges_python(txns, longest, appender)


# --- screen soundness: seeded cycles must survive the screen ----------------

def test_screen_never_acquits_g0():
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 2]],
              [["append", 1, 1], ["append", 2, 2]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [1, 2]]], 12, 13)
    rep, anoms = _screen(h)
    assert rep["screen"]["data"] == "undecided", rep
    assert rep["screen"]["realtime"] == "undecided", rep
    assert "G0" in anoms
    assert anoms == analyze(h, device="off")


def test_screen_never_acquits_g1c():
    h = []
    _txn_pair(h, [["append", 1, 1], ["r", 2, None]],
              [["append", 1, 1], ["r", 2, [1]]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 12, 13)
    rep, anoms = _screen(h)
    assert rep["screen"]["data"] == "undecided", rep
    assert "G1c" in anoms
    assert anoms == analyze(h, device="off")


def test_screen_never_acquits_g_single():
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 1]],
              [["append", 1, 1], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1]], ["r", 2, []]], 1, 11, proc=1)
    _txn_pair(h, [["r", 2, None]], [["r", 2, [1]]], 12, 13)
    rep, anoms = _screen(h)
    assert rep["screen"]["data"] == "undecided", rep
    assert "G-single" in anoms
    assert anoms == analyze(h, device="off")


def test_screen_never_acquits_g_nonadjacent():
    h = []
    _txn_pair(h, [["r", "a", None], ["append", "d", 2]],
              [["r", "a", []], ["append", "d", 2]], 0, 10, proc=0)
    _txn_pair(h, [["append", "a", 1], ["append", "b", 1]],
              [["append", "a", 1], ["append", "b", 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", "c", None], ["append", "b", 2]],
              [["r", "c", []], ["append", "b", 2]], 2, 12, proc=2)
    _txn_pair(h, [["append", "c", 1], ["append", "d", 1]],
              [["append", "c", 1], ["append", "d", 1]], 3, 13, proc=3)
    _txn_pair(h, [["r", "a", None], ["r", "b", None],
                  ["r", "c", None], ["r", "d", None]],
              [["r", "a", [1]], ["r", "b", [1, 2]],
               ["r", "c", [1]], ["r", "d", [1, 2]]], 4, 14, proc=4)
    rep, anoms = _screen(h)
    assert rep["screen"]["data"] == "undecided", rep
    assert "G-nonadjacent" in anoms
    assert anoms == analyze(h, device="off")


def test_screen_never_acquits_realtime_cycle():
    """Data graph acyclic, but a read misses a write that returned
    before the reader invoked: the realtime stage must stay undecided
    (the combined graph is cyclic) while the data stage may certify."""
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 1, proc=0)
    _txn_pair(h, [["r", 1, None]], [["r", 1, []]], 10, 11, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 20, 21, proc=2)
    rep, anoms = _screen(h)
    assert rep["screen"]["data"] == "acyclic", rep
    assert rep["screen"]["realtime"] == "undecided", rep
    assert "G-single-realtime" in anoms
    assert anoms == analyze(h, device="off")


def test_screen_certifies_valid_histories():
    """Clean concurrent histories certify end to end (data + realtime)
    — the >= 90% decided-fraction class the bench records — and the
    certificate skips Tarjan without changing the (empty) verdict."""
    decided = 0
    for seed in range(8):
        h = random_append_history(seed, n_txn=120)
        rep, anoms = _screen(h)
        ok = rep["screen"]["realtime"] == "acyclic"
        decided += ok
        assert anoms == analyze(h, device="off")
    assert decided >= 7, decided


# --- PR 3 regression shapes through the device path -------------------------

def test_device_empty_version_table():
    """Reads-only histories build an empty version table; the device
    gather must not index it (the PR 3 vectorized-gather crash)."""
    h = random_append_history(9, empty_reads=True)
    rep, anoms = _screen(h)
    assert anoms == analyze(h, device="off") \
        == analyze(h, edges_impl=_edges_python)


def test_device_list_subclass_reads_keep_their_edges():
    """Review regression: the columnar read filter must match the host
    builders' `isinstance(v, list)` — an exact-type check would drop a
    list-subclass read's wr/rw constraints from the screen, which could
    then certify a graph whose true edge set is cyclic."""
    class ObservedList(list):
        pass

    # the G-single cycle, but every read value is a list SUBCLASS
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 1]],
              [["append", 1, 1], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, ObservedList([1])],
               ["r", 2, ObservedList([])]], 1, 11, proc=1)
    _txn_pair(h, [["r", 2, None]],
              [["r", 2, ObservedList([1])]], 12, 13)
    rep, anoms = _screen(h)
    # the subclass reads' edges reached the device: screen undecided,
    # Tarjan classifies, verdict equals the host path
    assert rep["screen"]["data"] == "undecided", rep
    assert "G-single" in anoms
    assert anoms == analyze(h, device="off")


def test_device_no_reads_at_all():
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 1, proc=0)
    _txn_pair(h, [["append", 1, 2]], [["append", 1, 2]], 2, 3, proc=0)
    rep, anoms = _screen(h)
    assert anoms == analyze(h, device="off")


def test_device_empty_history():
    rep, anoms = _screen(History())
    assert anoms == analyze(History(), device="off") == {}


# --- the overlapped pipeline's stream observer ------------------------------

def _check_pair(h, device="on"):
    """(served result, post-hoc result) for the same history: once
    through a pipeline-fed stream observer (odd segment boundaries, so
    pairs complete out of invoke order), once post-hoc."""
    test = {"device_checker": device}
    c = ElleListAppendChecker(device=device)
    ob = c.make_stream_observer(test)
    assert ob is not None
    pipe = AnalysisPipeline(observers={"elle": ob})
    step = 37
    for lo in range(0, len(h), step):
        pipe.feed(h, lo, min(lo + step, len(h)))
    pipe.finish()
    served = c.check({"analysis": pipe, "device_checker": device}, h)
    posthoc = c.check({}, h, {"device_checker": device})
    return served, posthoc


@pytest.mark.parametrize("seed", [0, 4, 7])
def test_observer_serves_bit_equal_verdicts(seed):
    h = random_append_history(seed,
                              corrupt=0.15 if seed == 4 else 0.0)
    served, posthoc = _check_pair(h)
    stripped = {k: v for k, v in served.items()
                if k not in ("windows", "checker-lag")}
    assert stripped == posthoc, (stripped, posthoc)
    assert served["checker-lag"]["windows"] > 1
    # per-window early-warning screens ran (device on)
    assert any("screen" in w.get("verdict", {})
               for w in served["windows"])


def test_observer_flushes_open_invokes():
    """A still-open txn invoke at pipeline finish is an indeterminate
    txn whose appends enter the version tables — the observer must see
    it (`observe_open`) or served verdicts diverge from post-hoc (the
    observed open append would grade phantom-element)."""
    h = random_append_history(2, n_txn=60)
    # open (never-completed) txn appending to a fresh key...
    h.append(Op(type="invoke", f="txn", value=[["append", "zz", 1]],
                process=17, time=10 ** 9))
    # ...whose append a later committed read observes
    h.append(Op(type="invoke", f="txn", value=[["r", "zz", None]],
                process=18, time=10 ** 9 + 1))
    h.append(Op(type="ok", f="txn", value=[["r", "zz", [1]]],
                process=18, time=10 ** 9 + 2))
    posthoc_anoms = analyze(h, device="off")
    assert "phantom-element" not in posthoc_anoms
    served, posthoc = _check_pair(h)
    stripped = {k: v for k, v in served.items()
                if k not in ("windows", "checker-lag")}
    assert stripped == posthoc
    assert "phantom-element" not in stripped["anomaly-types"]


# --- end to end on the TPU runner -------------------------------------------

def _wl(res):
    return {k: v for k, v in res["workload"].items()
            if k not in ("device", "windows", "checker-lag")}


def _run(tag, **kw):
    from maelstrom_tpu import core
    root = os.path.join(STORE, f"elle-device-{tag}")
    opts = dict(store_root=root, seed=11, workload="txn-list-append",
                node="tpu:txn-list-append", node_count=5, rate=25,
                time_limit=2.0, audit=False)
    opts.update(kw)
    return core.run(opts)


def test_e2e_device_vs_host_bit_equal():
    r_dev = _run("on", device_checker="on")
    r_host = _run("off", device_checker="off", no_overlap=True)
    assert r_dev["valid"] is True and r_host["valid"] is True
    assert _wl(r_dev) == _wl(r_host)
    # the device actually engaged, certified, and booked its wall time
    assert r_dev["workload"]["device"]["screen"]["realtime"] \
        == "acyclic"
    assert r_dev["net"]["checker-device-calls"] >= 1
    assert r_dev["net"]["checker-device-s"] > 0
    # overlapped run: the observer fed the device path windowed
    assert r_dev["workload"]["checker-lag"]["windows"] >= 1
    assert r_dev["analysis-pipeline"]["rows"] > 0


@pytest.mark.multichip
def test_e2e_device_vs_host_mesh():
    r_dev = _run("mesh-on", device_checker="on", mesh="1,2")
    r_host = _run("mesh-off", device_checker="off", mesh="1,2",
                  no_overlap=True)
    assert r_dev["valid"] is True and r_host["valid"] is True
    assert _wl(r_dev) == _wl(r_host)


@pytest.mark.slow
def test_e2e_device_vs_host_nemesis_soup():
    """Under the combined four-package soup this workload's verdict may
    legitimately be invalid (the txn node sheds uncommitted state on
    kill — same reason test_fault_soup runs it partition-only); the
    device-path invariant is that the verdict — anomaly sets and
    rendered cycles included — is BIT-EQUAL to the host path."""
    kw = dict(nemesis={"kill", "pause", "partition", "duplicate"},
              nemesis_interval=0.7, time_limit=4.0, timeout_ms=1500)
    r_dev = _run("soup-on", device_checker="on", **kw)
    r_host = _run("soup-off", device_checker="off", no_overlap=True,
                  **kw)
    assert _wl(r_dev) == _wl(r_host)
    assert r_dev["valid"] == r_host["valid"]


@pytest.mark.slow
def test_e2e_device_vs_host_partition_soup_valid():
    kw = dict(nemesis={"partition"}, nemesis_interval=2.0,
              time_limit=4.0, rate=15.0, seed=23)
    r_dev = _run("part-on", device_checker="on", **kw)
    r_host = _run("part-off", device_checker="off", no_overlap=True,
                  **kw)
    assert _wl(r_dev) == _wl(r_host)
    assert r_dev["valid"] is True


def test_auto_mode_thresholds():
    assert ed.resolve("off", 10 ** 9) is False
    assert ed.resolve("on", 0) is True
    assert ed.resolve("auto", ed.AUTO_MIN_TXNS - 1) is False
    assert ed.resolve(None, ed.AUTO_MIN_TXNS) is True
