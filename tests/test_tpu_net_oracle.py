"""Property tests: the TPU flight-pool network vs a pure-Python oracle.

SURVEY.md section 4 calls for exactly this: the batched device network is
validated against a tiny queue model implementing the documented
semantics (constant latency L => a message sent in round r is delivered
in round r + max(1, L) — deadline = now + latency with a one-round
causal floor, reference `net.clj:201-204`; per-node inboxes take the
earliest-due messages first, capacity losers stay pooled; partitions
consume cross-component messages; nothing is ever silently dropped
while the pool has room).
Randomized schedules come from hypothesis; failures shrink to minimal
message schedules."""

from __future__ import annotations

from collections import defaultdict

import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    # minimal installs: the vendored fallback backend (same surface, no
    # shrinking) keeps the property suite running where hypothesis isn't
    # baked in; importorskip still guards truly bare environments
    minihyp = pytest.importorskip(
        "maelstrom_tpu.testing.minihyp",
        reason="property tests need hypothesis or the vendored fallback")
    given, settings, st = (minihyp.given, minihyp.settings,
                           minihyp.strategies)

import jax
import numpy as np

from maelstrom_tpu.net import tpu as T
from test_tpu_net import mk


def drive(cfg, schedule, rounds, seed=0):
    """Runs the device network over `schedule` = {round: [(src, dest, a)]}.
    Returns per-round delivered sets: [{(dest, a), ...} per round]."""
    net = T.make_net(cfg)
    key = jax.random.PRNGKey(seed)
    delivered = []
    for r in range(rounds):
        sends = schedule.get(r, [])
        if sends:
            key, k = jax.random.split(key)
            net, _ = T.send(cfg, net,
                            mk(cfg, [(s, d, 1, a) for s, d, a in sends]), k)
        net, inbox, _cm = T.deliver(cfg, net)
        ib = jax.device_get(inbox)
        got = set()
        for n in range(cfg.n_nodes):
            for slot in range(cfg.inbox_cap):
                if ib.valid[n, slot]:
                    got.add((n, int(ib.a[n, slot])))
        delivered.append(got)
        net = T.advance(net)
    return delivered, jax.device_get(net)


def oracle(cfg, schedule, rounds, lat):
    """The documented semantics in ~20 lines of Python."""
    in_flight = []                      # (due_round, dest, a)
    delivered = []
    for r in range(rounds):
        for s, d, a in schedule.get(r, []):
            in_flight.append((r + max(1, lat), d, a))
        got = set()
        by_dest = defaultdict(list)
        for m in in_flight:
            if m[0] <= r:
                by_dest[m[1]].append(m)
        for d, msgs in by_dest.items():
            msgs.sort(key=lambda m: m[0])           # earliest-due first
            for m in msgs[:cfg.inbox_cap]:
                got.add((d, m[2]))
                in_flight.remove(m)
        delivered.append(got)
    return delivered, in_flight


msg = st.tuples(st.integers(0, 3), st.integers(0, 3), st.integers(0, 999))
schedules = st.dictionaries(st.integers(0, 5),
                            st.lists(msg, min_size=1, max_size=6),
                            max_size=5)


@settings(max_examples=30, deadline=None)
@given(schedule=schedules, lat=st.integers(0, 3),
       inbox_cap=st.integers(2, 4))
def test_flight_pool_matches_oracle(schedule, lat, inbox_cap):
    # distinct payloads so set comparison is exact under capacity pressure
    uniq = {}
    for r, sends in schedule.items():
        uniq[r] = [(s, d, 1000 * r + i) for i, (s, d, _a) in enumerate(sends)]
    schedule = uniq
    rounds = 6 + 1 + lat + sum(len(v) for v in schedule.values())

    cfg = T.NetConfig(n_nodes=4, n_clients=0, pool_cap=64,
                      inbox_cap=inbox_cap, client_cap=0,
                      latency_mean_rounds=float(lat),
                      latency_dist="constant")
    got, net = drive(cfg, schedule, rounds)
    want, leftovers = oracle(cfg, schedule, rounds, lat)

    total_sent = sum(len(v) for v in schedule.values())
    assert not leftovers, "oracle run must drain for a fair comparison"
    assert got == want
    st_ = T.stats_dict(net)
    assert st_["sent_all"] == total_sent
    assert st_["recv_all"] == total_sent
    assert st_["dropped_overflow"] == 0
    assert not net.pool.valid.any()


@settings(max_examples=15, deadline=None)
@given(schedule=schedules)
def test_partition_consumes_cross_component_messages(schedule):
    """With nodes {0,1} | {2,3} partitioned, exactly the cross-component
    due messages are consumed and counted; same-side traffic flows."""
    uniq = {}
    for r, sends in schedule.items():
        uniq[r] = [(s, d, 1000 * r + i) for i, (s, d, _a) in enumerate(sends)]
    schedule = uniq
    rounds = 8 + sum(len(v) for v in schedule.values())
    cfg = T.NetConfig(n_nodes=4, n_clients=0, pool_cap=64, inbox_cap=4,
                      client_cap=0)
    net = T.make_net(cfg)
    net = T.partition_components(net, [0, 0, 1, 1])
    key = jax.random.PRNGKey(1)
    delivered = set()
    for r in range(rounds):
        sends = schedule.get(r, [])
        if sends:
            key, k = jax.random.split(key)
            net, _ = T.send(cfg, net,
                            mk(cfg, [(s, d, 1, a) for s, d, a in sends]), k)
        net, inbox, _cm = T.deliver(cfg, net)
        ib = jax.device_get(inbox)
        for n in range(cfg.n_nodes):
            for slot in range(cfg.inbox_cap):
                if ib.valid[n, slot]:
                    delivered.add((n, int(ib.a[n, slot])))
        net = T.advance(net)

    same, cross = set(), 0
    comp = [0, 0, 1, 1]
    for r, sends in schedule.items():
        for s, d, a in sends:
            if comp[s] == comp[d]:
                same.add((d, a))
            else:
                cross += 1
    assert delivered == same
    st_ = T.stats_dict(jax.device_get(net))
    assert st_["dropped_partition"] == cross
    assert not np.asarray(net.pool.valid).any()
