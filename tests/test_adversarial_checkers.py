"""Adversarial checker corpus: for every Elle-lite anomaly class and the
WGL register checker's edge cases, pin BOTH that the anomaly fires on a
minimal bad history AND that it does not false-fire on the nearest legal
neighbor of that history. This is the cross-validation discipline the
reference outsources to Elle/Knossos's own suites
(`workload/txn_list_append.clj:112-124`)."""

import pytest

from maelstrom_tpu.checkers.elle import ElleListAppendChecker, analyze
from maelstrom_tpu.checkers.linearizable import check_register_history

INF = float("inf")


def op(f, value, inv, ret, ok=True):
    return {"f": f, "value": value, "inv": inv, "ret": ret, "ok": ok}


def _txn_pair(h, micro_in, micro_out, t0, t1, typ="ok", proc=0):
    h.append({"type": "invoke", "f": "txn", "value": micro_in,
              "process": proc, "time": t0})
    h.append({"type": typ, "f": "txn",
              "value": micro_out if typ == "ok" else micro_in,
              "process": proc, "time": t1})


def _check(h, models=("strict-serializable",)):
    return ElleListAppendChecker(list(models)).check({}, h)


# --- G0: pure write cycle ---

def test_g0_fires():
    # key 1 order says T0 < T1; key 2 order says T1 < T0: ww cycle
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 2]],
              [["append", 1, 1], ["append", 2, 2]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [1, 2]]], 12, 13)
    r = _check(h, ["read-uncommitted"])
    assert r["valid"] is False and "G0" in r["anomalies"], r


def test_g0_near_miss_consistent_orders():
    # same structure, but both keys agree on the order: no cycle
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 1]],
              [["append", 1, 1], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 2]],
              [["append", 1, 2], ["append", 2, 2]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [1, 2]]], 12, 13)
    assert _check(h)["valid"] is True


def test_g0_near_miss_same_txn_multi_append():
    # both versions of both keys written by ONE txn: succession inside a
    # transaction is not a ww edge, so no cycle can form
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2],
                  ["append", 2, 2], ["append", 2, 1]],
              [["append", 1, 1], ["append", 1, 2],
               ["append", 2, 2], ["append", 2, 1]], 0, 1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [2, 1]]], 2, 3)
    assert _check(h)["valid"] is True


# --- G1a: aborted read / near-miss: indeterminate read ---

def test_g1a_near_miss_info_txn_observed():
    # an *indeterminate* (info) append being observed is legal — the txn
    # may well have committed; only a definite fail makes it G1a
    h = []
    _txn_pair(h, [["append", 1, 9]], None, 0, 1, typ="info")
    _txn_pair(h, [["r", 1, None]], [["r", 1, [9]]], 2, 3)
    r = _check(h)
    assert r["valid"] is True, r


# --- G1b: intermediate read / near-miss: final-state read ---

def test_g1b_fires():
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2]],
              [["append", 1, 1], ["append", 1, 2]], 0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 2, 3)
    r = _check(h, ["read-committed"])
    assert r["valid"] is False and "G1b" in r["anomalies"], r


def test_g1b_near_miss_reads_final_state():
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2]],
              [["append", 1, 1], ["append", 1, 2]], 0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 2, 3)
    assert _check(h)["valid"] is True


# --- G1c: ww/wr cycle (no rw) / near-miss: chain without closure ---

def test_g1c_fires():
    # T0 appends 1:1 and reads key 2 seeing T1's write (wr: T1->T0);
    # T1 appends 2:1 after observing... make T0 -[ww]-> T1 via key 1:
    # T1 also appends 1:2. Cycle: T0 -[ww key1]-> T1 -[wr key2]-> T0.
    h = []
    _txn_pair(h, [["append", 1, 1], ["r", 2, None]],
              [["append", 1, 1], ["r", 2, [1]]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 12, 13)
    r = _check(h, ["read-committed"])
    assert r["valid"] is False
    assert "G1c" in r["anomalies"], r


def test_g1c_near_miss_open_chain():
    # same edges minus the closing wr: T0 -[ww]-> T1 only
    h = []
    _txn_pair(h, [["append", 1, 1]],
              [["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [1]]], 12, 13)
    assert _check(h)["valid"] is True


# --- G2: multiple rw edges (write skew) / near-miss G-single labeling ---

def test_g2_write_skew_fires_and_is_not_g_single():
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 2, 1]],
              [["r", 1, []], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 2, None], ["append", 1, 1]],
              [["r", 2, []], ["append", 1, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1]], ["r", 2, [1]]], 12, 13)
    r = _check(h, ["serializable"])
    assert r["valid"] is False and "G2" in r["anomalies"], r
    assert "G-single" not in (r["anomalies"] or {})


def test_g_single_fires_with_one_rw():
    # T0 -[wr]-> T1 -[rw]-> T0: exactly one anti-dependency
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 1]],
              [["append", 1, 1], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1]], ["r", 2, []]], 1, 11, proc=1)
    _txn_pair(h, [["r", 2, None]], [["r", 2, [1]]], 12, 13)
    r = _check(h, ["serializable"])
    assert r["valid"] is False and "G-single" in r["anomalies"], r


def test_g2_near_miss_reads_in_serial_order():
    # the same two txns, but each observes the other's write: serial
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 2, 1]],
              [["r", 1, []], ["append", 2, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 2, None], ["append", 1, 1]],
              [["r", 2, [1]], ["append", 1, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1]], ["r", 2, [1]]], 12, 13)
    assert _check(h)["valid"] is True


# --- phantom / duplicates ---

def test_phantom_element_fires():
    h = []
    _txn_pair(h, [["r", 1, None]], [["r", 1, [42]]], 0, 1)
    r = _check(h)
    assert r["valid"] is False and "phantom-element" in r["anomalies"]


def test_duplicate_appends_fire():
    h = []
    _txn_pair(h, [["append", 1, 7]], [["append", 1, 7]], 0, 1, proc=0)
    _txn_pair(h, [["append", 1, 7]], [["append", 1, 7]], 2, 3, proc=1)
    r = _check(h)
    assert r["valid"] is False and "duplicate-appends" in r["anomalies"]


# --- realtime: long concurrent windows must NOT create rt edges ---

def test_realtime_near_miss_concurrent_window():
    # T1 misses T0's append, but their windows overlap: serializable
    # order T1 < T0 is legal even under strict serializability
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None]], [["r", 1, []]], 5, 15, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 16, 17, proc=0)
    r = _check(h, ["strict-serializable"])
    assert r["valid"] is True, r


def test_realtime_fires_only_past_the_gap():
    # shrink the overlap to nothing and the same reads become illegal
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 4, proc=0)
    _txn_pair(h, [["r", 1, None]], [["r", 1, []]], 5, 15, proc=1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1]]], 16, 17, proc=0)
    r = _check(h, ["strict-serializable"])
    assert r["valid"] is False, r
    assert any(k.endswith("-realtime") for k in r["anomalies"]), r


def test_cycle_witness_matches_classification():
    # every reported cycle carries a witness whose edge kinds justify
    # the label (the explain() contract)
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 2, 2]],
              [["append", 1, 1], ["append", 2, 2]], 0, 10, proc=0)
    _txn_pair(h, [["append", 1, 2], ["append", 2, 1]],
              [["append", 1, 2], ["append", 2, 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", 1, None], ["r", 2, None]],
              [["r", 1, [1, 2]], ["r", 2, [1, 2]]], 12, 13)
    anoms = analyze(h)
    for kind, items in anoms.items():
        for item in items:
            if isinstance(item, dict) and "cycle" in item:
                assert "-[" in item["cycle"] and "txn-ops" in item


# --- WGL register checker edge cases ---

def test_wgl_long_concurrent_window_is_not_a_free_pass():
    # a slow write spans many fast reads. Old-then-new is legal (the
    # write linearizes between them, inside its window)...
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, 100),          # the long window
           op("read", 1, 10, 11),
           op("read", 2, 20, 21),
           op("read", 2, 30, 31)]
    assert check_register_history(ops)["valid"] is True
    # ...but new-then-old is NOT: once any read observed the write,
    # later reads can't flip back, however wide the window still is
    ops = [op("write", 1, 0, 1),
           op("write", 2, 2, 100),
           op("read", 2, 10, 11),
           op("read", 1, 20, 21)]
    assert check_register_history(ops)["valid"] is False


def test_wgl_indeterminate_cas_chain():
    # two info cas ops form a chain 1->2->3; a later read of 3 is
    # explainable only if BOTH took effect — the checker must find it
    ops = [op("write", 1, 0, 1),
           op("cas", [1, 2], 2, INF, ok=False),
           op("cas", [2, 3], 3, INF, ok=False),
           op("read", 3, 10, 11)]
    assert check_register_history(ops)["valid"] is True
    # but a read of 3 while the 2->3 cas could never have applied
    # (its precondition 2 was never writable) is illegal
    ops = [op("write", 1, 0, 1),
           op("cas", [2, 3], 3, INF, ok=False),
           op("read", 3, 10, 11)]
    assert check_register_history(ops)["valid"] is False


def test_wgl_indeterminate_cas_applies_at_most_once():
    # an info cas may apply 0 or 1 times — never twice. 1->2 then a
    # read of 1 then a read of 2 would need it to apply after un-applying
    ops = [op("write", 1, 0, 1),
           op("cas", [1, 2], 2, INF, ok=False),
           op("read", 2, 10, 11),
           op("write", 1, 12, 13),
           op("read", 2, 14, 15)]
    # second read of 2 needs a SECOND application: illegal
    assert check_register_history(ops)["valid"] is False


def test_wgl_definite_fail_excluded_at_checker_level():
    # a definite :fail cas must NOT be applied — the per-key checker
    # drops it before the search (client.clj:214-233 semantics), so a
    # later read of the would-be value is a real violation
    from maelstrom_tpu.checkers.linearizable import \
        LinearizableRegisterChecker

    def hop(typ, f, value, proc, t):
        return {"type": typ, "f": f, "value": value, "process": proc,
                "time": t}

    h = [hop("invoke", "write", [0, 1], 0, 0), hop("ok", "write", [0, 1], 0, 1),
         hop("invoke", "cas", [0, [1, 2]], 0, 2),
         hop("fail", "cas", [0, [1, 2]], 0, 3),
         hop("invoke", "read", [0, None], 0, 4),
         hop("ok", "read", [0, 1], 0, 5)]
    assert LinearizableRegisterChecker().check({}, h)["valid"] is True
    # had the failed cas's effect leaked, this read would be "fine";
    # the checker must reject it because the cas definitely didn't run
    h[-1] = hop("ok", "read", [0, 2], 0, 5)
    assert LinearizableRegisterChecker().check({}, h)["valid"] is False


def test_parity_known_shift_quantiles():
    """parity_analysis.quantiles_with_shift: shifting `known` later by
    d ms reduces each element's stable latency by exactly d (down to 0)
    when the last-absent read stays fixed — the mechanism behind the
    known-offset parity analysis."""
    from maelstrom_tpu.history import History, Op
    from maelstrom_tpu.parity_analysis import quantiles_with_shift

    ms = 1e6
    ops = []
    # element 0: acked at t=0ms, reads miss it at 10ms and 20ms, then
    # present from 30ms on -> latency 20ms
    ops += [Op(type="invoke", f="broadcast", value=0, process=0, time=0),
            Op(type="ok", f="broadcast", value=0, process=0, time=0)]
    for i, (t, els) in enumerate([(10, []), (20, []), (30, [0]),
                                  (40, [0])]):
        ops += [Op(type="invoke", f="read", value=None, process=10 + i,
                   time=int(t * ms)),
                Op(type="ok", f="read", value=els, process=10 + i,
                   time=int(t * ms))]
    h = History(sorted(ops, key=lambda o: (o.time, o.type != "invoke")))
    assert quantiles_with_shift(h, 0)["max"] == 20.0
    assert quantiles_with_shift(h, 5)["max"] == 15.0
    # shifting past the last absent read: the 20ms miss no longer counts
    # (reads must begin strictly after known)
    assert quantiles_with_shift(h, 25)["max"] == 0.0


# --- lost-update: same loaded version, both append ---

def test_lost_update_fires_unobserved():
    # both txns load key 1 at version [] and append; NO later read ever
    # observes the colliding appends, so the dependency graph is empty —
    # this is the case only the load-collision rule can see
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 1, 1]],
              [["r", 1, []], ["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 2]],
              [["r", 1, []], ["append", 1, 2]], 1, 11, proc=1)
    a = analyze(h)
    assert "lost-update" in a, a
    assert sorted(a["lost-update"][0]["txns"]) == [0, 1]
    r = _check(h, ["serializable"])
    assert r["valid"] is False and "lost-update" in r["anomalies"]


def test_lost_update_not_illegal_at_read_committed():
    # Adya P4 is only proscribed from cursor stability up; the same
    # history passes a read-committed-only check
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 1, 1]],
              [["r", 1, []], ["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 2]],
              [["r", 1, []], ["append", 1, 2]], 1, 11, proc=1)
    assert _check(h, ["read-committed"])["valid"] is True


def test_lost_update_near_miss_sequential_loads():
    # the second txn loaded the FIRST txn's append: a legal sequential
    # read-modify-append chain
    h = []
    _txn_pair(h, [["r", 1, None], ["append", 1, 1]],
              [["r", 1, []], ["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 2]],
              [["r", 1, [1]], ["append", 1, 2]], 11, 20, proc=1)
    a = analyze(h)
    assert "lost-update" not in a, a
    assert _check(h)["valid"] is True


def test_lost_update_near_miss_blind_append():
    # a blind append (no read in the txn) is not a load-save collision
    h = []
    _txn_pair(h, [["append", 1, 1]], [["append", 1, 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 2]],
              [["r", 1, []], ["append", 1, 2]], 1, 11, proc=1)
    a = analyze(h)
    assert "lost-update" not in a, a


def test_lost_update_fires_via_own_append_stripped_read():
    # T0's read comes AFTER its own append; stripping its own tail
    # recovers the loaded version [] — colliding with T1's load
    h = []
    _txn_pair(h, [["append", 1, 7], ["r", 1, None]],
              [["append", 1, 7], ["r", 1, [7]]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 8]],
              [["r", 1, []], ["append", 1, 8]], 1, 11, proc=1)
    a = analyze(h)
    assert "lost-update" in a, a


# --- cyclic-version-order ---

def test_cyclic_version_order_fires():
    # one txn appends 1 then 2; readers observe [1,2] AND [2,1]: the
    # union of asserted adjacencies is the cycle 1<2<1 — no version
    # order exists at all (stronger than a prefix fork)
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2]],
              [["append", 1, 1], ["append", 1, 2]], 0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 2, 3)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [2, 1]]], 4, 5)
    a = analyze(h)
    assert "cyclic-version-order" in a, a
    r = _check(h, ["read-uncommitted"])
    assert r["valid"] is False and "cyclic-version-order" in r["anomalies"]


def test_cyclic_version_order_near_miss_fork():
    # forked reads [1,2] vs [1,3]: incompatible-order, but a version
    # order per branch still exists — NOT cyclic
    h = []
    _txn_pair(h, [["append", 1, 1], ["append", 1, 2],
                  ["append", 1, 3]],
              [["append", 1, 1], ["append", 1, 2], ["append", 1, 3]],
              0, 1)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 2]]], 2, 3)
    _txn_pair(h, [["r", 1, None]], [["r", 1, [1, 3]]], 4, 5)
    a = analyze(h)
    assert "cyclic-version-order" not in a, a
    assert "incompatible-order" in a


# --- G-nonadjacent: >=2 rw edges, none adjacent ---

def test_g_nonadjacent_fires():
    # T0 -rw-> T1 -ww-> T2 -rw-> T3 -ww-> T0: two anti-dependencies
    # separated by write dependencies on both sides — the cycle shape
    # that additionally violates snapshot isolation
    h = []
    # T0: reads a=[], appends d<-2 (ww tail from T3)
    _txn_pair(h, [["r", "a", None], ["append", "d", 2]],
              [["r", "a", []], ["append", "d", 2]], 0, 10, proc=0)
    # T1: appends a<-1 (making T0's read an rw edge), appends b<-1
    _txn_pair(h, [["append", "a", 1], ["append", "b", 1]],
              [["append", "a", 1], ["append", "b", 1]], 1, 11, proc=1)
    # T2: reads c=[], appends b<-2 (ww from T1)
    _txn_pair(h, [["r", "c", None], ["append", "b", 2]],
              [["r", "c", []], ["append", "b", 2]], 2, 12, proc=2)
    # T3: appends c<-1 (T2's rw target), appends d<-1 (ww into T0)
    _txn_pair(h, [["append", "c", 1], ["append", "d", 1]],
              [["append", "c", 1], ["append", "d", 1]], 3, 13, proc=3)
    # observer pins every version order
    _txn_pair(h, [["r", "a", None], ["r", "b", None],
                  ["r", "c", None], ["r", "d", None]],
              [["r", "a", [1]], ["r", "b", [1, 2]],
               ["r", "c", [1]], ["r", "d", [1, 2]]], 4, 14, proc=4)
    a = analyze(h)
    assert "G-nonadjacent" in a, a
    assert "G2" not in a, a
    r = _check(h, ["serializable"])
    assert r["valid"] is False and "G-nonadjacent" in r["anomalies"]


def test_g_nonadjacent_near_miss_write_skew_is_g2():
    # classic write skew: T0 -rw-> T1 -rw-> T0 — the two rw edges ARE
    # adjacent (cyclically), so this stays G2, not G-nonadjacent
    h = []
    _txn_pair(h, [["r", "a", None], ["append", "b", 1]],
              [["r", "a", []], ["append", "b", 1]], 0, 10, proc=0)
    _txn_pair(h, [["r", "b", None], ["append", "a", 1]],
              [["r", "b", []], ["append", "a", 1]], 1, 11, proc=1)
    _txn_pair(h, [["r", "a", None], ["r", "b", None]],
              [["r", "a", [1]], ["r", "b", [1]]], 12, 13, proc=2)
    a = analyze(h)
    assert "G2" in a, a
    assert "G-nonadjacent" not in a, a


# --- Knossos-style model generality: mutex / set / queue ---

def _mop(f, value, inv, ret, ok=True):
    return {"f": f, "value": value, "inv": inv, "ret": ret, "ok": ok}


def test_mutex_double_acquire_fires():
    from maelstrom_tpu.checkers.linearizable import (MutexModel,
                                                     check_history)
    # two non-overlapping acquires with no release between them
    h = [_mop("acquire", None, 0, 1), _mop("acquire", None, 2, 3)]
    r = check_history(h, MutexModel())
    assert r["valid"] is False
    assert r["stuck-op"]["f"] == "acquire"


def test_mutex_handoff_legal():
    from maelstrom_tpu.checkers.linearizable import (MutexModel,
                                                     check_history)
    h = [_mop("acquire", None, 0, 1), _mop("release", None, 2, 3),
         _mop("acquire", None, 4, 5), _mop("release", None, 6, 7)]
    assert check_history(h, MutexModel())["valid"] is True


def test_mutex_indeterminate_release_allows_reacquire():
    from maelstrom_tpu.checkers.linearizable import (MutexModel,
                                                     check_history)
    # the release never completed — it MAY have happened, so a later
    # acquire stays legal; but a second acquire after that is not
    h = [_mop("acquire", None, 0, 1),
         _mop("release", None, 2, INF, ok=False),
         _mop("acquire", None, 3, 4)]
    assert check_history(h, MutexModel())["valid"] is True
    h.append(_mop("acquire", None, 5, 6))
    assert check_history(h, MutexModel())["valid"] is False


def test_mutex_mixed_anonymous_and_named_raises():
    from maelstrom_tpu.checkers.linearizable import (MutexModel,
                                                     check_history)
    # an anonymous release against a NAMED holder's acquire is the
    # lock-stealing shape anonymous identity cannot check — the model
    # refuses to "verify" it instead of silently degrading (all-
    # anonymous histories remain the documented holder-blind mode)
    h = [_mop("acquire", "w0", 0, 1), _mop("release", None, 2, 3)]
    with pytest.raises(ValueError, match="anonymous"):
        check_history(h, MutexModel())


def test_mutex_named_foreign_release_fires():
    from maelstrom_tpu.checkers.linearizable import (MutexModel,
                                                     check_history)
    # holder-aware identity: w1 cannot release w0's lock
    h = [_mop("acquire", "w0", 0, 1), _mop("release", "w1", 2, 3)]
    assert check_history(h, MutexModel())["valid"] is False
    h2 = [_mop("acquire", "w0", 0, 1), _mop("release", "w0", 2, 3)]
    assert check_history(h2, MutexModel())["valid"] is True


def test_set_read_missing_add_fires():
    from maelstrom_tpu.checkers.linearizable import (SetModel,
                                                     check_history)
    # add 1 completed before the read began, yet the read saw {}
    h = [_mop("add", 1, 0, 1), _mop("read", [], 2, 3)]
    r = check_history(h, SetModel())
    assert r["valid"] is False
    # concurrent version is legal (the add may linearize after)
    h2 = [_mop("add", 1, 0, 5), _mop("read", [], 2, 3)]
    assert check_history(h2, SetModel())["valid"] is True


def test_queue_fifo_order_fires():
    from maelstrom_tpu.checkers.linearizable import (QueueModel,
                                                     check_history)
    # enqueue 1 then 2 (sequential), dequeue observes 2 first: not FIFO
    h = [_mop("enqueue", 1, 0, 1), _mop("enqueue", 2, 2, 3),
         _mop("dequeue", 2, 4, 5)]
    assert check_history(h, QueueModel())["valid"] is False
    # dequeuing 1 first is the legal history
    h2 = [_mop("enqueue", 1, 0, 1), _mop("enqueue", 2, 2, 3),
          _mop("dequeue", 1, 4, 5), _mop("dequeue", 2, 6, 7)]
    assert check_history(h2, QueueModel())["valid"] is True


def test_queue_concurrent_enqueues_either_order():
    from maelstrom_tpu.checkers.linearizable import (QueueModel,
                                                     check_history)
    # overlapping enqueues: both dequeue orders are linearizable
    h = [_mop("enqueue", 1, 0, 10), _mop("enqueue", 2, 1, 9),
         _mop("dequeue", 2, 11, 12), _mop("dequeue", 1, 13, 14)]
    assert check_history(h, QueueModel())["valid"] is True


# --- internal (list-append): own appends must be the read's suffix ---

def test_internal_fires():
    # the txn appended 5 to key 1, then its own read misses it
    h = []
    _txn_pair(h, [["append", 1, 5], ["r", 1, None]],
              [["append", 1, 5], ["r", 1, []]], 0, 1)
    a = analyze(h)
    assert "internal" in a, a
    r = _check(h, ["read-uncommitted"])
    assert r["valid"] is False and "internal" in r["anomalies"]


def test_internal_near_miss_own_suffix():
    # pre-state [3] plus the own append as suffix: consistent
    h = []
    _txn_pair(h, [["append", 1, 3]], [["append", 1, 3]], 0, 1)
    _txn_pair(h, [["append", 1, 5], ["r", 1, None]],
              [["append", 1, 5], ["r", 1, [3, 5]]], 2, 3)
    a = analyze(h)
    assert "internal" not in a, a


def test_fuzzy_read_fires_on_shifting_pre_state():
    # B's later read reveals a different pre-state than its first read:
    # Adya P2 (non-repeatable read) — legal at read-committed, fatal at
    # serializable
    h = []
    _txn_pair(h, [["append", 1, 3]], [["append", 1, 3]], 0, 10, proc=0)
    _txn_pair(h, [["r", 1, None], ["append", 1, 5], ["r", 1, None]],
              [["r", 1, []], ["append", 1, 5], ["r", 1, [3, 5]]],
              1, 11, proc=1)
    a = analyze(h)
    assert "fuzzy-read" in a, a
    assert "internal" not in a, a
    assert _check(h, ["read-committed"])["valid"] is True
    r = _check(h, ["serializable"])
    assert r["valid"] is False and "fuzzy-read" in r["anomalies"]


def test_internal_near_miss_stable_pre_state():
    # both reads reveal pre-state [3]: internally consistent
    h = []
    _txn_pair(h, [["append", 1, 3]], [["append", 1, 3]], 0, 1)
    _txn_pair(h, [["r", 1, None], ["append", 1, 5], ["r", 1, None]],
              [["r", 1, [3]], ["append", 1, 5], ["r", 1, [3, 5]]],
              2, 3)
    a = analyze(h)
    assert "internal" not in a, a
