"""Columnar client sessions (ISSUE 17, doc/perf.md "columnar client
sessions"): the two session backends — `CoroutineSessions` (the legacy
dict/list/set bookkeeping) and `ColumnarSessions` (one shared [F, S]
numpy table, refreshed by a single vectorized `encode_wave` pass) —
must be operation-for-operation interchangeable: same registration /
absorb / timeout-expiry / backoff-requeue / redirect-retry semantics,
same ORDERING of everything order-sensitive (expiry in insertion order,
due-requeues stable-sorted by due round), and the exact legacy
checkpoint-meta shapes, so a checkpoint written by one backend resumes
under the other and fingerprints don't move."""

from __future__ import annotations

import pytest

from maelstrom_tpu.runner.sessions import (ColumnarSessions,
                                           CoroutineSessions,
                                           make_sessions, resolve_mode,
                                           trunc_exp_bound)


def _both():
    return CoroutineSessions(), ColumnarSessions(1, 4).view(0)


OP = {"f": "read", "value": None}


# ---------------------------------------------------------------------------
# Pending-RPC columns: register / absorb / timeout transitions
# ---------------------------------------------------------------------------

def test_register_absorb_parity():
    for s in _both():
        s.register(100, 0, {**OP, "k": 0}, 2, 50)
        s.register(101, 1, {**OP, "k": 1}, 0, 60)
        assert len(s) == 2 and bool(s)
        assert s.min_deadline() == 50
        got = s.absorb_results([101, 999, 100])
        assert got[0] == (1, {**OP, "k": 1}, 0, 60)
        assert got[1] is None          # stale reply
        assert got[2] == (0, {**OP, "k": 0}, 2, 50)
        assert len(s) == 0 and not s
        assert s.min_deadline() is None


def test_take_expired_registration_order():
    # expiry completes in REGISTRATION order even when deadlines are
    # non-monotone — the dict-insertion order timeout completions have
    # always used (byte-identity depends on it)
    for s in _both():
        s.register(1, 0, {"k": "a"}, 0, 30)
        s.register(2, 1, {"k": "b"}, 1, 10)
        s.register(3, 2, {"k": "c"}, 2, 20)
        s.register(4, 3, {"k": "d"}, 3, 99)
        assert s.take_expired(5) == []
        assert s.take_expired(25) == [(1, {"k": "b"}, 1),
                                      (2, {"k": "c"}, 2)]
        assert len(s) == 2
        assert s.min_deadline() == 30
        assert s.take_expired(100) == [(0, {"k": "a"}, 0),
                                       (3, {"k": "d"}, 3)]
        assert not s


def test_columnar_capacity_growth():
    v = ColumnarSessions(1, 2, cap=2).view(0)
    for m in range(9):
        v.register(m, m % 2, {"m": m}, 0, 10 + m)
    assert len(v) == 9
    assert v.min_deadline() == 10
    got = v.absorb_results(list(range(9)))
    assert [e[1]["m"] for e in got] == list(range(9))


def test_single_mid_absorb_fast_path():
    # the continuous loop absorbs one mid per merged event
    v = ColumnarSessions(1, 4).view(0)
    v.register(7, 2, {"x": 1}, 1, 40)
    assert v.absorb_results([8]) == [None]
    assert v.absorb_results([7]) == [(2, {"x": 1}, 1, 40)]
    assert v.absorb_results([7]) == [None]


# ---------------------------------------------------------------------------
# Backoff-requeue columns
# ---------------------------------------------------------------------------

def test_requeue_due_order_stable():
    # due-retry merge order: stable sort by due round, append order
    # preserved within a round — `sorted(rows, key=due)` exactly
    for s in _both():
        s.requeue(20, 0, {"r": 0}, 1, 10, 0, 0, 0)
        s.requeue(10, 1, {"r": 1}, 2, 11, 0, 0, 0)
        s.requeue(10, 2, {"r": 2}, 0, 12, 0, 0, 0)
        s.requeue(30, 3, {"r": 3}, 1, 13, 0, 0, 0)
        assert s.has_requeue()
        assert s.requeue_min_due() == 10
        rows = s.take_due_requeues(20)
        assert [(rw[0], rw[1]["r"]) for rw in rows] == \
            [(1, 1), (2, 2), (0, 0)]
        assert rows[0] == (1, {"r": 1}, 2, 11, 0, 0, 0)
        assert s.has_requeue() and s.requeue_min_due() == 30
        assert s.take_due_requeues(29) == []
        assert s.take_due_requeues(30) == [(3, {"r": 3}, 1, 13, 0, 0, 0)]
        assert not s.has_requeue() and s.requeue_min_due() is None


def test_drain_requeues_clamps_and_keeps_append_order():
    # continuous mode: ALL rows drain in append order with due clamped
    # up to the window start
    for s in _both():
        s.requeue(50, 0, {"r": 0}, 1, 10, 0, 0, 0)
        s.requeue(5, 1, {"r": 1}, 2, 11, 0, 0, 0)
        rows = s.drain_requeues(20)
        assert rows == [(50, 0, {"r": 0}, 1, 10, 0, 0, 0),
                        (20, 1, {"r": 1}, 2, 11, 0, 0, 0)]
        assert not s.has_requeue()


# ---------------------------------------------------------------------------
# Redirect-retry chain columns
# ---------------------------------------------------------------------------

def test_retry_chain_transitions():
    for s in _both():
        assert s.attempt(2) == 0 and not s.retry_is_open(2)
        s.open_retry(2, 1)
        assert s.attempt(2) == 1 and s.retry_is_open(2)
        s.open_retry(2, 2)
        assert s.attempt(2) == 2
        assert not s.retry_is_open(3)
        s.close_retry(2)
        assert s.attempt(2) == 0 and not s.retry_is_open(2)
        # the nemesis completes through the same path with a string id
        s.close_retry("nemesis")
        assert not s.retry_is_open("nemesis")
        assert s.attempt("nemesis") == 0


def test_backoff_bound_shared_curve():
    assert trunc_exp_bound(50.0, 2000.0, 0) == 50.0
    assert trunc_exp_bound(50.0, 2000.0, 3) == 400.0
    assert trunc_exp_bound(50.0, 2000.0, 10) == 2000.0
    # the shift clamps: a pathological redirect chain cannot overflow
    assert trunc_exp_bound(50.0, 2000.0, 10 ** 6) == 2000.0
    assert trunc_exp_bound(4, 1 << 40, 20) == 4 * (1 << 16)


# ---------------------------------------------------------------------------
# Checkpoint meta: legacy shapes, cross-backend round-trip
# ---------------------------------------------------------------------------

def _populate(s):
    s.register(5, 0, {"k": "a"}, 1, 40)
    s.register(3, 1, {"k": "b"}, 2, 30)
    s.requeue(12, 2, {"r": 2}, 0, 9, 8, 7, 6)
    s.open_retry(2, 3)


def test_to_meta_legacy_shapes_identical():
    metas = []
    for s in _both():
        _populate(s)
        metas.append(s.to_meta())
    assert metas[0] == metas[1]
    m = metas[0]
    assert list(m["pending"]) == [5, 3]    # insertion order
    assert m["pending"][3] == (1, {"k": "b"}, 2, 30)
    assert m["requeue"]["rows"] == [(12, 2, {"r": 2}, 0, 9, 8, 7, 6)]
    assert m["requeue"]["attempt"] == {2: 3}
    assert m["requeue"]["open"] == [2]


@pytest.mark.parametrize("src,dst", [(0, 1), (1, 0), (0, 0), (1, 1)])
def test_meta_round_trip_cross_backend(src, dst):
    # a checkpoint written by either backend resumes under either:
    # the behavioral state (ordering included) survives the round trip
    pair = _both()
    _populate(pair[src])
    meta = pair[src].to_meta()
    d = pair[dst]
    d.register(99, 3, {"stale": True}, 0, 1)   # overwritten by load
    d.load_meta(meta["pending"], meta["requeue"])
    assert d.to_meta() == meta
    assert len(d) == 2 and d.min_deadline() == 30
    assert d.attempt(2) == 3 and d.retry_is_open(2)
    # expiry order replays the recorded insertion order
    assert d.take_expired(100) == [(0, {"k": "a"}, 1), (1, {"k": "b"}, 2)]


# ---------------------------------------------------------------------------
# The shared fleet table + mode resolution
# ---------------------------------------------------------------------------

def test_shared_table_shell_isolation_and_encode_wave():
    t = ColumnarSessions(3, 4)
    a, b = t.view(0), t.view(2)
    a.register(1, 0, {"s": 0}, 1, 25)
    b.register(1, 1, {"s": 2}, 0, 15)
    b.requeue(8, 2, {"r": 1}, 1, 0, 0, 0, 0)
    t.encode_wave()     # ONE vectorized pass refreshes every shell
    assert bool(t._cache_ok.all())
    assert a.min_deadline() == 25 and b.min_deadline() == 15
    assert t.view(1).min_deadline() is None
    assert not a.has_requeue() and b.requeue_min_due() == 8
    # same mid in two shells resolves per-shell
    assert a.absorb_results([1]) == [(0, {"s": 0}, 1, 25)]
    assert b.absorb_results([1]) == [(1, {"s": 2}, 0, 15)]
    # a mutation dirties only the touched rows; the per-shell refresh
    # fallback still answers correctly before the next wave pass
    assert b.min_deadline() is None


def test_resolve_mode_defaults_and_validation():
    assert resolve_mode({}) == "coroutine"
    assert resolve_mode({"fleet": 8}) == "columnar"
    assert resolve_mode({"fleet": 8, "sessions": "coroutine"}) \
        == "coroutine"
    assert resolve_mode({"sessions": "columnar"}) == "columnar"
    with pytest.raises(ValueError, match="sessions"):
        resolve_mode({"sessions": "hybrid"})
    assert isinstance(make_sessions({}, 4), CoroutineSessions)
    cols = make_sessions({"sessions": "columnar"}, 4)
    assert cols.table.F == 1 and cols.table.C == 4


# ---------------------------------------------------------------------------
# device-resident wave reduction (ISSUE 18, PR 17 follow-on)
# ---------------------------------------------------------------------------

def _fill_table(t):
    a, b, c = t.view(0), t.view(1), t.view(2)
    a.register(1, 0, {"s": 0}, 1, 25)
    a.register(2, 1, {"s": 0}, 2, 7)
    b.register(1, 1, {"s": 1}, 0, 15)
    b.requeue(8, 2, {"r": 1}, 1, 0, 0, 0, 0)
    b.requeue(3, 0, {"r": 2}, 0, 0, 0, 0, 0)
    c.requeue(40, 1, {"r": 3}, 2, 0, 0, 0, 0)
    return a, b, c


def test_encode_wave_device_parity():
    """The jitted device reduction and the numpy pass land
    bit-identical per-shell aggregates — including the int64
    empty-shell sentinel — so flipping `device_reduce` can never move
    a scan bound."""
    import numpy as np

    host = ColumnarSessions(3, 4, device_reduce=False)
    dev = ColumnarSessions(3, 4, device_reduce=True)
    _fill_table(host)
    _fill_table(dev)
    host.encode_wave()
    dev.encode_wave()
    assert host._min_dl.dtype == dev._min_dl.dtype == np.int64
    assert np.array_equal(host._min_dl, dev._min_dl)
    assert np.array_equal(host._min_due, dev._min_due)
    assert bool(dev._cache_ok.all())
    # and the view-level answers agree op-for-op
    for t in (host, dev):
        assert t.view(0).min_deadline() == 7
        assert t.view(1).min_deadline() == 15
        assert t.view(2).min_deadline() is None
        assert t.view(1).requeue_min_due() == 3
        assert t.view(2).requeue_min_due() == 40
    # mutations after the pass keep the caches in lockstep: absorb the
    # current min (raising bound -> dirty), then re-encode
    for t in (host, dev):
        assert t.view(0).absorb_results([2]) == [(1, {"s": 0}, 2, 7)]
        t.encode_wave()
    assert np.array_equal(host._min_dl, dev._min_dl)
    assert host.view(0).min_deadline() == dev.view(0).min_deadline() == 25


def test_device_reduce_resolution():
    """None = auto (on at F >= 64); MAELSTROM_SESSIONS_DEVICE forces
    either path; an explicit argument always wins."""
    import os
    from unittest import mock

    assert ColumnarSessions(2, 4).device_reduce is False
    assert ColumnarSessions(64, 4).device_reduce is True
    assert ColumnarSessions(64, 4, device_reduce=False).device_reduce \
        is False
    with mock.patch.dict(os.environ, {"MAELSTROM_SESSIONS_DEVICE": "1"}):
        assert ColumnarSessions(2, 4).device_reduce is True
    with mock.patch.dict(os.environ, {"MAELSTROM_SESSIONS_DEVICE": "0"}):
        assert ColumnarSessions(64, 4).device_reduce is False
        assert ColumnarSessions(64, 4, device_reduce=True).device_reduce \
            is True
