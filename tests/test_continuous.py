"""Continuous generator mode (doc/streams.md): ops injected at their
seeded offered-rate rounds INSIDE the compiled scan window, while
nemesis faults are live mid-window.

Pinned contracts:
  - same seed => byte-identical history (the whole open-world stream is
    deterministic), including under the combined five-package soup;
  - plain and --mesh runs are byte-identical (multichip);
  - the windowed incremental kafka verdict is bit-equal to the post-hoc
    whole-history checker, with the lag metric surfaced;
  - windows actually batch: one dispatch carries many offered-rate
    injections once replies take real latency;
  - checkpoint/resume carries the scheduled-but-not-injected rows.
"""

from __future__ import annotations

import json

import pytest

from maelstrom_tpu import core

STORE = "/tmp/maelstrom-tpu-test-store"

SOUP = {"kill", "pause", "partition", "duplicate", "weather"}


def _run(seed=29, **kw):
    opts = dict(store_root=STORE, seed=seed, workload="lin-kv",
                node="tpu:lin-kv", node_count=5, rate=10.0,
                time_limit=3.0, journal_rows=False, continuous=True,
                recovery_s=1.5, timeout_ms=1000, nemesis=set(SOUP),
                nemesis_interval=0.7)
    opts.update(kw)
    res = core.run(opts)
    with open(f"{STORE}/latest/history.jsonl") as f:
        return res, f.read()


@pytest.mark.slow
def test_continuous_soup_deterministic_and_valid():
    r1, h1 = _run()
    r2, h2 = _run()
    assert r1["valid"] is True and r2["valid"] is True
    assert h1 == h2                      # byte-identical histories
    hist = [json.loads(line) for line in h1.splitlines()]
    # the soup actually ran: every package started
    nem_fs = {o["f"] for o in hist if o.get("process") == "nemesis"
              and o["type"] == "info"}
    for f in SOUP:
        assert f"start-{f}" in nem_fs, nem_fs
    # open-world property: client ops were INVOKED strictly inside a
    # fault window (between a start op and its stop), not only at
    # boundaries
    starts = sorted(o["time"] for o in hist
                    if o.get("f", "").startswith("start-")
                    and o["type"] == "info")
    stops = sorted(o["time"] for o in hist
                   if o.get("f", "").startswith("stop-")
                   and o["type"] == "info")
    assert starts and stops
    in_window = [o for o in hist if o["type"] == "invoke"
                 and o.get("process") != "nemesis"
                 and any(s < o["time"] < e for s, e in
                         zip(starts, stops) if s < e)]
    assert in_window, "no client op arrived mid-fault"


@pytest.mark.slow
def test_continuous_windows_batch_many_ops_per_dispatch():
    """With real reply latency, one compiled window carries MANY
    offered-rate injections: drains stay far below the op count (the
    round-synchronous path pays >= 1 dispatch per op)."""
    res, h = _run(seed=3, workload="echo", node="tpu:echo",
                  nemesis=set(), rate=300.0, time_limit=1.0,
                  concurrency=64, latency={"mean": 10,
                                           "dist": "constant"},
                  timeout_ms=5000)
    assert res["valid"] is True
    ops = res["stats"]["count"]
    drains = res["net"]["drains"]
    assert ops > 100, ops
    assert drains < ops / 2, (drains, ops)


@pytest.mark.multichip
def test_continuous_mesh_bit_identical():
    """Same-seed continuous runs are byte-identical single-chip and
    sharded (--mesh 1,2) — sharding changes placement, never the
    stream. The acceptance configuration: streaming kafka under the
    full five-package soup (ISSUE 7)."""
    _r1, h1 = _kafka_stream(seed=17, time_limit=2.0)
    _r2, h2 = _kafka_stream(seed=17, time_limit=2.0, mesh="1,2")
    assert h1 == h2


def _kafka_stream(seed=7, **kw):
    opts = dict(store_root=STORE, seed=seed, workload="kafka",
                node="tpu:kafka", node_count=5, rate=20.0,
                time_limit=3.0, journal_rows=False, kafka_groups=2,
                continuous=True, recovery_s=1.5, timeout_ms=1000,
                nemesis=set(SOUP), nemesis_interval=0.7)
    opts.update(kw)
    res = core.run(opts)
    with open(f"{STORE}/latest/history.jsonl") as f:
        return res, f.read()


def test_continuous_kafka_windowed_verdict_equals_posthoc():
    """The acceptance pin (ISSUE 7): continuous kafka under the full
    soup — (a) byte-identical histories per seed, (b) the windowed
    incremental verdict bit-equal to the post-hoc whole-history
    checker, (c) the per-window lag metric surfaced and bounded."""
    r1, h1 = _kafka_stream()
    r2, h2 = _kafka_stream(no_overlap=True)   # post-hoc path
    assert h1 == h2
    w1 = dict(r1["workload"])
    w2 = dict(r2["workload"])
    windows = w1.pop("windows")
    lag = w1.pop("checker-lag")
    assert "windows" not in w2              # post-hoc has no windows
    assert w1 == w2                         # verdict bit-equal
    assert r1["valid"] is True
    assert w1["acked-sends"] > 0
    # rolling windows: every record carries a verdict + bounded lag
    assert len(windows) == lag["windows"] > 1
    assert all("verdict" in w for w in windows)
    assert all(w["verdict"]["ok"] for w in windows)
    max_scan_head = max(w["end-round"] for w in windows
                        if w["end-round"] is not None)
    assert 0 <= lag["max-lag-rounds"] <= max_scan_head
    # the analysis-pipeline block reports the same window accounting
    rep = r1["analysis-pipeline"]
    assert rep["windows"] == len(windows)


@pytest.mark.slow
def test_continuous_checkpoint_resume_identical():
    """A continuous run cut mid-stream and resumed from its checkpoint
    completes with the SAME ops as an uninterrupted run — the carry
    (ops drawn from the generator but not yet injected) rides the
    checkpoint."""
    from conftest import ops_projection as _ops

    from maelstrom_tpu import checkpoint as cp
    from maelstrom_tpu.runner.tpu_runner import TpuRunner

    def build(sub, **over):
        # the SAME cadence everywhere: continuous-mode op timing
        # depends on window boundaries and checkpoints are boundaries
        # (cadence is part of the continuous fingerprint —
        # doc/streams.md; the round-synchronous path stays neutral)
        opts = dict(workload="kafka", node="tpu:kafka", node_count=5,
                    rate=20.0, time_limit=3.0, kafka_groups=2,
                    continuous=True, journal_rows=False, seed=5,
                    recovery_s=1.0, timeout_ms=1000,
                    checkpoint_every=0.5,
                    store_root=f"{STORE}-cont/{sub}")
        opts.update(over)
        test = core.build_test(opts)
        test["store_dir"] = f"{STORE}-cont/{sub}"
        import os
        os.makedirs(test["store_dir"], exist_ok=True)
        return test

    hist_a = TpuRunner(build("a")).run()
    assert len(hist_a) > 20

    tb = build("b")
    tb["max_rounds"] = 1200
    TpuRunner(tb).run()

    tc = build("b")
    runner_c = TpuRunner(tc)
    resume = cp.load(f"{STORE}-cont/b")
    cp.check_fingerprint(resume, tc)
    hist_c = runner_c.run(resume=resume)
    assert _ops(hist_c) == _ops(hist_a)


def test_continuous_rejections():
    """Guard rails: --fleet composes with --continuous since ISSUE 12
    (covered by tests/test_fleet_continuous.py), so the one remaining
    rejection is per program — completions that read mutable
    end-of-stretch state cannot cross reply-bearing windows. It fires
    identically standalone and per fleet shell."""
    from maelstrom_tpu.runner.tpu_runner import TpuRunner

    # every stock program is continuous-capable today (state_reads_final
    # or reply payloads), so pin the guard itself: forcing the
    # per-reply dispatch mode (collect_replies False) puts any program
    # in the rejected class
    opts = dict(store_root=STORE, workload="broadcast",
                node="tpu:broadcast", node_count=4, continuous=True,
                time_limit=1.0, collect_replies=False)
    with pytest.raises(ValueError, match="continuous"):
        TpuRunner(core.build_test(dict(opts)))
    from maelstrom_tpu.runner.fleet_runner import FleetRunner
    with pytest.raises(ValueError, match="continuous"):
        FleetRunner(core.build_test({**opts, "fleet": 2}))
