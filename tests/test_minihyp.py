"""The vendored property-testing fallback (maelstrom_tpu.testing.minihyp).

The oracle suites run under real hypothesis when it's installed and
under minihyp otherwise; these tests pin the fallback's contract —
hypothesis-compatible surface, deterministic example schedules, failure
reporting with the generated inputs attached."""

from __future__ import annotations

import pytest

from maelstrom_tpu.testing import minihyp
from maelstrom_tpu.testing.minihyp import (MiniHypFailure, given, settings,
                                           strategies as st)


def test_examples_are_deterministic_across_runs():
    seen = []

    @settings(max_examples=10, deadline=None)
    @given(xs=st.lists(st.tuples(st.integers(0, 9), st.booleans()),
                       max_size=6),
           n=st.integers(-3, 3))
    def collect(xs, n):
        seen.append((tuple(xs), n))

    collect()
    first = list(seen)
    seen.clear()
    collect()
    assert seen == first
    assert len(first) == 10
    assert len(set(first)) > 1, "examples never varied"


def test_first_example_is_minimal():
    seen = []

    @settings(max_examples=3, deadline=None)
    @given(xs=st.lists(st.integers(5, 9), min_size=2, max_size=6),
           d=st.dictionaries(st.integers(0, 3), st.booleans(), max_size=4),
           b=st.booleans())
    def collect(xs, d, b):
        seen.append((xs, d, b))

    collect()
    assert seen[0] == ([5, 5], {}, False)


def test_bounds_respected():
    @settings(max_examples=50, deadline=None)
    @given(n=st.integers(2, 6),
           xs=st.lists(st.integers(0, 5), min_size=16, max_size=16))
    def check(n, xs):
        assert 2 <= n <= 6
        assert len(xs) == 16 and all(0 <= x <= 5 for x in xs)

    check()


def test_failure_reports_generated_inputs():
    @settings(max_examples=20, deadline=None)
    @given(n=st.integers(0, 100))
    def boom(n):
        assert n < 30

    with pytest.raises(MiniHypFailure, match="failed on example"):
        boom()


def test_wrapper_hides_strategy_params_from_pytest():
    """pytest must not mistake strategy names for fixtures: the wrapper
    takes no parameters."""
    import inspect

    @given(n=st.integers(0, 1))
    def t(n):
        pass

    assert inspect.signature(t).parameters == {}


def test_example_cap_env(monkeypatch):
    calls = []

    @settings(max_examples=40, deadline=None)
    @given(n=st.integers(0, 9))
    def collect(n):
        calls.append(n)

    monkeypatch.setenv("MAELSTROM_MINIHYP_MAX_EXAMPLES", "7")
    collect()
    assert len(calls) == 7


def test_sampled_from_contract():
    """`sampled_from`: draws come from the sequence, the minimal-first
    pass uses the FIRST element (hypothesis shrinks toward it), and an
    empty sequence is rejected up front."""
    seen = []

    @settings(max_examples=12, deadline=None)
    @given(x=st.sampled_from([0.25, 0.0, 0.1]))
    def collect(x):
        seen.append(x)

    collect()
    assert seen[0] == 0.25          # minimal example first
    assert set(seen) <= {0.25, 0.0, 0.1}
    assert len(set(seen)) > 1, "examples never varied"
    with pytest.raises(ValueError):
        st.sampled_from([])


def test_given_rejects_non_strategies():
    with pytest.raises(TypeError, match="non-strategies"):
        minihyp.given(x=42)
