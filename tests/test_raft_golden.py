"""Golden-state pin for the batched raft round.

The round-4 vectorization of `RaftProgram.edge_step` (unrolled one-hot
log writes -> batched gathers/scatters over a stacked [N, C, 3] log) was
proven bit-identical to the original unrolled implementation by this
exact scenario: 400 rounds, 32 clusters, randomized client read/write/
CAS traffic. The hash pins that behavior so future performance passes
can't silently change semantics.

The hash covers every node-state array (logs, kv, terms, roles, commit/
applied indices). It depends on jax's PRNG implementation (threefry,
fold_in) — stable for the pinned environment; if jax is upgraded and
only this test breaks, re-pin after checking the invariants asserted at
the bottom still hold.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from maelstrom_tpu.net import tpu as T
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.nodes.raft import T_CAS, T_READ, T_WRITE
from maelstrom_tpu.parallel import make_cluster_round_fn, make_cluster_sims

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'

GOLDEN = "e88bcde5428c5e33594854d9a60fc5f5456a5adeb793581cb5c6b7a3fae059d2"


def test_raft_round_golden_state():
    nodes = [f"n{i}" for i in range(5)]
    program = get_program("lin-kv", {"latency": {"mean": 0}}, nodes)
    cfg = T.NetConfig(n_nodes=5, n_clients=3, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    B = 32
    round_fn = make_cluster_round_fn(program, cfg)
    sims = make_cluster_sims(program, cfg, B, seed=7)
    rng = np.random.RandomState(42)
    for r in range(400):
        inj = T.Msgs.empty((B, 3))
        if r % 3 == 0 and r > 50:
            tp = rng.choice([T_READ, T_WRITE, T_CAS], size=B)
            dest = rng.randint(0, 5, size=B)
            a = rng.randint(0, 8, size=B)
            b = rng.randint(0, 5, size=B)
            c = rng.randint(0, 5, size=B)
            inj = inj.replace(
                valid=inj.valid.at[:, 0].set(True),
                src=inj.src.at[:, 0].set(5 + rng.randint(0, 3, size=B)),
                dest=inj.dest.at[:, 0].set(jnp.asarray(dest, jnp.int32)),
                type=inj.type.at[:, 0].set(jnp.asarray(tp, jnp.int32)),
                a=inj.a.at[:, 0].set(jnp.asarray(a, jnp.int32)),
                b=inj.b.at[:, 0].set(jnp.asarray(b, jnp.int32)),
                c=inj.c.at[:, 0].set(jnp.asarray(c, jnp.int32)),
                mid=inj.mid.at[:, 0].set(r * 10 + 1))
        sims, _cm, _io = round_fn(sims, inj)
    final = jax.device_get(sims.nodes)
    h = hashlib.sha256()
    for k in sorted(final):
        h.update(k.encode())
        h.update(np.ascontiguousarray(final[k]).tobytes())

    # semantic invariants first: if the hash breaks but these hold, the
    # change is a re-pin candidate rather than a correctness bug
    roles = np.asarray(final["role"])
    assert float(((roles == 2).sum(axis=1) == 1).mean()) == 1.0
    assert int((np.asarray(final["kv"]) > 0).sum()) > 0
    assert int(np.asarray(final["applied"]).max()) > 50
    assert (np.asarray(final["applied"]) <= np.asarray(final["commit"])).all()
    assert int(np.asarray(final["log_overflow"]).sum()) == 0

    assert h.hexdigest() == GOLDEN
