"""Coverage for the docs generator and the store web server."""

from __future__ import annotations

import json
import os
import socketserver
import threading
import urllib.request
from functools import partial

from maelstrom_tpu.doc_gen import write_docs
from maelstrom_tpu.serve import StoreHandler


def test_doc_generation(tmp_path):
    paths = write_docs(str(tmp_path))
    assert sorted(os.path.basename(p) for p in paths) == [
        "protocol.md", "workloads.md"]
    protocol = (tmp_path / "protocol.md").read_text()
    # the error table is rendered from the registry
    assert "timeout" in protocol and "precondition-failed" in protocol
    assert "| 22" in protocol
    workloads = (tmp_path / "workloads.md").read_text()
    for w in ("## Workload: Broadcast", "## Workload: G-counter",
              "## Workload: Lin-kv", "## Workload: Txn-list-append",
              "## Table of Contents"):
        assert w in workloads, w
    # RPC schemas include request/response types
    assert '"type": "echo_ok"' in workloads


def test_serve_renders_validity_badges(tmp_path):
    for name, valid in (("a", True), ("b", False), ("c", "unknown")):
        d = tmp_path / "lin-kv" / name
        d.mkdir(parents=True)
        (d / "results.json").write_text(json.dumps({"valid": valid}))

    handler = partial(StoreHandler, directory=str(tmp_path))
    httpd = socketserver.TCPServer(("127.0.0.1", 0), handler)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/lin-kv/") as resp:
            body = resp.read().decode()
        assert "[valid: True]" in body
        assert "[valid: False]" in body
        assert "[valid: unknown]" in body
        # green for valid, red for invalid, orange for unknown
        assert "#2ca02c" in body and "#d62728" in body and "#ff7f0e" in body
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_serve_root_run_index(tmp_path):
    # runs across two workloads, plus a latest symlink-alike dir name
    # that must be excluded; newest run sorts first
    for wl, ts, valid, count in (
            ("lin-kv", "20260101T000000", True, 40),
            ("broadcast", "20260201T000000", False, 7)):
        d = tmp_path / wl / ts
        d.mkdir(parents=True)
        (d / "results.json").write_text(json.dumps(
            {"valid": valid, "stats": {"count": count}}))
        (d / "history.jsonl").write_text("")
    (tmp_path / "lin-kv" / "latest").mkdir()

    handler = partial(StoreHandler, directory=str(tmp_path))
    httpd = socketserver.TCPServer(("127.0.0.1", 0), handler)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as resp:
            body = resp.read().decode()
        assert "runs (2)" in body, body
        # newest (broadcast) row renders before the older lin-kv row
        assert body.index("broadcast") < body.index("lin-kv")
        assert "history.jsonl" in body and ">results<" in body
        assert "#2ca02c" in body and "#d62728" in body
        assert "latest" not in body
    finally:
        httpd.shutdown()
        httpd.server_close()
