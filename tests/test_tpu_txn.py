"""End-to-end tests for transactional list-append: the host-path
datomic-style demo binary (CAS on a root register in the lin-kv service)
and the TPU-path raft-sequenced program, both graded strict-serializable
by the Elle-style checker."""

import pytest

from maelstrom_tpu import core


def test_txn_list_append_host_datomic_demo():
    res = core.run({"workload": "txn-list-append",
                    "bin": "demo/python/datomic_list_append.py",
                    "node_count": 2, "rate": 8.0, "time_limit": 3.0,
                    "seed": 4,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    assert res["stats"]["by-f"]["txn"]["ok-count"] > 5


def test_txn_list_append_tpu_raft():
    res = core.run({"workload": "txn-list-append",
                    "node": "tpu:txn-list-append",
                    "node_count": 5, "rate": 10.0, "time_limit": 3.0,
                    "seed": 9, "journal_rows": False,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    assert res["stats"]["by-f"]["txn"]["ok-count"] > 5


def test_txn_list_append_tpu_raft_partition():
    res = core.run({"workload": "txn-list-append",
                    "node": "tpu:txn-list-append",
                    "node_count": 5, "rate": 10.0, "time_limit": 4.0,
                    "nemesis": {"partition"}, "nemesis_interval": 1.0,
                    "seed": 9, "journal_rows": False,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
