"""End-to-end tests for transactional list-append: the host-path
datomic-style demo binary (CAS on a root register in the lin-kv service)
and the TPU-path raft-sequenced program, both graded strict-serializable
by the Elle-style checker."""

import pytest

from maelstrom_tpu import core


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def test_txn_list_append_host_datomic_demo():
    res = core.run({"workload": "txn-list-append",
                    "bin": "demo/python/datomic_list_append.py",
                    "node_count": 2, "rate": 8.0, "time_limit": 3.0,
                    "seed": 4,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    assert res["stats"]["by-f"]["txn"]["ok-count"] > 5


def test_txn_list_append_tpu_raft():
    res = core.run({"workload": "txn-list-append",
                    "node": "tpu:txn-list-append",
                    "node_count": 5, "rate": 10.0, "time_limit": 3.0,
                    "seed": 9, "journal_rows": False,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
    assert res["stats"]["by-f"]["txn"]["ok-count"] > 5


def test_txn_replay_cache_out_of_order_completions():
    """The incremental replay cache must serve completions at any
    committed position, in any arrival order, with the same results a
    full prefix replay would produce."""
    import numpy as np

    from maelstrom_tpu.nodes import Intern
    from maelstrom_tpu.nodes.raft import OP_TXN
    from maelstrom_tpu.nodes.txn_list_append import (TxnRaftProgram,
                                                     apply_txn)

    nodes = ["n0", "n1", "n2"]
    prog = TxnRaftProgram({"latency": {"mean": 0}}, nodes)
    intern = Intern()
    txns = [[["append", 1, i], ["r", 1, None]] for i in range(5)]
    tids = [intern.id(t) for t in txns]
    cap = prog.cap
    log_a = np.zeros(cap, np.int32)
    log_b = np.zeros(cap, np.int32)
    for i, tid in enumerate(tids):
        log_a[i] = (1 << 16) | OP_TXN
        log_b[i] = ((tid >> 8) & 0xFF) << 8 | (tid & 0xFF)
    row = {"commit": np.int32(len(tids) - 1),
           "log_len": np.int32(len(tids)),
           "log_a": log_a, "log_b": log_b}

    # ground truth: full replays
    expect = []
    db = {}
    for t in txns:
        db, out = apply_txn(db, t)
        expect.append(out)

    read_state = lambda i=0: row  # noqa: E731
    for p in (2, 0, 4, 1, 3):     # out of order, including rewinds
        got = prog.completion({"f": "txn"}, {"type": "txn_ok",
                                             "position": p},
                              read_state, intern)
        assert got["type"] == "ok" and got["value"] == expect[p], (p, got)


def test_txn_list_append_tpu_raft_partition():
    res = core.run({"workload": "txn-list-append",
                    "node": "tpu:txn-list-append",
                    "node_count": 5, "rate": 10.0, "time_limit": 4.0,
                    "nemesis": {"partition"}, "nemesis_interval": 1.0,
                    "seed": 9, "journal_rows": False,
                    "store_root": "/tmp/maelstrom-tpu-test-store"})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["valid"] is True
