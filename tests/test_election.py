"""Leader election and live failover (ISSUE 14, doc/compartment.md
"leader election"): ballot-numbered MultiPaxos phase 1 on the
compartmentalized cluster — quorum geometry, acceptor fencing,
dueling-candidate units, the kill-as-failover soup, availability
accounting, and the election-schedule byte-identity/resume pins."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu import core
from maelstrom_tpu import nemesis as nem
from maelstrom_tpu.errors import ERROR_REGISTRY
from maelstrom_tpu.net.tpu import Msgs
from maelstrom_tpu.nodes.compartment import (
    AcceptorRole, Layout, SequencerRole, _col_quorum,
    T_ASSIGN, T_P2A, T_P2B, T_P2R, T_PREP, T_PROM, T_QRY, T_QVAL,
    T_REJP)

STORE = "/tmp/maelstrom-election-store"

# ONE compact elected config shared by every e2e test in this file
# (2 candidates, 1 proxy, a 1x2 grid, 1 replica): the shapes stay
# identical across tests, so the compiled step is paid once per config
ELECT = dict(store_root=STORE, seed=11, rate=30.0, time_limit=2.5,
             journal_rows=False, audit=False, node="tpu:compartment",
             workload="lin-kv", timeout_ms=300,
             election_timeout_rounds=40,
             roles="sequencers=2,proxies=1,acceptors=1x2,replicas=1",
             nemesis_targets="kill=sequencer", recovery_s=1)


def _opts(**over):
    base = {"roles": "sequencers=2,proxies=1,acceptors=2x2,replicas=1",
            "rate": 5, "time_limit": 1}
    base.update(over)
    return base


def _ctx(rnd):
    return {"round": jnp.int32(rnd), "key": jax.random.PRNGKey(0)}


def _inbox(n, k, rows):
    """Msgs [n, k] from sparse rows: (node, lane, field dict)."""
    ib = Msgs.empty((n, k))
    cols = {f: np.array(getattr(ib, f)) for f in
            ("valid", "src", "dest", "type", "a", "b", "c", "mid")}
    for node, lane, fields in rows:
        cols["valid"][node, lane] = True
        for f, v in fields.items():
            cols[f][node, lane] = v
    return ib.replace(**{f: jnp.asarray(v) for f, v in cols.items()})


# --- layout / validation ---------------------------------------------------

def test_layout_election_validation():
    lay = Layout(_opts(), 8)
    assert (lay.S, lay.P, lay.A, lay.R) == (2, 1, 4, 1)
    assert lay.p_base == 2 and lay.a_base == 3 and lay.r_base == 7
    # S > 1 narrows the packed wire fields and validates them
    with pytest.raises(ValueError, match="12-bit slots"):
        Layout(_opts(log_cap=5000), 8)
    with pytest.raises(ValueError, match="client id"):
        Layout(_opts(concurrency=5000), 8)
    with pytest.raises(ValueError, match="ballot_width"):
        Layout(_opts(ballot_width=9), 8)
    with pytest.raises(ValueError, match="residue"):
        Layout(_opts(ballot_width=1), 8)
    with pytest.raises(ValueError, match="heartbeat"):
        Layout(_opts(election_timeout_rounds=5), 8)
    # the stable configuration keeps the PR 9 15-bit fields
    lay1 = Layout({"roles": None, "rate": 5, "time_limit": 1,
                   "log_cap": 5000}, 9)
    assert lay1.S == 1 and lay1.cap == 5000


def test_assign_packing_roundtrip():
    lay = Layout(_opts(), 8)
    a = lay.pack_assign_a(jnp.int32(5), jnp.int32(77), jnp.int32(123))
    bal, client, slot = lay.unpack_assign_a(a)
    assert (int(bal), int(client), int(slot)) == (5, 77, 123)
    la = lay.pack_learn_a(jnp.int32(77), jnp.int32(123))
    client2, slot2 = lay.unpack_learn_a(la)
    assert (int(client2), int(slot2)) == (77, 123)


def test_col_quorum_geometry():
    """Phase-1 quorums are COLUMNS: every column intersects every
    phase-2 row quorum; a full row does NOT (two different rows are
    disjoint) — the grid geometry the safety argument rests on."""
    lay = Layout(_opts(), 8)          # 2x2 grid: idx r*2+c
    col0 = (1 << 0) | (1 << 2)
    row0 = (1 << 0) | (1 << 1)
    assert bool(_col_quorum(lay, jnp.int32(col0)))
    assert not bool(_col_quorum(lay, jnp.int32(row0)))
    assert not bool(_col_quorum(lay, jnp.int32(1 << 0)))
    assert bool(_col_quorum(lay, jnp.int32((1 << 1) | (1 << 3))))


def test_not_leader_error_is_definite():
    err = ERROR_REGISTRY[31]
    assert err.name == "not-leader" and err.definite is True


# --- acceptor: promises, fencing, recovery reads ---------------------------

def test_acceptor_promises_highest_and_rejects_rest():
    lay = Layout(_opts(), 8)
    acc = AcceptorRole(_opts(), [f"n{i}" for i in range(3, 7)], lay)
    st = acc.init_state()
    # dueling prepares in one round: only the max is promised
    ib = _inbox(4, lay.K, [
        (0, 0, {"type": T_PREP, "a": 3, "src": 0}),
        (0, 1, {"type": T_PREP, "a": 5, "src": 1}),
    ])
    st, out = acc.step(st, ib, _ctx(1))
    assert int(st["promised"][0]) == 5
    types = np.array(out.type[0])[np.array(out.valid[0])]
    assert set(types) == {T_PROM, T_REJP}
    prom_lane = int(np.array(out.type[0]).tolist().index(T_PROM))
    assert int(out.a[0, prom_lane]) == 5      # the winning ballot
    assert int(out.c[0, prom_lane]) == 0      # hi+1: nothing accepted


def test_acceptor_fences_stale_p2a_and_answers_queries():
    """The deposed-sequencer replay fixture: after promising ballot 5,
    a stale-ballot T_P2A (the revived old leader's in-flight traffic)
    is NACKED (T_P2R) and never stored; a current-ballot T_P2A stores
    and acks; T_QRY reads back (cmd, accepted ballot)."""
    lay = Layout(_opts(), 8)
    acc = AcceptorRole(_opts(), [f"n{i}" for i in range(3, 7)], lay)
    st = acc.init_state()
    st, _ = acc.step(st, _inbox(4, lay.K, [
        (0, 0, {"type": T_PREP, "a": 5, "src": 1})]), _ctx(1))
    st, out = acc.step(st, _inbox(4, lay.K, [
        (0, 0, {"type": T_P2A, "a": 7, "b": 111, "c": 3, "src": 2}),
        (0, 1, {"type": T_P2A, "a": 8, "b": 222, "c": 5, "src": 2}),
    ]), _ctx(2))
    assert not bool(st["acc_has"][0, 7])      # stale: fenced
    assert bool(st["acc_has"][0, 8])
    assert int(st["acc_bal"][0, 8]) == 5
    assert int(st["acc_hi"][0]) == 8
    lanes = np.array(out.type[0])
    assert lanes[0] == T_P2R and int(out.c[0, 0]) == 5
    assert lanes[1] == T_P2B and int(out.c[0, 1]) == 5
    st, out = acc.step(st, _inbox(4, lay.K, [
        (0, 0, {"type": T_QRY, "a": 8, "c": 5, "src": 1}),
        (0, 1, {"type": T_QRY, "a": 9, "c": 5, "src": 1}),
    ]), _ctx(3))
    assert int(out.type[0, 0]) == T_QVAL
    assert int(out.b[0, 0]) == 222
    assert int(out.c[0, 0]) & 0xFFFF == 6     # accepted ballot 5 -> 5+1
    assert int(out.c[0, 1]) & 0xFFFF == 0     # slot 9: nothing accepted


def test_accept_raises_promise_floor():
    """The classic acceptor rule: accepting ballot b implies promising
    b. An acceptor that never saw the new leader's prepare (promise
    quorums are one COLUMN) accepts a value at the new ballot — a
    stale lower-ballot proposal arriving afterwards must be NACKED,
    not allowed to overwrite the (possibly chosen) higher-ballot
    value."""
    lay = Layout(_opts(), 8)
    acc = AcceptorRole(_opts(), [f"n{i}" for i in range(3, 7)], lay)
    st = acc.init_state()
    # promised still 0 (no prepare seen); accept Y=222 @ ballot 1
    st, out = acc.step(st, _inbox(4, lay.K, [
        (1, 0, {"type": T_P2A, "a": 10, "b": 222, "c": 1, "src": 2}),
    ]), _ctx(1))
    assert int(out.type[1, 0]) == T_P2B
    assert int(st["promised"][1]) == 1        # accept raised the floor
    # the old leader's stale X=111 @ ballot 0 replay: fenced, value kept
    st, out = acc.step(st, _inbox(4, lay.K, [
        (1, 0, {"type": T_P2A, "a": 10, "b": 111, "c": 0, "src": 2}),
    ]), _ctx(2))
    assert int(out.type[1, 0]) == T_P2R
    assert int(st["acc_cmd"][1, 10]) == 222
    assert int(st["acc_bal"][1, 10]) == 1


# --- sequencer: candidacy, duel, column win --------------------------------

def test_sequencer_duel_loser_backs_off_winner_takes_column():
    lay = Layout(_opts(), 8)
    seq = SequencerRole(_opts(), ["n0", "n1"], lay)
    st = seq.init_state()
    # candidate 1 (residue 1) mid-candidacy at ballot 3
    st["electing"] = jnp.asarray([False, True])
    st["leading"] = jnp.asarray([False, False])
    st["bal"] = jnp.asarray([0, 3], jnp.int32)
    st["cand_round"] = jnp.asarray([0, 10], jnp.int32)

    # a full ROW of promises (idx 0, 1) is NOT a phase-1 quorum
    st, _ = seq.step(st, _inbox(2, lay.K, [
        (1, 0, {"type": T_PROM, "a": 3, "b": 0, "c": 9}),
        (1, 1, {"type": T_PROM, "a": 3, "b": 1, "c": 4}),
    ]), _ctx(12))
    assert not bool(st["leading"][1])
    # completing a COLUMN (idx 0 + idx 2) wins; next_slot = hi + 1
    st, _ = seq.step(st, _inbox(2, lay.K, [
        (1, 0, {"type": T_PROM, "a": 3, "b": 2, "c": 9}),
    ]), _ctx(14))
    assert bool(st["leading"][1]) and not bool(st["electing"][1])
    assert int(st["next_slot"][1]) == 9       # promised hi+1 = 9 -> hi 8
    assert int(st["won_count"][1]) == 1
    assert int(st["won_sum"][1]) == 4         # candidacy 10 -> win 14

    # a rival's rejection aborts a candidacy and backs off
    st["electing"] = jnp.asarray([True, False])
    st["bal"] = jnp.asarray([4, 3], jnp.int32)
    st, _ = seq.step(st, _inbox(2, lay.K, [
        (0, 0, {"type": T_REJP, "a": 4, "c": 7}),
    ]), _ctx(20))
    assert not bool(st["electing"][0])
    assert int(st["seen"][0]) == 7
    assert int(st["boff"][0]) > 20


def test_sequencer_redirects_when_not_leading():
    from maelstrom_tpu.nodes.raft import T_READ
    lay = Layout(_opts(), 8)
    seq = SequencerRole(_opts(), ["n0", "n1"], lay)
    st = seq.init_state()
    st, out = seq.step(st, _inbox(2, lay.K, [
        (1, 0, {"type": T_READ, "a": 1, "src": 8, "mid": 42}),
    ]), _ctx(1))
    # node 1 does not lead: T_ERR code 31 with hint -> node 0
    v = np.array(out.valid[1])
    lane = int(np.argmax(v))
    assert int(out.type[1, lane]) == 1
    assert int(out.a[1, lane]) == 31
    assert int(out.b[1, lane]) == 0           # ballot-0 leader hint
    assert int(out.reply_to[1, lane]) == 42


# --- nemesis: dynamic sequencer target -------------------------------------

def test_resolve_dynamic_targets_and_expansion():
    groups = {"sequencers": ["n0", "n1"]}
    nodes = [f"n{i}" for i in range(6)]
    t = nem.resolve_targets("kill=sequencer", groups, nodes,
                            dynamic=("sequencer",))
    assert t == {"kill": ["@sequencer"]}
    # without the dynamic vocabulary the token is an unknown group
    with pytest.raises(ValueError, match="unknown group"):
        nem.resolve_targets("kill=sequencer", groups, nodes)
    d = nem.NemesisDecisions(nodes, seed=3, targets=t)
    with pytest.raises(ValueError, match="needs a live runner"):
        d.next_kill_targets()
    d.resolve_dynamic = lambda tok: ["n1"] if tok == "sequencer" else []
    assert d.next_kill_targets() == ["n1"]


# --- availability accounting (pure part) -----------------------------------

def test_availability_block_units():
    from maelstrom_tpu.checkers.availability import (availability_block,
                                                     gaps_rounds)
    assert gaps_rounds([5, 6, 20], 0, 25) == [(0, 5), (5, 1), (6, 14),
                                              (20, 5)]
    ms = 1.0
    rows = []
    for t_r, typ in ((5, "ok"), (6, "ok"), (500, "ok"), (900, "ok")):
        rows.append({"type": "invoke", "f": "read", "process": 0,
                     "time": int((t_r - 1) * 1e6)})
        rows.append({"type": typ, "f": "read", "process": 0,
                     "time": int(t_r * 1e6)})
    rows.append({"type": "invoke", "f": "start-kill",
                 "process": "nemesis", "time": int(100 * 1e6)})
    blk = availability_block(rows, ms, end_round=1000,
                             dip_threshold_rounds=200)
    assert blk["ok-count"] == 4
    assert blk["longest-ok-gap-rounds"] == 494
    assert blk["dip-count"] == 2              # 6->500 and 500->900
    rec = blk["failover-recovery-rounds"]
    assert rec["per-kill"] == [400]           # kill @100 -> ok @500
    assert rec["max"] == 400


# --- e2e: the kill-as-failover soup ----------------------------------------

def test_failover_kill_sequencer_soup():
    """The acceptance run in miniature: `kill=sequencer` under the
    combined kill/pause/partition/duplicate soup on the elected
    compartment — >= 2 completed failovers, a LINEARIZABLE verdict,
    bounded availability dips, and the stale-ballot fencing path
    actually exercised (a revived deposed sequencer replays its
    in-flight T_ASSIGNs; the grid must nack them)."""
    res = core.run({**ELECT,
                    "nemesis": {"kill", "pause", "partition",
                                "duplicate"},
                    "nemesis_interval": 0.6})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True
    avail = res["availability"]
    assert avail["election"]["failovers"] >= 2, avail["election"]
    assert avail["election"]["ballot-overflows"] == 0
    assert avail["ok-count"] > 10
    # dips, never durable unavailability: committed replies resume
    # inside the run after every kill window
    assert avail["longest-ok-gap-rounds"] < avail["final-round"] * 0.8
    assert "failover-recovery-rounds" in avail
    by_type = res["net"]["send-count-by-type"]
    assert by_type.get("prep", 0) > 0         # elections ran
    assert by_type.get("hb", 0) > 0           # leaders heartbeated
    # the kill ops targeted the LIVE leader (dynamic resolution):
    # every recorded kill names exactly one sequencer candidate
    with open(os.path.join(STORE, "latest", "history.jsonl")) as f:
        kills = [json.loads(ln) for ln in f
                 if '"start-kill"' in ln and '"info"' in ln]
    assert len(kills) >= 2
    for k in kills:
        v = str(k.get("value"))
        assert "n0" in v or "n1" in v, v


@pytest.mark.slow
def test_election_schedule_byte_identity_plain():
    """Same seed -> same elections, same failovers, same history BYTES
    (the election schedule is a pure function of the seed)."""
    runs = []
    for sub in ("bi-a", "bi-b"):
        root = os.path.join(STORE, sub)
        res = core.run({**ELECT, "store_root": root,
                        "nemesis": {"kill"}, "nemesis_interval": 0.6})
        with open(os.path.join(root, "latest", "history.jsonl"),
                  "rb") as f:
            runs.append((res, f.read()))
    (r1, h1), (r2, h2) = runs
    assert h1 == h2
    a1 = {k: v for k, v in r1["availability"].items()
          if k != "check-wall-s"}
    a2 = {k: v for k, v in r2["availability"].items()
          if k != "check-wall-s"}
    assert a1 == a2
    assert r1["availability"]["election"]["failovers"] >= 2


@pytest.mark.slow
def test_election_resume_byte_identity():
    """An in-progress election rides the durable store + checkpoint:
    a run checkpointed mid-soup (ballot state in the carry) truncated
    and resumed produces the BYTE-IDENTICAL history of the
    uninterrupted baseline."""
    from maelstrom_tpu import checkpoint as cp
    base_root = os.path.join(STORE, "resume-base")
    res = core.run({**ELECT, "store_root": base_root,
                    "nemesis": {"kill"}, "nemesis_interval": 0.6})
    assert res["valid"] is True

    part_root = os.path.join(STORE, "resume-part")
    core.run({**ELECT, "store_root": part_root,
              "nemesis": {"kill"}, "nemesis_interval": 0.6,
              "checkpoint_every": 0.7, "sync_checkpoint": True,
              "max_rounds": 1500})
    ck_dir = os.path.realpath(os.path.join(part_root, "latest"))
    state = cp.load(ck_dir)
    # the checkpoint carries election ballot state (the seam is real:
    # a kill window opened before round 1400, so ballots moved)
    seq = state["sim"].nodes["sequencers"]
    assert int(np.max(np.asarray(seq["bal"]))) > 0
    assert state["fingerprint"]["election_timeout_rounds"] == 40

    res2 = core.run({**ELECT, "store_root": part_root,
                     "nemesis": {"kill"}, "nemesis_interval": 0.6,
                     "checkpoint_every": 0.7, "sync_checkpoint": True,
                     "resume": ck_dir})
    assert res2["valid"] is True
    with open(os.path.join(base_root, "latest",
                           "history.jsonl"), "rb") as f:
        h_base = f.read()
    with open(os.path.join(part_root, "latest",
                           "history.jsonl"), "rb") as f:
        h_res = f.read()
    assert h_res == h_base
    ab = {k: v for k, v in res["availability"].items()
          if k != "check-wall-s"}
    ar = {k: v for k, v in res2["availability"].items()
          if k != "check-wall-s"}
    assert ab == ar


def test_fingerprint_pins_election_options():
    """A resume may not change the election schedule's inputs: the
    failure-detector deadline, ballot width, and candidate set (via
    roles) are all fingerprinted."""
    from maelstrom_tpu import checkpoint as cp
    t1 = core.build_test({**ELECT})
    fp = cp.fingerprint(t1)
    assert fp["election_timeout_rounds"] == 40
    assert fp["ballot_width"] == 6
    assert "sequencers=2" in fp["roles"]
    state = {"fingerprint": fp}
    t2 = core.build_test({**ELECT, "election_timeout_rounds": 80})
    with pytest.raises(ValueError, match="election_timeout_rounds"):
        cp.check_fingerprint(state, t2)


@pytest.mark.slow
def test_election_spans_acceptor_column_partition():
    """kill=sequencer + a partitioned acceptor COLUMN: phase 1 elects
    through the other column (column quorums need only one), writes
    stall until the heal (row quorums cross every column), and the
    verdict stays linearizable."""
    res = core.run({**ELECT, "seed": 13, "time_limit": 3.0,
                    "roles": "sequencers=2,proxies=1,acceptors=1x2,"
                             "replicas=1",
                    "nemesis": {"kill", "partition"},
                    "nemesis_interval": 0.7,
                    "nemesis_targets": "kill=sequencer,"
                                       "partition=acceptor-col-0",
                    "recovery_s": 2})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True
    assert res["availability"]["election"]["failovers"] >= 1


@pytest.mark.slow
def test_failover_composes_with_continuous():
    """Open-world composition: the elected cluster under --continuous
    (ops injected mid-window while the kill=sequencer soup runs).
    Exercises the redirect requeue's carry_sched path — a retried op
    re-injects inside a later window WITHOUT a second invoke row —
    and must stay linearizable with completed failovers."""
    res = core.run({**ELECT, "store_root": os.path.join(STORE, "cont"),
                    "continuous": True,
                    "nemesis": {"kill"}, "nemesis_interval": 0.6})
    assert res["valid"] is True, res.get("workload")
    assert res["workload"]["valid"] is True
    assert res["availability"]["election"]["failovers"] >= 1
    # pairing sanity: every process alternates invoke/completion (a
    # doubled invoke from a retried op would break this)
    with open(os.path.join(STORE, "cont", "latest",
                           "history.jsonl")) as f:
        open_p: dict = {}
        for ln in f:
            o = json.loads(ln)
            p = o.get("process")
            if p == "nemesis":
                continue
            if o["type"] == "invoke":
                assert p not in open_p, o
                open_p[p] = o
            elif o["type"] in ("ok", "fail", "info"):
                assert p in open_p, o
                del open_p[p]


@pytest.mark.slow
def test_election_sigkill_resume_bit_identical(tmp_path):
    """The real seam: the CLI run SIGKILLed mid-soup (a checkpoint
    cadence tight enough that the kill lands between checkpoints, with
    an election-driving kill=sequencer nemesis live) and resumed
    produces history + results bit-identical to an uninterrupted
    baseline — ballot state rides the durable store and the redirect
    requeue rides the checkpoint meta."""
    import random

    from maelstrom_tpu import crash_soak

    opts = {
        "-w": "lin-kv", "--node": "tpu:compartment",
        "--roles": "sequencers=2,proxies=1,acceptors=1x2,replicas=1",
        "--rate": "30", "--time-limit": "2.5", "--seed": "11",
        "--timeout-ms": "300", "--election-timeout-rounds": "40",
        "--nemesis": "kill", "--nemesis-interval": "0.6",
        "--nemesis-targets": "kill=sequencer",
        "--checkpoint-every": "0.25",
    }
    base_root = str(tmp_path / "base")
    os.makedirs(base_root, exist_ok=True)
    base_dir = crash_soak.run_once(
        base_root, opts, os.path.join(base_root, "baseline.log"))
    res = crash_soak.run_with_kills(str(tmp_path / "soak"), opts,
                                    kills=1, rng=random.Random(5))
    verdict = crash_soak.compare_runs(base_dir, res["dir"])
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict
    assert verdict["valid"] == (True, True)
    with open(os.path.join(res["dir"], "results.json")) as f:
        avail = json.load(f)["availability"]
    assert avail["election"]["failovers"] >= 2, avail["election"]


@pytest.mark.multichip
@pytest.mark.slow
def test_failover_soup_mesh_byte_identity():
    """The acceptance soup under --mesh 1,2: valid, >= 2 failovers, and
    history bytes IDENTICAL to the single-chip run of the same seed —
    the election schedule is mesh-invariant."""
    plain_root = os.path.join(STORE, "mesh-plain")
    res1 = core.run({**ELECT, "store_root": plain_root,
                     "nemesis": {"kill", "pause", "partition",
                                 "duplicate"},
                     "nemesis_interval": 0.6})
    mesh_root = os.path.join(STORE, "mesh-sharded")
    res2 = core.run({**ELECT, "store_root": mesh_root, "mesh": "1,2",
                     "nemesis": {"kill", "pause", "partition",
                                 "duplicate"},
                     "nemesis_interval": 0.6})
    assert res1["valid"] is True and res2["valid"] is True
    assert res2["availability"]["election"]["failovers"] >= 2
    with open(os.path.join(plain_root, "latest",
                           "history.jsonl"), "rb") as f:
        h1 = f.read()
    with open(os.path.join(mesh_root, "latest",
                           "history.jsonl"), "rb") as f:
        h2 = f.read()
    assert h1 == h2
