"""Regression tests for review findings: nested generators in Seq, the
Sequential service ring-buffer clamp, pn-counter open-invoke handling, and
real-time barrier edges in Elle-lite."""

from maelstrom_tpu import generators as g
from maelstrom_tpu import nemesis as nem
from maelstrom_tpu.checkers.pn_counter import PNCounterChecker
from maelstrom_tpu.message import message
from maelstrom_tpu.services import PersistentKV, Sequential
from tests.test_generators import interpret


def test_seq_nested_sleep_advances():
    # The nemesis cycle interleaves Sleep generators with op maps; Seq must
    # run each to exhaustion and keep successor state (previously Sleep
    # stayed PENDING forever, so no fault was ever injected).
    pkg = nem.package({"partition"}, interval_s=1.0)
    ops = interpret(g.time_limit(5.5, pkg["generator"]),
                    processes=("w0",), max_time_s=10)
    fs = [o["f"] for o in ops]
    assert fs[:4] == ["start-partition", "stop-partition",
                      "start-partition", "stop-partition"], fs
    # spaced ~1s apart
    assert ops[1]["time"] - ops[0]["time"] >= 0.9e9


def test_seq_nested_once_emits_once():
    ops = interpret(g.Seq([g.Once({"f": "a"}), g.Once({"f": "b"})]),
                    processes=("w0",))
    assert [o["f"] for o in ops] == ["a", "b"]


def test_sequential_service_lagging_client_clamped():
    svc = Sequential(PersistentKV(), buffer_size=8, seed=0)
    for i in range(50):
        svc.handle(message("c0", "svc", {"type": "write", "key": "x",
                                         "value": i}))
    # A fresh client laggier than the buffer must not crash, and must read
    # one of the retained states.
    for seed in range(20):
        svc.rng.seed(seed)
        r = svc.handle(message(f"c{seed+1}", "svc",
                               {"type": "read", "key": "x"}))
        assert r["type"] == "read_ok" and 42 <= r["value"] <= 49, r


def test_pn_counter_open_invoke_is_indeterminate():
    h = [
        {"type": "invoke", "f": "add", "value": 1, "process": 0, "time": 0},
        {"type": "ok", "f": "read", "final": True, "value": 1,
         "process": 1, "time": 5},
    ]
    r = PNCounterChecker().check({}, h)
    assert r["valid"] is True, r
    assert r["acceptable"] == [[0, 1]]


def test_elle_rt_barriers_scale():
    # 2000 sequential clean txns: must finish fast (previously O(n^2) edge
    # materialization) and stay valid.
    from maelstrom_tpu.checkers.elle import ElleListAppendChecker
    h = []
    t = 0
    for i in range(2000):
        h.append({"type": "invoke", "f": "txn",
                  "value": [["append", 1, i]], "process": 0, "time": t})
        h.append({"type": "ok", "f": "txn",
                  "value": [["append", 1, i]], "process": 0, "time": t + 1})
        t += 2
    h.append({"type": "invoke", "f": "txn", "value": [["r", 1, None]],
              "process": 0, "time": t})
    h.append({"type": "ok", "f": "txn",
              "value": [["r", 1, list(range(2000))]], "process": 0,
              "time": t + 1})
    import time
    t0 = time.monotonic()
    r = ElleListAppendChecker().check({}, h)
    assert r["valid"] is True, r
    assert time.monotonic() - t0 < 10


def test_latency_clipping_gates_netstats_validity():
    """Clipped latency draws silently shorten delays — a distortion of the
    latency model that must invalidate a run unless explicitly tolerated
    (VERDICT r2: fuzz-100k shipped latency_clipped: 2666 with ok: true)."""
    from types import SimpleNamespace

    import jax.numpy as jnp

    from maelstrom_tpu.net import tpu as T
    from maelstrom_tpu.nodes import get_program
    from maelstrom_tpu.runner.tpu_runner import TpuNetStats
    from maelstrom_tpu.sim import make_sim

    nodes = [f"n{i}" for i in range(4)]
    prog = get_program("broadcast", {"topology": "grid", "max_values": 8},
                       nodes)
    cfg = T.NetConfig(n_nodes=4, n_clients=1, pool_cap=16,
                      inbox_cap=prog.inbox_cap)
    sim = make_sim(prog, cfg)
    runner = SimpleNamespace(sim=sim, program=prog, journal=None)
    chk = TpuNetStats(runner)

    assert chk.check({}, [])["valid"] is True
    runner.sim = sim.replace(channels=sim.channels.replace(
        lat_clipped=jnp.int32(5)))
    out = chk.check({}, [])
    assert out["valid"] is False and out["latency-clipped"] == 5
    # explicit opt-in (the fuzz harness's randomized-dist configs)
    assert chk.check({"allow_latency_clipping": True}, [])["valid"] is True
    # overwrites still gate independently of clipping
    runner.sim = sim.replace(channels=sim.channels.replace(
        overwrites=jnp.int32(3)))
    prog.tolerates_channel_overwrites = False
    assert chk.check({}, [])["valid"] is False
