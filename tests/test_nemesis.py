"""The composable combined nemesis: fault-package registry, grudge
shapes, decision-stream determinism, raft crash durability, client
retry backoff, and fast per-package smoke runs on the echo/broadcast
programs (full storms live in test_fault_soup.py, marked slow)."""

import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu import core
from maelstrom_tpu import generators as g
from maelstrom_tpu import nemesis as nem
from tests.test_generators import interpret


# --- registry / schedule composition ---------------------------------------


def test_package_rejects_unknown_faults():
    with pytest.raises(ValueError, match="unknown nemesis fault"):
        nem.package({"partition", "clock-skew"})


def test_package_empty_is_inert():
    pkg = nem.package(set())
    assert pkg["generator"] is None
    assert pkg["final_generator"] is None
    assert pkg["faults"] == ()


def test_package_composes_all_fault_schedules():
    pkg = nem.package({"kill", "pause", "partition", "duplicate",
                       "weather"}, interval_s=1.0)
    assert pkg["faults"] == ("partition", "kill", "pause", "duplicate",
                             "weather")
    ops = interpret(g.time_limit(4.2, pkg["generator"]),
                    processes=("w0",), max_time_s=8)
    fs = [o["f"] for o in ops]
    # every package starts AND stops within the window, interleaved
    for f in ("partition", "kill", "pause", "duplicate", "weather"):
        assert f"start-{f}" in fs and f"stop-{f}" in fs, fs
    # final generator heals every package
    finals = interpret(pkg["final_generator"], processes=("w0",))
    assert [o["f"] for o in finals] == [
        "stop-partition", "stop-kill", "stop-pause", "stop-duplicate",
        "stop-weather"]


# --- grudge shapes ----------------------------------------------------------


NODES = [f"n{i}" for i in range(5)]


def test_majorities_ring_grudge_directional_majorities():
    import random
    name, grudge = nem.majorities_ring(NODES, random.Random(3))
    assert "majorities-ring" in name
    m = len(NODES) // 2 + 1
    # every node hears from exactly a majority (itself + m-1 others)
    for d in NODES:
        heard = set(NODES) - grudge[d]
        assert d in heard
        assert len(heard) == m, (d, heard)
    # and the grudge is genuinely one-way somewhere: some src->dest is
    # blocked while dest->src flows
    asym = [(s, d) for d in NODES for s in grudge[d]
            if d not in grudge.get(s, set())]
    assert asym, grudge


def test_bridge_grudge_shape():
    import random
    name, grudge = nem.bridge(NODES, random.Random(1))
    # exactly one node (the bridge) is absent from every block set
    blocked_nodes = set(grudge)
    bridges = set(NODES) - blocked_nodes
    assert len(bridges) == 1, grudge
    b = bridges.pop()
    assert all(b not in srcs for srcs in grudge.values())


def test_one_way_halves_is_asymmetric():
    import random
    name, grudge = nem.one_way_halves(NODES, random.Random(2))
    assert "one-way" in name
    # only one side blocks: every (src, dest) cut must flow dest -> src
    for d, srcs in grudge.items():
        for s in srcs:
            assert d not in grudge.get(s, set()), (s, d)


def test_grudge_matrix_expresses_one_way():
    grudge = {"n0": {"n1"}}             # n1 -> n0 blocked; n0 -> n1 flows
    groups, matrix = nem.grudge_matrix(NODES, grudge)
    assert matrix[1, 0] and not matrix[0, 1]


# --- decision-stream determinism -------------------------------------------


def test_decision_streams_deterministic_and_per_fault():
    a = nem.NemesisDecisions(NODES, seed=42)
    b = nem.NemesisDecisions(NODES, seed=42)
    # same seed: identical sequences, even when the streams interleave
    # differently (a draws kills between grudges, b draws grudges first)
    ga = [a.next_grudge()[0] for _ in range(4)]
    ka = [a.next_kill_targets() for _ in range(4)]
    kb = [b.next_kill_targets() for _ in range(4)]
    gb = [b.next_grudge()[0] for _ in range(4)]
    assert ga == gb and ka == kb
    # different seed: different schedule
    c = nem.NemesisDecisions(NODES, seed=43)
    assert [c.next_grudge()[0] for _ in range(4)] != ga


def _tpu_test(seed, faults, workload="echo", node="tpu:echo", **kw):
    opts = dict(store_root="/tmp/maelstrom-tpu-test-store", seed=seed,
                workload=workload, node=node, node_count=5, rate=10.0,
                time_limit=3.0, journal_rows=False, recovery_s=1.5,
                nemesis=set(faults), nemesis_interval=0.7)
    opts.update(kw)
    return core.run(opts)


def test_nemesis_determinism_tpu_path(tmp_path):
    """Same seed => byte-identical histories (every op, every nemesis
    fault choice, every virtual timestamp) across two full TPU runs of a
    kill+pause+partition+duplicate soup."""
    import json

    def run_once():
        res = _tpu_test(29, {"kill", "pause", "partition", "duplicate",
                             "weather"})
        assert res["valid"] is True
        with open("/tmp/maelstrom-tpu-test-store/latest/history.jsonl") as f:
            return [json.loads(line) for line in f]

    h1, h2 = run_once(), run_once()
    assert h1 == h2
    nem_ops = [(o["f"], o["value"], o["time"]) for o in h1
               if o.get("process") == "nemesis" and o["type"] == "info"]
    assert any(f == "start-kill" for f, _, _ in nem_ops), nem_ops
    assert any(f == "start-partition" for f, _, _ in nem_ops), nem_ops
    assert any(f == "start-weather" for f, _, _ in nem_ops), nem_ops


def test_nemesis_determinism_host_path():
    """Same seed => identical per-package fault schedules on the host
    path: each package's op sequence and every fault choice it made
    (grudge shape, kill/pause targets, dup probability) must match
    between runs. Wall-clock jitter may interleave ops from DIFFERENT
    packages differently — a real-time path cannot pin that — but the
    per-fault decision streams (`NemesisDecisions`) must not move."""
    import json

    composed = {"kill", "pause", "partition", "duplicate"}

    def run_once():
        res = core.run(dict(
            store_root="/tmp/maelstrom-tpu-test-store", seed=31,
            workload="echo", bin="demo/python/echo.py", node_count=5,
            rate=10.0, time_limit=3.5,
            nemesis=set(composed), nemesis_interval=0.8))
        assert res["valid"] is True
        with open("/tmp/maelstrom-tpu-test-store/latest/history.jsonl") as f:
            hist = [json.loads(line) for line in f]
        # every fault DECISION is in a start op's value (stop values are
        # derivative: "healed", or the accumulated start targets); final
        # heal ops interleave at window-dependent positions, so starts
        # are the comparable stream
        seq = [(o["f"], o["value"]) for o in hist
               if o.get("process") == "nemesis" and o["type"] == "info"
               and o["f"].startswith("start-")]
        return {f: [x for x in seq if x[0] == f"start-{f}"]
                for f in composed}

    s1, s2 = run_once(), run_once()
    for f in composed:
        # wall-clock may cut the window a cycle earlier in one run, so
        # compare the common prefix; every decision in it must match
        k = min(len(s1[f]), len(s2[f]))
        assert k >= 1, (f, s1[f], s2[f])
        assert s1[f][:k] == s2[f][:k], (f, s1[f], s2[f])


# --- raft crash durability --------------------------------------------------


def _raft_program(n=5):
    from maelstrom_tpu.nodes import get_program
    nodes = [f"n{i}" for i in range(n)]
    return get_program("lin-kv", {"rate": 5, "time_limit": 5}, nodes), nodes


def test_raft_restore_wipes_volatile_keeps_log():
    prog, _ = _raft_program()
    s = prog.init_state()
    # node 1 has a replicated log, applied state, and leadership
    s = dict(s)
    s["log_a"] = s["log_a"].at[1, 0].set(77)
    s["log_len"] = s["log_len"].at[1].set(1)
    s["term"] = s["term"].at[1].set(9)
    s["voted_for"] = s["voted_for"].at[1].set(1)
    s["kv"] = s["kv"].at[1, 3].set(5)
    s["commit"] = s["commit"].at[1].set(0)
    s["applied"] = s["applied"].at[1].set(0)
    s["role"] = s["role"].at[1].set(2)          # LEADER
    durable = prog.durable_view(s)
    mask = jnp.asarray(np.array([False, True, False, False, False]))
    r = prog.restore(prog.init_state(), durable, s, mask)
    # durable survives: the log, term, and vote (paper section 5.1)
    assert int(r["log_a"][1, 0]) == 77
    assert int(r["log_len"][1]) == 1
    assert int(r["term"][1]) == 9
    assert int(r["voted_for"][1]) == 1
    # volatile is wiped: kv/commit/applied/role rebuilt from scratch
    assert int(r["kv"][1, 3]) == 0
    assert int(r["commit"][1]) == -1
    assert int(r["applied"][1]) == -1
    assert int(r["role"][1]) == 0               # FOLLOWER
    # unmasked nodes are untouched
    assert int(r["term"][0]) == int(s["term"][0])


def test_default_program_is_fully_persistent():
    from maelstrom_tpu.nodes import get_program
    prog = get_program("echo", {}, ["n0", "n1"])
    s = prog.init_state()
    s = {"rounds": s["rounds"] + 7}
    assert prog.durable_view(s) is None
    r = prog.restore(prog.init_state(), None, s,
                     jnp.asarray(np.array([True, False])))
    assert int(r["rounds"][0]) == 7     # restart keeps persisted state


# --- client retry backoff ---------------------------------------------------


def test_with_errors_retries_unavailability_then_succeeds():
    from maelstrom_tpu.client import RetryPolicy, with_errors
    from maelstrom_tpu.errors import RPCError
    policy = RetryPolicy(retries=3, base_ms=0.01, cap_ms=0.02, seed=0)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RPCError(11, {"text": "no leader"})
        return {"f": "write", "type": "ok"}

    out = with_errors({"f": "write"}, set(), flaky, retry=policy)
    assert out["type"] == "ok" and len(calls) == 3


def test_with_errors_never_retries_indefinite_nonidempotent():
    from maelstrom_tpu.client import RetryPolicy, with_errors
    from maelstrom_tpu.errors import Timeout
    policy = RetryPolicy(retries=5, base_ms=0.01, seed=0)
    calls = []

    def never():
        calls.append(1)
        raise Timeout()

    # a timed-out write MAY have happened: re-issuing would double-apply
    out = with_errors({"f": "write"}, set(), never, retry=policy)
    assert out["type"] == "info" and len(calls) == 1
    # a timed-out read is safe to retry (and exhausts the budget)
    calls.clear()
    out = with_errors({"f": "read"}, {"read"}, never, retry=policy)
    assert out["type"] == "fail" and len(calls) == 6


def test_sync_client_usable_after_failed_send():
    """Regression (exposed by the kill package): a send that raises —
    e.g. node-not-found while the destination is crash-killed — must not
    leave the client stuck 'waiting', or every later op on that worker
    dies with 'Can't send more than one message at a time!'."""
    from maelstrom_tpu.client import SyncClient
    from maelstrom_tpu.errors import RPCError
    from maelstrom_tpu.net.host import HostNet
    net = HostNet()
    net.add_node("n0")
    c = SyncClient(net)
    with pytest.raises(RPCError):
        c.send("ghost", {"type": "echo"})
    assert c.send("n0", {"type": "echo"}) > 0       # still usable
    c.close()


def test_retry_policy_from_test_opts():
    from maelstrom_tpu.client import RetryPolicy
    assert RetryPolicy.from_test({}) is None
    p = RetryPolicy.from_test({"client_retries": 4,
                               "client_backoff_ms": 10,
                               "client_backoff_cap_ms": 100, "seed": 1})
    assert p.retries == 4 and p.base_ms == 10 and p.cap_ms == 100


# --- per-package smoke runs (echo = flight pool, broadcast = edges) ---------


@pytest.mark.parametrize("fault", ["partition", "kill", "pause",
                                   "duplicate", "weather"])
def test_fault_package_smoke_echo(fault):
    res = _tpu_test(7, {fault})
    assert res["valid"] is True, res["net"]


@pytest.mark.slow
@pytest.mark.parametrize("fault", ["partition", "kill", "pause",
                                   "duplicate", "weather"])
def test_fault_package_smoke_broadcast(fault):
    res = _tpu_test(7, {fault}, workload="broadcast",
                    node="tpu:broadcast", topology="grid")
    assert res["valid"] is True, (res["net"], res["workload"])
    assert res["workload"]["lost-count"] == 0


# --- weather package ---------------------------------------------------------


def test_weather_decision_stream_deterministic():
    a = nem.NemesisDecisions(NODES, seed=5)
    b = nem.NemesisDecisions(NODES, seed=5)
    fronts = [a.next_weather() for _ in range(6)]
    assert fronts == [b.next_weather() for _ in range(6)]
    assert all(f in nem.WEATHER_FRONTS for f in fronts)
    # a different seed moves the schedule
    c = nem.NemesisDecisions(NODES, seed=6)
    assert [c.next_weather() for _ in range(6)] != fronts


def test_weather_host_executor_toggles_and_restores_baseline():
    from maelstrom_tpu.net.host import HostNet
    net = HostNet(latency={"mean": 5, "dist": "constant"})
    net.p_loss = 0.01                    # the run's configured baseline
    net.latency_dist = net.latency_dist.scaled(3.0)
    ex = nem.CombinedNemesis(net, NODES, seed=1)
    r = ex.invoke({"f": "start-weather", "process": "nemesis"})
    assert r["type"] == "info" and "weather" in r["value"]
    name, p, scale = nem.NemesisDecisions(NODES, seed=1).next_weather()
    assert net.p_loss == p
    assert net.latency_dist.scale == scale
    r = ex.invoke({"f": "stop-weather", "process": "nemesis"})
    assert r["value"] == "weather cleared"
    assert net.p_loss == 0.01
    assert net.latency_dist.scale == 3.0


@pytest.mark.slow
def test_weather_tpu_history_reports_fronts_and_heals():
    """Weather fronts appear in the TPU history with their drawn values
    and the final heal restores the configured baseline on the live
    NetState (observable through a runner-level run)."""
    import json
    res = _tpu_test(13, {"weather"}, workload="broadcast",
                    node="tpu:broadcast", topology="grid",
                    p_loss=0.01, latency_scale=2.0)
    assert res["valid"] is True
    with open("/tmp/maelstrom-tpu-test-store/latest/history.jsonl") as f:
        hist = [json.loads(line) for line in f]
    starts = [o for o in hist if o.get("f") == "start-weather"
              and o["type"] == "info"]
    stops = [o for o in hist if o.get("f") == "stop-weather"
             and o["type"] == "info"]
    assert starts and stops
    # the drawn front is one of the presets, named in the op value
    assert any(name in starts[0]["value"]
               for name, _p, _s in nem.WEATHER_FRONTS), starts[0]
    # heal-before-grade: the last weather op is a stop
    last = max(starts + stops, key=lambda o: o["time"])
    assert last["f"] == "stop-weather"


def test_kill_soup_history_shows_downtime_and_recovery():
    """lin-kv under kill: ops against downed nodes fail/time out while
    the cluster stays linearizable, and post-heal ops succeed again."""
    res = _tpu_test(17, {"kill"}, workload="lin-kv", node="tpu:lin-kv",
                    time_limit=4.0)
    assert res["valid"] is True
    import json
    with open("/tmp/maelstrom-tpu-test-store/latest/history.jsonl") as f:
        hist = [json.loads(line) for line in f]
    kills = [o for o in hist if o.get("f") == "start-kill"
             and o["type"] == "info"]
    restarts = [o for o in hist if o.get("f") == "stop-kill"
                and o["type"] == "info"]
    assert kills and restarts
    # availability recovers: client oks exist after a restart (the very
    # last restart is the final-heal phase, after which no client ops
    # are generated — so gauge recovery from the first one)
    t_heal = min(o["time"] for o in restarts)
    assert any(o["type"] == "ok" and o.get("process") != "nemesis"
               and o["time"] > t_heal for o in hist)
