"""Generator combinator tests, driven by a tiny virtual-time interpreter."""

import random

from maelstrom_tpu import generators as g


def interpret(gen, processes=("w0", "w1"), max_time_s=100.0,
              complete_after_ns=1_000_000):
    """A minimal virtual-time interpreter: ops complete after a fixed
    latency; used to validate generator scheduling semantics."""
    t = 0
    free = list(processes)
    busy = []           # (completion_time, process, op)
    emitted = []
    gen = g.to_gen(gen)
    while t < max_time_s * 1e9:
        ctx = {"time": t, "free": sorted(free), "processes": list(processes)}
        res, gen = gen.op(ctx)
        if res is None:
            if not busy:
                break
        elif res == g.PENDING:
            pass
        else:
            emitted.append(res)
            free.remove(res["process"])
            busy.append((t + complete_after_ns, res["process"], res))
            continue    # try to fill remaining free workers at same time
        # advance time to next completion or +1ms
        if busy:
            busy.sort()
            t2, p, op = busy.pop(0)
            t = max(t, t2)
            free.append(p)
            gen = gen.update(
                {"time": t, "free": sorted(free),
                 "processes": list(processes)},
                {**op, "type": "ok", "time": t})
        else:
            t += 1_000_000
    return emitted


def test_seq_and_limit():
    ops = interpret(g.time_limit(10, [{"f": "echo", "value": i}
                                      for i in range(5)]))
    assert [o["value"] for o in ops] == [0, 1, 2, 3, 4]
    assert all(o["process"] in ("w0", "w1") for o in ops)


def test_each_thread():
    ops = interpret(g.each_thread({"f": "read", "final": True}))
    assert len(ops) == 2
    assert {o["process"] for o in ops} == {"w0", "w1"}


def test_stagger_rate():
    # rate 100/sec over 10s -> ~1000 ops (within statistical bounds)
    ops = interpret(g.time_limit(10, g.stagger(1 / 100,
                                               g.Repeat({"f": "read"}))))
    assert 700 < len(ops) < 1300, len(ops)


def test_mix():
    adds = ({"f": "add", "value": x} for x in range(1000))
    reads = g.Repeat({"f": "read"})
    ops = interpret(g.time_limit(5, g.mix([adds, reads])))
    fs = {o["f"] for o in ops}
    assert fs == {"add", "read"}


def test_filter():
    rng = random.Random(0)
    src = g.Fn(lambda: {"f": "add", "value": rng.randint(-5, 4)})
    ops = interpret(g.time_limit(3, g.Filter(
        lambda op: not (op["f"] == "add" and op["value"] < 0), src)))
    assert ops and all(o["value"] >= 0 for o in ops)


def test_phases_wait_for_quiescence():
    ops = interpret(g.phases(
        [{"f": "add", "value": 0}, {"f": "add", "value": 1}],
        g.sleep(1),
        g.each_thread({"f": "read", "final": True})))
    assert [o["f"] for o in ops] == ["add", "add", "read", "read"]
    # final reads must start after the sleep following both adds completing
    add_done = max(o["time"] for o in ops if o["f"] == "add")
    read_start = min(o["time"] for o in ops if o["f"] == "read")
    assert read_start >= add_done + 1e9


def test_nemesis_wrap_routing():
    nem = g.Seq([{"f": "start-partition"}, {"f": "stop-partition"}])
    cli = g.Repeat({"f": "read"})
    ops = interpret(g.time_limit(1, g.nemesis_wrap(nem, cli)),
                    processes=("w0", "w1", g.NEMESIS))
    nem_ops = [o for o in ops if o["process"] == g.NEMESIS]
    cli_ops = [o for o in ops if o["process"] != g.NEMESIS]
    assert [o["f"] for o in nem_ops] == ["start-partition", "stop-partition"]
    assert cli_ops and all(o["f"] == "read" for o in cli_ops)


def test_fn_generator_values_differ():
    rng = random.Random(42)
    src = g.Fn(lambda: {"f": "echo", "value": f"Please echo {rng.randrange(128)}"})
    ops = interpret(g.time_limit(1, src))
    assert len({o["value"] for o in ops}) > 1
