"""GSPMD sharding correctness: running the vmapped cluster round over a
("dp", "sp") device mesh must produce bit-identical results to running it
unsharded on one device. Sharding annotations change *placement*, never
semantics — XLA inserts the collectives; this pins that the spec choices
(cluster axis over dp, node/pool axes over sp) don't silently alter the
simulation. Runs on the 8 virtual CPU devices from conftest."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.net import tpu as T
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.parallel import (make_cluster_round_fn, make_cluster_sims,
                                    mesh_for, sim_shardings)

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def _build(n_nodes=8, n_clusters=4, name="broadcast"):
    nodes = [f"n{i}" for i in range(n_nodes)]
    program = get_program(
        name,
        {"topology": "grid", "max_values": 8, "latency": {"mean": 0}},
        nodes)
    cfg = T.NetConfig(n_nodes=n_nodes, n_clients=1, pool_cap=64,
                      inbox_cap=program.inbox_cap, client_cap=4)
    return program, cfg


def _inject(n_clusters, n_nodes, value, dest, name="broadcast"):
    if name == "broadcast":
        from maelstrom_tpu.nodes.broadcast import T_BCAST
        typ, a, b = T_BCAST, value, 0
    else:
        from maelstrom_tpu.nodes.raft import T_WRITE
        typ, a, b = T_WRITE, value, value
    inj = T.Msgs.empty((n_clusters, 2))
    return inj.replace(
        valid=inj.valid.at[:, 0].set(True),
        src=jnp.full_like(inj.src, n_nodes),
        dest=inj.dest.at[:, 0].set(dest),
        type=jnp.full_like(inj.type, typ),
        a=inj.a.at[:, 0].set(a),
        b=inj.b.at[:, 0].set(b))


def test_mesh_for_factorizations():
    mesh = mesh_for(8)
    assert mesh.shape["dp"] * mesh.shape["sp"] == 8
    mesh2 = mesh_for(8, dp=4)
    assert mesh2.shape["dp"] == 4 and mesh2.shape["sp"] == 2


@pytest.mark.parametrize("name,rounds", [("broadcast", 6), ("lin-kv", 30)])
def test_sharded_cluster_round_matches_unsharded(name, rounds):
    n_nodes, n_clusters = 8, 4
    program, cfg = _build(n_nodes, n_clusters, name=name)

    def run(round_fn, sims, put=None):
        for r in range(rounds):
            inj = _inject(n_clusters, n_nodes, value=r % 8,
                          dest=r % n_nodes, name=name)
            if put is not None:
                inj = jax.device_put(inj, put(inj))
            sims, _cm, _io = round_fn(sims, inj)
        return jax.device_get(sims)

    # unsharded reference
    sims0 = make_cluster_sims(program, cfg, n_clusters, seed=3)
    ref = run(make_cluster_round_fn(program, cfg), sims0)

    # sharded over the full 8-device mesh
    mesh = mesh_for(8)
    sims1 = make_cluster_sims(program, cfg, n_clusters, seed=3)
    example_inj = _inject(n_clusters, n_nodes, 0, 0, name=name)
    sims1 = jax.device_put(sims1, sim_shardings(mesh, sims1))
    round_fn = make_cluster_round_fn(program, cfg, mesh=mesh,
                                     example=sims1,
                                     example_inject=example_inj)
    with mesh:
        got = run(round_fn, sims1,
                  put=lambda inj: sim_shardings(mesh, inj))

    flat_ref, treedef_ref = jax.tree.flatten(ref)
    flat_got, treedef_got = jax.tree.flatten(got)
    assert treedef_ref == treedef_got
    for a, b in zip(flat_ref, flat_got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # sanity: the simulation did something (state moved, messages counted)
    if name == "broadcast":
        assert np.asarray(got.nodes["seen"]).any()
    else:
        assert (np.asarray(got.nodes["term"]) >= 1).any()
    assert np.asarray(got.net.stats.recv_all).sum() > 0


def test_multihost_mesh_initializes_distributed(monkeypatch):
    """With a cluster marker set, multihost_mesh must call
    jax.distributed.initialize (before touching the backend); without
    one it must not, and must fall back to the local mesh."""
    import maelstrom_tpu.parallel as par

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda coordinator_address=None, num_processes=None,
        process_id=None: calls.append(coordinator_address))

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.setattr(par, "_dist_initialized", False)
    mesh = par.multihost_mesh()
    assert calls == [] and mesh.shape["dp"] * mesh.shape["sp"] == 8

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    mesh = par.multihost_mesh()
    assert calls == [None]
    # idempotent: a second call must not re-initialize
    par.multihost_mesh()
    assert calls == [None]


def test_multihost_dcn_execution():
    """The multi-host path EXECUTED, not just compiled: two OS
    processes (4 virtual CPU devices each) join one jax.distributed
    cluster over loopback gloo — the cross-process transport shape DCN
    has on pods — build the global ("dp","sp") mesh through
    parallel.multihost_mesh, and drive the real broadcast cluster round
    with partitions + loss sharded across the process boundary. Both
    processes must report the sharded digest == their local unsharded
    digest (maelstrom_tpu.dcn_check)."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "maelstrom_tpu.dcn_check"],
        capture_output=True, text=True, timeout=580,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert r.returncode == 0, r.stdout + r.stderr
    assert '"dcn_check": "ok"' in r.stdout
