"""`--fleet N --continuous` (ISSUE 12, doc/perf.md "vectorized host
driver"): N independent OPEN-WORLD clusters — offered-rate client ops
injected inside the compiled windows while faults are live — advance in
one vmapped sched-inject scan, with the host cost amortized across the
fleet (one columnar [fleet, Q] inject tensor, one packed `inj_mids` +
reply drain, ONE host poll pass per wave).

The contract under test is the fleet runner's usual bar applied to the
continuous loop: every cluster's history is **bit-identical** to the
standalone `--continuous` run of its own option set — plain, sharded
(`--mesh 2,1`), under the combined nemesis, and across a
checkpoint/resume seam (graceful preemption in-process; the real
SIGKILL subprocess soak and the 3-workload soup are slow-marked). On
top of that, the host-poll counters must show the O(waves) claim: a
fleet's driver-level polls stay ~flat in fleet size instead of scaling
with clusters.
"""

from __future__ import annotations

import threading
import time

import pytest

from conftest import ops_projection as _ops
from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import core
from maelstrom_tpu.runner.fleet_runner import FleetRunner, run_fleet_test
from maelstrom_tpu.runner.tpu_runner import TpuRunner

LIN_KV = {"workload": "lin-kv", "node": "tpu:lin-kv", "node_count": 3,
          "rate": 10.0, "time_limit": 1.5, "recovery_s": 0.5, "seed": 11,
          "continuous": True, "timeout_ms": 1000, "audit": False}
ECHO = {"workload": "echo", "node": "tpu:echo", "node_count": 3,
        "rate": 20.0, "time_limit": 1.0, "seed": 7, "continuous": True,
        # size workers to the offered rate (doc/streams.md): emitted
        # ops reserve their worker for the window, so the capacity
        # sweep needs headroom for the ramped rates to differentiate
        "concurrency": 16, "timeout_ms": 1000, "audit": False}
KAFKA = {"workload": "kafka", "node": "tpu:kafka", "node_count": 4,
         "rate": 20.0, "time_limit": 1.5, "recovery_s": 0.5, "seed": 5,
         "kafka_groups": 2, "continuous": True, "timeout_ms": 1000,
         "audit": False}
SOUP = {"nemesis": ["kill", "pause", "partition", "duplicate"],
        "nemesis_interval": 0.4}


_SOLO_CACHE: dict = {}


def _solo(opts):
    # standalone-continuous baselines are shared across tests (runs are
    # deterministic by contract) — same memoization scheme as
    # tests/test_fleet_runner.py
    key = repr(sorted(opts.items(), key=lambda kv: repr(kv[0])))
    if key not in _SOLO_CACHE:
        test = core.build_test(dict(opts))
        # construct BEFORE the nemesis truthiness rewrite, exactly like
        # run_tpu_test: program builders sniff the fault SET (edge ring
        # headroom under `duplicate` — nodes.edge_timing)
        runner = TpuRunner(test)
        test["nemesis"] = (True if test["nemesis_pkg"]["generator"]
                           is not None else None)
        _SOLO_CACHE[key] = (runner.run(), runner)
    return _SOLO_CACHE[key]


def _fleet(opts, **fleet_over):
    test = core.build_test({**opts, **fleet_over})
    runner = FleetRunner(test)
    return runner, runner.run()


# ---------------------------------------------------------------------------
# Bit-identity: every open-world cluster == its standalone continuous run
# ---------------------------------------------------------------------------

def test_fleet_continuous_bit_identical_plain():
    """The core contract: a 2-cluster continuous lin-kv fleet equals the
    standalone continuous runs of seeds 11 and 12 op for op, and the
    fleet driver's wave count stays ~that of ONE run (host cost
    amortized, not multiplied)."""
    solos = [_solo({**LIN_KV, "seed": s})[0] for s in (11, 12)]
    runner, hs = _fleet(LIN_KV, fleet=2)
    assert len(hs[0]) > 10
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"
    # driver-level polls ~ waves ~ one cluster's window count, NOT the
    # fleet sum: the O(1)-in-fleet-size property (exact counts vary
    # with boundary interleaving, so assert the order, not a constant)
    solo_polls = [_solo({**LIN_KV, "seed": s})[1].transfer.host_polls
                  for s in (11, 12)]
    assert runner.transfer.host_polls < sum(solo_polls), (
        runner.transfer.host_polls, solo_polls)


def test_fleet_continuous_combined_nemesis_bit_identical():
    """Under the combined kill/pause/partition/duplicate soup, client
    ops keep landing INSIDE fault windows (the open-world point) and
    every cluster still replays its standalone continuous run."""
    opts = {**LIN_KV, **SOUP, "time_limit": 1.2}
    solos = [_solo({**opts, "seed": s})[0] for s in (11, 12)]
    _, hs = _fleet(opts, fleet=2)
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


@pytest.mark.multichip
def test_fleet_continuous_mesh_dp2_bit_identical():
    """`--fleet 2 --continuous --mesh 2,1`: the cluster axis sharded
    over dp while the sched-inject windows run inside the vmapped scan
    — every cluster equal to its (single-chip) standalone continuous
    run."""
    solos = [_solo({**LIN_KV, "seed": 11 + i})[0] for i in range(2)]
    runner, hs = _fleet(LIN_KV, fleet=2, mesh="2,1")
    assert runner.mesh is not None and runner.mesh.shape["dp"] == 2
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


@pytest.mark.multichip
def test_fleet_continuous_mesh_dp2_sp2_bit_identical():
    """`--fleet 2 --continuous --mesh 2,2`: the MIXED mesh with the
    sched-inject fleet scan — per-lane round-offset injection and the
    inj_mids drain run inside the shard_map manual body, and every
    cluster equals its standalone continuous run bit for bit."""
    solos = [_solo({**LIN_KV, "seed": 11 + i})[0] for i in range(2)]
    runner, hs = _fleet(LIN_KV, fleet=2, mesh="2,2")
    assert runner.mesh is not None
    assert runner.mesh.shape["dp"] == 2 and runner.mesh.shape["sp"] == 2
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"


def test_fleet_continuous_capacity_sweep():
    """`--fleet-sweep capacity` composes with --continuous: cluster i
    streams at rate * (i + 1) and equals the standalone continuous run
    at that rate."""
    solos = [_solo({**ECHO, "rate": 20.0 * (i + 1)})[0]
             for i in range(2)]
    _, hs = _fleet(ECHO, fleet=2, fleet_sweep="capacity")
    for i in range(2):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"
    assert len(hs[1]) > len(hs[0])


# ---------------------------------------------------------------------------
# Columnar vs coroutine sessions (ISSUE 17): same fleet, both backends
# ---------------------------------------------------------------------------
# The bit-identity tests above already pin the columnar path (the fleet
# default) against COROUTINE standalone baselines; these pin the
# `--sessions coroutine` fleet path directly against the columnar one,
# so the legacy backend stays alive and byte-equal.

def test_fleet_sessions_coroutine_vs_columnar_soup_bit_identical():
    opts = {**LIN_KV, **SOUP, "time_limit": 1.2}
    run_col, hs_col = _fleet(opts, fleet=2)
    run_cor, hs_cor = _fleet(opts, fleet=2, sessions="coroutine")
    assert run_col.sessions_mode == "columnar"
    assert run_cor.sessions_mode == "coroutine"
    assert run_col._session_table is not None
    assert run_cor._session_table is None
    for i in range(2):
        assert _ops(hs_col[i]) == _ops(hs_cor[i]), \
            f"cluster {i}: session backends diverged"


@pytest.mark.slow
def test_fleet_sessions_cross_backend_resume_bit_identical(tmp_path):
    """A coalesced fleet checkpoint written under COLUMNAR sessions
    resumes under COROUTINE sessions (and lands the uninterrupted
    histories): the meta shapes are the legacy ones and `sessions` is
    not a fingerprint key."""
    opts = {**KAFKA, "time_limit": 1.5, "checkpoint_every": 0.25}
    t = core.build_test({**opts, "fleet": 2})
    t["store_dir"] = str(tmp_path)
    fr = FleetRunner(t)

    def preempt_after_first_checkpoint():
        deadline = time.time() + 300
        while time.time() < deadline and not fr._preempt.is_set():
            if fr.transfer.ckpt_saves >= 1:
                fr._preempt.set()
                return
            time.sleep(0.01)
    threading.Thread(target=preempt_after_first_checkpoint,
                     daemon=True).start()
    try:
        hs = fr.run()
    except cp.Preempted:
        ck = cp.load(str(tmp_path))
        t2 = core.build_test({**opts, "fleet": 2,
                              "sessions": "coroutine"})
        t2["store_dir"] = str(tmp_path)
        cp.check_fingerprint(ck, t2)
        hs = FleetRunner(t2).run(resume=ck)
    full = _fleet({**opts, "checkpoint_every": None}, fleet=2)[1]
    for i in range(2):
        assert _ops(hs[i]) == _ops(full[i]), \
            f"cluster {i} diverged across the cross-backend seam"


# ---------------------------------------------------------------------------
# Windowed grading + the host-poll counters (run_fleet_test end to end)
# ---------------------------------------------------------------------------

def test_fleet_continuous_windowed_grading_and_polls(tmp_path):
    """The end-to-end entry point: a continuous kafka fleet grades
    every cluster through its own PR 7 windowed stream observer
    (per-cluster windows with bounded lag, cluster-tagged), the fleet
    results block carries the host-poll counters and the fleet-level
    checker-lag roll-up, and the old up-front rejection is gone."""
    test = core.build_test({**KAFKA, "fleet": 2})
    res = run_fleet_test(test, str(tmp_path))
    assert res["valid"] is True
    assert res["continuous"] is True and res["fleet"] == 2
    # host-driver poll accounting: one pass per wave, surfaced
    assert res["host-polls"] > 0
    assert res["host-poll-s"] >= 0
    # per-cluster windowed grading: each shell's pipeline saw only its
    # own rows, graded them as windows, and the lag roll-up is bounded
    # by the scan horizon
    assert "max-checker-lag-rounds" in res
    for i, c in enumerate(res["clusters"]):
        ap = c.get("analysis-pipeline")
        assert ap is not None and ap["windows"] >= 1
        assert ap["cluster"] == i
        w = c["workload"]
        assert w["valid"] is True
        assert all("verdict" in rec for rec in w["windows"])
    max_round = max(res["final-rounds"])
    assert 0 <= res["max-checker-lag-rounds"] <= max_round


def test_fleet_continuous_windowed_verdict_equals_posthoc():
    """The PR 7 equality contract holds per cluster under the fleet:
    the windowed incremental kafka verdict is bit-equal to the post-hoc
    whole-history checker (--no-overlap) for every cluster."""
    import jax

    def verdicts(**over):
        test = core.build_test({**KAFKA, "fleet": 2, **over})
        runner = FleetRunner(test)
        hs = runner.run()
        out = []
        for i, sh in enumerate(runner.shells):
            sh.sim = jax.tree.map(lambda a, i=i: a[i], runner.sim)
            t_i = sh.test
            if sh.pipeline is not None:
                t_i["analysis"] = sh.pipeline
            w = dict(t_i["workload_map"]["checker"].check(
                t_i, hs[i], {}))
            w.pop("windows", None)
            w.pop("checker-lag", None)
            out.append(w)
        return out

    windowed = verdicts()
    posthoc = verdicts(no_overlap=True)
    assert windowed == posthoc
    assert all(w["valid"] is True for w in windowed)


# ---------------------------------------------------------------------------
# Checkpoint / preemption / resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_continuous_preempt_resume_bit_identical(tmp_path):
    """Graceful preemption mid-stream: the coalesced fleet checkpoint
    carries each cluster's continuous-mode carry (scheduled-but-
    uninjected rows, drawn nemesis op) and program host state, and the
    resumed fleet lands histories bit-identical to the uninterrupted
    one — including the checkpoint-grid alignment across the seam
    (checkpoints are window boundaries in continuous mode)."""
    opts = {**KAFKA, "nemesis": ["partition"], "nemesis_interval": 0.6,
            "time_limit": 2.0, "checkpoint_every": 0.25}

    a_dir = tmp_path / "a"
    a_dir.mkdir()
    t = core.build_test({**opts, "fleet": 2})
    t["store_dir"] = str(a_dir)
    hs_a = FleetRunner(t).run()
    assert len(hs_a[0]) > 20

    b_dir = tmp_path / "b"
    b_dir.mkdir()
    t2 = core.build_test({**opts, "fleet": 2})
    t2["store_dir"] = str(b_dir)
    fr2 = FleetRunner(t2)

    def preempt_after_first_checkpoint():
        deadline = time.time() + 300
        while time.time() < deadline and not fr2._preempt.is_set():
            if fr2.transfer.ckpt_saves >= 1:
                fr2._preempt.set()
                return
            time.sleep(0.01)
    threading.Thread(target=preempt_after_first_checkpoint,
                     daemon=True).start()
    with pytest.raises(cp.Preempted):
        fr2.run()

    ck = cp.load(str(b_dir))
    t3 = core.build_test({**opts, "fleet": 2})
    t3["store_dir"] = str(b_dir)
    fr3 = FleetRunner(t3)
    cp.check_fingerprint(ck, t3)
    hs_c = fr3.run(resume=ck)
    for i in range(2):
        assert _ops(hs_c[i]) == _ops(hs_a[i]), \
            f"cluster {i} diverged after resume"


@pytest.mark.slow
def test_fleet_continuous_sigkill_resume_byte_identical(tmp_path):
    """Real SIGKILL, real subprocess: a --fleet 2 --continuous run
    killed after its first coalesced checkpoint and resumed with
    --resume lands byte-identical history.jsonl and verdict-identical
    results.json against the uninterrupted fleet baseline."""
    import os
    import random

    from maelstrom_tpu import crash_soak

    opts = {"-w": "lin-kv", "--node": "tpu:lin-kv", "--node-count": "3",
            "--rate": "10", "--time-limit": "4", "--seed": "16",
            "--continuous": True,
            "--nemesis": "partition", "--nemesis-interval": "1",
            "--checkpoint-every": "0.5", "--fleet": "2"}
    root = str(tmp_path / "baseline")
    baseline = crash_soak.run_once(root, opts,
                                   os.path.join(str(tmp_path),
                                                "baseline.log"))
    res = crash_soak.run_with_kills(str(tmp_path / "killed"), opts,
                                    kills=1, rng=random.Random(5),
                                    kill_jitter_s=0.2)
    assert len(res["kills"]) == 1, res
    verdict = crash_soak.compare_runs(baseline, res["dir"])
    assert verdict["history_identical"], verdict
    assert verdict["results_identical"], verdict


# ---------------------------------------------------------------------------
# The 3-workload open-world soup (the acceptance trio, slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("opts,seeds", [
    ({**LIN_KV, **SOUP, "time_limit": 2.0}, (11, 12)),
    ({**KAFKA, **SOUP, "time_limit": 2.0}, (5, 6)),
    ({**ECHO, **SOUP, "time_limit": 1.5, "recovery_s": 0.5}, (7, 8)),
])
def test_fleet_continuous_soup_bit_identical_all_workloads(opts, seeds):
    """Raft-backed lin-kv, streaming kafka (consumer groups), and echo
    fleets under the combined nemesis with --continuous: every cluster
    bit-identical to its standalone open-world run."""
    solos = [_solo({**opts, "seed": s})[0] for s in seeds]
    _, hs = _fleet({**opts, "seed": seeds[0]}, fleet=len(seeds))
    for i in range(len(seeds)):
        assert _ops(hs[i]) == _ops(solos[i]), f"cluster {i} diverged"
