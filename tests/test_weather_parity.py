"""--p-loss / --latency-scale path parity (ISSUE 7 satellite): the
same option keys install the same values on the host network and the
TPU NetState — including explicit zeros — so a `--bin` run and a
`--node tpu:` run of identical flags see the same network model."""

import jax

from maelstrom_tpu import core
from maelstrom_tpu.runner.tpu_runner import TpuRunner


def _host_net(**opts):
    test = core.build_test(dict(
        workload="echo", bin="demo/python/echo.py", node_count=3,
        **opts))
    return test["net"]


def _tpu_net(**opts):
    test = core.build_test(dict(
        workload="echo", node="tpu:echo", node_count=3, **opts))
    return TpuRunner(test).sim.net


def test_p_loss_and_latency_scale_flow_to_both_paths():
    host = _host_net(p_loss=0.25, latency_scale=3.0,
                     latency={"mean": 4, "dist": "constant"})
    tpu = _tpu_net(p_loss=0.25, latency_scale=3.0,
                   latency={"mean": 4, "dist": "constant"})
    assert host.p_loss == 0.25
    assert host.latency_dist.scale == 3.0
    assert float(jax.device_get(tpu.p_loss)) == 0.25
    assert float(jax.device_get(tpu.latency_scale)) == 3.0


def test_explicit_zero_p_loss_installs_on_both_paths():
    # the old code gated on truthiness: an explicit 0.0 was skipped on
    # the host path while defaults differed — both must install
    host = _host_net(p_loss=0.0)
    tpu = _tpu_net(p_loss=0.0)
    assert host.p_loss == 0.0
    assert float(jax.device_get(tpu.p_loss)) == 0.0


def test_default_baselines_match():
    host = _host_net()
    tpu = _tpu_net()
    assert host.p_loss == 0.0
    assert host.latency_dist.scale == 1.0
    assert float(jax.device_get(tpu.p_loss)) == 0.0
    assert float(jax.device_get(tpu.latency_scale)) == 1.0


def test_latency_scale_scales_host_draws():
    import random
    host = _host_net(latency_scale=10.0,
                     latency={"mean": 2, "dist": "constant"})
    assert host.latency_dist.draw(random.Random(0)) == 20.0
