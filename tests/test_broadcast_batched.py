"""Batched atomic broadcast (ISSUE 9, doc/perf.md): the distilled-batch
node, the columnar batch assembler, the expansion-proof checker —
adversarial fixtures each a definite fail, batched-vs-unbatched verdict
bit-equality on seeded soups — plus mesh and nemesis composition and the
net-layer units accounting."""

from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import ops_projection as _ops
from maelstrom_tpu import core
from maelstrom_tpu import generators as g
from maelstrom_tpu.checkers.set_full import (BatchedBroadcastChecker,
                                             BroadcastChecker,
                                             expand_batched_history,
                                             verify_batch_proofs)
from maelstrom_tpu.history import History
from maelstrom_tpu.net import tpu as T
from maelstrom_tpu.nodes import EncodeCapacityError, Intern, get_program
from maelstrom_tpu.nodes.broadcast_batched import (T_BATCH,
                                                   range_checksum)
from maelstrom_tpu.runner.tpu_runner import TpuRunner
from maelstrom_tpu.sim import make_run_fn, make_sim

STORE = "/tmp/maelstrom-tpu-test-store"


# --- the columnar distiller (generators.BatchCounting) ----------------------


def _ctx(free=(0, 1), t=0):
    return {"time": t, "free": list(free), "processes": list(free)}


def test_batch_counting_distills_sorted_dedup_contiguous():
    gen = g.BatchCounting(batch_max=8, dup_rate=0.9, seed=3)
    seen, raw_total = [], 0
    for _ in range(20):
        res, gen = gen.op(_ctx())
        vals = res["value"]
        assert vals == sorted(set(vals))            # deduped + sorted
        # fresh sequential values: each batch continues where the
        # previous ended (contiguity is what id-compression relies on)
        assert vals[0] == (seen[-1] + 1 if seen else 0)
        assert vals == list(range(vals[0], vals[0] + len(vals)))
        # the raw (pre-distill) stream was at-least-once: dup_rate=0.9
        # makes raw-count > len(vals) on most draws
        assert res["raw-count"] >= len(vals)
        raw_total += res["raw-count"]
        seen.extend(vals)
    # distillation never leaks a duplicate downstream, and at dup_rate
    # 0.9 over 20 batches the raw stream definitely contained some
    assert len(seen) == len(set(seen))
    assert raw_total > len(seen)        # dedup actually collapsed work


def test_batch_counting_pending_poll_is_rng_neutral_and_picklable():
    gen = g.BatchCounting(batch_max=8, dup_rate=0.5, seed=7)
    # PENDING polls (no free worker) must not advance the stream
    res, gen2 = gen.op(_ctx(free=()))
    assert res == g.PENDING
    r1, _ = gen2.op(_ctx())
    gen_b = g.BatchCounting(batch_max=8, dup_rate=0.5, seed=7)
    r2, _ = gen_b.op(_ctx())
    assert r1["value"] == r2["value"]
    # checkpointable: the generator tree pickles round trip
    blob = pickle.dumps(gen2)
    r3, _ = pickle.loads(blob).op(_ctx())
    assert r3["value"] == r1["value"]


# --- wire encode guards ------------------------------------------------------


def _program(n=9, **opts):
    o = {"topology": "grid", "max_values": 64, "latency": {"mean": 0}}
    o.update(opts)
    return get_program("broadcast-batched", o,
                       [f"n{i}" for i in range(n)])


def test_encode_rejects_malformed_batches():
    p = _program()
    intern = Intern()
    t, a, b, c = p.encode_body({"type": "batch", "values": [0, 1, 2]},
                               intern)
    assert (t, a, b) == (T_BATCH, 0, 3) and c == range_checksum(0, 3)
    with pytest.raises(EncodeCapacityError, match="duplicate"):
        p.encode_body({"type": "batch", "values": [3, 3]}, Intern())
    with pytest.raises(EncodeCapacityError, match="contiguous"):
        # ids 0 and 2 fresh-interned in this order are contiguous, so
        # force a gap through a pre-populated table
        i2 = Intern()
        i2.id(0), i2.id(1), i2.id(2)
        p.encode_body({"type": "batch", "values": [0, 2]}, i2)
    with pytest.raises(EncodeCapacityError, match="empty"):
        p.encode_body({"type": "batch", "values": []}, Intern())


# --- device protocol ---------------------------------------------------------


def _converge(prog, n, inject_rows, rounds=64):
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=64,
                      inbox_cap=prog.inbox_cap, client_cap=8,
                      unit_words=tuple(prog.unit_words))
    sim = make_sim(prog, cfg, seed=0)
    run_fn = make_run_fn(prog, cfg, collect_client_msgs=True)
    plan = T.Msgs.empty((rounds, 1))
    for r0, (lo, nn) in enumerate(inject_rows):
        plan = plan.replace(
            valid=plan.valid.at[r0, 0].set(True),
            src=plan.src.at[r0, 0].set(n),
            dest=plan.dest.at[r0, 0].set((lo * 7) % n),
            type=plan.type.at[r0, 0].set(T_BATCH),
            a=plan.a.at[r0, 0].set(lo),
            b=plan.b.at[r0, 0].set(nn),
            c=plan.c.at[r0, 0].set(range_checksum(lo, nn)))
    sim2, cms = run_fn(sim, plan)
    return sim2, cms


def test_range_gossip_converges_with_fewer_messages_and_exact_proofs():
    n, V = 9, 64
    prog = _program(n=n)
    sim2, cms = _converge(prog, n, [(0, 16), (16, 16), (32, 8)])
    seen = np.asarray(jax.device_get(sim2.nodes["seen"][:, :40]))
    assert seen.all()
    st = T.stats_dict(sim2.net)
    # one range message moves a whole run: total messages stay far
    # below the 40 values x 12 grid edges an eager per-value flood pays
    assert st["recv_all"] < 40 * 12
    # units booked: every delivered range counts its op payload
    assert st["recv_units"] > st["recv_all"]
    # each batch ack carries the exact expansion proof
    v = np.asarray(cms.valid)
    acks = [(int(cms.a[r, j]), int(cms.b[r, j]), int(cms.c[r, j]))
            for r, j in np.argwhere(v)
            if int(cms.type[r, j]) == 21]
    assert sorted(acks) == [
        (0, 16, range_checksum(0, 16)),
        (16, 16, range_checksum(16, 16)),
        (32, 8, range_checksum(32, 8))]


def test_eager_resend_mode_converges_too():
    n = 9
    prog = _program(n=n, eager_resend=True)
    sim2, _ = _converge(prog, n, [(0, 32)])
    assert np.asarray(jax.device_get(sim2.nodes["seen"][:, :32])).all()


# --- expansion-proof checker: adversarial fixtures ---------------------------


def _batch_pair(h, proc, t0, vals, lo=None, n=None, proof=None,
                expanded=None):
    lo = vals[0] if lo is None else lo
    n = len(vals) if n is None else n
    proof = range_checksum(lo, n) if proof is None else proof
    expanded = list(vals) if expanded is None else expanded
    h.append_row("invoke", "broadcast-batch", list(vals), proc, t0)
    h.append_row("ok", "broadcast-batch",
                 {"lo": lo, "n": n, "proof": proof,
                  "expanded": expanded}, proc, t0 + 1)


def _read_pair(h, proc, t0, vals):
    h.append_row("invoke", "read", None, proc, t0)
    h.append_row("ok", "read", list(vals), proc, t0 + 1, None, True)


def _fixture(mutate=None):
    h = History()
    _batch_pair(h, 0, 0, [0, 1, 2])
    _batch_pair(h, 1, 10, [3, 4])
    _read_pair(h, 2, 20, [0, 1, 2, 3, 4])
    if mutate:
        mutate(h)
    return h


def _errs(h):
    errors, _stats = verify_batch_proofs(h)
    return sorted(e["error"] for e in errors)


def test_clean_fixture_passes_and_grades():
    res = BatchedBroadcastChecker().check({}, _fixture())
    assert res["valid"] is True
    assert res["proof-errors"] == []
    assert res["batch-count"] == 2
    assert res["batched-op-count"] == 5
    assert res["stable-count"] == 5


def test_forged_count_is_definite_fail():
    def mutate(h):
        _batch_pair(h, 0, 30, [5, 6, 7], n=9)
    res = BatchedBroadcastChecker().check({}, _fixture(mutate))
    assert res["valid"] is False
    assert "forged-count" in [e["error"] for e in res["proof-errors"]]


def test_truncated_batch_is_definite_fail():
    def mutate(h):
        # the server acked fewer values than the batch claimed
        _batch_pair(h, 0, 30, [5, 6, 7], expanded=[5, 6], n=3)
    assert "truncated-batch" in _errs(_fixture(mutate))


def test_duplicated_id_inside_batch_is_definite_fail():
    def mutate(h):
        _batch_pair(h, 0, 30, [5, 5, 6])
    errs = _errs(_fixture(mutate))
    assert "duplicate-in-batch" in errs


def test_forged_proof_is_definite_fail():
    def mutate(h):
        _batch_pair(h, 0, 30, [5, 6], proof=12345)
    assert "forged-proof" in _errs(_fixture(mutate))


def test_replayed_batch_is_definite_fail():
    """The at-least-once hazard the `duplicate` nemesis models: the
    same distilled range acknowledged twice."""
    def mutate(h):
        _batch_pair(h, 0, 30, [0, 1, 2])        # same range as t=0
    errs = _errs(_fixture(mutate))
    assert "replayed-batch" in errs
    res = BatchedBroadcastChecker().check({}, _fixture(mutate))
    assert res["valid"] is False


def test_lost_batched_value_fails_through_setfull():
    """A value acked inside a batch but absent from every later read is
    data loss — surfaced by the expanded set-full fold, exactly as the
    unbatched checker would."""
    h = History()
    _batch_pair(h, 0, 0, [0, 1, 2])
    _read_pair(h, 1, 10, [0, 2])                # 1 vanished
    _read_pair(h, 2, 20, [0, 2])
    res = BatchedBroadcastChecker().check({}, h)
    assert res["valid"] is False
    assert res["lost"] == [1]
    assert res["proof-errors"] == []            # proofs were fine


# --- batched-vs-unbatched verdict bit-equality -------------------------------


def _run(tmp_path, **over):
    opts = {"workload": "broadcast-batched",
            "node": "tpu:broadcast-batched", "node_count": 5,
            "topology": "grid", "rate": 20.0, "time_limit": 2.0,
            "recovery_s": 0.5, "seed": 11, "journal_rows": False,
            "store_root": str(tmp_path), "audit": False}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(tmp_path)
    runner = TpuRunner(test)
    history = runner.run()
    return runner, history, test


SETFULL_KEYS = ("valid", "attempt-count", "acknowledged-count",
                "stable-count", "lost-count", "lost", "never-read-count",
                "never-read", "stale-count", "stale", "worst-stale",
                "duplicated-count", "duplicated", "stable-latencies")


def test_verdict_bit_equal_to_unbatched_checker_on_seeded_soup(tmp_path):
    """The acceptance pin: on a real seeded run, the batched checker's
    set-full section is bit-equal (dict equality, every field) to the
    stock BroadcastChecker run over the expanded op stream."""
    _runner, history, test = _run(tmp_path)
    batched = BatchedBroadcastChecker().check(test, history)
    unbatched = BroadcastChecker().check(
        test, expand_batched_history(history))
    assert {k: batched[k] for k in SETFULL_KEYS} == \
        {k: unbatched[k] for k in SETFULL_KEYS}
    assert batched["valid"] is True
    assert batched["stable-count"] == batched["batched-op-count"] > 0


def test_verdict_bit_equal_under_combined_nemesis(tmp_path):
    """Same pin under --nemesis kill,partition,duplicate: proofs hold
    and the expanded grade equals the stock checker's."""
    _runner, history, test = _run(
        tmp_path, time_limit=3.0, recovery_s=2.0,
        nemesis={"kill", "partition", "duplicate"},
        nemesis_interval=0.8, seed=13)
    batched = BatchedBroadcastChecker().check(test, history)
    unbatched = BroadcastChecker().check(
        test, expand_batched_history(history))
    assert {k: batched[k] for k in SETFULL_KEYS} == \
        {k: unbatched[k] for k in SETFULL_KEYS}
    assert batched["proof-errors"] == []
    assert batched["lost-count"] == 0


# --- e2e + composition -------------------------------------------------------


def test_batched_broadcast_tpu_e2e():
    res = core.run(dict(store_root=STORE, seed=7, rate=20.0,
                        time_limit=2.0, journal_rows=False,
                        workload="broadcast-batched",
                        node="tpu:broadcast-batched",
                        node_count=5, topology="grid", audit=False))
    w = res["workload"]
    assert res["valid"] is True, w
    assert w["valid"] is True
    assert w["proof-errors"] == []
    assert w["stable-count"] == w["batched-op-count"] > 0
    # batching on the wire: far fewer messages than client-op units
    net = res["net"]
    assert net["recv-units"] > net["all"]["recv-count"] > 0
    assert net["units-per-msg"] > 1.0


@pytest.mark.multichip
def test_batched_broadcast_mesh_bit_identical(tmp_path):
    """`--mesh 1,2` changes placement only: same-seed sharded runs are
    op-for-op identical and grade identically."""
    _r1, h1, t1 = _run(tmp_path / "a")
    r2, h2, t2 = _run(tmp_path / "b", mesh="1,2")
    assert len(h1) > 10
    assert _ops(h1) == _ops(h2)
    assert r2.mesh is not None and r2.mesh.shape["sp"] == 2
    assert BatchedBroadcastChecker().check(t1, h1) == \
        BatchedBroadcastChecker().check(t2, h2)


@pytest.mark.slow
def test_batched_broadcast_full_soup_and_mesh_nemesis(tmp_path):
    """Heavy composition: the combined five-fault soup, plain and
    sharded, stays valid with zero proof errors and zero losses."""
    for sub, mesh in ((tmp_path / "p", None), (tmp_path / "m", "1,2")):
        res = core.run(dict(
            store_root=str(sub), seed=17, rate=25.0, time_limit=4.0,
            recovery_s=2.0, journal_rows=False,
            workload="broadcast-batched",
            node="tpu:broadcast-batched", node_count=5,
            topology="grid", mesh=mesh, audit=False,
            nemesis={"kill", "pause", "partition", "duplicate"},
            nemesis_interval=0.9))
        w = res["workload"]
        assert res["valid"] is True, (mesh, w)
        assert w["proof-errors"] == [] and w["lost-count"] == 0


# --- net-layer units parity --------------------------------------------------


def test_hostnet_units_parity():
    """The host net books the same batch-units convention as the TPU
    net: a body with `batch_units: n` is one message carrying n ops."""
    from maelstrom_tpu.net.host import HostNet
    net = HostNet()
    net.add_node("n0"), net.add_node("n1")
    net.send({"src": "n0", "dest": "n1",
              "body": {"type": "x", "msg_id": 1, "batch_units": 5}})
    net.send({"src": "n1", "dest": "n0",
              "body": {"type": "y", "msg_id": 2}})
    assert net.sent_units == 6
    assert net.batched_msgs == 1
    assert net.recv("n1", 10).body["batch_units"] == 5
    assert net.recv_units == 5
