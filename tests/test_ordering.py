"""OrderedStream (ISSUE 15, doc/ordering.md): the pluggable ordering
layer — engine adapters over raft / compartment / batched broadcast,
deterministic appliers for lin-kv / kafka / txn-list-append, the
`--ordering` CLI axis, the shared fleet grader pool, and the
compartment's client-side leader lease.

Budget note: the e2e combination matrix runs TINY configs (a couple of
virtual seconds each) — the point is that every (engine x applier)
pair runs end to end and grades valid with the STOCK checkers, not
that it soaks. The combined-nemesis soup on a new combination is
slow-marked."""

import hashlib
import os

import pytest

from maelstrom_tpu import core
from maelstrom_tpu.nodes import EncodeCapacityError, Intern, get_program
from maelstrom_tpu.ordering import (get_applier, make_ordered,
                                    ordered_node_count)
from maelstrom_tpu.ordering.appliers import (KafkaApplier, LinKVApplier,
                                             TxnListAppendApplier)

STORE = "/tmp/maelstrom-ordering-store"
NODES5 = [f"n{i}" for i in range(5)]


def run(opts):
    base = dict(store_root=STORE, seed=7, rate=12.0, time_limit=1.6,
                journal_rows=False, audit=False)
    return core.run({**base, **opts})


def hist_md5():
    with open(os.path.join(STORE, "latest", "history.jsonl"), "rb") as f:
        return hashlib.md5(f.read()).hexdigest()


# --- applier units (the pure services machines are the oracles) ----------

def test_linkv_applier_matches_dict_model():
    import random
    ap = LinKVApplier({})
    st = ap.init_state()
    model = {}
    rng = random.Random(42)
    for i in range(300):
        k = rng.randrange(4)
        r = rng.random()
        if r < 0.4:
            op = {"f": "read", "value": [k, None], "process": 0}
        elif r < 0.7:
            op = {"f": "write", "value": [k, rng.randrange(5)],
                  "process": 0}
        else:
            op = {"f": "cas", "value": [k, [rng.randrange(5),
                                            rng.randrange(5)]],
                  "process": 0}
        st, res = ap.apply(st, ap.command(op))
        done = ap.completed(op, res)
        if op["f"] == "read":
            if k in model:
                assert done["type"] == "ok"
                assert done["value"] == [k, model[k]]
            else:
                assert done["type"] == "fail"
                assert done["error"][0] == "key-does-not-exist"
        elif op["f"] == "write":
            model[k] = op["value"][1]
            assert done["type"] == "ok"
        else:
            frm, to = op["value"][1]
            if k not in model:
                assert done["type"] == "fail"
                assert done["error"][0] == "key-does-not-exist"
            elif model[k] == frm:
                model[k] = to
                assert done["type"] == "ok"
            else:
                assert done["type"] == "fail"
                assert done["error"][0] == "precondition-failed"


def test_kafka_applier_replay_semantics():
    ap = KafkaApplier({})
    st = ap.init_state()
    # sends assign dense offsets per key
    for i, (k, m) in enumerate([(0, "a"), (0, "b"), (1, "c")]):
        st, res = ap.apply(st, ["send", k, m])
        done = ap.completed({"f": "send", "value": [k, m]}, res)
        assert done["value"][2] == (i if k == 0 else 0)
    # polls observe the full prefix and raise the session floors
    st, res = ap.apply(st, ["poll"])
    done = ap.completed({"f": "poll"}, res)
    assert done["value"] == {"0": [[0, "a"], [1, "b"]], "1": [[0, "c"]]}
    assert ap._polled == {"0": 1, "1": 0}
    # commits claim exactly the polled floors and are monotone
    claim = ap.command({"f": "commit"})
    st, res = ap.apply(st, claim)
    st, res2 = ap.apply(st, ["commit", {"0": 0}])    # stale re-claim
    st, res3 = ap.apply(st, ["list"])
    assert res3[1] == {"0": 1, "1": 0}
    # host session state rides checkpoints
    view = ap.host_view()
    ap2 = KafkaApplier({})
    ap2.restore(view)
    assert ap2._polled == ap._polled


def test_txn_applier_reuses_welded_interpreter():
    ap = TxnListAppendApplier({})
    st = ap.init_state()
    st, out = ap.apply(st, ["txn", [["append", 1, 7], ["r", 1, None]]])
    assert out == [["append", 1, 7], ["r", 1, [7]]]


def test_applier_registry_rejects_unserved_workloads():
    with pytest.raises(ValueError, match="no applier"):
        get_applier("broadcast", {})
    with pytest.raises(ValueError, match="--ordering"):
        make_ordered({"ordering": "gossip", "workload": "lin-kv"},
                     NODES5)


# --- stream boundary units -----------------------------------------------

def _batched(opts=None):
    return make_ordered({"ordering": "batched", "workload": "lin-kv",
                         "rate": 5, "time_limit": 1, **(opts or {})},
                        NODES5)


def test_proposals_are_stable_across_reencode():
    prog = _batched()
    intern = Intern()
    op = {"f": "write", "value": [1, 3], "process": 2}
    w1 = prog.encode_body(prog.request_for_op(op), intern)
    # a redirect requeue re-encodes the SAME op: same seq, same words
    w2 = prog.encode_body(prog.request_for_op(op), intern)
    assert w1 == w2
    # a DIFFERENT op (even with identical content) gets a fresh command
    op2 = {"f": "write", "value": [1, 3], "process": 4}
    w3 = prog.encode_body(prog.request_for_op(op2), intern)
    assert w3 != w1
    assert len(intern) == 2


def test_capacity_exhaustion_fails_definitely():
    prog = _batched({"max_values": 2})
    intern = Intern()
    for p in range(2):
        prog.encode_body(prog.request_for_op(
            {"f": "read", "value": [p, None], "process": p}), intern)
    with pytest.raises(EncodeCapacityError, match="max-values"):
        prog.encode_body(prog.request_for_op(
            {"f": "read", "value": [9, None], "process": 9}), intern)


def test_duplicate_delivery_applies_once():
    prog = _batched()
    intern = Intern()
    op = {"f": "write", "value": [0, 4], "process": 0}
    prog.encode_body(prog.request_for_op(op), intern)
    prog._apply_cid(0, intern)
    st1 = prog._app_state
    prog._apply_cid(0, intern)      # duplicate-nemesis re-delivery
    assert prog._app_state is st1   # at-most-once: no second apply


def test_host_state_roundtrip_preserves_stream_session():
    prog = make_ordered({"ordering": "raft", "workload": "kafka",
                         "rate": 5, "time_limit": 1}, NODES5)
    intern = Intern()
    prog.encode_body(prog.request_for_op({"f": "poll", "process": 0}),
                     intern)
    prog.applier._polled = {"0": 3}
    st = prog.host_state()
    prog2 = make_ordered({"ordering": "raft", "workload": "kafka",
                          "rate": 5, "time_limit": 1}, NODES5)
    prog2.set_host_state(st)
    assert prog2._oseq == 1
    assert prog2.applier._polled == {"0": 3}


def test_ordering_axis_wiring():
    # the compartment engine sizes the cluster from --roles
    assert ordered_node_count({"ordering": "compartment"}) == 9
    assert ordered_node_count({"ordering": "batched"}) is None
    nodes = core.parse_nodes({"node": "tpu:ordered",
                              "ordering": "compartment",
                              "roles": "proxies=1,acceptors=1x2,"
                                       "replicas=1"})
    assert len(nodes) == 5
    # get_program resolves the composed spec
    prog = get_program("ordered", {"ordering": "batched",
                                   "workload": "txn-list-append",
                                   "rate": 5, "time_limit": 1}, NODES5)
    assert prog.stream_engine == "batched"
    assert prog.applier.name == "txn-list-append"
    # an explicit conflicting --node is a config error
    with pytest.raises(ValueError, match="tpu:ordered"):
        core.build_test({"ordering": "raft", "node": "tpu:lin-kv"})


# --- shared fleet grader pool --------------------------------------------

def _feed_rows(pipe):
    from maelstrom_tpu.history import History
    h = History()
    t = 0
    lo = 0
    for seg in range(4):
        for i in range(6):
            p = i % 3
            h.append_row("invoke", "write", [0, i], p, t)
            t += 1
            h.append_row("ok", "write", [0, i], p, t)
            t += 1
        pipe.feed(h, lo, len(h))
        lo = len(h)
    pipe.finish()
    return h


def test_pooled_pipeline_bit_equal():
    """The shared AnalysisPool path produces bit-identical analysis to
    the dedicated-thread path (the fleet 512 default-posture pin)."""
    from maelstrom_tpu.checkers.pipeline import (AnalysisPipeline,
                                                 AnalysisPool)
    threaded = AnalysisPipeline(workers=1)
    h1 = _feed_rows(threaded)
    pool = AnalysisPool(workers=3)
    try:
        pooled = AnalysisPipeline(workers=1, pool=pool)
        h2 = _feed_rows(pooled)
    finally:
        pool.close()
    assert threaded.error is None and pooled.error is None
    pt = threaded.register_partitions(len(h1))
    pp = pooled.register_partitions(len(h2))
    assert pt is not None and pp is not None
    assert len(pt) == len(pp) == 1
    (k1, a1, s1), (k2, a2, s2) = pt[0], pp[0]
    assert k1 == k2 and s1 == s2
    for f in a1:
        assert list(a1[f]) == list(a2[f])
    rt = {k: v for k, v in threaded.report().items() if k != "busy-s"}
    rp = {k: v for k, v in pooled.report().items() if k != "busy-s"}
    assert rt == rp


def test_pool_preserves_per_pipeline_order():
    """Many pipelines multiplexed over few workers: per-pipeline
    segment order (and hence analysis state) is preserved."""
    from maelstrom_tpu.checkers.pipeline import (AnalysisPipeline,
                                                 AnalysisPool)
    pool = AnalysisPool(workers=2)
    try:
        pipes = [AnalysisPipeline(workers=1, pool=pool)
                 for _ in range(8)]
        hs = [_feed_rows(p) for p in pipes]
    finally:
        pool.close()
    for p, h in zip(pipes, hs):
        assert p.error is None
        assert p.rows == len(h)
        assert p.segments == 4


# --- client-side leader lease --------------------------------------------

def _compartment(roles, **opts):
    from maelstrom_tpu.nodes.compartment import roles_node_count
    return get_program("compartment",
                       {"roles": roles, "rate": 5, "time_limit": 1,
                        **opts},
                       [f"n{i}" for i in range(roles_node_count(roles))])


def test_lease_rotates_off_a_silent_leader():
    prog = _compartment("sequencers=3,proxies=1,acceptors=1x2,"
                        "replicas=1", election_timeout_rounds=20)
    assert prog._lease_rounds == 40          # 2x the election timeout
    prog.observe_round(10)
    assert prog.node_for_op({"f": "read"}) == 0
    # replies from the guess renew the lease
    prog.note_reply(0, 30)
    prog.observe_round(60)
    assert prog.node_for_op({}) == 0         # 60 - 30 <= 40: held
    # silence past the lease rotates to the next candidate, re-armed
    prog.observe_round(120)
    assert prog.node_for_op({}) == 1
    assert prog.node_for_op({}) == 1         # one probe per window
    # a redirect hint is lease evidence for the hinted node
    prog.note_leader(2)
    assert prog.node_for_op({}) == 2
    # lease state rides host_state (resume determinism)
    st = prog.host_state()
    assert st["lease"] == [120, 120]


def test_lease_is_inert_on_the_stable_sequencer():
    prog = _compartment("proxies=2,acceptors=2x2,replicas=2")
    assert prog._lease_rounds == 0
    prog.observe_round(10_000)
    assert prog.node_for_op({}) == 0         # never rotates (S == 1)


def test_lease_disabled_by_zero():
    prog = _compartment("sequencers=2,proxies=1,acceptors=1x2,"
                        "replicas=1", leader_lease_ms=0)
    assert prog._lease_rounds == 0


# --- the combination matrix, end to end ----------------------------------
# >= 6 (engine x applier) pairs run via --ordering and grade valid with
# the STOCK checkers (acceptance criterion); tiny configs, see module
# docstring.

@pytest.mark.parametrize("workload,engine", [
    ("lin-kv", "raft"),
    ("lin-kv", "compartment"),
    ("lin-kv", "batched"),
    ("kafka", "raft"),
    ("kafka", "compartment"),
    ("txn-list-append", "batched"),
])
def test_combination_grades_valid(workload, engine):
    res = run({"workload": workload, "ordering": engine,
               "name": f"{workload}-over-{engine}"})
    assert res["valid"] is True, res
    assert res["workload"]["valid"] is True


@pytest.mark.multichip
def test_ordered_mesh_identity():
    """A composed program under --mesh 1,2 is byte-identical to plain
    (the role-partitioned compartment engine exercises the role-aware
    state_row extraction on the sharded path)."""
    run({"workload": "lin-kv", "ordering": "compartment",
         "name": "mesh-plain"})
    h1 = hist_md5()
    run({"workload": "lin-kv", "ordering": "compartment",
         "name": "mesh-sharded", "mesh": "1,2"})
    assert hist_md5() == h1


# --- legacy welded paths: unchanged by the extraction --------------------
# Digest pins recorded at the extraction PR: the raft, compartment, and
# batched-broadcast device programs were not touched, so these seeds'
# histories must stay byte-identical (plain; the mesh-vs-plain identity
# of the same paths is pinned by test_sharded_runner /
# test_compartment / test_broadcast_batched).

LEGACY_PINS = [
    ({"workload": "lin-kv", "node": "tpu:lin-kv", "name": "legacy-raft"},
     "329c018996ee21daa5eb5f9f901391e5"),
    ({"workload": "lin-kv", "node": "tpu:compartment",
      "name": "legacy-compartment"},
     "0faa6484d6fcd53ae65a040fb60bf7ee"),
    ({"workload": "broadcast-batched", "node": "tpu:broadcast-batched",
      "name": "legacy-batched"},
     "8a5297c46f38c492b8f3525d55ad3af5"),
]


@pytest.mark.parametrize("opts,digest", LEGACY_PINS)
def test_legacy_history_digest_unchanged(opts, digest):
    res = run(opts)
    assert res["valid"] is True
    assert hist_md5() == digest


# --- slow: combined-nemesis soup on a NEW combination --------------------

@pytest.mark.slow
def test_soup_kafka_over_elected_compartment():
    """kafka partitions over the ELECTED compartment slot sequence
    under the combined kill/pause/partition/duplicate soup with
    sequencer-targeted kills: failovers happen mid-stream and the
    stock kafka checker still grades the expanded history valid."""
    res = run({"workload": "kafka", "ordering": "compartment",
               "roles": "sequencers=2,proxies=2,acceptors=2x2,"
                        "replicas=2",
               "rate": 20.0, "time_limit": 4.0, "timeout_ms": 400,
               "nemesis": {"kill", "pause", "partition", "duplicate"},
               "nemesis_interval": 0.8,
               "nemesis_targets": "kill=sequencer",
               "recovery_s": 2, "name": "soup-kafka-compartment"})
    assert res["workload"]["valid"] is True, res["workload"]
    assert res["valid"] is True, res
