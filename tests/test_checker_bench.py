"""Checker-throughput micro-benches (`checker_bench` marker).

Auto-skipped in tier-1 (see conftest): these measure the analysis
pipeline's register fast path and Elle edge build against their
pure-Python baselines on shrunk synthetic histories, asserting the
fast paths stay (a) correct and (b) actually faster. The full-size 1M
numbers ride bench.py's BENCH json (`checker` section); run these with
MAELSTROM_CHECKER_BENCH=1 pytest -m checker_bench."""

import os
import sys

import pytest

pytestmark = pytest.mark.checker_bench

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _record(n):
    import bench
    return bench.bench_checkers_record(n_rows=n, elle_ops=n)


def test_register_fast_path_beats_baseline():
    r = _record(120_000)["register"]
    assert r["verdicts_match"] is True
    assert r["valid"] is True
    # 5x is the acceptance bar at 1M ops; at this shrunk size fixed
    # overheads bite harder, so require a conservative 2x
    assert r["speedup"] >= 2.0, r


def test_elle_edge_build_matches_and_beats_baseline():
    r = _record(120_000)["elle"]
    assert r["match"] is True
    assert r["speedup"] >= 1.0, r


def test_full_record_shape():
    r = _record(40_000)
    assert r["valid"] is True
    for section in ("register", "elle"):
        assert r[section]["speedup"] > 0
