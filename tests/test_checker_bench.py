"""Checker-throughput micro-benches (`checker_bench` marker).

Auto-skipped in tier-1 (see conftest): these measure the analysis
pipeline's register fast path and Elle edge build against their
pure-Python baselines on shrunk synthetic histories, asserting the
fast paths stay (a) correct and (b) actually faster. The full-size 1M
numbers ride bench.py's BENCH json (`checker` section); run these with
MAELSTROM_CHECKER_BENCH=1 pytest -m checker_bench."""

import os
import sys

import pytest

pytestmark = pytest.mark.checker_bench

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _record(n):
    import bench
    return bench.bench_checkers_record(n_rows=n, elle_ops=n)


def test_register_fast_path_beats_baseline():
    r = _record(120_000)["register"]
    assert r["verdicts_match"] is True
    assert r["valid"] is True
    # 5x is the acceptance bar at 1M ops; at this shrunk size fixed
    # overheads bite harder, so require a conservative 2x
    assert r["speedup"] >= 2.0, r


def test_elle_edge_build_matches_and_beats_baseline():
    r = _record(120_000)["elle"]
    assert r["match"] is True
    assert r["speedup"] >= 1.0, r


def test_full_record_shape():
    r = _record(40_000)
    assert r["valid"] is True
    for section in ("register", "elle"):
        assert r[section]["speedup"] > 0


def test_elle_device_build_matches_and_screens():
    """ISSUE 11 shrunk variant: the jitted device edge build is
    set-equal to the host builds and already beats the pure-Python loop
    at this size; the screen fixtures decide >= 90% of valid synthetic
    histories (the full-size ratios ride BENCH_MODE=checker)."""
    d = _record(120_000)["elle"]["device"]
    assert d["match"] is True
    assert d["speedup"] >= 5.0, d          # 10x is the 1M acceptance bar
    assert d["screen_fixtures"]["decided_fraction"] >= 0.9, d


def test_tiny_elle_ops_still_well_formed():
    """Regression (ISSUE 11 satellite): tiny BENCH_CHECKER_ELLE_OPS
    used to derive versions_per_key before the read-count clamp,
    producing an appends-only workload with zero reads (no wr/rw edges
    to measure). The synthetic must stay read-bearing and
    multi-version at any size."""
    import bench
    for ops in (5, 37, 100, 640):
        txns, longest, appender, micro_ops = bench.elle_synthetic(ops)
        reads = sum(1 for t in txns
                    for m in t["micro"] if m[0] == "r")
        assert reads > 0, (ops, micro_ops)
        assert all(len(v) >= 2 for v in longest.values()), ops
        assert abs(micro_ops - ops) <= max(2 * len(longest), 10), \
            (ops, micro_ops)
    # and the record stays valid end to end at a tiny size
    r = _record(2_000)
    assert r["elle"]["micro_ops"] > 0
    assert r["elle"]["match"] is True
