"""Direct unit tests for the SVG/HTML renderers (ISSUE 13 satellite):
plots.py and timeline.py had no dedicated test file — exceptions were
only ever observed as a swallowed `plot-error` in results. These pin
the degenerate-input contract (empty / nemesis-only / unpaired /
zero-duration histories render, never raise), escaping, and the new
fleet telemetry heatmap."""

from __future__ import annotations

import math
import os

import pytest

from maelstrom_tpu.history import History
from maelstrom_tpu.viz.fleet import fleet_heatmap
from maelstrom_tpu.viz.plots import perf_charts, svg_chart
from maelstrom_tpu.viz.timeline import render_timeline

CHARTS = ("latency-raw.svg", "latency-quantiles.svg", "rate.svg")


def _nemesis_only():
    h = History()
    h.append_row("invoke", "start-partition", None, "nemesis", 0)
    h.append_row("info", "start-partition", "isolated", "nemesis",
                 5_000_000)
    h.append_row("invoke", "stop-partition", None, "nemesis", 9_000_000)
    h.append_row("info", "stop-partition", "healed", "nemesis",
                 10_000_000)
    return h


def _normal():
    h = History()
    for i in range(20):
        h.append_row("invoke", "read" if i % 2 else "write",
                     [None, i], i % 3, i * 10_000_000)
        h.append_row("ok" if i % 5 else "info",
                     "read" if i % 2 else "write", [None, i], i % 3,
                     i * 10_000_000 + 3_000_000)
    return h


@pytest.mark.parametrize("history", [
    History(),                      # empty
    _nemesis_only(),                # nemesis-only (pure-fault run)
    _normal(),
], ids=["empty", "nemesis-only", "normal"])
def test_perf_charts_always_writes_all_three(history, tmp_path):
    perf_charts(history, str(tmp_path))
    for name in CHARTS:
        p = tmp_path / name
        assert p.exists(), name
        text = p.read_text()
        assert text.startswith("<svg"), name
        assert "</svg>" in text, name


def test_perf_charts_unpaired_and_zero_duration(tmp_path):
    h = History()
    h.append_row("invoke", "read", None, 0, 0)      # never completes
    h.append_row("invoke", "write", [None, 1], 1, 0)
    h.append_row("ok", "write", [None, 1], 1, 0)    # zero latency
    perf_charts(h, str(tmp_path))
    for name in CHARTS:
        assert (tmp_path / name).exists()


@pytest.mark.parametrize("history", [
    History(), _nemesis_only(), _normal(),
], ids=["empty", "nemesis-only", "normal"])
def test_timeline_renders(history, tmp_path):
    path = str(tmp_path / "timeline.html")
    doc = render_timeline(history, path)
    assert os.path.exists(path)
    assert "<html" in doc and "</html>" in doc


def test_timeline_escapes_process_and_values(tmp_path):
    h = History()
    h.append_row("invoke", "read", "<script>alert(1)</script>",
                 "c0:<p>", 0)
    h.append_row("ok", "read", "<script>alert(1)</script>",
                 "c0:<p>", 1_000_000)
    doc = render_timeline(h)
    assert "<script>alert" not in doc
    assert "c0:<p></span>" not in doc


def test_svg_chart_drops_non_finite_points_and_escapes():
    svg = svg_chart({"a<b": {"points": [(0, 1), (1, math.nan),
                                        (2, math.inf), (3, 2)]}},
                    "t<itle", "x<", "y<", log_y=True)
    assert "nan" not in svg.lower()
    assert "a<b</text>" not in svg and "a&lt;b" in svg
    assert "t&lt;itle" in svg


def test_svg_chart_all_non_finite_is_no_data():
    svg = svg_chart({"a": {"points": [(0, math.nan)]}}, "T", "x", "y")
    assert "no data" in svg


def test_fleet_heatmap_basic(tmp_path):
    records = []
    for c in range(3):
        for w in range(5):
            records.append({"type": "window", "cluster": c, "window": w,
                            "lat_ms": {"count": 1, "p50": 1.0,
                                       "p95": 2.0, "p99": float(c + w),
                                       "max": 3.0}})
    records.append({"type": "final", "cluster": 0, "lat_ms": {}})
    path = str(tmp_path / "hm.svg")
    svg = fleet_heatmap(records, path)
    assert os.path.exists(path)
    assert svg.startswith("<svg") and "</svg>" in svg
    assert svg.count("<rect") >= 15          # one cell per window
    assert "Fleet telemetry" in svg


def test_fleet_heatmap_empty_and_missing_metric(tmp_path):
    svg = fleet_heatmap([])
    assert "no window records" in svg
    # windows with no lat_ms block render grey cells, no exception
    svg2 = fleet_heatmap([{"type": "window", "cluster": 0, "window": 0}])
    assert "#eee" in svg2
