"""Flight recorder (ISSUE 13, doc/observability.md).

Pinned contracts:
  - telemetry-on vs telemetry-off runs are BYTE-IDENTICAL per seed
    (plain and --fleet in tier-1; --mesh as multichip; the combined
    nemesis soup in the slow suite) — the rings are observational;
  - the device ring's message-flow counters equal the NetStats device
    counters (same run, same drain);
  - the streaming sketch is exact: the final telemetry.jsonl record's
    quantiles equal the post-hoc PerfChecker block on the same history;
  - trace.json is Chrome-trace shaped and carries the phase taxonomy;
  - the ring carry rides checkpoints: an interrupted+resumed run's
    final ring equals the uninterrupted run's (slow);
  - HostNet books the same counter vocabulary (parity test).
"""

from __future__ import annotations

import json
import os
import random

import pytest

from maelstrom_tpu import core
from maelstrom_tpu import telemetry as TM

STORE = "/tmp/maelstrom-tpu-telemetry-store"

SOUP = {"kill", "pause", "partition", "duplicate", "weather"}


def _run(tmp, tel=None, **kw):
    opts = dict(store_root=str(tmp), seed=23, workload="lin-kv",
                node="tpu:lin-kv", node_count=5, rate=15.0,
                time_limit=2.0, recovery_s=1.0, audit=False,
                audit_trace=False)
    if tel:
        opts["telemetry"] = tel
    opts.update(kw)
    res = core.run(opts)
    with open(os.path.join(str(tmp), "latest", "history.jsonl"),
              "rb") as f:
        return res, f.read()


# --- unit layer ------------------------------------------------------------

def test_sketch_quantiles_are_exact():
    from maelstrom_tpu.checkers.perf import _quantile_block
    import numpy as np
    for seed in range(6):
        rng = random.Random(seed)
        vals = [rng.choice([1.0, 2.0, 5.0, 5.0, 7.5, 100.0])
                for _ in range(rng.randint(1, 400))]
        sk = TM.Sketch()
        for v in vals:
            sk.add(v)
        assert sk.quantiles() == _quantile_block(
            np.sort(np.asarray(vals)))


def test_sketch_merge_and_empty():
    assert TM.Sketch().quantiles() == {}
    a, b = TM.Sketch(), TM.Sketch()
    for v in (1.0, 2.0):
        a.add(v)
    for v in (2.0, 9.0):
        b.add(v)
    a.merge(b)
    assert a.n == 4 and a.counts[2.0] == 2
    assert a.quantiles()["max"] == 9.0


def test_hostnet_counter_parity_vocabulary():
    """The host net books the same counter classes the device ring
    drains, under the same keys — and they behave: lossless send/recv
    conserves, loss and partitions land in `dropped`."""
    from maelstrom_tpu.net.host import HostNet
    net = HostNet()
    net.add_node("n0")
    net.add_node("n1")
    for i in range(10):
        net.send({"src": "n0", "dest": "n1",
                  "body": {"type": "echo", "msg_id": i}})
    got = 0
    while net.recv("n1", 10) is not None:
        got += 1
    c = net.telemetry_counters()
    assert got == 10
    assert c == {"sent": 10, "delivered": 10, "dropped": 0,
                 "duplicated": 0}
    # the vocabulary matches the device ring's message-flow keys
    ring_keys = {"sent", "delivered", "dropped", "duplicated"}
    assert set(c) == ring_keys

    net.flaky(1.0)                   # every send lost
    net.send({"src": "n0", "dest": "n1",
              "body": {"type": "echo", "msg_id": 99}})
    assert net.telemetry_counters()["dropped"] == 1
    net.flaky(0.0)
    net.drop_link("n0", "n1")        # partition consumes at recv
    net.send({"src": "n0", "dest": "n1",
              "body": {"type": "echo", "msg_id": 100}})
    assert net.recv("n1", 10) is None
    assert net.telemetry_counters()["dropped"] == 2


def test_render_top_and_validate_record():
    recs = [
        {"type": "window", "seq": 0, "window": 0, "round": 100,
         "t_s": 0.1, "ops": 5, "oks": 4, "fails": 0, "infos": 1,
         "lat_ms": {"count": 4, "p50": 5.0, "p95": 6.0, "p99": 6.0,
                    "max": 6.0},
         "cum_lat_ms": {"count": 4, "p50": 5.0, "p95": 6.0,
                        "p99": 6.0, "max": 6.0},
         "cluster": 1, "delivered_rate": 40.0,
         "checker_lag_rounds": 3},
        {"type": "final", "seq": 1, "round": 200, "t_s": 0.2,
         "ops": 9, "oks": 8, "fails": 0, "infos": 1, "windows": 1,
         "lat_ms": {"count": 8, "p50": 5.0, "p95": 6.0, "p99": 6.0,
                    "max": 6.0}},
    ]
    for r in recs:
        assert TM.validate_record(r) == [], r
    out = TM.render_top(recs)
    assert "cluster" in out and "p99ms" in out
    assert TM.render_top([]) == "telemetry: no records yet"
    assert TM.validate_record({"type": "bogus"})
    assert TM.validate_record({"type": "window", "seq": "x",
                               "round": 0, "ops": 0, "oks": 0})


# --- e2e: byte identity + exactness ---------------------------------------

def test_plain_byte_identity_ring_counters_and_stream(tmp_path):
    r_off, h_off = _run(tmp_path / "off")
    tel_dir = str(tmp_path / "teldir")
    r_on, h_on = _run(tmp_path / "on", tel=tel_dir)
    assert r_off["valid"] is True and r_on["valid"] is True
    assert h_on == h_off                 # byte-identical histories

    # ring counters == the device NetStats counters of the same run
    ring = r_on["net"]["telemetry"]
    assert ring["sent"] == r_on["net"]["all"]["send-count"]
    assert ring["delivered"] == r_on["net"]["all"]["recv-count"]
    assert ring["dropped"] == (r_on["net"]["lost"]
                               + r_on["net"]["dropped-partition"]
                               + r_on["net"]["dropped-down"]
                               + r_on["net"]["dropped-overflow"])
    assert ring["duplicated"] == r_on["net"]["duplicated"]
    assert ring["rounds"] > 0
    # occupancy histograms count every executed round
    assert sum(ring["pool-occupancy-hist"]) == ring["rounds"]
    assert ring["latency-count"] > 0
    assert "nodes" in ring["role-sent"]
    # the off-run's results carry NO telemetry block (shape preserved)
    assert "telemetry" not in r_off["net"]

    # telemetry.jsonl: schema-valid, final quantiles == PerfChecker
    recs = [json.loads(line)
            for line in open(os.path.join(tel_dir, "telemetry.jsonl"))]
    assert recs, "no telemetry records"
    for rec in recs:
        assert TM.validate_record(rec) == [], rec
    final = [r for r in recs if r["type"] == "final"][-1]
    perf = {k: v for k, v in r_on["perf"]["latency-ms"].items()
            if k != "by-f"}
    assert final["lat_ms"] == perf
    assert final["ops"] == sum(r["ops"] for r in recs
                               if r["type"] == "window")

    # trace.json: Chrome-trace shaped, the phase taxonomy present
    with open(os.path.join(tel_dir, "trace.json")) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"schedule-encode", "dispatch", "device-get"} <= names
    for e in trace["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0 and "ts" in e


def test_fleet_byte_identity_and_per_cluster_stream(tmp_path):
    kw = dict(fleet=2, continuous=True, time_limit=1.5, seed=31)
    r_off, _ = _run(tmp_path / "off", **kw)
    off_root = os.path.join(str(tmp_path / "off"), "latest")
    h_off = {i: open(os.path.join(off_root, f"cluster-{i:04d}",
                                  "history.jsonl"), "rb").read()
             for i in range(2)}
    tel_dir = str(tmp_path / "teldir")
    r_on, _ = _run(tmp_path / "on", tel=tel_dir, **kw)
    on_root = os.path.join(str(tmp_path / "on"), "latest")
    h_on = {i: open(os.path.join(on_root, f"cluster-{i:04d}",
                                 "history.jsonl"), "rb").read()
            for i in range(2)}
    assert h_on == h_off                 # per-cluster byte identity

    recs = [json.loads(line)
            for line in open(os.path.join(tel_dir, "telemetry.jsonl"))]
    finals = {r["cluster"]: r for r in recs if r["type"] == "final"}
    assert set(finals) == {0, 1}
    for i in range(2):
        perf = {k: v
                for k, v in r_on["clusters"][i]["perf"]
                ["latency-ms"].items() if k != "by-f"}
        assert finals[i]["lat_ms"] == perf, i
        # per-cluster ring in each cluster's net block
        ring = r_on["clusters"][i]["net"]["telemetry"]
        assert ring["sent"] == \
            r_on["clusters"][i]["net"]["all"]["send-count"]
    # the fleet heatmap rendered (>= 2 clusters in the stream)
    assert os.path.exists(os.path.join(tel_dir, "fleet-heatmap.svg"))
    # fleet + per-cluster trace rows
    with open(os.path.join(tel_dir, "trace.json")) as f:
        tids = {e["tid"] for e in json.load(f)["traceEvents"]}
    assert "fleet" in tids and {"c0", "c1"} & tids


@pytest.mark.multichip
def test_mesh_byte_identity(tmp_path):
    kw = dict(mesh="1,2", seed=37)
    _, h_off = _run(tmp_path / "off", **kw)
    _, h_on = _run(tmp_path / "on", tel=str(tmp_path / "teldir"), **kw)
    assert h_on == h_off


@pytest.mark.slow
def test_soup_byte_identity(tmp_path):
    kw = dict(nemesis=set(SOUP), nemesis_interval=0.7, time_limit=2.5,
              seed=41, timeout_ms=1000)
    r_off, h_off = _run(tmp_path / "off", **kw)
    r_on, h_on = _run(tmp_path / "on", tel=str(tmp_path / "teldir"),
                      **kw)
    assert h_on == h_off
    # faults actually ran and the ring saw them
    ring = r_on["net"]["telemetry"]
    assert ring["dropped"] + ring["duplicated"] >= 0
    assert ring["rounds"] > 0


@pytest.mark.slow
def test_ring_rides_checkpoint_resume(tmp_path):
    """The interrupted+resumed run's history AND final ring equal the
    uninterrupted run's — the MetricRing is part of the deterministic
    carry, snapshot and restored with the rest of SimState."""
    from conftest import ops_projection as _ops

    from maelstrom_tpu import checkpoint as cp
    from maelstrom_tpu.runner.tpu_runner import TpuRunner

    def build(sub, **over):
        opts = {"workload": "pn-counter", "node": "tpu:pn-counter",
                "node_count": 5, "rate": 20.0, "time_limit": 3.0,
                "nemesis": {"partition"}, "nemesis_interval": 1.0,
                "recovery_s": 1.0, "seed": 7,
                "telemetry": str(tmp_path / "tel"),
                "store_root": str(tmp_path / sub)}
        opts.update(over)
        test = core.build_test(opts)
        test["store_dir"] = str(tmp_path / sub)
        return test

    runner_a = TpuRunner(build("a"))
    hist_a = runner_a.run()
    ring_a = TM.ring_dict(runner_a._final_ring())
    assert ring_a["rounds"] > 0

    test_b = build("b", checkpoint_every=1.0)
    test_b["max_rounds"] = 1500
    TpuRunner(test_b).run()
    ck = cp.load(str(tmp_path / "b"))
    assert ck["sim"].telemetry is not None   # the ring is IN the file

    test_c = build("b")
    runner_c = TpuRunner(test_c)
    resume = cp.load(str(tmp_path / "b"))
    cp.check_fingerprint(resume, test_c)
    hist_c = runner_c.run(resume=resume)
    assert _ops(hist_c) == _ops(hist_a)
    assert TM.ring_dict(runner_c._final_ring()) == ring_a

    # rings-off resume against a rings-on checkpoint is REFUSED (the
    # carry shapes differ)
    test_d = build("b", ms_per_round=1.0)
    test_d.pop("telemetry")
    with pytest.raises(ValueError, match="telemetry_rings"):
        cp.check_fingerprint(cp.load(str(tmp_path / "b")), test_d)


@pytest.mark.slow
def test_fleet8_continuous_acceptance(tmp_path):
    """ISSUE 13 acceptance: a `--fleet 8 --continuous --telemetry` run
    produces a Chrome-trace JSON that loads (Perfetto format) and a
    telemetry.jsonl whose per-cluster final quantiles match the
    post-hoc PerfChecker values on the same histories."""
    tel_dir = str(tmp_path / "teldir")
    r_on, _ = _run(tmp_path / "on", tel=tel_dir, fleet=8,
                   continuous=True, time_limit=1.5, seed=47)
    recs = [json.loads(line)
            for line in open(os.path.join(tel_dir, "telemetry.jsonl"))]
    for rec in recs:
        assert TM.validate_record(rec) == [], rec
    finals = {r["cluster"]: r for r in recs if r["type"] == "final"}
    assert set(finals) == set(range(8))
    for i in range(8):
        perf = {k: v
                for k, v in r_on["clusters"][i]["perf"]
                ["latency-ms"].items() if k != "by-f"}
        assert finals[i]["lat_ms"] == perf, i
    with open(os.path.join(tel_dir, "trace.json")) as f:
        trace = json.load(f)
    assert isinstance(trace["traceEvents"], list) and \
        trace["traceEvents"]
    for e in trace["traceEvents"]:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    assert os.path.exists(os.path.join(tel_dir, "fleet-heatmap.svg"))


def test_continuous_windowed_stream(tmp_path):
    """Continuous mode: window records stream per wave; the final
    cumulative quantiles still match the post-hoc PerfChecker."""
    tel_dir = str(tmp_path / "teldir")
    r_on, _ = _run(tmp_path / "on", tel=tel_dir, continuous=True,
                   seed=43)
    recs = [json.loads(line)
            for line in open(os.path.join(tel_dir, "telemetry.jsonl"))]
    wins = [r for r in recs if r["type"] == "window"]
    assert len(wins) >= 2
    final = [r for r in recs if r["type"] == "final"][-1]
    perf = {k: v for k, v in r_on["perf"]["latency-ms"].items()
            if k != "by-f"}
    assert final["lat_ms"] == perf
    # window records carry ring DELTAS; the final record carries the
    # cumulative ring (== the results block's). Deltas sum to at most
    # the cumulative value (the recovery tail runs after the last wave)
    ring_total = r_on["net"]["telemetry"]
    assert final["ring"] == ring_total
    delta_sum = sum(r.get("ring", {}).get("sent", 0) for r in wins)
    assert 0 < delta_sum <= ring_total["sent"]
