"""Byzantine adversary + conviction contract (ISSUE 16, doc/faults.md
"byzantine is a conviction driver").

The acceptance bar: a byzantine run is valid only if EVERY injected
corruption is convicted with a named rule and culprit, on both
execution paths identically per seed — and benign runs stay
conviction-free (detectors armed, zero false positives).
"""

import json
import os

import pytest

from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import core
from maelstrom_tpu.byzantine import ATTACKS, RULE_ATTACK, assemble_block
from maelstrom_tpu.checkers.byzantine import (ByzantineChecker,
                                              classify_wire_diff)
from maelstrom_tpu.nemesis import NemesisDecisions

from conftest import ops_projection as _ops

STORE = "/tmp/maelstrom-byzantine-store"


def run(opts):
    base = dict(store_root=STORE, seed=3, rate=20.0, time_limit=3.0,
                journal_rows=False, audit=False,
                node="tpu:compartment", workload="lin-kv",
                roles="sequencers=2,proxies=2,acceptors=1x2,replicas=1",
                compartment_retry=3,
                nemesis={"byzantine"}, nemesis_interval=0.8)
    return core.run({**base, **opts})


def _history():
    with open(os.path.join(STORE, "latest", "history.jsonl")) as f:
        return [json.loads(ln) for ln in f]


# --- the ledger/block contract (pure) --------------------------------------

def test_assemble_block_grades_the_ledger():
    inj = {"equivocation": 5, "forged-proof": 0, "stale-ballot": 0}
    conv = [{"rule": "equivocation", "culprit": "n0",
             "evidence": {"count": 5}, "witness": "n2"}]
    blk = assemble_block(conv, inj)
    assert blk["valid"] is True
    assert blk["unconvicted"] == [] and blk["spurious"] == []
    # an injected attack nobody convicted invalidates the block
    blk2 = assemble_block([], inj)
    assert blk2["valid"] is False
    assert blk2["unconvicted"] == ["equivocation"]
    # a conviction for an attack that never ran is spurious
    blk3 = assemble_block(conv, {a: 0 for a in ATTACKS})
    assert blk3["valid"] is False
    assert blk3["spurious"] == ["equivocation"]


def test_classify_wire_diff_names_the_rule():
    sent = {"type": "assign", "slot": 7, "ballot": 2}
    # replayed old traffic beats field classification
    assert classify_wire_diff(sent, {"type": "assign", "slot": 3},
                              [{"type": "assign", "slot": 3}]) \
        == "stale-ballot"
    # diff confined to the proof vocabulary
    assert classify_wire_diff({"lo": 4, "n": 3}, {"lo": 5, "n": 4},
                              []) == "forged-proof"
    # anything else is an equivocation
    assert classify_wire_diff(sent, {**sent, "slot": 9}, []) \
        == "equivocation"


# --- per-attack convictions, TPU path --------------------------------------

def test_equivocation_convicted_on_device():
    res = run(dict(nemesis_targets="byzantine=n0",
                   byz_attacks="equivocation"))
    blk = res["byzantine"]
    assert blk["injected"]["equivocation"] > 0
    assert blk["injected"]["stale-ballot"] == 0
    assert blk["injected"]["forged-proof"] == 0
    assert blk["valid"] is True, blk
    assert blk["unconvicted"] == [] and blk["spurious"] == []
    rules = {(c["rule"], c["culprit"]) for c in blk["convictions"]}
    assert rules == {("equivocation", "n0")}
    for c in blk["convictions"]:
        assert c["evidence"]["count"] > 0
        assert c["witness"].startswith("n")     # a proxy testified
    # the workload verdict stays INDEPENDENT of the conviction block:
    # a first corrupted assign can land before the round-varying retry
    # exposes the lie, so lin-kv may legitimately fail — conviction is
    # about naming the liar, not absolving the run
    assert res["workload"]["valid"] in (True, False)
    # and the nemesis op stream names the plan both paths share
    vals = [o["value"] for o in _history()
            if o.get("process") == "nemesis" and o.get("type") == "info"
            and str(o.get("value", "")).startswith("byzantine ")]
    assert "byzantine equivocation culprit=n0" in vals


@pytest.mark.slow
def test_stale_ballot_convicted_on_device():
    res = run(dict(nemesis_targets="byzantine=sequencers",
                   byz_attacks="stale-ballot"))
    blk = res["byzantine"]
    assert blk["injected"]["stale-ballot"] > 0
    assert blk["valid"] is True, blk
    rules = {c["rule"] for c in blk["convictions"]}
    assert rules == {"stale-ballot"}
    for c in blk["convictions"]:
        assert RULE_ATTACK[c["rule"]] == "stale-ballot"
        assert c["culprit"] in ("n0", "n1")     # a sequencer lied
        assert "ballot" in c["evidence"]


def test_forged_proof_convicted_by_expansion_audit():
    """The forged-proof attack hits the batched-broadcast proof
    vocabulary; the conviction comes from the workload checker's OWN
    expansion-proof audit (BatchedBroadcastChecker.convictions) — the
    corruption surface picks the convicting auditor."""
    res = core.run(dict(
        store_root=STORE, seed=7, workload="broadcast-batched",
        node="tpu:broadcast-batched", node_count=5, rate=10.0,
        time_limit=6.0, journal_rows=False, audit=False,
        nemesis={"byzantine"}, nemesis_interval=1.5,
        byz_attacks="forged-proof"))
    blk = res["byzantine"]
    assert blk["injected"]["forged-proof"] > 0
    assert blk["valid"] is True, blk
    assert blk["convictions"]
    for c in blk["convictions"]:
        assert RULE_ATTACK[c["rule"]] == "forged-proof"
        assert c["culprit"].startswith("n")
        assert c["evidence"]["count"] > 0
    # forged proofs DID reach the graded record: the run itself fails
    # even though the byzantine block is satisfied — conviction is not
    # absolution
    assert res["valid"] is False


# --- benign runs stay conviction-free --------------------------------------

def test_benign_soup_has_no_byzantine_block():
    res = core.run(dict(
        store_root=STORE, seed=7, workload="lin-kv", node="tpu:lin-kv",
        node_count=5, rate=20.0, time_limit=2.0, journal_rows=False,
        audit=False, recovery_s=1.0,
        nemesis={"kill", "pause", "partition", "duplicate", "weather"},
        nemesis_interval=0.7))
    assert "byzantine" not in res


def test_armed_detectors_never_convict_honest_traffic():
    """byz_rate=0 arms every conviction lane (enable_byz compiles the
    detectors in, the nemesis schedules windows) while the corruption
    gate never fires: honest traffic must produce zero convictions,
    an all-zero ledger, and a valid block."""
    res = run(dict(nemesis_targets="byzantine=n0",
                   byz_attacks="equivocation", byz_rate=0.0,
                   time_limit=2.0))
    blk = res["byzantine"]
    assert blk["injected"] == {a: 0 for a in ATTACKS}
    assert blk["convictions"] == []
    assert blk["valid"] is True
    assert res["valid"] is True, res.get("valid")


# --- host/TPU parity per seed ----------------------------------------------

def test_plan_stream_identical_per_seed():
    """Host and TPU nemeses draw the adversary schedule from the same
    NemesisDecisions byzantine stream: same seed, same plans."""
    nodes = [f"n{i}" for i in range(6)]
    mk = lambda: NemesisDecisions(nodes, seed=13,   # noqa: E731
                                  attacks=("equivocation",
                                           "stale-ballot"))
    a, b = mk(), mk()
    plans = [a.next_byz_plan() for _ in range(10)]
    assert plans == [b.next_byz_plan() for _ in range(10)]
    for attack, culprit, delta in plans:
        assert attack in ("equivocation", "stale-ballot")
        assert culprit in nodes and 1 <= delta <= 0x7FFF


def _host_audit(attack, bodies, seed=13):
    """Drives one NemesisDecisions-planned attack window through a real
    HostNet + journal and returns (plan, injected ledger, convictions
    from the wire auditor)."""
    from maelstrom_tpu.net.host import HostNet
    from maelstrom_tpu.net.journal import Journal

    net = HostNet()
    net.journal = Journal()
    for nid in ("n0", "n1"):
        net.add_node(nid)
    plan = NemesisDecisions(["n0", "n1"], seed=seed,
                            attacks=(attack,)).next_byz_plan()
    attack_p, culprit, delta = plan
    assert attack_p == attack
    other = "n1" if culprit == "n0" else "n0"
    net.set_byzantine(attack_p, culprit, delta, rate=1.0)
    for body in bodies:
        net.send({"src": culprit, "dest": other, "body": body})
        assert net.recv(other, 1000) is not None
    net.clear_byzantine()
    convs = ByzantineChecker(net).convictions(
        {"nodes": ["n0", "n1"]}, [], {})
    return plan, dict(net.byz_injected), convs


@pytest.mark.parametrize("attack,bodies", [
    # slots > 63 apart: the equivocation xor mask is <= 0x3F, so a
    # corrupted delivery can never collide with the OTHER honest body
    # (which would legitimately classify as a replay instead)
    ("equivocation", [{"type": "assign", "slot": 2, "ballot": 0},
                      {"type": "assign", "slot": 200, "ballot": 0}]),
    ("stale-ballot", [{"type": "assign", "slot": 1},
                      {"type": "assign", "slot": 2}]),
    ("forged-proof", [{"type": "batch_ok", "lo": 4, "n": 3,
                       "proof": 9}]),
])
def test_host_wire_auditor_convicts_the_planned_culprit(attack, bodies):
    """The host path's half of per-seed conviction identity: the SAME
    seeded plan the TPU nemesis would draw drives HostNet's delivered-
    copy corruption, and the journal auditor convicts exactly that
    (attack, culprit) — so both paths' blocks name the same liar for
    the same seed."""
    (attack_p, culprit, _delta), injected, convs = \
        _host_audit(attack, bodies)
    assert injected.get(attack, 0) > 0
    assert len(convs) == 1
    c = convs[0]
    assert RULE_ATTACK[c["rule"]] == attack
    assert c["culprit"] == culprit
    assert c["evidence"]["count"] == injected[attack]
    assert c["evidence"]["sent"] != c["evidence"]["received"]
    # the block assembled from these convictions grades valid
    inj = {a: injected.get(a, 0) for a in ATTACKS}
    assert assemble_block(convs, inj)["valid"] is True


# --- resume fingerprint + byte-identity (satellite: checkpoint) ------------

def _build_byz(tmp_path, **over):
    opts = {"workload": "lin-kv", "node": "tpu:compartment",
            "roles": "sequencers=2,proxies=2,acceptors=1x2,replicas=1",
            "compartment_retry": 3, "rate": 20.0, "time_limit": 4.0,
            "nemesis": {"byzantine"}, "nemesis_interval": 0.8,
            "nemesis_targets": "byzantine=n0",
            "byz_attacks": "equivocation",
            "recovery_s": 1.0, "seed": 3, "store_root": str(tmp_path)}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(tmp_path)
    return test


def test_fingerprint_pins_byz_knobs(tmp_path):
    t1 = _build_byz(tmp_path)
    t2 = _build_byz(tmp_path, byz_attacks="stale-ballot")
    fp1, fp2 = cp.fingerprint(t1), cp.fingerprint(t2)
    assert fp1["byz_attacks"] != fp2["byz_attacks"]
    with pytest.raises(ValueError, match="byz_attacks"):
        cp.check_fingerprint({"fingerprint": fp1}, t2)
    t3 = _build_byz(tmp_path, byz_rate=0.25)
    with pytest.raises(ValueError, match="byz_rate"):
        cp.check_fingerprint({"fingerprint": fp1}, t3)


@pytest.mark.slow
def test_resume_mid_attack_byte_identical(tmp_path):
    """A run killed INSIDE a byzantine window and resumed from its
    checkpoint replays the identical history: the adversary plan
    stream, the compiled corruption masks' state (SimState.byz), and
    the injection ledger all live in the checkpoint."""
    from maelstrom_tpu.runner.tpu_runner import TpuRunner

    test_a = _build_byz(tmp_path / "a")
    hist_a = TpuRunner(test_a).run()
    assert len(hist_a) > 20

    test_b = _build_byz(tmp_path / "b", checkpoint_every=1.0)
    test_b["max_rounds"] = 1500     # die mid-run, past the first window
    TpuRunner(test_b).run()
    ck = cp.load(str(tmp_path / "b"))
    assert ck["r"] <= 1500

    test_c = _build_byz(tmp_path / "b")
    runner_c = TpuRunner(test_c)
    resume = cp.load(str(tmp_path / "b"))
    cp.check_fingerprint(resume, test_c)
    hist_c = runner_c.run(resume=resume)
    assert _ops(hist_c) == _ops(hist_a)


# --- sharded conviction identity -------------------------------------------

@pytest.mark.slow
@pytest.mark.multichip
def test_mesh_conviction_identity():
    """--mesh 1,2 runs the same adversary over the sharded round: the
    assembled byzantine block — ledger, convictions, verdict — is
    IDENTICAL to the single-device run for the same seed."""
    plain = run(dict(nemesis_targets="byzantine=n0",
                     byz_attacks="equivocation"))
    sharded = run(dict(nemesis_targets="byzantine=n0",
                       byz_attacks="equivocation", mesh="1,2"))
    assert plain["byzantine"] == sharded["byzantine"]
    assert plain["byzantine"]["valid"] is True
