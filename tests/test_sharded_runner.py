"""The production runner's `--mesh` mode: sharding the interactive
simulation over a ("dp", "sp") device mesh must change *placement only*.
Same-seed mesh runs are bit-identical to single-chip runs — histories,
completion times, journals — and extraction stays off the hot path
(host drains ~ dispatches, not ~ simulated rounds).

Runs on the 8 virtual CPU devices from conftest; the `multichip` marker
auto-skips on single-device environments (conftest hook)."""

from __future__ import annotations

import pytest

from conftest import ops_projection as _ops
from maelstrom_tpu import core
from maelstrom_tpu.runner.tpu_runner import TpuRunner

pytestmark = pytest.mark.multichip


def _run(tmp_path, journal=False, **over):
    opts = {"node_count": 8, "rate": 15.0, "time_limit": 1.5,
            "recovery_s": 0.5, "seed": 5, "store_root": str(tmp_path)}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(tmp_path)
    runner = TpuRunner(test)
    if journal:
        from maelstrom_tpu.net.journal import Journal
        runner.journal = Journal()
    history = runner.run()
    return runner, history, test


def test_mesh_smoke_bit_identical_and_drains_bounded(tmp_path):
    """Tier-1 CPU 2-device smoke: a sharded broadcast run equals the
    single-chip run op for op, and its host-drain count is
    O(host-relevant rounds) — far below the simulated round count."""
    over = {"workload": "broadcast", "node": "tpu:broadcast",
            "topology": "grid"}
    r1, h1, _ = _run(tmp_path / "a", **over)
    r2, h2, t2 = _run(tmp_path / "b", mesh="1,2", **over)
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)
    assert r2.mesh is not None and r2.mesh.shape["sp"] == 2

    # extraction off the hot path: each compiled dispatch drains once
    # (plus a few scalar probes); simulated rounds dwarf that
    assert r2.final_round > 1000
    assert 0 < r2.transfer.drains < r2.final_round // 4
    assert r2.transfer.host_bytes > 0

    # the counters surface in the net-stats checker result
    from maelstrom_tpu.runner.tpu_runner import TpuNetStats
    res = TpuNetStats(r2).check(t2, h2, {})
    assert res["drains"] == r2.transfer.drains
    assert res["host-bytes"] == r2.transfer.host_bytes
    assert res["valid"] is True


def test_mesh_rejects_cluster_axis(tmp_path):
    """The interactive runner simulates one cluster: dp > 1 has nothing
    to data-parallelize and must be rejected loudly (replicating over dp
    is not value-safe under GSPMD scatter partitioning)."""
    with pytest.raises(ValueError, match="cluster axis must be 1"):
        _run(tmp_path, workload="broadcast", node="tpu:broadcast",
             topology="grid", mesh="2,2")


@pytest.mark.slow
@pytest.mark.parametrize("workload,node,mesh", [
    ("broadcast", "tpu:broadcast", "1,4"),
    ("lin-kv", "tpu:lin-kv", "1,2"),        # raft consensus
    ("kafka", "tpu:kafka", "1,4"),
])
def test_mesh_bit_identical_all_workloads(tmp_path, workload, node, mesh):
    """Acceptance: sharded runs are bit-identical to single-chip for the
    same seed on broadcast, raft, and kafka."""
    over = {"workload": workload, "node": node}
    if workload == "broadcast":
        over["topology"] = "grid"
    r1, h1, _ = _run(tmp_path / "a", **over)
    r2, h2, t2 = _run(tmp_path / "b", mesh=mesh, **over)
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)
    res = t2["workload_map"]["checker"].check(t2, h2, {})
    assert res["valid"], res


@pytest.mark.slow
def test_mesh_bit_identical_under_faults_with_journal(tmp_path):
    """Nemesis mask surgery (directional partitions installed host-side
    mid-run) and the io-collecting journal scan, both under the mesh:
    history AND per-message journal must match single-chip exactly."""
    from collections import Counter

    over = {"workload": "broadcast", "node": "tpu:broadcast",
            "topology": "grid", "nemesis": {"partition"},
            "nemesis_interval": 0.4, "journal": True}
    r1, h1, _ = _run(tmp_path / "a", **over)
    r2, h2, _ = _run(tmp_path / "b", mesh="1,2", **over)
    assert _ops(h1) == _ops(h2)
    ev1 = Counter((e.type, e.id, e.time, e.src, e.dest)
                  for e in r1.journal.all_events())
    ev2 = Counter((e.type, e.id, e.time, e.src, e.dest)
                  for e in r2.journal.all_events())
    assert ev1 == ev2 and sum(ev1.values()) > 0


@pytest.mark.slow
def test_mesh_bit_identical_kill_pause(tmp_path):
    """Crash-kill + pause under the mesh: the durable store, down/paused
    masks, and the donated restart all live sharded; decisions and
    histories must match single-chip."""
    over = {"workload": "lin-kv", "node": "tpu:lin-kv",
            "nemesis": {"kill", "pause"}, "nemesis_interval": 0.4}
    r1, h1, _ = _run(tmp_path / "a", **over)
    r2, h2, _ = _run(tmp_path / "b", mesh="1,2", **over)
    assert len(h1) > 20
    assert _ops(h1) == _ops(h2)
