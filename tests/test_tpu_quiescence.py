"""Liveness regression tests: after a burst of activity with no faults,
edge programs must acknowledge everything and fall silent — traffic
converges to zero, sync/ack state engages, and the runner's idle
fast-forward becomes possible. Guards against the
echo-ack-cancelled-by-nb_ge class of bug (pending & ~nb_ge deleting the
acknowledgement before it was ever sent)."""

import jax
import jax.numpy as jnp

from maelstrom_tpu.net import tpu as T
from maelstrom_tpu.nodes import get_program
from maelstrom_tpu.sim import _round_edge, make_sim

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def drive_until_quiet(name, opts, inject_type, inject_a, n=5,
                      max_rounds=120):
    nodes = [f"n{i}" for i in range(n)]
    prog = get_program(name, opts, nodes)
    cfg = T.NetConfig(n_nodes=n, n_clients=1, pool_cap=256,
                      inbox_cap=prog.inbox_cap, client_cap=8)
    sim = make_sim(prog, cfg, seed=0)
    inject = T.Msgs.empty(1).replace(
        valid=jnp.ones(1, bool), src=jnp.full((1,), n, T.I32),
        dest=jnp.zeros(1, T.I32), type=jnp.full((1,), inject_type, T.I32),
        a=jnp.full((1,), inject_a, T.I32))
    empty = T.Msgs.empty(1)
    step = jax.jit(lambda s, i: _round_edge(prog, cfg, s, i))
    sim, _, _ = step(sim, inject)
    quiet_at = None
    for r in range(1, max_rounds):
        sim, _, _ = step(sim, empty)
        if (bool(prog.quiescent(sim.nodes))
                and not bool(sim.channels.valid.any())
                and not bool(sim.net.pool.valid.any())):
            quiet_at = r
            break
    return prog, sim, quiet_at


def test_pn_counter_quiesces_after_add():
    prog, sim, quiet_at = drive_until_quiet(
        "pn-counter", {"latency": {"mean": 0}}, inject_type=10, inject_a=7)
    assert quiet_at is not None, "pn-counter never acknowledged the add"
    # and traffic genuinely stops: message counters freeze afterwards
    before = T.stats_dict(sim.net)["sent_all"]
    empty = T.Msgs.empty(1)
    for _ in range(30):
        sim, _, _ = _round_edge(prog,
                                T.NetConfig(n_nodes=5, n_clients=1,
                                            pool_cap=256,
                                            inbox_cap=prog.inbox_cap,
                                            client_cap=8),
                                sim, empty)
    assert T.stats_dict(sim.net)["sent_all"] == before
    # every node converged on the value
    pos = jax.device_get(sim.nodes["pos"])
    assert (pos.sum(axis=1) == 7).all()


def test_broadcast_quiesces_after_value():
    prog, sim, quiet_at = drive_until_quiet(
        "broadcast", {"topology": "grid", "max_values": 64,
                      "latency": {"mean": 0}},
        inject_type=10, inject_a=0)
    assert quiet_at is not None, "broadcast never acknowledged the value"
    seen = jax.device_get(sim.nodes["seen"])
    assert seen[:, 0].all()


def test_tiny_cluster_pn_counter_no_crash():
    """n_nodes < gossip_per_neighbor must clamp top_k, not crash."""
    prog, sim, quiet_at = drive_until_quiet(
        "pn-counter", {"latency": {"mean": 0}}, inject_type=10,
        inject_a=3, n=2)
    assert quiet_at is not None


def test_fanout_ge_cluster_size_terminates():
    from maelstrom_tpu.nodes.gset import fanout_topology
    topo = fanout_topology(["a", "b", "c"], 5)
    assert all(len(v) == 2 for v in topo.values())
