"""End-to-end TPU-path tests for the CRDT node programs: g-set,
g-counter, pn-counter — including the BASELINE-style gossip-fanout and
message-loss configurations."""

from maelstrom_tpu import core

import pytest

pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def run(opts):
    # journal_rows off: engages the compiled scan-ahead fast path (the
    # journal needs per-round io; Lamport viz is covered by other tests)
    base = dict(store_root="/tmp/maelstrom-tpu-test-store", seed=11,
                rate=20.0, time_limit=2.0, journal_rows=False)
    return core.run({**base, **opts})


def test_g_set_tpu_e2e():
    res = run({"workload": "g-set", "node": "tpu:g-set", "node_count": 5})
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["lost-count"] == 0 and w["stable-count"] > 0
    assert res["net"]["servers"]["send-count"] > 0


def test_g_set_tpu_fanout_with_loss():
    """BASELINE config shape: gossip fanout 3 + 5% message loss."""
    res = run({"workload": "g-set", "node": "tpu:g-set", "node_count": 20,
               "gossip_fanout": 3, "p_loss": 0.05, "time_limit": 2.0,
               "recovery_s": 3, "ms_per_round": 5.0})
    assert res["valid"] is True, res["workload"]
    assert res["workload"]["lost-count"] == 0


def test_pn_counter_tpu_e2e():
    res = run({"workload": "pn-counter", "node": "tpu:pn-counter",
               "node_count": 5})
    assert res["valid"] is True, res["workload"]
    w = res["workload"]
    assert w["final-reads"], w
    assert all(v is not None for v in w["final-reads"])


def test_pn_counter_tpu_partition():
    res = run({"workload": "pn-counter", "node": "tpu:pn-counter",
               "node_count": 5, "nemesis": {"partition"},
               "nemesis_interval": 0.5, "time_limit": 3.0,
               "recovery_s": 2})
    assert res["valid"] is True, res["workload"]


def test_g_counter_tpu_e2e():
    res = run({"workload": "g-counter", "node": "tpu:g-counter",
               "node_count": 5})
    assert res["valid"] is True, res["workload"]


def test_broadcast_reply_payload_roundtrip():
    """The reply-log payload (packed seen bitmap) decodes to exactly the
    node's seen set — the device/host contract behind zero-round-trip
    read completions (NodeProgram.reply_payload_words)."""
    import jax.numpy as jnp
    import numpy as np

    from maelstrom_tpu.nodes import get_program

    prog = get_program("broadcast", {"topology": "grid",
                                     "max_values": 100},
                       [f"n{i}" for i in range(4)])
    state = prog.init_state()
    rows = np.zeros((4, 100), bool)
    rows[1, [0, 31, 32, 63, 64, 99]] = True
    rows[3, 97] = True
    state["seen"] = jnp.asarray(rows)
    payload = np.asarray(prog.reply_payload(state, jnp.asarray([1, 3, 0])))
    assert payload.shape == (3, prog.reply_payload_words)

    class FakeIntern:
        def value(self, i):
            return i
    done = prog.completion_payload({"f": "read"}, {"type": "read_ok"},
                                   payload[0], FakeIntern())
    assert done["value"] == [0, 31, 32, 63, 64, 99]
    done = prog.completion_payload({"f": "read"}, {"type": "read_ok"},
                                   payload[1], FakeIntern())
    assert done["value"] == [97]
    done = prog.completion_payload({"f": "read"}, {"type": "read_ok"},
                                   payload[2], FakeIntern())
    assert done["value"] == []
