"""Checkpoint/resume: a capability the reference lacks (SURVEY.md 5.4).

The key property: a run that is killed and resumed from its checkpoint
produces a history *identical* to an uninterrupted run with the same
options — every PRNG consumed (generator rngs, nemesis rng, the device
key) and every piece of bookkeeping (dispatch counter, in-flight RPCs,
intern table) lives in the checkpoint.
"""

from __future__ import annotations

import pickle

import pytest

from maelstrom_tpu import checkpoint as cp
from maelstrom_tpu import core
from maelstrom_tpu.runner.tpu_runner import TpuRunner


from conftest import ops_projection as _ops


pytestmark = pytest.mark.slow  # full-suite only; fast core runs -m 'not slow'


def _build(tmp_path, **over):
    opts = {"workload": "pn-counter", "node": "tpu:pn-counter",
            "node_count": 5, "rate": 20.0, "time_limit": 3.0,
            "nemesis": {"partition"}, "nemesis_interval": 1.0,
            "recovery_s": 1.0, "seed": 7, "store_root": str(tmp_path)}
    opts.update(over)
    test = core.build_test(opts)
    test["store_dir"] = str(tmp_path)
    return test


def test_generator_trees_pickle(tmp_path):
    """Every workload's composed generator tree must survive pickling
    (the foundation of checkpoint/resume)."""
    from maelstrom_tpu.workloads import registry
    for name in registry():
        test = core.build_test({
            "workload": name, "node_count": 3, "rate": 10.0,
            "time_limit": 2.0, "nemesis": {"partition"},
            "store_root": str(tmp_path)})
        tree = test["generator"]
        clone = pickle.loads(pickle.dumps(tree))
        ctx = {"time": 0, "free": [0, 1], "processes": [0, 1, "nemesis"]}
        res, _ = clone.op(ctx)
        assert res is not None, name


def test_checkpoint_resume_identical_history(tmp_path):
    # uninterrupted run
    test_a = _build(tmp_path / "a")
    runner_a = TpuRunner(test_a)
    hist_a = runner_a.run()
    assert len(hist_a) > 20

    # interrupted run: checkpoint every virtual second, die early
    test_b = _build(tmp_path / "b", checkpoint_every=1.0)
    test_b["max_rounds"] = 1500
    runner_b = TpuRunner(test_b)
    partial = runner_b.run()
    ck = cp.load(str(tmp_path / "b"))
    assert ck["r"] <= 1500
    assert len(partial) > 0

    # resume from the checkpoint in a fresh process-equivalent
    # (runner first, then fingerprint check — run_tpu_test's order; the
    # runner defaults ms_per_round into the test map)
    test_c = _build(tmp_path / "b")
    runner_c = TpuRunner(test_c)
    resume = cp.load(str(tmp_path / "b"))
    cp.check_fingerprint(resume, test_c)
    hist_c = runner_c.run(resume=resume)

    assert _ops(hist_c) == _ops(hist_a)

    # and the resumed history satisfies the workload checker
    res = test_c["workload_map"]["checker"].check(test_c, hist_c, {})
    assert res["valid"], res


def test_resume_rejects_mismatched_options(tmp_path):
    test = _build(tmp_path, checkpoint_every=0.5, time_limit=1.0,
                  nemesis=set())
    runner = TpuRunner(test)
    runner.run()
    ck = cp.load(str(tmp_path))

    other = _build(tmp_path, time_limit=1.0, nemesis=set(), seed=99)
    with pytest.raises(ValueError, match="seed"):
        cp.check_fingerprint(ck, other)


def test_missing_checkpoint_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        cp.load(str(tmp_path / "nope"))
